"""Sharding policy objects: how the serving engine places and constrains
its device state on a submesh (ISSUE 9 tentpole).

The engine is topology-OBLIVIOUS: every device placement it performs goes
through one of these hooks, and the single-device policy makes every hook
the identity — so a ``1x1`` engine traces exactly the graphs a
policy-free engine would (bit-identical compile keys, no constraint ops
inserted). :class:`MeshPolicy` is where multichip serving actually lives:

- weights placed by ``parallel.sharding.decoder_param_specs`` (Megatron
  column/row TP × FSDP, quantization-aware);
- the paged KV pool ``[L, N, BS, KH, D]`` sharded on the HEAD axis over
  ``tp`` (the block/position axes stay replicated-indexable, so the
  host-side block allocator, prefix cache and admission accounting are
  untouched — block ids are global, only the resident layout is sharded);
  int8 scale planes ``[L, N, BS, KH]`` shard identically so every write
  shares the table math;
- activations/pool outputs pinned with ``with_sharding_constraint`` at
  graph boundaries, so donation round-trips the pool without GSPMD ever
  deciding to gather it.

The dtype boundary stays where ISSUE 6 put it (ops.quant + the engine's
pool writers); this module only ever sees shapes.
"""

from __future__ import annotations

import functools
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .plan import Topology

Params = dict[str, Any]

# KV-array sharding rules by array name; rank tells payload from scale
# planes. Table rows are host-produced global block ids — replicated.
_HEAD_AXIS = "tp"


class SingleDevicePolicy:
    """The identity policy: today's single-chip engine, verbatim. Every
    hook returns its input unchanged (``zeros`` is a plain ``jnp.zeros``)
    so no sharding machinery exists anywhere near the traced graphs."""

    topology = Topology(1, 1)
    mesh = None

    def describe(self) -> dict:
        return self.topology.as_dict()

    # -- placement -----------------------------------------------------------

    def place_params(self, params: Params) -> Params:
        return params

    def place_kv(self, tree: Params) -> Params:
        return tree

    def zeros(self, shape, dtype, name: str = "") -> jnp.ndarray:
        return jnp.zeros(shape, dtype)

    def device_table(self, table_np: np.ndarray) -> jnp.ndarray:
        return jnp.asarray(table_np)

    # -- traced-graph hooks --------------------------------------------------

    def constrain_kv(self, tree: Params) -> Params:
        return tree

    # -- kvwire gather (ISSUE 16) --------------------------------------------

    def gather_kv(self, name: str, arr) -> np.ndarray:
        """Canonical full-head HOST copy of one pool array — the kvwire
        export gather. ``device_get`` on a head-sharded mesh array
        assembles the global array (single-process mesh), so a tp=2
        exporter emits byte-identical planes to a tp=1 one and import
        re-places through :meth:`place_kv`. Off the serve loop by
        construction (exports run between windows)."""
        return np.asarray(jax.device_get(arr))  # tpu9: noqa[JAX001] kvwire export / window-boundary down-page gather — never on the per-token path

    # -- spec introspection (graphcheck — ISSUE 11) --------------------------
    # The declared layout contract, exposed so the static verifier can
    # check lowered graphs against it without groping mesh internals. On
    # the identity policy every spec is None: nothing is sharded, and a
    # verifier must expect NO constraint ops in the traced graphs.

    def kv_spec(self, name: str, ndim: int):
        """PartitionSpec this policy pins KV-state array ``name`` (rank
        ``ndim``) to, or None when the policy places nothing."""
        return None

    def param_specs(self, tree: Any):
        """``(declared, resolved)`` PartitionSpec trees for a param tree:
        ``declared`` is the raw layout rule (Megatron TP×FSDP) and
        ``resolved`` what actually shards after the divisibility
        fallback — a leaf sharded in ``declared`` but replicated in
        ``resolved`` is the silent-replication case graphcheck flags.
        ``(None, None)`` on the identity policy."""
        return None, None

    # -- abstract (compile-ahead) --------------------------------------------

    def abstract(self, tree: Any, kv: bool = False) -> Any:
        return jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), tree)

    # -- observability -------------------------------------------------------

    def devices(self) -> list:
        return [jax.devices()[0]] if jax.devices() else []

    def hbm_used_gb_per_chip(self) -> float:
        return _hbm_used_gb(self.devices())

    def hbm_limit_gb_per_chip(self) -> float:
        """Smallest per-chip HBM capacity across the submesh, GB — the
        denominator of the health plane's headroom gauges (ISSUE 14).
        0.0 where the backend has no memory stats (CPU)."""
        return _hbm_limit_gb(self.devices())


class MeshPolicy(SingleDevicePolicy):
    """Mesh-sharded placement for a tp(×fsdp) serving submesh."""

    def __init__(self, topology: Topology,
                 devices: Optional[Sequence] = None):
        from ...parallel import make_mesh
        self.topology = topology
        # tp innermost (fastest ICI links), fsdp outside — the mesh.py
        # axis convention the MULTICHIP probes validated
        self.mesh = make_mesh(dp=1, fsdp=topology.fsdp, sp=1,
                              tp=topology.tp, devices=devices)

    def describe(self) -> dict:
        return self.topology.as_dict()

    def kv_spec(self, name: str, ndim: int):
        """PartitionSpec for one KV-state array by name/rank: payloads
        ``[..., KH, D]`` and scale planes ``[..., KH]`` shard the head
        axis; tables (int32 block ids) replicate. Public: this IS the
        declared KV layout contract graphcheck verifies lowered graphs
        against (ISSUE 11)."""
        from jax.sharding import PartitionSpec as P
        if name == "table" or ndim < 4:
            return P()
        dims: list = [None] * ndim
        dims[ndim - 1 if name.endswith("_scale") else ndim - 2] = _HEAD_AXIS
        return P(*dims)

    def param_specs(self, tree: Any):
        """Declared + divisibility-resolved weight specs (see base)."""
        from jax.sharding import PartitionSpec as P
        from ...parallel import decoder_param_specs, fit_spec
        try:
            declared = decoder_param_specs(tree)
        except (KeyError, TypeError):
            declared = jax.tree_util.tree_map(lambda _: P(), tree)
        resolved = jax.tree_util.tree_map(
            lambda a, s: (fit_spec(a.shape, s, self.mesh)
                          if hasattr(a, "shape") else s),
            tree, declared, is_leaf=lambda x: isinstance(x, P))
        return declared, resolved

    def _kv_sharding(self, name: str, shape):
        from jax.sharding import NamedSharding
        from ...parallel import fit_spec
        return NamedSharding(
            self.mesh, fit_spec(shape, self.kv_spec(name, len(shape)),
                                self.mesh))

    # -- placement -----------------------------------------------------------

    def place_params(self, params: Params) -> Params:
        from jax.sharding import PartitionSpec as P
        from ...parallel import decoder_param_specs, shard_params
        try:
            specs = decoder_param_specs(params)
        except (KeyError, TypeError):
            # non-decoder tree (custom handler model): replicate rather
            # than fail — correctness first, layout is the decoder path's
            specs = jax.tree_util.tree_map(lambda _: P(), params)
        return shard_params(params, self.mesh, specs)

    def place_kv(self, tree: Params) -> Params:
        return {name: jax.device_put(arr,
                                     self._kv_sharding(name, arr.shape))
                for name, arr in tree.items()}

    def zeros(self, shape, dtype, name: str = "") -> jnp.ndarray:
        # jit-with-out-shardings: each chip materializes only its shard —
        # a host zeros + device_put would stage the full array through
        # device 0 (for a 31B-class pool that is the whole HBM)
        return _sharded_zeros(tuple(shape), jnp.dtype(dtype),
                              self._kv_sharding(name, shape))()

    def device_table(self, table_np: np.ndarray) -> jnp.ndarray:
        from jax.sharding import NamedSharding, PartitionSpec as P
        return jax.device_put(jnp.asarray(table_np),
                              NamedSharding(self.mesh, P()))

    # -- traced-graph hooks --------------------------------------------------

    def constrain_kv(self, tree: Params) -> Params:
        """Pin KV-state outputs to their resident layout inside a traced
        graph, so the donated pool keeps its head sharding across every
        decode/verify/splice round trip."""
        return {name: jax.lax.with_sharding_constraint(
                    arr, self._kv_sharding(name, arr.shape))
                for name, arr in tree.items()}

    # -- abstract (compile-ahead) --------------------------------------------

    def abstract(self, tree: Any, kv: bool = False) -> Any:
        """ShapeDtypeStruct tree WITH shardings, so compile-ahead lowers
        the same SPMD executables the serve loop will dispatch. ``kv``
        trees use the KV rules (keyed by dict name); everything else uses
        the decoder param specs."""
        if kv:
            return {name: jax.ShapeDtypeStruct(
                        a.shape, a.dtype,
                        sharding=self._kv_sharding(name, a.shape))
                    for name, a in tree.items()}
        from jax.sharding import NamedSharding, PartitionSpec as P
        _, resolved = self.param_specs(tree)

        def one(a, spec):
            if not hasattr(a, "shape"):
                return a
            return jax.ShapeDtypeStruct(
                a.shape, a.dtype,
                sharding=NamedSharding(self.mesh, spec))

        return jax.tree_util.tree_map(
            one, tree, resolved, is_leaf=lambda x: isinstance(x, P))

    # -- observability -------------------------------------------------------

    def devices(self) -> list:
        return list(self.mesh.devices.flat)


@functools.lru_cache(maxsize=64)
def _sharded_zeros(shape: tuple, dtype, sharding):
    """Cached jitted sharded-zeros builder (NamedSharding hashes by mesh +
    spec): pools of one shape/layout compile their init exactly once."""
    return jax.jit(lambda: jnp.zeros(shape, dtype), out_shardings=sharding)


def _hbm_used_gb(devices: list) -> float:
    """Max live HBM across the submesh's chips, GB — 0.0 where the
    backend has no memory stats (CPU)."""
    worst = 0.0
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:   # noqa: BLE001 — backend-optional API
            return 0.0
        if not stats:
            return 0.0
        worst = max(worst, stats.get("bytes_in_use", 0) / 1e9)
    return round(worst, 3)


def _hbm_limit_gb(devices: list) -> float:
    """Min per-chip capacity across the submesh, GB (0.0 = no stats)."""
    best = float("inf")
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:   # noqa: BLE001 — backend-optional API
            return 0.0
        if not stats or not stats.get("bytes_limit"):
            return 0.0
        best = min(best, stats["bytes_limit"] / 1e9)
    return round(best, 3) if best != float("inf") else 0.0


def make_policy(topology: "Topology | str | None",
                devices: Optional[Sequence] = None) -> SingleDevicePolicy:
    """Policy for a topology: ``None``/``1x1`` → the identity policy (the
    engine stays byte-for-byte today's engine), anything larger → mesh."""
    from .plan import parse_topology
    topo = parse_topology(topology) or Topology(1, 1)
    if topo.is_single:
        return SingleDevicePolicy()
    n = len(devices) if devices is not None else len(jax.devices())
    if topo.n_chips > n:
        raise ValueError(
            f"topology {topo} needs {topo.n_chips} devices, have {n}")
    return MeshPolicy(topo, devices=devices)
