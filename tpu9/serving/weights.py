"""Streamable param-tree weight format (`*.tpu9w` directories).

The checkpoint restore chain used to be ``cache → workdir → np.load →
device`` — every hop serialized behind the previous one. This format makes
param trees *streamable*: a pytree is saved as one raw little-endian shard
file per leaf plus an ``index.json`` describing dtype/shape/order, inside a
directory whose name ends in ``.tpu9w``. Because shards are raw bytes (no
container framing), checkpoint chunks can be fed straight from the cache
into a preallocated host buffer and handed to ``jax.device_put`` the moment
a shard completes — no workdir round-trip, no deserialization step
(``tpu9/worker/weightstream.py`` runs that pipeline).

The ``.tpu9w`` suffix is the recognition contract: the worker's streaming
restore treats any manifest subtree under a ``*.tpu9w`` component as a
weight group and materializes everything else the classic way.

Scalars (python int/float/bool leaves) ride in the index skeleton directly;
only array leaves become shards.

Index versions (the ``version`` field; absent == 1, the pre-field layout):

- **v1** — plain param trees, every leaf an independent shard.
- **v2** — quantized trees (ISSUE 6): leaves come in ``{q: int8,
  scale: f32}`` pairs (``tpu9.ops.quant``), annotated with a ``role``
  field on their index entries and ``quantized: true`` at the top level.
  The byte layout is UNCHANGED — v1 readers stream v2 shards fine — but
  the version gate means a future incompatible layout fails with a clear
  error instead of a KeyError mid-restore.

Readers call :func:`check_index` before touching leaves.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Any, Optional

import numpy as np

WEIGHTS_SUFFIX = ".tpu9w"
INDEX_NAME = "index.json"
FORMAT = "tpu9-weights-v1"
# index versions this reader understands (absent `version` field == 1)
SUPPORTED_VERSIONS = (1, 2)

_LEAF = "__leaf__"
_SCALAR = "__scalar__"
_TUPLE = "__tuple__"


def _np_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes                    # jax's extended dtypes (bf16…)
        return np.dtype(getattr(ml_dtypes, name))


def _flatten(node: Any, path: str, leaves: list) -> Any:
    """Walk the tree depth-first, building a JSON skeleton whose array
    leaves are ``{"__leaf__": i}`` markers into ``leaves`` (order = stream
    order). Dicts keep insertion order — param builders are deterministic."""
    if isinstance(node, dict):
        for k in node:
            if not isinstance(k, str) or k in (_LEAF, _SCALAR, _TUPLE):
                # int keys (a legal pytree) would come back as strings —
                # a silent treedef change; marker-named keys would be
                # misparsed by _unflatten. Refuse both; the runner-level
                # saver falls back to orbax.
                raise TypeError(f"{path or '/'}: dict key {k!r} does not "
                                f"round-trip through {FORMAT}")
        return {k: _flatten(v, f"{path}/{k}", leaves)
                for k, v in node.items()}
    if isinstance(node, (list, tuple)):
        if hasattr(node, "_fields"):
            # a NamedTuple would silently come back as a plain tuple —
            # a treedef change the restored handler can't tree_map over.
            # Refuse; the runner-level saver falls back to orbax.
            raise TypeError(f"{path or '/'}: NamedTuple containers do not "
                            f"round-trip through {FORMAT}")
        out = [_flatten(v, f"{path}/{i}", leaves)
               for i, v in enumerate(node)]
        # tuples must round-trip as tuples: a restored handler whose
        # treedef silently changed list-ness would fail tree_map against
        # a cold-booted one
        return {_TUPLE: out} if isinstance(node, tuple) else out
    if isinstance(node, (bool, int, float, str)) or node is None:
        return {_SCALAR: node}
    # leaves stay UNMATERIALIZED here (shape/dtype duck-typing covers jax
    # device arrays): np.asarray of every leaf at once would hold a full
    # model-sized host copy before the first shard write — the per-leaf
    # conversion happens in save_params' write loop instead
    arr = node if hasattr(node, "shape") and hasattr(node, "dtype") \
        else np.asarray(node)
    if np.dtype(arr.dtype) == object:
        # an unrecognized container (custom pytree node, e.g. FrozenDict)
        # np.asarray'd into an object array would "save" pickle-less junk
        raise TypeError(f"{path or '/'}: {type(node).__name__} is not a "
                        f"{FORMAT}-representable node")
    leaves.append((path.lstrip("/"), arr))
    return {_LEAF: len(leaves) - 1}


def _unflatten(skel: Any, arrays: list) -> Any:
    if isinstance(skel, dict):
        if _LEAF in skel:
            return arrays[skel[_LEAF]]
        if _SCALAR in skel:
            return skel[_SCALAR]
        if _TUPLE in skel:
            return tuple(_unflatten(v, arrays) for v in skel[_TUPLE])
        return {k: _unflatten(v, arrays) for k, v in skel.items()}
    if isinstance(skel, list):
        return [_unflatten(v, arrays) for v in skel]
    raise ValueError(f"malformed weights skeleton node: {skel!r}")


def flatten_tree(tree: Any) -> tuple[Any, list[tuple[str, np.ndarray]]]:
    """Return ``(skeleton, [(key, array), ...])`` in stream order."""
    leaves: list[tuple[str, np.ndarray]] = []
    skel = _flatten(tree, "", leaves)
    return skel, leaves


def check_index(index: dict, src: str = "") -> int:
    """Validate an index's format family AND version; returns the version.
    Raises a clear :class:`ValueError` for unknown versions — a reader
    hitting a future layout must fail HERE, not with a KeyError halfway
    through a multi-GB restore."""
    where = f"{src}: " if src else ""
    if index.get("format") != FORMAT:
        raise ValueError(f"{where}not a {FORMAT} index: "
                         f"{index.get('format')!r}")
    version = index.get("version", 1)
    if version not in SUPPORTED_VERSIONS:
        raise ValueError(
            f"{where}.tpu9w index version {version} is not supported by "
            f"this reader (supported: {SUPPORTED_VERSIONS}) — upgrade "
            "tpu9 to restore this checkpoint")
    return version


def _mark_quant_pairs(entries: list[dict]) -> int:
    """Annotate quantized ``{q, scale}`` leaf pairs (tpu9.ops.quant trees):
    an int8 leaf at ``<path>/q`` whose sibling ``<path>/scale`` exists gets
    ``role: "q"`` and the sibling ``role: "scale"``. Returns the pair
    count — a nonzero count is what makes an index version 2."""
    by_key = {e["key"]: e for e in entries}
    pairs = 0
    for e in entries:
        if e["key"].endswith("/q") and e["dtype"] == "int8":
            sib = by_key.get(e["key"][:-len("/q")] + "/scale")
            if sib is not None:
                e["role"] = "q"
                sib["role"] = "scale"
                pairs += 1
    return pairs


def build_index(tree: Any) -> tuple[dict, list[np.ndarray]]:
    skel, leaves = flatten_tree(tree)
    entries = []
    arrays = []
    for i, (key, arr) in enumerate(leaves):
        if getattr(arr, "is_fully_addressable", True) is False:
            # fail BEFORE any shard write: np.asarray would raise on a
            # multi-host sharded jax.Array anyway, but mid-write the
            # partial dir would need cleanup at every call site
            raise TypeError(f"{key}: non-addressable sharded array is not "
                            f"{FORMAT}-representable")
        entries.append({"i": i, "key": key, "file": f"{i:06d}.bin",
                        "dtype": np.dtype(arr.dtype).name,
                        "shape": list(arr.shape),
                        "nbytes": int(arr.nbytes)})
        arrays.append(arr)
    pairs = _mark_quant_pairs(entries)
    index = {"format": FORMAT, "version": 2 if pairs else 1,
             "skeleton": skel, "leaves": entries,
             "total_bytes": int(sum(a.nbytes for a in arrays))}
    if pairs:
        index["quantized"] = True
    return index, arrays


def save_params(tree: Any, dest: str, quantize: Optional[str] = None) -> dict:
    """Write ``tree`` as a ``.tpu9w`` directory at ``dest`` (created). The
    caller picks a ``dest`` ending in :data:`WEIGHTS_SUFFIX` so snapshot
    manifests of the enclosing workdir are stream-recognizable.

    ``quantize="int8"`` runs ``tpu9.ops.quant.quantize_decoder`` over the
    tree first (save-time quantization, ISSUE 6): the shards land ~2x
    smaller and every downstream consumer — chunk cache, hedged peer
    reads, warm weights pool, double-buffered device puts — moves half
    the bytes with zero changes. Trees already holding quantized pairs
    mark themselves v2 with or without the flag."""
    if quantize:
        from ..ops.quant import validate_quant_mode
        validate_quant_mode(quantize)
        if quantize != "int8":
            # validated-but-unwired mode: fail here, never emit shards in
            # a different format than the caller opted into
            raise NotImplementedError(
                f"quantize mode {quantize!r} is not wired into save_params")
        if not (isinstance(tree, dict) and "layers" in tree):
            raise ValueError("quantize='int8' needs a decoder param tree "
                             "(dict with 'layers'); save this tree plain")
        from ..ops.quant import quantize_decoder
        tree = quantize_decoder(tree)
    index, arrays = build_index(tree)
    os.makedirs(dest, exist_ok=True)
    for entry, arr in zip(index["leaves"], arrays):
        with open(os.path.join(dest, entry["file"]), "wb") as f:
            # ONE leaf on host at a time (np.asarray pulls device arrays
            # here, not in build_index), and a uint8 view, not tobytes():
            # either would spike peak RSS by up to the model size inside
            # a container sized to the model (bf16 has no buffer-protocol
            # char, so the view)
            host = np.ascontiguousarray(np.asarray(arr))
            f.write(host.reshape(-1).view("u1").data)
    with open(os.path.join(dest, INDEX_NAME), "w") as f:
        json.dump(index, f)
    return index


def shard_to_array(buf, entry: dict) -> np.ndarray:
    """Zero-copy view of a filled shard buffer as its typed array."""
    arr = np.frombuffer(buf, dtype=_np_dtype(entry["dtype"]))
    return arr.reshape(entry["shape"])


def assemble(index: dict, arrays: list) -> Any:
    """Rebuild the pytree from a parsed index + arrays in leaf order."""
    check_index(index)
    if len(arrays) != len(index["leaves"]):
        raise ValueError(f"have {len(arrays)} arrays for "
                         f"{len(index['leaves'])} leaves")
    return _unflatten(index["skeleton"], list(arrays))


def load_params(src: str, mmap: bool = False) -> Any:
    """Read a ``.tpu9w`` directory back into a pytree of host arrays.
    ``mmap=True`` maps shards instead of reading them (lazy page-in)."""
    with open(os.path.join(src, INDEX_NAME)) as f:
        index = json.load(f)
    check_index(index, src)
    arrays = []
    for entry in index["leaves"]:
        path = os.path.join(src, entry["file"])
        dt = _np_dtype(entry["dtype"])
        if mmap:
            arr = np.memmap(path, dtype=dt, mode="r",
                            shape=tuple(entry["shape"]))
        else:
            with open(path, "rb") as f:
                arr = shard_to_array(f.read(), entry)
        arrays.append(arr)
    return assemble(index, arrays)


def is_weights_dir(path: str) -> bool:
    return path.endswith(WEIGHTS_SUFFIX) and os.path.isfile(
        os.path.join(path, INDEX_NAME))


def weight_group_of(rel_path: str) -> Optional[str]:
    """The ``.tpu9w`` group prefix of a manifest path, or None. The FIRST
    matching component wins (nested groups don't exist by construction)."""
    parts = rel_path.split("/")
    for i, part in enumerate(parts[:-1]):
        if part.endswith(WEIGHTS_SUFFIX):
            return "/".join(parts[: i + 1])
    return None


def manifest_weight_groups(manifest) -> dict[str, list]:
    """Group an ImageManifest's entries by ``.tpu9w`` directory. Only groups
    with an ``index.json`` entry qualify — anything else stays on the
    classic materialize path. Symlink entries disqualify their group (a
    weights dir is flat regular files by construction; a link smells like
    tampering)."""
    groups: dict[str, list] = {}
    bad: set[str] = set()
    for entry in manifest.files:
        group = weight_group_of(entry.path)
        if group is None:
            continue
        if entry.link_target:
            bad.add(group)
            continue
        groups.setdefault(group, []).append(entry)
    out = {}
    for group, entries in groups.items():
        if group in bad:
            continue
        if any(os.path.basename(e.path) == INDEX_NAME for e in entries):
            out[group] = entries
    return out


def content_key(entries) -> str:
    """Stable content hash of a weight group: the sorted (path, chunks)
    pairs. Two checkpoints of identical weights share the key — this is
    what the warm weights pool is keyed on."""
    h = hashlib.sha256()
    for entry in sorted(entries, key=lambda e: e.path):
        # NUL-framed fields: without separators a path ending in hex is
        # ambiguous against a shorter path plus one more chunk digest,
        # and the pool key MUST be collision-free across manifests
        h.update(entry.path.encode() + b"\0")
        for c in entry.chunks:
            h.update(c.encode() + b"\0")
        h.update(b"\0")
    return h.hexdigest()
