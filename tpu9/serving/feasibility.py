"""HBM feasibility math for LLM deployments (VERDICT r03 #8).

Before a deployment schedules real chips, the weights + KV cache + runtime
overhead must provably fit the slice's HBM — the reference relies on CUDA
OOMs at runtime; tpu9 validates at deploy time so config #4 (llama3-70b
on v5e-8, BASELINE.md) is accepted or rejected with arithmetic, not a
crashed container.

Accounting (per chip, tensor-parallel over ``tp`` chips):
- weights: matmul params at 1 B (int8 weight-only) or 2 B (bf16) + scales,
  embeddings always bf16; all divided by tp (row/col-sharded)
- KV cache: ``2 (k,v) × layers × max_batch × max_seq × kv_heads × head_dim
  × 2 B`` divided by tp (head-sharded; n_kv_heads % tp may force
  replication — accounted)
- overhead: XLA workspace / fragmentation reserve (default 10%) + the
  paged engine's batch-1 prefill scratch
"""

from __future__ import annotations

from dataclasses import dataclass

from ..types import TpuSpec, parse_tpu_spec


class InfeasibleDeployment(ValueError):
    """Raised at deploy time when the model + KV cannot fit the slice."""


@dataclass(frozen=True)
class HbmBudget:
    tpu: str
    chips: int
    tp: int
    # weight-only sharding on top of tp (ISSUE 9 planner): weights divide
    # by tp×fsdp, KV/scratch by the tp head shard only — fsdp chips add
    # zero KV capacity, which is why the planner prefers tp when heads
    # allow it
    fsdp: int
    hbm_per_chip_gb: float
    weight_gb_per_chip: float
    kv_gb_per_chip: float
    scratch_gb_per_chip: float
    overhead_frac: float
    # sequences the SAME kv_gb holds relative to bf16 (int8 pool: ~1.94x
    # at head_dim 128). The budget's kv bytes don't shrink under kv_quant
    # (equal-HBM auto sizing); this factor is where the win shows.
    kv_capacity_factor: float = 1.0

    @property
    def required_gb_per_chip(self) -> float:
        raw = (self.weight_gb_per_chip + self.kv_gb_per_chip
               + self.scratch_gb_per_chip)
        return raw * (1.0 + self.overhead_frac)

    @property
    def fits(self) -> bool:
        return self.required_gb_per_chip <= self.hbm_per_chip_gb

    def as_dict(self) -> dict:
        return {
            "tpu": self.tpu, "chips": self.chips, "tp": self.tp,
            "fsdp": self.fsdp,
            "hbm_per_chip_gb": round(self.hbm_per_chip_gb, 2),
            "weight_gb_per_chip": round(self.weight_gb_per_chip, 3),
            "kv_gb_per_chip": round(self.kv_gb_per_chip, 3),
            "scratch_gb_per_chip": round(self.scratch_gb_per_chip, 3),
            "overhead_frac": self.overhead_frac,
            "kv_capacity_factor": round(self.kv_capacity_factor, 3),
            "required_gb_per_chip": round(self.required_gb_per_chip, 3),
            "fits": self.fits,
        }


def matmul_param_count(cfg) -> int:
    """Per-model matmul parameters (the int8-quantizable set)."""
    per_layer = (cfg.dim * cfg.n_heads * cfg.head_dim
                 + 2 * cfg.dim * cfg.n_kv_heads * cfg.head_dim
                 + cfg.n_heads * cfg.head_dim * cfg.dim)
    if getattr(cfg, "n_experts", 0):
        per_layer += 3 * cfg.dim * cfg.hidden_dim * cfg.n_experts
        per_layer += cfg.dim * cfg.n_experts          # router
    else:
        per_layer += 3 * cfg.dim * cfg.hidden_dim
    total = per_layer * cfg.n_layers
    if not getattr(cfg, "tie_embeddings", False):
        total += cfg.dim * cfg.vocab_size             # lm_head
    return total


def weight_bytes(cfg, quantized: bool) -> int:
    """EXACT bytes of the preset's served param tree, priced on abstract
    shapes: ``jax.eval_shape`` over the same init fns ``build_params``
    uses, summed by ``ops.quant.quantized_bytes``. One source of truth —
    the HBM gate, the ``.tpu9w`` shard sizes a checkpoint emits, and the
    warm-pool ``weight_pool_mb`` sizing can no longer disagree about a
    quantized tree (the old hand-rolled estimate budgeted MoE experts at
    bf16 because per-expert int8 didn't exist; now it does, and this
    derivation tracks whatever the quantizer actually emits)."""
    import jax
    if quantized:
        from ..ops.quant import init_quantized_decoder as init
    else:
        from ..models import init_decoder as init
    from ..ops.quant import quantized_bytes
    spec = jax.eval_shape(lambda rng: init(rng, cfg), jax.random.PRNGKey(0))
    return quantized_bytes(spec)


def kv_cache_bytes(cfg, max_batch: int, max_seq: int,
                   kv_quant: bool = False) -> int:
    """Dense-equivalent KV bytes: ``max_batch`` sequences of ``max_seq``
    tokens, priced by the SAME helper the engine's pool sizing divides by
    (``paged_kv.kv_block_bytes`` — one arithmetic, no drift when modes
    are added)."""
    from .paged_kv import kv_block_bytes
    return max_batch * kv_block_bytes(cfg, max_seq, kv_quant)


def hbm_budget(preset: str, tpu: "str | TpuSpec", *, max_batch: int = 8,
               max_seq_len: int = 2048, tp: int = 0, fsdp: int = 1,
               overhead_frac: float = 0.10,
               quantize: "str | None" = None,
               kv_quant: bool = False) -> HbmBudget:
    """Compute the per-chip HBM budget for serving ``preset`` on ``tpu``
    with tensor parallelism ``tp`` (default: all chips of the slice) and
    optional weight-only ``fsdp`` sharding on top (ISSUE 9 topology
    planner: weights divide by tp×fsdp; KV divides by the tp head shard
    only). ``quantize="int8"`` prices a PLAIN preset name as int8 weights
    — the same opt-in surface ``load_engine(quantize=)``/TPU9_QUANTIZE
    uses, so a knob-opted deployment is not mispriced as bf16."""
    from .presets import resolve_preset
    cfg, quantized = resolve_preset(preset, quantize)
    spec = parse_tpu_spec(tpu) if isinstance(tpu, str) else tpu
    if spec is None:
        raise ValueError("feasibility needs a TPU spec")
    tp = tp or spec.chips

    w = weight_bytes(cfg, quantized) / (tp * max(fsdp, 1))
    # KV is head-sharded; the EVEN shard is gcd(tp, kv_heads) — min()
    # would assume a tp=6 mesh splits 8 heads 6 ways and under-count
    # per-chip KV 3x, approving deploys that OOM at runtime
    import math
    kv_shard = math.gcd(tp, cfg.n_kv_heads)
    # kv_quant does NOT shrink the budget: the engine's auto pool sizing
    # (kv_pool_blocks=0) deliberately spends the SAME HBM as the bf16
    # pool on ~2x the blocks — the win is capacity, not bytes. Pricing
    # the int8 byte count here would under-count the pool the engine
    # actually allocates ~2x and approve deploys that OOM at engine
    # construction. Deployments that pin kv_pool_blocks explicitly can
    # price themselves with kv_cache_bytes(kv_quant=True) directly.
    kv = kv_cache_bytes(cfg, max_batch, max_seq_len) / kv_shard
    # paged engine's batch-1 dense prefill scratch rides on one chip's
    # shard of the kv lanes (always model-dtype — the int8 pool
    # quantizes at splice, the scratch itself stays bf16)
    scratch = kv_cache_bytes(cfg, 1, max_seq_len) / kv_shard

    return HbmBudget(
        tpu=spec.name, chips=spec.chips, tp=tp, fsdp=max(fsdp, 1),
        hbm_per_chip_gb=float(spec.hbm_gb_per_chip),
        weight_gb_per_chip=w / 1e9,
        kv_gb_per_chip=kv / 1e9,
        scratch_gb_per_chip=scratch / 1e9,
        overhead_frac=overhead_frac,
        kv_capacity_factor=(
            kv_cache_bytes(cfg, max_batch, max_seq_len)
            / kv_cache_bytes(cfg, max_batch, max_seq_len, kv_quant=True)
            if kv_quant else 1.0))


def validate_llm_deployment(preset: str, tpu: "str | TpuSpec", *,
                            max_batch: int = 8, max_seq_len: int = 2048,
                            tp: int = 0, quantize: "str | None" = None,
                            kv_quant: bool = False) -> HbmBudget:
    """Deploy-time gate: raises :class:`InfeasibleDeployment` with the
    arithmetic when the configuration cannot fit; returns the budget when
    it can. Suggests the standard remedies in the message. ``quantize``/
    ``kv_quant`` mirror the ``load_engine`` opt-ins so knob-opted int8
    deployments are priced as what they serve."""
    budget = hbm_budget(preset, tpu, max_batch=max_batch,
                        max_seq_len=max_seq_len, tp=tp,
                        quantize=quantize, kv_quant=kv_quant)
    if not budget.fits:
        d = budget.as_dict()
        raise InfeasibleDeployment(
            f"{preset} on {d['tpu']} (tp={d['tp']}) needs "
            f"{d['required_gb_per_chip']} GB/chip "
            f"(weights {d['weight_gb_per_chip']} + KV {d['kv_gb_per_chip']}"
            f" + scratch {d['scratch_gb_per_chip']} + "
            f"{int(budget.overhead_frac * 100)}% overhead) but the chip "
            f"has {d['hbm_per_chip_gb']} GB. Remedies: int8 weights "
            f"(-50% weight bytes), smaller max_batch/max_seq_len (KV "
            f"scales linearly), or a larger slice.")
    return budget
