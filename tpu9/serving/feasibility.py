"""HBM feasibility math for LLM deployments (VERDICT r03 #8).

Before a deployment schedules real chips, the weights + KV cache + runtime
overhead must provably fit the slice's HBM — the reference relies on CUDA
OOMs at runtime; tpu9 validates at deploy time so config #4 (llama3-70b
on v5e-8, BASELINE.md) is accepted or rejected with arithmetic, not a
crashed container.

Accounting (per chip, tensor-parallel over ``tp`` chips):
- weights: matmul params at 1 B (int8 weight-only) or 2 B (bf16) + scales,
  embeddings always bf16; all divided by tp (row/col-sharded)
- KV cache: ``2 (k,v) × layers × max_batch × max_seq × kv_heads × head_dim
  × 2 B`` divided by tp (head-sharded; n_kv_heads % tp may force
  replication — accounted)
- overhead: XLA workspace / fragmentation reserve (default 10%) + the
  paged engine's batch-1 prefill scratch
"""

from __future__ import annotations

from dataclasses import dataclass

from ..types import TpuSpec, parse_tpu_spec


class InfeasibleDeployment(ValueError):
    """Raised at deploy time when the model + KV cannot fit the slice."""


@dataclass(frozen=True)
class HbmBudget:
    tpu: str
    chips: int
    tp: int
    hbm_per_chip_gb: float
    weight_gb_per_chip: float
    kv_gb_per_chip: float
    scratch_gb_per_chip: float
    overhead_frac: float

    @property
    def required_gb_per_chip(self) -> float:
        raw = (self.weight_gb_per_chip + self.kv_gb_per_chip
               + self.scratch_gb_per_chip)
        return raw * (1.0 + self.overhead_frac)

    @property
    def fits(self) -> bool:
        return self.required_gb_per_chip <= self.hbm_per_chip_gb

    def as_dict(self) -> dict:
        return {
            "tpu": self.tpu, "chips": self.chips, "tp": self.tp,
            "hbm_per_chip_gb": round(self.hbm_per_chip_gb, 2),
            "weight_gb_per_chip": round(self.weight_gb_per_chip, 3),
            "kv_gb_per_chip": round(self.kv_gb_per_chip, 3),
            "scratch_gb_per_chip": round(self.scratch_gb_per_chip, 3),
            "overhead_frac": self.overhead_frac,
            "required_gb_per_chip": round(self.required_gb_per_chip, 3),
            "fits": self.fits,
        }


def matmul_param_count(cfg) -> int:
    """Per-model matmul parameters (the int8-quantizable set)."""
    per_layer = (cfg.dim * cfg.n_heads * cfg.head_dim
                 + 2 * cfg.dim * cfg.n_kv_heads * cfg.head_dim
                 + cfg.n_heads * cfg.head_dim * cfg.dim)
    if getattr(cfg, "n_experts", 0):
        per_layer += 3 * cfg.dim * cfg.hidden_dim * cfg.n_experts
        per_layer += cfg.dim * cfg.n_experts          # router
    else:
        per_layer += 3 * cfg.dim * cfg.hidden_dim
    total = per_layer * cfg.n_layers
    if not getattr(cfg, "tie_embeddings", False):
        total += cfg.dim * cfg.vocab_size             # lm_head
    return total


def weight_bytes(cfg, quantized: bool) -> int:
    mm = matmul_param_count(cfg)
    embed = cfg.vocab_size * cfg.dim * 2              # always bf16
    if quantized:
        # int8 payload + one f32 scale per output column (≈dim⁻¹
        # relative). Stacked MoE expert weights are NOT yet quantized
        # (ops/quant.py handles 2D mats only) — budgeting them at 1
        # byte/param would under-count a Mixtral's HBM ~2x and approve
        # deploys that OOM, the exact failure this gate exists to stop.
        moe = 0
        if getattr(cfg, "n_experts", 0):
            moe = 3 * cfg.dim * cfg.hidden_dim * cfg.n_experts \
                * cfg.n_layers
        dense = mm - moe
        return dense + dense // max(cfg.dim, 1) * 4 + moe * 2 + embed
    return mm * 2 + embed


def kv_cache_bytes(cfg, max_batch: int, max_seq: int) -> int:
    return (2 * cfg.n_layers * max_batch * max_seq
            * cfg.n_kv_heads * cfg.head_dim * 2)


def hbm_budget(preset: str, tpu: "str | TpuSpec", *, max_batch: int = 8,
               max_seq_len: int = 2048, tp: int = 0,
               overhead_frac: float = 0.10) -> HbmBudget:
    """Compute the per-chip HBM budget for serving ``preset`` on ``tpu``
    with tensor parallelism ``tp`` (default: all chips of the slice)."""
    from .presets import resolve_preset
    cfg, quantized = resolve_preset(preset)
    spec = parse_tpu_spec(tpu) if isinstance(tpu, str) else tpu
    if spec is None:
        raise ValueError("feasibility needs a TPU spec")
    tp = tp or spec.chips

    w = weight_bytes(cfg, quantized) / tp
    # KV is head-sharded; the EVEN shard is gcd(tp, kv_heads) — min()
    # would assume a tp=6 mesh splits 8 heads 6 ways and under-count
    # per-chip KV 3x, approving deploys that OOM at runtime
    import math
    kv_shard = math.gcd(tp, cfg.n_kv_heads)
    kv = kv_cache_bytes(cfg, max_batch, max_seq_len) / kv_shard
    # paged engine's batch-1 dense prefill scratch rides on one chip's
    # shard of the kv lanes
    scratch = kv_cache_bytes(cfg, 1, max_seq_len) / kv_shard

    return HbmBudget(
        tpu=spec.name, chips=spec.chips, tp=tp,
        hbm_per_chip_gb=float(spec.hbm_gb_per_chip),
        weight_gb_per_chip=w / 1e9,
        kv_gb_per_chip=kv / 1e9,
        scratch_gb_per_chip=scratch / 1e9,
        overhead_frac=overhead_frac)


def validate_llm_deployment(preset: str, tpu: "str | TpuSpec", *,
                            max_batch: int = 8, max_seq_len: int = 2048,
                            tp: int = 0) -> HbmBudget:
    """Deploy-time gate: raises :class:`InfeasibleDeployment` with the
    arithmetic when the configuration cannot fit; returns the budget when
    it can. Suggests the standard remedies in the message."""
    budget = hbm_budget(preset, tpu, max_batch=max_batch,
                        max_seq_len=max_seq_len, tp=tp)
    if not budget.fits:
        d = budget.as_dict()
        raise InfeasibleDeployment(
            f"{preset} on {d['tpu']} (tp={d['tp']}) needs "
            f"{d['required_gb_per_chip']} GB/chip "
            f"(weights {d['weight_gb_per_chip']} + KV {d['kv_gb_per_chip']}"
            f" + scratch {d['scratch_gb_per_chip']} + "
            f"{int(budget.overhead_frac * 100)}% overhead) but the chip "
            f"has {d['hbm_per_chip_gb']} GB. Remedies: int8 weights "
            f"(-50% weight bytes), smaller max_batch/max_seq_len (KV "
            f"scales linearly), or a larger slice.")
    return budget
