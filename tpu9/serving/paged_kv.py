"""Host-side block allocator for the paged KV pool.

Reference analogue: the engine-side KV accounting the reference's LLM
router prices admission against (``pkg/abstractions/pod/llm.go:124``
token-pressure). tpu9 makes it real: the device cache is a pool of
fixed-size blocks (``tpu9/ops/paged_attention.py:paged_decode_attention``
reads them by table lookup), and this allocator hands logical sequence
positions physical blocks — so KV memory scales with LIVE TOKENS, not
``max_batch × max_seq`` (VERDICT r03 #5 / weak #5).

Sharing: a block may back several sequences (prefix reuse) — refcounted;
only FULL, block-aligned prefix blocks are ever shared, so decode writes
(always at positions past the shared prefix) never touch shared blocks.

Safety: admission RESERVES a worst-case budget (prompt + max_new tokens)
in accounting only; physical blocks are allocated lazily per decode
window. Reservations guarantee a mid-decode allocation can never fail
while allocated memory tracks actual live tokens.
"""

from __future__ import annotations

import collections
import hashlib
import time
from dataclasses import dataclass, field
from typing import Optional


def blocks_for(n_tokens: int, block_s: int) -> int:
    """Physical blocks needed so positions [0, n_tokens) are addressable."""
    return max(0, -(-n_tokens // block_s))


def kv_block_bytes(cfg, block_s: int, quantized: bool = False) -> int:
    """HBM bytes ONE k+v pool block holds across all layers of ``cfg``.
    The single source of truth the engine's equal-HBM pool sizing, the
    feasibility gate, and the quant bench all price blocks with — int8
    blocks carry 1 byte/element plus one f32 absmax scale per
    (position, head) vector (``tpu9.ops.quant.quantize_kv``)."""
    import numpy as np
    per_vec = cfg.head_dim * (1 if quantized
                              else np.dtype(cfg.dtype).itemsize)
    if quantized:
        per_vec += 4                       # f32 scale alongside the pool
    return 2 * cfg.n_layers * block_s * cfg.n_kv_heads * per_vec


@dataclass
class PrefixEntry:
    key: bytes
    blocks: list[int]          # full, block-aligned prefix blocks (shared)
    n_tokens: int
    last_used: float = field(default_factory=time.monotonic)
    # admissions holding this entry between lookup() and retaining its
    # blocks: eviction must not release blocks out from under them
    pins: int = 0
    # which tier physically holds the KV: "device" (blocks index the HBM
    # pool) or "host" (blocks is empty; planes live in the pool's
    # HostKvTier until an up-page re-places them) — ISSUE 20
    tier: str = "device"
    # lifetime lookup hits; with last_used this is the hits×recency
    # clock the host tier scores peer-spill candidates by
    hits: int = 0


class BlockAllocator:
    def __init__(self, n_blocks: int, block_s: int):
        self.n_blocks = n_blocks
        self.block_s = block_s
        self._free: list[int] = list(range(n_blocks - 1, -1, -1))
        self._refs = [0] * n_blocks
        self.reserved = 0          # accounting-only worst-case reservations
        # blocks reservations may count on: excludes permanently-held
        # blocks (the engine's trash block) — the engine adjusts this
        self.reserve_capacity = n_blocks

    # -- physical blocks -----------------------------------------------------

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def used_count(self) -> int:
        return self.n_blocks - len(self._free)

    def alloc(self, n: int) -> Optional[list[int]]:
        if n > len(self._free):
            return None
        out = [self._free.pop() for _ in range(n)]
        for b in out:
            self._refs[b] = 1
        return out

    def retain(self, blocks: list[int]) -> None:
        for b in blocks:
            self._refs[b] += 1

    def release(self, blocks: list[int]) -> None:
        for b in blocks:
            self._refs[b] -= 1
            if self._refs[b] == 0:
                self._free.append(b)
            elif self._refs[b] < 0:
                raise AssertionError(f"double free of block {b}")

    # -- reservations (admission control) ------------------------------------

    def can_reserve(self, n_tokens: int) -> bool:
        return (self.reserved + blocks_for(n_tokens, self.block_s)
                <= self.reserve_capacity)

    def reserve(self, n_tokens: int) -> int:
        n = blocks_for(n_tokens, self.block_s)
        self.reserved += n
        return n

    def unreserve(self, n_blocks: int) -> None:
        self.reserved -= n_blocks
        assert self.reserved >= 0


class PrefixCache:
    """Engine-level KV prefix reuse over shared pool blocks (the router's
    prefix affinity finally has a mechanism behind it — VERDICT r03
    weak #5 'the engine doesn't actually implement' note).

    Entries hold refcounts on their blocks; eviction (LRU, or on-demand
    when the allocator runs dry) releases them. Keys are hashes of
    block-aligned token prefixes, so a lookup walks from the longest
    possible prefix down and the first hit is the best reuse."""

    def __init__(self, allocator: BlockAllocator, max_blocks: int):
        self.allocator = allocator
        self.max_blocks = max_blocks
        self._entries: dict[bytes, PrefixEntry] = {}
        self.hits = 0
        self.misses = 0
        self.tokens_reused = 0
        self.evictions = 0      # lifetime counter (flight-recorder deltas)
        self.pinned = 0         # live lookup pins (O(1), not an entry scan)
        self.adopted = 0        # entries imported off the wire (ISSUE 16)
        self.spills = 0         # device→host down-pages (prefix survives)
        self.hits_device = 0    # lookup hits split by serving tier
        self.hits_host = 0
        # tier-change journal for the directory (ISSUE 20 satellite):
        # every eviction/spill appends (seq, kind, key-hex16) so the next
        # heartbeat ships a delta — without it, an entry evicted between
        # two advertisements leaves the fleet believing the prefix is
        # resident. Bounded; consumers that fall behind resync from the
        # full digest summary instead.
        self._delta_seq = 0
        self._deltas: collections.deque = collections.deque(maxlen=512)
        # set by KvPool when host tiering is on: called with the entry
        # key when a host-tier copy must be discarded (entry upgraded
        # back to device residency, or destroyed)
        self.on_host_drop = None

    def _note_delta(self, kind: str, key: bytes) -> None:
        self._delta_seq += 1
        self._deltas.append((self._delta_seq, kind, key.hex()[:16]))

    def deltas_since(self, seq: int) -> tuple[list[tuple[str, str]], int]:
        """Tier-change events after journal position ``seq`` (oldest
        first) plus the new cursor. The caller advances its cursor only
        once the delta is known-delivered (heartbeat accepted)."""
        out = [(kind, hx) for s, kind, hx in self._deltas if s > seq]
        return out, self._delta_seq

    @staticmethod
    def _key(tokens: list[int]) -> bytes:
        h = hashlib.sha1()
        h.update(b",".join(str(t).encode() for t in tokens))
        return h.digest()

    @property
    def held_blocks(self) -> int:
        return sum(len(e.blocks) for e in self._entries.values())

    def contains(self, key: bytes) -> bool:
        return key in self._entries

    def lookup(self, prompt: list[int]) -> Optional[PrefixEntry]:
        """Longest cached block-aligned strict prefix of ``prompt``.
        Strict: at least one prompt token must remain to prefill, because
        admission samples the first output from the suffix's logits.

        The returned entry is PINNED: a concurrent admission's
        ``evict_for_space`` (interleaved at any await point) must not
        release the blocks before the caller retains them. Call
        :meth:`release_pin` once the blocks are retained (or the entry is
        abandoned)."""
        bs = self.allocator.block_s
        nb = (len(prompt) - 1) // bs
        while nb > 0:
            entry = self._entries.get(self._key(prompt[:nb * bs]))
            if entry is not None:
                entry.last_used = time.monotonic()
                entry.pins += 1
                entry.hits += 1
                self.pinned += 1
                self.hits += 1
                if entry.tier == "host":
                    self.hits_host += 1
                else:
                    self.hits_device += 1
                self.tokens_reused += entry.n_tokens
                return entry
            nb -= 1
        self.misses += 1
        return None

    def release_pin(self, entry: PrefixEntry) -> None:
        entry.pins -= 1
        self.pinned -= 1
        assert entry.pins >= 0, "unbalanced prefix-cache pin release"

    # -- kvwire export/adopt (ISSUE 16) --------------------------------------

    def acquire_for_export(self,
                           tokens: list[int]) -> Optional[PrefixEntry]:
        """Longest cached block-aligned prefix of ``tokens`` for a kvwire
        export, PINNED for the duration of the payload gather — the same
        race class as the lookup/evict pin fix (PR 2): an eviction
        interleaved at the device_get await must not recycle a block
        mid-gather. Deliberately separate from :meth:`lookup`: export
        traffic is not admission traffic and must not skew the
        hit/miss/tokens_reused signals the router keys affinity on.
        Balance with :meth:`release_pin`. Non-strict: a whole-prompt
        entry is exactly what a handoff wants to ship."""
        bs = self.allocator.block_s
        nb = len(tokens) // bs
        while nb > 0:
            entry = self._entries.get(self._key(tokens[:nb * bs]))
            # host-tier entries hold no pool blocks to gather — keep
            # walking down to the longest DEVICE-resident prefix
            if entry is not None and entry.tier == "device":
                entry.last_used = time.monotonic()
                entry.pins += 1
                self.pinned += 1
                return entry
            nb -= 1
        return None

    def adopt(self, key: bytes, blocks: list[int], n_tokens: int) -> bool:
        """Register an IMPORTED prefix under the exporter's key, taking
        ownership of freshly-allocated blocks (ref already 1 from the
        alloc — no retain; eviction releases them like any entry's).
        False = an entry under this key already exists (this replica
        prefilled it concurrently) or the entry cannot fit the budget —
        the caller must release its duplicate blocks."""
        nb = len(blocks)
        if (nb == 0 or self.max_blocks <= 0 or nb > self.max_blocks
                or key in self._entries):
            return False
        self._entries[key] = PrefixEntry(key=key, blocks=list(blocks),
                                         n_tokens=n_tokens)
        self.adopted += 1
        self._evict_to_budget()
        return True

    def insert(self, prompt: list[int], slot_blocks: list[int]) -> None:
        """Register the prompt's full-block prefix, sharing the slot's
        physical blocks (retained; safe because decode never writes into
        full prefix blocks)."""
        bs = self.allocator.block_s
        nb = len(prompt) // bs
        # an entry alone bigger than the whole budget could only evict
        # everything and then itself — refuse it instead
        if nb == 0 or self.max_blocks <= 0 or nb > self.max_blocks:
            return
        key = self._key(prompt[:nb * bs])
        ent = self._entries.get(key)
        if ent is not None:
            ent.last_used = time.monotonic()
            # a host-tier entry re-prefilled on-device (recompute beat the
            # up-page, or tiering raced admission): upgrade it in place —
            # share the fresh slot blocks, drop the redundant host copy
            if ent.tier == "host" and not ent.blocks:
                blocks = slot_blocks[:nb]
                self.allocator.retain(blocks)
                ent.blocks = blocks
                ent.tier = "device"
                ent.n_tokens = nb * bs
                if self.on_host_drop is not None:
                    self.on_host_drop(key)
            return
        blocks = slot_blocks[:nb]
        self.allocator.retain(blocks)
        self._entries[key] = PrefixEntry(key=key, blocks=blocks,
                                         n_tokens=nb * bs)
        self._evict_to_budget()

    def _evict_to_budget(self) -> None:
        while self.held_blocks > self.max_blocks and self._evict_one():
            pass

    def _evict_one(self) -> bool:
        """Evict the LRU *unpinned* DEVICE entry. Pinned entries (a
        lookup handed their blocks to an admission that hasn't retained
        them yet) are untouchable — evicting one would release blocks
        another coroutine is about to splice into a slot. Host-tier
        entries hold no pool blocks, so evicting them here would free
        nothing; the HostKvTier's byte budget reaps those. Every
        eviction lands in the delta journal so the next heartbeat
        retracts the directory advertisement (ISSUE 20 satellite — the
        silent prefix-loss window)."""
        victims = [e for e in self._entries.values()
                   if e.pins == 0 and e.tier == "device"]
        if not victims:
            return False
        oldest = min(victims, key=lambda e: e.last_used)
        del self._entries[oldest.key]
        self.allocator.release(oldest.blocks)
        self.evictions += 1
        self._note_delta("evict", oldest.key)
        return True

    # -- host tier transitions (ISSUE 20) ------------------------------------

    def spill_candidates(self, n: int) -> list[PrefixEntry]:
        """Up to ``n`` LRU unpinned device entries — what a window-
        boundary down-page would move to host DRAM instead of letting
        ``_evict_one`` destroy. Pinned / in-flight entries never move."""
        victims = [e for e in self._entries.values()
                   if e.pins == 0 and e.tier == "device" and e.blocks]
        victims.sort(key=lambda e: e.last_used)
        return victims[:n]

    def spill_to_host(self, entry: PrefixEntry) -> None:
        """Transition a device entry to host residency: its pool blocks
        are released (the host tier already holds the planes), the entry
        survives for lookup. Caller guarantees the planes were captured
        first and the entry is unpinned."""
        assert entry.pins == 0 and entry.tier == "device"
        self.allocator.release(entry.blocks)
        entry.blocks = []
        entry.tier = "host"
        self.spills += 1
        self._note_delta("spill", entry.key)

    def promote_to_device(self, entry: PrefixEntry,
                          blocks: list[int]) -> None:
        """Complete an up-page: freshly-allocated blocks (ref already 1)
        now back the entry on-device. The host copy is dropped by the
        pool, not here."""
        assert entry.tier == "host" and not entry.blocks
        entry.blocks = list(blocks)
        entry.tier = "device"

    def drop(self, key: bytes, kind: str = "evict") -> None:
        """Destroy an entry outright (host-tier reap, or adoption
        cleanup), journaling the loss for the directory."""
        ent = self._entries.pop(key, None)
        if ent is None:
            return
        if ent.blocks:
            self.allocator.release(ent.blocks)
        self.evictions += 1
        self._note_delta(kind, key)

    def evict_for_space(self, blocks_needed: int) -> None:
        """Free cache-held blocks until the allocator can satisfy an
        allocation (called when a fresh alloc comes up short)."""
        while (self.allocator.free_count < blocks_needed
               and self._evict_one()):
            pass

    def stats(self) -> dict:
        return {"entries": len(self._entries),
                "held_blocks": self.held_blocks,
                "hits": self.hits, "misses": self.misses,
                "tokens_reused": self.tokens_reused,
                "evictions": self.evictions, "pinned": self.pinned,
                "adopted": self.adopted, "spills": self.spills,
                "hits_device": self.hits_device,
                "hits_host": self.hits_host}
