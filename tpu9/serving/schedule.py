"""Window scheduling for the serving engine (ISSUE 9 engine split).

The engine split's scheduling third: every "what should the next device
dispatch be" decision — decode-window size (K), speculative-verify
eligibility and the acceptance-EWMA gate, and the admission-can-proceed
check that shrinks windows when a queued request could actually land.
Pure host arithmetic over the engine's scheduling state (host length
mirrors, budgets, in-flight step counts); it never touches device arrays
or dispatches anything itself, so it is identical on one chip and on a
sharded submesh.

The scheduler reads the engine directly (they are one subsystem split by
responsibility, not an RPC boundary) and records WHY it chose a window in
``engine._pick_reason`` — the flight recorder's "why was K small" answer.
"""

from __future__ import annotations


class WindowScheduler:
    """Scheduling brain for one :class:`~tpu9.serving.engine.
    InferenceEngine` — constructed by, and reading, that engine."""

    def __init__(self, engine):
        self.engine = engine

    def admission_can_proceed(self) -> bool:
        """True only when a waiting request could ACTUALLY be admitted
        right now (free slot + KV room for the FIFO head) — the only case
        where shrinking the next window to K=1 buys admission latency.
        The old check (`not queue.empty()`) collapsed throughput to
        single-step windows under saturation, when the queued head could
        not be admitted anyway (batch full / pool exhausted) and small
        windows bought nothing."""
        e = self.engine
        if e.active.all():
            return False
        head = None
        if e.paged and e._wait_room:
            head = e._wait_room[0]
        else:
            q = getattr(e._queue, "_queue", None)    # deque peek, no pop
            if q:
                head = q[0]
        return head is not None and e._room_for(head)

    def pick_steps(self) -> int:
        """Largest decode-window bucket every active slot can absorb: no
        slot may outrun its max_new_tokens budget past the window (tokens
        beyond a stop are discarded host-side, so only bounded compute is
        wasted) nor its cache room. Budget/room subtract steps already in
        flight (the steady-state overlap window). Admission latency wins
        when an admission could actually proceed: K=1."""
        e = self.engine
        if self.admission_can_proceed():
            # shrink to the smallest window so the waiting head admits
            # sooner — the flight recorder's "why was K small" answer
            e._pick_reason = "admission"
            return e.ecfg.decode_steps[0]
        limit = max(e.ecfg.decode_steps)
        for slot in range(e.ecfg.max_batch):
            req = e.slot_req[slot]
            if req is None or not e.active[slot]:
                continue
            remaining = (req.max_new_tokens - len(req.generated)
                         - e._inflight_steps)
            room = (e.ecfg.max_seq_len - 1 - e._host_len[slot]
                    - e._inflight_steps)
            limit = min(limit, max(1, remaining), max(1, room))
        e._pick_reason = ("max" if limit >= max(e.ecfg.decode_steps)
                          else "budget")
        for k in reversed(e.ecfg.decode_steps):
            if k <= limit:
                return k
        return e.ecfg.decode_steps[0]

    def spec_room_len(self) -> int:
        """Largest spec bucket the batch has ROOM for, or 0 when
        speculation is off or structurally blocked (imminent admission,
        cache room, exhausted budgets). Slots near their cache limit veto
        the bucket — a dense write past max_seq_len would clamp backwards
        over valid KV."""
        e = self.engine
        if not e._spec_lens:
            return 0
        if self.admission_can_proceed():
            return 0              # admission latency wins, as for K
        min_room = e.ecfg.max_seq_len
        max_remaining = 0
        any_active = False
        for slot in range(e.ecfg.max_batch):
            req = e.slot_req[slot]
            if req is None or not e.active[slot]:
                continue
            any_active = True
            min_room = min(min_room,
                           e.ecfg.max_seq_len - 1
                           - int(e._host_len[slot])
                           - e._inflight_steps)
            max_remaining = max(max_remaining,
                                req.max_new_tokens - len(req.generated)
                                - e._inflight_steps)
        if not any_active or max_remaining < 2:
            return 0
        for s in sorted(e._spec_lens, reverse=True):
            if s + 1 <= min_room:
                return s
        return 0

    def spec_gate(self, s: int) -> int:
        """Acceptance-EWMA gate: speculate only when the mean EFFECTIVE
        acceptance over active slots clears the floor. Effective means a
        slot with nothing to propose RIGHT NOW contributes 0 — a verify
        window hands it ~1 token where a classic K-step window hands it
        K, so idle proposers must drag the decision toward classic (their
        optimistic starting EWMA must not). Below the floor speculation
        auto-disables, except one probe window every ``spec_probe_every``
        classic windows — which is how a stream that turns repetitive
        later gets speculation back."""
        e = self.engine
        total = 0.0
        n = 0
        for slot in range(e.ecfg.max_batch):
            if e.slot_req[slot] is None or not e.active[slot]:
                continue
            n += 1
            st = e._spec_slots[slot]
            if st is not None and st.proposer.propose(1):
                total += st.ewma
        if n == 0:
            return 0
        mean = total / n
        if mean >= e.ecfg.spec_min_accept:
            e._spec_disabled_windows = 0
            return s
        e._spec_disabled_windows += 1
        pe = e.ecfg.spec_probe_every
        if pe > 0 and e._spec_disabled_windows >= pe:
            e._spec_disabled_windows = 0
            return s
        return 0

    def downpage_quota(self) -> int:
        """How many prefix-cache entries the current window boundary
        should down-page to host DRAM (ISSUE 20): 0 unless the free list
        has sunk under the low-water mark — the point where the NEXT
        burst of admissions would push ``evict_for_space`` into
        destroying prefixes the host tier could have kept. Bounded per
        boundary (each down-page is one device gather) so a pressure
        spike amortizes over windows instead of stalling one."""
        e = self.engine
        pool = e.pool
        if pool is None or not pool.tiered:
            return 0
        alloc = pool.allocator
        # low water: an eighth of the pool, or at least one admission
        # chunk's worth of blocks — below it, eviction is imminent
        chunk_blocks = max(1, e._chunk // e.ecfg.kv_block_size)
        low = max(2 * chunk_blocks, alloc.n_blocks // 8)
        if alloc.free_count >= low:
            return 0
        return 2
