"""Engine construction from model presets, shared by the LLM runner and the
benchmark harness so the number the bench reports comes from the exact code
path a ``@endpoint`` deployment serves.

The flagship single-chip serving config is ``llama3-8b`` with int8
weight-only quantization: 8B params in bf16 are 16.06 GB — more than a
v5e's 16 GiB HBM — so the reference north-star config #2 (Llama-3-8B on
v5e-1, BASELINE.md) is served int8 (~8.1 GB weights + bf16 KV cache), the
standard weight-only recipe for this chip class.
"""

from __future__ import annotations

from typing import Optional

from .engine import EngineConfig, InferenceEngine


def resolve_preset(name: str, quantize: Optional[str] = None):
    """Return (DecoderConfig, quantized: bool) for a preset name.
    ``<preset>-int8`` suffixes select int8 weight-only quantization;
    ``quantize="int8"`` selects it for a plain name (the per-preset
    opt-in ``load_engine`` exposes — ISSUE 6)."""
    from ..ops.quant import validate_quant_mode
    quantize = validate_quant_mode(quantize)
    if quantize and quantize != "int8":
        # a mode added to SUPPORTED_MODES but not wired into the init/
        # quantizer path must fail here, not silently build a bf16 tree
        raise NotImplementedError(
            f"quantize mode {quantize!r} is not wired into the presets")
    from ..models.gemma import GEMMA_PRESETS
    from ..models.llama import LLAMA_PRESETS
    from ..models.mixtral import MIXTRAL_PRESETS
    presets = {**LLAMA_PRESETS, **GEMMA_PRESETS, **MIXTRAL_PRESETS}
    quantized = name.endswith("-int8") or quantize == "int8"
    base = name[:-len("-int8")] if name.endswith("-int8") else name
    if base not in presets:
        raise KeyError(f"unknown model preset {base!r}; have {sorted(presets)}")
    return presets[base], quantized


def build_params(name: str, seed: int = 0, quantize: Optional[str] = None):
    """Random-initialized params for a preset (weight loading from a real
    checkpoint is ``tpu9.serving.weights``' concern). int8 presets are
    synthesized directly at int8 so the bf16 intermediate never exists."""
    import jax
    cfg, quantized = resolve_preset(name, quantize)
    rng = jax.random.PRNGKey(seed)
    if quantized:
        from ..ops.quant import init_quantized_decoder
        return init_quantized_decoder(rng, cfg), cfg
    from ..models import init_decoder
    return init_decoder(rng, cfg), cfg


def abstract_params_for(cfg, quantized: bool = False):
    """Abstract (``jax.ShapeDtypeStruct``) param tree for an explicit
    DecoderConfig — ``jax.eval_shape`` over the same init fn real params
    come from, so spec and params can never drift apart. Exposed for
    graphcheck's depth-reduced matrix cells (ISSUE 11); presets go
    through :func:`params_spec`."""
    import jax
    if quantized:
        from ..ops.quant import init_quantized_decoder
        init = init_quantized_decoder
    else:
        from ..models import init_decoder
        init = init_decoder
    return jax.eval_shape(lambda rng: init(rng, cfg), jax.random.PRNGKey(0))


def params_spec(name: str, quantize: Optional[str] = None):
    """Abstract (``jax.ShapeDtypeStruct``) param tree for a preset — the
    shapes compile-ahead needs before a single weight byte has streamed."""
    cfg, quantized = resolve_preset(name, quantize)
    return abstract_params_for(cfg, quantized), cfg


def load_engine(name: str, *, max_batch: int = 8, max_seq_len: int = 2048,
                prefill_buckets: tuple = (128, 512, 2048),
                decode_steps: tuple = (1, 8, 32),
                paged: Optional[bool] = None,
                kv_block_size: int = 256,
                kv_pool_blocks: int = 0,
                prefix_cache_blocks: Optional[int] = None,
                spec_len: int = 0,
                spec_min_accept: float = 0.35,
                quantize: Optional[str] = None,
                kv_quant: Optional[str] = None,
                flight_cap: int = 256,
                engine_cfg: Optional[EngineConfig] = None,
                seed: int = 0,
                compile_ahead: bool = False,
                topology=None,
                tpu: Optional[str] = None) -> InferenceEngine:
    """``paged=None`` (default) enables the paged-KV engine whenever the
    alignment invariants hold (block | chunk | max_seq_len) — the
    production serving path (block allocator + chunked prefill + prefix
    reuse). ``paged=False`` forces the legacy dense cache.
    ``prefix_cache_blocks=0`` DISABLES the prefix cache (None = auto).

    ``spec_len`` enables self-speculative decoding (prompt-lookup n-gram
    drafts verified in one batched forward — ISSUE 5): no draft model, so
    it works for EVERY preset; ``spec_min_accept`` is the acceptance-EWMA
    floor below which the engine auto-falls-back to classic windowed
    decode (adversarial prompts never regress past a probe's worth of
    wasted verify compute). Greedy output is token-identical with the
    knob on or off.

    ``quantize="int8"`` opts a PLAIN preset name into int8 weight-only
    serving (equivalent to the ``-int8`` suffix); ``kv_quant="int8"``
    stores the paged KV pool as int8 with per-vector scales, sizing the
    auto pool to the same HBM the bf16 pool would use — ~2x the blocks,
    so admission headroom and the router's heartbeated ``kv_blocks``
    double (ISSUE 6). The two knobs are independent; together they are
    quantized serving end-to-end.

    ``compile_ahead=True`` builds the engine on the preset's ABSTRACT param
    spec and runs :meth:`InferenceEngine.precompile` in a thread WHILE the
    weights materialize, binding them when both finish — serving bring-up
    pays max(compile, weight load) instead of their sum (λScale-style
    pipelined bring-up; the per-graph timings land in
    ``engine.compile_ahead_timings``).

    ``topology`` (ISSUE 9) selects the serving submesh: ``"2x1"`` /
    ``"tp=2,fsdp=2"`` / a :class:`~tpu9.serving.shard.Topology` shard
    weights and the paged-KV head axis across tp(×fsdp) local devices;
    ``"auto"`` plans the smallest submesh that provably fits (needs
    ``tpu``, e.g. ``"v5e-8"``, for the HBM arithmetic). ``None`` honors
    the ``TPU9_TOPOLOGY`` env override and otherwise serves single-chip —
    a ``1x1`` engine compiles bit-identical graphs to a topology-oblivious
    build."""
    cfg, _quantized = resolve_preset(name, quantize)
    from .shard import make_policy, resolve_topology
    topo = resolve_topology(topology, preset=name, tpu=tpu,
                            max_batch=max_batch, max_seq_len=max_seq_len,
                            quantize=quantize, kv_quant=bool(kv_quant))
    policy = make_policy(topo)
    from ..ops.quant import validate_quant_mode
    kv_quant = validate_quant_mode(kv_quant, "kv_quant")
    if engine_cfg is not None and kv_quant \
            and engine_cfg.kv_quant != kv_quant:
        # an explicit engine_cfg replaces the whole knob surface — a
        # kv_quant opt-in it doesn't carry would be silently dropped,
        # serving a bf16 pool the caller sized admission/HBM around
        raise ValueError(
            "kv_quant conflicts with the explicit engine_cfg — set "
            "EngineConfig(kv_quant=...) there instead")
    # the chunk is the smallest prefill bucket; the block size must divide
    # it (a chunk smaller than a block would lose prefill KV — the engine
    # rejects that) AND divide max_seq_len; max_seq_len must also be a
    # chunk multiple or the final chunk window would clamp past the cache
    chunk = min(prefill_buckets)
    block = min(kv_block_size, chunk)
    if paged is None:
        paged = (max_seq_len % block == 0 and chunk % block == 0
                 and max_seq_len % chunk == 0)
    if kv_quant and not paged:
        # silently serving a bf16 pool after an explicit int8-KV opt-in
        # would fake the capacity win the caller sized admission around
        raise ValueError(
            "kv_quant='int8' needs the paged engine, but the alignment "
            f"invariants rejected paging (block {block}, chunk {chunk}, "
            f"max_seq_len {max_seq_len})")
    ecfg = engine_cfg or EngineConfig(
        max_batch=max_batch, max_seq_len=max_seq_len,
        prefill_buckets=prefill_buckets, decode_steps=decode_steps,
        kv_block_size=block if paged else 0,
        kv_pool_blocks=kv_pool_blocks,
        prefill_chunk=chunk if paged else 0,
        # `or` would make an explicit 0 (documented: disables) silently
        # re-enable the auto default
        prefix_cache_blocks=prefix_cache_blocks
        if prefix_cache_blocks is not None
        else (max_seq_len // block if paged else 0),
        spec_len=spec_len, spec_min_accept=spec_min_accept,
        kv_quant=kv_quant or "",
        # flight recorder (ISSUE 8): per-window black box; 0 disables
        flight_cap=flight_cap)
    if compile_ahead:
        import logging
        import threading
        import time

        from ..observability import coldstart as _cs
        from ..observability.trace import tracer
        spec, _ = params_spec(name, quantize)
        engine = InferenceEngine(spec, cfg, ecfg, policy=policy)
        timings: dict = {}
        errors: list = []
        # monotonic window of the ACTUAL compile work inside the thread,
        # recorded as a restore.compile_ahead span after join — the
        # overlap with the weight-load interval is the evidence that
        # bring-up paid max(compile, load), not their sum (ISSUE 13)
        compile_iv: list = [None, None]

        def _precompile() -> None:
            compile_iv[0] = time.monotonic()
            try:
                timings.update(engine.precompile())
            except Exception as exc:   # noqa: BLE001 — surfaced after join
                errors.append(exc)
            finally:
                compile_iv[1] = time.monotonic()

        wall_anchor = time.time()
        anchor_mono = time.monotonic()
        compiler = threading.Thread(target=_precompile,
                                    name="tpu9-compile-ahead", daemon=True)
        compiler.start()
        params, _ = build_params(name, seed=seed,    # ∥ the compile
                                 quantize=quantize)
        load_end = time.monotonic()
        compiler.join()
        if errors:
            # lazy compile still serves correctly — but the bring-up stall
            # compile-ahead exists to hide must be attributable in logs
            logging.getLogger("tpu9.serving").warning(
                "compile-ahead failed (%s); graphs compile lazily on "
                "first use", errors[0])
        tracer.record_window(_cs.SPAN_LOAD, wall_anchor, anchor_mono,
                             anchor_mono, load_end,
                             attrs={"preset": name, "source": "build"})
        tracer.record_window(_cs.SPAN_COMPILE_AHEAD, wall_anchor,
                             anchor_mono, compile_iv[0], compile_iv[1],
                             attrs={"preset": name,
                                    "graphs": len(timings),
                                    "failed": bool(errors)})
        bind_start = time.monotonic()
        with tracer.span(_cs.SPAN_BIND, attrs={"preset": name}):
            engine.bind_params(params)
        bind_end = time.monotonic()
        engine.compile_ahead_timings = timings
        # bring-up decomposition the runner heartbeats as coldstart_*
        # extras (flat scalars; engine.stats() forwards them verbatim)
        engine.bringup = {
            "load_s": round(load_end - anchor_mono, 4),
            "compile_ahead_s": round((compile_iv[1] or anchor_mono)
                                     - (compile_iv[0] or anchor_mono), 4),
            "bind_s": round(bind_end - bind_start, 4),
            "compile_overlap_s": round(_cs.interval_overlap_s(
                (anchor_mono, load_end),
                (compile_iv[0], compile_iv[1])), 4)}
        return engine
    params, _ = build_params(name, seed=seed, quantize=quantize)
    # placement through the policy BEFORE construction: the engine's pool
    # arrays and the weights must land on the same submesh
    return InferenceEngine(policy.place_params(params), cfg, ecfg,
                           policy=policy)
