"""Typed input/output schemas for endpoints, functions, and task queues.

Reference analogue: ``sdk/src/beta9/schema.py`` (SchemaField hierarchy,
Schema metaclass, dynamic from_dict/to_dict round-trip) wired into the
runner via stub config (``sdk/src/beta9/runner/common.py:212-221``).

tpu9 redesign: one wheel serves both the SDK and the in-container runner,
so the schema lives at package top level and serializes through the stub
config → ``TPU9_INPUTS``/``TPU9_OUTPUTS`` env → runner validation. Fields
register themselves by ``kind`` via ``__init_subclass__`` (no metaclass on
the field side), and a Schema subclass collects its fields the same way —
declaration order preserved, inheritance composed.
"""

from __future__ import annotations

import base64
import binascii
import json
from typing import Any, Optional


class ValidationError(Exception):
    """Raised when a client-supplied value does not satisfy a field or
    schema. Runners map this to HTTP 400."""

    def __init__(self, message: str, field: str = ""):
        super().__init__(message)
        self.message = message
        self.field = field

    def to_payload(self) -> dict:
        out = {"error": "validation", "message": self.message}
        if self.field:
            out["field"] = self.field
        return out


class OutputValidationError(Exception):
    """Raised when a *handler's return value* violates the declared output
    schema — a server-side defect, surfaced as HTTP 500 (never blamed on
    the client)."""


class Field:
    """Base class for schema fields. Subclasses register by ``kind``."""

    kind = ""
    _registry: dict[str, type["Field"]] = {}

    def __init_subclass__(cls, **kwargs):
        super().__init_subclass__(**kwargs)
        if cls.kind:
            Field._registry[cls.kind] = cls

    def __init__(self, required: bool = True, default: Any = None):
        self.required = required
        self.default = default

    # -- the two value-direction hooks --------------------------------------
    def check(self, value: Any) -> Any:
        """Validate + coerce an incoming (wire) value to the python value."""
        return value

    def encode(self, value: Any) -> Any:
        """Serialize a python value back to a JSON-safe wire value."""
        return value

    # -- spec round-trip -----------------------------------------------------
    def params(self) -> dict:
        """Subclass hook: kind-specific spec parameters."""
        return {}

    def spec(self) -> dict:
        out = {"kind": self.kind, **self.params()}
        if not self.required:
            out["required"] = False
            if self.default is not None:
                out["default"] = self.default
        return out

    @classmethod
    def from_spec(cls, data: dict) -> "Field":
        kind = data.get("kind", "")
        sub = cls._registry.get(kind)
        if sub is None:
            raise ValidationError(f"unknown field kind {kind!r}")
        params = {k: v for k, v in data.items() if k != "kind"}
        return sub._from_params(params)

    @classmethod
    def _from_params(cls, params: dict) -> "Field":
        return cls(required=params.get("required", True),
                   default=params.get("default"))


class String(Field):
    kind = "string"

    def __init__(self, max_len: int = 0, **kw):
        super().__init__(**kw)
        self.max_len = int(max_len)

    def check(self, value: Any) -> str:
        if not isinstance(value, str):
            raise ValidationError(f"expected string, got {type(value).__name__}")
        if self.max_len and len(value) > self.max_len:
            raise ValidationError(f"string longer than {self.max_len}")
        return value

    def params(self) -> dict:
        return {"max_len": self.max_len} if self.max_len else {}

    @classmethod
    def _from_params(cls, p: dict) -> "String":
        return cls(max_len=p.get("max_len", 0),
                   required=p.get("required", True), default=p.get("default"))


class Integer(Field):
    kind = "integer"

    def check(self, value: Any) -> int:
        # bool is an int subclass but "true" is never a valid integer input
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            raise ValidationError(
                f"expected integer, got {type(value).__name__}")
        if isinstance(value, float) and not value.is_integer():
            raise ValidationError(f"expected integer, got float {value}")
        return int(value)


class Float(Field):
    kind = "float"

    def check(self, value: Any) -> float:
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            raise ValidationError(f"expected number, got {type(value).__name__}")
        return float(value)


class Boolean(Field):
    kind = "boolean"

    def check(self, value: Any) -> bool:
        if not isinstance(value, bool):
            raise ValidationError(f"expected boolean, got {type(value).__name__}")
        return value


class JSON(Field):
    """Any JSON value (dict or list)."""

    kind = "json"

    def check(self, value: Any) -> Any:
        if isinstance(value, str):
            try:
                value = json.loads(value)
            except json.JSONDecodeError as e:
                raise ValidationError(f"invalid JSON string: {e}") from e
        if not isinstance(value, (dict, list)):
            raise ValidationError(
                f"expected JSON object/array, got {type(value).__name__}")
        return value


class File(Field):
    """Binary payloads carried as base64 on the wire, bytes in Python."""

    kind = "file"

    def __init__(self, max_bytes: int = 0, **kw):
        super().__init__(**kw)
        self.max_bytes = int(max_bytes)

    def check(self, value: Any) -> bytes:
        if isinstance(value, bytes):
            data = value
        elif isinstance(value, str):
            b64 = value.split(",", 1)[1] if value.startswith("data:") else value
            try:
                data = base64.b64decode(b64, validate=True)
            except (binascii.Error, ValueError) as e:
                raise ValidationError(f"invalid base64 file: {e}") from e
        else:
            raise ValidationError(
                f"expected file (bytes or base64), got {type(value).__name__}")
        if self.max_bytes and len(data) > self.max_bytes:
            raise ValidationError(f"file larger than {self.max_bytes} bytes")
        return data

    def encode(self, value: Any) -> str:
        if isinstance(value, str):
            value = value.encode()
        return base64.b64encode(value).decode()

    def params(self) -> dict:
        return {"max_bytes": self.max_bytes} if self.max_bytes else {}

    @classmethod
    def _from_params(cls, p: dict) -> "File":
        return cls(max_bytes=p.get("max_bytes", 0),
                   required=p.get("required", True), default=p.get("default"))


class Image(Field):
    """Images on the wire as base64; decoded to PIL when available, bytes
    otherwise (PIL is optional — zero hard deps beyond the baked-in set)."""

    kind = "image"

    def __init__(self, max_width: int = 0, max_height: int = 0, **kw):
        super().__init__(**kw)
        self.max_width = int(max_width)
        self.max_height = int(max_height)

    @staticmethod
    def _pil():
        try:
            from PIL import Image as PILImage
            return PILImage
        except ImportError:
            return None

    def check(self, value: Any) -> Any:
        data = File().check(value)
        pil = self._pil()
        if pil is None:
            return data
        import io
        try:
            img = pil.open(io.BytesIO(data))
            img.load()
        except Exception as e:
            raise ValidationError(f"invalid image: {e}") from e
        if self.max_width and img.width > self.max_width:
            raise ValidationError(f"image wider than {self.max_width}")
        if self.max_height and img.height > self.max_height:
            raise ValidationError(f"image taller than {self.max_height}")
        return img

    def encode(self, value: Any) -> str:
        pil = self._pil()
        if pil is not None and isinstance(value, pil.Image):
            import io
            buf = io.BytesIO()
            value.save(buf, format=value.format or "PNG")
            value = buf.getvalue()
        return File().encode(value)

    def params(self) -> dict:
        out = {}
        if self.max_width:
            out["max_width"] = self.max_width
        if self.max_height:
            out["max_height"] = self.max_height
        return out

    @classmethod
    def _from_params(cls, p: dict) -> "Image":
        return cls(max_width=p.get("max_width", 0),
                   max_height=p.get("max_height", 0),
                   required=p.get("required", True), default=p.get("default"))


class Array(Field):
    """Homogeneous list of a nested field type."""

    kind = "array"

    def __init__(self, item: Optional[Field] = None, **kw):
        super().__init__(**kw)
        self.item = item or JSON()

    def check(self, value: Any) -> list:
        if not isinstance(value, list):
            raise ValidationError(f"expected array, got {type(value).__name__}")
        return [self.item.check(v) for v in value]

    def encode(self, value: Any) -> list:
        return [self.item.encode(v) for v in value]

    def params(self) -> dict:
        return {"item": self.item.spec()}

    @classmethod
    def _from_params(cls, p: dict) -> "Array":
        item = Field.from_spec(p["item"]) if "item" in p else JSON()
        return cls(item=item, required=p.get("required", True),
                   default=p.get("default"))


class Object(Field):
    """Nested schema field."""

    kind = "object"

    def __init__(self, schema: Optional[type["Schema"]] = None, **kw):
        super().__init__(**kw)
        self.schema = schema

    def check(self, value: Any) -> dict:
        if not isinstance(value, dict):
            raise ValidationError(f"expected object, got {type(value).__name__}")
        return self.schema.validate(value) if self.schema else value

    def encode(self, value: Any) -> dict:
        if self.schema and isinstance(value, dict):
            return self.schema.encode(value)
        return value

    def params(self) -> dict:
        return {"fields": self.schema.to_spec()["fields"]} if self.schema \
            else {}

    @classmethod
    def _from_params(cls, p: dict) -> "Object":
        schema = Schema.from_spec({"fields": p["fields"]}) if "fields" in p \
            else None
        return cls(schema=schema, required=p.get("required", True),
                   default=p.get("default"))


class Schema:
    """Declare fields as class attributes::

        class Inputs(tpu9.Schema):
            prompt = tpu9.schema.String()
            max_tokens = tpu9.schema.Integer(required=False, default=64)

    The gateway stores ``to_spec()`` in stub config; the runner rebuilds it
    with ``from_spec()`` and validates every request before the handler runs.
    """

    _fields: dict[str, Field] = {}

    def __init_subclass__(cls, **kwargs):
        super().__init_subclass__(**kwargs)
        fields: dict[str, Field] = {}
        for base in reversed(cls.__mro__[1:]):
            fields.update(getattr(base, "_fields", {}))
        fields.update({k: v for k, v in vars(cls).items()
                       if isinstance(v, Field)})
        cls._fields = fields

    def __init__(self, **kwargs):
        validated = self.validate(kwargs)
        for k, v in validated.items():
            setattr(self, k, v)
        self._data = validated

    # -- validation ----------------------------------------------------------
    @classmethod
    def validate(cls, data: Any) -> dict:
        if not isinstance(data, dict):
            raise ValidationError(
                f"expected JSON object, got {type(data).__name__}")
        out = {}
        for name, f in cls._fields.items():
            if name not in data:
                if f.required:
                    raise ValidationError(f"missing required field {name!r}",
                                          field=name)
                out[name] = f.default
                continue
            try:
                out[name] = f.check(data[name])
            except ValidationError as e:
                raise ValidationError(f"{name}: {e.message}",
                                      field=name) from e
        return out

    @classmethod
    def encode(cls, data: dict) -> dict:
        """Serialize a validated dict back to wire form (outputs path)."""
        out = {}
        for name, f in cls._fields.items():
            if name in data:
                out[name] = f.encode(data[name])
        # pass through extras untouched — outputs may carry extra keys
        for k, v in data.items():
            if k not in out:
                out[k] = v
        return out

    @classmethod
    def encode_output(cls, data: dict) -> dict:
        """Outputs path: handler return values are already python-side
        (PIL images, bytes), so they are encoded — not check()ed, which
        expects wire form — and any failure is the *handler's* fault."""
        missing = [n for n, f in cls._fields.items()
                   if f.required and n not in data]
        if missing:
            raise OutputValidationError(
                f"handler output missing required field(s): {missing}")
        try:
            return cls.encode(data)
        except Exception as e:
            raise OutputValidationError(
                f"handler output does not match output schema: {e}") from e

    def dump(self) -> dict:
        return self.encode(self._data)

    # -- spec round-trip -----------------------------------------------------
    @classmethod
    def to_spec(cls) -> dict:
        return {"fields": {n: f.spec() for n, f in cls._fields.items()}}

    @classmethod
    def from_spec(cls, spec: dict) -> type["Schema"]:
        attrs = {n: Field.from_spec(fs)
                 for n, fs in spec.get("fields", {}).items()}
        return type("DynamicSchema", (Schema,), attrs)

    @classmethod
    def object(cls, fields: dict) -> type["Schema"]:
        """Build a schema class from a plain dict of fields; nested dicts
        and Schema subclasses become Object fields."""
        attrs: dict[str, Field] = {}
        for k, v in fields.items():
            if isinstance(v, dict):
                attrs[k] = Object(cls.object(v))
            elif isinstance(v, type) and issubclass(v, Schema):
                attrs[k] = Object(v)
            elif isinstance(v, Field):
                attrs[k] = v
            else:
                raise TypeError(f"field {k!r}: expected Field/Schema/dict, "
                                f"got {type(v).__name__}")
        return type("DynamicSchema", (cls,), attrs)


def schema_spec(obj: Any) -> Optional[dict]:
    """Normalize an ``inputs=``/``outputs=`` argument to a spec dict."""
    if obj is None:
        return None
    if isinstance(obj, dict) and "fields" in obj:
        return obj
    if isinstance(obj, dict):
        return Schema.object(obj).to_spec()
    if isinstance(obj, type) and issubclass(obj, Schema):
        return obj.to_spec()
    raise TypeError(f"expected Schema subclass or field dict, got {obj!r}")
