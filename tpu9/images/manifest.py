"""Chunked image manifests — tpu9's lazy image format (CLIP analogue).

Reference analogue: the external ``beam-cloud/clip`` archive format mounted
over FUSE (pkg/worker/image.go:274). tpu9's manifest is a flat JSON document:
every file carries mode/size and the sha256 list of its chunks; content is
deduplicated in the distributed cache. Materialization can be eager
(hardlink/copy all chunks) or sparse (fetch only requested prefixes), and a
FUSE frontend can mount the same manifest without format changes.
"""

from __future__ import annotations

import hashlib
import json
import os
from dataclasses import dataclass, field
from typing import Iterator, Optional

DEFAULT_CHUNK = 4 * 1024 * 1024


def safe_join(dest: str, rel: str, dest_real: str | None = None) -> str:
    """Join a manifest-supplied relative path onto ``dest``, refusing
    absolute paths, ``..`` traversal, and symlinked parents that resolve
    outside ``dest``. Manifests can arrive over the wire (manifest_fetch)
    and every materialize/skeleton/fill writer runs with root privileges —
    a hostile entry must never place a write outside the bundle (advisor
    r04).

    Only the PARENT directory chain is realpath-resolved; the final
    component is returned unresolved so an entry that IS a symlink (legit:
    venv links to absolute host paths) can be re-checked/re-created on a
    second pass (lazy-fill resume) without being resolved through.
    Callers looping over a manifest should hoist ``dest_real =
    os.path.realpath(dest)`` and pass it in (one lstat walk per entry is
    enough on the cold-start path)."""
    if not rel or os.path.isabs(rel) or "\x00" in rel:
        raise ValueError(f"unsafe manifest path: {rel!r}")
    if dest_real is None:
        dest_real = os.path.realpath(dest)
    norm = os.path.normpath(rel)
    if norm in (".", "..") or norm.startswith(".." + os.sep):
        raise ValueError(f"manifest path escapes bundle: {rel!r}")
    full = os.path.join(dest_real, norm)
    # realpath on the parent resolves ".." and any symlinked intermediate
    # directory, so a symlink entry pointing outside followed by files
    # beneath it fails containment instead of writing through the link
    parent = os.path.realpath(os.path.dirname(full))
    if parent != dest_real and not parent.startswith(dest_real + os.sep):
        raise ValueError(f"manifest path escapes bundle: {rel!r}")
    return os.path.join(parent, os.path.basename(full))


def open_nofollow(target: str, flags: int = 0) -> int:
    """Open a manifest-addressed file for writing WITHOUT following a
    symlink at the final component. safe_join leaves that component
    unresolved (legit symlink entries must stay re-creatable on resume),
    which would let a hostile manifest place a symlink entry and then a
    same-path FILE entry whose root-privileged write follows the link
    anywhere on the host — O_NOFOLLOW (plus clearing any pre-existing
    non-regular node) closes that, race-free. Returns a raw fd."""
    if os.path.islink(target) or (os.path.lexists(target)
                                  and not os.path.isfile(target)):
        os.unlink(target)
    return os.open(target,
                   os.O_WRONLY | os.O_CREAT | os.O_NOFOLLOW | flags, 0o644)


@dataclass
class FileEntry:
    path: str                  # relative path in the bundle
    mode: int
    size: int
    chunks: list[str] = field(default_factory=list)
    link_target: str = ""      # symlink destination ("" = regular file)


@dataclass
class ImageManifest:
    image_id: str = ""
    files: list[FileEntry] = field(default_factory=list)
    env: dict[str, str] = field(default_factory=dict)
    python_version: str = ""
    total_bytes: int = 0
    # "env" = snapshot overlaying the host fs; "oci" = full root filesystem
    # under rootfs/ (runc chroots into it — decided at build time, never
    # inferred from directory layout)
    kind: str = "env"
    # chunking granularity the files were split at — readers that seek
    # (t9cachefs page faults) need it to map offsets to chunk indices
    chunk_bytes: int = DEFAULT_CHUNK

    def to_json(self) -> str:
        return json.dumps({
            "image_id": self.image_id,
            "python_version": self.python_version,
            "env": self.env,
            "total_bytes": self.total_bytes,
            "kind": self.kind,
            "chunk_bytes": self.chunk_bytes,
            "files": [{"path": f.path, "mode": f.mode, "size": f.size,
                       "chunks": f.chunks, "link_target": f.link_target}
                      for f in self.files],
        }, sort_keys=True)

    @classmethod
    def from_json(cls, blob: str) -> "ImageManifest":
        d = json.loads(blob)
        return cls(
            image_id=d["image_id"],
            python_version=d.get("python_version", ""),
            env=d.get("env", {}),
            total_bytes=d.get("total_bytes", 0),
            kind=d.get("kind", "env"),
            chunk_bytes=d.get("chunk_bytes", DEFAULT_CHUNK),
            files=[FileEntry(**f) for f in d.get("files", [])],
        )

    @property
    def manifest_hash(self) -> str:
        return hashlib.sha256(self.to_json().encode()).hexdigest()

    def all_chunks(self) -> Iterator[str]:
        for f in self.files:
            yield from f.chunks


def snapshot_dir(root: str, chunk_bytes: int = DEFAULT_CHUNK,
                 put_chunk=None) -> ImageManifest:
    """Walk ``root`` and build a manifest; ``put_chunk(data, digest)`` stores
    each chunk (sync callback so the walk can run in a thread)."""
    manifest = ImageManifest(chunk_bytes=chunk_bytes)
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames.sort()
        for fn in sorted(filenames):
            full = os.path.join(dirpath, fn)
            rel = os.path.relpath(full, root)
            try:
                st = os.lstat(full)
            except OSError:
                continue
            if os.path.islink(full):
                manifest.files.append(FileEntry(
                    path=rel, mode=st.st_mode & 0xFFFF, size=0,
                    link_target=os.readlink(full)))
                continue
            if not os.path.isfile(full):
                continue
            chunks = []
            size = 0
            with open(full, "rb") as f:
                while True:
                    data = f.read(chunk_bytes)
                    if not data:
                        break
                    digest = hashlib.sha256(data).hexdigest()
                    if put_chunk is not None:
                        put_chunk(data, digest)
                    chunks.append(digest)
                    size += len(data)
            manifest.files.append(FileEntry(path=rel,
                                            mode=st.st_mode & 0xFFFF,
                                            size=size, chunks=chunks))
            manifest.total_bytes += size
    return manifest


def materialize(manifest: ImageManifest, dest: str, get_chunk,
                link_from: Optional[str] = None) -> None:
    """Write the manifest's tree under ``dest``. ``get_chunk(digest) ->
    bytes`` (sync). When ``link_from`` holds a chunk file path resolver,
    single-chunk files are hardlinked instead of copied (zero-copy warm
    start)."""
    dest_real = os.path.realpath(dest)
    for entry in manifest.files:
        target = safe_join(dest, entry.path, dest_real)
        os.makedirs(os.path.dirname(target), exist_ok=True)
        if entry.link_target:
            try:
                os.symlink(entry.link_target, target)
            except FileExistsError:
                pass
            continue
        if link_from is not None and len(entry.chunks) == 1:
            src = link_from(entry.chunks[0])
            if src is not None:
                try:
                    os.link(src, target)
                    # fd-based chmod via O_NOFOLLOW — the same racing-
                    # symlink-swap hardening as the copy path below
                    fd = os.open(target, os.O_WRONLY | os.O_NOFOLLOW)
                    try:
                        os.fchmod(fd, entry.mode & 0o777)
                    finally:
                        os.close(fd)
                    continue
                except OSError:
                    pass
        fd = open_nofollow(target, os.O_TRUNC)
        with os.fdopen(fd, "wb") as f:
            for digest in entry.chunks:
                data = get_chunk(digest)
                if data is None:
                    raise IOError(f"missing chunk {digest} for {entry.path}")
                f.write(data)
            # fchmod on the fd we actually wrote — a path chmod would
            # follow a racing symlink swap
            os.fchmod(f.fileno(), entry.mode & 0o777)
