"""Image builder: spec → environment dir → chunked manifest in the registry.

Reference analogue: the build service (pkg/abstractions/image/build.go:62)
which synthesizes a dockerfile from steps and runs it in a build container.
tpu9 builds an **env snapshot** instead: venv creation + ``pip install`` +
arbitrary commands executed in a scratch dir, then ``snapshot_dir`` chunks
the result into the content store. Zero-egress environments (CI, this image)
use ``pip --no-index`` against a local wheel dir or skip package install;
the build degrades explicitly, never silently.
"""

from __future__ import annotations

import asyncio
import logging
import os
import shutil
import subprocess
import sys
import tempfile
from typing import Optional

from .manifest import ImageManifest, snapshot_dir
from .spec import ImageSpec

log = logging.getLogger("tpu9.images")


class BuildError(RuntimeError):
    pass


class ImageBuilder:
    def __init__(self, registry_dir: str, wheel_dir: str = "",
                 network_ok: bool = True):
        self.registry_dir = registry_dir
        self.wheel_dir = wheel_dir
        self.network_ok = network_ok
        os.makedirs(os.path.join(registry_dir, "manifests"), exist_ok=True)
        os.makedirs(os.path.join(registry_dir, "chunks"), exist_ok=True)

    # -- registry ------------------------------------------------------------

    def manifest_path(self, image_id: str) -> str:
        return os.path.join(self.registry_dir, "manifests",
                            f"{image_id}.json")

    def chunk_path(self, digest: str) -> str:
        return os.path.join(self.registry_dir, "chunks", digest[:2], digest)

    def has_image(self, image_id: str) -> bool:
        return os.path.exists(self.manifest_path(image_id))

    def load_manifest(self, image_id: str) -> Optional[ImageManifest]:
        p = self.manifest_path(image_id)
        if not os.path.exists(p):
            return None
        return ImageManifest.from_json(open(p).read())

    def read_chunk(self, digest: str) -> Optional[bytes]:
        p = self.chunk_path(digest)
        if not os.path.exists(p):
            return None
        with open(p, "rb") as f:
            return f.read()

    def _store_chunk(self, data: bytes, digest: str) -> None:
        p = self.chunk_path(digest)
        if os.path.exists(p):
            return
        os.makedirs(os.path.dirname(p), exist_ok=True)
        tmp = p + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.rename(tmp, p)

    def store_chunk_verified(self, data: bytes, digest: str) -> bool:
        """Store an uploaded chunk iff its content matches the digest —
        content addressing makes tampered uploads self-evident."""
        import hashlib
        if hashlib.sha256(data).hexdigest() != digest:
            return False
        self._store_chunk(data, digest)
        return True

    def store_manifest(self, image_id: str, manifest: ImageManifest) -> list[str]:
        """Persist an uploaded manifest; returns digests it references that
        are NOT in the chunk store (callers reject incomplete uploads)."""
        missing = [d for d in dict.fromkeys(manifest.all_chunks())
                   if not os.path.exists(self.chunk_path(d))]
        if missing:
            return missing
        # atomic like _store_chunk: a torn manifest would read as a "ready"
        # image that crashes every puller with no rebuild path
        path = self.manifest_path(image_id)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(manifest.to_json())
        os.rename(tmp, path)
        return []

    # -- building ------------------------------------------------------------

    async def build(self, spec: ImageSpec,
                    log_cb=None) -> ImageManifest:
        """Build (or return the cached) image for a spec."""
        existing = self.load_manifest(spec.image_id)
        if existing is not None:
            return existing
        return await asyncio.to_thread(self._build_sync, spec, log_cb)

    def _build_sync(self, spec: ImageSpec, log_cb=None) -> ImageManifest:
        def emit(line: str) -> None:
            log.info("[build %s] %s", spec.image_id, line)
            if log_cb:
                log_cb(line)

        if spec.from_registry:
            # the OCI pull lives in the build runner (worker mode) only —
            # succeeding here without the rootfs would mark a broken image
            # ready
            raise BuildError(
                "from_registry images require build_mode='worker'")
        scratch = tempfile.mkdtemp(prefix="tpu9-build-")
        try:
            env_dir = os.path.join(scratch, "env")
            os.makedirs(env_dir)

            if spec.python_packages:
                self._install_packages(spec, env_dir, emit)

            for cmd in spec.commands:
                emit(f"RUN {cmd}")
                proc = subprocess.run(cmd, shell=True, cwd=scratch,
                                      capture_output=True, text=True,
                                      timeout=1800)
                if proc.stdout:
                    emit(proc.stdout[-2000:])
                if proc.returncode != 0:
                    raise BuildError(
                        f"command failed ({proc.returncode}): {cmd}\n"
                        f"{proc.stderr[-2000:]}")

            emit("snapshotting environment")
            manifest = snapshot_dir(scratch, put_chunk=self._store_chunk)
            manifest.image_id = spec.image_id
            manifest.python_version = spec.python_version
            manifest.env = dict(spec.env)
            if spec.python_packages:
                manifest.env.setdefault(
                    "TPU9_IMAGE_SITE", "env/site-packages")
            with open(self.manifest_path(spec.image_id), "w") as f:
                f.write(manifest.to_json())
            emit(f"built {spec.image_id}: {len(manifest.files)} files, "
                 f"{manifest.total_bytes >> 20} MiB")
            return manifest
        finally:
            shutil.rmtree(scratch, ignore_errors=True)

    def _install_packages(self, spec: ImageSpec, env_dir: str, emit) -> None:
        site = os.path.join(env_dir, "site-packages")
        os.makedirs(site, exist_ok=True)
        cmd = [sys.executable, "-m", "pip", "install", "--target", site,
               "--no-compile"]
        if not self.network_ok:
            if not self.wheel_dir:
                raise BuildError(
                    "package install requested but the builder has no network "
                    "and no wheel_dir configured")
            cmd += ["--no-index", "--find-links", self.wheel_dir]
        elif self.wheel_dir:
            cmd += ["--find-links", self.wheel_dir]
        cmd += spec.python_packages
        emit(f"pip install {' '.join(spec.python_packages)}")
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=1800)
        if proc.returncode != 0:
            raise BuildError(f"pip install failed:\n{proc.stderr[-3000:]}")
