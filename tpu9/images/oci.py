"""OCI registry pull: distribution-API client + layer unpacking.

Reference analogue: ``pkg/worker/image.go:274,953`` (skopeo pull + CLIP lazy
mount) and the buildah path (``pkg/abstractions/image/build.go:340``). tpu9
pulls via the plain OCI distribution HTTP API, unpacks layers (whiteout-
aware) into a ``rootfs/`` tree, and snapshots that tree through the same
chunked manifest format every other image uses — so registry images ride
the existing lazy puller + distributed cache with zero special-casing.

The transport is injected (``async (method, url, headers) -> (status,
headers, body)``) so the client is testable against an in-process fake
registry and swappable for authenticated transports; zero-egress
environments never construct the default aiohttp transport.
"""

from __future__ import annotations

import gzip
import io
import json
import logging
import os
import tarfile
from typing import Awaitable, Callable, Optional

log = logging.getLogger("tpu9.images")

MEDIA_MANIFEST_LIST = "application/vnd.docker.distribution.manifest.list.v2+json"
MEDIA_MANIFEST = "application/vnd.docker.distribution.manifest.v2+json"
MEDIA_OCI_INDEX = "application/vnd.oci.image.index.v1+json"
MEDIA_OCI_MANIFEST = "application/vnd.oci.image.manifest.v1+json"
ACCEPT = ", ".join([MEDIA_MANIFEST, MEDIA_MANIFEST_LIST, MEDIA_OCI_MANIFEST,
                    MEDIA_OCI_INDEX])

Transport = Callable[..., Awaitable[tuple[int, dict, bytes]]]


class OciError(RuntimeError):
    pass


def parse_ref(ref: str) -> tuple[str, str, str]:
    """'python:3.12' → (registry-base-url, name, tag). Docker Hub shortnames
    get the library/ prefix and registry-1.docker.io, like the reference's
    skopeo wrapper resolves them."""
    registry = "registry-1.docker.io"
    rest = ref
    if "/" in ref and ("." in ref.split("/")[0] or ":" in ref.split("/")[0]):
        registry, rest = ref.split("/", 1)
    tag = "latest"
    if "@" in rest:
        rest, tag = rest.split("@", 1)        # digest pin
    elif ":" in rest:
        rest, tag = rest.rsplit(":", 1)
    if registry == "registry-1.docker.io" and "/" not in rest:
        rest = f"library/{rest}"
    scheme = "http" if registry.startswith(("127.", "localhost")) else "https"
    return f"{scheme}://{registry}", rest, tag


def registry_host(ref: str) -> str:
    """The host credentials must be keyed by for ``ref`` — the SAME
    resolution parse_ref applies (docker-hub shortnames →
    registry-1.docker.io), so 'python:3.12' creds land on the host the
    requests actually go to."""
    base, _, _ = parse_ref(ref)
    return base.split("://", 1)[-1]


class OciClient:
    def __init__(self, transport: Transport):
        self.transport = transport

    async def _get(self, url: str, headers: Optional[dict] = None) -> bytes:
        status, _, body = await self.transport("GET", url, headers or {})
        if status != 200:
            raise OciError(f"GET {url} → {status}")
        return body

    async def pull(self, ref: str, dest: str,
                   platform: str = "linux/amd64",
                   log_cb=None) -> dict:
        """Pull ``ref`` and unpack its layers under ``dest`` (a ``rootfs``
        tree). Returns the image config dict (env/entrypoint/cmd)."""
        def emit(line: str) -> None:
            log.info("[oci] %s", line)
            if log_cb:
                log_cb(line)

        base, name, tag = parse_ref(ref)
        emit(f"pulling {name}:{tag} from {base}")
        raw = await self._get(f"{base}/v2/{name}/manifests/{tag}",
                              {"Accept": ACCEPT})
        manifest = json.loads(raw)

        if manifest.get("mediaType") in (MEDIA_MANIFEST_LIST,
                                         MEDIA_OCI_INDEX) \
                or "manifests" in manifest and "layers" not in manifest:
            os_name, arch = platform.split("/")
            chosen = None
            for m in manifest["manifests"]:
                p = m.get("platform", {})
                if p.get("os") == os_name and p.get("architecture") == arch:
                    chosen = m
                    break
            if chosen is None:
                raise OciError(f"no {platform} manifest in index for {ref}")
            raw = await self._get(
                f"{base}/v2/{name}/manifests/{chosen['digest']}",
                {"Accept": ACCEPT})
            manifest = json.loads(raw)

        config = {}
        if manifest.get("config", {}).get("digest"):
            blob = await self._get(
                f"{base}/v2/{name}/blobs/{manifest['config']['digest']}")
            config = json.loads(blob)

        os.makedirs(dest, exist_ok=True)
        for layer in manifest.get("layers", []):
            digest = layer["digest"]
            emit(f"layer {digest[:19]} ({layer.get('size', '?')} bytes)")
            blob = await self._get(f"{base}/v2/{name}/blobs/{digest}")
            _extract_layer(blob, dest)
        emit(f"unpacked {len(manifest.get('layers', []))} layers")
        return config.get("config", config)


def _extract_layer(blob: bytes, dest: str) -> None:
    """Apply one layer tar (gzip or plain) onto ``dest``, honoring OCI
    whiteouts (.wh. files delete, .wh..wh..opq clears a directory)."""
    if blob[:2] == b"\x1f\x8b":
        blob = gzip.decompress(blob)
    dest_real = os.path.realpath(dest)

    def safe_path(member_name: str) -> str:
        p = os.path.realpath(os.path.join(dest_real, member_name))
        if p != dest_real and not p.startswith(dest_real + os.sep):
            raise OciError(f"layer path escapes rootfs: {member_name}")
        return p

    with tarfile.open(fileobj=io.BytesIO(blob)) as tf:
        for member in tf:
            base = os.path.basename(member.name)
            if base == ".wh..wh..opq":
                target_dir = safe_path(os.path.dirname(member.name))
                if os.path.isdir(target_dir):
                    for entry in os.listdir(target_dir):
                        _rm(os.path.join(target_dir, entry))
                continue
            if base.startswith(".wh."):
                victim = safe_path(os.path.join(os.path.dirname(member.name),
                                                base[len(".wh."):]))
                _rm(victim)
                continue
            target = safe_path(member.name)
            if member.isdir():
                os.makedirs(target, exist_ok=True)
            elif member.issym():
                os.makedirs(os.path.dirname(target), exist_ok=True)
                if os.path.lexists(target):
                    os.unlink(target)
                os.symlink(member.linkname, target)
            elif member.islnk():
                os.makedirs(os.path.dirname(target), exist_ok=True)
                src = safe_path(member.linkname)
                if os.path.lexists(target):
                    os.unlink(target)
                if os.path.exists(src):
                    os.link(src, target)
            elif member.isfile():
                os.makedirs(os.path.dirname(target), exist_ok=True)
                f = tf.extractfile(member)
                with open(target, "wb") as out:
                    out.write(f.read() if f else b"")
                os.chmod(target, member.mode & 0o7777 or 0o644)
            # devices/fifos skipped: rootless snapshots can't mknod


def _rm(path: str) -> None:
    import shutil
    if os.path.isdir(path) and not os.path.islink(path):
        shutil.rmtree(path, ignore_errors=True)
    elif os.path.lexists(path):
        os.unlink(path)


def aiohttp_transport(session=None,
                      credentials: "dict | None" = None) -> Transport:
    """Default transport over aiohttp (handles Docker Hub's anonymous token
    dance transparently on 401). One ClientSession is shared across requests
    — an N-layer pull must not pay N connector/TLS setups; callers without
    their own session should ``await transport.aclose()`` when done.

    ``credentials``: registry host → (user, password) for private
    registries (reference pkg/registry/credentials.go's basic-auth case) —
    sent as Basic auth on the token exchange AND on direct requests the
    registry answers without a token dance."""
    import base64

    import aiohttp

    state: dict = {"session": session, "tokens": {}}
    credentials = credentials or {}

    def _basic(url: str) -> "str | None":
        host = url.split("://", 1)[-1].split("/", 1)[0]
        cred = credentials.get(host)
        if cred is None:
            return None
        raw = f"{cred[0]}:{cred[1]}".encode()
        return "Basic " + base64.b64encode(raw).decode()

    def _session() -> "aiohttp.ClientSession":
        if state["session"] is None or state["session"].closed:
            state["session"] = aiohttp.ClientSession()
        return state["session"]

    async def fetch(method: str, url: str,
                    headers: dict) -> tuple[int, dict, bytes]:
        own = _session()
        hdrs = dict(headers)
        realm_key = url.split("/v2/")[0]
        if realm_key in state["tokens"]:
            hdrs["Authorization"] = f"Bearer {state['tokens'][realm_key]}"
        else:
            basic = _basic(url)
            if basic:
                hdrs["Authorization"] = basic
        async with own.request(method, url, headers=hdrs) as resp:
            body = await resp.read()
            if resp.status == 401 and "Www-Authenticate" in resp.headers:
                # anonymous pull token
                import re
                chal = resp.headers["Www-Authenticate"]
                m = dict(re.findall(r'(\w+)="([^"]*)"', chal))
                if "realm" in m:
                    token_url = (f"{m['realm']}?service={m.get('service', '')}"
                                 f"&scope={m.get('scope', '')}")
                    token_hdrs = {}
                    basic = _basic(url)
                    if basic:
                        # private pull: the token endpoint authenticates
                        # the basic credentials and scopes the bearer token
                        token_hdrs["Authorization"] = basic
                    async with own.get(token_url,
                                       headers=token_hdrs) as tr:
                        tok = (await tr.json()).get("token", "")
                    state["tokens"][realm_key] = tok
                    hdrs["Authorization"] = f"Bearer {tok}"
                    async with own.request(method, url,
                                           headers=hdrs) as resp2:
                        return (resp2.status, dict(resp2.headers),
                                await resp2.read())
            return resp.status, dict(resp.headers), body

    async def aclose() -> None:
        if session is None and state["session"] is not None:
            await state["session"].close()

    fetch.aclose = aclose
    return fetch
