"""Image puller: materialize a manifest into a worker-local bundle via the
distributed cache.

Reference analogue: the worker's CLIP pull path (pkg/worker/image.go:274
PullLazy + content routing). tpu9 pull: manifest (small JSON) from the
registry, chunks through the CacheClient (local disk → HRW peers → source),
single-chunk files hardlinked straight out of the chunk store so warm pulls
are metadata-speed. Bundles are refcount-shared across containers on a host.
"""

from __future__ import annotations

import asyncio
import logging
import os
import shutil
from typing import Optional

from ..cache import CacheClient
from .manifest import ImageManifest, materialize

log = logging.getLogger("tpu9.images")


class ImagePuller:
    def __init__(self, cache: CacheClient, bundles_dir: str,
                 manifest_fetch=None):
        """``manifest_fetch(image_id) -> ImageManifest | None`` (async)."""
        self.cache = cache
        self.bundles_dir = bundles_dir
        self.manifest_fetch = manifest_fetch
        os.makedirs(bundles_dir, exist_ok=True)
        self._locks: dict[str, asyncio.Lock] = {}
        self._refs: dict[str, int] = {}

    def bundle_path(self, image_id: str) -> str:
        return os.path.join(self.bundles_dir, image_id)

    async def pull(self, image_id: str,
                   manifest: Optional[ImageManifest] = None) -> str:
        """Materialize (once) and return the bundle dir."""
        lock = self._locks.setdefault(image_id, asyncio.Lock())
        async with lock:
            dest = self.bundle_path(image_id)
            done_marker = os.path.join(dest, ".tpu9-complete")
            if os.path.exists(done_marker):
                self._refs[image_id] = self._refs.get(image_id, 0) + 1
                return dest
            if manifest is None:
                if self.manifest_fetch is None:
                    raise IOError(f"no manifest source for {image_id}")
                manifest = await self.manifest_fetch(image_id)
                if manifest is None:
                    raise IOError(f"image {image_id} not found")

            # prefetch every chunk into the local store (bounded parallel),
            # then materialize with hardlinks from the store
            chunks = list(dict.fromkeys(manifest.all_chunks()))
            fetched = await self.cache.get_many(chunks)
            missing = [d for d, v in fetched.items() if v is None]
            if missing:
                raise IOError(
                    f"image {image_id}: {len(missing)} chunks unavailable")

            tmp = dest + ".partial"
            shutil.rmtree(tmp, ignore_errors=True)
            os.makedirs(tmp, exist_ok=True)

            def get_chunk(digest: str) -> Optional[bytes]:
                return fetched.get(digest)

            await asyncio.to_thread(
                materialize, manifest, tmp, get_chunk,
                self.cache.store.get_path)
            os.makedirs(tmp, exist_ok=True)
            # runtime metadata the lifecycle reads when wiring the container
            import json
            with open(os.path.join(tmp, ".tpu9-env.json"), "w") as f:
                json.dump({"env": manifest.env,
                           "python_version": manifest.python_version,
                           "kind": manifest.kind}, f)
            with open(os.path.join(tmp, ".tpu9-complete"), "w") as f:
                f.write(manifest.manifest_hash)
            shutil.rmtree(dest, ignore_errors=True)
            os.rename(tmp, dest)
            self._refs[image_id] = self._refs.get(image_id, 0) + 1
            log.info("pulled %s: %d files, %d chunks", image_id,
                     len(manifest.files), len(chunks))
            return dest

    def release(self, image_id: str) -> None:
        if image_id in self._refs:
            self._refs[image_id] -= 1

    async def gc(self, keep: int = 4) -> int:
        """Drop unreferenced bundles beyond ``keep`` most-recent."""
        entries = []
        for name in os.listdir(self.bundles_dir):
            p = self.bundle_path(name)
            if self._refs.get(name, 0) > 0 or not os.path.isdir(p):
                continue
            entries.append((os.path.getmtime(p), name))
        entries.sort(reverse=True)
        removed = 0
        for _mtime, name in entries[keep:]:
            shutil.rmtree(self.bundle_path(name), ignore_errors=True)
            self._refs.pop(name, None)
            removed += 1
        return removed
