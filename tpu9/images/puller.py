"""Image puller: materialize a manifest into a worker-local bundle via the
distributed cache.

Reference analogue: the worker's CLIP pull path (pkg/worker/image.go:274
PullLazy + content routing). tpu9 pull: manifest (small JSON) from the
registry, chunks through the CacheClient (local disk → HRW peers → source),
single-chunk files hardlinked straight out of the chunk store so warm pulls
are metadata-speed. Bundles are refcount-shared across containers on a host.
"""

from __future__ import annotations

import asyncio
import logging
import os
import shutil
from typing import Optional

from ..cache import CacheClient
from ..utils.aio import event_wait
from .lazy import LazyFill
from .manifest import ImageManifest, materialize

log = logging.getLogger("tpu9.images")

# images at/above this size stream lazily by default: the container starts
# on the sparse skeleton while chunks arrive (reference: PullLazy is the
# default for ALL images, image.go:274; tpu9 keeps small images eager
# because a one-shot hardlink materialization beats socket round-trips)
LAZY_THRESHOLD_BYTES = 64 * 1024 * 1024


class ImagePuller:
    def __init__(self, cache: CacheClient, bundles_dir: str,
                 manifest_fetch=None,
                 lazy_threshold: int = LAZY_THRESHOLD_BYTES,
                 fusefs=None):
        """``manifest_fetch(image_id) -> ImageManifest | None`` (async).
        ``fusefs`` (a CacheFsManager) enables lazy OCI rootfs serving:
        the bundle becomes a FUSE read-through mount that overlayfs uses
        as its lowerdir, so container starts never wait for the rootfs
        and page faults stream exactly the chunks touched."""
        self.cache = cache
        self.bundles_dir = bundles_dir
        self.manifest_fetch = manifest_fetch
        self.lazy_threshold = lazy_threshold
        self.fusefs = fusefs
        os.makedirs(bundles_dir, exist_ok=True)
        self._locks: dict[str, asyncio.Lock] = {}
        self._refs: dict[str, int] = {}
        self._fills: dict[str, LazyFill] = {}
        self._fuse_mounts: dict[str, object] = {}
        # boot gate (VERDICT r04 #3): while ANY container on this worker is
        # cold-starting, background bulk fills yield — on a small host the
        # fill's sha256+disk work otherwise contends with runner boot and
        # the cold-pull p50 pays for bytes nobody needs yet. Faulted reads
        # (ensure_file) always bypass the gate.
        self._boots = 0
        self._boot_clear = asyncio.Event()
        self._boot_clear.set()

    def boot_started(self) -> None:
        self._boots += 1
        self._boot_clear.clear()

    def boot_finished(self) -> None:
        self._boots = max(0, self._boots - 1)
        if self._boots == 0:
            self._boot_clear.set()

    async def boot_gate(self) -> None:
        """Await until no container is cold-starting — bounded so a wedged
        boot can never starve fills forever."""
        # event_wait, not wait_for (ASY001): a fill cancelled while the
        # boot gate clears must actually cancel, not start a bulk fetch
        await event_wait(self._boot_clear, timeout=15.0)

    def bundle_path(self, image_id: str) -> str:
        return os.path.join(self.bundles_dir, image_id)

    def lazy_sock(self, image_id: str) -> str:
        # sockets live OUTSIDE the (read-only-bound) bundle dir: connect(2)
        # needs write permission on the socket inode, which an ro bind
        # denies. One subdirectory PER IMAGE — the lifecycle binds exactly
        # that subdir into containers, so a tenant can only reach its own
        # image's fault socket (not every image on the node)
        return os.path.join(self.bundles_dir, ".sock", image_id,
                            "fill.sock")

    def active_fill(self, image_id: str) -> Optional[LazyFill]:
        """The in-progress lazy fill for this bundle, if any (the lifecycle
        wires the open-gating shim into containers while one is active).
        A fill whose task finished — successfully or abandoned after
        failures — is not active; an abandoned one lets the next pull
        re-skeleton from scratch."""
        fill = self._fills.get(image_id)
        if fill is None or fill.complete:
            return None
        if fill._task is not None and fill._task.done():
            return None
        return fill

    async def pull(self, image_id: str,
                   manifest: Optional[ImageManifest] = None,
                   lazy: Optional[bool] = None) -> str:
        """Materialize (once) and return the bundle dir. With ``lazy`` (the
        default for large images) the bundle is usable on return — a
        stat-correct sparse skeleton — while a background :class:`LazyFill`
        streams content; callers gate opens via the shim + fault socket."""
        lock = self._locks.setdefault(image_id, asyncio.Lock())
        async with lock:
            dest = self.bundle_path(image_id)
            done_marker = os.path.join(dest, ".tpu9-complete")
            if image_id in self._fuse_mounts:
                self._refs[image_id] = self._refs.get(image_id, 0) + 1
                return dest
            if os.path.exists(done_marker):
                self._refs[image_id] = self._refs.get(image_id, 0) + 1
                return dest
            if self.active_fill(image_id) is not None:
                # another container already started this lazy pull
                self._refs[image_id] = self._refs.get(image_id, 0) + 1
                return dest
            if manifest is None:
                if self.manifest_fetch is None:
                    raise IOError(f"no manifest source for {image_id}")
                manifest = await self.manifest_fetch(image_id)
                if manifest is None:
                    raise IOError(f"image {image_id} not found")

            # OCI rootfs: lazy = a FUSE read-through mount (overlay
            # lowerdir); the open-gating skeleton trick can't work under a
            # mounted overlay, but CacheFS covers every reader incl. mmap
            if (manifest.kind == "oci" and self.fusefs is not None
                    and manifest.total_bytes >= self.lazy_threshold
                    and lazy is not False):
                mount = await self._mount_oci(image_id, manifest, dest)
                if mount is not None:
                    self._refs[image_id] = self._refs.get(image_id, 0) + 1
                    return dest

            if lazy is None:
                # env-kind bundles only: their host paths are what the
                # shim's TPU9_LAZY_DIRS match and what containers read.
                # OCI rootfs trees become overlay LOWER dirs after
                # pivot_root — streaming under a mounted overlay is
                # undefined and the shim .so isn't in the rootfs.
                lazy = (manifest.kind == "env"
                        and manifest.total_bytes >= self.lazy_threshold)
            if lazy:
                # an interrupted previous fill leaves placeholders with no
                # completion marker; restart the fill. Only rebuild the
                # skeleton when NO running container references the bundle
                # (rmtree/truncate under a live container's bind mount
                # yanks files mid-read) — with live refs, refill in place:
                # writes are idempotent content.
                stale = self._fills.pop(image_id, None)
                if stale is not None:
                    await stale.close()
                live_refs = self._refs.get(image_id, 0) > 0
                if not live_refs:
                    # off-loop (ASY004): a GB-scale stale bundle rmtree
                    # would stall every pull/heartbeat on the worker loop
                    await asyncio.to_thread(
                        shutil.rmtree, dest, ignore_errors=True)
                fill = LazyFill(manifest, dest, self.cache,
                                self.lazy_sock(image_id),
                                boot_gate=self.boot_gate)
                await fill.start(write_skeleton=not live_refs)
                self._fills[image_id] = fill
                self._refs[image_id] = self._refs.get(image_id, 0) + 1
                log.info("lazy pull %s: skeleton ready, %d files / %.1f MB "
                         "streaming", image_id, len(manifest.files),
                         manifest.total_bytes / 1e6)
                return dest

            # prefetch every chunk into the local store (bounded parallel),
            # then materialize with hardlinks from the store
            chunks = list(dict.fromkeys(manifest.all_chunks()))
            fetched = await self.cache.get_many(chunks)
            missing = [d for d, v in fetched.items() if v is None]
            if missing:
                raise IOError(
                    f"image {image_id}: {len(missing)} chunks unavailable")

            tmp = dest + ".partial"
            await asyncio.to_thread(
                shutil.rmtree, tmp, ignore_errors=True)   # off-loop (ASY004)
            os.makedirs(tmp, exist_ok=True)

            def get_chunk(digest: str) -> Optional[bytes]:
                return fetched.get(digest)

            await asyncio.to_thread(
                materialize, manifest, tmp, get_chunk,
                self.cache.store.get_path)
            os.makedirs(tmp, exist_ok=True)
            # runtime metadata the lifecycle reads when wiring the container
            import json
            import subprocess

            def publish() -> None:
                # off-loop (ASY004): metadata writes + lazy-umount +
                # GB-scale rmtree + rename, all blocking syscalls
                with open(os.path.join(tmp, ".tpu9-env.json"), "w") as f:
                    json.dump(self.runtime_meta(manifest), f)
                with open(os.path.join(tmp, ".tpu9-complete"), "w") as f:
                    f.write(manifest.manifest_hash)
                # a crashed worker may have left a FUSE mount at dest —
                # rmtree can't remove a live mount and the rename would
                # get EBUSY
                subprocess.run(["umount", "-l", dest], capture_output=True)
                shutil.rmtree(dest, ignore_errors=True)
                os.rename(tmp, dest)

            await asyncio.to_thread(publish)
            self._refs[image_id] = self._refs.get(image_id, 0) + 1
            log.info("pulled %s: %d files, %d chunks", image_id,
                     len(manifest.files), len(chunks))
            return dest

    @staticmethod
    def runtime_meta(manifest: ImageManifest) -> dict:
        """The .tpu9-env.json payload the lifecycle reads at container
        start — ONE definition for the eager and FUSE paths."""
        return {"env": manifest.env,
                "python_version": manifest.python_version,
                "kind": manifest.kind}

    async def _mount_oci(self, image_id: str, manifest: ImageManifest,
                         dest: str):
        """FUSE-mount an OCI manifest at the bundle path. The runtime
        metadata file the lifecycle reads (.tpu9-env.json) is synthesized
        into the manifest as a content chunk so it exists inside the
        read-only mount."""
        import hashlib
        import json as _json

        from .manifest import FileEntry
        meta = _json.dumps(self.runtime_meta(manifest)).encode()
        digest = hashlib.sha256(meta).hexdigest()
        await self.cache.put(meta, digest)
        manifest = ImageManifest.from_json(manifest.to_json())  # copy
        manifest.files.append(FileEntry(
            path=".tpu9-env.json", mode=0o644, size=len(meta),
            chunks=[digest]))
        try:
            mount = await self.fusefs.mount(manifest, dest)
        except Exception as exc:      # noqa: BLE001 — fall back to eager
            log.warning("cachefs mount for %s failed (%s); eager pull",
                        image_id, exc)
            return None
        self._fuse_mounts[image_id] = mount
        log.info("lazy OCI mount %s: %d files / %.1f MB served on demand",
                 image_id, len(manifest.files), manifest.total_bytes / 1e6)
        return mount

    def release(self, image_id: str) -> None:
        if image_id in self._refs:
            self._refs[image_id] -= 1

    async def close(self) -> None:
        for fill in list(self._fills.values()):
            await fill.close()
        self._fills.clear()
        for image_id, mount in list(self._fuse_mounts.items()):
            try:
                if self.fusefs is not None:
                    await self.fusefs.unmount(mount.mountpoint)
                else:
                    await mount.unmount()
            except Exception:         # noqa: BLE001
                pass
        self._fuse_mounts.clear()

    async def gc(self, keep: int = 4) -> int:
        """Drop unreferenced bundles beyond ``keep`` most-recent. FUSE
        mounts with zero live containers count as candidates too —
        otherwise a long-lived worker accumulates one daemon + kernel
        mount per large OCI image forever."""
        entries = []
        for name in os.listdir(self.bundles_dir):
            p = self.bundle_path(name)
            if (name.startswith(".") or self._refs.get(name, 0) > 0
                    or not os.path.isdir(p)
                    or self.active_fill(name) is not None):
                continue
            entries.append((os.path.getmtime(p), name))
        entries.sort(reverse=True)
        removed = 0
        for _mtime, name in entries[keep:]:
            # per-image lock + ref re-check: the rmtree now awaits (to keep
            # GB-scale deletes off the loop), so a concurrent pull() could
            # otherwise revive the bundle mid-delete and hand a container a
            # tree the thread is unlinking under it
            async with self._locks.setdefault(name, asyncio.Lock()):
                if (self._refs.get(name, 0) > 0
                        or self.active_fill(name) is not None):
                    continue
                mount = self._fuse_mounts.pop(name, None)
                if mount is not None:
                    try:
                        if self.fusefs is not None:
                            await self.fusefs.unmount(mount.mountpoint)
                        else:
                            await mount.unmount()
                    except Exception:     # noqa: BLE001 — lazy umount below
                        pass
                await asyncio.to_thread(
                    shutil.rmtree, self.bundle_path(name),
                    ignore_errors=True)   # off-loop (ASY004)
                self._refs.pop(name, None)
                removed += 1
        return removed
