"""Image puller: materialize a manifest into a worker-local bundle via the
distributed cache.

Reference analogue: the worker's CLIP pull path (pkg/worker/image.go:274
PullLazy + content routing). tpu9 pull: manifest (small JSON) from the
registry, chunks through the CacheClient (local disk → HRW peers → source),
single-chunk files hardlinked straight out of the chunk store so warm pulls
are metadata-speed. Bundles are refcount-shared across containers on a host.
"""

from __future__ import annotations

import asyncio
import logging
import os
import shutil
from typing import Optional

from ..cache import CacheClient
from .lazy import LazyFill
from .manifest import ImageManifest, materialize

log = logging.getLogger("tpu9.images")

# images at/above this size stream lazily by default: the container starts
# on the sparse skeleton while chunks arrive (reference: PullLazy is the
# default for ALL images, image.go:274; tpu9 keeps small images eager
# because a one-shot hardlink materialization beats socket round-trips)
LAZY_THRESHOLD_BYTES = 64 * 1024 * 1024


class ImagePuller:
    def __init__(self, cache: CacheClient, bundles_dir: str,
                 manifest_fetch=None,
                 lazy_threshold: int = LAZY_THRESHOLD_BYTES):
        """``manifest_fetch(image_id) -> ImageManifest | None`` (async)."""
        self.cache = cache
        self.bundles_dir = bundles_dir
        self.manifest_fetch = manifest_fetch
        self.lazy_threshold = lazy_threshold
        os.makedirs(bundles_dir, exist_ok=True)
        self._locks: dict[str, asyncio.Lock] = {}
        self._refs: dict[str, int] = {}
        self._fills: dict[str, LazyFill] = {}

    def bundle_path(self, image_id: str) -> str:
        return os.path.join(self.bundles_dir, image_id)

    def lazy_sock(self, image_id: str) -> str:
        # sockets live OUTSIDE the (read-only-bound) bundle dir: connect(2)
        # needs write permission on the socket inode, which an ro bind
        # denies. One subdirectory PER IMAGE — the lifecycle binds exactly
        # that subdir into containers, so a tenant can only reach its own
        # image's fault socket (not every image on the node)
        return os.path.join(self.bundles_dir, ".sock", image_id,
                            "fill.sock")

    def active_fill(self, image_id: str) -> Optional[LazyFill]:
        """The in-progress lazy fill for this bundle, if any (the lifecycle
        wires the open-gating shim into containers while one is active).
        A fill whose task finished — successfully or abandoned after
        failures — is not active; an abandoned one lets the next pull
        re-skeleton from scratch."""
        fill = self._fills.get(image_id)
        if fill is None or fill.complete:
            return None
        if fill._task is not None and fill._task.done():
            return None
        return fill

    async def pull(self, image_id: str,
                   manifest: Optional[ImageManifest] = None,
                   lazy: Optional[bool] = None) -> str:
        """Materialize (once) and return the bundle dir. With ``lazy`` (the
        default for large images) the bundle is usable on return — a
        stat-correct sparse skeleton — while a background :class:`LazyFill`
        streams content; callers gate opens via the shim + fault socket."""
        lock = self._locks.setdefault(image_id, asyncio.Lock())
        async with lock:
            dest = self.bundle_path(image_id)
            done_marker = os.path.join(dest, ".tpu9-complete")
            if os.path.exists(done_marker):
                self._refs[image_id] = self._refs.get(image_id, 0) + 1
                return dest
            if self.active_fill(image_id) is not None:
                # another container already started this lazy pull
                self._refs[image_id] = self._refs.get(image_id, 0) + 1
                return dest
            if manifest is None:
                if self.manifest_fetch is None:
                    raise IOError(f"no manifest source for {image_id}")
                manifest = await self.manifest_fetch(image_id)
                if manifest is None:
                    raise IOError(f"image {image_id} not found")

            if lazy is None:
                # env-kind bundles only: their host paths are what the
                # shim's TPU9_LAZY_DIRS match and what containers read.
                # OCI rootfs trees become overlay LOWER dirs after
                # pivot_root — streaming under a mounted overlay is
                # undefined and the shim .so isn't in the rootfs.
                lazy = (manifest.kind == "env"
                        and manifest.total_bytes >= self.lazy_threshold)
            if lazy:
                # an interrupted previous fill leaves placeholders with no
                # completion marker; restart the fill. Only rebuild the
                # skeleton when NO running container references the bundle
                # (rmtree/truncate under a live container's bind mount
                # yanks files mid-read) — with live refs, refill in place:
                # writes are idempotent content.
                stale = self._fills.pop(image_id, None)
                if stale is not None:
                    await stale.close()
                live_refs = self._refs.get(image_id, 0) > 0
                if not live_refs:
                    shutil.rmtree(dest, ignore_errors=True)
                fill = LazyFill(manifest, dest, self.cache,
                                self.lazy_sock(image_id))
                await fill.start(write_skeleton=not live_refs)
                self._fills[image_id] = fill
                self._refs[image_id] = self._refs.get(image_id, 0) + 1
                log.info("lazy pull %s: skeleton ready, %d files / %.1f MB "
                         "streaming", image_id, len(manifest.files),
                         manifest.total_bytes / 1e6)
                return dest

            # prefetch every chunk into the local store (bounded parallel),
            # then materialize with hardlinks from the store
            chunks = list(dict.fromkeys(manifest.all_chunks()))
            fetched = await self.cache.get_many(chunks)
            missing = [d for d, v in fetched.items() if v is None]
            if missing:
                raise IOError(
                    f"image {image_id}: {len(missing)} chunks unavailable")

            tmp = dest + ".partial"
            shutil.rmtree(tmp, ignore_errors=True)
            os.makedirs(tmp, exist_ok=True)

            def get_chunk(digest: str) -> Optional[bytes]:
                return fetched.get(digest)

            await asyncio.to_thread(
                materialize, manifest, tmp, get_chunk,
                self.cache.store.get_path)
            os.makedirs(tmp, exist_ok=True)
            # runtime metadata the lifecycle reads when wiring the container
            import json
            with open(os.path.join(tmp, ".tpu9-env.json"), "w") as f:
                json.dump({"env": manifest.env,
                           "python_version": manifest.python_version,
                           "kind": manifest.kind}, f)
            with open(os.path.join(tmp, ".tpu9-complete"), "w") as f:
                f.write(manifest.manifest_hash)
            shutil.rmtree(dest, ignore_errors=True)
            os.rename(tmp, dest)
            self._refs[image_id] = self._refs.get(image_id, 0) + 1
            log.info("pulled %s: %d files, %d chunks", image_id,
                     len(manifest.files), len(chunks))
            return dest

    def release(self, image_id: str) -> None:
        if image_id in self._refs:
            self._refs[image_id] -= 1

    async def close(self) -> None:
        for fill in list(self._fills.values()):
            await fill.close()
        self._fills.clear()

    async def gc(self, keep: int = 4) -> int:
        """Drop unreferenced bundles beyond ``keep`` most-recent."""
        entries = []
        for name in os.listdir(self.bundles_dir):
            p = self.bundle_path(name)
            if (name.startswith(".") or self._refs.get(name, 0) > 0
                    or not os.path.isdir(p)
                    or self.active_fill(name) is not None):
                continue
            entries.append((os.path.getmtime(p), name))
        entries.sort(reverse=True)
        removed = 0
        for _mtime, name in entries[keep:]:
            shutil.rmtree(self.bundle_path(name), ignore_errors=True)
            self._refs.pop(name, None)
            removed += 1
        return removed
