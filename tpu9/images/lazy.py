"""Lazy image materialization — containers start while the image streams.

Reference analogue: the CLIP lazy FUSE mount (``/root/reference/pkg/worker/
image.go:274`` PullLazy + ``pkg/cache/cachefs.go:47``): the reference mounts
a content-addressed archive and faults pages in from the distributed cache
on demand, which is its core cold-start weapon (a multi-GB image must not
gate ``container.ready``).

tpu9's TPU-first redesign keeps the distributed chunk store but swaps the
FUSE layer for *sparse-skeleton + open-gating*:

1. **Skeleton** — the whole tree is created instantly: directories,
   symlinks, and every regular file as a sparse placeholder truncated to
   its final size with its final mode. ``stat``/``readdir``/``access`` are
   correct from t=0 with zero bytes transferred.
2. **Background filler** — an asyncio task streams chunks from the
   CacheClient into the placeholders (manifest order), bounded-parallel,
   segment-at-a-time so a multi-GB file never sits in RAM.
3. **Open gating** — the ``t9lazy_preload.so`` LD_PRELOAD shim gates
   ``open()`` of a not-yet-filled file on a UNIX-socket round-trip to this
   filler, which *prioritizes* that file and replies when its bytes are
   real. A file the workload never opens never blocks anything.
4. **Completion marker** — when every file is filled, ``.tpu9-complete``
   is written and the shim stops consulting the socket (one cached stat).

Trade-off vs FUSE (documented, same stance as the vcache shim): processes
that bypass libc's open family (static binaries, direct syscalls) can read
placeholder zeros until the background fill completes — seconds, not
correctness-forever; the serving runners are all dynamically-linked
CPython. In exchange there is no kernel FUSE dependency, no userspace
page-fault round-trip on the hot path after fill, and the materialized
bundle is a plain directory eligible for hardlink warm starts.
"""

from __future__ import annotations

import asyncio
import logging
import os
from typing import Optional

from ..cache import CacheClient
from .manifest import FileEntry, ImageManifest, open_nofollow, safe_join
from ..utils.aio import reap

log = logging.getLogger("tpu9.images")

# chunks fetched per write-segment of one file — bounds filler RSS at
# roughly SEGMENT_CHUNKS * chunk_size (default 8 * 4 MiB = 32 MiB)
SEGMENT_CHUNKS = 8

LAZY_MARKER = ".tpu9-lazy"
COMPLETE_MARKER = ".tpu9-complete"


class LazyFill:
    """One in-progress lazy materialization of a manifest into ``dest``."""

    def __init__(self, manifest: ImageManifest, dest: str,
                 cache: CacheClient, sock_path: str, boot_gate=None):
        self.manifest = manifest
        self.dest = dest
        self.cache = cache
        self.sock_path = sock_path
        # async callable: the BACKGROUND filler awaits it between segments
        # so bulk streaming yields to cold-starting containers (VERDICT
        # r04 #3); on-demand faults never wait on it
        self._boot_gate = boot_gate
        # faults waiting on files the background filler has claimed: the
        # gate must release immediately or the booting container would
        # deadlock against the very gate protecting its boot
        self._pending_faults = 0
        self._fault_wakeup = asyncio.Event()
        self._entries: dict[str, FileEntry] = {
            e.path: e for e in manifest.files if not e.link_target}
        self._done: dict[str, asyncio.Event] = {
            p: asyncio.Event() for p in self._entries}
        self._claimed: set[str] = set()
        self._server: Optional[asyncio.AbstractServer] = None
        self._task: Optional[asyncio.Task] = None
        self.failed: list[str] = []
        self.stats = {"files_total": len(self._entries), "files_filled": 0,
                      "faults": 0, "bytes_streamed": 0}

    # -- lifecycle -----------------------------------------------------------

    async def start(self, write_skeleton: bool = True) -> None:
        """Write the skeleton, open the fault socket, start the filler.
        Returns as soon as the bundle is usable (stat-correct).
        ``write_skeleton=False`` refills an existing tree in place (resume
        after an abandoned fill while containers still reference it —
        truncating live files would yank data out from under readers)."""
        if write_skeleton:
            await asyncio.to_thread(self._write_skeleton)
        else:
            await asyncio.to_thread(self._ensure_tree)
        os.makedirs(os.path.dirname(self.sock_path), exist_ok=True)
        try:
            os.unlink(self.sock_path)
        except OSError:
            pass
        self._server = await asyncio.start_unix_server(
            self._serve_fault, path=self.sock_path)
        # any in-container uid (incl. dropped 65534) may fault files in
        os.chmod(self.sock_path, 0o666)
        self._task = asyncio.create_task(self._fill_all())

    def _ensure_tree(self) -> None:
        """Resume path: create only MISSING placeholders (never truncate an
        existing file — it may be mid-read in a running container)."""
        os.makedirs(self.dest, exist_ok=True)
        dest_real = os.path.realpath(self.dest)
        for entry in self.manifest.files:
            target = safe_join(self.dest, entry.path, dest_real)
            if os.path.lexists(target):
                continue
            os.makedirs(os.path.dirname(target), exist_ok=True)
            if entry.link_target:
                try:
                    os.symlink(entry.link_target, target)
                except FileExistsError:
                    pass
                continue
            with os.fdopen(open_nofollow(target), "wb") as f:
                f.truncate(entry.size)
                os.fchmod(f.fileno(), entry.mode & 0o777)
        with open(os.path.join(self.dest, LAZY_MARKER), "w") as f:
            f.write(self.manifest.manifest_hash)

    def _write_skeleton(self) -> None:
        os.makedirs(self.dest, exist_ok=True)
        dest_real = os.path.realpath(self.dest)
        for entry in self.manifest.files:
            target = safe_join(self.dest, entry.path, dest_real)
            os.makedirs(os.path.dirname(target), exist_ok=True)
            if entry.link_target:
                try:
                    os.symlink(entry.link_target, target)
                except FileExistsError:
                    pass
                continue
            # sparse placeholder: final size + mode, zero bytes on disk.
            # O_NOFOLLOW + fchmod: a hostile manifest pairing a symlink
            # entry with a same-path file entry must not write (or chmod)
            # through the link as root
            with os.fdopen(open_nofollow(target, os.O_TRUNC), "wb") as f:
                f.truncate(entry.size)
                os.fchmod(f.fileno(), entry.mode & 0o777)
        import json
        with open(os.path.join(self.dest, ".tpu9-env.json"), "w") as f:
            json.dump({"env": self.manifest.env,
                       "python_version": self.manifest.python_version,
                       "kind": self.manifest.kind}, f)
        with open(os.path.join(self.dest, LAZY_MARKER), "w") as f:
            f.write(self.manifest.manifest_hash)

    @property
    def complete(self) -> bool:
        return self.stats["files_filled"] >= self.stats["files_total"]

    async def wait(self) -> None:
        if self._task is not None:
            await self._task

    async def close(self) -> None:
        if self._task is not None and not self._task.done():
            # reap: absorbs the fill's cancel/crash (already logged) but
            # re-raises OUR cancellation (ASY003)
            await reap(self._task, absorb_errors=True)
        if self._server is not None:
            self._server.close()
            try:
                await self._server.wait_closed()
            except Exception:
                pass
            self._server = None
        try:
            os.unlink(self.sock_path)
        except OSError:
            pass

    # -- filling -------------------------------------------------------------

    async def ensure_file(self, rel: str) -> bool:
        """Fault one file in NOW (jumps the background queue). Returns False
        for paths outside the manifest (caller passes through)."""
        entry = self._entries.get(rel)
        if entry is None:
            return False
        ev = self._done[rel]
        if ev.is_set():
            return True
        self.stats["faults"] += 1
        if rel in self._claimed:           # background filler owns it
            self._pending_faults += 1
            self._fault_wakeup.set()
            try:
                await ev.wait()
            finally:
                self._pending_faults -= 1
                if self._pending_faults == 0:
                    self._fault_wakeup.clear()
            return True
        self._claimed.add(rel)
        try:
            await self._fill_one(entry)
        except Exception:
            # ANY failure (chunk transport errors included, not just
            # OSError) must release _fill_all's completion wait — an unset
            # event would pin active_fill forever and gate every later
            # container on a fill that cannot finish
            self.failed.append(rel)
            ev.set()
            raise
        return True

    async def _yield_for_boot(self) -> None:
        if self._boot_gate is None or self._fault_wakeup.is_set():
            return
        gate = asyncio.ensure_future(self._boot_gate())
        wake = asyncio.ensure_future(self._fault_wakeup.wait())
        try:
            await asyncio.wait({gate, wake},
                               return_when=asyncio.FIRST_COMPLETED)
        finally:
            for t in (gate, wake):
                if not t.done():
                    t.cancel()

    async def _fill_one(self, entry: FileEntry,
                        background: bool = False) -> None:
        target = safe_join(self.dest, entry.path)
        offset = 0
        for i in range(0, len(entry.chunks), SEGMENT_CHUNKS):
            if background:
                # bulk streaming yields to cold-starting containers at
                # segment granularity — unless a fault is waiting on a
                # claimed file, in which case filling IS the boot's
                # critical path and must continue
                await self._yield_for_boot()
            seg = entry.chunks[i:i + SEGMENT_CHUNKS]
            fetched = await self.cache.get_many(seg)
            datas = []
            for d in seg:
                blob = fetched.get(d)
                if blob is None:
                    raise IOError(f"missing chunk {d} for {entry.path}")
                datas.append(blob)

            def write(off: int, blobs: list) -> int:
                # placeholder already has final size+mode; write in place.
                # O_NOFOLLOW: a symlink swapped in at this path must fail,
                # never receive root-privileged chunk bytes
                fd = os.open(target, os.O_WRONLY | os.O_NOFOLLOW)
                with os.fdopen(fd, "wb", closefd=True) as f:
                    f.seek(off)
                    for b in blobs:
                        f.write(b)
                        off += len(b)
                return off

            offset = await asyncio.to_thread(write, offset, datas)
            self.stats["bytes_streamed"] += sum(len(b) for b in datas)
        self.stats["files_filled"] += 1
        self._done[entry.path].set()

    async def _fill_all(self) -> None:
        for entry in self.manifest.files:
            if entry.link_target:
                continue
            ev = self._done[entry.path]
            if ev.is_set() or entry.path in self._claimed:
                continue
            self._claimed.add(entry.path)
            try:
                await self._fill_one(entry, background=True)
            except Exception as exc:     # noqa: BLE001
                # bundle deleted underneath us, chunk unavailable, or any
                # transport error: record, release waiters, move on — a
                # hung filler must never pin active_fill forever
                log.warning("lazy fill %s failed: %s", entry.path, exc)
                self.failed.append(entry.path)
                ev.set()
        # wait for fault-claimed stragglers, then publish completion —
        # but ONLY on a fully successful fill; a partial bundle keeps its
        # lazy marker so the next pull re-skeletons from scratch
        for ev in self._done.values():
            await ev.wait()
        if not self.failed:
            with open(os.path.join(self.dest, COMPLETE_MARKER), "w") as f:
                f.write(self.manifest.manifest_hash)
            try:
                os.unlink(os.path.join(self.dest, LAZY_MARKER))
            except OSError:
                pass
            log.info("lazy fill of %s complete: %d files, %.1f MB",
                     self.dest, self.stats["files_filled"],
                     self.stats["bytes_streamed"] / 1e6)
        else:
            log.warning("lazy fill of %s ABANDONED: %d/%d files failed",
                        self.dest, len(self.failed),
                        self.stats["files_total"])
        if self._server is not None:
            self._server.close()

    # -- fault socket --------------------------------------------------------

    async def _serve_fault(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter) -> None:
        """Protocol: ``REQ <abspath>\\n`` → ``OK\\n`` once the file is real
        (or immediately for paths we don't manage)."""
        try:
            while True:
                line = await reader.readline()
                if not line:
                    break
                parts = line.decode(errors="replace").strip().split(" ", 1)
                if len(parts) != 2 or parts[0] != "REQ":
                    writer.write(b"ERR\n")
                    await writer.drain()
                    continue
                path = os.path.normpath(parts[1])
                rel = os.path.relpath(path, self.dest) \
                    if path.startswith(self.dest + os.sep) else path
                try:
                    await self.ensure_file(rel)
                    writer.write(b"OK\n")
                except IOError as exc:
                    log.warning("fault %s failed: %s", rel, exc)
                    writer.write(b"ERR\n")
                await writer.drain()
        except (ConnectionResetError, BrokenPipeError):
            pass
        finally:
            try:
                writer.close()
            except Exception:
                pass
