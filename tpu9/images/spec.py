"""Image specs: the user-facing build recipe.

Reference analogue: the SDK ``Image`` builder DSL (sdk image.py, 912 LoC) +
the build service's dockerfile-from-steps synthesis
(pkg/abstractions/image/build.go:369-567). tpu9 images are **environment
snapshots**, not OCI layers: a spec deterministically hashes to an image_id,
the builder materializes the env (venv + packages + commands) and snapshots
it into a chunked content-addressed manifest — the lazy-load format that
replaces CLIP.
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import asdict, dataclass, field


@dataclass
class ImageSpec:
    python_version: str = "python3.11"
    python_packages: list[str] = field(default_factory=list)
    commands: list[str] = field(default_factory=list)
    env: dict[str, str] = field(default_factory=dict)
    base_image: str = ""                 # optional base manifest to extend
    include_host_site_packages: bool = False
    # OCI registry ref ("python:3.12", "127.0.0.1:5000/app:v1") — layers are
    # pulled and unpacked into a rootfs/ tree before commands run
    from_registry: str = ""
    # workspace-secret NAME holding "user:password" for private registries
    # (the VALUE never enters the spec/hash — it reaches the build
    # container as env, like the reference's registry credentials)
    registry_secret: str = ""

    def to_dict(self) -> dict:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "ImageSpec":
        return cls(**{k: v for k, v in d.items()
                      if k in cls.__dataclass_fields__})

    @property
    def image_id(self) -> str:
        """Deterministic id: same spec → same image (dedupe at build).
        Fields added after round 1 join the hash only when set, so every
        previously built image keeps its id across upgrades."""
        d = self.to_dict()
        for late_field in ("from_registry", "registry_secret"):
            if not d.get(late_field):
                d.pop(late_field, None)
        blob = json.dumps(d, sort_keys=True).encode()
        return "img-" + hashlib.sha256(blob).hexdigest()[:16]
