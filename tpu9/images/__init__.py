from .spec import ImageSpec
from .manifest import ImageManifest, FileEntry
from .builder import ImageBuilder
from .puller import ImagePuller

__all__ = ["ImageSpec", "ImageManifest", "FileEntry", "ImageBuilder",
           "ImagePuller"]
