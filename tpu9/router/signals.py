"""Router signals bus: observability + autoscaler feed.

Everything the router decides is exported two ways:

- into the process-global metrics registry
  (``tpu9.observability.metrics``) under ``tpu9_router_*`` — visible in
  the gateway's ``/api/v1/metrics`` (JSON and Prometheus) without
  SSHing a node;
- as a live ``pressure(stub_id)`` scalar the endpoint autoscaler mixes
  into its sample, so scale-up is driven by ROUTER pressure (queued work
  + shed events at the front door) and not only by requests that already
  made it into a replica buffer. A fleet that sheds is by definition
  under-provisioned — the shed counter is the loudest scale-up signal
  there is.
"""

from __future__ import annotations

import time
from collections import deque

from ..observability import metrics


class RouterSignals:
    def __init__(self):
        # per-stub rolling counters for shed-rate / pressure computation
        self._submitted: dict[str, int] = {}
        self._shed: dict[str, int] = {}
        self._queue_depth: dict[str, int] = {}
        self._capacity: dict[str, int] = {}     # replicas × budget snapshot
        self._last_shed_ts: dict[str, float] = {}
        # fleet speculative-decoding counters (latest heartbeat fold)
        self._spec_proposed = 0
        self._spec_accepted = 0
        # SLO burn fold (ISSUE 12): stub -> (fast-window burn rate, mono)
        # written by the gateway's SLO sampler each tick; pressure() takes
        # the max of queue pressure and the burn-derived pressure so a
        # burning SLO scales the fleet BEFORE queue depth explodes
        self._slo_burn: dict[str, tuple[float, float]] = {}
        # burn HISTORY (ISSUE 17): stub -> deque of (mono, burn_fast,
        # burn_slow) — the predictive controller fits its slope over
        # this; bounded so a chatty sampler cannot grow it
        self._burn_hist: dict[str, deque] = {}
        # measured bring-up seconds (ISSUE 17): stub -> EWMA of the
        # coldstart record's ready_s heartbeat extra — the scale-down
        # guard's re-acquisition cost
        self._bringup_s: dict[str, float] = {}

    # -- recording -------------------------------------------------------------

    def submitted(self, stub_id: str, tenant: str) -> None:
        self._submitted[stub_id] = self._submitted.get(stub_id, 0) + 1
        metrics.inc("tpu9_router_requests_total", labels={"stub": stub_id})

    def shed(self, stub_id: str, tenant: str, reason: str) -> None:
        self._shed[stub_id] = self._shed.get(stub_id, 0) + 1
        self._last_shed_ts[stub_id] = time.monotonic()
        metrics.inc("tpu9_router_shed_total",
                    labels={"stub": stub_id, "reason": reason})

    def failover(self, stub_id: str, reason: str) -> None:
        """One automatic failover attempt (ISSUE 15): a dispatched
        request failed (replica crash / transport error / stall) and the
        gateway is re-submitting it. Failovers are the fleet's honest
        instability signal — a rising rate with a flat shed rate means
        replicas are dying under requests, not capacity running out."""
        metrics.inc("tpu9_router_failover_total",
                    labels={"stub": stub_id, "reason": reason})

    def retry_result(self, stub_id: str, recovered: bool) -> None:
        """Terminal accounting for a request that needed ≥1 failover:
        did the retries save it? ``recovered_total`` staying equal to
        ``exhausted_total + recovered_total``'s recovered share is the
        zero-client-visible-failures story the faults bench gates."""
        metrics.inc("tpu9_router_failover_recovered_total"
                    if recovered else "tpu9_router_failover_exhausted_total",
                    labels={"stub": stub_id})

    def queue_sample(self, stub_id: str, depth: int, capacity: int) -> None:
        self._queue_depth[stub_id] = depth
        self._capacity[stub_id] = capacity
        metrics.set_gauge("tpu9_router_queue_depth", depth,
                          labels={"stub": stub_id})

    def queue_wait(self, stub_id: str, tenant: str, seconds: float) -> None:
        metrics.observe("tpu9_router_queue_wait_s", seconds,
                        labels={"tenant": tenant})
        # per-STUB series too (ISSUE 8 latency decomposition): the tenant
        # series answers fairness questions, this one answers "where did
        # stub X's TTFT go" next to its ttft series below. Distinct metric
        # name — reusing tpu9_router_queue_wait_s with a different label
        # schema would double-count every request in cross-series sums
        metrics.observe("tpu9_router_stub_queue_wait_s", seconds,
                        labels={"stub": stub_id})

    def ttft(self, stub_id: str, seconds: float) -> None:
        metrics.observe("tpu9_router_ttft_s", seconds,
                        labels={"stub": stub_id})

    def affinity_sample(self, stats: dict) -> None:
        metrics.set_gauge("tpu9_router_prefix_hit_rate",
                          stats.get("hit_rate", 0.0))
        metrics.set_gauge("tpu9_router_prefix_entries",
                          stats.get("entries", 0))

    def slo_sample(self, stub_id: str, burn_fast: float,
                   burn_slow: float = 0.0) -> None:
        """Record the stub's worst fast-window SLO burn rate (ISSUE 12).
        Called by the gateway's SLO sampler; feeds :meth:`pressure` and
        — with the slow-window burn, appended to the bounded history —
        the predictive scaling controller (ISSUE 17)."""
        now = time.monotonic()
        self._slo_burn[stub_id] = (max(float(burn_fast), 0.0), now)
        hist = self._burn_hist.get(stub_id)
        if hist is None:
            hist = self._burn_hist[stub_id] = deque(maxlen=256)
        hist.append((now, max(float(burn_fast), 0.0),
                     max(float(burn_slow), 0.0)))
        metrics.set_gauge("tpu9_router_slo_burn", burn_fast,
                          labels={"stub": stub_id})

    def burn_history(self, stub_id: str) -> list:
        """(mono_ts, burn_fast, burn_slow) series for the predictive
        controller — staleness judged by the CONSUMER against the last
        sample's age (the PR 12 guard lives in the controller)."""
        return list(self._burn_hist.get(stub_id, ()))

    def note_bringup(self, stub_id: str, seconds: float) -> None:
        """Measured replica bring-up (coldstart ``ready_s`` off the
        pressure heartbeat): EWMA so one outlier restore neither hides
        nor dominates the scale-down guard's re-acquisition cost."""
        s = float(seconds)
        if s <= 0:
            return
        prior = self._bringup_s.get(stub_id)
        self._bringup_s[stub_id] = s if prior is None \
            else 0.3 * s + 0.7 * prior

    def bringup_s(self, stub_id: str):
        """Measured bring-up EWMA, or None before any replica of this
        stub has reported one (the controller falls back to its
        configured default)."""
        return self._bringup_s.get(stub_id)

    def slo_pressure(self, stub_id: str) -> float:
        """Pressure contribution of a burning SLO ∈ [0, 1]: burn 1.0 (the
        budget spending exactly at its allowed pace) reads as half
        pressure, burn ≥ 2 saturates. Evaluations older than 30 s are
        ignored — a stopped sampler must not pin pressure forever."""
        burn, ts = self._slo_burn.get(stub_id, (0.0, 0.0))
        if ts == 0.0 or time.monotonic() - ts > 30.0:
            return 0.0
        return min(burn / 2.0, 1.0)

    def spec_sample(self, replica_stats: list,
                    max_age_s: float = 0.0) -> None:
        """Fleet-wide speculative-decoding acceptance (ISSUE 5): fold the
        heartbeated per-engine ``spec_proposed``/``spec_accepted``
        counters into one ratio — the signal that says whether the
        fleet's traffic is actually repetitive enough for prompt-lookup
        speculation to pay for its verify compute.

        ``max_age_s`` > 0 excludes stale heartbeats (ISSUE 12 satellite):
        a replica that stopped beating keeps its last hash in the store
        until the TTL, and folding that corpse into the fleet ratio
        misattributes dead counters to live traffic."""
        proposed = accepted = 0
        for stats in replica_stats:
            if not stats:
                continue
            if max_age_s > 0:
                try:
                    # heartbeat stamps are wall by design (they cross
                    # hosts via the store); staleness here is coarse
                    # (seconds vs an NTP step) and fails open
                    beat_ts = float(stats.get("ts", 0.0))
                    # tpu9: noqa[OBS001] cross-host heartbeat age must use the wall stamp the runner shipped; a step mis-ages one fold, the next beat self-corrects
                    if beat_ts and time.time() - beat_ts > max_age_s:
                        continue
                except (TypeError, ValueError):
                    pass
            try:
                proposed += int(float(stats.get("spec_proposed", 0)))
                accepted += int(float(stats.get("spec_accepted", 0)))
            except (TypeError, ValueError):
                continue
        self._spec_proposed = proposed
        self._spec_accepted = accepted
        metrics.set_gauge("tpu9_router_spec_proposed", proposed)
        metrics.set_gauge("tpu9_router_spec_accepted", accepted)
        metrics.set_gauge("tpu9_router_spec_acceptance_rate",
                          accepted / proposed if proposed else 0.0)

    def forget_stub(self, stub_id: str) -> None:
        """Drop a deleted stub's rolling state and its per-stub gauge
        series (ISSUE 18): the fleet observer calls this when a stub
        leaves ``active_stubs()``. Without it the set_gauge-only series
        hold their last value forever and every per-stub dict grows
        monotonically with stub churn — the same unbounded-cardinality
        class the replica gauges fixed in PR 14."""
        for d in (self._submitted, self._shed, self._queue_depth,
                  self._capacity, self._last_shed_ts, self._slo_burn,
                  self._burn_hist, self._bringup_s):
            d.pop(stub_id, None)
        metrics.remove_gauge("tpu9_router_queue_depth",
                             labels={"stub": stub_id})
        metrics.remove_gauge("tpu9_router_slo_burn",
                             labels={"stub": stub_id})

    # -- reading ---------------------------------------------------------------

    def shed_rate(self, stub_id: str) -> float:
        total = self._submitted.get(stub_id, 0) + self._shed.get(stub_id, 0)
        return self._shed.get(stub_id, 0) / total if total else 0.0

    def queue_depth(self, stub_id: str) -> int:
        return self._queue_depth.get(stub_id, 0)

    def pressure(self, stub_id: str) -> float:
        """Router pressure ∈ [0, 1+]: queued work over fleet capacity,
        saturating to 1.0 whenever a shed happened in the last 10 s — a
        front door that is actively turning traffic away must read as
        fully pressured regardless of instantaneous queue depth. A
        burning SLO (ISSUE 12) raises the floor the same way: objective
        burn is the leading signal, queue depth the trailing one."""
        if time.monotonic() - self._last_shed_ts.get(stub_id, -1e9) < 10.0:
            return 1.0
        slo = self.slo_pressure(stub_id)
        cap = self._capacity.get(stub_id, 0)
        depth = self._queue_depth.get(stub_id, 0)
        if cap <= 0:
            return max(1.0 if depth > 0 else 0.0, slo)
        return max(min(depth / cap, 1.0), slo)

    def latency(self, stub_id: str) -> dict:
        """Front-door latency decomposition for one stub (ISSUE 8): p50/
        p95/count of router TTFT (submit → response) and queue wait
        (submit → dispatch), read back from the registry summaries. The
        engine-side phases (prefill / decode windows / TBT) live in the
        heartbeated "engines" section — together the two answer where a
        request's latency went without SSHing anything."""
        out = {}
        for phase, metric in (("ttft", "tpu9_router_ttft_s"),
                              ("queue_wait", "tpu9_router_stub_queue_wait_s")):
            snap = metrics.summary(metric, labels={"stub": stub_id})
            if snap:
                out[phase] = {"p50_s": round(snap["p50"], 6),
                              "p95_s": round(snap["p95"], 6),
                              "mean_s": round(snap["mean"], 6),
                              "count": snap["count"]}
        return out

    def snapshot(self, stub_id: str) -> dict:
        return {"submitted": self._submitted.get(stub_id, 0),
                "shed": self._shed.get(stub_id, 0),
                "shed_rate": self.shed_rate(stub_id),
                "queue_depth": self.queue_depth(stub_id),
                "pressure": self.pressure(stub_id),
                "slo_burn": self._slo_burn.get(stub_id, (0.0, 0.0))[0],
                "slo_pressure": self.slo_pressure(stub_id),
                "latency": self.latency(stub_id),
                # fleet_ prefix: every other field is per-stub, but the
                # speculation counters fold ALL heartbeating replicas —
                # summing snapshots across stubs must not double-count
                "fleet_spec_proposed": self._spec_proposed,
                "fleet_spec_accepted": self._spec_accepted,
                "fleet_spec_acceptance_rate": (
                    self._spec_accepted / self._spec_proposed
                    if self._spec_proposed else 0.0)}
