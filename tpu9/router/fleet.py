"""FleetRouter: the fleet-level front door between gateway and replicas.

Request lifecycle (buffered invoke path)::

    gateway._serve_stub
      └─ FleetRouter.submit(stub, tenant, body, forward)
           ├─ shed check (queue depth cap) ──────────────→ 429 + Retry-After
           ├─ TenantFairQueue.put (DRR over token cost, quota-weighted)
           └─ per-stub dispatcher task
                ├─ queue-wait deadline ─────────────────→ 503 + Retry-After
                ├─ replica choice: affinity (block-prefix keys) →
                │  join-shortest-queue fallback; draining skipped
                ├─ per-replica in-flight budget (KV headroom) gate
                └─ forward(prefer) → RequestBuffer (per-container
                   concurrency tokens, retries) → replica engine

Streaming requests ride the same shed check and affinity preference but
skip the fair queue: a token stream holds its replica for minutes, and
holding its *admission* in a DRR lane would let one queued stream block
the lane's chat traffic behind it. Budgets still count them (acquired on
connect, released on stream close).

The router is deliberately process-local state over the SHARED container
repository: the gateway is its fleet's single front door, replicas are
discovered from the store exactly like the request buffer does, and the
engines' KV headroom arrives via the pressure table runners already
heartbeat. No new wire protocol, no consensus — λScale's observation is
that placement quality, not placement coordination, is what moves TTFT.
"""

from __future__ import annotations

import asyncio
import json
import logging
import math
import os
import time
from dataclasses import dataclass, field
from typing import Any, Awaitable, Callable, Optional

from ..abstractions.common.buffer import ForwardResult
from ..observability.decisions import ledger, rej
from ..observability.trace import tracer
from ..types import ContainerStatus, Stub
from .admission import AdmissionController, ReplicaBudgets
from .affinity import AffinityRouter
from .fairness import QueuedRequest, TenantFairQueue, estimate_cost
from .prefixdir import PrefixDirectory
from .signals import RouterSignals

log = logging.getLogger("tpu9.router")

PRESSURE_KEY = "llm:pressure:{cid}"     # runner heartbeat table (llm.py)
# health verdicts the router will route to (ISSUE 14); anything else —
# including garbage from a version-skewed runner — reads as stalled, the
# same never-look-healthy contract observability.health.health_code pins
_ROUTABLE_HEALTH = ("ok", "degraded")


def _shed_result(status: int, error: str, retry_after_s: float) -> ForwardResult:
    return ForwardResult(
        status=status,
        body=json.dumps({"error": error,
                         "retry_after_s": round(retry_after_s, 3)}).encode(),
        headers=[("Retry-After", str(max(1, math.ceil(retry_after_s)))),
                 ("Content-Type", "application/json")])


def _deadline_result() -> ForwardResult:
    """Request already past its propagated deadline (ISSUE 15): 504
    without Retry-After — the budget is SPENT, a retry would only burn
    chips on an answer the client stopped waiting for."""
    return ForwardResult(
        status=504,
        body=json.dumps({"error": "deadline_exceeded: budget exhausted "
                                  "at the front door"}).encode(),
        headers=[("Content-Type", "application/json")])


@dataclass
class _Pending:
    body: bytes
    forward: Callable[[list], Awaitable[ForwardResult]]
    dispatched: bool = False
    # trace propagation (ISSUE 8): the gateway.invoke span context captured
    # at submit — the dispatcher runs in a different task, so the
    # contextvar chain breaks here and the pair is carried explicitly.
    # ("", "") = untraced (e.g. bench driving the router directly).
    ctx: tuple = ("", "")
    ws: str = ""                  # workspace stamp for /api/v1/traces scoping
    qspan: Any = None             # open router.queue_wait span (one finisher)


@dataclass
class _StubState:
    stub: Stub
    queue: TenantFairQueue
    dispatcher: Optional[asyncio.Task] = None
    cold_inflight: int = 0          # forwards admitted with zero replicas
    # last observed RUNNING replica count: the shed path reads this
    # instead of paying a store round-trip per rejected request
    replica_count: int = 0
    created_at: float = field(default_factory=time.monotonic)


class FleetRouter:
    def __init__(self, cfg, store, containers, backend=None):
        """``cfg`` is an AppConfig.router (RouterConfig); ``backend`` is
        the BackendDB used for workspace quota → tenant weight lookups
        (None = every tenant weighs 1.0)."""
        self.cfg = cfg
        self.store = store
        self.containers = containers
        self.backend = backend
        self.affinity = AffinityRouter(block_tokens=cfg.affinity_block_tokens,
                                       ttl_s=cfg.affinity_ttl_s)
        # prefix directory (ISSUE 20): evidence-based placement layered
        # over the affinity guess. None when disabled — every use site
        # guards, so TPU9_KV_TIER=0 routes bit-identically to today.
        from ..config import env_kv_tier_on
        self.prefix_dir: Optional[PrefixDirectory] = None
        if getattr(cfg, "prefix_directory", True) and env_kv_tier_on():
            self.prefix_dir = PrefixDirectory(
                block_tokens=cfg.affinity_block_tokens)
        self.budgets = ReplicaBudgets(
            default_inflight=cfg.default_replica_inflight,
            kv_tokens_per_request=cfg.kv_tokens_per_request,
            max_inflight=cfg.max_replica_inflight)
        self.admission = AdmissionController(
            self.budgets,
            max_queue_depth=cfg.max_queue_depth,
            max_queue_wait_s=cfg.max_queue_wait_s,
            shed_retry_after_s=cfg.shed_retry_after_s)
        self.signals = RouterSignals()
        self._stubs: dict[str, _StubState] = {}
        # (workspace_id) -> (weight, fetched_at): quota reads are a DB hit
        self._weights: dict[str, tuple[float, float]] = {}
        self._stopping = False
        # strong refs to spawned forward tasks: the event loop only holds
        # weak ones, and a GC'd mid-flight task would strand its future
        # AND leak the replica's budget slot (the CacheClient._peer_put
        # lesson from ISSUE 1)
        self._bg_tasks: set[asyncio.Task] = set()

    # -- lifecycle -------------------------------------------------------------

    async def stop(self) -> None:
        self._stopping = True
        for stub_id in list(self._stubs):
            await self.drop_stub(stub_id)

    async def drop_stub(self, stub_id: str) -> None:
        """Tear down one stub's router state (deployment drained/deleted):
        cancel its dispatcher, answer still-queued submitters. Without
        this, a long-lived gateway leaks a suspended dispatcher task per
        stub it ever served."""
        st = self._stubs.pop(stub_id, None)
        if st is None:
            return
        if st.dispatcher is not None:
            # re-cancel until done (PR 1's Dispatcher.stop lesson): a
            # cancel racing an in-flight wakeup can be consumed by the
            # loop body; one unbounded await would hang shutdown
            while not st.dispatcher.done():
                st.dispatcher.cancel()
                await asyncio.wait({st.dispatcher}, timeout=1.0)
            try:
                st.dispatcher.exception()
            except asyncio.CancelledError:  # tpu9: noqa[ASY003] exception() on a done cancelled task raises its stored CancelledError — retrieval, not a swallowed live cancel
                pass
            st.dispatcher = None
        # flush still-queued requests: their submitters must get an
        # answer now, not hang out their whole queue-wait budget while
        # the HTTP runner drains
        while True:
            req = st.queue.pop()
            if req is None:
                break
            if isinstance(req.item, _Pending):
                self._finish_qspan(req.item, status="error",
                                   reason="deployment_shutdown")
            if req.future is not None and not req.future.done():
                req.future.set_result(_shed_result(
                    503, "deployment shutting down",
                    self.cfg.shed_retry_after_s))

    def _state(self, stub: Stub) -> Optional[_StubState]:
        """Per-stub router state, or None once stopping — a submit racing
        shutdown must not respawn a dispatcher nobody will ever cancel."""
        if self._stopping:
            return None
        st = self._stubs.get(stub.stub_id)
        if st is None:
            st = _StubState(stub=stub, queue=TenantFairQueue(
                quantum_tokens=self.cfg.tenant_quantum_tokens))
            self._stubs[stub.stub_id] = st
        if st.dispatcher is None or st.dispatcher.done():
            st.dispatcher = asyncio.create_task(self._dispatch_loop(st))
        return st

    # -- autoscaler / observability feed --------------------------------------

    def queue_depth(self, stub_id: str) -> int:
        st = self._stubs.get(stub_id)
        return st.queue.depth if st else 0

    def pressure(self, stub_id: str) -> float:
        return self.signals.pressure(stub_id)

    def snapshot(self, stub_id: str) -> dict:
        out = self.signals.snapshot(stub_id)
        out["affinity"] = self.affinity.stats()
        if self.prefix_dir is not None:
            out["prefix_dir"] = self.prefix_dir.stats()
        return out

    def snapshot_all(self) -> dict:
        return {stub_id: self.snapshot(stub_id) for stub_id in self._stubs}

    def active_stubs(self) -> list[Stub]:
        """Stubs with live router state (the gateway's SLO sampler walks
        these for per-stub timeline series + burn evaluation)."""
        return [st.stub for st in self._stubs.values()]

    # -- tenant weights --------------------------------------------------------

    async def _tenant_weight(self, workspace_id: str) -> float:
        """DRR weight from the workspace concurrency quota: a tenant with
        a reserved chip cap gets front-door share proportional to it
        (cap/4, clamped to [0.5, 16]); unlimited/unconfigured tenants
        weigh 1.0. Cached 30 s — quota edits apply within a refresh."""
        if self.backend is None or not workspace_id:
            return 1.0
        cached = self._weights.get(workspace_id)
        now = time.monotonic()
        if cached is not None and now - cached[1] < 30.0:
            return cached[0]
        weight = 1.0
        try:
            limit = await self.backend.get_concurrency_limit(workspace_id)
            chips = int((limit or {}).get("tpu_chip_limit") or 0)
            if chips > 0:
                weight = min(max(chips / 4.0, 0.5), 16.0)
        except Exception as exc:    # noqa: BLE001 — fairness degrades to
            log.debug("tenant weight lookup failed: %s", exc)   # equal share
        self._weights[workspace_id] = (weight, now)
        return weight

    # -- trace spans (ISSUE 8) -------------------------------------------------

    def _adm_span(self, ctx: tuple, stub: Stub, tenant: str, decision: str,
                  reason: str = "", **extra) -> None:
        """Record the admission DECISION as a (near-instant) child span of
        the invoke span: admitted/queued vs shed, with the shed reason —
        the evidence `why did my request 429` queries need. The span
        no-ops when the request carries no trace context (bench drives
        the router raw); the decision LEDGER record (ISSUE 19) is
        unconditional — fleet-level shed history must exist even for
        untraced traffic."""
        ledger.record(
            "admission", decision, request_id=ctx[0],
            chosen="shed" if decision == "shed" else "admit",
            rejected=[rej("admit", reason)] if decision == "shed" else (),
            signals={"tenant": tenant, **extra},
            stub_id=stub.stub_id, workspace_id=stub.workspace_id)
        if not ctx[0]:
            return
        attrs = {"stub_id": stub.stub_id, "workspace_id": stub.workspace_id,
                 "tenant": tenant, "decision": decision, **extra}
        if reason:
            attrs["reason"] = reason
        sp = tracer.start_span("router.admission", trace_id=ctx[0],
                               parent_id=ctx[1], attrs=attrs)
        tracer.finish_span(sp, status="error" if decision == "shed"
                           else "ok")

    @staticmethod
    def _finish_qspan(pending: _Pending, status: str = "ok",
                      **attrs) -> None:
        sp, pending.qspan = pending.qspan, None    # exactly one finisher
        if sp is not None:
            sp.attrs.update(attrs)
            tracer.finish_span(sp, status=status)

    # -- submit (buffered path) ------------------------------------------------

    async def submit(self, stub: Stub, tenant: str, body: bytes,
                     forward: Callable[[list], Awaitable[ForwardResult]],
                     deadline_mono: float = 0.0) -> ForwardResult:
        """Admit → fair-queue → dispatch → forward. ``forward`` receives
        the router's replica preference order (container ids, best first)
        and performs the actual buffer forward.

        ``deadline_mono`` (ISSUE 15): the request's propagated monotonic
        deadline — already-expired requests are answered 504 at the door
        (never queued, never dispatched), and the queue-wait budget is
        clamped to the remaining budget so the fair queue cannot hold a
        request past its own deadline."""
        ctx = tracer.context()          # gateway.invoke, when routed via HTTP
        st = self._state(stub)
        if st is None:                  # racing shutdown
            return _shed_result(503, "gateway shutting down",
                                self.cfg.shed_retry_after_s)
        if deadline_mono > 0 and time.monotonic() >= deadline_mono:
            self.signals.shed(stub.stub_id, tenant, "deadline")
            self._adm_span(ctx, stub, tenant, "shed", reason="deadline")
            return _deadline_result()
        if self.admission.should_shed(st.queue.depth):
            # no store reads on the reject path: shedding must stay cheap
            # under exactly the burst that triggers it
            ra = self.admission.retry_after_s(stub.stub_id, st.queue.depth,
                                              max(st.replica_count, 1))
            self.signals.shed(stub.stub_id, tenant, "queue_full")
            self._adm_span(ctx, stub, tenant, "shed", reason="queue_full",
                           queue_depth=st.queue.depth,
                           retry_after_s=round(ra, 3))
            return _shed_result(429, "fleet at capacity, retry later", ra)

        loop = asyncio.get_running_loop()
        pending = _Pending(body=body, forward=forward, ctx=ctx,
                           ws=stub.workspace_id)
        self._adm_span(ctx, stub, tenant, "queued",
                       queue_depth=st.queue.depth)
        if ctx[0]:
            pending.qspan = tracer.start_span(
                "router.queue_wait", trace_id=ctx[0], parent_id=ctx[1],
                attrs={"stub_id": stub.stub_id,
                       "workspace_id": stub.workspace_id, "tenant": tenant})
        wait_budget = min(self.cfg.max_queue_wait_s,
                          max(stub.config.timeout_s, 1.0))
        if deadline_mono > 0:
            wait_budget = min(wait_budget,
                              max(deadline_mono - time.monotonic(), 0.01))
        req = QueuedRequest(tenant=tenant, cost=estimate_cost(body),
                            item=pending, future=loop.create_future(),
                            deadline=time.monotonic() + wait_budget)
        st.queue.put(req, weight=await self._tenant_weight(tenant))
        self.signals.submitted(stub.stub_id, tenant)
        try:
            return await asyncio.wait_for(asyncio.shield(req.future),
                                          wait_budget)
        except asyncio.TimeoutError:
            # Retry-After computed WITHOUT awaiting: an await here opens a
            # window for the dispatcher to launch the request, and a 503
            # set after that would double-execute on the client's retry
            ra = self.admission.retry_after_s(stub.stub_id, st.queue.depth,
                                              1)
            if not pending.dispatched and not req.future.done():
                # still queued past the SLO budget: dead weight — shed it
                # and purge it (and any other resolved entries) from the
                # lanes so they stop counting toward the shed depth
                self.signals.shed(stub.stub_id, tenant, "queue_wait")
                self._finish_qspan(pending, status="error",
                                   reason="queue_wait_deadline")
                req.future.set_result(_shed_result(
                    503, "queue wait exceeded deadline", ra))
                st.queue.drop_completed()
            # dispatched (or resolved) meanwhile: the forward's own
            # timeout governs from here
            return await req.future

    # -- streaming path --------------------------------------------------------

    async def admit_stream(self, stub: Stub, tenant: str, body: bytes,
                           deadline_mono: float = 0.0
                           ) -> tuple[Optional[ForwardResult], list[str]]:
        """Shed check + preference order for a streaming request.
        Returns (shed_response, prefer): shed_response is None when
        admitted. The caller reports the serving replica via
        :meth:`stream_started` / releases with the returned callback."""
        ctx = tracer.context()
        st = self._state(stub)
        if st is None:                  # racing shutdown
            return (_shed_result(503, "gateway shutting down",
                                 self.cfg.shed_retry_after_s), [])
        if deadline_mono > 0 and time.monotonic() >= deadline_mono:
            self.signals.shed(stub.stub_id, tenant, "deadline")
            self._adm_span(ctx, stub, tenant, "shed", reason="deadline",
                           stream=True)
            return (_deadline_result(), [])
        if self.admission.should_shed(st.queue.depth):
            ra = self.admission.retry_after_s(stub.stub_id, st.queue.depth,
                                              max(st.replica_count, 1))
            self.signals.shed(stub.stub_id, tenant, "queue_full")
            self._adm_span(ctx, stub, tenant, "shed", reason="queue_full",
                           stream=True, queue_depth=st.queue.depth)
            return (_shed_result(429, "fleet at capacity, retry later", ra),
                    [])
        self.signals.submitted(stub.stub_id, tenant)
        replicas = await self._running(stub.stub_id)
        order, _, _, hit, ev = await self._preference(stub.stub_id, body,
                                                      replicas)
        self._adm_span(ctx, stub, tenant, "admitted", stream=True,
                       affinity_hit=hit,
                       replica=order[0] if order else "cold")
        # the stream's placement decision happens HERE (no fair queue /
        # dispatcher pass): one ledger record with the same evidence
        # shape as the buffered path's _launch record
        ledger.record("placement", "stream_admit", request_id=ctx[0],
                      chosen=order[0] if order else "cold_start",
                      rejected=ev["rejected"], signals=ev["signals"],
                      stub_id=stub.stub_id, workspace_id=stub.workspace_id)
        return None, order

    def stream_started(self, stub: Stub, body: bytes,
                       container_id: str) -> Callable[[], None]:
        """Count a live stream against the replica's budget + record the
        affinity mapping. Returns the release callback (idempotent —
        StreamHandle.close may race teardown paths). A failed acquire
        (replica already at its hard ceiling) must NOT release on close,
        or every such cycle undercounts in-flight by one and admission
        drifts past the KV-headroom budget."""
        acquired = self.budgets.try_acquire(container_id,
                                            self.budgets.max_inflight)
        self.affinity.record_served(body, container_id)
        released = not acquired

        def release() -> None:
            nonlocal released
            if not released:
                released = True
                self.budgets.release(container_id)

        return release

    # -- replica health (ISSUE 14) ---------------------------------------------

    def note_replica_health(self, container_id: str, state: str,
                            reason: str = "") -> None:
        """Fold one replica's heartbeated health verdict into routing: a
        ``stalled`` replica is ejected like a draining one (skipped by
        affinity and JSQ, its affinity entries dropped so prefix traffic
        re-homes NOW, its budget excluded from fleet capacity so the
        autoscaler sees the missing replica as pressure), and a recovered
        one is restored. Called by the gateway's FleetObserver on the
        heartbeat cadence; the dispatch path re-checks the same field on
        the pressure stats it already fetches, so direct drivers (bench)
        get the same ejection without the observer.

        Only the states this router KNOWS to be routable restore a
        replica — an unparseable verdict (version skew, corruption) is
        treated as stalled, matching ``health_code``'s never-look-healthy
        contract: the gauges and the routing plane must agree on what a
        garbage verdict means."""
        if state not in _ROUTABLE_HEALTH:
            newly = not self.admission.is_stalled(container_id)
            self.admission.mark_stalled(
                container_id, ttl_s=self.cfg.health_eject_ttl_s)
            if newly:
                self.affinity.forget_replica(container_id)
                if self.prefix_dir is not None:
                    self.prefix_dir.forget_replica(container_id)
                log.warning("replica %s health=%s (%s) — ejected "
                            "from routing", container_id,
                            state or "?", reason)
        elif self.admission.is_stalled(container_id):
            self.admission.clear_stalled(container_id)
            log.warning("replica %s health=%s — restored to routing",
                        container_id, state or "ok")

    def note_dispatch_failure(self, container_id: str) -> None:
        """A dispatched request FAILED on this replica (gateway failover,
        ISSUE 15): drop its affinity entries so repeat-prefix traffic
        re-homes now instead of riding the TTL back into the same
        failure. Deliberately NOT a routing ejection — one failed request
        is not a stall verdict; eligibility stays the health plane's call
        (`note_replica_health`), this only stops steering warm prefixes
        at a replica that just dropped one."""
        self.affinity.forget_replica(container_id)
        if self.prefix_dir is not None:
            self.prefix_dir.forget_replica(container_id)

    # -- drain -----------------------------------------------------------------

    async def drain_replica(self, container_id: str,
                            migrate: Optional[Callable[
                                [str], Awaitable[None]]] = None) -> bool:
        """Graceful scale-down: stop routing to the replica, drop its
        affinity entries (traffic re-homes now, not at TTL), and wait for
        its in-flight requests to complete.

        ``migrate`` (ISSUE 16) is an optional injected hook run AFTER the
        replica leaves rotation but BEFORE the drain wait — the control
        plane uses it to ask the still-serving replica to export its
        in-flight streams' KV blocks, so generations that outlive the
        drain window resume elsewhere by block ship instead of dying
        with the container. Injected because the router is payload-free
        by contract (BND001: no serving/runner imports here)."""
        self.admission.mark_draining(container_id)
        self.affinity.forget_replica(container_id)
        if self.prefix_dir is not None:
            # residency claims die with the replica; its PEER publications
            # survive inside the directory — that is the scale-to-zero
            # recovery path (ISSUE 20)
            self.prefix_dir.forget_replica(container_id)
        inflight0 = self.budgets.inflight(container_id)
        migrate_ok = migrate is not None
        if migrate is not None:
            try:
                await migrate(container_id)
            except Exception as exc:    # noqa: BLE001 — best-effort
                migrate_ok = False
                log.warning("drain migration hook failed for %s: %s",
                            container_id, exc)
        drained = await self.admission.wait_drained(
            container_id, timeout=self.cfg.drain_timeout_s)
        if not drained:
            log.warning("replica %s still has %d in-flight after %.1fs "
                        "drain window — stopping anyway", container_id,
                        self.budgets.inflight(container_id),
                        self.cfg.drain_timeout_s)
        # the control-plane half of the migration story (ISSUE 19): did
        # this replica leave gracefully, and was a KV export attempted?
        # (the runner's per-stream export/adopt records are the other
        # half, keyed by request id over the heartbeat)
        ledger.record(
            "migration", "drain",
            chosen="drained" if drained else "force_stop",
            rejected=[] if drained else [rej("graceful_drain",
                                             "drain_timeout")],
            signals={"container_id": container_id,
                     "inflight_at_drain": inflight0,
                     "inflight_left": self.budgets.inflight(container_id),
                     "migrate_hook": migrate is not None,
                     "migrate_ok": migrate_ok,
                     "timeout_s": self.cfg.drain_timeout_s})
        return drained

    # -- dispatch --------------------------------------------------------------

    async def _running(self, stub_id: str) -> list:
        states = await self.containers.containers_by_stub(
            stub_id, status=ContainerStatus.RUNNING.value)
        # draining AND stalled replicas are both out of rotation; the
        # stalled mark's TTL expiry is the recovery probe (ISSUE 14)
        return [s for s in states
                if not self.admission.is_draining(s.container_id)
                and not self.admission.is_stalled(s.container_id)]

    async def _replica_stats(self, container_id: str) -> Optional[dict]:
        data = await self.store.hgetall(
            PRESSURE_KEY.format(cid=container_id))
        return data or None

    async def _preference(self, stub_id: str, body: bytes, replicas: list
                          ) -> tuple[list[str], dict[str, int], int, bool,
                                     dict]:
        """(ordered container ids, per-replica budgets, fleet capacity,
        affinity hit, decision evidence). Load for JSQ = router-tracked
        in-flight plus the replica's OWN reported queue (requests the
        engine already holds). The evidence dict carries the
        rejected-alternatives list + input signals the placement ledger
        record (ISSUE 19) needs — built here because only this pass
        knows WHY a replica fell out of the candidate order."""
        budgets: dict[str, int] = {}
        load: dict[str, float] = {}
        saturated: set[str] = set()
        rejected: list[dict] = []
        # execute-while-scaling (ISSUE 17): cid -> (ready_frac, ready
        # group names) off the pressure stats; replicas not reporting
        # the scaleout family are fully ready (steady state / old beat)
        readiness: dict[str, tuple[float, set[str]]] = {}
        # pressure snapshots are independent per replica: fetch them
        # concurrently — N serial store round-trips per dispatch attempt
        # (re-paid every 250 ms retry pass) would dominate TTFT on a
        # remote store
        all_stats = await asyncio.gather(*(
            self._replica_stats(s.container_id) for s in replicas))
        # fold the heartbeated speculative-decoding counters into the
        # fleet-wide tpu9_router_spec_* gauges (ISSUE 5) — this is the
        # dispatch path, so the signal refreshes exactly as often as the
        # stats it is derived from; replicas silent past the staleness
        # budget are excluded (ISSUE 12: dead counters must not haunt
        # the fleet aggregate until the store TTL)
        self.signals.spec_sample(all_stats,
                                 max_age_s=getattr(self.cfg,
                                                   "heartbeat_stale_s", 6.0))
        for s, stats in zip(replicas, all_stats):
            cid = s.container_id
            if self.prefix_dir is not None and stats:
                # directory fold rides the dispatch-path stats fetch, the
                # same refresh cadence as every other pressure signal
                self.prefix_dir.observe_replica(cid, stats)
            health = str(stats.get("health", "") or "") if stats else ""
            if health and health not in _ROUTABLE_HEALTH:
                # dispatch-time defense (ISSUE 14): the heartbeat fold
                # normally marks this before a dispatch ever sees it, but
                # a direct driver (bench) or a verdict landing between
                # passes must still eject HERE — zero budget, no order
                # slot, capacity shrinks by the whole replica
                self.note_replica_health(cid, health,
                                         str(stats.get("health_reason",
                                                       "")))
                rejected.append(rej(cid, f"health:{health}"))
                continue
            budgets[cid] = self.budgets.budget_from_stats(stats)
            if stats and "scaleout_ready_frac" in stats:
                try:
                    frac = float(stats.get("scaleout_ready_frac", 1.0))
                except (TypeError, ValueError):
                    frac = 1.0
                readiness[cid] = (frac, {
                    g for g in str(stats.get("scaleout_ready_groups",
                                             "")).split(",") if g})
            queued = 0.0
            if stats:
                try:
                    queued = float(stats.get("queued", 0))
                except (TypeError, ValueError):
                    queued = 0.0
            load[cid] = self.budgets.inflight(cid) + queued
            if self.budgets.inflight(cid) >= budgets[cid]:
                saturated.add(cid)
        # affinity hit detection via the counter delta: order() classifies
        # internally and the call is synchronous, so no other coroutine
        # can interleave between the read and the call (single-threaded
        # loop) — cheaper than re-walking the block keys a second time
        hits0 = self.affinity.hits
        # candidates = the replicas that survived the health check above
        # (load preserves replica order); a stalled replica must not even
        # be an affinity target or it re-enters through the JSQ fallback
        order = self.affinity.order(body, list(load), load, saturated)
        order = self._disagg_order(body, order)
        fenced = list(order)
        order = self._scaleout_admit(body, order, readiness)
        rejected.extend(rej(cid, "scaleout_fence") for cid in fenced
                        if cid not in order)
        rejected.extend(rej(cid, "saturated") for cid in saturated
                        if cid not in order)
        hit = self.affinity.hits > hits0
        order, dir_hit = self._directory_promote(body, order, saturated)
        signals = {"candidates": len(order), "affinity_hit": hit,
                   "capacity": sum(budgets.values()),
                   "queue_depth": self.queue_depth(stub_id)}
        if dir_hit:
            signals["prefix_dir_tier"] = dir_hit.get("tier", "p")
            signals["prefix_dir_tokens"] = dir_hit.get("n_tokens", 0)
        for cid, ld in load.items():
            signals[f"load.{cid}"] = ld
        return (order, budgets, signals["capacity"], hit,
                {"rejected": rejected, "signals": signals})

    def _directory_promote(self, body: bytes, order: list[str],
                           saturated: set) -> tuple[list[str],
                                                    Optional[dict]]:
        """Directory-informed placement (ISSUE 20): when the prefix
        directory knows a replica that holds this request's longest
        prefix — from any tier — move it to the head of the candidate
        order. Runs AFTER the scale-out fence and the disagg bias, so a
        directory hit can only promote a replica that already survived
        every eligibility check; a saturated or fenced claimant is left
        where JSQ put it (placement quality must not beat availability).
        A peer-only hit promotes nothing (any replica can pull the tier)
        but still returns the hit so the adopt path and the ledger see
        it. Every promotion leaves a ``kv_tier`` "place" record: the
        'why' evidence for steering past shorter-queue replicas."""
        if self.prefix_dir is None or not order:
            return order, None
        hit = self.prefix_dir.lookup(body, live=set(order))
        if not hit:
            return order, None
        cid = hit.get("cid")
        if cid and cid in order and cid not in saturated:
            if order[0] != cid:
                order = [cid] + [c for c in order if c != cid]
                ledger.record(
                    "kv_tier", "place",
                    chosen=f"{hit['tier']}:{cid}",
                    rejected=[rej("jsq_head", "shorter_prefix")],
                    signals={"key": hit["key"],
                             "tier": hit["tier"],
                             "n_tokens": hit["n_tokens"]})
        return order, hit

    def kv_adopt_hint(self, body: bytes) -> Optional[dict]:
        """Peer-tier adopt hint for the gateway's stream path: when the
        directory's best residency for this body is ONLY the peer cache
        (no live replica claims it), return the ``adopt_kv`` payload the
        chosen replica should pull instead of recomputing the prefix —
        the scale-to-zero / replica-death recovery path. Returns None on
        a live-replica hit (tiers pull locally) or a miss."""
        if self.prefix_dir is None:
            return None
        hit = self.prefix_dir.lookup(body)
        if not hit or "peer_digest" not in hit:
            return None
        ledger.record(
            "kv_tier", "pull",
            chosen=f"peer:{hit['key']}",
            rejected=[rej("recompute", "peer_copy_resident")],
            signals={"key": hit["key"], "digest": hit["peer_digest"],
                     "n_tokens": hit["n_tokens"]})
        return {"key": hit["peer_digest"], "n_tokens": hit["n_tokens"]}

    @staticmethod
    def _scaleout_admit(body: bytes, order: list[str],
                        readiness: dict[str, tuple[float, set[str]]]
                        ) -> list[str]:
        """Partial-readiness admission (ISSUE 17 execute-while-scaling):
        a replica mid-restore reports its bound weight groups on the
        pressure heartbeat; it may serve a request ONLY when the
        request's declared ``weight_groups`` are all resident. Unlike
        the disagg bias this is a FENCE — a half-restored replica
        serving a request whose groups have not landed would fail it,
        not slow it. Requests that declare nothing require full
        readiness (the conservative "admit nothing until complete"
        fallback); an emptied order falls into the dispatch loop's
        existing budget-wait, so the request queues rather than fails.
        ``TPU9_SCALEOUT_PARTIAL=0`` disables group-hint admission
        entirely (fence on readiness fraction alone)."""
        partial = [c for c in order
                   if readiness.get(c, (1.0, set()))[0] < 1.0]
        if not partial:
            return order
        want: set[str] = set()
        from ..config import env_scaleout_partial_on
        if env_scaleout_partial_on():
            try:
                payload = json.loads(body or b"{}")
                wg = payload.get("weight_groups") or []
                if isinstance(wg, list):
                    want = {str(g) for g in wg if g}
            except (ValueError, TypeError, AttributeError):
                want = set()
        return [c for c in order
                if readiness.get(c, (1.0, set()))[0] >= 1.0
                or (want and want.issubset(readiness[c][1]))]

    def _disagg_on(self) -> bool:
        env = os.environ.get("TPU9_DISAGG", "")
        if env:
            return env == "1"
        return bool(getattr(self.cfg, "disagg_enabled", False))

    def _disagg_order(self, body: bytes, order: list[str]) -> list[str]:
        """Disaggregated prefill/decode placement (ISSUE 16): classify
        the request by prompt/output shape and bias the candidate order
        toward the matching partition. The partition is DETERMINISTIC —
        sorted container ids, the first ``ceil(fraction * n)`` lean
        prefill, always leaving at least one decode replica — so every
        router instance agrees without coordination, and the same split
        is stable across dispatch passes (a long-doc prompt keeps
        landing where its prefix already is).

        This is a BIAS, not a fence: a saturated preferred partition
        still falls through to the other one (availability beats
        placement), and an ``adopt_kv`` resume/handoff body is always
        decode-leaning regardless of its replayed prompt length — the
        whole point of the handoff is to get the long sequence OFF the
        prefill replicas."""
        if len(order) < 2 or not self._disagg_on():
            return order
        try:
            payload = json.loads(body or b"{}")
            tokens = payload.get("tokens") \
                or payload.get("prompt_tokens") or []
            prompt_len = len(tokens) if isinstance(tokens, list) else 0
            adopting = bool(payload.get("adopt_kv"))
        except (ValueError, TypeError, AttributeError):
            return order
        ranked = sorted(order)
        frac = float(getattr(self.cfg, "disagg_prefill_fraction", 0.5))
        n_prefill = min(max(1, math.ceil(len(ranked) * frac)),
                        len(ranked) - 1)
        prefill = set(ranked[:n_prefill])
        heavy = (not adopting and prompt_len
                 >= int(getattr(self.cfg, "disagg_prefill_tokens", 512)))
        want = prefill if heavy else set(ranked) - prefill
        return ([c for c in order if c in want]
                + [c for c in order if c not in want])

    async def _dispatch_loop(self, st: _StubState) -> None:
        stub_id = st.stub.stub_id
        while True:
            req = None
            try:
                req = await st.queue.get()
                await self._dispatch_one(st, req)
            except asyncio.CancelledError:
                # an in-hand request (popped, not yet launched) must get
                # an answer — its submitter would otherwise wait out the
                # full queue budget during shutdown
                if (req is not None and req.future is not None
                        and not req.future.done()):
                    if isinstance(req.item, _Pending):
                        self._finish_qspan(req.item, status="error",
                                           reason="gateway_shutdown")
                    req.future.set_result(_shed_result(
                        503, "gateway shutting down",
                        self.cfg.shed_retry_after_s))
                raise
            except Exception as exc:    # noqa: BLE001 — one bad request /
                # store blip must not kill routing for the stub forever
                log.warning("router dispatch pass failed for %s: %s",
                            stub_id, exc)
                # the popped request is no longer in the queue: answer it
                # NOW with a 502 — abandoning it would hang its submitter
                # for the whole queue-wait budget over one store blip
                if (req is not None and req.future is not None
                        and not req.future.done()):
                    if isinstance(req.item, _Pending):
                        self._finish_qspan(req.item, status="error",
                                           reason=type(exc).__name__)
                    req.future.set_result(ForwardResult(
                        status=502,
                        body=json.dumps(
                            {"error": type(exc).__name__}).encode()))
                await asyncio.sleep(0.05)

    async def _dispatch_one(self, st: _StubState, req: QueuedRequest) -> None:
        stub_id = st.stub.stub_id
        pending: _Pending = req.item
        while True:
            if req.future.done():       # caller shed/abandoned while queued
                return
            if self.admission.expired(req.enqueued_at, req.deadline):
                # resolved by submit's own deadline arm; belt-and-braces
                # for direct callers (bench drives the router without HTTP)
                if not req.future.done():
                    ra = self.admission.retry_after_s(stub_id, st.queue.depth,
                                                      1)
                    self.signals.shed(stub_id, req.tenant, "queue_wait")
                    self._finish_qspan(pending, status="error",
                                       reason="queue_wait_deadline")
                    req.future.set_result(_shed_result(
                        503, "queue wait exceeded deadline", ra))
                return
            replicas = await self._running(stub_id)
            st.replica_count = len(replicas)
            if req.future.done():
                # the submitter's deadline fired during the store read:
                # launching now would EXECUTE a request whose client was
                # just told 503-retry — the double-execution this check
                # exists to prevent
                return
            if not replicas:
                # scale-from-zero: the buffer knows how to wait for the
                # first container; bound the stampede so one cold stub
                # can't hold thousands of forwards open at once
                if st.cold_inflight < self.cfg.default_replica_inflight:
                    self._launch(st, req, prefer=[], replica="")
                    return
            else:
                order, budgets, capacity, hit, ev = await self._preference(
                    stub_id, pending.body, replicas)
                self.signals.queue_sample(stub_id, st.queue.depth, capacity)
                if req.future.done():    # deadline racing _preference
                    return
                busy: list[str] = []
                for cid in order:
                    if self.budgets.try_acquire(cid, budgets.get(cid, 1)):
                        # replicas ranked ahead but at budget were real
                        # rejections for THIS dispatch — fold them into
                        # the evidence the _launch record carries
                        ev["rejected"] = (ev["rejected"]
                                          + [rej(c, "budget_busy")
                                             for c in busy])
                        self._launch(st, req, prefer=order, replica=cid,
                                     affinity_hit=hit, evidence=ev)
                        return
                    busy.append(cid)
            # every replica at budget (or cold cap hit): wait for a
            # release / container event, then re-evaluate
            await self.budgets.wait_release(0.25)

    def _launch(self, st: _StubState, req: QueuedRequest,
                prefer: list[str], replica: str,
                affinity_hit: Optional[bool] = None,
                evidence: Optional[dict] = None) -> None:
        pending: _Pending = req.item
        pending.dispatched = True
        if not replica:                 # replica slots are acquired by the
            st.cold_inflight += 1       # dispatcher before _launch
        wait_s = time.monotonic() - req.enqueued_at
        self.signals.queue_wait(st.stub.stub_id, req.tenant, wait_s)
        self._finish_qspan(pending, wait_s=round(wait_s, 6))
        # cold-start launches carry no preference pass: the honest
        # evidence is an empty candidate set, not a missing signal
        ev = evidence or {"rejected": [], "signals": {"candidates": 0}}
        ledger.record("placement", "dispatch", request_id=pending.ctx[0],
                      chosen=replica or "cold_start",
                      rejected=ev["rejected"],
                      signals={**ev["signals"],
                               "queue_wait_s": round(wait_s, 6)},
                      stub_id=st.stub.stub_id, workspace_id=pending.ws)
        if pending.ctx[0]:
            # the placement decision: affinity hit/miss + chosen replica
            # (an instant span — it records an outcome, not an interval)
            now_m = time.monotonic()
            tracer.record_span(
                "router.dispatch", pending.ctx[0], pending.ctx[1],
                time.time(), now_m,
                attrs={"stub_id": st.stub.stub_id, "workspace_id": pending.ws,
                       "tenant": req.tenant,
                       "replica": replica or "cold_start",
                       "affinity_hit": bool(affinity_hit),
                       "candidates": len(prefer)},
                end_mono=now_m)
        t = asyncio.create_task(self._forward_one(st, req, prefer, replica))
        self._bg_tasks.add(t)
        t.add_done_callback(self._bg_tasks.discard)

    async def _forward_one(self, st: _StubState, req: QueuedRequest,
                           prefer: list[str], replica: str) -> None:
        stub_id = st.stub.stub_id
        pending: _Pending = req.item
        t0 = time.monotonic()
        try:
            result = await pending.forward(prefer)
        except Exception as exc:        # noqa: BLE001 — forward failures
            # surface as a 502 result, never a lost future
            log.warning("router forward failed for %s: %s", stub_id, exc)
            result = ForwardResult(
                status=502,
                body=json.dumps({"error": type(exc).__name__}).encode())
        finally:
            if replica:
                self.budgets.release(replica)
            else:
                st.cold_inflight = max(0, st.cold_inflight - 1)
                self.budgets.notify()   # wake dispatchers at the cold cap
        elapsed = time.monotonic() - t0
        if result.status < 500:
            self.admission.observe_service(stub_id, elapsed)
            # record where the prefix ACTUALLY landed (the buffer may have
            # fallen past the preferred replica to win a token)
            if result.container_id:
                self.affinity.record_served(pending.body,
                                            result.container_id)
        self.signals.ttft(stub_id, time.monotonic() - req.enqueued_at)
        self.signals.affinity_sample(self.affinity.stats())
        if req.future is not None and not req.future.done():
            req.future.set_result(result)
