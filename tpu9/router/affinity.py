"""KV-affinity replica selection: block-boundary prefix keys + JSQ fallback.

The engine's :class:`tpu9.serving.paged_kv.PrefixCache` caches KV for
FULL, block-aligned prompt prefixes, keyed by a hash of the token prefix
(``PrefixCache._key``). A fleet router that wants its placement to turn
into engine-level cache hits must therefore key on the SAME boundaries:
hashing the whole prompt (or a fixed byte prefix, like the per-instance
``LlmRouter``) makes "shares a 2-block system prompt" and "identical
request" look different, and the replica that holds the prefix is never
found. λScale (arxiv 2502.09922) calls this locality-aware dispatch; the
reference's pod/llm.go:211 approximates it with byte-prefix hashes.

Routing walks the prompt's block-aligned prefix keys from LONGEST to
shortest — the first key any replica has served is the best possible KV
reuse — then falls back to join-shortest-queue over replica load
snapshots when there is no affinity hit or the target is saturated or
draining. The table is process-local (the gateway is the single front
door for its fleet) with TTL'd entries, so a replaced replica ages out
instead of attracting traffic forever.
"""

from __future__ import annotations

import hashlib
import json
import time
from typing import Callable, Optional

# longest prefix worth keying, in blocks: bounds per-request hash work and
# table growth on pathological prompts (64 blocks × 16 tok = 1k tokens of
# prefix discrimination, far past where decode cost dominates prefill reuse)
MAX_KEY_BLOCKS = 64


def extract_prompt_tokens(body: bytes) -> Optional[list[int]]:
    """Token list from a generate-request body (the llm runner's wire
    shape), or None for non-token payloads."""
    try:
        payload = json.loads(body)
    except (ValueError, TypeError):
        return None
    if not isinstance(payload, dict):
        return None
    tokens = payload.get("tokens") or payload.get("prompt_tokens")
    if isinstance(tokens, list) and tokens and \
            all(isinstance(t, (int, float)) for t in tokens):
        return [int(t) for t in tokens]
    return None


def block_keys(body: bytes, block_tokens: int) -> list[bytes]:
    """Block-aligned prefix keys for a request body, longest first.

    Token bodies use the engine's exact keying. Text payloads (prompt /
    messages / raw bytes) approximate a block as ``4 × block_tokens``
    characters — byte-prefix blocks keep the longest-first walk semantics
    even when the gateway never sees token ids.
    """
    bs = max(block_tokens, 1)
    tokens = extract_prompt_tokens(body)
    if tokens is not None:
        # EXACTLY PrefixCache._key at each block boundary — the router's
        # table key and the engine's cache key must agree or affinity
        # placement and actual KV reuse silently diverge. One incremental
        # pass: the joined bytes for prefix k are a prefix of those for
        # k+1, so a running hash + copy() per boundary is O(n), not the
        # O(n²) of hashing every prefix from scratch (this runs 2-3 times
        # per routed request on the gateway's single thread).
        # Strict prefix, like PrefixCache.lookup: at least one token must
        # remain to prefill.
        nb = min((len(tokens) - 1) // bs, MAX_KEY_BLOCKS)
        h = hashlib.sha1()
        keys = []
        for n in range(1, nb + 1):
            if n > 1:
                h.update(b",")
            h.update(b",".join(str(t).encode()
                               for t in tokens[(n - 1) * bs: n * bs]))
            keys.append(h.copy().digest())
        return keys[::-1]
    raw = body
    try:
        payload = json.loads(body)
        if isinstance(payload, dict):
            for key in ("prompt", "messages", "input", "text"):
                if key in payload:
                    raw = json.dumps(payload[key]).encode()
                    break
    except (ValueError, TypeError):
        pass
    char_block = bs * 4
    nb = min(len(raw) // char_block, MAX_KEY_BLOCKS)
    h = hashlib.sha1()
    keys = []
    for n in range(1, nb + 1):
        h.update(raw[(n - 1) * char_block: n * char_block])
        keys.append(h.copy().digest())
    return keys[::-1]


class AffinityRouter:
    """Block-prefix → replica table with TTL and load-aware fallback."""

    def __init__(self, block_tokens: int = 16, ttl_s: float = 300.0,
                 max_entries: int = 65536,
                 clock: Callable[[], float] = time.monotonic):
        self.block_tokens = block_tokens
        self.ttl_s = ttl_s
        self.max_entries = max_entries
        self._clock = clock
        # key -> (container_id, expires_at)
        self._table: dict[bytes, tuple[str, float]] = {}
        self.hits = 0
        self.misses = 0

    # -- table ----------------------------------------------------------------

    def _lookup(self, key: bytes) -> str:
        entry = self._table.get(key)
        if entry is None:
            return ""
        cid, expires = entry
        if self._clock() > expires:
            del self._table[key]
            return ""
        return cid

    def record_served(self, body: bytes, container_id: str) -> None:
        """Register every block prefix of the served prompt: a future
        request sharing only the system-prompt blocks still finds the
        replica through its shorter keys."""
        expires = self._clock() + self.ttl_s
        for key in block_keys(body, self.block_tokens):
            self._table[key] = (container_id, expires)
        if len(self._table) > self.max_entries:
            self._prune()

    def forget_replica(self, container_id: str) -> None:
        """Drop a drained/stopped replica's entries so its traffic
        re-homes immediately instead of waiting out the TTL."""
        self._table = {k: v for k, v in self._table.items()
                       if v[0] != container_id}

    def _prune(self) -> None:
        now = self._clock()
        self._table = {k: v for k, v in self._table.items() if v[1] >= now}
        if len(self._table) > self.max_entries:
            # still over (hot table): drop the soonest-expiring half
            keep = sorted(self._table.items(), key=lambda kv: -kv[1][1])
            self._table = dict(keep[: self.max_entries // 2])

    # -- selection -------------------------------------------------------------

    def target(self, body: bytes, live: set[str]) -> str:
        """Longest-prefix affinity target among ``live`` replicas, or ""."""
        for key in block_keys(body, self.block_tokens):
            cid = self._lookup(key)
            if cid and cid in live:
                return cid
        return ""

    def order(self, body: bytes, replicas: list[str],
              load: dict[str, float],
              saturated: Optional[set[str]] = None) -> list[str]:
        """Preference order: affinity target first (unless saturated),
        then join-shortest-queue by the caller's load snapshot. Saturated
        replicas keep their JSQ order at the tail — admission budgets are
        the hard gate; ordering only expresses preference."""
        saturated = saturated or set()
        target = self.target(body, set(replicas))
        if target:
            if target not in saturated:
                self.hits += 1
                rest = [r for r in replicas if r != target]
                rest.sort(key=lambda r: (r in saturated,
                                         load.get(r, 0.0), r))
                return [target] + rest
            # affinity hit on a saturated replica counts as a miss for the
            # hit-rate signal: the KV reuse did NOT happen
        self.misses += 1
        out = list(replicas)
        out.sort(key=lambda r: (r in saturated, load.get(r, 0.0), r))
        return out

    def stats(self) -> dict:
        total = self.hits + self.misses
        return {"entries": len(self._table), "hits": self.hits,
                "misses": self.misses,
                "hit_rate": self.hits / total if total else 0.0}
