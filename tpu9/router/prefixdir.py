"""Fleet-global prefix directory (ISSUE 20): who can serve which KV
prefix, from which tier, at what cost.

The affinity table (:mod:`tpu9.router.affinity`) is a TTL'd *guess* —
"this replica served this prefix recently, its cache probably still has
it". The directory is *evidence*: each replica's pressure heartbeat
carries a bounded top-K summary of the prefix keys it actually holds
(``kvtier_keys``, with the serving tier per key), an eviction delta
(``kvtier_evicted`` — retractions for entries destroyed since the last
accepted beat, closing the silent prefix-loss window), and the peer-cache
publications it made (``kvtier_peer`` — digests that survive the replica
itself). Placement then prefers the replica that can serve the LONGEST
prefix from the CHEAPEST tier (device < host < peer), and when only the
peer cache holds a prefix the router hands the chosen replica an
``adopt_kv`` hint so it pulls the tier instead of recomputing.

Staleness semantics: summaries are snapshots — a key absent from a
replica's latest summary drops that replica's claim (reconciliation),
an eviction delta drops it immediately, and claims older than ``ttl_s``
expire. The directory can still be briefly wrong (an eviction in the
beat gap); consumers must treat every hit as a HINT — the engine
degrades a lost prefix to recompute, never an error, and the regression
test pins that.

Key digests are the first 16 hex chars of the engine's
``PrefixCache._key`` sha1 — long enough that collisions are noise-level
for fleet-sized key sets, short enough that a 48-entry summary rides a
heartbeat in ~1.3 KB.
"""

from __future__ import annotations

import time
from typing import Optional

from .affinity import block_keys

# tier cost order: serving from device HBM is free, host DRAM pays one
# up-page, the peer cache pays a transport round-trip + splice
TIER_COST = {"d": 0, "h": 1, "p": 2}
MAX_CLAIMS = 4096          # directory-wide key bound (LRU-ish trim)


class PrefixDirectory:
    def __init__(self, block_tokens: int = 16, ttl_s: float = 30.0,
                 peer_ttl_s: float = 600.0):
        self.block_tokens = max(int(block_tokens), 1)
        self.ttl_s = float(ttl_s)
        self.peer_ttl_s = float(peer_ttl_s)
        # key_hex16 -> {container_id: (tier, n_tokens, seen_mono)}
        self._claims: dict[str, dict[str, tuple[str, int, float]]] = {}
        # peer residency outlives replicas: key_hex16 -> (digest,
        # n_tokens, seen_mono). Deliberately NOT dropped by
        # forget_replica — surviving replica death is the point.
        self._peer: dict[str, tuple[str, int, float]] = {}
        self.hits = 0
        self.misses = 0
        self.retractions = 0

    # -- heartbeat fold ------------------------------------------------------

    def observe_replica(self, container_id: str, stats: dict) -> None:
        """Fold one replica's heartbeat snapshot. Reconciliation: the
        summary is authoritative for this replica — claims it no longer
        advertises are dropped, then the eviction delta retracts
        anything destroyed since the summary was built."""
        now = time.monotonic()
        raw = str(stats.get("kvtier_keys", "") or "")
        if raw or "kvtier_evicted" in stats or "kvtier_peer" in stats:
            seen: dict[str, tuple[str, int]] = {}
            for item in raw.split(","):
                parts = item.split(":")
                if len(parts) != 3 or not parts[0]:
                    continue
                try:
                    seen[parts[0]] = (parts[1], int(parts[2]))
                except ValueError:
                    continue
            for hx in list(self._claims):
                claims = self._claims[hx]
                if container_id in claims and hx not in seen:
                    del claims[container_id]
                    if not claims:
                        del self._claims[hx]
            for hx, (tier, n_tok) in seen.items():
                self._claims.setdefault(hx, {})[container_id] = \
                    (tier, n_tok, now)
            for hx in str(stats.get("kvtier_evicted", "") or "").split(","):
                if not hx:
                    continue
                claims = self._claims.get(hx)
                if claims and container_id in claims:
                    del claims[container_id]
                    self.retractions += 1
                    if not claims:
                        del self._claims[hx]
            for item in str(stats.get("kvtier_peer", "") or "").split(","):
                parts = item.split(":")
                if len(parts) != 3 or not parts[0] or not parts[1]:
                    continue
                try:
                    self._peer[parts[0]] = (parts[1], int(parts[2]), now)
                except ValueError:
                    continue
        self._trim(now)

    def _trim(self, now: float) -> None:
        for hx in list(self._claims):
            claims = self._claims[hx]
            for cid in list(claims):
                if now - claims[cid][2] > self.ttl_s:
                    del claims[cid]
            if not claims:
                del self._claims[hx]
        if len(self._claims) > MAX_CLAIMS:
            # oldest-claim-first trim; rare (bounded per-replica top-K ×
            # fleet size normally stays far under the cap)
            by_age = sorted(
                self._claims,
                key=lambda h: max(s for _, _, s in
                                  self._claims[h].values()))
            for hx in by_age[:len(self._claims) - MAX_CLAIMS]:
                del self._claims[hx]
        for hx in list(self._peer):
            if now - self._peer[hx][2] > self.peer_ttl_s:
                del self._peer[hx]

    def forget_replica(self, container_id: str) -> None:
        """Replica died/drained: its residency claims are gone. Its peer
        publications SURVIVE — the peer cache holds them, not the
        replica."""
        for hx in list(self._claims):
            claims = self._claims[hx]
            if container_id in claims:
                del claims[container_id]
                if not claims:
                    del self._claims[hx]

    # -- lookup --------------------------------------------------------------

    def lookup(self, body: bytes, live: Optional[set] = None) -> dict:
        """One directory lookup for a request body: walk its block-
        aligned prefix keys longest-first; the first key with any
        residency wins. Returns ``{}`` on a miss, else a dict with
        ``key``/``n_tokens`` plus either ``cid``+``tier`` (a live
        replica serves it; cheapest tier among claimants) or
        ``peer_digest`` (only the peer cache holds it — the router
        injects an adopt hint). ``live`` restricts claims to currently
        routable replicas."""
        now = time.monotonic()
        for kb in block_keys(body, self.block_tokens):
            hx = kb.hex()[:16]
            claims = self._claims.get(hx)
            if claims:
                ranked = sorted(
                    (TIER_COST.get(tier, 3), cid, tier, n_tok)
                    for cid, (tier, n_tok, seen) in claims.items()
                    if now - seen <= self.ttl_s
                    and (live is None or cid in live))
                if ranked:
                    cost, cid, tier, n_tok = ranked[0]
                    self.hits += 1
                    return {"key": hx, "cid": cid, "tier": tier,
                            "n_tokens": n_tok}
            peer = self._peer.get(hx)
            if peer is not None and now - peer[2] <= self.peer_ttl_s:
                self.hits += 1
                return {"key": hx, "peer_digest": peer[0],
                        "n_tokens": peer[1]}
        self.misses += 1
        return {}

    def stats(self) -> dict:
        return {"keys": len(self._claims), "peer_keys": len(self._peer),
                "hits": self.hits, "misses": self.misses,
                "retractions": self.retractions}
