"""Per-tenant weighted fair queuing: deficit round-robin over token cost.

One tenant's burst must not starve everyone else's TTFT. Classic DRR
(Shreedhar & Varghese) over TOKEN cost, not request count: an LLM request
is as heavy as the tokens it prefills + decodes, and counting requests
would let one tenant's 8k-token prompts crowd out another's chat turns at
"fair" request parity. Each tenant owns a FIFO lane; a round-robin ring
visits lanes, tops up a deficit by ``quantum × weight``, and serves while
the head's cost fits. Weights come from workspace concurrency quotas (a
tenant paying for 8 chips gets proportionally more of the front door than
the free tier — ``tpu9/scheduler/quota.py`` is the source of truth).

The queue is strictly in-process and lock-free under asyncio: ``get``
suspends on an event when empty, ``put`` never blocks (admission control
decides whether a request may enqueue at all — see admission.py).
"""

from __future__ import annotations

import asyncio
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Optional


@dataclass
class QueuedRequest:
    tenant: str
    cost: int                     # estimated tokens (prefill + decode)
    item: Any = None              # caller payload
    enqueued_at: float = field(default_factory=time.monotonic)
    deadline: float = 0.0         # monotonic queue-wait deadline (0 = none)
    future: Optional[asyncio.Future] = None


class _Lane:
    __slots__ = ("queue", "deficit", "weight", "fresh", "ringed")

    def __init__(self, weight: float):
        self.queue: deque[QueuedRequest] = deque()
        self.deficit = 0.0
        self.weight = weight
        self.fresh = True          # gets a quantum top-up on next visit
        self.ringed = False        # present in the round-robin ring


class TenantFairQueue:
    def __init__(self, quantum_tokens: int = 2048):
        self.quantum = max(int(quantum_tokens), 1)
        self._lanes: dict[str, _Lane] = {}
        self._ring: deque[str] = deque()
        self._nonempty = asyncio.Event()
        self._depth = 0

    @property
    def depth(self) -> int:
        return self._depth

    def put(self, req: QueuedRequest, weight: float = 1.0) -> None:
        lane = self._lanes.get(req.tenant)
        if lane is None:
            lane = _Lane(max(weight, 0.01))
            self._lanes[req.tenant] = lane
        else:
            lane.weight = max(weight, 0.01)   # quota changes apply live
        if not lane.ringed:
            # the ringed flag, not queue emptiness, gates the append: a
            # drop_completed() purge can empty a lane that is still in
            # the ring, and a double entry would double the tenant's
            # quantum per rotation
            self._ring.append(req.tenant)
            lane.ringed = True
            lane.fresh = True
        lane.queue.append(req)
        self._depth += 1
        self._nonempty.set()

    async def get(self) -> QueuedRequest:
        """Next request in DRR order; suspends while empty."""
        while True:
            req = self.pop()
            if req is not None:
                return req
            self._nonempty.clear()
            await self._nonempty.wait()

    def pop(self) -> Optional[QueuedRequest]:
        """Non-blocking DRR pop (None when empty). The ring visit rotates
        a lane to the back once its deficit can't cover its head — a heavy
        tenant banks no more than one quantum of credit per visit while
        light tenants get served every round."""
        while self._ring:
            tenant = self._ring[0]
            lane = self._lanes.get(tenant)
            if lane is None or not lane.queue:
                # drained lane: drop from the ring; deficit resets so idle
                # tenants can't bank credit for a later burst
                self._ring.popleft()
                if lane is not None:
                    lane.deficit = 0.0
                    lane.ringed = False
                continue
            if len(self._ring) == 1:
                # sole tenant: fairness is moot, and looping one quantum
                # per rotation until the deficit covers a huge head would
                # spin the single-threaded gateway ~cost/quantum sync
                # iterations — serve directly
                lane.deficit = 0.0
                lane.fresh = True
                head = lane.queue.popleft()
                self._depth -= 1
                if not lane.queue:
                    self._ring.popleft()
                    lane.ringed = False
                return head
            if lane.fresh:
                lane.deficit += self.quantum * lane.weight
                lane.fresh = False
            head = lane.queue[0]
            if head.cost <= lane.deficit:
                lane.queue.popleft()
                lane.deficit -= head.cost
                self._depth -= 1
                if not lane.queue:
                    self._ring.popleft()
                    lane.deficit = 0.0
                    lane.ringed = False
                return head
            # deficit exhausted: next tenant's turn (classic DRR carries
            # the remainder so an over-quantum request eventually goes)
            self._ring.rotate(-1)
            lane.fresh = True
        return None

    def drop_completed(self) -> int:
        """Purge requests whose future already resolved (caller timeout /
        disconnect) so they don't burn dispatch turns. Returns count."""
        dropped = 0
        for lane in self._lanes.values():
            alive = deque(r for r in lane.queue
                          if r.future is None or not r.future.done())
            dropped += len(lane.queue) - len(alive)
            lane.queue = alive
        self._depth -= dropped
        return dropped


# client-supplied max_new_tokens is CLAMPED: a forged 10**12 would make
# the DRR deficit loop spin ~cost/quantum synchronous iterations — a
# one-request event-loop DoS. No real decode budget approaches this.
MAX_COST_TOKENS = 1_000_000


def estimate_cost(body: bytes, default_decode: int = 64) -> int:
    """Token cost of a request for DRR accounting: prompt tokens (or a
    bytes/4 proxy for text payloads) plus the requested decode budget.
    Cheap and deliberately rough — fairness needs relative weight, not
    billing-grade accuracy."""
    import json
    prompt_tokens = 0
    decode = default_decode
    try:
        payload = json.loads(body)
        if isinstance(payload, dict):
            toks = payload.get("tokens") or payload.get("prompt_tokens")
            if isinstance(toks, list):
                prompt_tokens = len(toks)
            else:
                for key in ("prompt", "messages", "input", "text"):
                    if key in payload:
                        prompt_tokens = len(json.dumps(payload[key])) // 4
                        break
            decode = int(payload.get("max_new_tokens", default_decode))
    except (ValueError, TypeError):
        prompt_tokens = len(body) // 4
    return min(max(1, prompt_tokens + max(decode, 0)), MAX_COST_TOKENS)
