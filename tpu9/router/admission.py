"""Admission control and load shedding for the fleet router.

Three mechanisms, all cheap enough for the per-request hot path:

- **Per-replica in-flight budgets** derived from the engine's OWN
  admission headroom: the paged engine reserves worst-case KV blocks per
  request (``paged_kv.BlockAllocator``), runners heartbeat
  ``kv_blocks_free``/``kv_block_size`` through ``/rpc/llm/pressure``, and
  the budget is "how many worst-case requests still fit", clamped to a
  configured ceiling. Replicas that report nothing (plain endpoints,
  engines mid-bring-up) get the configured default. Admitting past this
  budget would only move the queue INSIDE the replica where fairness and
  deadlines can no longer see it — DeepServe's (arxiv 2501.14417) core
  argument for fleet-level admission.

- **Queue-wait deadlines**: a request that waited longer than the SLO
  budget is dead weight — serving it wastes chip time on a response the
  client already abandoned. Shed with 503 + Retry-After.

- **Shedding with honest backpressure**: when the queue is past its
  depth cap, reject NEW work at the door with 429 + Retry-After derived
  from observed service rate, instead of accepting it into a queue whose
  wait already blows the deadline.

Graceful drain: a replica being scaled down is marked draining — routing
skips it, its in-flight requests complete, and the caller (the instance
reconciler) waits for the in-flight count to hit zero before stopping
the container.
"""

from __future__ import annotations

import asyncio
import time
from typing import Optional


class ReplicaBudgets:
    """In-flight slots per replica, sized from engine KV headroom."""

    def __init__(self, default_inflight: int = 8,
                 kv_tokens_per_request: int = 2048,
                 max_inflight: int = 64):
        self.default_inflight = max(default_inflight, 1)
        self.kv_tokens_per_request = max(kv_tokens_per_request, 1)
        self.max_inflight = max(max_inflight, 1)
        self._inflight: dict[str, int] = {}
        self._released = asyncio.Event()

    def budget_from_stats(self, stats: Optional[dict]) -> int:
        """Worst-case requests the replica's free KV pool still admits."""
        if not stats:
            return self.default_inflight
        try:
            free_blocks = float(stats.get("kv_blocks_free", -1))
            block_s = float(stats.get("kv_block_size", 0))
        except (TypeError, ValueError):
            return self.default_inflight
        if free_blocks < 0 or block_s <= 0:
            return self.default_inflight
        # requests already running hold their reservations, so the free
        # pool admits headroom/worst_case MORE on top of them: budget =
        # what's running + what still fits (floor 1 so a full replica
        # isn't deadlocked out of the rotation forever)
        headroom = int(free_blocks * block_s // self.kv_tokens_per_request)
        return min(self.max_inflight,
                   max(1, self._inflight_floor(stats) + headroom))

    @staticmethod
    def _inflight_floor(stats: dict) -> int:
        try:
            return int(float(stats.get("active_streams", 0)))
        except (TypeError, ValueError):
            return 0

    def inflight(self, container_id: str) -> int:
        return self._inflight.get(container_id, 0)

    def try_acquire(self, container_id: str, budget: int) -> bool:
        cur = self._inflight.get(container_id, 0)
        if cur >= max(budget, 1):
            return False
        self._inflight[container_id] = cur + 1
        return True

    def release(self, container_id: str) -> None:
        cur = self._inflight.get(container_id, 0)
        if cur <= 1:
            self._inflight.pop(container_id, None)
        else:
            self._inflight[container_id] = cur - 1
        # wake every waiter; they re-check budgets (event, not condition:
        # waiters span stubs and a spurious wake only costs one re-check)
        self._released.set()

    def notify(self) -> None:
        """Wake budget waiters for capacity freed OUTSIDE the per-replica
        accounting (the router's cold-start passthrough slots) — without
        this, dispatchers blocked at the cold cap only notice a freed
        slot at the 250 ms fallback poll."""
        self._released.set()

    async def wait_release(self, timeout: float) -> None:
        # NOT wait_for: py3.10's wait_for swallows a cancellation that
        # races the inner future's completion (the exact Dispatcher
        # ._exit_loop hang PR 1 diagnosed) — a dispatcher cancelled while
        # a release fires would keep looping uncancelled and hang stop().
        # asyncio.wait never consumes the CancelledError.
        self._released.clear()
        waiter = asyncio.ensure_future(self._released.wait())
        try:
            await asyncio.wait({waiter}, timeout=timeout)
        finally:
            if not waiter.done():
                waiter.cancel()
                try:
                    await waiter
                except asyncio.CancelledError:  # tpu9: noqa[ASY003] the waiter's own cancel, deliberately absorbed; an in-flight cancellation of THIS task resumes propagating after the finally
                    pass


class AdmissionController:
    def __init__(self, budgets: ReplicaBudgets,
                 max_queue_depth: int = 256,
                 max_queue_wait_s: float = 30.0,
                 shed_retry_after_s: float = 1.0):
        self.budgets = budgets
        self.max_queue_depth = max(max_queue_depth, 1)
        self.max_queue_wait_s = max_queue_wait_s
        self.shed_retry_after_s = shed_retry_after_s
        # container_id -> drain mark expiry (bounded even if a stop never
        # lands: the mark ages out with the container TTL)
        self._draining: dict[str, float] = {}
        # container_id -> stalled mark expiry (gray-failure ejection,
        # ISSUE 14): kept separate from draining so health recovery can
        # clear it without cancelling a genuine scale-down drain
        self._stalled: dict[str, float] = {}
        # EWMA of request service seconds, per stub — feeds Retry-After
        self._service_ewma: dict[str, float] = {}

    # -- shedding --------------------------------------------------------------

    def should_shed(self, queue_depth: int) -> bool:
        return queue_depth >= self.max_queue_depth

    def retry_after_s(self, stub_id: str, queue_depth: int,
                      replicas: int) -> float:
        """Honest Retry-After: the time for the current queue to drain at
        the observed per-replica service rate. Clients that honor it come
        back when there is actually room, instead of hammering a shedding
        gateway into a retry storm."""
        svc = self._service_ewma.get(stub_id, 0.0)
        if svc <= 0 or replicas <= 0:
            return self.shed_retry_after_s
        est = queue_depth * svc / replicas
        return min(max(est, self.shed_retry_after_s), 30.0)

    def observe_service(self, stub_id: str, seconds: float) -> None:
        prev = self._service_ewma.get(stub_id, 0.0)
        self._service_ewma[stub_id] = seconds if prev <= 0 \
            else prev * 0.8 + seconds * 0.2

    def expired(self, enqueued_at: float, deadline: float = 0.0) -> bool:
        limit = deadline or (enqueued_at + self.max_queue_wait_s)
        return time.monotonic() > limit

    # -- draining --------------------------------------------------------------

    def mark_draining(self, container_id: str, ttl_s: float = 120.0) -> None:
        self._draining[container_id] = time.monotonic() + ttl_s

    def is_draining(self, container_id: str) -> bool:
        expiry = self._draining.get(container_id)
        if expiry is None:
            return False
        if time.monotonic() > expiry:
            del self._draining[container_id]
            return False
        return True

    # -- gray-failure ejection (ISSUE 14) --------------------------------------
    # A replica whose heartbeat reports health == "stalled" is ejected
    # from routing exactly like a draining one — but on its OWN ledger:
    # clearing it on recovery must never cancel a genuine scale-down
    # drain mark. The TTL doubles as the recovery probe: when no fresh
    # heartbeat clears OR renews the mark (e.g. bench driving the router
    # without the gateway's observer), expiry puts the replica back in
    # the candidate set and the next dispatch pass re-reads its stats.

    def mark_stalled(self, container_id: str, ttl_s: float = 6.0) -> None:
        self._stalled[container_id] = time.monotonic() + ttl_s

    def clear_stalled(self, container_id: str) -> None:
        self._stalled.pop(container_id, None)

    def is_stalled(self, container_id: str) -> bool:
        expiry = self._stalled.get(container_id)
        if expiry is None:
            return False
        if time.monotonic() > expiry:
            del self._stalled[container_id]
            return False
        return True

    async def wait_drained(self, container_id: str,
                           timeout: float = 10.0) -> bool:
        """True once the replica's in-flight count reaches zero (its
        requests completed); False if the timeout elapsed first — the
        caller stops the container anyway, in-flight requests get 502s
        like any container death and the gateway failover's retry
        semantics apply. Event-driven on budget releases; the lost-
        wakeup fallback poll ramps 20→250 ms via the shared backoff
        helper (ISSUE 15 satellite) instead of a fixed 250 ms spin."""
        from ..utils.backoff import BackoffPolicy
        deadline = time.monotonic() + timeout
        delays = BackoffPolicy(base_s=0.02, factor=2.0, max_s=0.25,
                               jitter=0.0).delays()
        while time.monotonic() < deadline:
            if self.budgets.inflight(container_id) == 0:
                return True
            await self.budgets.wait_release(
                min(next(delays),
                    max(deadline - time.monotonic(), 0.01)))
        return self.budgets.inflight(container_id) == 0
