"""Fleet inference router (ISSUE 2): the fleet-level front door between
the gateway's invoke paths and engine replicas — KV-affinity routing,
per-tenant weighted fair queuing, SLO-aware admission/shedding, and a
signals bus feeding the metrics registry + autoscaler.
"""

from .admission import AdmissionController, ReplicaBudgets
from .affinity import AffinityRouter, block_keys, extract_prompt_tokens
from .fairness import QueuedRequest, TenantFairQueue, estimate_cost
from .fleet import FleetRouter
from .signals import RouterSignals

__all__ = [
    "AdmissionController", "AffinityRouter", "FleetRouter",
    "QueuedRequest", "ReplicaBudgets", "RouterSignals",
    "TenantFairQueue", "block_keys", "estimate_cost",
    "extract_prompt_tokens",
]
