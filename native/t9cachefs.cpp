// t9cachefs — read-through FUSE view of content-addressed manifests,
// speaking the kernel FUSE protocol directly (no libfuse dependency).
//
// Reference analogue: the embedded cache's FUSE CacheFS
// (pkg/cache/cachefs.go:47, cachefs_node.go) and the CLIP lazy image
// mount (pkg/worker/image.go:274). Those cover the readers tpu9's
// LD_PRELOAD shims cannot: static binaries, direct syscalls, and mmap —
// a page fault through this mount reads exactly the chunks it needs.
//
// Layout: the mounted tree is described by a chunk manifest (the same
// JSON format images/volumes/snapshots already use — images/manifest.py):
// every regular file is a sequence of sha256 chunks. Reads resolve chunks
// against a local STORE directory (the worker cache's DiskStore layout,
// <store>/<aa>/<hash>); a missing chunk triggers one round-trip on the
// worker's fault socket ("CHUNK <digest>\n" → "OK\n" once the store has
// it) — so cold pages stream from cache peers on demand.
//
// Invocation (trusted worker only):
//   t9cachefs --manifest m.json --store DIR --mount MNT [--sock PATH]
//             [--foreground]
//
// The mount uses allow_other + default_permissions so dropped-uid tenant
// containers can read through bind mounts of MNT while the kernel
// enforces the manifest's file modes.

#include <cerrno>
#include <cstdint>
#include <dirent.h>
#include <thread>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <map>
#include <string>
#include <vector>

#include <fcntl.h>
#include <linux/fuse.h>
#include <sys/mount.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <unistd.h>

#include <sys/socket.h>
#include <sys/un.h>

namespace {

[[noreturn]] void die(const char* what) {
  fprintf(stderr, "t9cachefs: %s: %s\n", what, strerror(errno));
  exit(111);
}

// ---- manifest model -------------------------------------------------------

struct Node {
  uint64_t ino = 0;
  std::string name;
  bool is_dir = false;
  uint32_t mode = 0644;
  uint64_t size = 0;
  std::string link_target;               // symlink when non-empty
  std::vector<std::string> chunks;       // regular files
  uint32_t chunk_bytes = 4 * 1024 * 1024;
  std::map<std::string, uint64_t> children;   // name -> ino (dirs)
};

std::vector<Node> g_nodes;               // index == ino (0 unused)
std::string g_store;
std::string g_sock;

Node& node(uint64_t ino) { return g_nodes[ino]; }

uint64_t new_node() {
  g_nodes.emplace_back();
  g_nodes.back().ino = g_nodes.size() - 1;
  return g_nodes.size() - 1;
}

uint64_t ensure_dir(uint64_t parent, const std::string& name) {
  auto it = node(parent).children.find(name);
  if (it != node(parent).children.end()) return it->second;
  uint64_t ino = new_node();
  node(ino).name = name;
  node(ino).is_dir = true;
  node(ino).mode = 0755;
  node(parent).children[name] = ino;
  return ino;
}

// ---- tiny JSON scanning (same trusted-input stance as t9proc) -------------

std::string read_file(const std::string& path) {
  FILE* f = fopen(path.c_str(), "rb");
  if (!f) die("open manifest");
  std::string out;
  char buf[65536];
  size_t n;
  while ((n = fread(buf, 1, sizeof buf, f)) > 0) out.append(buf, n);
  fclose(f);
  return out;
}

// decode a JSON string starting at the opening quote; advances i past the
// closing quote. Handles \", \\, \/, \n, \t, \r and \uXXXX (→ UTF-8).
std::string scan_string(const std::string& s, size_t& i) {
  std::string out;
  ++i;                                   // past opening quote
  while (i < s.size() && s[i] != '"') {
    char c = s[i];
    if (c == '\\' && i + 1 < s.size()) {
      char n = s[++i];
      switch (n) {
        case 'n': out += '\n'; break;
        case 't': out += '\t'; break;
        case 'r': out += '\r'; break;
        case 'b': out += '\b'; break;
        case 'f': out += '\f'; break;
        case 'u': {
          if (i + 4 < s.size()) {
            unsigned cp = static_cast<unsigned>(
                strtoul(s.substr(i + 1, 4).c_str(), nullptr, 16));
            i += 4;
            if (cp < 0x80) {
              out += static_cast<char>(cp);
            } else if (cp < 0x800) {
              out += static_cast<char>(0xC0 | (cp >> 6));
              out += static_cast<char>(0x80 | (cp & 0x3F));
            } else {
              out += static_cast<char>(0xE0 | (cp >> 12));
              out += static_cast<char>(0x80 | ((cp >> 6) & 0x3F));
              out += static_cast<char>(0x80 | (cp & 0x3F));
            }
          }
          break;
        }
        default: out += n;
      }
    } else {
      out += c;
    }
    ++i;
  }
  ++i;                                   // past closing quote
  return out;
}

void skip_ws(const std::string& s, size_t& i) {
  while (i < s.size() && (s[i] == ' ' || s[i] == '\n' || s[i] == '\t'
                          || s[i] == '\r' || s[i] == ','))
    ++i;
}

// parse ONE file object at s[i] (pointing at '{'): a real key-by-key scan
// — find()-based extraction would let a crafted filename containing
// escaped quotes shadow keys like "chunks" (content-injection risk) and
// brace/escape content would desync the object boundaries
void parse_file_object(const std::string& s, size_t& i, uint64_t root,
                       uint32_t chunk_bytes) {
  ++i;                                   // past '{'
  std::string rel, link;
  uint32_t mode = 0644;
  uint64_t size = 0;
  std::vector<std::string> chunks;
  for (;;) {
    skip_ws(s, i);
    if (i >= s.size() || s[i] == '}') {
      ++i;
      break;
    }
    std::string key = scan_string(s, i);
    skip_ws(s, i);
    if (i < s.size() && s[i] == ':') ++i;
    skip_ws(s, i);
    if (s[i] == '"') {
      std::string val = scan_string(s, i);
      if (key == "path") rel = val;
      else if (key == "link_target") link = val;
    } else if (s[i] == '[') {
      ++i;
      for (;;) {
        skip_ws(s, i);
        if (i >= s.size() || s[i] == ']') {
          ++i;
          break;
        }
        if (s[i] == '"') {
          std::string item = scan_string(s, i);
          if (key == "chunks") chunks.push_back(item);
        } else {
          ++i;
        }
      }
    } else {                             // number / literal
      size_t start = i;
      while (i < s.size() && s[i] != ',' && s[i] != '}') ++i;
      long v = strtol(s.c_str() + start, nullptr, 10);
      if (key == "mode") mode = static_cast<uint32_t>(v);
      else if (key == "size") size = static_cast<uint64_t>(v);
    }
  }
  if (rel.empty()) return;
  uint64_t parent = root;
  size_t start = 0, slash;
  while ((slash = rel.find('/', start)) != std::string::npos) {
    parent = ensure_dir(parent, rel.substr(start, slash - start));
    start = slash + 1;
  }
  std::string name = rel.substr(start);
  uint64_t ino = new_node();
  Node& nd = node(ino);
  nd.name = name;
  nd.mode = mode;
  nd.size = size;
  nd.link_target = link;
  nd.chunks = std::move(chunks);
  nd.chunk_bytes = chunk_bytes;
  node(parent).children[name] = ino;
}

void load_manifest(const std::string& path) {
  std::string blob = read_file(path);
  new_node();                            // ino 0 unused
  uint64_t root = new_node();            // ino 1 = root
  node(root).is_dir = true;
  node(root).mode = 0755;

  // walk the TOP-LEVEL object properly (string-aware, depth-tracked) to
  // find the real "files" key and "chunk_bytes" — a tenant env value that
  // happens to contain '"files"' must not derail the parse
  uint32_t chunk_bytes = 4 * 1024 * 1024;
  size_t files_at = std::string::npos;
  size_t i = blob.find('{');
  if (i == std::string::npos) die("manifest is not JSON");
  ++i;
  int depth = 1;
  while (i < blob.size() && depth >= 1) {
    skip_ws(blob, i);
    if (i >= blob.size()) break;
    char c = blob[i];
    if (c == '}') { depth--; ++i; continue; }
    if (c == '{') { depth++; ++i; continue; }
    if (c == '[') { ++i; continue; }
    if (c == ']') { ++i; continue; }
    if (c == '"') {
      std::string str = scan_string(blob, i);
      skip_ws(blob, i);
      bool is_key = i < blob.size() && blob[i] == ':';
      if (!is_key) continue;
      ++i;                               // past ':'
      skip_ws(blob, i);
      if (depth == 1 && str == "chunk_bytes") {
        chunk_bytes = static_cast<uint32_t>(
            strtol(blob.c_str() + i, nullptr, 10));
      } else if (depth == 1 && str == "files" && blob[i] == '[') {
        files_at = i;
        break;
      }
      continue;
    }
    ++i;                                 // number/literal char
  }
  if (files_at == std::string::npos) die("manifest has no files array");

  i = files_at + 1;                      // past '['
  for (;;) {
    skip_ws(blob, i);
    if (i >= blob.size() || blob[i] == ']') break;
    if (blob[i] == '{') parse_file_object(blob, i, root, chunk_bytes);
    else ++i;
  }
}

// ---- chunk resolution -----------------------------------------------------

std::string chunk_path(const std::string& digest) {
  // DiskStore layout: <store>/<first2>/<digest>
  return g_store + "/" + digest.substr(0, 2) + "/" + digest;
}

bool fault_chunk(const std::string& digest) {
  if (g_sock.empty()) return false;
  int fd = socket(AF_UNIX, SOCK_STREAM | SOCK_CLOEXEC, 0);
  if (fd < 0) return false;
  // bounded round-trip: a hung fault server must surface as EIO to the
  // reader, never wedge the FUSE request (and with it the mount) forever
  struct timeval tv;
  tv.tv_sec = 30;
  tv.tv_usec = 0;
  setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
  setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
  struct sockaddr_un addr;
  memset(&addr, 0, sizeof(addr));
  addr.sun_family = AF_UNIX;
  strncpy(addr.sun_path, g_sock.c_str(), sizeof(addr.sun_path) - 1);
  bool ok = false;
  if (connect(fd, reinterpret_cast<struct sockaddr*>(&addr),
              sizeof(addr)) == 0) {
    std::string req = "CHUNK " + digest + "\n";
    if (write(fd, req.data(), req.size()) ==
        static_cast<ssize_t>(req.size())) {
      char buf[16];
      ssize_t n = read(fd, buf, sizeof(buf) - 1);
      ok = n >= 2 && strncmp(buf, "OK", 2) == 0;
    }
  }
  close(fd);
  return ok;
}

// read [off, off+want) of a manifest file into out; returns bytes or -errno
ssize_t read_node(const Node& nd, uint64_t off, uint32_t want, char* out) {
  if (off >= nd.size) return 0;
  if (off + want > nd.size) want = static_cast<uint32_t>(nd.size - off);
  uint32_t done = 0;
  while (done < want) {
    uint64_t pos = off + done;
    size_t ci = pos / nd.chunk_bytes;
    uint64_t coff = pos % nd.chunk_bytes;
    if (ci >= nd.chunks.size()) break;
    const std::string& digest = nd.chunks[ci];
    std::string path = chunk_path(digest);
    int fd = open(path.c_str(), O_RDONLY | O_CLOEXEC);
    if (fd < 0) {
      if (!fault_chunk(digest)) return -EIO;
      fd = open(path.c_str(), O_RDONLY | O_CLOEXEC);
      if (fd < 0) return -EIO;
    }
    ssize_t n = pread(fd, out + done, want - done,
                      static_cast<off_t>(coff));
    close(fd);
    if (n < 0) return -errno;
    if (n == 0) break;                   // short chunk (last one)
    done += static_cast<uint32_t>(n);
    if (coff + static_cast<uint64_t>(n) >= nd.chunk_bytes) continue;
    if (done < want && pos + static_cast<uint64_t>(n) < nd.size &&
        ci + 1 < nd.chunks.size()) {
      // short read inside a chunk that is not the last: the store file
      // is truncated/corrupt — better loud than zeros
      return -EIO;
    }
  }
  return done;
}

// ---- FUSE protocol --------------------------------------------------------

int g_fuse_fd = -1;

void fill_attr(const Node& nd, struct fuse_attr* a) {
  memset(a, 0, sizeof(*a));
  a->ino = nd.ino;
  a->size = nd.link_target.empty() ? nd.size : nd.link_target.size();
  a->blocks = (nd.size + 511) / 512;
  a->mode = nd.link_target.empty()
                ? ((nd.is_dir ? S_IFDIR : S_IFREG) | (nd.mode & 07777))
                : (S_IFLNK | 0777);
  a->nlink = 1;
  a->blksize = 4096;
}

void reply(uint64_t unique, int32_t error, const void* data, size_t n) {
  struct fuse_out_header h;
  h.len = static_cast<uint32_t>(sizeof(h) + n);
  h.error = error;
  h.unique = unique;
  struct iovec {
    const void* base;
    size_t len;
  };
  // writev without <sys/uio.h> struct mismatch: build one buffer
  std::string buf(reinterpret_cast<char*>(&h), sizeof(h));
  if (n) buf.append(reinterpret_cast<const char*>(data), n);
  if (write(g_fuse_fd, buf.data(), buf.size()) < 0 && errno != ENOENT) {
    // ENOENT = request interrupted; anything else is fatal for the mount
    if (errno != EINTR) die("fuse write");
  }
}

void reply_entry(uint64_t unique, const Node& nd) {
  struct fuse_entry_out e;
  memset(&e, 0, sizeof(e));
  e.nodeid = nd.ino;
  e.attr_valid = 3600;
  e.entry_valid = 3600;
  fill_attr(nd, &e.attr);
  reply(unique, 0, &e, sizeof(e));
}

void serve() {
  // must exceed the negotiated max_write by at least one page of header
  // space or the kernel rejects the read with EINVAL
  std::vector<char> buf((1 << 20) + 65536);
  for (;;) {
    ssize_t n = read(g_fuse_fd, buf.data(), buf.size());
    if (n < 0) {
      if (errno == EINTR || errno == EAGAIN) continue;
      if (errno == ENODEV) return;       // unmounted
      die("fuse read");
    }
    auto* in = reinterpret_cast<struct fuse_in_header*>(buf.data());
    char* arg = buf.data() + sizeof(*in);
    switch (in->opcode) {
      case FUSE_INIT: {
        auto* ii = reinterpret_cast<struct fuse_init_in*>(arg);
        struct fuse_init_out out;
        memset(&out, 0, sizeof(out));
        out.major = FUSE_KERNEL_VERSION;
        out.minor = FUSE_KERNEL_MINOR_VERSION < ii->minor
                        ? FUSE_KERNEL_MINOR_VERSION
                        : ii->minor;
        out.max_readahead = 1 << 20;
        out.max_write = 1 << 20;
        reply(in->unique, 0, &out, sizeof(out));
        break;
      }
      case FUSE_GETATTR: {
        if (in->nodeid >= g_nodes.size()) {
          reply(in->unique, -ENOENT, nullptr, 0);
          break;
        }
        struct fuse_attr_out out;
        memset(&out, 0, sizeof(out));
        out.attr_valid = 3600;
        fill_attr(node(in->nodeid), &out.attr);
        reply(in->unique, 0, &out, sizeof(out));
        break;
      }
      case FUSE_LOOKUP: {
        std::string name(arg);
        if (in->nodeid >= g_nodes.size() || !node(in->nodeid).is_dir) {
          reply(in->unique, -ENOENT, nullptr, 0);
          break;
        }
        auto& ch = node(in->nodeid).children;
        auto it = ch.find(name);
        if (it == ch.end()) reply(in->unique, -ENOENT, nullptr, 0);
        else reply_entry(in->unique, node(it->second));
        break;
      }
      case FUSE_READLINK: {
        const Node& nd = node(in->nodeid);
        if (nd.link_target.empty()) reply(in->unique, -EINVAL, nullptr, 0);
        else reply(in->unique, 0, nd.link_target.data(),
                   nd.link_target.size());
        break;
      }
      case FUSE_OPEN:
      case FUSE_OPENDIR: {
        struct fuse_open_out out;
        memset(&out, 0, sizeof(out));
        out.open_flags = FOPEN_KEEP_CACHE;
        reply(in->unique, 0, &out, sizeof(out));
        break;
      }
      case FUSE_READ: {
        auto* ri = reinterpret_cast<struct fuse_read_in*>(arg);
        const Node& nd = node(in->nodeid);
        std::vector<char> out(ri->size);
        ssize_t got = read_node(nd, ri->offset, ri->size, out.data());
        if (got < 0) reply(in->unique, static_cast<int32_t>(got),
                           nullptr, 0);
        else reply(in->unique, 0, out.data(), got);
        break;
      }
      case FUSE_READDIR: {
        auto* ri = reinterpret_cast<struct fuse_read_in*>(arg);
        const Node& nd = node(in->nodeid);
        std::string out;
        uint64_t idx = 0;
        for (auto& kv : nd.children) {
          idx++;
          if (idx <= ri->offset) continue;
          const Node& c = node(kv.second);
          size_t entlen = FUSE_NAME_OFFSET + kv.first.size();
          size_t padded = FUSE_DIRENT_ALIGN(entlen);
          if (out.size() + padded > ri->size) break;
          struct fuse_dirent d;
          d.ino = c.ino;
          d.off = idx;
          d.namelen = kv.first.size();
          d.type = c.is_dir ? DT_DIR
                            : (c.link_target.empty() ? DT_REG : DT_LNK);
          out.append(reinterpret_cast<char*>(&d), FUSE_NAME_OFFSET);
          out.append(kv.first);
          out.append(padded - entlen, '\0');
        }
        reply(in->unique, 0, out.data(), out.size());
        break;
      }
      case FUSE_STATFS: {
        struct fuse_statfs_out out;
        memset(&out, 0, sizeof(out));
        out.st.namelen = 255;
        out.st.bsize = 4096;
        reply(in->unique, 0, &out, sizeof(out));
        break;
      }
      case FUSE_RELEASE:
      case FUSE_RELEASEDIR:
      case FUSE_FLUSH:
        reply(in->unique, 0, nullptr, 0);
        break;
      case FUSE_FORGET:
      case FUSE_BATCH_FORGET:
        break;                           // no reply by protocol
      case FUSE_ACCESS:
        reply(in->unique, 0, nullptr, 0);
        break;
      default:
        reply(in->unique, -ENOSYS, nullptr, 0);
    }
  }
}

}  // namespace

int main(int argc, char** argv) {
  std::string manifest, mount_point;
  bool foreground = false;
  for (int i = 1; i < argc; i++) {
    std::string a = argv[i];
    auto next = [&]() -> std::string {
      if (i + 1 >= argc) { fprintf(stderr, "missing value\n"); exit(2); }
      return argv[++i];
    };
    if (a == "--manifest") manifest = next();
    else if (a == "--store") g_store = next();
    else if (a == "--mount") mount_point = next();
    else if (a == "--sock") g_sock = next();
    else if (a == "--foreground") foreground = true;
  }
  if (manifest.empty() || g_store.empty() || mount_point.empty()) {
    fprintf(stderr, "usage: t9cachefs --manifest M --store DIR --mount MNT"
                    " [--sock PATH] [--foreground]\n");
    return 2;
  }
  load_manifest(manifest);

  g_fuse_fd = open("/dev/fuse", O_RDWR | O_CLOEXEC);
  if (g_fuse_fd < 0) die("open /dev/fuse");
  char opts[256];
  snprintf(opts, sizeof opts,
           "fd=%d,rootmode=40755,user_id=0,group_id=0,allow_other,"
           "default_permissions",
           g_fuse_fd);
  if (mount("t9cachefs", mount_point.c_str(), "fuse.t9cachefs",
            MS_NOSUID | MS_NODEV | MS_RDONLY, opts) != 0)
    die("mount");

  if (!foreground) {
    // detach: the worker supervises by mountpoint, not pid
    if (fork() != 0) return 0;
    setsid();
  }
  printf("t9cachefs: serving %zu nodes at %s\n", g_nodes.size() - 2,
         mount_point.c_str());
  fflush(stdout);
  // multithreaded dispatch (the kernel load-balances requests across
  // /dev/fuse readers): a cold chunk fault blocking one thread must not
  // stall warm reads from other containers sharing the mount. The node
  // tree is read-only after load; each reply is a single write(2), which
  // /dev/fuse treats atomically.
  std::vector<std::thread> workers;
  for (int t = 0; t < 3; t++) workers.emplace_back(serve);
  serve();
  for (auto& th : workers) th.join();
  return 0;
}
