// t9container — namespace/chroot container launcher for tpu9's NativeRuntime.
//
// Reference analogue: the forked runc binary the reference worker drives
// (pkg/runtime/runc.go; docker/Dockerfile.worker builds beam-cloud/runc).
// tpu9 implements the containment primitives directly instead of shelling
// out to an OCI runtime: new pid/mount/uts/ipc namespaces, optional join of
// a pre-created network namespace, pivot_root into an (overlayfs) rootfs,
// bind mounts, /proc + /dev essentials, then exec of the entrypoint as the
// namespace's PID 1 (or under t9proc when a supervisor is requested).
//
// Invocation (trusted worker only — arguments are not an end-user surface):
//   t9container --rootfs DIR [--workdir DIR] [--hostname NAME]
//               [--netns NAME] [--bind SRC:DST[:ro]]... [--env-file FILE]
//               [--dev PATH]... -- ARGV...
//
// env-file: NUL-separated KEY=VALUE entries (values may contain anything
// but NUL). The child starts with a clean environment.

#include <cerrno>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

#include <fcntl.h>
#include <sched.h>
#include <sys/mount.h>
#include <sys/stat.h>
#include <sys/syscall.h>
#include <sys/types.h>
#include <sys/wait.h>
#include <unistd.h>

namespace {

[[noreturn]] void die(const char* what) {
  fprintf(stderr, "t9container: %s: %s\n", what, strerror(errno));
  exit(111);
}

struct Bind {
  std::string src, dst;
  bool ro = false;
};

struct Opts {
  std::string rootfs, workdir = "/", hostname, netns, env_file;
  std::vector<Bind> binds;
  std::vector<std::string> devices;
  std::vector<char*> argv;
  std::vector<std::string> env;   // loaded BEFORE pivot_root hides the file
};

Opts parse(int argc, char** argv) {
  Opts o;
  int i = 1;
  for (; i < argc; i++) {
    std::string a = argv[i];
    auto next = [&]() -> std::string {
      if (i + 1 >= argc) { fprintf(stderr, "missing value for %s\n", a.c_str()); exit(2); }
      return argv[++i];
    };
    if (a == "--rootfs") o.rootfs = next();
    else if (a == "--workdir") o.workdir = next();
    else if (a == "--hostname") o.hostname = next();
    else if (a == "--netns") o.netns = next();
    else if (a == "--env-file") o.env_file = next();
    else if (a == "--dev") o.devices.push_back(next());
    else if (a == "--bind") {
      std::string spec = next();
      Bind b;
      size_t p1 = spec.find(':');
      size_t p2 = spec.find(':', p1 == std::string::npos ? p1 : p1 + 1);
      if (p1 == std::string::npos) { fprintf(stderr, "bad --bind %s\n", spec.c_str()); exit(2); }
      b.src = spec.substr(0, p1);
      b.dst = p2 == std::string::npos ? spec.substr(p1 + 1)
                                      : spec.substr(p1 + 1, p2 - p1 - 1);
      b.ro = p2 != std::string::npos && spec.substr(p2 + 1) == "ro";
      o.binds.push_back(b);
    } else if (a == "--") { i++; break; }
    else { fprintf(stderr, "unknown flag %s\n", a.c_str()); exit(2); }
  }
  for (; i < argc; i++) o.argv.push_back(argv[i]);
  o.argv.push_back(nullptr);
  if (o.rootfs.empty() || o.argv.size() < 2) {
    fprintf(stderr, "usage: t9container --rootfs DIR [...] -- ARGV...\n");
    exit(2);
  }
  return o;
}

std::vector<std::string> read_env_file(const std::string& path) {
  std::vector<std::string> out;
  if (path.empty()) return out;
  FILE* f = fopen(path.c_str(), "rb");
  if (!f) die("open env-file");
  std::string cur;
  int c;
  while ((c = fgetc(f)) != EOF) {
    if (c == '\0') { if (!cur.empty()) out.push_back(cur); cur.clear(); }
    else cur.push_back(static_cast<char>(c));
  }
  if (!cur.empty()) out.push_back(cur);
  fclose(f);
  return out;
}

void join_netns(const std::string& name) {
  std::string path = "/run/netns/" + name;
  int fd = open(path.c_str(), O_RDONLY | O_CLOEXEC);
  if (fd < 0) die("open netns");
  if (setns(fd, CLONE_NEWNET) != 0) die("setns net");
  close(fd);
}

void mkdir_p(const std::string& path, mode_t mode) {
  std::string cur;
  for (size_t i = 0; i < path.size(); i++) {
    cur.push_back(path[i]);
    if ((path[i] == '/' && i > 0) || i + 1 == path.size()) {
      if (mkdir(cur.c_str(), mode) != 0 && errno != EEXIST) die("mkdir");
    }
  }
}

void bind_mount(const std::string& src, const std::string& dst, bool ro) {
  struct stat st{};
  if (stat(src.c_str(), &st) != 0) die("bind source missing");
  if (S_ISDIR(st.st_mode)) {
    mkdir_p(dst, 0755);
  } else {
    mkdir_p(dst.substr(0, dst.rfind('/')), 0755);
    int fd = open(dst.c_str(), O_CREAT | O_WRONLY | O_CLOEXEC, 0644);
    if (fd >= 0) close(fd);
  }
  if (mount(src.c_str(), dst.c_str(), nullptr, MS_BIND | MS_REC, nullptr) != 0)
    die("bind mount");
  if (ro && mount(nullptr, dst.c_str(), nullptr,
                  MS_BIND | MS_REMOUNT | MS_RDONLY, nullptr) != 0)
    die("bind remount ro");
}

int child_main(void* arg) {
  Opts& o = *static_cast<Opts*>(arg);

  if (!o.hostname.empty() &&
      sethostname(o.hostname.c_str(), o.hostname.size()) != 0)
    die("sethostname");

  // private mount propagation so nothing we do leaks to the host
  if (mount(nullptr, "/", nullptr, MS_REC | MS_PRIVATE, nullptr) != 0)
    die("make / private");

  // rootfs must be a mount point for pivot_root
  if (mount(o.rootfs.c_str(), o.rootfs.c_str(), nullptr, MS_BIND | MS_REC,
            nullptr) != 0)
    die("bind rootfs");

  const std::string root = o.rootfs;
  // /dev: tmpfs with the handful of nodes every runtime needs, bound from
  // the host (mknod is blocked in many kernels' userns; bind is universal)
  mkdir_p(root + "/dev", 0755);
  if (mount("tmpfs", (root + "/dev").c_str(), "tmpfs", MS_NOSUID,
            "mode=755,size=65536k") != 0)
    die("mount /dev");
  for (const char* n : {"null", "zero", "full", "random", "urandom", "tty"})
    bind_mount(std::string("/dev/") + n, root + "/dev/" + n, false);
  mkdir_p(root + "/dev/shm", 01777);
  mount("tmpfs", (root + "/dev/shm").c_str(), "tmpfs", MS_NOSUID | MS_NODEV,
        "mode=1777,size=268435456");
  mkdir_p(root + "/dev/pts", 0755);
  mount("devpts", (root + "/dev/pts").c_str(), "devpts", MS_NOSUID | MS_NOEXEC,
        "newinstance,ptmxmode=0666,mode=0620");
  // accelerator devices (TPU chips: /dev/accel*, vfio) requested explicitly
  for (const auto& dev : o.devices)
    bind_mount(dev, root + dev, false);

  // /tmp BEFORE binds: a bind target under /tmp must land on top of the
  // container's tmpfs, not get shadowed by it
  mkdir_p(root + "/tmp", 01777);
  mount("tmpfs", (root + "/tmp").c_str(), "tmpfs", MS_NOSUID | MS_NODEV,
        "mode=1777");

  for (const auto& b : o.binds) bind_mount(b.src, root + b.dst, b.ro);

  // pivot into the rootfs
  const std::string put_old = root + "/.t9-oldroot";
  mkdir_p(put_old, 0700);
  if (syscall(SYS_pivot_root, root.c_str(), put_old.c_str()) != 0)
    die("pivot_root");
  if (chdir("/") != 0) die("chdir /");
  if (umount2("/.t9-oldroot", MNT_DETACH) != 0) die("umount oldroot");
  rmdir("/.t9-oldroot");

  // fresh /proc for the new pid namespace
  mkdir_p("/proc", 0555);
  if (mount("proc", "/proc", "proc", MS_NOSUID | MS_NOEXEC | MS_NODEV,
            nullptr) != 0)
    die("mount /proc");
  mkdir_p("/sys", 0555);
  // RO sysfs scoped to the container's netns (best effort: some kernels
  // refuse sysfs mounts inside nested namespaces)
  mount("sysfs", "/sys", "sysfs",
        MS_RDONLY | MS_NOSUID | MS_NOEXEC | MS_NODEV, nullptr);

  if (chdir(o.workdir.c_str()) != 0 && chdir("/") != 0) die("chdir workdir");

  std::vector<char*> envp;
  envp.reserve(o.env.size() + 1);
  for (auto& e : o.env) envp.push_back(e.data());
  envp.push_back(nullptr);

  execvpe(o.argv[0], o.argv.data(), envp.data());
  die("execvpe");
}

}  // namespace

pid_t g_child = -1;

void forward_signal(int sig) {
  if (g_child <= 0) return;
  // a pid-namespace init ignores signals it has no handler for, even from
  // the parent namespace — forward the polite signal, then guarantee death
  // with SIGKILL (always deliverable from an ancestor ns) after a grace
  // period so a graceful stop can never orphan the workload
  kill(g_child, sig);
  if (sig == SIGTERM || sig == SIGINT) alarm(10);
}

void on_alarm(int) {
  if (g_child > 0) kill(g_child, SIGKILL);
}

int main(int argc, char** argv) {
  static Opts o = parse(argc, argv);
  o.env = read_env_file(o.env_file);   // before pivot_root hides the path

  // the netns join happens in the parent side of clone so the child's other
  // namespaces nest inside it cleanly
  if (!o.netns.empty()) join_netns(o.netns);

  constexpr size_t kStack = 1 << 20;
  static char stack[kStack];
  int flags = CLONE_NEWPID | CLONE_NEWNS | CLONE_NEWUTS | CLONE_NEWIPC |
              SIGCHLD;
  pid_t pid = clone(child_main, stack + kStack, flags, &o);
  if (pid < 0) die("clone");
  g_child = pid;

  struct sigaction sa{};
  sa.sa_handler = forward_signal;
  sigaction(SIGTERM, &sa, nullptr);
  sigaction(SIGINT, &sa, nullptr);
  sigaction(SIGHUP, &sa, nullptr);
  struct sigaction saa{};
  saa.sa_handler = on_alarm;
  sigaction(SIGALRM, &saa, nullptr);

  int status = 0;
  for (;;) {
    pid_t got = waitpid(pid, &status, 0);
    if (got == pid) break;
    if (got < 0 && errno != EINTR) die("waitpid");
  }
  if (WIFEXITED(status)) return WEXITSTATUS(status);
  if (WIFSIGNALED(status)) return 128 + WTERMSIG(status);
  return 1;
}
