// t9container — namespace/chroot container launcher for tpu9's NativeRuntime.
//
// Reference analogue: the forked runc binary the reference worker drives
// (pkg/runtime/runc.go; docker/Dockerfile.worker builds beam-cloud/runc).
// tpu9 implements the containment primitives directly instead of shelling
// out to an OCI runtime: new pid/mount/uts/ipc namespaces, optional join of
// a pre-created network namespace, pivot_root into an (overlayfs) rootfs,
// bind mounts, /proc + /dev essentials, then exec of the entrypoint as the
// namespace's PID 1 (or under t9proc when a supervisor is requested).
//
// Invocation (trusted worker only — arguments are not an end-user surface):
//   t9container --rootfs DIR [--workdir DIR] [--hostname NAME]
//               [--netns NAME] [--bind SRC:DST[:ro]]... [--env-file FILE]
//               [--dev PATH]... [--uid N] [--gid N]
//               [--seccomp-mode allow|deny|off] [--no-seccomp]
//               -- ARGV...
//
// env-file: NUL-separated KEY=VALUE entries (values may contain anything
// but NUL). The child starts with a clean environment.
//
// Privilege containment (reference analogue: the hardened base OCI spec
// pkg/runtime/base_runc_config.json + the gVisor fork runsc.go:52). After
// all privileged setup (mounts, pivot_root) and BEFORE exec:
//   1. no_new_privs — setuid/filecap binaries can never re-escalate
//   2. capability drop — bounding set cleared of everything dangerous;
//      with --uid != 0 the cred change additionally zeroes CapEff/CapPrm
//   3. seccomp ALLOW-list (default; VERDICT r04 #2): only syscalls
//      recorded from live runner traces (native/t9_allowlist.h, generated
//      by scripts/gen_syscall_allowlist.py) pass; everything else returns
//      EPERM — the same default-deny polarity as the reference's gVisor.
//      --seccomp-mode deny keeps the legacy deny-list (broad-compat
//      fallback for exotic user images); --seccomp-mode off / --no-seccomp
//      for debugging only.
//   4. --uid/--gid — setgroups([]) + setgid + setuid to an unprivileged id

#include <cerrno>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

#include <cstddef>
#include <fcntl.h>
#include <grp.h>
#include <linux/audit.h>
#include <linux/filter.h>
#include <linux/seccomp.h>
#include <sched.h>
#include <sys/mount.h>
#include <sys/prctl.h>
#include <sys/stat.h>
#include <sys/syscall.h>
#include <sys/types.h>
#include <sys/wait.h>
#include <unistd.h>

namespace {

[[noreturn]] void die(const char* what) {
  fprintf(stderr, "t9container: %s: %s\n", what, strerror(errno));
  exit(111);
}

struct Bind {
  std::string src, dst;
  bool ro = false;
};

enum class SeccompMode { kAllow, kDeny, kOff };

struct Opts {
  std::string rootfs, workdir = "/", hostname, netns, env_file;
  std::vector<Bind> binds;
  std::vector<std::string> devices;
  std::vector<char*> argv;
  std::vector<std::string> env;   // loaded BEFORE pivot_root hides the file
  uid_t uid = 0;
  gid_t gid = 0;
  SeccompMode seccomp = SeccompMode::kAllow;
};

Opts parse(int argc, char** argv) {
  Opts o;
  int i = 1;
  for (; i < argc; i++) {
    std::string a = argv[i];
    auto next = [&]() -> std::string {
      if (i + 1 >= argc) { fprintf(stderr, "missing value for %s\n", a.c_str()); exit(2); }
      return argv[++i];
    };
    if (a == "--rootfs") o.rootfs = next();
    else if (a == "--workdir") o.workdir = next();
    else if (a == "--hostname") o.hostname = next();
    else if (a == "--netns") o.netns = next();
    else if (a == "--env-file") o.env_file = next();
    else if (a == "--dev") o.devices.push_back(next());
    else if (a == "--uid") o.uid = static_cast<uid_t>(atoi(next().c_str()));
    else if (a == "--gid") o.gid = static_cast<gid_t>(atoi(next().c_str()));
    else if (a == "--no-seccomp") o.seccomp = SeccompMode::kOff;
    else if (a == "--seccomp-mode") {
      std::string m = next();
      if (m == "allow") o.seccomp = SeccompMode::kAllow;
      else if (m == "deny") o.seccomp = SeccompMode::kDeny;
      else if (m == "off") o.seccomp = SeccompMode::kOff;
      else { fprintf(stderr, "bad --seccomp-mode %s\n", m.c_str()); exit(2); }
    }
    else if (a == "--bind") {
      std::string spec = next();
      Bind b;
      size_t p1 = spec.find(':');
      size_t p2 = spec.find(':', p1 == std::string::npos ? p1 : p1 + 1);
      if (p1 == std::string::npos) { fprintf(stderr, "bad --bind %s\n", spec.c_str()); exit(2); }
      b.src = spec.substr(0, p1);
      b.dst = p2 == std::string::npos ? spec.substr(p1 + 1)
                                      : spec.substr(p1 + 1, p2 - p1 - 1);
      b.ro = p2 != std::string::npos && spec.substr(p2 + 1) == "ro";
      o.binds.push_back(b);
    } else if (a == "--") { i++; break; }
    else { fprintf(stderr, "unknown flag %s\n", a.c_str()); exit(2); }
  }
  for (; i < argc; i++) o.argv.push_back(argv[i]);
  o.argv.push_back(nullptr);
  if (o.rootfs.empty() || o.argv.size() < 2) {
    fprintf(stderr, "usage: t9container --rootfs DIR [...] -- ARGV...\n");
    exit(2);
  }
  return o;
}

std::vector<std::string> read_env_file(const std::string& path) {
  std::vector<std::string> out;
  if (path.empty()) return out;
  FILE* f = fopen(path.c_str(), "rb");
  if (!f) die("open env-file");
  std::string cur;
  int c;
  while ((c = fgetc(f)) != EOF) {
    if (c == '\0') { if (!cur.empty()) out.push_back(cur); cur.clear(); }
    else cur.push_back(static_cast<char>(c));
  }
  if (!cur.empty()) out.push_back(cur);
  fclose(f);
  return out;
}

void join_netns(const std::string& name) {
  std::string path = "/run/netns/" + name;
  int fd = open(path.c_str(), O_RDONLY | O_CLOEXEC);
  if (fd < 0) die("open netns");
  if (setns(fd, CLONE_NEWNET) != 0) die("setns net");
  close(fd);
}

void mkdir_p(const std::string& path, mode_t mode) {
  std::string cur;
  for (size_t i = 0; i < path.size(); i++) {
    cur.push_back(path[i]);
    if ((path[i] == '/' && i > 0) || i + 1 == path.size()) {
      if (mkdir(cur.c_str(), mode) != 0 && errno != EEXIST) die("mkdir");
    }
  }
}

void bind_mount(const std::string& src, const std::string& dst, bool ro) {
  struct stat st{};
  if (stat(src.c_str(), &st) != 0) die("bind source missing");
  if (S_ISDIR(st.st_mode)) {
    mkdir_p(dst, 0755);
  } else {
    mkdir_p(dst.substr(0, dst.rfind('/')), 0755);
    int fd = open(dst.c_str(), O_CREAT | O_WRONLY | O_CLOEXEC, 0644);
    if (fd >= 0) close(fd);
  }
  if (mount(src.c_str(), dst.c_str(), nullptr, MS_BIND | MS_REC, nullptr) != 0)
    die("bind mount");
  if (ro && mount(nullptr, dst.c_str(), nullptr,
                  MS_BIND | MS_REMOUNT | MS_RDONLY, nullptr) != 0)
    die("bind remount ro");
}

// ---- privilege containment -------------------------------------------------

// Capabilities kept in the bounding set when the workload stays uid 0
// (t9proc supervisor mode needs kill/setuid/setgid to manage children;
// everything host-threatening — sys_admin, sys_module, sys_ptrace,
// sys_rawio, net_admin, mknod, sys_boot, syslog, ... — is dropped).
// With --uid != 0 the setuid() additionally clears CapEff/CapPrm to 0.
constexpr int kKeepCaps[] = {
    0 /*chown*/, 1 /*dac_override*/, 3 /*fowner*/, 5 /*kill*/,
    6 /*setgid*/, 7 /*setuid*/, 10 /*net_bind_service*/, 13 /*net_raw*/,
};

void drop_bounding_caps() {
  for (int cap = 0; cap <= 63; cap++) {
    bool keep = false;
    for (int k : kKeepCaps) keep |= (cap == k);
    if (keep) continue;
    // past the kernel's last cap prctl returns EINVAL — done
    if (prctl(PR_CAPBSET_DROP, cap, 0, 0, 0) != 0) {
      if (errno == EINVAL) break;
      die("capbset drop");
    }
  }
  // no ambient caps survive into the workload
  prctl(PR_CAP_AMBIENT, PR_CAP_AMBIENT_CLEAR_ALL, 0, 0, 0);
}

// Allow-list (default): syscalls recorded from live traces of the real
// runners (scripts/gen_syscall_allowlist.py → t9_allowlist.h); anything
// else returns EPERM. Same polarity as the reference's gVisor: unknown
// kernel surface is unreachable by default.
constexpr int kAllowed[] = {
#include "t9_allowlist.h"
};

// Deny-list (--seccomp-mode deny): legacy fallback for exotic user images
// whose syscall needs outrun the recorded trace; blocks only the known
// escape/attack surface.
void install_seccomp(SeccompMode mode) {
  const bool allow_mode = mode == SeccompMode::kAllow;
  static const int kDenied[] = {
      SYS_mount, SYS_umount2, SYS_pivot_root, SYS_chroot, SYS_swapon,
      SYS_swapoff, SYS_reboot, SYS_kexec_load, SYS_kexec_file_load,
      SYS_init_module, SYS_finit_module, SYS_delete_module, SYS_bpf,
      SYS_ptrace, SYS_process_vm_readv, SYS_process_vm_writev,
      SYS_perf_event_open, SYS_setns, SYS_mknod, SYS_mknodat,
      SYS_open_by_handle_at, SYS_quotactl, SYS_acct, SYS_settimeofday,
      SYS_clock_settime, SYS_clock_adjtime, SYS_adjtimex, SYS_sethostname,
      SYS_setdomainname, SYS_add_key, SYS_request_key, SYS_keyctl,
      SYS_userfaultfd, SYS_vhangup, SYS_nfsservctl,
#ifdef SYS_iopl
      SYS_iopl,
#endif
#ifdef SYS_ioperm
      SYS_ioperm,
#endif
#ifdef SYS_lookup_dcookie
      SYS_lookup_dcookie,
#endif
  };
  constexpr size_t kN = sizeof(kDenied) / sizeof(kDenied[0]);

#if defined(__x86_64__)
  constexpr uint32_t kArch = AUDIT_ARCH_X86_64;
#elif defined(__aarch64__)
  constexpr uint32_t kArch = AUDIT_ARCH_AARCH64;
#else
#error "unsupported architecture for seccomp filter"
#endif

  std::vector<sock_filter> prog;
  // wrong-arch callers are killed outright
  prog.push_back(BPF_STMT(BPF_LD | BPF_W | BPF_ABS,
                          offsetof(seccomp_data, arch)));
  prog.push_back(BPF_JUMP(BPF_JMP | BPF_JEQ | BPF_K, kArch, 1, 0));
  prog.push_back(BPF_STMT(BPF_RET | BPF_K, SECCOMP_RET_KILL_PROCESS));
  prog.push_back(BPF_STMT(BPF_LD | BPF_W | BPF_ABS,
                          offsetof(seccomp_data, nr)));
#if defined(__x86_64__)
  // x32-ABI syscalls report arch == AUDIT_ARCH_X86_64 with
  // nr | 0x40000000 — they'd sail past every JEQ below and reopen
  // mount/ptrace through the x32 entry points. Kill them (Docker's
  // default profile does the same).
  prog.push_back(BPF_JUMP(BPF_JMP | BPF_JGE | BPF_K, 0x40000000u, 0, 1));
  prog.push_back(BPF_STMT(BPF_RET | BPF_K, SECCOMP_RET_KILL_PROCESS));
#endif
  // clone3 → ENOSYS so glibc falls back to clone (whose flags we can
  // inspect; clone3 passes flags in memory where BPF cannot see them)
#ifdef SYS_clone3
  prog.push_back(BPF_JUMP(BPF_JMP | BPF_JEQ | BPF_K,
                          static_cast<uint32_t>(SYS_clone3), 0, 1));
  prog.push_back(BPF_STMT(BPF_RET | BPF_K,
                          SECCOMP_RET_ERRNO | (ENOSYS & SECCOMP_RET_DATA)));
#endif
  // clone with any namespace flag is an escape vector (CLONE_NEWUSER
  // grants full caps in the child userns, then x32/mount games) — deny;
  // plain thread/fork clones pass. flags is arg0 on x86_64 and aarch64.
  constexpr uint32_t kNsFlags =
      CLONE_NEWUSER | CLONE_NEWNS | CLONE_NEWNET | CLONE_NEWPID |
      CLONE_NEWIPC | CLONE_NEWUTS | CLONE_NEWCGROUP;
  // allow mode resolves clean clones right here (clone never reaches the
  // allow array); deny mode falls through to its deny array
  prog.push_back(BPF_JUMP(BPF_JMP | BPF_JEQ | BPF_K,
                          static_cast<uint32_t>(SYS_clone), 0,
                          static_cast<uint8_t>(allow_mode ? 5 : 4)));
  prog.push_back(BPF_STMT(BPF_LD | BPF_W | BPF_ABS,
                          offsetof(seccomp_data, args[0])));
  prog.push_back(BPF_JUMP(BPF_JMP | BPF_JSET | BPF_K, kNsFlags, 0, 1));
  prog.push_back(BPF_STMT(BPF_RET | BPF_K,
                          SECCOMP_RET_ERRNO | (EPERM & SECCOMP_RET_DATA)));
  if (allow_mode)
    prog.push_back(BPF_STMT(BPF_RET | BPF_K, SECCOMP_RET_ALLOW));
  prog.push_back(BPF_STMT(BPF_LD | BPF_W | BPF_ABS,
                          offsetof(seccomp_data, nr)));   // restore A = nr

  if (allow_mode) {
    for (int nr : kAllowed) {
      prog.push_back(BPF_JUMP(BPF_JMP | BPF_JEQ | BPF_K,
                              static_cast<uint32_t>(nr), 0, 1));
      prog.push_back(BPF_STMT(BPF_RET | BPF_K, SECCOMP_RET_ALLOW));
    }
    // default-deny: EPERM (not KILL) so an off-list syscall surfaces as a
    // debuggable error in the workload, not a silent SIGSYS corpse
    prog.push_back(BPF_STMT(BPF_RET | BPF_K,
                            SECCOMP_RET_ERRNO | (EPERM & SECCOMP_RET_DATA)));
  } else {
    for (size_t i = 0; i < kN; i++) {
      prog.push_back(BPF_JUMP(BPF_JMP | BPF_JEQ | BPF_K,
                              static_cast<uint32_t>(kDenied[i]), 0, 1));
      prog.push_back(BPF_STMT(BPF_RET | BPF_K,
                              SECCOMP_RET_ERRNO |
                                  (EPERM & SECCOMP_RET_DATA)));
    }
    // unshare with namespace flags is an escape vector; plain unshare(0)
    // or CLONE_FILES-style uses are harmless but rare — deny it entirely
    // (the reference's gVisor denies it too)
    prog.push_back(BPF_JUMP(BPF_JMP | BPF_JEQ | BPF_K,
                            static_cast<uint32_t>(SYS_unshare), 0, 1));
    prog.push_back(BPF_STMT(BPF_RET | BPF_K,
                            SECCOMP_RET_ERRNO | (EPERM & SECCOMP_RET_DATA)));
    prog.push_back(BPF_STMT(BPF_RET | BPF_K, SECCOMP_RET_ALLOW));
  }

  sock_fprog fprog = {static_cast<unsigned short>(prog.size()), prog.data()};
  if (prctl(PR_SET_SECCOMP, SECCOMP_MODE_FILTER, &fprog, 0, 0) != 0)
    die("seccomp");
}

void contain_privileges(const Opts& o) {
  // no_new_privs FIRST: required for unprivileged seccomp and guarantees
  // setuid binaries in the image can never re-escalate
  if (prctl(PR_SET_NO_NEW_PRIVS, 1, 0, 0, 0) != 0) die("no_new_privs");
  drop_bounding_caps();
  if (o.gid != 0 || o.uid != 0) {
    if (setgroups(0, nullptr) != 0) die("setgroups");
    if (setgid(o.gid) != 0) die("setgid");
    if (setuid(o.uid) != 0) die("setuid");
    // with no PR_SET_KEEPCAPS the uid transition zeroed CapEff/CapPrm
  }
  if (o.seccomp != SeccompMode::kOff)
    install_seccomp(o.seccomp);       // last: it would block the above
}

int child_main(void* arg) {
  Opts& o = *static_cast<Opts*>(arg);

  if (!o.hostname.empty() &&
      sethostname(o.hostname.c_str(), o.hostname.size()) != 0)
    die("sethostname");

  // private mount propagation so nothing we do leaks to the host
  if (mount(nullptr, "/", nullptr, MS_REC | MS_PRIVATE, nullptr) != 0)
    die("make / private");

  // rootfs must be a mount point for pivot_root
  if (mount(o.rootfs.c_str(), o.rootfs.c_str(), nullptr, MS_BIND | MS_REC,
            nullptr) != 0)
    die("bind rootfs");

  const std::string root = o.rootfs;
  // /dev: tmpfs with the handful of nodes every runtime needs, bound from
  // the host (mknod is blocked in many kernels' userns; bind is universal)
  mkdir_p(root + "/dev", 0755);
  if (mount("tmpfs", (root + "/dev").c_str(), "tmpfs", MS_NOSUID,
            "mode=755,size=65536k") != 0)
    die("mount /dev");
  for (const char* n : {"null", "zero", "full", "random", "urandom", "tty"})
    bind_mount(std::string("/dev/") + n, root + "/dev/" + n, false);
  mkdir_p(root + "/dev/shm", 01777);
  mount("tmpfs", (root + "/dev/shm").c_str(), "tmpfs", MS_NOSUID | MS_NODEV,
        "mode=1777,size=268435456");
  mkdir_p(root + "/dev/pts", 0755);
  mount("devpts", (root + "/dev/pts").c_str(), "devpts", MS_NOSUID | MS_NOEXEC,
        "newinstance,ptmxmode=0666,mode=0620");
  // accelerator devices (TPU chips: /dev/accel*, vfio) requested explicitly
  for (const auto& dev : o.devices)
    bind_mount(dev, root + dev, false);

  // /tmp BEFORE binds: a bind target under /tmp must land on top of the
  // container's tmpfs, not get shadowed by it
  mkdir_p(root + "/tmp", 01777);
  mount("tmpfs", (root + "/tmp").c_str(), "tmpfs", MS_NOSUID | MS_NODEV,
        "mode=1777");

  for (const auto& b : o.binds) bind_mount(b.src, root + b.dst, b.ro);

  // pivot into the rootfs
  const std::string put_old = root + "/.t9-oldroot";
  mkdir_p(put_old, 0700);
  if (syscall(SYS_pivot_root, root.c_str(), put_old.c_str()) != 0)
    die("pivot_root");
  if (chdir("/") != 0) die("chdir /");
  if (umount2("/.t9-oldroot", MNT_DETACH) != 0) die("umount oldroot");
  rmdir("/.t9-oldroot");

  // fresh /proc for the new pid namespace
  mkdir_p("/proc", 0555);
  if (mount("proc", "/proc", "proc", MS_NOSUID | MS_NOEXEC | MS_NODEV,
            nullptr) != 0)
    die("mount /proc");
  mkdir_p("/sys", 0555);
  // RO sysfs scoped to the container's netns (best effort: some kernels
  // refuse sysfs mounts inside nested namespaces)
  mount("sysfs", "/sys", "sysfs",
        MS_RDONLY | MS_NOSUID | MS_NOEXEC | MS_NODEV, nullptr);

  if (chdir(o.workdir.c_str()) != 0 && chdir("/") != 0) die("chdir workdir");

  std::vector<char*> envp;
  envp.reserve(o.env.size() + 1);
  for (auto& e : o.env) envp.push_back(e.data());
  envp.push_back(nullptr);

  // all privileged setup is done — contain before handing over to the
  // (untrusted) workload
  contain_privileges(o);

  execvpe(o.argv[0], o.argv.data(), envp.data());
  die("execvpe");
}

}  // namespace

pid_t g_child = -1;

void forward_signal(int sig) {
  if (g_child <= 0) return;
  // a pid-namespace init ignores signals it has no handler for, even from
  // the parent namespace — forward the polite signal, then guarantee death
  // with SIGKILL (always deliverable from an ancestor ns) after a grace
  // period so a graceful stop can never orphan the workload
  kill(g_child, sig);
  if (sig == SIGTERM || sig == SIGINT) alarm(10);
}

void on_alarm(int) {
  if (g_child > 0) kill(g_child, SIGKILL);
}

int main(int argc, char** argv) {
  static Opts o = parse(argc, argv);
  o.env = read_env_file(o.env_file);   // before pivot_root hides the path

  // the netns join happens in the parent side of clone so the child's other
  // namespaces nest inside it cleanly
  if (!o.netns.empty()) join_netns(o.netns);

  constexpr size_t kStack = 1 << 20;
  static char stack[kStack];
  int flags = CLONE_NEWPID | CLONE_NEWNS | CLONE_NEWUTS | CLONE_NEWIPC |
              SIGCHLD;
  pid_t pid = clone(child_main, stack + kStack, flags, &o);
  if (pid < 0) die("clone");
  g_child = pid;

  struct sigaction sa{};
  sa.sa_handler = forward_signal;
  sigaction(SIGTERM, &sa, nullptr);
  sigaction(SIGINT, &sa, nullptr);
  sigaction(SIGHUP, &sa, nullptr);
  struct sigaction saa{};
  saa.sa_handler = on_alarm;
  sigaction(SIGALRM, &saa, nullptr);

  int status = 0;
  for (;;) {
    pid_t got = waitpid(pid, &status, 0);
    if (got == pid) break;
    if (got < 0 && errno != EINTR) die("waitpid");
  }
  if (WIFEXITED(status)) return WEXITSTATUS(status);
  if (WIFSIGNALED(status)) return 128 + WTERMSIG(status);
  return 1;
}
