// t9trace — minimal process-tree syscall-set recorder (strace -c without
// strace: this image ships no tracer, and the seccomp allow-list for
// t9container must be generated from what the REAL runners actually call).
//
// Reference analogue: the reference derives its sandbox posture from
// gVisor's implemented-syscall surface (pkg/runtime/runsc.go:52); tpu9
// derives its allow-list from live traces of its own runners instead.
//
// Usage: t9trace OUTFILE -- CMD [ARGS...]
//   Runs CMD under PTRACE_SYSCALL, following forks/vforks/clones, and
//   appends every distinct syscall number seen (one per line, decimal) to
//   OUTFILE. Exit status mirrors CMD's.
//
// Dev tool only: built on demand by scripts/gen_syscall_allowlist.py; not
// part of the production `make all` set and never shipped into containers.

#include <cerrno>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <set>

#include <sys/ptrace.h>
#include <sys/types.h>
#include <sys/user.h>
#include <sys/wait.h>
#include <unistd.h>

namespace {

[[noreturn]] void die(const char* what) {
  fprintf(stderr, "t9trace: %s: %s\n", what, strerror(errno));
  exit(112);
}

constexpr int kTraceOpts = PTRACE_O_TRACESYSGOOD | PTRACE_O_TRACEFORK |
                           PTRACE_O_TRACEVFORK | PTRACE_O_TRACECLONE |
                           PTRACE_O_TRACEEXEC;

}  // namespace

int main(int argc, char** argv) {
  if (argc < 4 || strcmp(argv[2], "--") != 0) {
    fprintf(stderr, "usage: t9trace OUTFILE -- CMD [ARGS...]\n");
    return 2;
  }
  const char* outfile = argv[1];

  pid_t child = fork();
  if (child < 0) die("fork");
  if (child == 0) {
    if (ptrace(PTRACE_TRACEME, 0, nullptr, nullptr) != 0) die("traceme");
    // stop so the parent can set options before the exec races ahead
    raise(SIGSTOP);
    execvp(argv[3], argv + 3);
    die("execvp");
  }

  std::set<long> seen;
  std::set<pid_t> tracees = {child};
  int root_status = 0;
  bool opts_set = false;
  bool root_done = false;
  while (!tracees.empty()) {
    int status;
    pid_t pid = waitpid(-1, &status, __WALL);
    if (pid < 0) {
      if (errno == ECHILD) break;
      if (errno == EINTR) continue;
      die("waitpid");
    }
    tracees.insert(pid);
    if (WIFEXITED(status) || WIFSIGNALED(status)) {
      tracees.erase(pid);
      if (pid == child) {
        root_status = status;
        root_done = true;
        // daemons double-forked by the traced command (reparented to
        // init but still our tracees) would block this wait forever —
        // their syscalls so far are recorded; kill the strays and drain
        for (pid_t p : tracees) kill(p, SIGKILL);
      }
      continue;
    }
    if (root_done) {
      // a stray stopping post-root: resume toward its SIGKILL
      ptrace(PTRACE_CONT, pid, nullptr, 0);
      continue;
    }
    if (!WIFSTOPPED(status)) continue;
    int sig = WSTOPSIG(status);
    if (!opts_set && pid == child) {
      if (ptrace(PTRACE_SETOPTIONS, pid, nullptr, kTraceOpts) != 0)
        die("setoptions");
      opts_set = true;
    }
    unsigned event = static_cast<unsigned>(status) >> 16;
    if (event == PTRACE_EVENT_FORK || event == PTRACE_EVENT_VFORK ||
        event == PTRACE_EVENT_CLONE) {
      // the new tracee inherits options and auto-stops for us; it joins
      // `tracees` when its first stop arrives
      ptrace(PTRACE_SYSCALL, pid, nullptr, 0);
      continue;
    }
    long forward = 0;
    if (sig == (SIGTRAP | 0x80)) {
      // syscall-enter or -exit stop; orig_rax is stable at both
      struct user_regs_struct regs;
      if (ptrace(PTRACE_GETREGS, pid, nullptr, &regs) == 0) {
#if defined(__x86_64__)
        seen.insert(static_cast<long>(regs.orig_rax));
#else
#error "t9trace supports x86_64 only"
#endif
      }
    } else if (sig == SIGTRAP || sig == SIGSTOP) {
      // exec event / group-stop noise: swallow
    } else {
      forward = sig;  // real signal: deliver it
    }
    ptrace(PTRACE_SYSCALL, pid, nullptr, forward);
  }

  FILE* f = fopen(outfile, "a");
  if (!f) die("open outfile");
  for (long nr : seen) fprintf(f, "%ld\n", nr);
  fclose(f);

  if (WIFEXITED(root_status)) return WEXITSTATUS(root_status);
  if (WIFSIGNALED(root_status)) return 128 + WTERMSIG(root_status);
  return 0;
}
