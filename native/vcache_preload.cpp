// vcache_preload.so — LD_PRELOAD shim routing volume file reads to the
// worker's local content cache.
//
// Reference analogue: the prebuilt bin/volume_cache_{x86,arm}.so C shim the
// reference injects with LD_PRELOAD + VOLUME_CACHE_MAP
// (pkg/worker/file_cache.go:21-24) so container reads of network-volume files
// hit the node's distributed cache instead of the object store. The source of
// that shim is not vendored upstream; this is tpu9's own implementation.
//
// Contract (set by the worker when a container has cached volumes):
//   TPU9_VCACHE_MAP=/volumes/models=/cache/vol/models:/volumes/data=/cache/vol/data
//     (colon-separated "<mount-prefix>=<cache-dir>" pairs)
//   TPU9_VCACHE_STATS=/tmp/vcache-stats   (optional; hit/miss counters
//                                          appended on process exit)
//
// open()/open64()/fopen()/fopen64() of a path under a mapped prefix is
// redirected to the cache copy when one exists (the worker materializes hot
// volume files into the cache dir via hardlinks, so a hit is a local-disk
// open). Writes and missing files fall through to the real path — the shim
// is a read accelerator, never a correctness layer.
//
// The stat() family is intentionally NOT interposed: cache entries must be
// byte-identical materializations (hardlinks) of the volume file so
// stat-then-read consumers see consistent sizes. Mismatched cache copies are
// an operator error.

#include <atomic>
#include <cstdarg>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

#include <dlfcn.h>
#include <fcntl.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

struct Mapping {
  std::string prefix;
  std::string cache_dir;
};

std::vector<Mapping>* mappings = nullptr;
std::atomic<long> g_hits{0};
std::atomic<long> g_misses{0};

using open_fn = int (*)(const char*, int, ...);
using fopen_fn = FILE* (*)(const char*, const char*);
using stat_fn = int (*)(const char*, struct stat*);

open_fn real_open = nullptr;
open_fn real_open64 = nullptr;
fopen_fn real_fopen = nullptr;
fopen_fn real_fopen64 = nullptr;

std::once_flag g_init_flag;

void init_impl() {
  auto* m = new std::vector<Mapping>();
  const char* raw = getenv("TPU9_VCACHE_MAP");
  if (raw != nullptr) {
    std::string spec(raw);
    size_t start = 0;
    while (start < spec.size()) {
      size_t end = spec.find(':', start);
      if (end == std::string::npos) end = spec.size();
      std::string pair = spec.substr(start, end - start);
      size_t eq = pair.find('=');
      if (eq != std::string::npos && eq > 0) {
        m->push_back({pair.substr(0, eq), pair.substr(eq + 1)});
      }
      start = end + 1;
    }
  }
  real_open = reinterpret_cast<open_fn>(dlsym(RTLD_NEXT, "open"));
  real_open64 = reinterpret_cast<open_fn>(dlsym(RTLD_NEXT, "open64"));
  real_fopen = reinterpret_cast<fopen_fn>(dlsym(RTLD_NEXT, "fopen"));
  real_fopen64 = reinterpret_cast<fopen_fn>(dlsym(RTLD_NEXT, "fopen64"));
  mappings = m;   // publish last: readers go through init_once's call_once
}

// Thread-safe: concurrent first opens from multiple threads must not observe
// a half-built mapping table or null function pointers.
void init_once() { std::call_once(g_init_flag, init_impl); }

// Returns the cache path when `path` is under a mapped prefix AND the cache
// copy exists; empty string otherwise.
std::string redirect(const char* path, bool write_mode) {
  if (path == nullptr || write_mode) return "";
  init_once();
  for (const auto& map : *mappings) {
    size_t n = map.prefix.size();
    if (strncmp(path, map.prefix.c_str(), n) == 0 &&
        (path[n] == '/' || path[n] == '\0')) {
      std::string candidate = map.cache_dir + (path + n);
      struct stat st;
      if (::stat(candidate.c_str(), &st) == 0 && S_ISREG(st.st_mode)) {
        g_hits.fetch_add(1, std::memory_order_relaxed);
        return candidate;
      }
      g_misses.fetch_add(1, std::memory_order_relaxed);
      return "";
    }
  }
  return "";
}

bool flags_write(int flags) {
  return (flags & (O_WRONLY | O_RDWR | O_CREAT | O_TRUNC | O_APPEND)) != 0;
}

bool mode_write(const char* mode) {
  return mode != nullptr && (strchr(mode, 'w') || strchr(mode, 'a') ||
                             strchr(mode, '+'));
}

struct StatsDumper {
  ~StatsDumper() {
    const char* stats = getenv("TPU9_VCACHE_STATS");
    if (stats == nullptr) return;
    FILE* f = real_fopen != nullptr ? real_fopen(stats, "a")
                                    : ::fopen(stats, "a");
    if (f != nullptr) {
      fprintf(f, "{\"hits\": %ld, \"misses\": %ld}\n", g_hits.load(),
              g_misses.load());
      fclose(f);
    }
  }
} g_stats_dumper;

}  // namespace

extern "C" {

int open(const char* path, int flags, ...) {
  mode_t mode = 0;
  if (flags & O_CREAT) {
    va_list ap;
    va_start(ap, flags);
    mode = va_arg(ap, mode_t);
    va_end(ap);
  }
  init_once();
  std::string alt = redirect(path, flags_write(flags));
  const char* target = alt.empty() ? path : alt.c_str();
  return real_open(target, flags, mode);
}

int open64(const char* path, int flags, ...) {
  mode_t mode = 0;
  if (flags & O_CREAT) {
    va_list ap;
    va_start(ap, flags);
    mode = va_arg(ap, mode_t);
    va_end(ap);
  }
  init_once();
  std::string alt = redirect(path, flags_write(flags));
  const char* target = alt.empty() ? path : alt.c_str();
  return (real_open64 != nullptr ? real_open64 : real_open)(target, flags,
                                                            mode);
}

FILE* fopen(const char* path, const char* mode) {
  init_once();
  std::string alt = redirect(path, mode_write(mode));
  return real_fopen(alt.empty() ? path : alt.c_str(), mode);
}

FILE* fopen64(const char* path, const char* mode) {
  init_once();
  std::string alt = redirect(path, mode_write(mode));
  return (real_fopen64 != nullptr ? real_fopen64 : real_fopen)(
      alt.empty() ? path : alt.c_str(), mode);
}

}  // extern "C"
