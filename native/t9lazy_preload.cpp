// t9lazy_preload.so — LD_PRELOAD shim gating open() of lazily-materialized
// image files on the worker's background filler.
//
// Reference analogue: the CLIP FUSE mount's page-fault path
// (pkg/worker/image.go:274 PullLazy; pkg/cache/cachefs.go): the reference
// blocks a read until the content is fetched from the distributed cache.
// tpu9 gates at open() granularity instead of page granularity — the bundle
// skeleton is stat-correct sparse files, so only the first open of a
// not-yet-filled file pays a round-trip to the filler daemon, and once the
// bundle's .tpu9-complete marker exists the shim is a single cached check.
//
// Contract (set by the worker on containers whose image is still filling):
//   TPU9_LAZY_DIRS=/bundles/img-a:/bundles/img-b   (lazy bundle roots)
//   TPU9_LAZY_SOCK=/bundles/.sock/img-a.sock       (fault socket)
//   TPU9_LAZY_TIMEOUT_S=120                        (optional)
//
// Protocol: "REQ <abspath>\n" -> "OK\n" when the file's bytes are real.
// Fallback: if the socket is unreachable the shim polls for the
// .tpu9-complete marker until the timeout, then fails the open with EIO —
// never silently reads placeholder zeros.

#include <atomic>
#include <cstdarg>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

#include <dlfcn.h>
#include <fcntl.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/un.h>
#include <time.h>
#include <unistd.h>

namespace {

using open_fn = int (*)(const char*, int, ...);
using openat_fn = int (*)(int, const char*, int, ...);
using fopen_fn = FILE* (*)(const char*, const char*);

open_fn real_open = nullptr;
open_fn real_open64 = nullptr;
openat_fn real_openat = nullptr;
openat_fn real_openat64 = nullptr;
fopen_fn real_fopen = nullptr;
fopen_fn real_fopen64 = nullptr;

std::vector<std::string>* g_roots = nullptr;
std::string* g_sock = nullptr;
int g_timeout_s = 120;
std::atomic<bool> g_all_complete{false};
std::atomic<long> g_gated{0};
std::once_flag g_init_flag;

void init_impl() {
  auto* roots = new std::vector<std::string>();
  const char* raw = getenv("TPU9_LAZY_DIRS");
  if (raw != nullptr) {
    std::string spec(raw);
    size_t start = 0;
    while (start < spec.size()) {
      size_t end = spec.find(':', start);
      if (end == std::string::npos) end = spec.size();
      if (end > start) roots->push_back(spec.substr(start, end - start));
      start = end + 1;
    }
  }
  const char* sock = getenv("TPU9_LAZY_SOCK");
  g_sock = new std::string(sock != nullptr ? sock : "");
  const char* to = getenv("TPU9_LAZY_TIMEOUT_S");
  if (to != nullptr && atoi(to) > 0) g_timeout_s = atoi(to);
  real_open = reinterpret_cast<open_fn>(dlsym(RTLD_NEXT, "open"));
  real_open64 = reinterpret_cast<open_fn>(dlsym(RTLD_NEXT, "open64"));
  real_openat = reinterpret_cast<openat_fn>(dlsym(RTLD_NEXT, "openat"));
  real_openat64 = reinterpret_cast<openat_fn>(dlsym(RTLD_NEXT, "openat64"));
  real_fopen = reinterpret_cast<fopen_fn>(dlsym(RTLD_NEXT, "fopen"));
  real_fopen64 = reinterpret_cast<fopen_fn>(dlsym(RTLD_NEXT, "fopen64"));
  g_roots = roots;   // publish last
}

void init_once() { std::call_once(g_init_flag, init_impl); }

// root the path lives under, or nullptr
const std::string* match_root(const char* path) {
  if (path == nullptr || g_roots == nullptr || g_roots->empty())
    return nullptr;
  for (const auto& root : *g_roots) {
    size_t n = root.size();
    if (strncmp(path, root.c_str(), n) == 0 &&
        (path[n] == '/' || path[n] == '\0'))
      return &root;
  }
  return nullptr;
}

bool complete_marker(const std::string& root) {
  struct stat st;
  return ::stat((root + "/.tpu9-complete").c_str(), &st) == 0;
}

// Ask the filler daemon to make `path` real. Returns true when safe to
// open. Blocks (bounded) — that IS the lazy-load semantic.
bool fault_in(const std::string& root, const char* path) {
  if (g_all_complete.load(std::memory_order_relaxed)) return true;
  if (complete_marker(root)) {
    g_all_complete.store(true, std::memory_order_relaxed);
    return true;
  }
  g_gated.fetch_add(1, std::memory_order_relaxed);
  struct timespec start;
  clock_gettime(CLOCK_MONOTONIC, &start);
  for (;;) {
    if (!g_sock->empty()) {
      int fd = ::socket(AF_UNIX, SOCK_STREAM | SOCK_CLOEXEC, 0);
      if (fd >= 0) {
        struct sockaddr_un addr;
        memset(&addr, 0, sizeof(addr));
        addr.sun_family = AF_UNIX;
        strncpy(addr.sun_path, g_sock->c_str(), sizeof(addr.sun_path) - 1);
        if (::connect(fd, reinterpret_cast<struct sockaddr*>(&addr),
                      sizeof(addr)) == 0) {
          std::string req = std::string("REQ ") + path + "\n";
          if (::write(fd, req.data(), req.size()) ==
              static_cast<ssize_t>(req.size())) {
            char buf[16];
            ssize_t n = ::read(fd, buf, sizeof(buf) - 1);
            ::close(fd);
            if (n >= 2 && strncmp(buf, "OK", 2) == 0) return true;
            return false;                    // daemon says unfetchable
          }
        }
        ::close(fd);
      }
    }
    // daemon unreachable (filling finished? worker restarting?) — the
    // completion marker is the fallback truth
    if (complete_marker(root)) {
      g_all_complete.store(true, std::memory_order_relaxed);
      return true;
    }
    struct timespec now;
    clock_gettime(CLOCK_MONOTONIC, &now);
    if (now.tv_sec - start.tv_sec >= g_timeout_s) return false;
    usleep(50 * 1000);
  }
}

// Returns false when the open must fail with EIO (unfetchable lazy file).
bool gate(const char* path) {
  init_once();
  const std::string* root = match_root(path);
  if (root == nullptr) return true;
  return fault_in(*root, path);
}

}  // namespace

extern "C" {

int open(const char* path, int flags, ...) {
  mode_t mode = 0;
  if (flags & O_CREAT) {
    va_list ap;
    va_start(ap, flags);
    mode = va_arg(ap, mode_t);
    va_end(ap);
  }
  init_once();
  if (!gate(path)) { errno = EIO; return -1; }
  return real_open(path, flags, mode);
}

int open64(const char* path, int flags, ...) {
  mode_t mode = 0;
  if (flags & O_CREAT) {
    va_list ap;
    va_start(ap, flags);
    mode = va_arg(ap, mode_t);
    va_end(ap);
  }
  init_once();
  if (!gate(path)) { errno = EIO; return -1; }
  return (real_open64 != nullptr ? real_open64 : real_open)(path, flags,
                                                            mode);
}

int openat(int dirfd, const char* path, int flags, ...) {
  mode_t mode = 0;
  if (flags & O_CREAT) {
    va_list ap;
    va_start(ap, flags);
    mode = va_arg(ap, mode_t);
    va_end(ap);
  }
  init_once();
  // only absolute paths can match a bundle root; AT_FDCWD-relative opens
  // of bundle files come through as absolute from CPython
  if (path[0] == '/' && !gate(path)) {
    errno = EIO;
    return -1;
  }
  return real_openat(dirfd, path, flags, mode);
}

int openat64(int dirfd, const char* path, int flags, ...) {
  mode_t mode = 0;
  if (flags & O_CREAT) {
    va_list ap;
    va_start(ap, flags);
    mode = va_arg(ap, mode_t);
    va_end(ap);
  }
  init_once();
  if (path[0] == '/' && !gate(path)) {
    errno = EIO;
    return -1;
  }
  return (real_openat64 != nullptr ? real_openat64 : real_openat)(
      dirfd, path, flags, mode);
}

FILE* fopen(const char* path, const char* mode) {
  init_once();
  if (!gate(path)) { errno = EIO; return nullptr; }
  return real_fopen(path, mode);
}

FILE* fopen64(const char* path, const char* mode) {
  init_once();
  if (!gate(path)) { errno = EIO; return nullptr; }
  return (real_fopen64 != nullptr ? real_fopen64 : real_fopen)(path, mode);
}

}  // extern "C"
