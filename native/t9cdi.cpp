// t9cdi — TPU Container Device Interface spec generator.
//
// Reference analogue: the forked nvidia-container-toolkit the reference
// drives for CDI spec generation + sanitization
// (pkg/worker/nvidia.go:92-203, docker/Dockerfile.worker:135-153). TPU
// hosts have no nvidia-ctk equivalent, so tpu9 ships its own: enumerate
// the host's TPU device nodes (/dev/accel*, /dev/vfio/*), locate
// libtpu.so, and emit a CDI v0.6.0 JSON spec that any CDI-aware runtime
// (containerd, CRI-O, podman, runc via spec injection) can use to hand
// chips to containers — the k8s-native deployment path for tpu9 workers.
//
// Devices emitted:
//   tpu9.dev/accel=<N>   one per chip (device node + env)
//   tpu9.dev/accel=all   every chip + libtpu mount + topology env
//
// Usage:
//   t9cdi [--dev-root DIR] [--libtpu PATH] [--out FILE]
//
// --dev-root substitutes the /dev prefix (tests enumerate a fake tree);
// default output is stdout (operators typically redirect to
// /etc/cdi/tpu9.json).

#include <algorithm>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

#include <dirent.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

std::vector<std::string> list_dir(const std::string& dir) {
  std::vector<std::string> out;
  DIR* d = opendir(dir.c_str());
  if (d == nullptr) return out;
  struct dirent* e;
  while ((e = readdir(d)) != nullptr) {
    std::string name = e->d_name;
    if (name != "." && name != "..") out.push_back(name);
  }
  closedir(d);
  std::sort(out.begin(), out.end());
  return out;
}

// chips-per-process bounds for common per-host chip counts — MUST match
// tpu9/worker/tpu_manager.py::_bounds_for (the worker-injected contract;
// t9cdi exists for k8s/containerd hosts where the Python worker is not
// the one mounting devices, but the env the container sees must agree)
std::string bounds_for(size_t chips) {
  switch (chips) {
    case 1: return "1,1,1";
    case 2: return "1,2,1";
    case 4: return "2,2,1";
    case 8: return "2,4,1";
    default: return std::to_string(chips) + ",1,1";
  }
}

bool exists(const std::string& p) {
  struct stat st;
  return stat(p.c_str(), &st) == 0;
}

std::string json_escape(const std::string& s) {
  std::string out;
  for (char c : s) {
    if (c == '"' || c == '\\') out += '\\';
    out += c;
  }
  return out;
}

struct Ctx {
  std::string dev_root = "/dev";
  std::string libtpu;
  // (chip_id, device_path): chip ids come from the node's numeric suffix,
  // NOT the enumeration index — a host with a failed chip (accel0+accel2)
  // must map TPU_VISIBLE_CHIPS to the right nodes
  std::vector<std::pair<int, std::string>> chips;
  std::vector<std::string> vfio;       // vfio group paths
};

void emit_device_node(std::string& out, const std::string& path,
                      bool last) {
  out += "        {\"path\": \"" + json_escape(path) + "\"}";
  out += last ? "\n" : ",\n";
}

std::string emit(const Ctx& ctx) {
  std::string out;
  out += "{\n";
  out += "  \"cdiVersion\": \"0.6.0\",\n";
  out += "  \"kind\": \"tpu9.dev/accel\",\n";
  out += "  \"devices\": [\n";

  // one CDI device per chip (named by the chip's real id)
  for (auto& [chip_id, path] : ctx.chips) {
    out += "    {\n";
    out += "      \"name\": \"" + std::to_string(chip_id) + "\",\n";
    out += "      \"containerEdits\": {\n";
    out += "        \"deviceNodes\": [\n";
    out += "          {\"path\": \"" + json_escape(path) + "\"}\n";
    out += "        ],\n";
    out += "        \"env\": [\n";
    out += "          \"TPU_VISIBLE_CHIPS=" + std::to_string(chip_id)
           + "\",\n";
    out += "          \"TPU_CHIPS_PER_PROCESS_BOUNDS=1,1,1\",\n";
    out += "          \"TPU_PROCESS_BOUNDS=1,1,1\",\n";
    out += "          \"TPU_SKIP_MDS_QUERY=1\",\n";
    out += "          \"PJRT_DEVICE=TPU\"\n";
    out += "        ]\n";
    out += "      }\n";
    out += "    },\n";
  }

  // "all": the whole host slice (the common serving shape)
  out += "    {\n";
  out += "      \"name\": \"all\",\n";
  out += "      \"containerEdits\": {\n";
  out += "        \"deviceNodes\": [\n";
  {
    std::vector<std::string> nodes;
    for (auto& [id, path] : ctx.chips) nodes.push_back(path);
    nodes.insert(nodes.end(), ctx.vfio.begin(), ctx.vfio.end());
    for (size_t i = 0; i < nodes.size(); i++)
      emit_device_node(out, nodes[i], i + 1 == nodes.size());
  }
  out += "        ],\n";
  std::string chips;
  for (size_t i = 0; i < ctx.chips.size(); i++) {
    if (i) chips += ",";
    chips += std::to_string(ctx.chips[i].first);
  }
  out += "        \"env\": [\n";
  out += "          \"TPU_VISIBLE_CHIPS=" + chips + "\",\n";
  out += "          \"TPU_CHIPS_PER_PROCESS_BOUNDS="
         + bounds_for(ctx.chips.size()) + "\",\n";
  out += "          \"TPU_PROCESS_BOUNDS=1,1,1\",\n";
  out += "          \"TPU_SKIP_MDS_QUERY=1\",\n";
  out += "          \"PJRT_DEVICE=TPU\"\n";
  out += "        ]";
  if (!ctx.libtpu.empty()) {
    out += ",\n        \"mounts\": [\n";
    out += "          {\"hostPath\": \"" + json_escape(ctx.libtpu)
           + "\", \"containerPath\": \"/usr/lib/libtpu.so\", "
             "\"options\": [\"ro\", \"rbind\"]}\n";
    out += "        ]\n";
  } else {
    out += "\n";
  }
  out += "      }\n";
  out += "    }\n";
  out += "  ]\n";
  out += "}\n";
  return out;
}

}  // namespace

int main(int argc, char** argv) {
  Ctx ctx;
  std::string out_path;
  for (int i = 1; i < argc - 1; i++) {
    if (strcmp(argv[i], "--dev-root") == 0) ctx.dev_root = argv[++i];
    else if (strcmp(argv[i], "--libtpu") == 0) ctx.libtpu = argv[++i];
    else if (strcmp(argv[i], "--out") == 0) out_path = argv[++i];
  }

  // chips: /dev/accel<N> (TPU VM runtime), numerically keyed by suffix
  for (const auto& name : list_dir(ctx.dev_root)) {
    if (name.rfind("accel", 0) == 0 && name.size() > 5 &&
        name.find_first_not_of("0123456789", 5) == std::string::npos)
      ctx.chips.emplace_back(atoi(name.c_str() + 5),
                             ctx.dev_root + "/" + name);
  }
  std::sort(ctx.chips.begin(), ctx.chips.end());
  // vfio groups (v5p+ runtimes expose chips through vfio)
  std::string vfio_dir = ctx.dev_root + "/vfio";
  for (const auto& name : list_dir(vfio_dir))
    ctx.vfio.push_back(vfio_dir + "/" + name);
  if (ctx.chips.empty() && !ctx.vfio.empty()) {
    // vfio-only host (same fallback as tpu_manager._inventory): the vfio
    // groups ARE the chips
    int i = 0;
    for (const auto& name : list_dir(vfio_dir))
      if (name != "vfio")
        ctx.chips.emplace_back(i++, vfio_dir + "/" + name);
  }
  if (ctx.chips.empty()) {
    fprintf(stderr, "t9cdi: no TPU devices under %s — refusing to write "
                    "an empty spec\n", ctx.dev_root.c_str());
    return 2;
  }

  if (ctx.libtpu.empty()) {
    for (const char* cand :
         {"/usr/lib/libtpu.so", "/usr/local/lib/libtpu.so",
          "/lib/libtpu.so"}) {
      if (exists(cand)) {
        ctx.libtpu = cand;
        break;
      }
    }
  }

  std::string spec = emit(ctx);
  if (out_path.empty()) {
    fwrite(spec.data(), 1, spec.size(), stdout);
  } else {
    FILE* f = fopen(out_path.c_str(), "w");
    if (f == nullptr) {
      perror("t9cdi: open --out");
      return 111;
    }
    size_t wrote = fwrite(spec.data(), 1, spec.size(), f);
    if (wrote != spec.size() || fclose(f) != 0) {
      perror("t9cdi: write --out");
      unlink(out_path.c_str());   // never leave a truncated spec behind
      return 111;
    }
  }
  fprintf(stderr, "t9cdi: %zu chips, %zu vfio groups, libtpu=%s\n",
          ctx.chips.size(), ctx.vfio.size(),
          ctx.libtpu.empty() ? "(none)" : ctx.libtpu.c_str());
  return 0;
}
