// t9proc — minimal PID-1 process supervisor for tpu9 sandbox containers.
//
// Reference analogue: the external beam-cloud/goproc binary the reference
// bind-mounts as sandbox PID 1 (pkg/worker/lifecycle.go:1299-1325) and talks
// to over gRPC. t9proc speaks newline-delimited JSON on stdin/stdout (no
// proto toolchain needed inside minimal containers):
//
//   → {"op": "spawn", "id": "t1", "argv": ["sh", "-c", "echo hi"]}
//   ← {"event": "spawned", "id": "t1", "pid": 123}
//   ← {"event": "stdout", "id": "t1", "data": "hi\n"}
//   ← {"event": "exit", "id": "t1", "code": 0}
//   → {"op": "signal", "id": "t1", "signum": 15}
//   → {"op": "list"}
//   ← {"event": "list", "procs": [{"id": "t1", "pid": 123}]}
//
// As PID 1 it also reaps orphaned zombies (the classic init duty containers
// need). JSON parsing is a tiny purpose-built scanner — inputs come from the
// trusted worker, not end users.

#include <cerrno>
#include <csignal>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <map>
#include <string>
#include <vector>

#include <fcntl.h>
#include <poll.h>
#include <sys/types.h>
#include <sys/wait.h>
#include <unistd.h>

namespace {

struct Proc {
  pid_t pid = -1;
  int out_fd = -1;
  std::string id;
};

std::map<std::string, Proc> procs;       // id -> proc
std::map<int, std::string> fd_to_id;     // stdout fd -> id

void emit(const std::string& line) {
  fputs(line.c_str(), stdout);
  fputc('\n', stdout);
  fflush(stdout);
}

std::string json_escape(const std::string& s) {
  std::string out;
  for (char c : s) {
    switch (c) {
      case '"': out += "\\\""; break;
      case '\\': out += "\\\\"; break;
      case '\n': out += "\\n"; break;
      case '\r': out += "\\r"; break;
      case '\t': out += "\\t"; break;
      default:
        if (static_cast<unsigned char>(c) < 0x20) {
          char buf[8];
          snprintf(buf, sizeof buf, "\\u%04x", c);
          out += buf;
        } else {
          out += c;
        }
    }
  }
  return out;
}

// --- minimal JSON field extraction (flat objects, string/array values) ----

std::string get_string(const std::string& line, const std::string& key) {
  std::string pat = "\"" + key + "\"";
  size_t k = line.find(pat);
  if (k == std::string::npos) return "";
  size_t q1 = line.find('"', line.find(':', k + pat.size()));
  if (q1 == std::string::npos) return "";
  std::string out;
  for (size_t i = q1 + 1; i < line.size(); ++i) {
    char c = line[i];
    if (c == '\\' && i + 1 < line.size()) {
      char n = line[++i];
      out += (n == 'n') ? '\n' : (n == 't') ? '\t' : n;
    } else if (c == '"') {
      return out;
    } else {
      out += c;
    }
  }
  return out;
}

long get_number(const std::string& line, const std::string& key, long dflt) {
  std::string pat = "\"" + key + "\"";
  size_t k = line.find(pat);
  if (k == std::string::npos) return dflt;
  size_t colon = line.find(':', k + pat.size());
  if (colon == std::string::npos) return dflt;
  return strtol(line.c_str() + colon + 1, nullptr, 10);
}

std::vector<std::string> get_array(const std::string& line,
                                   const std::string& key) {
  std::vector<std::string> out;
  std::string pat = "\"" + key + "\"";
  size_t k = line.find(pat);
  if (k == std::string::npos) return out;
  size_t open = line.find('[', k);
  if (open == std::string::npos) return out;
  size_t i = open + 1;
  while (i < line.size() && line[i] != ']') {
    if (line[i] == '"') {
      std::string item;
      ++i;
      while (i < line.size() && line[i] != '"') {
        if (line[i] == '\\' && i + 1 < line.size()) {
          char n = line[++i];
          item += (n == 'n') ? '\n' : (n == 't') ? '\t' : n;
        } else {
          item += line[i];
        }
        ++i;
      }
      out.push_back(item);
    }
    ++i;
  }
  return out;
}

// --- ops ------------------------------------------------------------------

void do_spawn(const std::string& line) {
  std::string id = get_string(line, "id");
  std::vector<std::string> argv = get_array(line, "argv");
  if (id.empty() || argv.empty()) {
    emit("{\"event\": \"error\", \"message\": \"spawn needs id and argv\"}");
    return;
  }
  if (procs.count(id) != 0) {
    emit("{\"event\": \"error\", \"id\": \"" + json_escape(id) +
         "\", \"message\": \"id in use\"}");
    return;
  }
  int pipefd[2];
  if (pipe(pipefd) != 0) {
    emit("{\"event\": \"error\", \"message\": \"pipe failed\"}");
    return;
  }
  pid_t pid = fork();
  if (pid == 0) {
    close(pipefd[0]);
    dup2(pipefd[1], STDOUT_FILENO);
    dup2(pipefd[1], STDERR_FILENO);
    close(pipefd[1]);
    std::vector<char*> cargv;
    for (auto& a : argv) cargv.push_back(const_cast<char*>(a.c_str()));
    cargv.push_back(nullptr);
    execvp(cargv[0], cargv.data());
    fprintf(stderr, "exec failed: %s\n", strerror(errno));
    _exit(127);
  }
  close(pipefd[1]);
  fcntl(pipefd[0], F_SETFL, O_NONBLOCK);
  Proc p;
  p.pid = pid;
  p.out_fd = pipefd[0];
  p.id = id;
  procs[id] = p;
  fd_to_id[pipefd[0]] = id;
  char buf[160];
  snprintf(buf, sizeof buf, "{\"event\": \"spawned\", \"id\": \"%s\", \"pid\": %d}",
           json_escape(id).c_str(), pid);
  emit(buf);
}

void do_signal(const std::string& line) {
  std::string id = get_string(line, "id");
  long signum = get_number(line, "signum", SIGTERM);
  auto it = procs.find(id);
  if (it == procs.end()) {
    emit("{\"event\": \"error\", \"id\": \"" + json_escape(id) +
         "\", \"message\": \"unknown id\"}");
    return;
  }
  kill(it->second.pid, static_cast<int>(signum));
  emit("{\"event\": \"signaled\", \"id\": \"" + json_escape(id) + "\"}");
}

void do_list() {
  std::string out = "{\"event\": \"list\", \"procs\": [";
  bool first = true;
  for (auto& kv : procs) {
    if (!first) out += ",";
    first = false;
    out += "{\"id\": \"" + json_escape(kv.first) + "\", \"pid\": " +
           std::to_string(kv.second.pid) + "}";
  }
  out += "]}";
  emit(out);
}

void pump_fd(int fd) {
  char buf[4096];
  ssize_t n;
  while ((n = read(fd, buf, sizeof buf)) > 0) {
    auto it = fd_to_id.find(fd);
    if (it == fd_to_id.end()) continue;
    emit("{\"event\": \"stdout\", \"id\": \"" + json_escape(it->second) +
         "\", \"data\": \"" + json_escape(std::string(buf, n)) + "\"}");
  }
}

void reap() {
  int status;
  pid_t pid;
  while ((pid = waitpid(-1, &status, WNOHANG)) > 0) {
    for (auto it = procs.begin(); it != procs.end(); ++it) {
      if (it->second.pid != pid) continue;
      pump_fd(it->second.out_fd);  // drain trailing output
      int code = WIFEXITED(status) ? WEXITSTATUS(status)
                                   : 128 + WTERMSIG(status);
      emit("{\"event\": \"exit\", \"id\": \"" + json_escape(it->first) +
           "\", \"code\": " + std::to_string(code) + "}");
      close(it->second.out_fd);
      fd_to_id.erase(it->second.out_fd);
      procs.erase(it);
      break;
    }
    // unknown pids (orphans re-parented to PID 1) are silently reaped
  }
}

}  // namespace

int main() {
  signal(SIGPIPE, SIG_IGN);
  emit("{\"event\": \"ready\", \"pid\": " + std::to_string(getpid()) + "}");

  std::string inbuf;
  char chunk[4096];
  bool stdin_open = true;
  while (stdin_open || !procs.empty()) {
    std::vector<pollfd> fds;
    if (stdin_open) fds.push_back({STDIN_FILENO, POLLIN, 0});
    for (auto& kv : procs) fds.push_back({kv.second.out_fd, POLLIN, 0});
    int rc = poll(fds.data(), fds.size(), 200);
    if (rc > 0) {
      for (auto& pfd : fds) {
        if (!(pfd.revents & (POLLIN | POLLHUP))) continue;
        if (pfd.fd == STDIN_FILENO) {
          ssize_t n = read(STDIN_FILENO, chunk, sizeof chunk);
          if (n <= 0) {
            stdin_open = false;
            continue;
          }
          inbuf.append(chunk, n);
          size_t nl;
          while ((nl = inbuf.find('\n')) != std::string::npos) {
            std::string line = inbuf.substr(0, nl);
            inbuf.erase(0, nl + 1);
            std::string op = get_string(line, "op");
            if (op == "spawn") do_spawn(line);
            else if (op == "signal") do_signal(line);
            else if (op == "list") do_list();
            else if (op == "shutdown") { stdin_open = false; }
            else if (!line.empty())
              emit("{\"event\": \"error\", \"message\": \"unknown op\"}");
          }
        } else {
          pump_fd(pfd.fd);
        }
      }
    }
    reap();
  }
  return 0;
}
