// t9proc — minimal PID-1 process supervisor for tpu9 sandbox containers.
//
// Reference analogue: the external beam-cloud/goproc binary the reference
// bind-mounts as sandbox PID 1 (pkg/worker/lifecycle.go:1299-1325) and talks
// to over gRPC. t9proc speaks newline-delimited JSON on stdin/stdout (no
// proto toolchain needed inside minimal containers):
//
//   → {"op": "spawn", "id": "t1", "argv": ["sh", "-c", "echo hi"]}
//   ← {"event": "spawned", "id": "t1", "pid": 123}
//   ← {"event": "stdout", "id": "t1", "data": "hi\n"}
//   ← {"event": "exit", "id": "t1", "code": 0}
//   → {"op": "signal", "id": "t1", "signum": 15}
//   → {"op": "list"}
//   ← {"event": "list", "procs": [{"id": "t1", "pid": 123}]}
//
// As PID 1 it also reaps orphaned zombies (the classic init duty containers
// need). JSON parsing is a tiny purpose-built scanner — inputs come from the
// trusted worker, not end users.
//
// Modes:
//   t9proc                    — stdio protocol (exits when stdin closes)
//   t9proc --sock PATH        — PID-1 mode: listens on a unix socket, the
//                               worker (re)connects across its own restarts;
//                               runs until SIGTERM (kills children first).
//                               Process stdout/stdin payloads ride base64
//                               (`data_b64`) so binary output can't corrupt
//                               the JSON framing.

#include <cerrno>
#include <csignal>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <map>
#include <string>
#include <vector>

#include <fcntl.h>
#include <poll.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <sys/un.h>
#include <sys/wait.h>
#include <unistd.h>

namespace {

struct Proc {
  pid_t pid = -1;
  int out_fd = -1;
  int in_fd = -1;                        // child stdin (write end)
  std::string id;
};

std::map<std::string, Proc> procs;       // id -> proc
std::map<int, std::string> fd_to_id;     // stdout fd -> id
int g_ctrl_out = STDOUT_FILENO;          // control channel (stdout or conn)

void emit(const std::string& line) {
  std::string buf = line + "\n";
  size_t off = 0;
  while (off < buf.size()) {
    ssize_t n = write(g_ctrl_out, buf.data() + off, buf.size() - off);
    if (n <= 0) {
      if (errno == EINTR) continue;
      return;                            // client gone; drop the event
    }
    off += static_cast<size_t>(n);
  }
}

// --- base64 (binary-safe stdout/stdin payloads) ---------------------------

const char kB64[] =
    "ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789+/";

std::string b64_encode(const char* data, size_t n) {
  std::string out;
  out.reserve((n + 2) / 3 * 4);
  for (size_t i = 0; i < n; i += 3) {
    unsigned v = static_cast<unsigned char>(data[i]) << 16;
    if (i + 1 < n) v |= static_cast<unsigned char>(data[i + 1]) << 8;
    if (i + 2 < n) v |= static_cast<unsigned char>(data[i + 2]);
    out += kB64[(v >> 18) & 63];
    out += kB64[(v >> 12) & 63];
    out += (i + 1 < n) ? kB64[(v >> 6) & 63] : '=';
    out += (i + 2 < n) ? kB64[v & 63] : '=';
  }
  return out;
}

int b64_val(char c) {
  if (c >= 'A' && c <= 'Z') return c - 'A';
  if (c >= 'a' && c <= 'z') return c - 'a' + 26;
  if (c >= '0' && c <= '9') return c - '0' + 52;
  if (c == '+') return 62;
  if (c == '/') return 63;
  return -1;
}

std::string b64_decode(const std::string& s) {
  std::string out;
  int acc = 0, bits = 0;
  for (char c : s) {
    int v = b64_val(c);
    if (v < 0) continue;
    acc = (acc << 6) | v;
    bits += 6;
    if (bits >= 8) {
      bits -= 8;
      out += static_cast<char>((acc >> bits) & 0xFF);
    }
  }
  return out;
}

std::string json_escape(const std::string& s) {
  std::string out;
  for (char c : s) {
    switch (c) {
      case '"': out += "\\\""; break;
      case '\\': out += "\\\\"; break;
      case '\n': out += "\\n"; break;
      case '\r': out += "\\r"; break;
      case '\t': out += "\\t"; break;
      default:
        if (static_cast<unsigned char>(c) < 0x20) {
          char buf[8];
          snprintf(buf, sizeof buf, "\\u%04x", c);
          out += buf;
        } else {
          out += c;
        }
    }
  }
  return out;
}

// --- minimal JSON field extraction (flat objects, string/array values) ----

std::string get_string(const std::string& line, const std::string& key) {
  std::string pat = "\"" + key + "\"";
  size_t k = line.find(pat);
  if (k == std::string::npos) return "";
  size_t q1 = line.find('"', line.find(':', k + pat.size()));
  if (q1 == std::string::npos) return "";
  std::string out;
  for (size_t i = q1 + 1; i < line.size(); ++i) {
    char c = line[i];
    if (c == '\\' && i + 1 < line.size()) {
      char n = line[++i];
      out += (n == 'n') ? '\n' : (n == 't') ? '\t' : n;
    } else if (c == '"') {
      return out;
    } else {
      out += c;
    }
  }
  return out;
}

long get_number(const std::string& line, const std::string& key, long dflt) {
  std::string pat = "\"" + key + "\"";
  size_t k = line.find(pat);
  if (k == std::string::npos) return dflt;
  size_t colon = line.find(':', k + pat.size());
  if (colon == std::string::npos) return dflt;
  return strtol(line.c_str() + colon + 1, nullptr, 10);
}

std::vector<std::string> get_array(const std::string& line,
                                   const std::string& key) {
  std::vector<std::string> out;
  std::string pat = "\"" + key + "\"";
  size_t k = line.find(pat);
  if (k == std::string::npos) return out;
  size_t open = line.find('[', k);
  if (open == std::string::npos) return out;
  size_t i = open + 1;
  while (i < line.size() && line[i] != ']') {
    if (line[i] == '"') {
      std::string item;
      ++i;
      while (i < line.size() && line[i] != '"') {
        if (line[i] == '\\' && i + 1 < line.size()) {
          char n = line[++i];
          item += (n == 'n') ? '\n' : (n == 't') ? '\t' : n;
        } else {
          item += line[i];
        }
        ++i;
      }
      out.push_back(item);
    }
    ++i;
  }
  return out;
}

// --- ops ------------------------------------------------------------------

void do_spawn(const std::string& line) {
  std::string id = get_string(line, "id");
  std::vector<std::string> argv = get_array(line, "argv");
  if (id.empty() || argv.empty()) {
    emit("{\"event\": \"error\", \"message\": \"spawn needs id and argv\"}");
    return;
  }
  if (procs.count(id) != 0) {
    emit("{\"event\": \"error\", \"id\": \"" + json_escape(id) +
         "\", \"message\": \"id in use\"}");
    return;
  }
  int pipefd[2];
  int infd[2];
  if (pipe(pipefd) != 0 || pipe(infd) != 0) {
    emit("{\"event\": \"error\", \"message\": \"pipe failed\"}");
    return;
  }
  pid_t pid = fork();
  if (pid == 0) {
    close(pipefd[0]);
    close(infd[1]);
    dup2(infd[0], STDIN_FILENO);
    dup2(pipefd[1], STDOUT_FILENO);
    dup2(pipefd[1], STDERR_FILENO);
    close(pipefd[1]);
    close(infd[0]);
    std::vector<char*> cargv;
    for (auto& a : argv) cargv.push_back(const_cast<char*>(a.c_str()));
    cargv.push_back(nullptr);
    execvp(cargv[0], cargv.data());
    fprintf(stderr, "exec failed: %s\n", strerror(errno));
    _exit(127);
  }
  close(pipefd[1]);
  close(infd[0]);
  fcntl(pipefd[0], F_SETFL, O_NONBLOCK);
  // stdin writes must never block the single-threaded PID-1 loop: a child
  // that ignores stdin would otherwise wedge every proc in the container
  fcntl(infd[1], F_SETFL, O_NONBLOCK);
  Proc p;
  p.pid = pid;
  p.out_fd = pipefd[0];
  p.in_fd = infd[1];
  p.id = id;
  procs[id] = p;
  fd_to_id[pipefd[0]] = id;
  char buf[160];
  snprintf(buf, sizeof buf, "{\"event\": \"spawned\", \"id\": \"%s\", \"pid\": %d}",
           json_escape(id).c_str(), pid);
  emit(buf);
}

void do_stdin(const std::string& line) {
  std::string id = get_string(line, "id");
  auto it = procs.find(id);
  if (it == procs.end()) {
    emit("{\"event\": \"error\", \"id\": \"" + json_escape(id) +
         "\", \"message\": \"unknown id\"}");
    return;
  }
  std::string data = b64_decode(get_string(line, "data_b64"));
  if (get_number(line, "eof", 0) == 1) {
    if (it->second.in_fd >= 0) {
      close(it->second.in_fd);
      it->second.in_fd = -1;
    }
    emit("{\"event\": \"stdin_ok\", \"id\": \"" + json_escape(id) + "\"}");
    return;
  }
  if (it->second.in_fd < 0) {
    emit("{\"event\": \"error\", \"id\": \"" + json_escape(id) +
         "\", \"message\": \"stdin closed\"}");
    return;
  }
  size_t off = 0;
  bool backpressure = false;
  while (off < data.size()) {
    ssize_t n = write(it->second.in_fd, data.data() + off,
                      data.size() - off);
    if (n > 0) {
      off += static_cast<size_t>(n);
    } else if (n < 0 && errno == EINTR) {
      continue;
    } else {
      // EAGAIN: pipe full because the child isn't reading. Dropping with
      // an explicit error beats wedging every proc in the container.
      backpressure = true;
      break;
    }
  }
  if (backpressure)
    emit("{\"event\": \"error\", \"id\": \"" + json_escape(id) +
         "\", \"message\": \"stdin backpressure: child not reading (" +
         std::to_string(data.size() - off) + " bytes dropped)\"}");
  else
    emit("{\"event\": \"stdin_ok\", \"id\": \"" + json_escape(id) + "\"}");
}

void do_signal(const std::string& line) {
  std::string id = get_string(line, "id");
  long signum = get_number(line, "signum", SIGTERM);
  auto it = procs.find(id);
  if (it == procs.end()) {
    emit("{\"event\": \"error\", \"id\": \"" + json_escape(id) +
         "\", \"message\": \"unknown id\"}");
    return;
  }
  kill(it->second.pid, static_cast<int>(signum));
  emit("{\"event\": \"signaled\", \"id\": \"" + json_escape(id) + "\"}");
}

void do_list() {
  std::string out = "{\"event\": \"list\", \"procs\": [";
  bool first = true;
  for (auto& kv : procs) {
    if (!first) out += ",";
    first = false;
    out += "{\"id\": \"" + json_escape(kv.first) + "\", \"pid\": " +
           std::to_string(kv.second.pid) + "}";
  }
  out += "]}";
  emit(out);
}

void pump_fd(int fd) {
  char buf[4096];
  ssize_t n;
  while ((n = read(fd, buf, sizeof buf)) > 0) {
    auto it = fd_to_id.find(fd);
    if (it == fd_to_id.end()) continue;
    emit("{\"event\": \"stdout\", \"id\": \"" + json_escape(it->second) +
         "\", \"data_b64\": \"" + b64_encode(buf, n) + "\"}");
  }
}

void reap() {
  int status;
  pid_t pid;
  while ((pid = waitpid(-1, &status, WNOHANG)) > 0) {
    for (auto it = procs.begin(); it != procs.end(); ++it) {
      if (it->second.pid != pid) continue;
      pump_fd(it->second.out_fd);  // drain trailing output
      int code = WIFEXITED(status) ? WEXITSTATUS(status)
                                   : 128 + WTERMSIG(status);
      emit("{\"event\": \"exit\", \"id\": \"" + json_escape(it->first) +
           "\", \"code\": " + std::to_string(code) + "}");
      close(it->second.out_fd);
      if (it->second.in_fd >= 0) close(it->second.in_fd);
      fd_to_id.erase(it->second.out_fd);
      procs.erase(it);
      break;
    }
    // unknown pids (orphans re-parented to PID 1) are silently reaped
  }
}

bool g_shutdown = false;

// returns false on a shutdown op
bool handle_line(const std::string& line) {
  std::string op = get_string(line, "op");
  if (op == "spawn") do_spawn(line);
  else if (op == "signal") do_signal(line);
  else if (op == "stdin") do_stdin(line);
  else if (op == "list") do_list();
  else if (op == "shutdown") return false;
  else if (!line.empty())
    emit("{\"event\": \"error\", \"message\": \"unknown op\"}");
  return true;
}

void on_term(int) { g_shutdown = true; }

}  // namespace

int main(int argc, char** argv) {
  signal(SIGPIPE, SIG_IGN);
  // PID 1 in a pid namespace ignores signals without handlers — install
  // one so a container stop (SIGTERM from t9container) actually works
  struct sigaction sa{};
  sa.sa_handler = on_term;
  sigaction(SIGTERM, &sa, nullptr);
  sigaction(SIGINT, &sa, nullptr);

  const char* sock_path = nullptr;
  for (int i = 1; i < argc - 1; i++)
    if (strcmp(argv[i], "--sock") == 0) sock_path = argv[i + 1];

  int listen_fd = -1;
  int ctrl_fd = -1;                    // connected worker (sock mode)
  if (sock_path != nullptr) {
    unlink(sock_path);
    listen_fd = socket(AF_UNIX, SOCK_STREAM | SOCK_CLOEXEC, 0);
    struct sockaddr_un addr;
    memset(&addr, 0, sizeof(addr));
    addr.sun_family = AF_UNIX;
    strncpy(addr.sun_path, sock_path, sizeof(addr.sun_path) - 1);
    if (listen_fd < 0 ||
        bind(listen_fd, reinterpret_cast<struct sockaddr*>(&addr),
             sizeof(addr)) != 0 ||
        listen(listen_fd, 4) != 0) {
      fprintf(stderr, "t9proc: socket %s: %s\n", sock_path,
              strerror(errno));
      return 111;
    }
    chmod(sock_path, 0666);
    fprintf(stdout, "t9proc: pid1 ready on %s\n", sock_path);
    fflush(stdout);
    g_ctrl_out = STDOUT_FILENO;        // until a client connects
  } else {
    emit("{\"event\": \"ready\", \"pid\": " + std::to_string(getpid()) +
         "}");
  }

  std::string inbuf;
  char chunk[4096];
  bool stdin_open = (sock_path == nullptr);
  // stdio mode exits when stdin closes and children drain; sock (PID-1)
  // mode runs until SIGTERM
  while (!g_shutdown &&
         (sock_path != nullptr || stdin_open || !procs.empty())) {
    std::vector<pollfd> fds;
    int ctrl_in = -1;
    if (sock_path != nullptr) {
      if (ctrl_fd >= 0) {
        ctrl_in = ctrl_fd;
        fds.push_back({ctrl_fd, POLLIN, 0});
      }
      fds.push_back({listen_fd, POLLIN, 0});
    } else if (stdin_open) {
      ctrl_in = STDIN_FILENO;
      fds.push_back({STDIN_FILENO, POLLIN, 0});
    }
    for (auto& kv : procs) fds.push_back({kv.second.out_fd, POLLIN, 0});
    int rc = poll(fds.data(), fds.size(), 200);
    if (rc > 0) {
      for (auto& pfd : fds) {
        if (!(pfd.revents & (POLLIN | POLLHUP))) continue;
        if (sock_path != nullptr && pfd.fd == listen_fd) {
          int c = accept(listen_fd, nullptr, nullptr);
          if (c >= 0) {
            if (ctrl_fd >= 0) close(ctrl_fd);  // newest client wins
            ctrl_fd = c;
            g_ctrl_out = c;
            inbuf.clear();
          }
          continue;
        }
        if (pfd.fd == ctrl_in) {
          ssize_t n = read(pfd.fd, chunk, sizeof chunk);
          if (n <= 0) {
            if (sock_path != nullptr) {
              close(ctrl_fd);
              ctrl_fd = -1;
              g_ctrl_out = STDOUT_FILENO;   // drop events until reconnect
            } else {
              stdin_open = false;
            }
            continue;
          }
          inbuf.append(chunk, n);
          size_t nl;
          while ((nl = inbuf.find('\n')) != std::string::npos) {
            std::string line = inbuf.substr(0, nl);
            inbuf.erase(0, nl + 1);
            if (!handle_line(line)) {
              if (sock_path == nullptr) stdin_open = false;
              else g_shutdown = true;
            }
          }
        } else {
          pump_fd(pfd.fd);
        }
      }
    }
    reap();
  }
  // PID-1 teardown: no child survives init
  for (auto& kv : procs) kill(kv.second.pid, SIGKILL);
  reap();
  return 0;
}
