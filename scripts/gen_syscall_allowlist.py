#!/usr/bin/env python3
"""Generate t9container's seccomp ALLOW-list from live runner traces.

VERDICT r04 #2: the deny-list's polarity was wrong for multi-tenant
serving — any syscall the list didn't anticipate was allowed. This script
records what tpu9's REAL runner processes (gateway/worker/endpoint/
taskqueue/LLM engine, t9proc, build shells) actually call, using
native/t9trace (a ptrace syscall-set recorder; the image has no strace),
merges a curated robustness margin (glibc variants that differ across
minor versions), REFUSES to allow anything on the never-allow list, and
emits ``native/t9_allowlist.h`` for t9container's allow-mode filter.

Reference analogue: the reference pins its posture to gVisor's
implemented-syscall surface (/root/reference/pkg/runtime/runsc.go:52) and
a hardened base OCI spec (base_runc_config.json); tpu9 pins to a recorded
trace of its own workloads.

Usage:
    python scripts/gen_syscall_allowlist.py [--trace-only OUT.txt]
    python scripts/gen_syscall_allowlist.py --from-traces a.txt b.txt ...
"""

import argparse
import os
import re
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
UNISTD = "/usr/include/x86_64-linux-gnu/asm/unistd_64.h"
HEADER = os.path.join(REPO, "native", "t9_allowlist.h")

# Syscalls that must NEVER be allowed no matter what a trace contains —
# the sandbox-escape / kernel-attack surface (mirrors t9container's
# deny-list plus the clone/unshare special cases handled by dedicated
# BPF rules there).
NEVER_ALLOW = {
    "mount", "umount2", "pivot_root", "chroot", "swapon", "swapoff",
    "reboot", "kexec_load", "kexec_file_load", "init_module",
    "finit_module", "delete_module", "bpf", "ptrace", "process_vm_readv",
    "process_vm_writev", "perf_event_open", "setns", "mknod", "mknodat",
    "open_by_handle_at", "quotactl", "acct", "settimeofday",
    "clock_settime", "clock_adjtime", "adjtimex", "sethostname",
    "setdomainname", "add_key", "request_key", "keyctl", "userfaultfd",
    "vhangup", "nfsservctl", "iopl", "ioperm", "lookup_dcookie",
    "unshare", "io_uring_setup", "io_uring_enter", "io_uring_register",
    "fsopen", "fsconfig", "fsmount", "fspick", "move_mount", "open_tree",
    "mount_setattr", "pidfd_getfd", "kcmp",
    # clone3 passes flags in MEMORY where BPF cannot inspect them — the
    # filter's dedicated rule returns ENOSYS so glibc falls back to clone
    # (whose namespace flags the filter CAN check); it must never appear
    # in the allow array or that rule is bypassed
    "clone3",
}

# Robustness margin: syscalls a runner MAY hit depending on glibc minor
# version, allocator, or library build flags, even if one recorded trace
# missed them. Everything here is harmless inside the sandbox.
CURATED = {
    # process / thread basics and variants
    "restart_syscall", "sched_yield", "sched_getparam", "sched_setparam",
    "sched_getscheduler", "sched_setscheduler", "sched_rr_get_interval",
    "membarrier", "rseq", "set_tid_address", "gettid", "tkill",
    "capget", "waitid", "vfork", "fork", "execveat", "prctl", "kill",
    "tgkill", "sched_getaffinity", "sched_setaffinity", "futex",
    "futex_waitv", "futex_wait", "futex_wake", "futex_requeue",
    "get_robust_list", "set_robust_list", "arch_prctl",
    # scatter/positional io variants glibc rotates between
    "readv", "writev", "pread64", "pwrite64", "preadv", "pwritev",
    "preadv2", "pwritev2",
    # memory
    "mlock", "mlock2", "munlock", "mlockall", "munlockall", "msync",
    "mincore", "mremap", "pkey_alloc",
    "pkey_free", "pkey_mprotect", "madvise", "process_madvise",
    # files — older/newer variants of what python/glibc rotate between.
    # The *at family is what coreutils/tar ACTUALLY issue (mv uses
    # renameat2 and only falls back on ENOSYS, never EPERM — a missing
    # entry here breaks `mv` inside every default container)
    "open", "creat", "access", "faccessat", "faccessat2", "stat", "lstat",
    "chmod", "chown", "lchown", "rename", "mkdir", "rmdir", "unlink",
    "renameat", "renameat2", "mkdirat", "unlinkat", "symlinkat", "linkat",
    "readlinkat", "fchmod", "fchown", "fchmodat", "fchownat", "fchmodat2",
    "pipe", "pipe2", "newfstatat", "fstat", "lseek", "fcntl", "chdir",
    "fchdir", "getcwd",
    "link", "symlink", "readlink", "utime", "utimes", "futimesat",
    "utimensat", "statx", "statfs", "fstatfs", "sync", "syncfs",
    "fsync", "fdatasync", "sync_file_range", "fallocate", "flock",
    "truncate", "ftruncate", "copy_file_range", "splice", "tee",
    "sendfile", "readahead", "fadvise64", "dup", "dup2", "dup3",
    "getdents", "getdents64", "openat2", "close_range",
    # xattrs (pip/tar touch these)
    "getxattr", "lgetxattr", "fgetxattr", "listxattr", "llistxattr",
    "flistxattr", "setxattr", "lsetxattr", "fsetxattr", "removexattr",
    "lremovexattr", "fremovexattr",
    # io multiplexing variants
    "poll", "ppoll", "select", "pselect6", "epoll_create",
    "epoll_create1", "epoll_ctl", "epoll_wait", "epoll_pwait",
    "epoll_pwait2", "eventfd", "eventfd2", "signalfd", "signalfd4",
    "timerfd_create", "timerfd_settime", "timerfd_gettime",
    "pidfd_open", "pidfd_send_signal",
    # aio (numpy/torch data loaders on some builds)
    "io_setup", "io_destroy", "io_submit", "io_cancel", "io_getevents",
    # sockets — full client/server set (runners serve HTTP and dial peers)
    "socket", "socketpair", "bind", "listen", "accept", "accept4",
    "connect", "getsockname", "getpeername", "sendto", "recvfrom",
    "sendmsg", "recvmsg", "sendmmsg", "recvmmsg", "shutdown",
    "getsockopt", "setsockopt",
    # signals / timers / clocks
    "alarm", "pause", "getitimer", "setitimer", "timer_create",
    "timer_settime", "timer_gettime", "timer_getoverrun", "timer_delete",
    "clock_gettime", "clock_getres", "clock_nanosleep", "nanosleep",
    "sigaltstack", "rt_sigqueueinfo", "rt_tgsigqueueinfo",
    # identity / limits / info
    "getuid", "geteuid", "getgid", "getegid", "getgroups", "setgroups",
    "setuid", "setgid", "setreuid", "setregid", "setresuid", "setresgid",
    "getresuid", "getresgid", "setfsuid", "setfsgid", "getpgid",
    "setpgid", "getpgrp", "setsid", "getsid", "getrusage", "times",
    "sysinfo", "uname", "getcpu", "getpriority", "setpriority",
    "prlimit64", "getrlimit", "setrlimit", "umask", "getrandom",
    "memfd_create", "personality",
    # terminal (shells inside build containers)
    "ioctl",
}


def syscall_table() -> dict[int, str]:
    table: dict[int, str] = {}
    with open(UNISTD) as f:
        for line in f:
            m = re.match(r"#define __NR_(\w+)\s+(\d+)", line)
            if m:
                table[int(m.group(2))] = m.group(1)
    if not table:
        raise SystemExit(f"no syscalls parsed from {UNISTD}")
    return table


def build_tracer() -> str:
    out = os.path.join(REPO, "native", "build", "t9trace")
    src = os.path.join(REPO, "native", "t9trace.cpp")
    if (not os.path.exists(out)
            or os.path.getmtime(out) < os.path.getmtime(src)):
        os.makedirs(os.path.dirname(out), exist_ok=True)
        subprocess.run(["g++", "-O2", "-Wall", "-std=c++17", "-o", out, src],
                       check=True)
    return out


# The workloads whose union defines "what runners do". CPU-forced e2e
# suites drive the real gateway/worker/runner processes (ProcessRuntime —
# same Python, no namespaces, so the trace has no mount/pivot noise).
WORKLOADS = [
    [sys.executable, "-m", "pytest", "tests/test_e2e_endpoint.py",
     "tests/test_e2e_tasks.py", "-x", "-q", "--no-header", "-p",
     "no:cacheprovider"],
    [sys.executable, "-m", "pytest", "tests/test_e2e_llm.py", "-x", "-q",
     "--no-header", "-p", "no:cacheprovider"],
    ["sh", "-c", "ls /tmp >/dev/null && cat /etc/os-release >/dev/null "
     "&& head -c 16 /dev/urandom >/dev/null"],
]


def record(tracer: str, trace_path: str) -> None:
    # FULL tier: the slow-marked LLM e2e tests exercise runner syscall
    # surface the default tier skips
    env = dict(os.environ, JAX_PLATFORMS="cpu", TPU9_FULL_SUITE="1")
    for cmd in WORKLOADS:
        print(f"[gen_allowlist] tracing: {' '.join(cmd[:6])} ...",
              flush=True)
        r = subprocess.run([tracer, trace_path, "--"] + cmd, cwd=REPO,
                           env=env)
        if r.returncode != 0:
            raise SystemExit(
                f"traced workload failed rc={r.returncode}: {cmd}")
    # t9proc supervisor (runs as the in-container PID 1)
    t9proc = os.path.join(REPO, "native", "build", "t9proc")
    if os.path.exists(t9proc):
        subprocess.run([tracer, trace_path, "--", t9proc, "--",
                        "sh", "-c", "echo t9proc-traced"], cwd=REPO)


def emit(numbers: set[int]) -> None:
    table = syscall_table()
    names = {table[n] for n in numbers if n in table}
    unknown = sorted(n for n in numbers if n not in table)
    if unknown:
        print(f"[gen_allowlist] WARNING: {len(unknown)} traced numbers "
              f"not in {UNISTD}: {unknown}", flush=True)
    traced_denied = sorted(names & NEVER_ALLOW)
    if traced_denied:
        print(f"[gen_allowlist] dropping never-allow syscalls seen in "
              f"trace: {traced_denied}", flush=True)
    allowed = sorted((names | CURATED) - NEVER_ALLOW)
    # without these nothing can start inside the filter — refuse to emit
    # an allowlist that bricks every container
    missing = [s for s in ("execve", "exit", "exit_group", "clone")
               if s not in allowed]
    if missing:
        raise SystemExit(
            f"generated allowlist is missing {missing} — trace is broken")

    # JSON twin for the RuncRuntime's OCI seccomp profile (same policy,
    # different wire format — runc consumes JSON, t9container a C header)
    with open(HEADER.replace(".h", ".json"), "w") as f:
        json.dump({"allow": allowed,
                   "never_allow": sorted(NEVER_ALLOW)}, f, indent=1)
        f.write("\n")

    with open(HEADER, "w") as f:
        f.write(
            "// t9_allowlist.h — GENERATED by scripts/"
            "gen_syscall_allowlist.py.\n"
            "// Seccomp ALLOW-list for t9container's default filter "
            "(VERDICT r04 #2):\n"
            "// union of live runner traces (endpoint/taskqueue/LLM e2e, "
            "t9proc, build\n"
            "// shells) plus a curated glibc-variant margin; the "
            "never-allow set is\n"
            "// excluded at generation time and again at runtime by the "
            "deny rules.\n"
            f"// {len(allowed)} syscalls.\n\n")
        for name in allowed:
            f.write(f"#ifdef SYS_{name}\n    SYS_{name},\n#endif\n")
    print(f"[gen_allowlist] wrote {HEADER}: {len(allowed)} syscalls "
          f"({len(names)} traced, {len(set(allowed) - names)} "
          "curated-only)", flush=True)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--trace-only", help="record traces to this file and "
                    "exit (no header generation)")
    ap.add_argument("--from-traces", nargs="+",
                    help="skip recording; merge these trace files")
    args = ap.parse_args()

    if args.from_traces:
        numbers: set[int] = set()
        for path in args.from_traces:
            with open(path) as f:
                numbers.update(int(x) for x in f.read().split())
        emit(numbers)
        return

    tracer = build_tracer()
    trace_path = args.trace_only or tempfile.mktemp(prefix="t9trace-")
    record(tracer, trace_path)
    if args.trace_only:
        print(f"[gen_allowlist] traces in {trace_path}")
        return
    with open(trace_path) as f:
        numbers = {int(x) for x in f.read().split()}
    os.unlink(trace_path)
    emit(numbers)


if __name__ == "__main__":
    main()
