#!/usr/bin/env python3
"""wirecheck ratchet gate (ISSUE 18) — fails on any NEW wire-contract drift.

Tier-1 wiring next to lint_gate.py / graph_gate.py (tests/
test_wirecheck.py runs it): producer/consumer key sets for the
string-keyed wire surfaces (heartbeat fields, tpu9_* metrics, store key
namespaces, TPU9_* env knobs, rpc routes) are AST-extracted and checked
against tpu9/analysis/contracts.toml. Triaged debt lives in
scripts/wire_baseline.json; inline ``# tpu9: noqa[RULE] reason``
suppressions cover reviewed sites; anything else fails CI.

    python scripts/wire_gate.py                    # gate the repo
    python scripts/wire_gate.py --select WIR001 --roots tpu9/serving
    python scripts/wire_gate.py --update-baseline --reason "why"
    python scripts/wire_gate.py --strict-stale     # also fail on stale debt

Exit codes: 0 clean, 1 new findings (or stale with --strict-stale, or
budget exceeded), 2 contract/parse errors.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tpu9.analysis.gatelib import ratchet_main  # noqa: E402
from tpu9.analysis.wirecheck import (DEFAULT_BASELINE,  # noqa: E402
                                     DEFAULT_CONTRACTS, run_wirecheck)


def _run(repo_root, roots, select, args):
    cpath = args.contracts or DEFAULT_CONTRACTS
    if not os.path.isabs(cpath):
        cpath = os.path.join(repo_root, cpath)
    return run_wirecheck(repo_root, roots=roots, select=select,
                         contracts_path=cpath)


def main(argv=None) -> int:
    return ratchet_main(
        "wire_gate", _run, DEFAULT_BASELINE, argv=argv,
        doc=__doc__.splitlines()[0], budget_s=120.0,
        add_args=lambda ap: ap.add_argument(
            "--contracts", default=None,
            help="override contracts.toml (tests)"))


if __name__ == "__main__":
    sys.exit(main())
