#!/usr/bin/env python3
"""Opportunistic TPU bench capture (VERDICT r03 next-round #1b).

The TPU tunnel in this environment flaps: it can be dead for hours (a dead
tunnel hangs ``jax.devices()`` indefinitely) and then come alive. This
watcher probes cheaply in a loop; the moment a probe succeeds it runs the
chip bench phases through bench.py's own orchestration helpers and persists
``BENCH_TPU.json`` in-repo — so a mid-round alive-window is captured even if
the tunnel is dead again by the time the driver runs ``bench.py``.

Usage: python scripts/tpu_opportunist.py [--interval 300] [--once]
"""

import argparse
import importlib.util
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def load_bench():
    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(REPO, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def probe_log(line: str) -> None:
    """Append a timestamped line to the in-repo probe log (VERDICT r05
    "no evidence trail" gap): BENCH_PROBELOG.txt rides along with the
    BENCH artifacts, so every round shows WHEN the tunnel was probed and
    what it answered — a dead-tunnel round is distinguishable from a
    never-probed one."""
    try:
        with open(os.path.join(REPO, "BENCH_PROBELOG.txt"), "a") as f:
            f.write(line + "\n")
    except OSError:
        pass


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--interval", type=float, default=300.0)
    ap.add_argument("--probe-timeout", type=float, default=90.0)
    ap.add_argument("--once", action="store_true")
    ap.add_argument("--quick", action="store_true")
    args = ap.parse_args()

    bench = load_bench()
    attempt = 0
    while True:
        attempt += 1
        alive = bench._tpu_alive(timeout_s=args.probe_timeout)
        stamp = time.strftime("%Y-%m-%d %H:%M:%S")
        print(f"[{stamp}] probe {attempt}: tpu_alive={alive}", flush=True)
        probe_log(f"[{stamp}] probe {attempt}: tpu_alive={alive}")
        if alive:
            detail: dict = {"captured_by": "tpu_opportunist",
                            "captured_at": stamp}
            ok = bench._run_chip_phases(detail, quick=args.quick, cpu=False)
            v = detail.get("validation", {"violations": []})
            v["ok"] = not v["violations"]
            detail["validation"] = v
            print(f"chip phases ok={ok} on_tpu={detail.get('on_tpu')} "
                  f"violations={len(v['violations'])}", flush=True)
            probe_log(f"[{stamp}] chip phases ok={ok} "
                      f"on_tpu={detail.get('on_tpu')} "
                      f"violations={len(v['violations'])}")
            if ok and detail.get("on_tpu"):
                bench._persist("BENCH_TPU.json", detail)
                print(json.dumps(bench.compact_line(detail)), flush=True)
                return 0
            # chip answered the probe but the phase failed — keep trying
        if args.once:
            return 1
        time.sleep(args.interval)


if __name__ == "__main__":
    sys.exit(main())
