#!/usr/bin/env python3
"""graphcheck gate (ISSUE 11) — fails on ANY graph-invariant finding.

Tier-1 wiring next to lint_gate.py / bench_guard.py (tests/
test_graphcheck.py runs it): Pass A lowers the full preset × topology
matrix on a forced 8-device CPU mesh and verifies sharding / dtype /
donation / closed-signature invariants; Pass B gates the SHD/DTY AST
rules against the triaged lint baseline. Unlike the lint ratchet there
is NO baseline for Pass A findings — a graph invariant is either intact
or the gate is red.

    python scripts/graph_gate.py                 # full matrix
    python scripts/graph_gate.py --cell llama3-8b@2x1
    python scripts/graph_gate.py --budget-s 120  # enforce the runtime gate

When the forced CPU mesh is unavailable (caller pinned XLA_FLAGS without
the device-count forcing), the gate SKIPS LOUDLY with the re-run recipe
and exits 0 — mirroring the multichip test marker: a silent red would
block CI on an environment quirk, a silent green would claim coverage
that never ran.

Exit codes: 0 clean (or loud skip), 1 findings / budget exceeded,
2 internal errors.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tpu9.utils import force_cpu  # noqa: E402

# must happen before anything imports jax (the graphcheck CLI does the
# same); harmless no-op when conftest already forced it
force_cpu(host_devices=8)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--cell", action="append", default=None)
    ap.add_argument("--budget-s", type=float, default=120.0,
                    help="fail when the full matrix exceeds this wall "
                         "clock (0 disables; default %(default)s — the "
                         "tier-1 contract)")
    ap.add_argument("--no-compile", action="store_true")
    ap.add_argument("--repo-root", default=None)
    ap.add_argument("--strict-stale", action="store_true",
                    help="fail when lint-baseline entries for the graph "
                         "AST rules no longer fire (shared ratchet "
                         "semantics with lint_gate/wire_gate)")
    args = ap.parse_args(argv)

    from tpu9.analysis import load_baseline, run_analysis
    from tpu9.analysis.graphcheck import GRAPH_AST_RULES
    from tpu9.analysis.graphcheck import passes
    from tpu9.analysis.graphcheck.matrix import find_cells
    from tpu9.analysis.runner import (DEFAULT_BASELINE, find_repo_root,
                                      gate)

    guard = passes.device_guard()
    if guard is not None:
        print(f"graph_gate: SKIP — {guard}", file=sys.stderr)
        return 0

    t0 = time.perf_counter()
    try:
        cells = find_cells(args.cell)
    except KeyError as exc:
        # bad --cell name only — an internal error inside the matrix run
        # must keep its traceback, not masquerade as a CLI typo
        print(f"graph_gate: {exc}", file=sys.stderr)
        return 2
    report = passes.run_matrix(cells, compile_jobs=not args.no_compile)

    repo_root = args.repo_root or find_repo_root()
    result = run_analysis(repo_root, select=set(GRAPH_AST_RULES))
    baseline = load_baseline(os.path.join(repo_root, DEFAULT_BASELINE))
    lint_new, _known, lint_stale = gate(result, baseline)
    # this pass only ran the graph AST rules — staleness elsewhere in
    # the lint ledger is lint_gate's business, not ours
    lint_stale = [e for e in lint_stale
                  if e.get("rule") in set(GRAPH_AST_RULES)]

    findings = list(report["findings"]) + lint_new
    for f in findings:
        print(f"FAIL {f.format()}")
    elapsed = time.perf_counter() - t0
    matrix_s = report["elapsed_s"]
    n_graphs = sum(s["jobs"] for s in report["cells"])
    print(f"graph_gate: {len(report['cells'])} cells / {n_graphs} graphs "
          f"in {matrix_s:.1f}s (+ lint, total {elapsed:.1f}s) — "
          f"{len(findings)} findings")

    if findings:
        print("graph_gate: FAIL — graph invariants violated (Pass A "
              "findings have no baseline: fix the graph or the policy).",
              file=sys.stderr)
        return 1
    # the budget is the MATRIX contract — Pass B's repo-wide lint scan
    # scales with repo size, not with the matrix, and must not bill it
    if args.budget_s and not args.cell and matrix_s > args.budget_s:
        print(f"graph_gate: FAIL — full matrix took {matrix_s:.1f}s > "
              f"budget {args.budget_s:.0f}s (trim the matrix or move a "
              "cell to the slow tier)", file=sys.stderr)
        return 1
    if args.strict_stale and lint_stale:
        for e in lint_stale:
            print(f"stale baseline entry (prune or lint_gate "
                  f"--update-baseline): {e['rule']} {e['path']} "
                  f"[{e.get('symbol')}]")
        print("graph_gate: FAIL — stale baseline entries (--strict-stale)",
              file=sys.stderr)
        return 1
    print("graph_gate: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
