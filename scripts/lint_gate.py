#!/usr/bin/env python3
"""tpu9lint ratchet gate (ISSUE 7) — fails on any NEW finding.

The fast suite runs this next to bench_guard.py (tests/test_lint.py): the
triaged debt lives in scripts/lint_baseline.json, inline ``# tpu9:
noqa[RULE] reason`` suppressions cover reviewed sites, and anything else is
a regression that fails CI. Gate semantics (scoped stale filtering,
baseline updates that preserve out-of-scope triage, ``--strict-stale``)
are shared with wire_gate.py via tpu9/analysis/gatelib.py.

    python scripts/lint_gate.py                    # gate the repo
    python scripts/lint_gate.py --update-baseline --reason "why"
    python scripts/lint_gate.py --strict-stale     # also fail on stale debt

Exit codes: 0 clean, 1 new findings (or stale with --strict-stale),
2 parse/internal errors.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tpu9.analysis import DEFAULT_BASELINE, run_analysis  # noqa: E402
from tpu9.analysis.gatelib import ratchet_main  # noqa: E402


def _run(repo_root, roots, select, args):
    kwargs = {}
    if roots:
        kwargs["roots"] = roots
    if args.boundaries:
        kwargs["boundaries_toml"] = args.boundaries
    return run_analysis(repo_root, select=select, **kwargs)


def main(argv=None) -> int:
    return ratchet_main(
        "lint_gate", _run, DEFAULT_BASELINE, argv=argv,
        doc=__doc__.splitlines()[0],
        add_args=lambda ap: ap.add_argument(
            "--boundaries", default=None,
            help="override boundaries.toml (tests)"))


if __name__ == "__main__":
    sys.exit(main())
