#!/usr/bin/env python3
"""tpu9lint ratchet gate (ISSUE 7) — fails on any NEW finding.

The fast suite runs this next to bench_guard.py (tests/test_lint.py): the
triaged debt lives in scripts/lint_baseline.json, inline ``# tpu9:
noqa[RULE] reason`` suppressions cover reviewed sites, and anything else is
a regression that fails CI.

    python scripts/lint_gate.py                    # gate the repo
    python scripts/lint_gate.py --update-baseline --reason "why"
    python scripts/lint_gate.py --strict-stale     # also fail on stale debt

Exit codes: 0 clean, 1 new findings (or stale with --strict-stale),
2 parse/internal errors.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tpu9.analysis import (DEFAULT_BASELINE, find_repo_root,  # noqa: E402
                           load_baseline, run_analysis)
from tpu9.analysis.findings import Baseline  # noqa: E402
from tpu9.analysis.runner import gate  # noqa: E402


def _in_roots(path: str, roots) -> bool:
    for r in roots:
        r = r.rstrip("/")
        if path == r or path.startswith(r + "/"):
            return True
    return False


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--repo-root", default=None)
    ap.add_argument("--roots", nargs="*", default=None)
    ap.add_argument("--baseline", default=DEFAULT_BASELINE)
    ap.add_argument("--boundaries", default=None,
                    help="override boundaries.toml (tests)")
    ap.add_argument("--strict-stale", action="store_true",
                    help="fail when baseline entries no longer fire")
    ap.add_argument("--update-baseline", action="store_true",
                    help="record every NEW finding as suppressed (requires "
                         "--reason) and prune stale entries")
    ap.add_argument("--reason", default="",
                    help="mandatory triage reason for --update-baseline")
    args = ap.parse_args(argv)

    repo_root = args.repo_root or find_repo_root()
    # a run over non-default roots sees only a slice of the repo: baseline
    # entries outside the slice would look "stale" and must not be pruned
    # or even reported as such
    scoped = bool(args.roots)
    kwargs = {}
    if args.roots:
        kwargs["roots"] = args.roots
    if args.boundaries:
        kwargs["boundaries_toml"] = args.boundaries
    result = run_analysis(repo_root, **kwargs)

    bl_path = args.baseline
    if not os.path.isabs(bl_path):
        bl_path = os.path.join(repo_root, bl_path)
    baseline = load_baseline(bl_path)
    new, known, stale = gate(result, baseline)
    if scoped:
        # keep only stale entries the narrowed run actually scanned —
        # entries outside the slice are not evidence of anything
        stale = [e for e in stale
                 if _in_roots(e.get("path", ""), args.roots)]

    for err in result.parse_errors:
        print(f"lint_gate: parse error: {err}", file=sys.stderr)
    if result.parse_errors:
        return 2

    if args.update_baseline:
        if new and not args.reason.strip():
            print("lint_gate: --update-baseline needs --reason (suppressions "
                  "without a reason are not triage)", file=sys.stderr)
            return 2
        fresh = Baseline()
        fresh.fixed = baseline.fixed
        for f in known:
            fresh.entries[f.fingerprint] = baseline.entries[f.fingerprint]
        if scoped:
            # keep everything the narrowed run could not see — a scoped
            # update must never destroy the rest of the triage ledger
            # (in-scope stale entries are still pruned)
            live = {f.fingerprint for f in known}
            for fp, e in baseline.entries.items():
                if fp not in live and not _in_roots(e.get("path", ""),
                                                    args.roots):
                    fresh.entries[fp] = e
        for f in new:
            fresh.add(f, args.reason.strip())
        fresh.save(bl_path)
        pruned = len(stale)     # already scope-filtered above
        print(f"lint_gate: baseline updated — {len(new)} added, "
              f"{pruned} stale pruned, {len(known)} kept"
              + (" (scoped run: out-of-scope entries preserved)"
                 if scoped else ""))
        return 0

    for f in new:
        print(f"NEW  {f.format()}")
    for e in stale:
        print(f"stale baseline entry (prune or --update-baseline): "
              f"{e['rule']} {e['path']} [{e.get('symbol')}]")
    print(f"lint_gate: {result.files_scanned} files in "
          f"{result.elapsed_s:.2f}s — {len(new)} new, {len(known)} "
          f"baselined, {len(result.suppressed)} noqa'd, {len(stale)} stale")
    if new:
        print("lint_gate: FAIL — new findings above. Fix them, or suppress "
              "with `# tpu9: noqa[RULE] reason` / --update-baseline "
              "--reason.", file=sys.stderr)
        return 1
    if stale and args.strict_stale:
        print("lint_gate: FAIL — stale baseline entries (--strict-stale)",
              file=sys.stderr)
        return 1
    print("lint_gate: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
