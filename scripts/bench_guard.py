#!/usr/bin/env python3
"""Benchmark regression guard (ISSUE 1 satellite).

Diffs the newest round's ``BENCH_r*.json`` cold-start and engine-throughput
fields against the previous round and exits non-zero on any regression
worse than the threshold (default 15%). Run it after a bench round:

    python scripts/bench_guard.py                 # repo BENCH_r*.json
    python scripts/bench_guard.py --base A --current B   # explicit files
    python scripts/bench_guard.py --report-only   # never fail (CI smoke)

Accepted file shapes: the driver's round capture (``{"parsed": {"extra":
{...}}}``), a bare compact bench line (``{"extra": {...}}``), or a flat
metrics dict — whatever ``bench.py`` produced, the guard finds the fields.
A field missing on either side is skipped (new metrics don't fail old
rounds); improvements are reported, never fatal.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

# guarded headline fields → direction ("down" = lower is better)
GUARDED_FIELDS = {
    "cold_start_p50_s": "down",
    "cold_start_native_p50_s": "down",
    "cold_start_native_pull_p50_s": "down",
    "cold_start_jax_restore_p50_s": "down",
    "cold_start_jax_restore_stream_p50_s": "down",
    "cold_start_warm_pool_restore_p50_s": "down",
    "kernel_flash_ms": "down",
    "kernel_paged_ms": "down",
    "engine_tokens_per_sec_per_chip": "up",
    "endpoint_tokens_per_sec_per_chip": "up",
    # fleet router (ISSUE 2): TTFT under mixed-tenant load must not
    # regress; shed rate under the fixed overload burst must not grow;
    # prefix/KV hit rates must not collapse
    "router_ttft_p50_ms": "down",
    "router_ttft_p99_ms": "down",
    "router_shed_rate": "down",
    "router_prefix_hit_rate": "up",
    "router_kv_hit_rate": "up",
    # request survivability (ISSUE 15): recovery time after an induced
    # replica failure must not creep up. Zero-failed-requests is enforced
    # INSIDE the phase (any client-visible failure is a violation that
    # strips the headline fields) — the HARD presence check below turns a
    # stripped round into a guard failure rather than silently lost
    # coverage. The backoff schedule in the phase is deterministic
    # (jitter=0, 50 ms base) so the p95 is schedule-dominated, not
    # host-noise-dominated.
    "faults_recovery_p95_s": "down",
    # KV wire + disaggregated prefill/decode (ISSUE 16): the roundtrip
    # bit-exactness bit is binary and HARD (the disagg phase strips it
    # when any pool roundtrip or the version gate fails — the quant
    # parity precedent); the long-doc TTFT win of disagg-on routing
    # must not decay. The short-chat ratio is deliberately NOT guarded
    # here: the phase hard-gates it at 1.02, and its absolute value
    # (~0.01-0.1) is far too small for a meaningful 15% ratio guard.
    "kvwire_roundtrip_exact": "up",
    "disagg_longdoc_ttft_improvement": "up",
    # speculative decoding (ISSUE 5): the repetitive-workload uplift must
    # not decay back toward 1.0, and the adversarial auto-disable must
    # keep holding the ratio near parity
    "spec_uplift_repetitive": "up",
    "spec_adversarial_ratio": "up",
    "spec_tokens_per_sec_on_repetitive": "up",
    # quantized serving (ISSUE 6): the bytes-moved headlines must not
    # decay (a dtype regression shows up here first), and the quant-on
    # engine must not slow down
    "quant_shard_bytes_ratio": "up",
    "quant_kv_capacity_ratio": "up",
    "quant_tokens_per_sec_ratio": "up",
    "quant_tokens_per_sec_on": "up",
    # mesh-sharded serving (ISSUE 9): the sharded engine must not slow
    # down across rounds, the per-chip weight shard must stay ~1/tp (a
    # creep toward 1.0 means placement stopped sharding), and the planner
    # pricing must keep describing the resident layout
    "multichip_tokens_per_sec_tp2": "up",
    "multichip_total_ratio": "up",
    "multichip_weight_shard_ratio": "down",
    "multichip_planner_weight_err": "down",
    # observability overhead (ISSUE 8 + ISSUE 12): the deterministic
    # instrumentation price (microbenched hook cost × measured window/
    # request rates, PLUS the fleet timeline sampler + SLO burn evaluator
    # at their fixed cadences) must not creep. The wall-clock on/off
    # ratio and the decomposition coverage are deliberately NOT guarded
    # here — on a shared CPU host the ratio's cross-round noise is
    # ±10-15% (the phase floors it) and coverage's goodness is "≈1", not
    # monotonic; the phase gates both.
    "obs_overhead_frac": "down",
    # (ISSUE 14: the watchdog assess + HBM memory_stats() sweep fold
    # into obs_overhead_frac above via the obs phase's microbench×rate
    # pricing; their raw µs fields ride the round unguarded like the
    # other per-hook prices — host-to-host µs noise is not a regression)
    # (ISSUE 19: the decision-ledger record hook — admission + placement
    # on every request, eviction once per fresh request id, autoscaler
    # records at sampler cadence — folds into the same obs_overhead_frac
    # budget; the phase additionally hard-fails if the record hot path
    # exceeds 8 µs, the same bar as the cache exchange-accounting hook)
    # cold-start decomposition (ISSUE 13): the fetch∥consume overlap of
    # the streamed restore must not collapse back toward serial (the
    # double-buffering win the coldstart report exists to evidence). The
    # traced-vs-measured disagreement is NOT ratio-guarded (it is a small
    # noisy number; the phase hard-gates it at 10% and strips the whole
    # decomposition on failure — the HARD presence check below catches
    # that via this field).
    "coldstart_overlap_frac": "up",
    # scale-out plane (ISSUE 17): concurrent tree bring-up of N joiners
    # vs the serial no-peer baseline must not decay back toward N×, and
    # the source-tier byte share must stay sub-linear in N (the O(1)
    # source story — the phase strips it when the tree degenerates to
    # everyone-reads-source, so it is HARD below).
    "scaleout_bringup_ratio": "down",
    "scaleout_source_bytes_ratio": "down",
    # KV tiering + prefix directory (ISSUE 20): the directory+tier hit
    # rate must stay strictly above the affinity-only baseline (the
    # phase strips every kvtier field when it is not — HARD below), and
    # the modeled TTFT p95 ratio of tiering-on vs affinity-only must not
    # creep back toward parity. Storm survival is gated inside the phase
    # (on > off is binary); the paging µs fields ride unguarded like the
    # other per-hook prices — host-to-host µs noise is not a regression.
    "kvtier_prefix_hit_rate": "up",
    "kvtier_ttft_p95_ratio": "down",
}

# HARD-gated fields: the quant phase's oracle-margin parity judge and the
# obs phase's overhead/decomposition gates STRIP these from the round on
# failure (bench._merge_validated), so — unlike ordinary new/dropped
# metrics, which are skipped — a base round carrying them and a current
# round missing them IS the failure signal and must fail the guard, not
# silently lose coverage.
HARD_FIELDS = ("quant_shard_bytes_ratio", "quant_kv_capacity_ratio",
               "quant_tokens_per_sec_ratio", "obs_overhead_frac",
               # the faults phase strips its fields when ANY request was
               # client-visibly lost (zero-failed-requests is HARD) or
               # the watermark splice duplicated/skipped a token
               "faults_recovery_p95_s",
               # the multichip phase's parity judge / planner checks strip
               # these on failure — a vanished value IS the regression
               "multichip_weight_shard_ratio", "multichip_total_ratio",
               # coldstart_stream strips its decomposition when the traced
               # spans disagree with the measured intervals (>10%) — a
               # vanished value means the restore evidence went wrong
               "coldstart_overlap_frac",
               # the disagg phase strips its kvwire fields when any pool
               # roundtrip loses bit-exactness or the version gate fails
               # to refuse a bumped reader — the quant parity precedent:
               # a stripped round IS the wire-format regression
               "kvwire_roundtrip_exact",
               # the scaleout phase strips its fields when the source
               # tier served a linear share of joiner bytes (no tree),
               # any restore failed under the chaos leg, or the
               # execute-while-scaling leg never admitted early — a
               # vanished value IS the scale-out regression
               "scaleout_source_bytes_ratio",
               # the kvtier phase strips its fields when the directory+
               # tier hit rate fails to beat the affinity-only baseline,
               # the TTFT p95 ratio regresses, the eviction storm shows
               # no survival win, or any sim request dropped — a
               # vanished value IS the tiering regression
               "kvtier_prefix_hit_rate")


def extract_metrics(path: str) -> dict:
    """Pull the guarded fields out of any of the bench output shapes."""
    with open(path) as f:
        node = json.load(f)
    if isinstance(node.get("parsed"), dict):
        node = node["parsed"]
    if isinstance(node.get("extra"), dict):
        node = node["extra"]
    return {k: float(node[k]) for k in GUARDED_FIELDS
            if isinstance(node.get(k), (int, float))
            and not isinstance(node.get(k), bool)}


def find_rounds(bench_dir: str) -> list[str]:
    """BENCH_r*.json paths sorted by round number (oldest first)."""
    rounds = []
    for path in glob.glob(os.path.join(bench_dir, "BENCH_r*.json")):
        m = re.fullmatch(r"BENCH_r(\d+)\.json", os.path.basename(path))
        if m:
            rounds.append((int(m.group(1)), path))
    return [p for _, p in sorted(rounds)]


def compare(base: dict, cur: dict, threshold: float) -> tuple[list, list]:
    """Returns (rows, regressions). Each row is a dict with field/base/
    current/delta_pct/status; regressions is the failing subset."""
    rows, regressions = [], []
    for field, direction in GUARDED_FIELDS.items():
        if field in base and field not in cur and field in HARD_FIELDS:
            # present in the base but stripped from the current round —
            # for hard-gated fields that means the phase's own validation
            # rejected the numbers (e.g. parity-judge failure)
            row = {"field": field, "base": base[field], "current": None,
                   "delta_pct": None,
                   "status": "REGRESSION (missing — phase validation "
                             "stripped it)"}
            rows.append(row)
            regressions.append(row)
            continue
        if field not in base or field not in cur:
            continue
        b, c = base[field], cur[field]
        if b <= 0:
            continue
        delta = (c - b) / b
        regress_frac = delta if direction == "down" else -delta
        status = "ok"
        if regress_frac > threshold:
            status = "REGRESSION"
        elif regress_frac < -threshold:
            status = "improved"
        row = {"field": field, "base": b, "current": c,
               "delta_pct": round(delta * 100, 1), "status": status}
        rows.append(row)
        if status == "REGRESSION":
            regressions.append(row)
    return rows, regressions


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--dir", default=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))),
        help="directory holding BENCH_r*.json (default: repo root)")
    ap.add_argument("--base", help="explicit previous-round file")
    ap.add_argument("--current", help="explicit current-round file")
    ap.add_argument("--threshold", type=float, default=0.15,
                    help="max tolerated regression fraction (default 0.15)")
    ap.add_argument("--report-only", action="store_true",
                    help="print the diff but always exit 0")
    args = ap.parse_args(argv)

    if bool(args.base) != bool(args.current):
        ap.error("--base and --current must be given together")
    if args.base:
        base_path, cur_path = args.base, args.current
    else:
        rounds = find_rounds(args.dir)
        if len(rounds) < 2:
            print("bench_guard: fewer than two BENCH_r*.json rounds — "
                  "nothing to compare")
            return 0
        base_path, cur_path = rounds[-2], rounds[-1]

    base = extract_metrics(base_path)
    cur = extract_metrics(cur_path)
    rows, regressions = compare(base, cur, args.threshold)

    print(f"bench_guard: {os.path.basename(base_path)} → "
          f"{os.path.basename(cur_path)} "
          f"(threshold {args.threshold:.0%})")
    if not rows:
        print("  no shared guarded fields — nothing to compare")
        return 0
    for row in rows:
        if row["current"] is None:
            print(f"  REGRESSION  {row['field']}: {row['base']:g} → "
                  f"MISSING (phase validation stripped it)")
            continue
        print(f"  {row['status']:>10}  {row['field']}: "
              f"{row['base']:g} → {row['current']:g} "
              f"({row['delta_pct']:+.1f}%)")
    if regressions and not args.report_only:
        print(f"bench_guard: FAIL — {len(regressions)} field(s) regressed "
              f"more than {args.threshold:.0%}")
        return 1
    print("bench_guard: ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
