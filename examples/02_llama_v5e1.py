"""Baseline config #2: Llama-3-8B JAX inference on a single v5e chip behind
@endpoint — the continuous-batching engine runner with checkpointed weights,
paged KV (block allocator + chunked prefill + prefix reuse), and SSE token
streaming.

    tpu9 deploy examples/02_llama_v5e1.py:llama --name llama8b
    curl -X POST $GW/endpoint/llama8b -H "Authorization: Bearer $TOK" \
         -d '{"tokens": [1, 3124, 310], "max_new_tokens": 64}'
    # token streaming (one SSE event per token):
    tpu9 invoke llama8b '{"tokens": [1, 3124, 310], "max_new_tokens": 64,
                          "stream": true}' --stream

The declarative ``model=`` lets the gateway verify at deploy time that
weights + KV fit the chip's HBM (an infeasible config is a 400 with the
arithmetic, not a chip OOM).
"""

from tpu9 import Volume, endpoint


def load_engine():
    import jax
    from tpu9.models import init_decoder
    from tpu9.models.llama import LLAMA_PRESETS
    from tpu9.ops import quantize_decoder
    from tpu9.runner import ckpt
    from tpu9.serving import EngineConfig, InferenceEngine

    cfg = LLAMA_PRESETS["llama3-8b"]

    def init():
        # real weights come from the mounted volume (safetensors → pytree
        # loader); random init keeps the example self-contained
        return init_decoder(jax.random.PRNGKey(0), cfg)

    # restore from the container checkpoint when present; otherwise init and
    # save so the next cold start skips this entirely
    params = ckpt.maybe_restore(init)
    # weight-only int8: halves HBM reads per decode step (8B bf16 ≈ 16 GB is
    # tight next to the KV cache on a 16 GB v5e chip; int8 leaves headroom)
    params = quantize_decoder(params)
    # paged KV: memory tracks live tokens (not max_batch × max_seq), long
    # prompts chunk-prefill through one (128, 2048) graph, and requests
    # sharing a prompt prefix reuse its KV blocks
    return InferenceEngine(params, cfg, EngineConfig(
        max_batch=8, max_seq_len=2048, prefill_buckets=(128, 512, 2048),
        kv_block_size=128, prefill_chunk=128, prefix_cache_blocks=16))


llama = endpoint(
    tpu="v5e-1", cpu=4, memory="16Gi", runner="llm",
    model="llama3-8b-int8",      # deploy-time HBM feasibility gate
    checkpoint_enabled=True, keep_warm_seconds=300,
    volumes=[Volume(name="llama3-8b", mount_path="/models/llama3-8b")],
)(load_engine)
