"""Baseline config #5: Gemma-7B LoRA fine-tune on a multi-host v5p-64 slice
via @function — 16 gang-scheduled containers, one per host, joined into a
single jax.distributed job with FSDP over ICI.

    from examples.x05_gemma_lora_v5p64 import finetune
    task = finetune.submit(dataset_path="/data/corpus.jsonl", steps=1000)
    print(task.result(timeout=7200))
"""

from tpu9 import Volume, function


@function(tpu="v5p-64", cpu=32, memory="200Gi", timeout=4 * 3600,
          volumes=[Volume(name="gemma-7b", mount_path="/models/gemma-7b"),
                   Volume(name="datasets", mount_path="/data")])
def finetune(dataset_path: str = "", steps: int = 100, lr: float = 1e-4,
             lora_rank: int = 16):
    # 1) join the slice-wide jax.distributed job (the worker injected
    #    TPU9_GANG_RANK/SIZE + JAX_COORDINATOR_ADDRESS for this gang)
    from tpu9.parallel.distributed import initialize_multihost
    info = initialize_multihost()

    import jax
    import jax.numpy as jnp
    import optax

    from tpu9.models import init_decoder, lora
    from tpu9.models.gemma import GEMMA_PRESETS
    from tpu9.parallel import decoder_param_specs, fsdp_specs, make_mesh, shard_params
    from tpu9.train import build_lora_train_step

    cfg = GEMMA_PRESETS["gemma-7b"]
    n = jax.device_count()               # 64 chips across the 16 hosts
    mesh = make_mesh(dp=1, fsdp=n // 4, sp=1, tp=4)

    base = init_decoder(jax.random.PRNGKey(0), cfg)     # volume loader IRL
    base = shard_params(base, mesh, decoder_param_specs(base))
    adapters = lora.init_lora(jax.random.PRNGKey(1), base, rank=lora_rank)
    adapters = shard_params(adapters, mesh, fsdp_specs(adapters, min_size=1))

    opt = optax.adamw(lr)
    opt_state = opt.init(adapters)
    step = build_lora_train_step(cfg, opt, scale=lora.lora_scale(lora_rank))

    losses = []
    with mesh:
        for i in range(steps):
            # dataset iterator elided: per-host shards of dataset_path
            tokens = jax.random.randint(jax.random.PRNGKey(i), (8, 512), 0,
                                        cfg.vocab_size)
            adapters, opt_state, metrics = step(adapters, opt_state, base,
                                                tokens)
            if i % 10 == 0:
                losses.append(float(metrics["loss"]))

    if info is None or info.is_coordinator:
        from tpu9.runner import ckpt
        ckpt.save_params(adapters, name="lora_adapters")
    return {"final_loss": losses[-1] if losses else None,
            "loss_curve": losses, "ranks": info.size if info else 1}
