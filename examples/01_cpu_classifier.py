"""Baseline config #1: CPU-only sentiment endpoint (distilbert-class model),
single container, scale-to-zero.

    tpu9 deploy examples/01_cpu_classifier.py:classify --name sentiment
    curl -X POST $GW/endpoint/sentiment -H "Authorization: Bearer $TOK" \
         -d '{"text": "tpu9 is great"}'
"""

from tpu9 import endpoint


def load_model():
    """Loads once per container (on_start); HF pipeline when the image
    bundles transformers + weights, tiny JAX classifier otherwise."""
    import os
    try:
        # no network retries when the hub cache is cold (zero-egress images)
        os.environ.setdefault("HF_HUB_OFFLINE", "1")
        from transformers import pipeline
        return pipeline("sentiment-analysis",
                        model="distilbert-base-uncased-finetuned-sst-2-english")
    except Exception:
        import jax
        from tpu9.models.classifier import (TEXTCLS_TINY, classifier_forward,
                                            init_classifier)
        params = init_classifier(jax.random.PRNGKey(0), TEXTCLS_TINY)

        def tiny(text: str):
            import jax.numpy as jnp
            tokens = jnp.array([[hash(w) % TEXTCLS_TINY.vocab_size
                                 for w in text.split()[:32]] or [0]])
            mask = jnp.ones_like(tokens)
            logits = classifier_forward(params, tokens, mask, TEXTCLS_TINY)
            label = int(logits.argmax())
            return [{"label": ["NEGATIVE", "POSITIVE"][label],
                     "score": float(jax.nn.softmax(logits)[0, label])}]

        return tiny


@endpoint(cpu=1, memory="2Gi", keep_warm_seconds=60, on_start=load_model)
def classify(text: str = "", context=None):
    return {"prediction": context(text)[0]}
