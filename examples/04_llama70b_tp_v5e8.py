"""Baseline config #4: Llama-3-70B pjit tensor-parallel on a v5e-8 slice,
token-pressure autoscaled.

The worker hands the container all 8 chips of the host slice; the handler
builds a tp=8 mesh from the slice topology and shards the params with the
Megatron-style specs — GSPMD inserts the ICI collectives.

    tpu9 deploy examples/04_llama70b_tp_v5e8.py:llama70b --name llama70b
"""

from tpu9 import TokenPressureAutoscaler, Volume, endpoint


def load_engine():
    import jax
    from tpu9.models import init_decoder
    from tpu9.models.llama import LLAMA_PRESETS
    from tpu9.parallel import decoder_param_specs, mesh_for_spec, shard_params
    from tpu9.serving import EngineConfig, InferenceEngine
    from tpu9.types import parse_tpu_spec

    cfg = LLAMA_PRESETS["llama3-70b"]
    spec = parse_tpu_spec("v5e-8")
    mesh = mesh_for_spec(spec)          # tp=8 on the host's ICI

    params = init_decoder(jax.random.PRNGKey(0), cfg)   # volume loader IRL
    params = shard_params(params, mesh, decoder_param_specs(params))

    # the engine's jitted prefill/decode inherit the param shardings; each
    # request is served by all 8 chips cooperatively
    engine = InferenceEngine(params, cfg, EngineConfig(
        max_batch=16, max_seq_len=4096, prefill_buckets=(512, 2048, 4096)))
    engine.mesh = mesh
    return engine


llama70b = endpoint(
    tpu="v5e-8", cpu=16, memory="100Gi", runner="llm",
    keep_warm_seconds=600,
    autoscaler=TokenPressureAutoscaler(max_containers=4,
                                       max_token_pressure=0.85),
    volumes=[Volume(name="llama3-70b", mount_path="/models/llama3-70b")],
)(load_engine)
