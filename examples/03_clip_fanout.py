"""Baseline config #3: CLIP ViT-L image embedding fan-out across N×v5e-1
task-queue workers (queue-depth autoscaling from zero).

    # producer side:
    python3 -c "
    from examples.x03_clip_fanout import embed_image
    handles = [embed_image.put(url) for url in urls]
    vectors = [h.result(timeout=300) for h in handles]"
"""

from tpu9 import QueueDepthAutoscaler, task_queue

_state = {}


def _model():
    if "apply" not in _state:
        import jax
        from tpu9.models.clip_vit import (CLIP_VIT_L14, clip_vision_forward,
                                          init_clip_vision)
        params = init_clip_vision(jax.random.PRNGKey(0), CLIP_VIT_L14)
        _state["apply"] = jax.jit(
            lambda imgs: clip_vision_forward(params, imgs, CLIP_VIT_L14))
    return _state["apply"]


@task_queue(tpu="v5e-1", cpu=2, memory="8Gi",
            autoscaler=QueueDepthAutoscaler(max_containers=16,
                                            tasks_per_container=4))
def embed_image(url: str = "", pixels=None):
    """One task per image; the engine batches at the XLA level via jit."""
    import jax.numpy as jnp
    import numpy as np

    if pixels is None:
        # image fetch/decode left to the deployment's image (PIL etc.);
        # callers may pass raw pixel arrays directly
        raise ValueError("pass pixels=[H][W][3] floats (0..1)")
    img = jnp.asarray(np.array(pixels, dtype=np.float32))[None]
    return {"embedding": _model()(img)[0].tolist()}
