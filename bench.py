#!/usr/bin/env python3
"""tpu9 benchmark — prints ONE JSON line.

Phases mirror BASELINE.md's north star ("container cold-start p50 +
tokens/sec/chip") plus kernel validation, each in a FRESH subprocess so they
cannot interfere (round-1 failure mode: the cold-start stack's child
processes outlived their phase and the TPU tunnel refused the LLM phase):

1. **llm** (chip first, while it's free): Llama-architecture decode
   steady-state tokens/sec/chip on the default backend. If the TPU backend
   cannot initialize within the timeout, re-runs forced-CPU and marks
   ``backend: "cpu"`` honestly rather than hanging the bench.
2. **kernels**: pallas flash-attention + ragged paged-decode vs the XLA
   fallback — max abs diff (correctness) and per-step latency on the chip.
3. **coldstart**: deploy→first-response p50 through the real local stack
   (gateway + scheduler + worker + subprocess runner), forced CPU. The
   subprocess runs in its own process group and the group is killed after,
   so no stack child can leak into later phases or the caller.

Primary metric: cold_start_p50_s with ``vs_baseline`` = 1.0 / p50 against
the reference's headline "under a second" cold-start claim (README.md:39 of
beam-cloud/beta9) — >1.0 means beating it. Decode throughput + kernel
numbers ride in ``extra``.

Usage:
    python3 bench.py [--quick] [--cpu]          # full orchestrated run
    python3 bench.py --phase llm|kernels|coldstart   # one phase, in-process
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import statistics
import subprocess
import sys
import time

# generous: first XLA compile through a cold relay can take minutes
PHASE_TIMEOUT_S = {"llm": 900, "kernels": 900, "coldstart": 900}


# ---------------------------------------------------------------------------
# phase: llm decode throughput
# ---------------------------------------------------------------------------

def bench_llm_decode(quick: bool = False) -> dict:
    import jax
    import jax.numpy as jnp

    from tpu9.models import decoder_forward, init_decoder, init_kv_cache
    from tpu9.models.llama import LLAMA_PRESETS
    from tpu9.ops.sampling import sample_logits
    from tpu9.utils import on_tpu

    backend = jax.default_backend()
    n_chips = jax.device_count()
    tpu = on_tpu()
    preset = "llama-tiny" if (quick or not tpu) else "llama-1b"
    cfg = LLAMA_PRESETS[preset]

    batch, prompt_len, decode_steps = (4, 64, 16) if quick or not tpu \
        else (8, 1024, 64)
    max_len = prompt_len + decode_steps + 8
    # the ragged pallas decode kernel needs S % 256 == 0 and S >= 512
    if tpu:
        max_len = max(512, (max_len + 255) // 256 * 256)

    params = init_decoder(jax.random.PRNGKey(0), cfg)
    cache = init_kv_cache(cfg, batch, max_len)

    @jax.jit
    def prefill(params, tokens, cache):
        logits, cache = decoder_forward(params, tokens, cfg, kv_cache=cache)
        return logits[:, -1:].argmax(-1).astype(jnp.int32), cache

    def decode(params, cache, tok, cache_len, rng):
        positions = cache_len[:, None]
        logits, cache = decoder_forward(params, tok, cfg, positions=positions,
                                        kv_cache=cache, cache_len=cache_len + 1,
                                        decode=True)
        rng, sub = jax.random.split(rng)
        nxt = sample_logits(logits[:, -1], sub, temperature=0.0)
        return nxt[:, None].astype(jnp.int32), cache, cache_len + 1, rng

    decode = jax.jit(decode, donate_argnums=(1,))

    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, prompt_len),
                                0, cfg.vocab_size)
    # compile + warmup
    t0 = time.perf_counter()
    tok, cache = prefill(params, tokens, cache)
    tok.block_until_ready()
    prefill_compile_s = time.perf_counter() - t0

    cache_len = jnp.full((batch,), prompt_len, jnp.int32)
    rng = jax.random.PRNGKey(2)
    t0 = time.perf_counter()
    tok, cache, cache_len, rng = decode(params, cache, tok, cache_len, rng)
    tok.block_until_ready()
    decode_compile_s = time.perf_counter() - t0

    # steady state
    t0 = time.perf_counter()
    for _ in range(decode_steps):
        tok, cache, cache_len, rng = decode(params, cache, tok, cache_len, rng)
    tok.block_until_ready()
    elapsed = time.perf_counter() - t0

    toks_per_sec = batch * decode_steps / elapsed
    return {
        "backend": backend,
        "on_tpu": tpu,
        "model": preset,
        "n_chips": n_chips,
        "batch": batch,
        "decode_tokens_per_sec": round(toks_per_sec, 2),
        "decode_tokens_per_sec_per_chip": round(toks_per_sec / max(n_chips, 1), 2),
        "decode_step_ms": round(1000 * elapsed / decode_steps, 3),
        "prefill_compile_s": round(prefill_compile_s, 2),
        "decode_compile_s": round(decode_compile_s, 2),
    }


# ---------------------------------------------------------------------------
# phase: kernel validation (pallas vs XLA: correctness + step time)
# ---------------------------------------------------------------------------

def bench_kernels(quick: bool = False) -> dict:
    import jax
    import jax.numpy as jnp

    from tpu9.ops.attention import flash_attention, xla_attention
    from tpu9.ops.paged_attention import ragged_decode_attention
    from tpu9.utils import on_tpu

    tpu = on_tpu()
    interpret = not tpu           # CPU runs the same kernels interpreted
    out: dict = {"backend": jax.default_backend(), "on_tpu": tpu}

    def timeit(fn, *args, iters=3 if quick or not tpu else 20, **kw):
        r = fn(*args, **kw)
        jax.block_until_ready(r)
        t0 = time.perf_counter()
        for _ in range(iters):
            r = fn(*args, **kw)
        jax.block_until_ready(r)
        return r, (time.perf_counter() - t0) / iters * 1000

    # flash attention: [B, T, H, D]
    b, t, h, d = (1, 256, 4, 64) if quick or not tpu else (4, 2048, 16, 128)
    kq = jax.random.PRNGKey(0)
    q = jax.random.normal(kq, (b, t, h, d), jnp.bfloat16)
    k = jax.random.normal(jax.random.PRNGKey(1), (b, t, h, d), jnp.bfloat16)
    v = jax.random.normal(jax.random.PRNGKey(2), (b, t, h, d), jnp.bfloat16)

    flash, flash_ms = timeit(flash_attention, q, k, v, causal=True,
                             interpret=interpret)
    ref, xla_ms = timeit(xla_attention, q, k, v, causal=True)
    out["flash_max_abs_diff"] = float(
        jnp.max(jnp.abs(flash.astype(jnp.float32) - ref.astype(jnp.float32))))
    out["flash_ms"] = round(flash_ms, 3)
    out["flash_xla_ms"] = round(xla_ms, 3)
    out["flash_shape"] = [b, t, h, d]

    # ragged paged decode: q [B,1,QH,D], cache [B,S,KH,D]
    b, s, qh, kh, d = (2, 512, 8, 2, 64) if quick or not tpu \
        else (8, 4096, 16, 4, 128)
    q1 = jax.random.normal(kq, (b, 1, qh, d), jnp.bfloat16)
    kc = jax.random.normal(jax.random.PRNGKey(3), (b, s, kh, d), jnp.bfloat16)
    vc = jax.random.normal(jax.random.PRNGKey(4), (b, s, kh, d), jnp.bfloat16)
    lens = jnp.linspace(s // 4, s, b).astype(jnp.int32)

    paged, paged_ms = timeit(ragged_decode_attention, q1, kc, vc, lens,
                             interpret=interpret)
    from tpu9.ops.attention import xla_decode_attention
    ref2, xla2_ms = timeit(jax.jit(xla_decode_attention), q1, kc, vc, lens)
    out["paged_max_abs_diff"] = float(
        jnp.max(jnp.abs(paged.astype(jnp.float32) - ref2.astype(jnp.float32))))
    out["paged_ms"] = round(paged_ms, 3)
    out["paged_xla_ms"] = round(xla2_ms, 3)
    out["paged_shape"] = [b, s, qh, kh, d]
    return out


# ---------------------------------------------------------------------------
# phase: serving cold start
# ---------------------------------------------------------------------------

def bench_cold_start(quick: bool = False) -> dict:
    """Deploy→first-response p50/p95/max through the local stack."""
    import asyncio

    from tpu9.testing.localstack import LocalStack  # noqa: WPS433

    trials = 5 if quick else 20

    async def run() -> dict:
        times = []
        backoffs = 0
        async with LocalStack() as stack:
            name = "bench-echo"
            deploy = await stack.deploy_echo_endpoint(name)
            # prime once so the first measured trial isn't paying one-time
            # stack setup (workspace unpack cache etc.)
            await stack.invoke(deploy, {"warm": 1})
            for _ in range(trials):
                await stack.scale_to_zero(deploy)
                t0 = time.perf_counter()
                resp = await stack.invoke(deploy, {"ping": 1})
                assert resp is not None
                times.append(time.perf_counter() - t0)
            inst = stack.gateway.endpoints.instances.get(deploy["stub_id"])
            if inst is not None:
                backoffs = getattr(inst.instance, "backoff_events", 0)
        times.sort()
        # nearest-rank p95: ceil(0.95*n)-th sample — for small n this is the
        # max, never an optimistic lower percentile mislabeled as p95
        p95_idx = max(0, -(-95 * len(times) // 100) - 1)
        return {
            "cold_start_p50_s": round(statistics.median(times), 4),
            "cold_start_p95_s": round(times[p95_idx], 4),
            "cold_start_min_s": round(times[0], 4),
            "cold_start_max_s": round(times[-1], 4),
            "cold_start_backoff_events": backoffs,
            "trials": trials,
        }

    return asyncio.run(run())


# ---------------------------------------------------------------------------
# orchestration
# ---------------------------------------------------------------------------

def _run_phase(phase: str, quick: bool, cpu: bool) -> dict:
    """Run one phase in a fresh subprocess (own process group), parse the
    last JSON line, then kill the whole group so nothing leaks forward."""
    cmd = [sys.executable, os.path.abspath(__file__), "--phase", phase]
    if quick:
        cmd.append("--quick")
    if cpu or phase == "coldstart":
        # the serving stack and its runner children must never dial the chip
        cmd.append("--cpu")
    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True,
                            start_new_session=True)
    # setsid'd runner containers leave the group AND reparent to init when
    # the phase dies, so pids must be snapshotted WHILE the phase is alive —
    # a post-exit walk from a dead pid finds nothing
    seen_pids: set[int] = set()
    deadline = time.monotonic() + PHASE_TIMEOUT_S[phase]
    timed_out = False
    while True:
        try:
            out, err = proc.communicate(timeout=2)
            break
        except subprocess.TimeoutExpired:
            seen_pids.update(_descendants(proc.pid))
            if time.monotonic() > deadline:
                timed_out = True
                _kill_group(proc, seen_pids)
                out, err = proc.communicate()
                break
    _kill_group(proc, seen_pids)
    if timed_out:
        return {f"{phase}_error": f"timeout after {PHASE_TIMEOUT_S[phase]}s",
                f"{phase}_stderr_tail": err[-500:] if err else ""}

    for line in reversed(out.strip().splitlines()):
        try:
            return json.loads(line)
        except json.JSONDecodeError:
            continue
    return {f"{phase}_error": f"no JSON (rc={proc.returncode})",
            f"{phase}_stderr_tail": (err or "")[-500:]}


def _descendants(root_pid: int) -> list[int]:
    """All live descendant pids of root_pid via /proc PPid chains. Needed
    because ProcessRuntime starts runner containers with os.setsid() — they
    leave the phase's process group, so killpg alone cannot reach them."""
    ppid_of: dict[int, int] = {}
    for entry in os.listdir("/proc"):
        if not entry.isdigit():
            continue
        try:
            with open(f"/proc/{entry}/status") as f:
                for line in f:
                    if line.startswith("PPid:"):
                        ppid_of[int(entry)] = int(line.split()[1])
                        break
        except OSError:
            continue
    out, frontier = [], {root_pid}
    while frontier:
        nxt = {pid for pid, ppid in ppid_of.items() if ppid in frontier}
        nxt -= set(out)
        out.extend(nxt)
        frontier = nxt
    return out


def _kill_group(proc: subprocess.Popen, extra_pids: set[int] = frozenset()) -> None:
    """SIGKILL the phase's process group plus every pid snapshotted while
    the phase was alive (setsid'd runner containers sit outside the group
    and reparent to init on phase death — the snapshot is the only handle)."""
    kids = set(_descendants(proc.pid)) | set(extra_pids)
    kids.discard(proc.pid)
    try:
        os.killpg(proc.pid, signal.SIGKILL)
    except (ProcessLookupError, PermissionError):
        pass
    for pid in kids:
        # snapshot pids may have died and been REUSED by unrelated
        # processes — only kill ones that are verifiably ours (runner
        # containers carry TPU9_* env)
        try:
            with open(f"/proc/{pid}/environ", "rb") as f:
                if b"TPU9_" not in f.read():
                    continue
            os.kill(pid, signal.SIGKILL)
        except (OSError, ProcessLookupError, PermissionError):
            continue


def _tpu_alive(timeout_s: float = 120.0) -> bool:
    """One cheap probe: can a fresh process initialize the accelerator
    backend at all? A dead tunnel hangs indefinitely — probing once here
    avoids paying the full phase timeout twice."""
    code = ("import jax; d = jax.devices(); "
            "print('TPU9_PROBE_OK', len(d), jax.default_backend())")
    proc = subprocess.Popen([sys.executable, "-c", code],
                            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
                            text=True, start_new_session=True)
    try:
        out, _ = proc.communicate(timeout=timeout_s)
        return "TPU9_PROBE_OK" in (out or "")
    except subprocess.TimeoutExpired:
        return False
    finally:
        _kill_group(proc)


def orchestrate(quick: bool, cpu: bool) -> dict:
    extra: dict = {}

    if not cpu and not _tpu_alive():
        extra["tpu_probe"] = "accelerator backend did not initialize; " \
                             "falling back to CPU"
        cpu = True

    # chip phases FIRST, while nothing else has touched the tunnel
    llm = _run_phase("llm", quick, cpu)
    if "llm_error" in llm and not cpu:
        # TPU init failed/hung — fall back to CPU so the metric exists
        extra["llm_tpu_error"] = llm["llm_error"]
        llm = _run_phase("llm", quick, True)
    extra.update(llm)

    kern = _run_phase("kernels", quick, cpu)
    if "kernels_error" in kern and not cpu:
        extra["kernels_tpu_error"] = kern["kernels_error"]
        kern = _run_phase("kernels", quick, True)
    extra.update({f"kernel_{k}" if not k.startswith("kernel") else k: v
                  for k, v in kern.items()})

    extra.update(_run_phase("coldstart", quick, cpu))
    return extra


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (local verification)")
    ap.add_argument("--phase", choices=["llm", "kernels", "coldstart"],
                    help="run one phase in-process (used by the orchestrator)")
    args = ap.parse_args()

    if args.cpu:
        from tpu9.utils import force_cpu
        force_cpu(host_devices=8 if args.phase != "coldstart" else 0)

    if args.phase:
        fn = {"llm": bench_llm_decode, "kernels": bench_kernels,
              "coldstart": bench_cold_start}[args.phase]
        try:
            print(json.dumps(fn(quick=args.quick)))
        except Exception as exc:   # noqa: BLE001 — phase errors are data
            print(json.dumps(
                {f"{args.phase}_error": f"{type(exc).__name__}: {exc}"}))
            sys.exit(1)
        return

    extra = orchestrate(args.quick, args.cpu)

    if "cold_start_p50_s" in extra:
        value = extra["cold_start_p50_s"]
        line = {"metric": "cold_start_p50_s", "value": value, "unit": "s",
                "vs_baseline": round(1.0 / max(value, 1e-9), 3),
                "extra": extra}
    elif "decode_tokens_per_sec_per_chip" in extra:
        line = {"metric": "decode_tokens_per_sec_per_chip",
                "value": extra["decode_tokens_per_sec_per_chip"],
                "unit": "tok/s/chip", "vs_baseline": 0.0, "extra": extra}
    else:
        line = {"metric": "bench_failed", "value": 0, "unit": "",
                "vs_baseline": 0.0, "extra": extra}
        print(json.dumps(line))
        sys.exit(1)

    print(json.dumps(line))


if __name__ == "__main__":
    main()
