#!/usr/bin/env python3
"""tpu9 benchmark — prints ONE JSON line.

Every number in the line is defended by evidence computed in-harness
(`tpu9/benchsuite/physics.py`), the same evidence-or-fail stance as the
reference's b9bench validators (`benchmarks/b9bench/validators.py:6-60`):

- **Fencing**: all timing windows end in a forced device→host copy of data
  computed by the window (``np.asarray(jax.device_get(...))``). On the TPU
  tunnel backend ``block_until_ready()`` returns before execution finishes
  (measured: 4.4 TFLOP "completing" in 0.24 ms), so it is never used for
  timing here.
- **Physics**: model-bandwidth-utilization and MFU are computed for every
  throughput phase and the phase FAILS if either is >= 1.0 — a number that
  implies more than HBM bandwidth or MXU peak is a timing bug, not a result.
- **Linear scaling**: doubling the decode-step count must ~double elapsed
  time, which catches async backends whose clock stops early.
- **Engine path**: the headline LLM number comes from the serving
  InferenceEngine (and, on TPU, through a real ``@endpoint`` deployment of
  the LLM runner), not a hand-rolled loop.

Phases (each in a fresh subprocess so they cannot interfere, and so only one
process at a time dials the TPU tunnel):

1. **llm**: Llama3-8B int8 weight-only (bf16 8B = 16.06 GB does not fit a
   v5e's 16 GiB HBM; int8 is the standard single-chip recipe) — raw decode
   windows through the engine's own compiled graph, then the engine
   end-to-end with concurrent requests.
2. **llm_endpoint** (TPU only): same engine served by ``tpu9.runner.llm``
   behind ``@endpoint tpu=v5e-1`` through the real gateway/scheduler/worker
   stack; reports served tokens/sec with a container-side served-count proof.
3. **kernels**: pallas flash-attention + ragged paged-decode vs the XLA
   fallback — correctness (max abs diff) + fenced latency + MFU sanity.
4. **coldstart**: deploy→first-response p50 through the real local stack
   (gateway + scheduler + worker + subprocess runner), forced CPU.

Primary metric: cold_start_p50_s with ``vs_baseline`` = 1.0 / p50 against
the reference's headline "under a second" cold-start claim (README.md:39 of
beam-cloud/beta9). LLM throughput + kernel numbers + their evidence ride in
``extra``; any number whose evidence fails is REMOVED from extra and
replaced by a ``*_rejected`` reason.

Usage:
    python3 bench.py [--quick] [--cpu]               # full orchestrated run
    python3 bench.py --phase llm|llm_endpoint|kernels|coldstart
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import statistics
import struct
import subprocess
import sys
import time

from tpu9.utils.aio import cancellable_wait

PHASE_TIMEOUT_S = {"llm": 1800, "llm_endpoint": 1800, "kernels": 900,
                   "coldstart": 900, "coldstart_native": 900,
                   "coldstart_jax": 900, "coldstart_jax_tpu": 900,
                   "coldstart_stream": 900, "router": 300, "spec": 900,
                   "quant": 900, "obs": 900, "multichip": 900,
                   "faults": 300, "disagg": 600, "scaleout": 600,
                   "kvtier": 600}

# share compiled XLA programs between the in-process llm phase and the
# runner container in the endpoint phase (identical graphs → second phase
# skips the multi-minute 8B compiles)
XLA_CACHE_DIR = "/tmp/tpu9-bench/xla-cache"

# env a runner CONTAINER needs to reach the TPU tunnel backend from a
# stripped-environment subprocess (ProcessRuntime allowlists env; the
# gateway/worker stay forced-CPU while only the serving container gets these)
_TUNNEL_ENV_KEYS = ("JAX_PLATFORMS", "AXON_LOOPBACK_RELAY", "TPU_SKIP_MDS_QUERY",
                    "PALLAS_AXON_TPU_GEN", "PALLAS_AXON_POOL_IPS",
                    "PALLAS_AXON_REMOTE_COMPILE")


def fence(x) -> float:
    """Force completion of x's computation by copying a small dependent
    slice to host. Returns a checksum so callers can accumulate it (keeps
    the compiler from eliminating the work)."""
    import jax
    import numpy as np
    leaf = jax.tree_util.tree_leaves(x)[0]
    host = np.asarray(jax.device_get(leaf.ravel()[:8].astype("float32")))
    return float(host.sum())


# ---------------------------------------------------------------------------
# phase: llm decode throughput (engine graph + engine e2e)
# ---------------------------------------------------------------------------

def _llm_settings(tpu: bool, quick: bool) -> dict:
    if quick or not tpu:
        return dict(preset="llama-tiny", batch=4, max_seq=256, ctx0=64,
                    window_k=8, windows=2, prefill_buckets=(32, 64),
                    decode_steps=(1, 4, 8), requests=4, max_new=13,
                    prompt_len=24)
    # requests == max_batch: with work queued the engine drops to K=1
    # admission-latency windows — steady-state throughput is all slots busy
    # with no queue, decoding K=32 windows
    return dict(preset="llama3-8b-int8", batch=8, max_seq=2048, ctx0=512,
                window_k=32, windows=4, prefill_buckets=(128,),
                decode_steps=(1, 8, 32), requests=8, max_new=41,
                prompt_len=120)


def bench_llm(quick: bool = False) -> dict:
    import asyncio

    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpu9.benchsuite.physics import (chip_spec, decode_byte_counts,
                                         decode_physics,
                                         linear_scaling_violations,
                                         physics_violations)
    from tpu9.serving.presets import load_engine
    from tpu9.utils import on_tpu

    os.makedirs(XLA_CACHE_DIR, exist_ok=True)
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", XLA_CACHE_DIR)

    tpu = on_tpu()
    s = _llm_settings(tpu, quick)
    dev = jax.devices()[0]
    spec = chip_spec(getattr(dev, "device_kind", ""))
    out: dict = {
        "backend": jax.default_backend(), "on_tpu": tpu,
        "device_kind": getattr(dev, "device_kind", "unknown"),
        "chip_spec": {"name": spec.name, "hbm_gbps": spec.hbm_gbps,
                      "peak_bf16_tflops": spec.peak_bf16_tflops},
        "model": s["preset"], "batch": s["batch"],
        "max_seq_len": s["max_seq"],
        "note": ("llama3-8b served int8 weight-only: 8B bf16 = 16.06 GB > "
                 "16 GiB v5e HBM" if "8b" in s["preset"] else ""),
    }
    violations: list[str] = []

    t0 = time.perf_counter()
    engine = load_engine(s["preset"], max_batch=s["batch"],
                         max_seq_len=s["max_seq"],
                         prefill_buckets=s["prefill_buckets"],
                         decode_steps=s["decode_steps"])
    fence(engine.params["layers"][0]["wq"])
    out["param_init_s"] = round(time.perf_counter() - t0, 2)

    counts = decode_byte_counts(engine.params, engine.cfg, s["batch"],
                                s["ctx0"])
    out["streamed_weight_gb"] = round(counts["streamed_bytes"] / 1e9, 3)

    # --- raw decode windows through the ENGINE's compiled decode graph ----
    k = s["window_k"]
    if getattr(engine, "paged", False):
        # paged engine: slots need real physical blocks so the decode
        # windows move production-shaped HBM traffic
        engine.bench_reset_slots(
            s["ctx0"], 3 * s["windows"] * s["window_k"] + max(
                s["decode_steps"]))
        out["kv_mode"] = (f"paged(block={engine.ecfg.kv_block_size}, "
                          f"pool={engine.allocator.n_blocks})")
    dec = engine._decode_k(k)
    cache_len = jnp.full((s["batch"],), s["ctx0"], jnp.int32)
    last = jnp.ones((s["batch"], 1), jnp.int32)
    active = jnp.ones((s["batch"],), bool)
    rng = jax.random.PRNGKey(0)
    kv = engine.kv_cache

    t0 = time.perf_counter()
    last, kv, cache_len, rng, toks = dec(engine.params, kv, last, cache_len,
                                         active, rng)
    checksum = fence(toks)
    out["decode_compile_s"] = round(time.perf_counter() - t0, 2)

    def run_windows(n: int) -> float:
        nonlocal last, kv, cache_len, rng, checksum
        # reset position so every run does identical work
        cache_len = jnp.full((s["batch"],), s["ctx0"], jnp.int32)
        checksum += fence(cache_len)                      # start fence
        t0 = time.perf_counter()
        for _ in range(n):
            last, kv, cache_len, rng, toks = dec(
                engine.params, kv, last, cache_len, active, rng)
            checksum += fence(toks)                       # window fence
        return time.perf_counter() - t0

    w = s["windows"]
    elapsed_1x = run_windows(w)
    elapsed_2x = run_windows(2 * w)
    # the raw loop donated the engine's cache through each call — hand the
    # final buffer back so the engine e2e below starts from a live cache
    engine.kv_cache = kv
    out["fence_checksum"] = round(checksum, 2)
    out["raw_elapsed_1x_s"] = round(elapsed_1x, 4)
    out["raw_elapsed_2x_s"] = round(elapsed_2x, 4)
    out["raw_scaling_ratio"] = round(elapsed_2x / max(elapsed_1x, 1e-9), 3)

    steps = w * k
    step_ms = elapsed_1x / steps * 1e3
    raw_tps = s["batch"] * steps / elapsed_1x
    phys = decode_physics(
        step_ms=step_ms, batch=s["batch"],
        streamed_bytes=counts["streamed_bytes"],
        kv_bytes_per_step=counts["kv_bytes_per_step"],
        matmul_params=counts["matmul_params"],
        attn_flops_per_step=counts["attn_flops_per_step"], spec=spec)
    out["raw_decode_step_ms"] = round(step_ms, 3)
    out["raw_decode_tokens_per_sec"] = round(raw_tps, 1)
    out["raw_physics"] = phys

    if tpu:
        violations += physics_violations(phys, what="raw decode")
        violations += linear_scaling_violations(
            elapsed_1x, elapsed_2x, what="raw decode")

    # --- engine end-to-end: concurrent requests through generate() --------
    async def engine_e2e() -> dict:
        t0 = time.perf_counter()
        engine.warmup()        # compile all prefill/decode graphs up front
        await engine.start()
        prompt = list(range(3, 3 + s["prompt_len"]))
        await engine.generate(prompt, max_new_tokens=s["max_new"])
        warm_s = time.perf_counter() - t0
        before = engine._stats["tokens_generated"] + 1    # + prefill token
        t0 = time.perf_counter()
        results = await asyncio.gather(*[
            engine.generate([p + i for p in prompt],
                            max_new_tokens=s["max_new"])
            for i in range(s["requests"])])
        elapsed = time.perf_counter() - t0
        await engine.stop()
        total = sum(len(r) for r in results)
        # served proof: the engine's own counter must account for every
        # token the callers received (first tokens come from prefill and are
        # not in tokens_generated — count them explicitly)
        counted = (engine._stats["tokens_generated"] + len(results)
                   + 1) - before
        return {"warm_s": warm_s, "elapsed": elapsed, "total": total,
                "counted": counted}

    ee = asyncio.run(engine_e2e())
    out["engine_warmup_s"] = round(ee["warm_s"], 2)
    out["engine_requests"] = s["requests"]
    out["engine_tokens_returned"] = ee["total"]
    out["engine_elapsed_s"] = round(ee["elapsed"], 3)
    engine_tps = ee["total"] / ee["elapsed"]
    out["engine_tokens_per_sec"] = round(engine_tps, 1)
    out["engine_tokens_per_sec_per_chip"] = round(engine_tps, 1)
    out["engine_served_proof_ok"] = ee["counted"] >= ee["total"]
    if not out["engine_served_proof_ok"]:
        violations.append(
            f"engine: callers received {ee['total']} tokens but engine "
            f"counted {ee['counted']}")

    # engine-path physics: requests run in waves of max_batch; per-step
    # weight bytes are the same as raw decode (weights stream regardless
    # of occupancy), but the KV/attention terms use the E2E workload's own
    # mean context (prompt + half the generation budget) — the raw loop's
    # ctx0 would overstate KV traffic and fake the ceiling ratio (ISSUE 5
    # satellite: engine_mbu/mfu must be honest, not copied from another
    # workload's accounting)
    eng_counts = decode_byte_counts(
        engine.params, engine.cfg, s["batch"],
        s["prompt_len"] + s["max_new"] // 2)
    eng_steps = ee["total"] / s["batch"]                  # lower bound
    eng_step_ms = ee["elapsed"] / max(eng_steps, 1e-9) * 1e3
    eng_phys = decode_physics(
        step_ms=eng_step_ms, batch=s["batch"],
        streamed_bytes=eng_counts["streamed_bytes"],
        kv_bytes_per_step=eng_counts["kv_bytes_per_step"],
        matmul_params=eng_counts["matmul_params"],
        attn_flops_per_step=eng_counts["attn_flops_per_step"], spec=spec)
    out["engine_physics"] = eng_phys
    if tpu:
        violations += physics_violations(eng_phys, what="engine decode")

    out["violations"] = violations
    out["valid"] = not violations
    return out


# ---------------------------------------------------------------------------
# phase: llm through a real @endpoint deployment (runner container on TPU)
# ---------------------------------------------------------------------------

LLM_BENCH_APP = """
from tpu9.serving.presets import load_engine

def load():
    return load_engine("{preset}", max_batch={batch}, max_seq_len={max_seq},
                       prefill_buckets={prefill_buckets},
                       decode_steps={decode_steps})
"""


def bench_llm_endpoint(quick: bool = False) -> dict:
    """Serve the flagship engine behind ``@endpoint tpu=v5e-1`` through the
    real gateway/scheduler/worker stack. The gateway/worker processes stay
    forced-CPU; ONLY the runner container gets the TPU env, mirroring
    production (the worker injects chip env per assignment)."""
    import asyncio

    tunnel_env = {k: os.environ[k] for k in _TUNNEL_ENV_KEYS
                  if k in os.environ}
    on_real_tpu = bool(tunnel_env.get("JAX_PLATFORMS")) and not quick \
        and os.environ.get("TPU9_BENCH_CPU") != "1"

    from tpu9.utils import force_cpu
    force_cpu(host_devices=0)      # this process must never dial the chip

    from tpu9.testing.localstack import LocalStack

    s = _llm_settings(on_real_tpu, quick)
    os.makedirs(XLA_CACHE_DIR, exist_ok=True)

    container_env = {"JAX_COMPILATION_CACHE_DIR": XLA_CACHE_DIR}
    if on_real_tpu:
        container_env.update(tunnel_env)
        container_env["PYTHONPATH"] = "/root/.axon_site"
    else:
        container_env["JAX_PLATFORMS"] = "cpu"

    app = LLM_BENCH_APP.format(
        preset=s["preset"], batch=s["batch"], max_seq=s["max_seq"],
        prefill_buckets=tuple(s["prefill_buckets"]),
        decode_steps=tuple(s["decode_steps"]))

    async def run() -> dict:
        out: dict = {"endpoint_model": s["preset"],
                     "endpoint_container_on_tpu": on_real_tpu}
        violations: list[str] = []
        async with LocalStack(pool_tpu_type="v5e-1") as stack:
            await stack._worker_factory(tpu_chips=1, tpu_generation="v5e")
            dep = await stack.deploy_endpoint(
                "llm-bench", {"app.py": app}, "app:load",
                config_extra={
                    "timeout_s": 1500.0,
                    "concurrent_requests": 64,
                    "extra": {"runner": "llm"},
                    "env": container_env,
                    "runtime": {"tpu": "v5e-1", "cpu_millicores": 2000,
                                "memory_mb": 16384},
                    "autoscaler": {"max_containers": 1}})
            prompt = list(range(3, 3 + s["prompt_len"]))
            t0 = time.perf_counter()
            status, warm = await stack.api(
                "POST", "/endpoint/llm-bench",
                json_body={"tokens": prompt, "max_new_tokens": s["max_new"]},
                timeout=1500)
            out["endpoint_warmup_s"] = round(time.perf_counter() - t0, 2)
            if status != 200:
                return {"llm_endpoint_error": f"warmup status {status}: "
                        f"{str(warm)[:300]}"}
            # pre-run served counter: the proof below must cover ONLY the
            # timed requests, not the warmup's tokens
            status, h0 = await stack.api("GET", "/endpoint/llm-bench/health")
            served_before = int(h0.get("tokens_generated", 0)) \
                if status == 200 else -1

            async def one(i: int):
                return await stack.api(
                    "POST", "/endpoint/llm-bench",
                    json_body={"tokens": [p + i for p in prompt],
                               "max_new_tokens": s["max_new"]},
                    timeout=1500)

            t0 = time.perf_counter()
            results = await asyncio.gather(*[one(i)
                                             for i in range(s["requests"])])
            elapsed = time.perf_counter() - t0
            bad = [r for r in results if r[0] != 200]
            if bad:
                return {"llm_endpoint_error":
                        f"{len(bad)} failed requests: {str(bad[0])[:300]}"}
            total = sum(len(r[1]["tokens"]) for r in results)

            # container-side served proof via the runner's /health stats:
            # decode-counter delta + one prefill-sampled token per request
            status, health = await stack.api("GET",
                                             "/endpoint/llm-bench/health")
            served = (int(health.get("tokens_generated", 0)) - served_before
                      + len(results)) if status == 200 and served_before >= 0 \
                else -1
            out["endpoint_requests"] = s["requests"]
            out["endpoint_tokens_returned"] = total
            out["endpoint_elapsed_s"] = round(elapsed, 3)
            tps = total / elapsed
            out["endpoint_tokens_per_sec"] = round(tps, 1)
            out["endpoint_tokens_per_sec_per_chip"] = round(tps, 1)
            out["endpoint_served_proof_ok"] = served >= total
            if not out["endpoint_served_proof_ok"]:
                violations.append(
                    f"endpoint: received {total} tokens but container "
                    f"reports {served}")

            if on_real_tpu:
                from tpu9.benchsuite.physics import (chip_spec,
                                                     decode_physics,
                                                     physics_violations)
                from tpu9.serving.presets import resolve_preset
                cfg, _ = resolve_preset(s["preset"])
                # weight bytes from config (the engine lives in the
                # container; recompute analytically at int8 widths)
                per_layer = (cfg.dim * cfg.n_heads * cfg.head_dim
                             + 2 * cfg.dim * cfg.n_kv_heads * cfg.head_dim
                             + cfg.n_heads * cfg.head_dim * cfg.dim
                             + 3 * cfg.dim * cfg.hidden_dim)
                matmul_params = (per_layer * cfg.n_layers
                                 + cfg.dim * cfg.vocab_size)
                streamed = matmul_params          # int8: 1 byte/param
                kv_row = cfg.n_kv_heads * cfg.head_dim * 2
                kv_bytes = 2 * cfg.n_layers * s["batch"] * (
                    s["prompt_len"] + s["max_new"] // 2) * kv_row
                eng_step_ms = elapsed / max(total / s["batch"], 1e-9) * 1e3
                spec = chip_spec(os.environ.get("PALLAS_AXON_TPU_GEN", ""))
                phys = decode_physics(
                    step_ms=eng_step_ms, batch=s["batch"],
                    streamed_bytes=streamed, kv_bytes_per_step=kv_bytes,
                    matmul_params=matmul_params, spec=spec)
                out["endpoint_physics"] = phys
                violations += physics_violations(phys, what="endpoint decode")
        out["violations"] = violations
        out["valid"] = not violations
        return out

    return asyncio.run(run())


# ---------------------------------------------------------------------------
# phase: kernel validation (pallas vs XLA: correctness + fenced step time)
# ---------------------------------------------------------------------------

def bench_kernels(quick: bool = False) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpu9.benchsuite.physics import (chip_spec, matmul_physics,
                                         physics_violations)
    from tpu9.ops.attention import flash_attention, xla_attention
    from tpu9.ops.paged_attention import ragged_decode_attention
    from tpu9.utils import on_tpu

    tpu = on_tpu()
    interpret = not tpu           # CPU runs the same kernels interpreted
    dev = jax.devices()[0]
    spec = chip_spec(getattr(dev, "device_kind", ""))
    out: dict = {"backend": jax.default_backend(), "on_tpu": tpu}
    violations: list[str] = []

    def timeit(fn, *args, iters=3 if quick or not tpu else 20, **kw):
        r = fn(*args, **kw)
        fence(r)                                  # compile + warmup fence
        fence(args[0])                            # start fence
        t0 = time.perf_counter()
        for _ in range(iters):
            r = fn(*args, **kw)
        fence(r)                                  # same-stream order: forces all
        return r, (time.perf_counter() - t0) / iters * 1000

    # flash attention: [B, T, H, D]
    b, t, h, d = (1, 256, 4, 64) if quick or not tpu else (4, 2048, 16, 128)
    kq = jax.random.PRNGKey(0)
    q = jax.random.normal(kq, (b, t, h, d), jnp.bfloat16)
    k = jax.random.normal(jax.random.PRNGKey(1), (b, t, h, d), jnp.bfloat16)
    v = jax.random.normal(jax.random.PRNGKey(2), (b, t, h, d), jnp.bfloat16)

    flash, flash_ms = timeit(flash_attention, q, k, v, causal=True,
                             interpret=interpret)
    ref, xla_ms = timeit(xla_attention, q, k, v, causal=True)
    out["flash_max_abs_diff"] = float(
        jnp.max(jnp.abs(flash.astype(jnp.float32) - ref.astype(jnp.float32))))
    out["flash_ms"] = round(flash_ms, 3)
    out["flash_xla_ms"] = round(xla_ms, 3)
    out["flash_shape"] = [b, t, h, d]
    # causal attention: ~0.5 * 4 * B*T^2*H*D FLOPs (half the square masked)
    flash_flops = 2.0 * b * t * t * h * d
    flash_bytes = 4 * b * t * h * d * 2           # q,k,v read + out write, bf16
    fp = matmul_physics(elapsed_ms=flash_ms, flops=flash_flops,
                        bytes_moved=flash_bytes, spec=spec)
    out["flash_physics"] = fp
    if tpu:
        violations += physics_violations(fp, what="flash attention")

    # ragged paged decode: q [B,1,QH,D], cache [B,S,KH,D]
    b, s, qh, kh, d = (2, 512, 8, 2, 64) if quick or not tpu \
        else (8, 4096, 16, 4, 128)
    q1 = jax.random.normal(kq, (b, 1, qh, d), jnp.bfloat16)
    kc = jax.random.normal(jax.random.PRNGKey(3), (b, s, kh, d), jnp.bfloat16)
    vc = jax.random.normal(jax.random.PRNGKey(4), (b, s, kh, d), jnp.bfloat16)
    lens = jnp.linspace(s // 4, s, b).astype(jnp.int32)

    paged, paged_ms = timeit(ragged_decode_attention, q1, kc, vc, lens,
                             interpret=interpret)
    from tpu9.ops.attention import xla_decode_attention
    ref2, xla2_ms = timeit(jax.jit(xla_decode_attention), q1, kc, vc, lens)
    out["paged_max_abs_diff"] = float(
        jnp.max(jnp.abs(paged.astype(jnp.float32) - ref2.astype(jnp.float32))))
    out["paged_ms"] = round(paged_ms, 3)
    out["paged_xla_ms"] = round(xla2_ms, 3)
    out["paged_shape"] = [b, s, qh, kh, d]

    # decode attention is bandwidth-bound: reads mean(lens) K+V rows/seq
    mean_len = float(jnp.mean(lens))
    paged_bytes = int(2 * b * mean_len * kh * d * 2)
    paged_flops = 4.0 * b * mean_len * qh * d
    pp = matmul_physics(elapsed_ms=paged_ms, flops=paged_flops,
                        bytes_moved=paged_bytes, spec=spec)
    out["paged_physics"] = pp
    if tpu:
        violations += physics_violations(pp, what="paged decode")

    # block-table paged kernel (the serving engine's production read path):
    # same workload through a scrambled block POOL — correctness against
    # the densify+XLA oracle and fenced latency vs the dense ragged kernel
    from tpu9.ops.paged_attention import (paged_decode_attention,
                                          xla_paged_decode_attention)
    bs_blk = 128 if (quick or not tpu) else 256
    mb = s // bs_blk
    n_pool = b * mb + 4
    rng_t = np.random.default_rng(5)
    table_np = rng_t.permutation(n_pool)[:b * mb].reshape(b, mb)
    table = jnp.asarray(table_np, jnp.int32)
    pool_k = jnp.zeros((n_pool, bs_blk, kh, d), jnp.bfloat16)
    pool_v = jnp.zeros((n_pool, bs_blk, kh, d), jnp.bfloat16)
    kc_blocks = kc.reshape(b * mb, bs_blk, kh, d)
    vc_blocks = vc.reshape(b * mb, bs_blk, kh, d)
    pool_k = pool_k.at[table.reshape(-1)].set(kc_blocks)
    pool_v = pool_v.at[table.reshape(-1)].set(vc_blocks)

    blocktab, blocktab_ms = timeit(paged_decode_attention, q1, pool_k,
                                   pool_v, table, lens, interpret=interpret)
    oracle = xla_paged_decode_attention(q1, pool_k, pool_v, table, lens)
    out["blocktable_max_abs_diff"] = float(jnp.max(jnp.abs(
        blocktab.astype(jnp.float32) - oracle.astype(jnp.float32))))
    out["blocktable_ms"] = round(blocktab_ms, 3)
    out["blocktable_block_size"] = bs_blk
    bt = matmul_physics(elapsed_ms=blocktab_ms, flops=paged_flops,
                        bytes_moved=paged_bytes, spec=spec)
    out["blocktable_physics"] = bt
    if tpu:
        violations += physics_violations(bt, what="block-table decode")
    # the oracle-diff check is backend-independent: a wrong kernel must be
    # rejected on the interpret path too, not just on-chip
    if out["blocktable_max_abs_diff"] > 0.05:
        violations.append(
            f"block-table kernel diverges from oracle by "
            f"{out['blocktable_max_abs_diff']}")
    out["violations"] = violations
    out["valid"] = not violations
    return out


# ---------------------------------------------------------------------------
# phase: serving cold start
# ---------------------------------------------------------------------------

def bench_cold_start(quick: bool = False) -> dict:
    """Deploy→first-response p50/p95/max through the local stack."""
    import asyncio

    from tpu9.testing.localstack import LocalStack  # noqa: WPS433

    trials = 5 if quick else 20

    async def run() -> dict:
        times = []
        backoffs = 0
        async with LocalStack() as stack:
            name = "bench-echo"
            deploy = await stack.deploy_echo_endpoint(name)
            # prime once so the first measured trial isn't paying one-time
            # stack setup (workspace unpack cache etc.)
            await stack.invoke(deploy, {"warm": 1})
            for _ in range(trials):
                await stack.scale_to_zero(deploy)
                t0 = time.perf_counter()
                resp = await stack.invoke(deploy, {"ping": 1})
                assert resp is not None
                times.append(time.perf_counter() - t0)
            inst = stack.gateway.endpoints.instances.get(deploy["stub_id"])
            if inst is not None:
                backoffs = getattr(inst.instance, "backoff_events", 0)
        times.sort()
        # nearest-rank p95: ceil(0.95*n)-th sample — for small n this is the
        # max, never an optimistic lower percentile mislabeled as p95
        p95_idx = max(0, -(-95 * len(times) // 100) - 1)
        out = {
            "cold_start_p50_s": round(statistics.median(times), 4),
            "cold_start_p95_s": round(times[p95_idx], 4),
            "cold_start_min_s": round(times[0], 4),
            "cold_start_max_s": round(times[-1], 4),
            "cold_start_backoff_events": backoffs,
            "trials": trials,
        }
        out["violations"] = (
            [f"coldstart: {backoffs} circuit-breaker backoff events "
             f"polluted the run"] if backoffs else [])
        out["valid"] = not out["violations"]
        return out

    return asyncio.run(run())


def _percentiles(times: list[float]) -> dict:
    times = sorted(times)
    p95_idx = max(0, -(-95 * len(times) // 100) - 1)
    return {"p50": round(statistics.median(times), 4),
            "p95": round(times[p95_idx], 4),
            "min": round(times[0], 4), "max": round(times[-1], 4)}


def _phase_report() -> dict:
    """p50/p95/max per lifecycle phase from the worker's startup timeline
    (reference: benchmarks/sandbox_startup_report.py — per-phase report
    derived from lifecycle events)."""
    from tpu9.observability.metrics import metrics as registry
    out = {}
    for key, summ in registry.summaries.items():
        if key.startswith("tpu9_startup_phase_s"):
            snap = summ.snapshot()
            phase = key.split('phase="')[-1].rstrip('"}')
            out[phase] = {"p50": round(snap["p50"], 4),
                          "p95": round(snap["p95"], 4),
                          "max": round(snap["max"], 4),
                          "n": snap["count"]}
    return out


def bench_cold_start_native(quick: bool = False) -> dict:
    """VERDICT round-2 item #2 + round-3 item #3: the REAL cold-start path —
    NativeRuntime containers (netns + overlay + pivot_root) started from a
    chunked image pulled through the content cache, not a bare
    ProcessRuntime echo. The image is GB-scale (multi-file) so the lazy
    path is what's actually measured: a cold pull must go ready on the
    sparse skeleton while the bulk streams in the background.

    Reports, each with phase-timeline evidence:
    - warm-node: bundle already materialized (the common autoscale cycle)
    - cold-pull: bundle deleted between trials; READY must precede full
      materialization, an on-demand faulted read must return real bytes,
      and cache counters prove chunks were re-fetched
    """
    import asyncio
    import shutil

    if os.geteuid() != 0:
        return {"coldstart_native_skipped": "requires root for NativeRuntime"}

    os.environ["TPU9_RUNTIME"] = "native"
    from tpu9.testing.localstack import LocalStack

    # payload = n_files × file_mb; 1 GiB full-run per VERDICT r03 #3
    n_files, file_mb = (8, 4) if quick else (256, 4)
    payload_mb = n_files * file_mb
    warm_trials = 3 if quick else 10
    pull_trials = 2 if quick else 5

    app = ("import hashlib, os\n"
           "def handler(op='', **kwargs):\n"
           "    blob = os.environ['BLOB_PATH']\n"
           "    if op == 'read':\n"
           "        data = open(blob, 'rb').read()\n"
           "        return {'sha': hashlib.sha256(data).hexdigest(),\n"
           "                'n': len(data)}\n"
           "    return {'blob_bytes': os.path.getsize(blob)}\n")

    async def run() -> dict:
        out: dict = {"runtime": "native", "image_payload_mb": payload_mb,
                     "image_files": n_files}
        violations: list[str] = []
        async with LocalStack() as stack:
            # quick mode's payload is smaller — keep it above the lazy
            # threshold either way (the lazy path IS the thing measured)
            stack.cfg.cache.lazy_threshold_mb = 16 if quick else 64
            status, img = await stack.api("POST", "/rpc/image/build", json_body={
                "commands": [f"mkdir -p env && i=0; while [ $i -lt {n_files} ]"
                             f"; do head -c {file_mb*1024*1024} /dev/urandom "
                             f"> env/blob$i.bin; i=$((i+1)); done"]})
            assert status == 200, img
            image_id = img["image_id"]
            for _ in range(6000):
                _, st = await stack.api("GET", f"/rpc/image/status/{image_id}")
                if st["status"] in ("ready", "failed"):
                    break
                await asyncio.sleep(0.1)
            if st["status"] != "ready":
                return {"coldstart_native_error": f"image build: {st}"}

            bundle = os.path.join(stack.cfg.cache.data_dir, "bundles",
                                  image_id)
            blob = os.path.join(bundle, "env", "blob3.bin")
            dep = await stack.deploy_endpoint(
                "native-imaged", {"app.py": app}, "app:handler",
                config_extra={
                    "runtime": {"image_id": image_id, "cpu_millicores": 1000,
                                "memory_mb": 1024},
                    "env": {"BLOB_PATH": blob}})

            t0 = time.perf_counter()
            first = await stack.invoke(dep, {"n": 0})
            out["first_deploy_s"] = round(time.perf_counter() - t0, 4)
            if first.get("blob_bytes") != file_mb * 1024 * 1024:
                violations.append(
                    f"coldstart_native: container did not see the image "
                    f"payload ({first})")

            warm = []
            for _ in range(warm_trials):
                await stack.scale_to_zero(dep)
                t0 = time.perf_counter()
                await stack.invoke(dep, {"n": 1})
                warm.append(time.perf_counter() - t0)
            out["cold_start_native_warmnode"] = _percentiles(warm)
            out["cold_start_native_p50_s"] = out[
                "cold_start_native_warmnode"]["p50"]

            # cold-pull tier: delete the bundle so materialization (from the
            # node cache store) is back on the path. Cache stats are summed
            # across ALL workers — the pool can run several and the timed
            # container may land on any of them (round-3 advisor finding:
            # reading workers[0] alone can fake a 'pull did not happen').
            workers = list(getattr(stack, "workers", None) or [])

            def cache_ops() -> int:
                return sum(sum(w.cache.client.stats.values())
                           for w in workers if getattr(w, "cache", None))

            async def fill_of(img):
                for w in workers:
                    f = w.cache.puller._fills.get(img)
                    if f is not None:
                        return f
                return None

            pulls = []
            fetch_counts = []
            ready_early = []      # ready BEFORE full materialization?
            for trial in range(pull_trials):
                await stack.scale_to_zero(dep)
                # let any in-flight fill finish before invalidating, so the
                # rmtree races nothing and each trial is a clean cold pull
                f = await fill_of(image_id)
                if f is not None:
                    await cancellable_wait(f.wait(), 300)
                shutil.rmtree(bundle, ignore_errors=True)
                # benchmark hygiene: the previous trial's 1 GiB fill
                # leaves dirty pages whose writeback otherwise bleeds
                # into this trial's timed window (observed ±0.5 s noise)
                await asyncio.to_thread(os.sync)
                await asyncio.sleep(0.3)
                before = cache_ops()
                t0 = time.perf_counter()
                await stack.invoke(dep, {"n": 2})
                pulls.append(time.perf_counter() - t0)
                ready_early.append(not os.path.exists(
                    os.path.join(bundle, ".tpu9-complete")))
                # ops counted over the whole pull CYCLE (timed invoke +
                # background fill): the boot gate intentionally defers
                # bulk fetches past container.ready, so the invoke window
                # alone may show ~0 ops on a healthy lazy pull
                f = await fill_of(image_id)
                if f is not None:
                    await cancellable_wait(f.wait(), 300)
                fetch_counts.append(cache_ops() - before)
            out["cold_start_native_pull"] = _percentiles(pulls)
            out["cold_start_native_pull_p50_s"] = out[
                "cold_start_native_pull"]["p50"]
            if workers and not any(c > 0 for c in fetch_counts):
                violations.append(
                    "coldstart_native: bundle deleted but zero cache "
                    "activity during re-pull — the pull did not happen")
            out["pull_cache_ops_per_trial"] = fetch_counts
            # lazy-load proofs (VERDICT r03 #3): readiness must not wait for
            # the whole image, and a gated on-demand read must return the
            # real bytes, not placeholder zeros
            out["pull_ready_before_complete"] = ready_early
            # at GB scale the fill takes many seconds — ready MUST win the
            # race; quick mode's small payload can legitimately fill first
            if not quick and not all(ready_early):
                violations.append(
                    "coldstart_native: container.ready waited for full "
                    "materialization — lazy path not in effect")
            read = await stack.invoke(dep, {"op": "read"})
            import hashlib
            manifest = await stack._manifest_fetch(image_id)
            entry = next(e for e in manifest.files
                         if e.path == "env/blob3.bin")
            want_chunks = []
            for c in entry.chunks:
                for w in workers:
                    data = await w.cache.client.get(c)
                    if data is not None:
                        want_chunks.append(data)
                        break
            want = hashlib.sha256(b"".join(want_chunks)).hexdigest()
            out["ondemand_read_sha_ok"] = read.get("sha") == want
            if not out["ondemand_read_sha_ok"]:
                violations.append(
                    "coldstart_native: on-demand faulted read returned "
                    "wrong bytes")
            f = await fill_of(image_id)
            if f is not None:
                await cancellable_wait(f.wait(), 600)
                out["lazy_fill_stats"] = dict(f.stats)
            out["phase_timeline"] = _phase_report()
        out["violations"] = violations
        out["valid"] = not violations
        return out

    return asyncio.run(run())


_JAX_RESTORE_APP = (
    "import jax, jax.numpy as jnp\n"
    "@jax.jit\n"
    "def f(x):\n"
    "    for _ in range(8):\n"
    "        x = jnp.tanh(x @ x.T) + x\n"
    "    return x.sum()\n"
    "X = jnp.ones((256, 256), jnp.bfloat16)\n"
    "Y0 = float(f(X))          # compile at import: the cold-start cost\n"
    "def handler(**kwargs):\n"
    "    return {'y': float(f(X)), 'backend': jax.default_backend(),\n"
    "            'kind': jax.devices()[0].device_kind}\n")


def _bench_jax_restore(phase: str, container_env: dict, cache_dir: str,
                       trials: int, suffix: str,
                       invoke_timeout: float) -> tuple[dict, list, dict]:
    """Shared core of the JAX cold-start phases: deploy the compile-at-import
    app, first invoke (cold compile), check the persistent cache filled, then
    N scale-to-zero → invoke restore trials. Returns (out, violations,
    first_reply) — the caller owns backend validation and cache cleanup."""
    import asyncio

    from tpu9.testing.localstack import LocalStack

    async def run():
        out: dict = {}
        violations: list[str] = []
        async with LocalStack() as stack:
            dep = await stack.deploy_endpoint(
                "jax-restore" + suffix.replace("_", "-"),
                {"app.py": _JAX_RESTORE_APP}, "app:handler",
                config_extra={"timeout_s": invoke_timeout,
                              "env": container_env})
            t0 = time.perf_counter()
            first = await stack.invoke(dep, {}, timeout=invoke_timeout)
            out[f"cold_start_jax_first{suffix}_s"] = round(
                time.perf_counter() - t0, 4)
            assert "y" in first, first
            cached = sum(len(fs) for _, _, fs in os.walk(cache_dir))
            out[f"jax_cache_entries{suffix}"] = cached
            if cached == 0:
                violations.append(
                    f"{phase}: no persistent-cache entries written — "
                    "restore trials would be re-measuring cold compiles")
            restores = []
            for _ in range(trials):
                await stack.scale_to_zero(dep)
                t0 = time.perf_counter()
                await stack.invoke(dep, {}, timeout=invoke_timeout)
                restores.append(time.perf_counter() - t0)
            out[f"cold_start_jax_restore{suffix}"] = _percentiles(restores)
            out[f"cold_start_jax_restore{suffix}_p50_s"] = out[
                f"cold_start_jax_restore{suffix}"]["p50"]
        return out, violations, first

    return asyncio.run(run())


def bench_cold_start_jax(quick: bool = False) -> dict:
    """Cold start of a JAX container with persistent-compile-cache restore:
    first boot pays the XLA compile; every later cold start restores the
    executable from JAX_COMPILATION_CACHE_DIR (the real TPU cold-start tail
    is compile time — SURVEY.md §7 hard-part #2)."""
    import tempfile

    cache_dir = tempfile.mkdtemp(prefix="tpu9-bench-jaxcache-")
    env = {"JAX_PLATFORMS": "cpu",
           "JAX_COMPILATION_CACHE_DIR": cache_dir,
           "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS": "0",
           "JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES": "0"}
    try:
        out, violations, _ = _bench_jax_restore(
            "coldstart_jax", env, cache_dir, trials=3 if quick else 10,
            suffix="", invoke_timeout=300.0)
        out["violations"] = violations
        out["valid"] = not violations
        return out
    finally:
        import shutil
        shutil.rmtree(cache_dir, ignore_errors=True)


def bench_cold_start_stream(quick: bool = False) -> dict:
    """Weight-streaming restore (ISSUE 1 tentpole): the same checkpoint
    restored through the three tiers on one node —

    - **classic**: cache → workdir materialize → re-read → deserialize →
      ``jax.device_put`` (the chain every restore used to pay)
    - **streamed**: cache → preallocated host buffer → device, fetch of
      shard *i+1* overlapped with device transfer of shard *i*
    - **warm pool**: deserialized host tree already resident (λScale
      keep-alive) → device only

    Emits per-phase evidence straight from
    ``CheckpointManager.last_restore_metrics`` (``weight_stream_fetch_s``,
    ``weight_stream_put_s``, ``warm_pool_hit``) and FAILS itself if the
    tiers don't strictly order warm < streamed < classic on p50."""
    import asyncio
    import shutil
    import tempfile

    import numpy as np

    async def run() -> dict:
        from tpu9.cache import CacheClient, DiskStore
        from tpu9.serving import weights as wfmt
        from tpu9.worker.checkpoint import CheckpointManager
        from tpu9.worker.weightpool import WeightPool

        out: dict = {}
        violations: list[str] = []
        tmp = tempfile.mkdtemp(prefix="tpu9-bench-stream-")
        try:
            import jax

            rng = np.random.default_rng(0)
            n_shards = 4 if quick else 8
            shard_mb = 4 if quick else 8
            tree = {"model": {"blocks": [
                rng.standard_normal(shard_mb << 18, dtype=np.float32)
                for _ in range(n_shards)], "step": 1234}}
            src = os.path.join(tmp, "src")
            os.makedirs(src)
            wfmt.save_params(tree, os.path.join(src, "params.tpu9w"))
            with open(os.path.join(src, "app.py"), "w") as f:
                f.write("# handler code rides the classic path\n")

            store = DiskStore(os.path.join(tmp, "cache"),
                              max_bytes=8 << 30)

            async def peers():
                return []

            client = CacheClient(store, peers)
            manifests: dict = {}

            async def record(stub, ws, cid):
                return "ckpt-stream-bench"

            async def store_manifest(cid, blob):
                manifests[cid] = blob

            async def fetch_manifest(cid):
                return manifests.get(cid)

            pool = WeightPool(2 << 30)
            cm = CheckpointManager(client, record=record,
                                   store_manifest=store_manifest,
                                   fetch_manifest=fetch_manifest,
                                   weight_pool=pool)
            ckpt = await cm.create("stub", "ws", "c0", src)
            assert ckpt, "checkpoint create failed"
            total_bytes = sum(a.nbytes for a in tree["model"]["blocks"])
            out["weight_stream_checkpoint_mb"] = total_bytes >> 20

            def to_device(tree_or_arrays):
                dev = jax.device_put(tree_or_arrays)
                return jax.block_until_ready(dev)

            trials = 3 if quick else 5
            cm_classic = CheckpointManager(client,
                                           fetch_manifest=fetch_manifest,
                                           stream_weights=False)
            classic = []
            for i in range(trials):
                dest = os.path.join(tmp, f"classic{i}")
                t0 = time.perf_counter()
                assert await cm_classic.restore(ckpt, dest)
                loaded = wfmt.load_params(
                    os.path.join(dest, "params.tpu9w"))
                to_device(loaded)
                classic.append(time.perf_counter() - t0)
                shutil.rmtree(dest)
            out["cold_start_classic_restore"] = _percentiles(classic)
            out["cold_start_classic_restore_p50_s"] = out[
                "cold_start_classic_restore"]["p50"]

            streamed, fetch_s, put_s = [], [], []
            decomp: list = []
            for i in range(trials):
                pool.clear()                      # every trial is Nth=1
                t0 = time.perf_counter()
                trees, metrics = await cm.restore_params(ckpt)
                streamed.append(time.perf_counter() - t0)
                assert trees and not metrics["warm_pool_hit"]
                fetch_s.append(metrics["weight_stream_fetch_s"])
                put_s.append(metrics["weight_stream_put_s"])
                decomp.append(metrics)
            out["cold_start_jax_restore_stream"] = _percentiles(streamed)
            out["cold_start_jax_restore_stream_p50_s"] = out[
                "cold_start_jax_restore_stream"]["p50"]
            out["weight_stream_fetch_s"] = round(
                statistics.median(fetch_s), 4)
            out["weight_stream_put_s"] = round(statistics.median(put_s), 4)

            # ---- cold-start decomposition + trace cross-check (ISSUE
            # 13): per-trial fetch/consume WINDOWS from the restore
            # record's interval anchors, and the same intervals read back
            # from the restore.request span tree the restore emitted —
            # two independent pipelines (record dict vs tracer ring /
            # wall-anchor arithmetic) that must agree within 10%, the
            # same artifact a LocalStack cold start serves at
            # /api/v1/coldstart and /api/v1/traces.
            from tpu9.observability import coldstart as cs_mod
            from tpu9.observability.trace import tracer as _tracer

            def windows(m: dict) -> tuple[float, float]:
                fw = pw = 0.0
                for g in m.get("groups_detail", []):
                    if g.get("fetch_iv"):
                        fw += g["fetch_iv"][1] - g["fetch_iv"][0]
                    if g.get("put_iv"):
                        pw += g["put_iv"][1] - g["put_iv"][0]
                return fw, pw
            fetch_w = [windows(m)[0] for m in decomp]
            put_w = [windows(m)[1] for m in decomp]
            out["coldstart_fetch_window_s"] = round(
                statistics.median(fetch_w), 4)
            out["coldstart_put_window_s"] = round(
                statistics.median(put_w), 4)
            out["coldstart_overlap_frac"] = round(statistics.median(
                [m.get("overlap_frac", 0.0) for m in decomp]), 4)
            out["coldstart_plan_s"] = round(statistics.median(
                [m.get("plan_s", 0.0) for m in decomp]), 4)
            out["coldstart_bytes_by_tier"] = decomp[-1].get("tiers", {})
            # per-EDGE peer split (ISSUE 17 satellite 6): which serving
            # replica fed which bytes — empty here (no peers in this
            # phase) but present, so the field's shape is exercised on
            # every round, not only when the scaleout phase runs
            out["coldstart_bytes_by_edge"] = decomp[-1].get("peer_bytes",
                                                            {})
            out["coldstart_hedge"] = decomp[-1].get("hedge", {})

            last = decomp[-1]
            traced = cs_mod.decompose_spans(
                _tracer.export(trace_id=last.get("trace_id", "")))
            mf, mp = windows(last)
            dis = max(cs_mod.agreement(traced["fetch_s"], mf),
                      cs_mod.agreement(traced["device_put_s"], mp))
            out["coldstart_trace_decomposition"] = traced
            out["coldstart_trace_disagreement"] = round(dis, 4)
            if dis > 0.10:
                violations.append(
                    f"coldstart_stream: traced span intervals disagree "
                    f"with the measured restore intervals by {dis:.1%} "
                    f"(gate 10%) — fetch {traced['fetch_s']:.4f}s vs "
                    f"{mf:.4f}s, put {traced['device_put_s']:.4f}s vs "
                    f"{mp:.4f}s")
            if out["coldstart_overlap_frac"] <= 0.0:
                violations.append(
                    "coldstart_stream: zero fetch-consume overlap — the "
                    "double-buffered pipeline is running serial")

            warm, hits = [], []
            for i in range(trials):               # pool stays warm
                t0 = time.perf_counter()
                trees, metrics = await cm.restore_params(ckpt)
                warm.append(time.perf_counter() - t0)
                hits.append(bool(metrics["warm_pool_hit"]))
            out["cold_start_warm_pool_restore"] = _percentiles(warm)
            out["cold_start_warm_pool_restore_p50_s"] = out[
                "cold_start_warm_pool_restore"]["p50"]
            out["warm_pool_hit"] = all(hits)
            out["weight_pool_stats"] = pool.snapshot()
            out["cache_stats"] = {k: v for k, v in
                                  client.snapshot().items()
                                  if k not in ("peers", "hist_buckets_s")}

            if not all(hits):
                violations.append(
                    "coldstart_stream: warm-pool trials missed the pool — "
                    "the keep-alive tier is not engaging")
            if out["cold_start_warm_pool_restore_p50_s"] >= \
                    out["cold_start_jax_restore_stream_p50_s"]:
                violations.append(
                    "coldstart_stream: warm-pool restore not faster than "
                    "cold streamed restore")
            if out["cold_start_jax_restore_stream_p50_s"] >= \
                    out["cold_start_classic_restore_p50_s"]:
                violations.append(
                    "coldstart_stream: streamed restore not faster than "
                    "the classic workdir chain")
            await client.close()
        finally:
            shutil.rmtree(tmp, ignore_errors=True)
        out["violations"] = violations
        out["valid"] = not violations
        return out

    return asyncio.run(run())


def bench_scaleout(quick: bool = False) -> dict:
    """Scale-out plane (ISSUE 17): N replicas join one deployment and
    restore the same multi-group checkpoint —

    - **serial baseline**: each joiner alone, no peers — every byte from
      the source tier (the pre-tree world: source bytes grow N×)
    - **tree**: real ChunkServers per replica, edges planned by the real
      :class:`ScaleoutCoordinator` over advertised groups, joiners
      staggered by tree depth and re-serving every group they consume —
      source-tier bytes must stay sub-linear in N (HARD) and the
      concurrent 1→N bring-up must beat N× serial
    - **execute-while-scaling**: per-group ``on_group`` readiness drives
      the router's real ``_scaleout_admit`` fence mid-restore — a
      group-hinted request is admitted BEFORE the final group lands, an
      un-hinted one is fenced out
    - **chaos**: one more joiner restores while ``tree_peer_loss`` kills
      its primary parent mid-transfer — the hedged read must fall
      through the surviving preference list with zero failed restores
      and no new source traffic (every group has live holders)."""
    import asyncio
    import shutil
    import tempfile
    import threading

    import numpy as np

    async def run() -> dict:
        from tpu9.cache import CacheClient, DiskStore
        from tpu9.cache.server import ChunkServer
        from tpu9.scaleout.coordinator import ScaleoutCoordinator, \
            build_report
        from tpu9.serving import weights as wfmt
        from tpu9.worker.checkpoint import CheckpointManager

        out: dict = {}
        violations: list[str] = []
        tmp = tempfile.mkdtemp(prefix="tpu9-bench-scaleout-")
        seed_client = seed_srv = None
        threads: list[threading.Thread] = []
        stop_evt = threading.Event()
        try:
            rng = np.random.default_rng(7)
            n_groups = 3 if quick else 4
            n_shards = 2 if quick else 3
            shard_mb = 2 if quick else 4
            n_join = 4
            src = os.path.join(tmp, "src")
            os.makedirs(src)
            for g in range(n_groups):
                tree = {"blk": [rng.standard_normal(shard_mb << 18,
                                                    dtype=np.float32)
                                for _ in range(n_shards)]}
                wfmt.save_params(tree, os.path.join(src, f"g{g}.tpu9w"))
            total_bytes = n_groups * n_shards * (shard_mb << 20)
            out["scaleout_groups"] = n_groups
            out["scaleout_replicas"] = n_join
            out["scaleout_checkpoint_mb"] = total_bytes >> 20

            manifests: dict = {}

            async def record(stub, ws, cid):
                return "ckpt-scaleout-bench"

            async def store_manifest(cid, blob):
                manifests[cid] = blob

            async def fetch_manifest(cid):
                return manifests.get(cid)

            def ident(entry, arr):
                # the phase measures the transfer plane, not device_put
                return arr

            # the SEED replica: creates the checkpoint (its store is the
            # only replica-side copy), then restores once from its local
            # tier so its client ADVERTISES every group
            seed_store = DiskStore(os.path.join(tmp, "seed"),
                                   max_bytes=8 << 30)

            async def no_peers():
                return []

            seed_client = CacheClient(seed_store, no_peers)
            seed_cm = CheckpointManager(seed_client, record=record,
                                        store_manifest=store_manifest,
                                        fetch_manifest=fetch_manifest)
            ckpt = await seed_cm.create("stub", "ws", "seed", src)
            assert ckpt, "checkpoint create failed"
            trees, _ = await seed_cm.restore_params(ckpt, device_put=ident)
            assert trees and len(trees) == n_groups
            group_keys = sorted(seed_client.groups)
            assert len(group_keys) == n_groups, "seed advertised " \
                f"{len(group_keys)}/{n_groups} groups"
            seed_srv = await ChunkServer(
                seed_store, port=0,
                groups_fn=lambda: seed_client.groups).start()
            seed_client.self_address = seed_srv.address
            seed_addr = seed_srv.address

            # the source tier (object store stand-in): serves chunk bytes
            # out of the seed's store but is counted as SOURCE by every
            # client that falls through to it, at object-store-class
            # per-connection bandwidth — unthrottled it would be a local
            # disk read, faster than any real S3/GCS GET and faster than
            # the peer plane's real TCP transfers, making the serial
            # baseline a fantasy the tree could never beat. Thread-loop
            # safe: DiskStore only touches its asyncio.Lock on eviction,
            # which an 8 GiB cap over ~100 MiB of chunks never reaches.
            SRC_BW = 48 << 20    # bytes/s per connection

            async def source_fn(digest):
                data = await seed_store.get(digest)
                if data is not None:
                    await asyncio.sleep(len(data) / SRC_BW)
                return data

            # each replica runs in its OWN thread with its own event loop
            # — one shared loop would serialize the "concurrent" bring-up
            # and the CPU-scaled bound could never hold (in production
            # these are separate processes)
            def in_thread(coro_fn, *args):
                return asyncio.to_thread(
                    lambda: asyncio.run(coro_fn(*args)))

            # ---- serial no-peer baseline: N joiners, one at a time,
            # every byte from source — the pre-tree cost the headline
            # ratios are judged against
            async def serial_one(i: int) -> tuple:
                st = DiskStore(os.path.join(tmp, f"ser{i}"),
                               max_bytes=8 << 30)
                cl = CacheClient(st, no_peers, source=source_fn)
                cm = CheckpointManager(cl,
                                       fetch_manifest=fetch_manifest)
                t0 = time.perf_counter()
                trees, _m = await cm.restore_params(ckpt,
                                                    device_put=ident)
                wall = time.perf_counter() - t0
                ok = bool(trees and len(trees) == n_groups)
                nsrc = cl.stats["bytes_source"]
                await cl.close()
                return wall, nsrc, ok

            serial_walls: list[float] = []
            serial_source = 0
            for i in range(n_join):
                wall, nsrc, ok = await in_thread(serial_one, i)
                assert ok, f"serial baseline restore {i} failed"
                serial_walls.append(wall)
                serial_source += nsrc
                await asyncio.to_thread(
                    shutil.rmtree, os.path.join(tmp, f"ser{i}"),
                    ignore_errors=True)
            single_wall = statistics.median(serial_walls)
            serial_total = sum(serial_walls)
            out["scaleout_single_restore_s"] = round(single_wall, 4)
            out["scaleout_serial_total_s"] = round(serial_total, 4)
            out["scaleout_source_bytes_serial"] = serial_source

            # ---- tree leg: the real coordinator plans edges over the
            # advertised groups; every joiner runs a live ChunkServer and
            # re-serves what it consumes. Protocol: each thread brings up
            # its server, parks until the coordinator (main thread) has
            # planned over the full membership, restores along its edges
            # with a depth stagger, then KEEPS SERVING (for descendants
            # and the chaos leg) until stop_evt.
            addr_box: list = [None] * n_join
            addr_evts = [threading.Event() for _ in range(n_join)]
            clients_box: list = [None] * n_join
            shared: dict = {}
            plan_evt = threading.Event()
            results: dict[int, dict] = {}

            async def joiner_main(i: int) -> None:
                st = DiskStore(os.path.join(tmp, f"join{i}"),
                               max_bytes=8 << 30)

                async def peers():
                    return [seed_addr] + [a for a in addr_box if a]

                cl = CacheClient(st, peers, source=source_fn)
                srv = await ChunkServer(
                    st, port=0, groups_fn=lambda: cl.groups).start()
                cl.self_address = srv.address
                clients_box[i] = cl
                addr_box[i] = srv.address
                addr_evts[i].set()
                try:
                    while not plan_evt.is_set():
                        await asyncio.sleep(0.005)
                    plan = shared["plan"]
                    lag = (shared["depth"].get(srv.address, 1) - 1) \
                        * shared["stagger"] \
                        - (time.perf_counter() - shared["t0"])
                    if lag > 0:
                        await asyncio.sleep(lag)

                    async def hints(key, _a=srv.address):
                        return plan.peer_prefs(_a, key)

                    cm = CheckpointManager(cl,
                                           fetch_manifest=fetch_manifest,
                                           tree_hints=hints)
                    res: dict = {"start_mono": time.perf_counter()}

                    def on_group(group, tree, done, total):
                        res.setdefault("first_group_mono",
                                       time.perf_counter())
                        res.setdefault("first_group", group)

                    trees, m = await cm.restore_params(
                        ckpt, device_put=ident, on_group=on_group)
                    res["done_mono"] = time.perf_counter()
                    res["ok"] = bool(trees and len(trees) == n_groups)
                    res["metrics"] = m
                    results[i] = res
                    while not stop_evt.is_set():
                        await asyncio.sleep(0.02)
                finally:
                    await cl.close()
                    await srv.stop()

            threads = [threading.Thread(
                target=lambda i=i: asyncio.run(joiner_main(i)),
                daemon=True) for i in range(n_join)]
            for t in threads:
                t.start()
            for ev in addr_evts:
                ok = await asyncio.to_thread(ev.wait, 60)
                assert ok, "joiner cache server never came up"

            coord = ScaleoutCoordinator()
            coord.observe_worker("seed",
                                 {"cache": seed_client.snapshot()})
            for i, a in enumerate(addr_box):
                coord.observe_worker(f"join{i}",
                                     {"cache": {"addr": a, "groups": []}})
            plan = coord.refresh()
            out["scaleout_tree_edges"] = len(plan.edges())
            out["scaleout_tree_source_edges"] = \
                sum(1 for _, _, p in plan.edges() if p == "@source")
            if out["scaleout_tree_source_edges"]:
                violations.append(
                    "scaleout: planner minted source edges with a live "
                    "seed holding every group")

            def depth_of(addr: str) -> int:
                d, cur, seen = 0, addr, set()
                while cur not in (seed_addr, "", "@source") \
                        and cur not in seen and d <= n_join:
                    seen.add(cur)
                    pref = plan.peer_prefs(cur, group_keys[0])
                    cur = pref[0] if pref else ""
                    d += 1
                return d

            shared["plan"] = plan
            shared["depth"] = {a: depth_of(a) for a in addr_box}
            # head start per tree depth so a child mostly streams from
            # its parent instead of falling back to the seed — sized to
            # PEER transfer time (loopback TCP), not the source-throttled
            # single-restore wall
            shared["stagger"] = 0.05
            shared["t0"] = time.perf_counter()
            plan_evt.set()
            deadline = time.perf_counter() + 240
            while len(results) < n_join:
                assert time.perf_counter() < deadline, \
                    f"tree bring-up stalled ({len(results)}/{n_join})"
                await asyncio.sleep(0.01)
            tree_wall = max(r["done_mono"] for r in results.values()) \
                - shared["t0"]
            failed = [i for i, r in results.items() if not r["ok"]]
            assert not failed, f"tree restores failed: {failed}"

            tree_source = sum(cl.stats["bytes_source"]
                              for cl in clients_box)
            tree_peer = sum(cl.stats["bytes_peer"] for cl in clients_box)
            edge_bytes: dict[str, int] = {}
            for r in results.values():
                for addr, n in r["metrics"].get("peer_bytes",
                                                {}).items():
                    edge_bytes[addr] = edge_bytes.get(addr, 0) + n
            nonseed = sum(n for a, n in edge_bytes.items()
                          if a != seed_addr)
            out["scaleout_tree_wall_s"] = round(tree_wall, 4)
            out["scaleout_bringup_ratio"] = round(
                tree_wall / single_wall, 4) if single_wall > 0 else 0.0
            out["scaleout_serial_speedup"] = round(
                serial_total / tree_wall, 4) if tree_wall > 0 else 0.0
            out["scaleout_source_bytes_tree"] = tree_source
            out["scaleout_peer_bytes_tree"] = tree_peer
            out["scaleout_source_bytes_ratio"] = round(
                tree_source / serial_source, 4) if serial_source else 1.0
            out["scaleout_bytes_by_edge"] = edge_bytes
            out["scaleout_nonseed_peer_bytes"] = nonseed

            # O(1)-source (HARD): N joiners over the tree must not pull
            # anywhere near the serial N× from the source tier
            if out["scaleout_source_bytes_ratio"] >= 0.6:
                violations.append(
                    f"scaleout: source tier served "
                    f"{out['scaleout_source_bytes_ratio']:.0%} of the "
                    f"serial baseline bytes across {n_join} joiners — "
                    "the tree is not keeping source traffic O(1)")
            # CPU-scaled bring-up gate: with the source tier at object
            # -store bandwidth the single restore is transfer-bound, so
            # the concurrent 1→N bring-up must land near 1× — scaled by
            # the core deficit, because N replicas hashing/framing on
            # K < N cores genuinely serialize that much of the work
            cores = os.cpu_count() or 1
            bound = 1.6 * max(1.0, n_join / min(cores, n_join))
            out["scaleout_bringup_bound"] = round(bound, 3)
            if out["scaleout_bringup_ratio"] > bound:
                violations.append(
                    f"scaleout: concurrent 1→{n_join} bring-up took "
                    f"{out['scaleout_bringup_ratio']:.2f}× a single "
                    f"restore (bound {bound:.2f}× on {cores} cores)")

            # ---- execute-while-scaling: the real router fence, driven
            # by the per-group readiness the restores just reported —
            # judged on the LAST joiner to finish (the worst case)
            from tpu9.router.fleet import FleetRouter
            ews_i = max(results, key=lambda i: results[i]["done_mono"])
            r = results[ews_i]
            span = r["done_mono"] - r["start_mono"]
            first_frac = ((r["first_group_mono"] - r["start_mono"])
                          / span if span > 0 else 1.0)
            out["scaleout_first_group_frac"] = round(first_frac, 4)
            first_group = r["first_group"]
            readiness = {"r0": (1.0 / n_groups, {first_group})}
            hinted = json.dumps(
                {"weight_groups": [first_group]}).encode()
            admitted = FleetRouter._scaleout_admit(hinted, ["r0"],
                                                   readiness)
            fenced = FleetRouter._scaleout_admit(b"{}", ["r0"],
                                                 readiness)
            out["scaleout_partial_admitted"] = admitted == ["r0"]
            out["scaleout_unhinted_fenced"] = fenced == []
            out["scaleout_first_admit_before_complete"] = bool(
                admitted == ["r0"] and 0.0 < first_frac < 1.0)
            if not out["scaleout_first_admit_before_complete"]:
                violations.append(
                    "scaleout: execute-while-scaling never admitted a "
                    "group-hinted request before the final group landed "
                    f"(first-group frac {first_frac:.2f}, admitted "
                    f"{admitted})")
            if not out["scaleout_unhinted_fenced"]:
                violations.append(
                    "scaleout: an un-hinted request was admitted to a "
                    "partially-ready replica — the fence leaks")

            # ---- chaos leg: one more joiner plans real tree edges, then
            # tree_peer_loss kills its primary parent mid-transfer; the
            # hedged read must fall through the surviving preference list
            for i, cl in enumerate(clients_box):
                coord.observe_worker(f"join{i}",
                                     {"cache": cl.snapshot()})
            chaos_addr = "127.0.0.1:1"   # plan identity only; never serves
            coord.observe_worker("chaos",
                                 {"cache": {"addr": chaos_addr,
                                            "groups": []}})
            plan = coord.refresh()
            probe = plan.peer_prefs(chaos_addr, group_keys[0])
            assert probe, "chaos joiner got no tree edges"
            victim = probe[0]
            out["scaleout_chaos_victim"] = victim
            out["scaleout_chaos_backups"] = len(probe) - 1

            async def all_peers():
                return [seed_addr] + [a for a in addr_box if a]

            async def chaos_hints(key):
                return plan.peer_prefs(chaos_addr, key)

            chaos_store = DiskStore(os.path.join(tmp, "chaos"),
                                    max_bytes=8 << 30)
            # the fault plane arms at client CONSTRUCTION — set the env
            # first, like a real worker booting into a chaos run
            os.environ["TPU9_FAULTS"] = \
                f"tree_peer_loss:peer={victim},after_calls=2"
            try:
                chaos_cl = CacheClient(chaos_store, all_peers,
                                       source=source_fn)
                chaos_cl.self_address = chaos_addr
            finally:
                os.environ.pop("TPU9_FAULTS", None)
            chaos_ok = False
            try:
                chaos_cm = CheckpointManager(
                    chaos_cl, fetch_manifest=fetch_manifest,
                    tree_hints=chaos_hints)
                t0c = time.perf_counter()
                trees, _m = await chaos_cm.restore_params(
                    ckpt, device_put=ident)
                out["scaleout_chaos_restore_s"] = round(
                    time.perf_counter() - t0c, 4)
                chaos_ok = bool(trees and len(trees) == n_groups)
            except Exception as exc:   # noqa: BLE001 — a failed restore
                                       # IS the violation being tested
                out["scaleout_chaos_error"] = \
                    f"{type(exc).__name__}: {exc}"
            out["scaleout_chaos_restore_ok"] = chaos_ok
            out["scaleout_chaos_peer_errors"] = \
                chaos_cl.stats["peer_errors"]
            out["scaleout_chaos_source_bytes"] = \
                chaos_cl.stats["bytes_source"]
            await chaos_cl.close()
            if not chaos_ok:
                violations.append(
                    "scaleout: chaos restore FAILED under tree_peer_loss "
                    "— peer death must fall through to survivors, never "
                    "fail the restore")
            if chaos_ok and not chaos_cl.stats["peer_errors"]:
                violations.append(
                    "scaleout: tree_peer_loss never fired — the chaos "
                    "leg tested nothing")
            if chaos_ok and chaos_cl.stats["bytes_source"] > 0:
                violations.append(
                    "scaleout: chaos restore fell back to SOURCE while "
                    "live peers held every group — re-plan must prefer "
                    "surviving holders")

            # evidence artifact: the same report /api/v1/scaleout serves
            out["scaleout_report"] = build_report(
                coord.ledger.snapshot(), plan)
            out["scaleout_coordinator"] = coord.stats()
        finally:
            stop_evt.set()
            for t in threads:
                await asyncio.to_thread(t.join, 30)
            if seed_client is not None:
                try:
                    await seed_client.close()
                except Exception:   # noqa: BLE001 — teardown
                    pass
            if seed_srv is not None:
                try:
                    await seed_srv.stop()
                except Exception:   # noqa: BLE001 — teardown
                    pass
            await asyncio.to_thread(shutil.rmtree, tmp, ignore_errors=True)
        out["violations"] = violations
        out["valid"] = not violations
        return out

    return asyncio.run(run())


def bench_cold_start_jax_tpu(quick: bool = False) -> dict:
    """On-CHIP JAX restore cold start (VERDICT r04 next-round #1): same
    restore loop as ``bench_cold_start_jax`` but the runner container dials
    the real TPU — so the measured p50 includes libtpu/PJRT init and the
    persistent-compile-cache restore on the hardware, which the CPU-host
    number structurally cannot show. Parent stays forced-CPU like
    ``bench_llm_endpoint``; only the container gets the tunnel env."""
    import tempfile

    tunnel_env = {k: os.environ[k] for k in _TUNNEL_ENV_KEYS
                  if k in os.environ}
    cpu_forced = os.environ.get("TPU9_BENCH_CPU") == "1"
    on_real_tpu = bool(tunnel_env.get("JAX_PLATFORMS")) and not cpu_forced

    from tpu9.utils import force_cpu
    force_cpu(host_devices=0)      # this process must never dial the chip

    cache_dir = tempfile.mkdtemp(prefix="tpu9-bench-jaxcache-tpu-")
    container_env = {
        "JAX_COMPILATION_CACHE_DIR": cache_dir,
        "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS": "0",
        "JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES": "0"}
    if on_real_tpu:
        container_env.update(tunnel_env)
        container_env["PYTHONPATH"] = "/root/.axon_site"
    elif cpu_forced:
        container_env["JAX_PLATFORMS"] = "cpu"
    # else: leave JAX_PLATFORMS unset — a direct-attached (non-tunnel) chip
    # is auto-detected by the container; the backend check below still
    # rejects the numbers if no chip was actually reached

    try:
        out, violations, first = _bench_jax_restore(
            "coldstart_jax_tpu", container_env, cache_dir,
            trials=2 if quick else 3,   # tunnel windows are precious
            suffix="_tpu", invoke_timeout=600.0)
        out["jax_restore_tpu_container_on_tpu"] = on_real_tpu
        backend = (first.get("backend") or "").lower()
        kind = (first.get("kind") or "").lower()
        out["jax_restore_tpu_backend"] = backend
        out["jax_restore_tpu_device_kind"] = first.get("kind", "")
        # same polarity as tpu9.utils.on_tpu(): a tunnel backend may not be
        # literally named "tpu" but its devices report a TPU kind. Unless
        # the whole bench was explicitly CPU-forced, a non-chip container
        # is a violation — this phase exists ONLY to produce on-chip
        # numbers, and an off-chip p50 must never ship under the _tpu key
        # (even if the chip was auto-detected without tunnel env).
        container_on_chip = backend != "cpu" and (
            "tpu" in backend or "tpu" in kind)
        if not cpu_forced and not container_on_chip:
            violations.append(
                "coldstart_jax_tpu: container backend is "
                f"'{backend}' (kind '{kind}'), not a TPU — the restore "
                "numbers would not be on-chip")
        out["violations"] = violations
        out["valid"] = not violations
        return out
    finally:
        import shutil
        shutil.rmtree(cache_dir, ignore_errors=True)


# ---------------------------------------------------------------------------
# phase: fleet router (ISSUE 2) — p50/p99 TTFT under mixed-tenant load with
# affinity on vs off, and shed behavior under overload. Drives the REAL
# FleetRouter (fair queue, affinity table, admission, signals) against a
# simulated replica fleet whose service time models KV prefix reuse: a
# replica serving a prompt whose prefix it has cached skips the prefill
# cost. Pure asyncio, CPU-only, deterministic seed.
# ---------------------------------------------------------------------------

def bench_router(quick: bool = False) -> dict:
    import asyncio
    import random as _random

    from tpu9.abstractions.common.buffer import ForwardResult
    from tpu9.config import RouterConfig
    from tpu9.router import FleetRouter
    from tpu9.statestore import MemoryStore
    from tpu9.types import ContainerState, ContainerStatus, Stub, StubConfig

    N_REPLICAS = 4
    N_REQUESTS = 120 if quick else 400
    N_GROUPS = 12            # distinct shared prefixes in the workload
    CACHE_GROUPS = 4         # per-replica KV capacity, in prefix groups
    BASE_MS = 2.0            # decode/dispatch floor per request
    PREFILL_MS = 10.0        # full prefill when the prefix is NOT cached
    STAGGER_MS = 1.5         # request inter-arrival

    class FakeFleet:
        def __init__(self, n):
            self.states = [ContainerState(
                container_id=f"r{i}", stub_id="s",
                status=ContainerStatus.RUNNING.value,
                address=f"127.0.0.1:{9000 + i}") for i in range(n)]

        async def containers_by_stub(self, stub_id, status=None):
            return list(self.states)

    def build_workload():
        """Mixed tenants: one flooding tenant (60% of traffic, long
        prompts), two light tenants. Seeded — both routing modes see the
        IDENTICAL sequence."""
        rng = _random.Random(1994)
        out = []
        for i in range(N_REQUESTS):
            r = rng.random()
            tenant = "flood" if r < 0.6 else ("chat-b" if r < 0.8 else "chat-c")
            group = rng.randrange(N_GROUPS)
            prefix = [group * 1000 + t for t in range(64)]   # 4 blocks of 16
            body = json.dumps({"tokens": prefix + [90000 + i],
                               "max_new_tokens": 16,
                               "_group": group}).encode()
            out.append((tenant, group, body))
        return out

    async def run_mode(affinity_on: bool) -> dict:
        cfg = RouterConfig(default_replica_inflight=4,
                           max_queue_depth=10000, max_queue_wait_s=30.0,
                           affinity_block_tokens=16)
        router = FleetRouter(cfg, MemoryStore(), FakeFleet(N_REPLICAS))
        if not affinity_on:
            rng = _random.Random(71)

            def random_order(body, replicas, load, saturated=None):
                out = list(replicas)
                rng.shuffle(out)
                return out

            router.affinity.order = random_order
        stub = Stub(stub_id="s", name="s", workspace_id="w",
                    config=StubConfig(timeout_s=60.0))
        # replica KV caches: group-granular LRU, bounded like a real pool
        caches: dict[str, list] = {f"r{i}": [] for i in range(N_REPLICAS)}
        hits = misses = 0

        def forward_for(group):
            async def forward(prefer):
                nonlocal hits, misses
                cid = prefer[0] if prefer else "r0"
                cache = caches[cid]
                if group in cache:
                    hits += 1
                    cache.remove(group)
                    cost_ms = BASE_MS
                else:
                    misses += 1
                    cost_ms = BASE_MS + PREFILL_MS
                    if len(cache) >= CACHE_GROUPS:
                        cache.pop(0)
                cache.append(group)
                await asyncio.sleep(cost_ms / 1000.0)
                return ForwardResult(status=200, body=b"{}",
                                     container_id=cid)
            return forward

        workload = build_workload()
        ttfts: list[float] = []

        async def one(tenant, group, body):
            t0 = time.monotonic()
            res = await router.submit(stub, tenant, body, forward_for(group))
            assert res.status == 200
            ttfts.append((time.monotonic() - t0) * 1000.0)

        tasks = []
        for tenant, group, body in workload:
            tasks.append(asyncio.create_task(one(tenant, group, body)))
            await asyncio.sleep(STAGGER_MS / 1000.0)
        await asyncio.gather(*tasks)
        await router.stop()
        ttfts.sort()
        total = hits + misses
        return {
            "ttft_p50_ms": round(ttfts[len(ttfts) // 2], 3),
            "ttft_p99_ms": round(ttfts[int(len(ttfts) * 0.99) - 1], 3),
            "kv_hit_rate": round(hits / total, 4) if total else 0.0,
            "router_hit_rate": round(
                router.affinity.stats()["hit_rate"], 4),
        }

    async def run_overload() -> dict:
        """Burst past a tiny admission window: shed rate + honest 429s."""
        cfg = RouterConfig(default_replica_inflight=1, max_queue_depth=2,
                           max_queue_wait_s=10.0)
        router = FleetRouter(cfg, MemoryStore(), FakeFleet(1))
        stub = Stub(stub_id="s", name="s", workspace_id="w",
                    config=StubConfig(timeout_s=60.0))

        async def slow_forward(prefer):
            await asyncio.sleep(0.05)
            return ForwardResult(status=200, body=b"{}", container_id="r0")

        body = json.dumps({"tokens": list(range(32))}).encode()
        results = await asyncio.gather(*[
            router.submit(stub, "burst", body, slow_forward)
            for _ in range(12)])
        await router.stop()
        shed = [r for r in results if r.status == 429]
        ok = [r for r in results if r.status == 200]
        bad_headers = [r for r in shed
                       if "Retry-After" not in dict(r.headers)]
        return {"shed_rate": round(len(shed) / len(results), 4),
                "served": len(ok), "shed": len(shed),
                "sheds_missing_retry_after": len(bad_headers)}

    async def run_all():
        return (await run_mode(affinity_on=True),
                await run_mode(affinity_on=False),
                await run_overload())

    aff, rand, overload = asyncio.run(run_all())

    out = {
        "router_ttft_p50_ms": aff["ttft_p50_ms"],
        "router_ttft_p99_ms": aff["ttft_p99_ms"],
        "router_ttft_random_p50_ms": rand["ttft_p50_ms"],
        "router_ttft_random_p99_ms": rand["ttft_p99_ms"],
        "router_kv_hit_rate": aff["kv_hit_rate"],
        "router_kv_hit_rate_random": rand["kv_hit_rate"],
        "router_prefix_hit_rate": aff["router_hit_rate"],
        "router_shed_rate": overload["shed_rate"],
        "router_overload_served": overload["served"],
        "router_requests": N_REQUESTS,
    }
    violations = []
    # affinity must not be slower than random routing (the whole point of
    # KV-aware placement is a better TTFT; 5% tolerance for jitter)
    if aff["ttft_p50_ms"] > rand["ttft_p50_ms"] * 1.05:
        violations.append(
            f"affinity p50 {aff['ttft_p50_ms']}ms slower than random "
            f"{rand['ttft_p50_ms']}ms")
    if aff["kv_hit_rate"] <= rand["kv_hit_rate"]:
        violations.append(
            f"affinity kv hit rate {aff['kv_hit_rate']} not better than "
            f"random {rand['kv_hit_rate']}")
    if overload["shed"] == 0 or overload["served"] == 0:
        violations.append("overload phase did not both shed and serve")
    if overload["sheds_missing_retry_after"]:
        violations.append(
            f"{overload['sheds_missing_retry_after']} sheds lacked "
            "Retry-After")
    out["violations"] = violations
    out["valid"] = not violations
    return out


# ---------------------------------------------------------------------------
# phase: request survivability under induced faults (ISSUE 15) — a
# simulated replica fleet driven through the REAL FleetRouter, the REAL
# gateway failover driver (survival.submit_with_failover) and the REAL
# watermark-splice machinery (survival.StreamResumption), with the
# deterministic fault plane (tpu9.testing.faults) scheduling replica
# crashes, stalls and RPC transport errors. Never imports jax.
#
# Gates (bench_guard): zero client-visible failed requests is HARD (a
# violation strips the headline fields, and faults_recovery_p95_s is in
# HARD_FIELDS so the stripped round FAILS); recovery-time p95 is guarded
# "down" across rounds. ISSUE 16 adds block-ship resume: the headline
# leg recovers by adopting shipped KV blocks, a kv-ship-off leg prices
# the re-prefill baseline it must beat at p95, and a kv_ship_error
# chaos leg proves the fallback degrades to re-prefill — never to a
# client-visible failure.
# ---------------------------------------------------------------------------

def bench_faults(quick: bool = False) -> dict:
    import asyncio

    from tpu9.abstractions.common.buffer import ForwardResult
    from tpu9.config import RouterConfig
    from tpu9.gateway import survival as sv
    from tpu9.router import FleetRouter
    from tpu9.statestore import MemoryStore
    from tpu9.testing.faults import FaultPlane, parse_spec
    from tpu9.types import ContainerState, ContainerStatus, Stub, StubConfig
    from tpu9.utils.backoff import BackoffPolicy

    N_REPLICAS = 3
    N_REQUESTS = 80 if quick else 240
    STAGGER_MS = 2.0              # request inter-arrival
    SERVICE_MS = 2.0              # healthy per-request service floor
    CRASH_DOWN_S = 0.1            # replica outage window after a crash
    STALL_S = 0.08                # wedged-dispatch latency (≫ healthy)
    # ISSUE 16: a failover retry must rebuild the victim's KV state on
    # the survivor — a full re-prefill of the delivered watermark, or an
    # O(blocks) adopt of shipped kvwire blocks. The gap between the two
    # is what block-ship resume buys. Magnitudes are the realistic ones
    # (and deliberately large enough to survive the p95 tail, which the
    # crash outage windows otherwise dominate): a multi-hundred-token
    # watermark at single-digit-k tok/s prefill is hundreds of ms; an
    # adopt is one hedged cache read + a device scatter.
    REPREFILL_S = 0.25            # watermark re-prefill on the survivor
    ADOPT_S = 0.004               # kvwire fetch + import_blocks splice

    class FakeFleet:
        def __init__(self, n):
            self.states = [ContainerState(
                container_id=f"r{i}", stub_id="s",
                status=ContainerStatus.RUNNING.value,
                address=f"127.0.0.1:{9100 + i}") for i in range(n)]

        async def containers_by_stub(self, stub_id, status=None):
            return list(self.states)

    async def run(kv_ship: bool, ship_faults: bool) -> dict:
        # deterministic fault plan: replica crashes open a recovery
        # window, stalls wedge single dispatches, rpc errors reset
        # transports — all from one seeded plane
        # times= lifts crash's oneshot default: every crash opens a
        # CRASH_DOWN_S outage window, so each one fans out into many
        # per-request failovers. Rates are tuned so the 3-replica fleet
        # never has every replica down longer than the 5-attempt backoff
        # schedule can outlast — the phase asserts the recovery machinery
        # wins a WINNABLE fight; an unwinnable one (whole fleet dark for
        # seconds) is a capacity incident, not a failover test.
        spec = "crash:prob=0.03,times=5;stall:prob=0.04;rpc_error:prob=0.05"
        if ship_faults:
            # ISSUE 16 chaos leg: half the block ships fail before the
            # fetch (the runner's kv_ship_error hook) — every one must
            # degrade to re-prefill, never to a client-visible failure
            spec += ";kv_ship_error:prob=0.5"
        plane = FaultPlane(parse_spec(spec), seed=1994)
        kv_counts = {"resumes": 0, "fallbacks": 0}
        down_until: dict[str, float] = {}
        # backoff deliberately deterministic (jitter=0) and big enough
        # (50 ms base) that recovery time is dominated by the schedule,
        # not host sleep noise — the p95 is guarded across rounds
        # 6 attempts (was 5): the ISSUE 16 chaos leg adds kv_ship_error
        # on top of the crash/stall/rpc plan, and a failed ship's
        # re-prefill keeps the retry in flight longer — one more rung on
        # the schedule keeps the fight winnable without stretching the
        # (guarded) recovery tail of requests that win earlier
        cfg = RouterConfig(default_replica_inflight=8,
                           max_queue_depth=10000, max_queue_wait_s=10.0,
                           failover_max_attempts=6,
                           failover_backoff_base_s=0.05,
                           failover_backoff_max_s=0.2)
        router = FleetRouter(cfg, MemoryStore(), FakeFleet(N_REPLICAS))
        stub = Stub(stub_id="s", name="s", workspace_id="w",
                    config=StubConfig(timeout_s=30.0))
        injected = {"crash": 0, "stall": 0, "rpc_error": 0}

        def forward_for(avoid):
            async def forward(prefer):
                # the buffer's avoid semantics (gateway failover): failed
                # replicas deprioritized unless nothing else exists
                cands = [c for c in (prefer or ["r0"])
                         if c not in avoid] or list(prefer or ["r0"])
                cid = cands[0]
                now = time.monotonic()
                if down_until.get(cid, 0.0) > now:
                    # replica still restarting: connect refused
                    return ForwardResult(
                        status=502, body=b'{"error":"ConnectRefused"}',
                        container_id=cid)
                if plane.fire("crash"):
                    injected["crash"] += 1
                    down_until[cid] = now + CRASH_DOWN_S
                    return ForwardResult(
                        status=500,
                        body=b'{"error":"engine failure: induced"}',
                        container_id=cid)
                if plane.fire("rpc_error"):
                    injected["rpc_error"] += 1
                    return ForwardResult(
                        status=502,
                        body=b'{"error":"ConnectionResetError"}',
                        container_id=cid)
                svc = SERVICE_MS / 1000.0
                if avoid:
                    # failover retry: the survivor rebuilds the victim's
                    # KV — adopt shipped blocks when the ship lands,
                    # re-prefill the watermark when it doesn't (kv ship
                    # disabled, or the kv_ship_error fault fired)
                    if kv_ship and not plane.fire("kv_ship_error"):
                        kv_counts["resumes"] += 1
                        svc += ADOPT_S
                    else:
                        kv_counts["fallbacks"] += 1
                        svc += REPREFILL_S
                if plane.fire("stall"):
                    injected["stall"] += 1
                    svc += STALL_S    # wedged dispatch, then the
                    #                   watchdog-shaped 502
                    await asyncio.sleep(svc)
                    return ForwardResult(
                        status=502, body=b'{"error":"stream_gap"}',
                        container_id=cid)
                await asyncio.sleep(svc)
                return ForwardResult(status=200, body=b'{"ok":1}',
                                     container_id=cid)
            return forward

        recoveries: list[float] = []
        outcomes = {"ok": 0, "failed": 0, "failovers": 0}

        async def one(i: int) -> None:
            body = json.dumps({"tokens": [i % 7, i % 11, i % 13],
                               "max_new_tokens": 8}).encode()
            budget = sv.FailoverBudget(
                cfg.failover_max_attempts,
                BackoffPolicy(base_s=cfg.failover_backoff_base_s,
                              max_s=cfg.failover_backoff_max_s,
                              jitter=0.0))

            async def attempt(attempt, avoid):
                return await router.submit(stub, "chaos", body,
                                           forward_for(avoid))

            t_fail = [0.0]

            def on_failover(attempt, failed, delay):
                outcomes["failovers"] += 1
                if t_fail[0] == 0.0:
                    t_fail[0] = time.monotonic()

            res = await sv.submit_with_failover(attempt, budget,
                                                on_failover=on_failover)
            if res.status == 200:
                outcomes["ok"] += 1
                if t_fail[0]:
                    recoveries.append(time.monotonic() - t_fail[0])
            else:
                outcomes["failed"] += 1

        tasks = []
        for i in range(N_REQUESTS):
            tasks.append(asyncio.create_task(one(i)))
            await asyncio.sleep(STAGGER_MS / 1000.0)
        await asyncio.gather(*tasks)
        await router.stop()

        # ---- mid-stream watermark splice, same machinery the gateway
        # runs: a deterministic 'model' killed mid-generation, resumed
        # via prompt+delivered replay — the client sequence must equal
        # the unkilled reference exactly (no dup, no skip)
        def model_next(prefix):
            return (sum(prefix) * 31 + len(prefix)) % 997

        def serve(prompt, max_new, die_after=None):
            toks, prefix = [], list(prompt)
            for j in range(max_new):
                if die_after is not None and j >= die_after:
                    return toks, True
                t = model_next(prefix)
                toks.append(t)
                prefix.append(t)
            return toks, False

        splice_ok = 0
        splice_n = 16 if quick else 48
        for j in range(splice_n):
            prompt = [j + 1, (j * 3) % 17 + 1]
            max_new = 8 + (j % 9)
            die_after = 1 + (j % (max_new - 1)) if max_new > 1 else None
            reference, _ = serve(prompt, max_new)
            res = sv.StreamResumption(prompt, max_new,
                                      {"tokens": prompt,
                                       "max_new_tokens": max_new})
            got, died = serve(prompt, max_new, die_after=die_after)
            for t in got:
                res.note_token(t)
            body = json.loads(res.resume_payload())
            got2, _ = serve(body["tokens"], body["max_new_tokens"])
            for t in got2:
                res.note_token(t)
            if res.delivered == reference:
                splice_ok += 1

        recoveries.sort()

        def pct(p):
            if not recoveries:
                return 0.0
            return recoveries[min(int(len(recoveries) * p),
                                  len(recoveries) - 1)]

        return {"outcomes": outcomes, "injected": dict(injected),
                "kv": dict(kv_counts),
                "recovery_p50_s": round(pct(0.50), 4),
                "recovery_p95_s": round(pct(0.95), 4),
                "recovered": len(recoveries),
                "splice_ok": splice_ok, "splice_n": splice_n}

    # three legs, one seed (ISSUE 16): the headline leg recovers via
    # block-ship resume; the reprefill leg is the same chaos with kv
    # ship off (the improvement baseline); the chaos leg fault-injects
    # the ship itself (kv_ship_error) — every failed ship must degrade
    # to re-prefill with ZERO client-visible failures
    r = asyncio.run(run(kv_ship=True, ship_faults=False))
    r_off = asyncio.run(run(kv_ship=False, ship_faults=False))
    r_chaos = asyncio.run(run(kv_ship=True, ship_faults=True))
    out = {
        "faults_requests": N_REQUESTS,
        "faults_failed_requests": r["outcomes"]["failed"],
        "faults_failovers": r["outcomes"]["failovers"],
        "faults_recovered": r["recovered"],
        "faults_recovery_p50_s": r["recovery_p50_s"],
        "faults_recovery_p95_s": r["recovery_p95_s"],
        "faults_injected_crash": r["injected"]["crash"],
        "faults_injected_stall": r["injected"]["stall"],
        "faults_injected_rpc_error": r["injected"]["rpc_error"],
        "faults_stream_splice_ok": r["splice_ok"],
        "faults_stream_splice_n": r["splice_n"],
        "faults_kv_resumes": r["kv"]["resumes"],
        "faults_recovery_p95_reprefill_s": r_off["recovery_p95_s"],
        "faults_kv_fallbacks": r_chaos["kv"]["fallbacks"],
        "faults_kv_chaos_failed_requests": r_chaos["outcomes"]["failed"],
    }
    violations = []
    failed_total = (r["outcomes"]["failed"] + r_off["outcomes"]["failed"]
                    + r_chaos["outcomes"]["failed"])
    if failed_total > 0:
        violations.append(
            f"{failed_total} client-visible failed requests "
            "under induced faults (must be ZERO across all legs)")
    if r["outcomes"]["failovers"] == 0 or sum(r["injected"].values()) == 0:
        violations.append("no faults were actually induced — the chaos "
                          "phase measured nothing")
    if r["splice_ok"] != r["splice_n"]:
        violations.append(
            f"stream splice produced a duplicated/skipped token in "
            f"{r['splice_n'] - r['splice_ok']}/{r['splice_n']} resumes")
    if r["recovered"] == 0:
        violations.append("no request actually recovered via failover")
    if r["kv"]["resumes"] == 0:
        violations.append("no failover actually resumed via block ship")
    if r["recovered"] and r_off["recovered"] \
            and r["recovery_p95_s"] >= r_off["recovery_p95_s"]:
        violations.append(
            f"block-ship resume did not improve recovery p95 "
            f"({r['recovery_p95_s']}s vs re-prefill "
            f"{r_off['recovery_p95_s']}s)")
    if r_chaos["kv"]["fallbacks"] == 0:
        violations.append("kv_ship_error injected nothing — the "
                          "re-prefill fallback went unexercised")
    out["violations"] = violations
    out["valid"] = not violations
    return out


# ---------------------------------------------------------------------------
# phase: disaggregated prefill/decode + the KV wire format (ISSUE 16).
#
# Two legs:
#
# 1. kvwire roundtrip bit-exactness through the REAL pool machinery
#    (KvPool.export_blocks → import_blocks → re-export) on bf16 and
#    int8(+scale-plane) pools, plus the version gate. Judged HARD the
#    way quant parity is: a violation strips kvwire_roundtrip_exact
#    from the round, and bench_guard's HARD presence check fails the
#    stripped round.
#
# 2. TTFT p99 under a mixed long-doc / short-chat workload through the
#    REAL FleetRouter with the disagg policy on vs off. The replica
#    model is the continuous-batching interference disagg exists to
#    remove: prefills serialize per replica, and a prefill slows by
#    (1 + concurrent decodes) — so with disagg OFF, short chats queue
#    behind multi-hundred-ms long-doc prefills and long-doc prefills
#    crawl through decode-heavy replicas. Gates: disagg ON must WIN
#    long-doc p99 and never lose >2% short-chat p99.
# ---------------------------------------------------------------------------

def bench_disagg(quick: bool = False) -> dict:
    import asyncio

    import numpy as np

    out: dict = {}
    violations: list[str] = []

    # ---- leg 1: kvwire roundtrip bit-exactness ----------------------------
    import jax.numpy as jnp

    from tpu9.models.llama import LLAMA_PRESETS
    from tpu9.serving import kvwire
    from tpu9.serving.engine import EngineConfig
    from tpu9.serving.kvpool import KvPool
    from tpu9.serving.paged_kv import PrefixCache
    from tpu9.serving.shard import make_policy

    cfg = LLAMA_PRESETS["llama-tiny"]
    ecfg = EngineConfig(max_batch=2, max_seq_len=256,
                        prefill_buckets=(32, 64), decode_steps=(1, 4),
                        kv_block_size=32, kv_pool_blocks=16,
                        prefill_chunk=32, prefix_cache_blocks=8)
    rng = np.random.default_rng(7)
    exact = True
    payload = b""
    for kv_quant in (False, True):
        pool_a = KvPool(cfg, ecfg, kv_quant, make_policy(None))
        kv_a = pool_a.init_arrays()
        blocks = pool_a.alloc_blocks(3)
        idx = jnp.asarray(blocks, dtype=jnp.int32)
        for name in pool_a.wire_names():
            shape, dt = pool_a.array_shapes()[name]
            sub = (shape[0], len(blocks)) + tuple(shape[2:])
            vals = (rng.integers(-127, 128, size=sub, dtype=np.int8)
                    if np.dtype(dt) == np.dtype(np.int8)
                    else rng.standard_normal(sub).astype(np.float32))
            kv_a[name] = kv_a[name].at[:, idx].set(
                jnp.asarray(vals, dtype=dt))
        tokens = [(i * 7) % 211 + 1 for i in range(3 * 32)]
        t0 = time.perf_counter()
        payload = pool_a.export_blocks(
            kv_a, blocks, PrefixCache._key(tokens), len(tokens))
        t_exp = time.perf_counter() - t0
        pool_b = KvPool(cfg, ecfg, kv_quant, make_policy(None))
        kv_b = pool_b.init_arrays()
        t0 = time.perf_counter()
        kv_b, adopted, _ = pool_b.import_blocks(kv_b, payload)
        t_imp = time.perf_counter() - t0
        entry = pool_b.prefix_cache.acquire_for_export(tokens)
        back = b""
        if entry is not None:
            back = pool_b.export_blocks(kv_b, entry.blocks, entry.key,
                                        entry.n_tokens)
            pool_b.prefix_cache.release_pin(entry)
        which = "int8" if kv_quant else "bf16"
        if not (adopted and back == payload):
            exact = False
            violations.append(
                f"kvwire roundtrip not bit-exact ({which} pool)")
        out[f"kvwire_payload_kb_{which}"] = round(len(payload) / 1024, 2)
        out[f"kvwire_export_ms_{which}"] = round(t_exp * 1000, 3)
        out[f"kvwire_import_ms_{which}"] = round(t_imp * 1000, 3)
    # version gate: a bumped payload must refuse loudly, not misparse
    bumped = bytearray(payload)
    struct.pack_into("<H", bumped, 7, kvwire.FORMAT_VERSION + 1)
    try:
        kvwire.decode_header(bytes(bumped))
        exact = False
        violations.append("kvwire accepted an unknown format version")
    except kvwire.KvWireError:
        pass
    out["kvwire_roundtrip_exact"] = 1 if exact else 0

    # ---- leg 2: disagg routing, TTFT p99 on vs off ------------------------
    from tpu9.abstractions.common.buffer import ForwardResult
    from tpu9.config import RouterConfig
    from tpu9.router import FleetRouter
    from tpu9.statestore import MemoryStore
    from tpu9.types import ContainerState, ContainerStatus, Stub, StubConfig

    N_REPLICAS = 4
    N_REQUESTS = 160 if quick else 400
    STAGGER_MS = 4.0
    LONG_EVERY = 5                    # 20% long-doc, 80% short-chat
    LONG_PROMPT = 640                 # > disagg_prefill_tokens
    SHORT_PROMPT = 48
    PREFILL_S = {"long": 0.025, "short": 0.001}
    DECODE_S = {"long": 0.005, "short": 0.025}   # chats decode LONG

    class FakeFleet:
        def __init__(self, n):
            self.states = [ContainerState(
                container_id=f"r{i}", stub_id="s",
                status=ContainerStatus.RUNNING.value,
                address=f"127.0.0.1:{9200 + i}") for i in range(n)]

        async def containers_by_stub(self, stub_id, status=None):
            return list(self.states)

    async def run(disagg: bool) -> dict:
        cfg_r = RouterConfig(default_replica_inflight=8,
                             max_queue_depth=10000, max_queue_wait_s=30.0,
                             disagg_enabled=disagg,
                             disagg_prefill_tokens=512,
                             disagg_prefill_fraction=0.5)
        router = FleetRouter(cfg_r, MemoryStore(), FakeFleet(N_REPLICAS))
        stub = Stub(stub_id="s", name="s", workspace_id="w",
                    config=StubConfig(timeout_s=60.0))
        prefill_lock = {f"r{i}": asyncio.Lock() for i in range(N_REPLICAS)}
        decoding = {f"r{i}": 0 for i in range(N_REPLICAS)}
        # the deterministic partition _disagg_order computes: sorted ids,
        # first ceil(0.5 * 4) = 2 lean prefill
        prefill_part = {"r0", "r1"}
        ttft = {"long": [], "short": []}
        placed = {"long_on_prefill": 0, "long": 0}

        def forward_for(kind, t_start):
            async def forward(prefer):
                cid = (prefer or ["r0"])[0]
                if kind == "long":
                    placed["long"] += 1
                    placed["long_on_prefill"] += cid in prefill_part
                async with prefill_lock[cid]:
                    # continuous-batching interference: a prefill step
                    # shares the replica with every in-flight decode
                    slow = 1.0 + decoding[cid]
                    await asyncio.sleep(PREFILL_S[kind] * slow)
                ttft[kind].append(time.monotonic() - t_start)
                decoding[cid] += 1
                try:
                    await asyncio.sleep(DECODE_S[kind])
                finally:
                    decoding[cid] -= 1
                return ForwardResult(status=200, body=b'{"ok":1}',
                                     container_id=cid)
            return forward

        async def one(i: int) -> int:
            kind = "long" if i % LONG_EVERY == 0 else "short"
            n = LONG_PROMPT if kind == "long" else SHORT_PROMPT
            body = json.dumps({"tokens": [(i + j) % 251 + 1
                                          for j in range(n)],
                               "max_new_tokens":
                                   8 if kind == "long" else 128}).encode()
            res = await router.submit(stub, "mix", body,
                                      forward_for(kind, time.monotonic()))
            return res.status

        tasks = []
        for i in range(N_REQUESTS):
            tasks.append(asyncio.create_task(one(i)))
            await asyncio.sleep(STAGGER_MS / 1000.0)
        statuses = await asyncio.gather(*tasks)
        await router.stop()

        def p99(xs):
            xs = sorted(xs)
            return xs[min(int(len(xs) * 0.99), len(xs) - 1)] if xs else 0.0

        return {"long_p99_ms": round(p99(ttft["long"]) * 1000, 2),
                "short_p99_ms": round(p99(ttft["short"]) * 1000, 2),
                "failed": sum(1 for s in statuses if s != 200),
                "long_on_prefill_frac": round(
                    placed["long_on_prefill"] / max(1, placed["long"]), 3)}

    r_on = asyncio.run(run(disagg=True))
    r_off = asyncio.run(run(disagg=False))
    out.update({
        "disagg_longdoc_ttft_p99_ms_on": r_on["long_p99_ms"],
        "disagg_longdoc_ttft_p99_ms_off": r_off["long_p99_ms"],
        "disagg_shortchat_ttft_p99_ms_on": r_on["short_p99_ms"],
        "disagg_shortchat_ttft_p99_ms_off": r_off["short_p99_ms"],
        "disagg_longdoc_ttft_improvement": round(
            r_off["long_p99_ms"] / max(r_on["long_p99_ms"], 1e-6), 3),
        "disagg_shortchat_ttft_ratio": round(
            r_on["short_p99_ms"] / max(r_off["short_p99_ms"], 1e-6), 3),
        "disagg_long_on_prefill_frac": r_on["long_on_prefill_frac"],
    })
    if r_on["failed"] or r_off["failed"]:
        violations.append(f"disagg sim dropped requests "
                          f"(on={r_on['failed']}, off={r_off['failed']})")
    if out["disagg_longdoc_ttft_improvement"] <= 1.0:
        violations.append(
            "disagg ON did not win long-doc TTFT p99 "
            f"({r_on['long_p99_ms']}ms vs off {r_off['long_p99_ms']}ms)")
    if out["disagg_shortchat_ttft_ratio"] > 1.02:
        violations.append(
            "disagg ON lost >2% short-chat TTFT p99 "
            f"(ratio {out['disagg_shortchat_ttft_ratio']})")
    if r_on["long_on_prefill_frac"] < 0.8:
        violations.append(
            "disagg placement did nothing — only "
            f"{r_on['long_on_prefill_frac']:.0%} of long-doc prompts "
            "landed on the prefill partition")
    out["violations"] = violations
    out["valid"] = not violations
    return out


# ---------------------------------------------------------------------------
# phase: KV tiering + prefix directory (ISSUE 20) — two legs:
#   1. session-reuse routing under replica churn + a scale-to-zero/restore
#      round, directory+tiers ON vs affinity-only OFF, through the REAL
#      FleetRouter (the directory fold, promotion and adopt-hint paths are
#      the production code; only the serving replicas are simulated). The
#      prefix hit rate must be STRICTLY above the affinity baseline and
#      the modeled TTFT p95 no worse — the whole point of the tier ladder.
#   2. an eviction storm through the REAL KvPool, host tier on vs off:
#      down-paging must keep prefixes findable that the untiered pool
#      destroys, and one timed down/up-page cycle prices the paging path.
# ---------------------------------------------------------------------------


def bench_kvtier(quick: bool = False) -> dict:
    import asyncio

    out: dict = {}
    violations: list[str] = []

    from tpu9.abstractions.common.buffer import ForwardResult
    from tpu9.config import RouterConfig
    from tpu9.router import FleetRouter
    from tpu9.router.affinity import block_keys
    from tpu9.statestore import MemoryStore
    from tpu9.types import ContainerState, ContainerStatus, Stub, StubConfig

    BT = 16                               # affinity_block_tokens
    N_REPLICAS = 3
    N_SESSIONS = 12 if quick else 24
    TURNS = 4 if quick else 6
    CHURN_EVERY = 2                       # kill a replica every N turns
    PREFIX_BLOCKS = 12                    # 192-token session prefix
    BASE_MS = 1.0
    PREFILL_MS_PER_TOK = 0.02             # recompute price per token
    ADOPT_MS = 0.4                        # peer pull price (flat)

    class FakeFleet:
        def __init__(self, n):
            self.next_id = n
            self.states = [self._mk(i) for i in range(n)]

        @staticmethod
        def _mk(i):
            return ContainerState(
                container_id=f"r{i}", stub_id="s",
                status=ContainerStatus.RUNNING.value,
                address=f"127.0.0.1:{9300 + i}")

        def replace(self, cid: str) -> str:
            self.states = [st for st in self.states
                           if st.container_id != cid]
            st = self._mk(self.next_id)
            self.next_id += 1
            self.states.append(st)
            return st.container_id

        async def containers_by_stub(self, stub_id, status=None):
            return list(self.states)

    def session_tokens(s: int) -> list:
        return [(s * 131 + j * 7) % 251 + 1
                for j in range(PREFIX_BLOCKS * BT)]

    async def run(directory: bool) -> dict:
        import os
        os.environ.pop("TPU9_KV_TIER", None)
        cfg_r = RouterConfig(default_replica_inflight=8,
                             max_queue_depth=10000, max_queue_wait_s=30.0,
                             affinity_block_tokens=BT,
                             prefix_directory=directory)
        fleet = FakeFleet(N_REPLICAS)
        router = FleetRouter(cfg_r, MemoryStore(), fleet)
        if not directory:
            router.prefix_dir = None      # affinity-only baseline
        stub = Stub(stub_id="s", name="s", workspace_id="w",
                    config=StubConfig(timeout_s=60.0))
        # simulated replica prefix caches: cid -> {key_hex: n_tokens}
        caches: dict = {st.container_id: {} for st in fleet.states}
        key_hits: dict = {}               # (cid, key_hex) -> hit count
        ttft_ms: list = []
        hits = [0]
        total = [0]

        def heartbeat():
            """Fold each live replica's digest (and hot-key peer
            publications) into the directory — the pressure-beat path."""
            if router.prefix_dir is None:
                return
            for cid, cache in caches.items():
                stats = {"kvtier_keys": ",".join(
                    f"{k}:d:{n}" for k, n in cache.items())}
                peer = [k for k in cache
                        if key_hits.get((cid, k), 0) >= 2]
                if peer:
                    # digest == key in the sim's peer store
                    stats["kvtier_peer"] = ",".join(
                        f"{k}:{k}:{cache[k]}" for k in peer)
                router.prefix_dir.observe_replica(cid, stats)

        def forward_for(body: bytes, adopt):
            keys = [k.hex()[:16] for k in block_keys(body, BT)]
            nb_max = len(keys)

            async def forward(prefer):
                cid = (prefer or [fleet.states[0].container_id])[0]
                cache = caches.setdefault(cid, {})
                covered = 0
                for i, k in enumerate(keys):
                    if k in cache:
                        covered = (nb_max - i) * BT
                        key_hits[(cid, k)] = key_hits.get((cid, k), 0) + 1
                        break
                cost = BASE_MS
                if covered == 0 and adopt is not None:
                    # peer pull: the runner fetches kv:<digest> and the
                    # engine adopts — far cheaper than a full re-prefill
                    covered = adopt["n_tokens"]
                    cost += ADOPT_MS
                    cache[adopt["key"]] = adopt["n_tokens"]
                n_tok = len(json.loads(body)["tokens"])
                cost += PREFILL_MS_PER_TOK * max(0, n_tok - covered)
                hits[0] += covered > 0
                total[0] += 1
                ttft_ms.append(cost)
                for i, k in enumerate(keys):
                    cache[k] = (nb_max - i) * BT
                await asyncio.sleep(0.0005)
                return ForwardResult(status=200, body=b'{"ok":1}',
                                     container_id=cid)
            return forward

        in_peer = set()                   # sim peer store (key_hex)

        async def one(s: int, turn: int) -> int:
            toks = session_tokens(s) + [(turn * 13 + j) % 251 + 1
                                        for j in range(8)]
            body = json.dumps({"tokens": toks,
                               "max_new_tokens": 16}).encode()
            adopt = router.kv_adopt_hint(body)
            if adopt is not None and adopt["key"] not in in_peer:
                adopt = None              # stale hint: recompute path
            res = await router.submit(stub, "kv", body,
                                      forward_for(body, adopt))
            return res.status

        failed = 0
        for turn in range(TURNS):
            statuses = await asyncio.gather(
                *[one(s, turn) for s in range(N_SESSIONS)])
            failed += sum(1 for st in statuses if st != 200)
            heartbeat()
            if turn % CHURN_EVERY == CHURN_EVERY - 1:
                # replica death: hot keys were already peer-published on
                # the beat; claims die with the replica
                victim = fleet.states[0].container_id
                for k, n in caches.get(victim, {}).items():
                    if key_hits.get((victim, k), 0) >= 2:
                        in_peer.add(k)
                caches.pop(victim, None)
                newb = fleet.replace(victim)
                caches[newb] = {}
                router.note_dispatch_failure(victim)
        # scale-to-zero: every replica dies, fresh fleet restores; only
        # the peer tier (directory survivors + adopt hints) carries state
        for st in list(fleet.states):
            cid = st.container_id
            for k in caches.get(cid, {}):
                if key_hits.get((cid, k), 0) >= 2:
                    in_peer.add(k)
            caches.pop(cid, None)
            newb = fleet.replace(cid)
            caches[newb] = {}
            router.note_dispatch_failure(cid)
        statuses = await asyncio.gather(
            *[one(s, TURNS) for s in range(N_SESSIONS)])
        failed += sum(1 for st in statuses if st != 200)
        await router.stop()

        xs = sorted(ttft_ms)
        p95 = xs[min(int(len(xs) * 0.95), len(xs) - 1)] if xs else 0.0
        return {"hit_rate": round(hits[0] / max(1, total[0]), 4),
                "ttft_p95_ms": round(p95, 3), "failed": failed}

    r_on = asyncio.run(run(directory=True))
    r_off = asyncio.run(run(directory=False))
    out.update({
        "kvtier_prefix_hit_rate": r_on["hit_rate"],
        "kvtier_affinity_hit_rate": r_off["hit_rate"],
        "kvtier_ttft_p95_ms_on": r_on["ttft_p95_ms"],
        "kvtier_ttft_p95_ms_off": r_off["ttft_p95_ms"],
        "kvtier_ttft_p95_ratio": round(
            r_on["ttft_p95_ms"] / max(r_off["ttft_p95_ms"], 1e-6), 4),
    })
    if r_on["failed"] or r_off["failed"]:
        violations.append(f"kvtier sim dropped requests "
                          f"(on={r_on['failed']}, off={r_off['failed']})")
    if r_on["hit_rate"] <= r_off["hit_rate"]:
        violations.append(
            "prefix directory + tiers did not beat the affinity-only hit "
            f"rate ({r_on['hit_rate']} vs {r_off['hit_rate']})")
    if out["kvtier_ttft_p95_ratio"] > 1.0:
        violations.append(
            "tiering-on TTFT p95 regressed vs affinity-only "
            f"(ratio {out['kvtier_ttft_p95_ratio']})")

    # ---- leg 2: eviction storm through the real pool, tier on vs off ------
    import numpy as np

    from tpu9.models.llama import LLAMA_PRESETS
    from tpu9.serving.engine import EngineConfig
    from tpu9.serving.kvpool import KvPool
    from tpu9.serving.paged_kv import PrefixCache
    from tpu9.serving.shard import make_policy

    cfg = LLAMA_PRESETS["llama-tiny"]
    ecfg = EngineConfig(max_batch=2, max_seq_len=256,
                        prefill_buckets=(32, 64), decode_steps=(1, 4),
                        kv_block_size=32, kv_pool_blocks=16,
                        prefill_chunk=32, prefix_cache_blocks=12)
    N_PREFIXES = 10 if quick else 20

    def storm(host_mb: int) -> float:
        pool = KvPool(cfg, ecfg, False, make_policy(None),
                      host_pool_mb=host_mb)
        kv = pool.init_arrays()
        inserted = []
        for i in range(N_PREFIXES):
            blocks = pool.alloc_blocks(2)
            tokens = [(i * 97 + j) % 241 + 1 for j in range(2 * 32)]
            pool.prefix_cache.insert(tokens, blocks)
            pool.allocator.release(blocks)
            inserted.append(PrefixCache._key(tokens))
            if pool.allocator.free_count < 6:
                if pool.tiered:
                    for e in pool.prefix_cache.spill_candidates(2):
                        pool.downpage(kv, e)
                pool.prefix_cache.evict_for_space(4)
        alive = sum(pool.prefix_cache.contains(k) for k in inserted)
        return alive / N_PREFIXES

    out["kvtier_storm_survival_on"] = round(storm(64), 4)
    out["kvtier_storm_survival_off"] = round(storm(0), 4)
    if out["kvtier_storm_survival_on"] <= out["kvtier_storm_survival_off"]:
        violations.append(
            "host tier did not improve eviction-storm prefix survival "
            f"({out['kvtier_storm_survival_on']} vs "
            f"{out['kvtier_storm_survival_off']})")

    # one timed down/up-page cycle prices the paging path (bit-exactness
    # is the test suite's job; the bench reports the device-sync cost)
    pool = KvPool(cfg, ecfg, False, make_policy(None), host_pool_mb=64)
    kv = pool.init_arrays()
    blocks = pool.alloc_blocks(3)
    tokens = [(j * 7) % 211 + 1 for j in range(3 * 32)]
    pool.prefix_cache.insert(tokens, blocks)
    pool.allocator.release(blocks)
    entry = pool.prefix_cache._entries[PrefixCache._key(tokens)]
    t0 = time.perf_counter()
    ok_down = pool.downpage(kv, entry)
    t_down = time.perf_counter() - t0
    t0 = time.perf_counter()
    planes = pool.uppage_planes(entry)
    kv = pool.complete_uppage(kv, entry, planes)
    np.asarray(kv[pool.wire_names()[0]])  # land the scatter
    t_up = time.perf_counter() - t0
    if not ok_down:
        violations.append("kvtier pricing cycle failed to down-page")
    out["kvtier_downpage_ms"] = round(t_down * 1000, 3)
    out["kvtier_uppage_ms"] = round(t_up * 1000, 3)

    out["violations"] = violations
    out["valid"] = not violations
    return out


# ---------------------------------------------------------------------------
# phase: speculative decoding (ISSUE 5) — tokens/sec spec-on vs spec-off
# through the REAL serving engine on two workloads: repetitive/code-like
# generations (prompt-lookup drafts must WIN) and random-token prompts
# (the acceptance-EWMA auto-disable must hold the regression under 5%).
# Greedy parity between the two engines is asserted on every request —
# a throughput win from wrong tokens is not a win.
# ---------------------------------------------------------------------------

def bench_spec(quick: bool = False) -> dict:
    import asyncio
    import random as _random

    from tpu9.serving.presets import load_engine
    from tpu9.utils import on_tpu

    os.makedirs(XLA_CACHE_DIR, exist_ok=True)
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", XLA_CACHE_DIR)

    tpu = on_tpu()
    if tpu and not quick:
        # reachable only from a STANDALONE `bench.py --phase spec` on a
        # chip host (no --cpu): the orchestrator always forces this phase
        # CPU so the regression gate stays deterministic and the precious
        # tunnel window goes to the llm/llm_endpoint/kernels phases
        settings = dict(preset="llama3-8b-int8", batch=8, max_seq=2048,
                        spec_len=8, requests=8, rep_new=256, adv_new=128,
                        passes=2, adv_passes=3, prefill_buckets=(128,),
                        decode_steps=(1, 8, 32))
    else:
        # passes: per-pass ratio noise on a shared CPU is ~±10%; the gate
        # reads the MEDIAN of paired per-pass ratios. The adversarial
        # ratio sits near 1.0 with a 0.95 gate — it gets more, shorter
        # passes so its median cannot flake below the gate on noise alone
        settings = dict(preset="llama-tiny", batch=4, max_seq=512,
                        spec_len=8, requests=4 if quick else 8,
                        rep_new=240 if quick else 400,
                        adv_new=96,
                        passes=2 if quick else 5,
                        adv_passes=3 if quick else 9,
                        prefill_buckets=(32, 64), decode_steps=(1, 4, 8))
    s = settings
    out: dict = {"spec_model": s["preset"], "spec_len": s["spec_len"],
                 "on_tpu": tpu}
    violations: list[str] = []

    # Repetitive workload: prompts whose GREEDY TRAJECTORY is genuinely
    # repetitive — found by an offline cycle search over seed prompts
    # (the random-weight bench model, like a real LLM on code/tables/
    # quoting traffic, drifts into short cycles for some contexts; these
    # seeds reach theirs within the first ~100 tokens). This is the
    # regime prompt-lookup speculation exists for. Adversarial workload:
    # uniform-random token prompts — nothing for the proposer to find,
    # the EWMA gate must keep verify compute off the hot path.
    rep_seeds = (487, 239, 232, 280, 52, 457, 404, 84)[:s["requests"]]
    rep_prompts = [[sd % 500 + 1, (sd * 7) % 500 + 1, (sd * 13) % 500 + 1]
                   * 3 for sd in rep_seeds]
    rng = _random.Random(5)
    adv_prompts = [[rng.randrange(1, 500) for _ in range(48)]
                   for _ in range(s["requests"])]

    def build(spec_len: int):
        eng = load_engine(s["preset"], max_batch=s["batch"],
                          max_seq_len=s["max_seq"],
                          prefill_buckets=s["prefill_buckets"],
                          decode_steps=s["decode_steps"],
                          spec_len=spec_len)
        eng.warmup()
        return eng

    async def one_pass(eng, prompts, max_new):
        t0 = time.perf_counter()
        outs = await asyncio.gather(*[
            eng.generate(list(p), max_new_tokens=max_new)
            for p in prompts])
        return sum(len(o) for o in outs) / (time.perf_counter() - t0), outs

    async def run() -> dict:
        res: dict = {}
        for name, prompts, max_new, passes in (
                ("repetitive", rep_prompts, s["rep_new"], s["passes"]),
                ("adversarial", adv_prompts, s["adv_new"],
                 s["adv_passes"])):
            off, on = build(0), build(s["spec_len"])
            await off.start()
            await on.start()
            for eng in (off, on):     # untimed admission/graph warm pass
                await asyncio.gather(*[
                    eng.generate(list(p), max_new_tokens=8)
                    for p in prompts])
            # PAIRED passes: each pass times off then on back-to-back and
            # the gate reads the median of per-pass ratios — host noise
            # (turbo, page cache, neighbors) drifts on seconds timescales
            # and unpaired comparisons drown a 1.1-1.3x effect in it
            ratios, offs_t, ons_t = [], [], []
            outs_off = outs_on = None
            for _ in range(passes):
                tps_off, outs_off = await one_pass(off, prompts, max_new)
                tps_on, outs_on = await one_pass(on, prompts, max_new)
                offs_t.append(tps_off)
                ons_t.append(tps_on)
                ratios.append(tps_on / tps_off)
            st = on.stats()
            await off.stop()
            await on.stop()
            res[f"spec_tokens_per_sec_off_{name}"] = round(
                statistics.median(offs_t), 1)
            res[f"spec_tokens_per_sec_on_{name}"] = round(
                statistics.median(ons_t), 1)
            res[f"spec_ratio_{name}"] = round(statistics.median(ratios), 4)
            res[f"spec_acceptance_rate_{name}"] = round(
                st["spec_acceptance_rate"], 4)
            res[f"spec_windows_{name}"] = st["spec_windows"]
            # greedy-parity evidence. Exact token-for-token parity is the
            # f32 unit tests' gate (tests/test_spec_decode.py): at bf16,
            # random-weight logits carry exact and near (1-ulp) TIES
            # whose argmax can break differently between the decode and
            # verify graph shapes — a rare tie then forks the whole
            # downstream stream. So each fork is judged against the
            # full-context forward ORACLE: the spec-emitted token must be
            # within bf16 noise of the oracle's best logit, else it is a
            # verify/rollback bug, not a tie.
            import jax.numpy as _jnp

            from tpu9.models.transformer import decoder_forward
            from tpu9.serving.presets import build_params
            oracle_params, oracle_cfg = build_params(s["preset"])
            first_div = None
            for a, b, p in zip(outs_off, outs_on, prompts):
                if len(a) != len(b):
                    violations.append(
                        f"spec: output LENGTHS diverge on {name}")
                    break
                i = next((i for i, (x, y) in enumerate(zip(a, b))
                          if x != y), None)
                if i is None:
                    continue
                first_div = i if first_div is None else min(first_div, i)
                logits = decoder_forward(
                    oracle_params, _jnp.asarray([list(p) + a[:i]],
                                                _jnp.int32),
                    oracle_cfg)[0, -1]
                margin = float(_jnp.max(logits) - logits[b[i]])
                if margin > 0.05:           # far past bf16 rounding noise
                    violations.append(
                        f"spec: stream forks at token {i} on {name} and "
                        f"the spec token is {margin:.3f} below the "
                        "oracle argmax — verify/rollback bug, not a tie")
            res[f"spec_first_divergence_{name}"] = (
                -1 if first_div is None else first_div)
        return res

    out.update(asyncio.run(run()))
    out["spec_uplift_repetitive"] = out["spec_ratio_repetitive"]
    out["spec_adversarial_ratio"] = out["spec_ratio_adversarial"]
    if out["spec_uplift_repetitive"] < 1.0:
        violations.append(
            f"spec: repetitive workload ratio "
            f"{out['spec_uplift_repetitive']} < 1.0 — speculation does "
            "not pay for its verify compute where it should win")
    if out["spec_adversarial_ratio"] < 0.95:
        violations.append(
            f"spec: adversarial workload ratio "
            f"{out['spec_adversarial_ratio']} < 0.95 — the acceptance-"
            "EWMA auto-disable is not containing the regression")
    if out["spec_acceptance_rate_repetitive"] <= \
            out["spec_acceptance_rate_adversarial"]:
        violations.append(
            "spec: repetitive acceptance not above adversarial — the "
            "proposer is not finding the structure the workload has")
    out["violations"] = violations
    out["valid"] = not violations
    return out


# ---------------------------------------------------------------------------
# phase: quantized serving (ISSUE 6) — int8 weights + int8 paged KV vs bf16
# through the REAL serving engine, plus the two pure bytes-moved headlines:
# `.tpu9w` shard bytes (cold start / scale-out traffic) and KV-pool
# capacity at equal HBM (admission headroom). Output parity between the
# engines is judged with the spec phase's oracle-margin rule — a
# throughput win from wrong tokens is not a win.
# ---------------------------------------------------------------------------

def bench_quant(quick: bool = False) -> dict:
    import asyncio
    import tempfile

    import jax
    import jax.numpy as jnp

    from tpu9.models import init_decoder
    from tpu9.models.llama import LLAMA_PRESETS
    from tpu9.models.transformer import decoder_forward
    from tpu9.ops.quant import quantize_decoder, quantized_bytes
    from tpu9.serving import weights as wfmt
    from tpu9.serving.engine import EngineConfig, InferenceEngine
    from tpu9.serving.feasibility import weight_bytes
    from tpu9.serving.paged_kv import kv_block_bytes
    from tpu9.serving.presets import resolve_preset
    from tpu9.utils import on_tpu

    os.makedirs(XLA_CACHE_DIR, exist_ok=True)
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", XLA_CACHE_DIR)

    tpu = on_tpu()
    if tpu and not quick:
        # standalone on a chip host: the ~1B preset is the smallest config
        # where decode is genuinely HBM-bandwidth-bound AND the bf16
        # baseline still fits next to the quantized engine
        s = dict(preset="llama-1b", batch=8, max_seq=2048,
                 prefill_buckets=(128,), decode_steps=(1, 8, 32),
                 kv_block=256, requests=8, max_new=192, passes=2,
                 dtype=None, tps_gate=1.15)
    else:
        # CPU (the orchestrated/regression path): compute-bound, so the
        # HBM win physically cannot show — the tokens/sec gate here is
        # only a catastrophe floor; the byte/capacity headlines and the
        # parity judge are the CPU-verifiable contract. f32 activations
        # kill bf16 argmax-tie noise in the parity comparison.
        s = dict(preset="llama-tiny", batch=4, max_seq=512,
                 prefill_buckets=(32, 64), decode_steps=(1, 4, 8),
                 kv_block=32, requests=4, max_new=96 if quick else 160,
                 passes=2 if quick else 3, dtype=jnp.float32,
                 tps_gate=0.5)
    out: dict = {"quant_model": s["preset"], "on_tpu": tpu}
    violations: list[str] = []

    from dataclasses import replace as _replace
    cfg, _ = resolve_preset(s["preset"])
    if s["dtype"] is not None:
        cfg = _replace(cfg, dtype=s["dtype"])

    # -- headline 1: .tpu9w shard bytes (flagship arithmetic + measured) --
    # the flagship ratio comes from the EXACT abstract-tree byte counts
    # the feasibility gate uses (jax.eval_shape — nothing materializes);
    # the measured ratio writes real tiny shards through save_params to
    # prove the pipeline (quantize → v2 index → shards) delivers it.
    # Measurements use the preset's REAL dtype (bf16): the f32 override
    # below exists only so the parity comparison has no argmax-tie noise
    # — an f32 baseline would inflate the "measured" int8 win ~2x over
    # the bf16 deployment story the flagship numbers tell.
    cfg8b, _ = resolve_preset("llama3-8b")
    mcfg, _ = resolve_preset(s["preset"])
    out["quant_shard_bytes_ratio"] = round(
        weight_bytes(cfg8b, False) / weight_bytes(cfg8b, True), 4)
    mparams = init_decoder(jax.random.PRNGKey(0), mcfg)
    with tempfile.TemporaryDirectory() as td:
        di = wfmt.save_params(mparams, os.path.join(td, "d.tpu9w"))
        qi = wfmt.save_params(mparams, os.path.join(td, "q.tpu9w"),
                              quantize="int8")
        out["quant_shard_bytes_ratio_measured"] = round(
            di["total_bytes"] / qi["total_bytes"], 4)
        out["quant_shard_index_version"] = qi["version"]
    if out["quant_shard_bytes_ratio"] < 1.8:
        violations.append(
            f"quant: flagship shard-bytes ratio "
            f"{out['quant_shard_bytes_ratio']} < 1.8")
    if abs(quantized_bytes(quantize_decoder(mparams)) - qi["total_bytes"]) \
            > qi["total_bytes"] * 0.01:
        violations.append("quant: feasibility bytes disagree with the "
                          "shards actually written")
    del mparams

    # -- headline 2: KV-pool capacity at equal HBM ------------------------
    # flagship arithmetic from the SAME helper the engine's auto sizing
    # divides by; measured from two real engines' allocators below
    out["quant_kv_capacity_ratio"] = round(
        kv_block_bytes(cfg8b, 256, False)
        / kv_block_bytes(cfg8b, 256, True), 4)
    if out["quant_kv_capacity_ratio"] < 1.9:
        violations.append(
            f"quant: flagship KV capacity ratio "
            f"{out['quant_kv_capacity_ratio']} < 1.9")

    def build(params, bcfg, kv_quant: str, warm: bool = True):
        eng = InferenceEngine(params, bcfg, EngineConfig(
            max_batch=s["batch"], max_seq_len=s["max_seq"],
            prefill_buckets=s["prefill_buckets"],
            decode_steps=s["decode_steps"],
            kv_block_size=s["kv_block"], kv_pool_blocks=0,
            prefill_chunk=min(s["prefill_buckets"]),
            prefix_cache_blocks=s["max_seq"] // s["kv_block"],
            kv_quant=kv_quant))
        if warm:
            eng.warmup()
        return eng

    # measured capacity at the preset's REAL dtype: construction alone
    # sizes the pools — no warmup, no weights touched
    m_off = build({}, mcfg, "", warm=False)
    m_on = build({}, mcfg, "int8", warm=False)
    out["quant_kv_blocks_bf16"] = m_off.allocator.n_blocks - 1
    out["quant_kv_blocks_int8"] = m_on.allocator.n_blocks - 1
    out["quant_kv_capacity_ratio_measured"] = round(
        (m_on.allocator.n_blocks - 1) / (m_off.allocator.n_blocks - 1), 4)
    del m_off, m_on

    dense_params = init_decoder(jax.random.PRNGKey(0), cfg)
    quant_params = quantize_decoder(dense_params)
    del dense_params
    off = build(quant_params, cfg, "")
    on = build(quant_params, cfg, "int8")

    # -- tokens/sec + parity: paired passes through both engines ----------
    import random as _random
    rng = _random.Random(11)
    prompts = [[rng.randrange(1, 400) for _ in range(24)]
               for _ in range(s["requests"])]

    async def one_pass(eng):
        t0 = time.perf_counter()
        outs = await asyncio.gather(*[
            eng.generate(list(p), max_new_tokens=s["max_new"])
            for p in prompts])
        return sum(len(o) for o in outs) / (time.perf_counter() - t0), outs

    async def run():
        await off.start()
        await on.start()
        for eng in (off, on):        # untimed admission/graph warm pass
            await asyncio.gather(*[
                eng.generate(list(p), max_new_tokens=8) for p in prompts])
        ratios, offs_t, ons_t = [], [], []
        outs_off = outs_on = None
        for _ in range(s["passes"]):
            tps_off, outs_off = await one_pass(off)
            tps_on, outs_on = await one_pass(on)
            offs_t.append(tps_off)
            ons_t.append(tps_on)
            ratios.append(tps_on / tps_off)
        await off.stop()
        await on.stop()
        return ratios, offs_t, ons_t, outs_off, outs_on

    ratios, offs_t, ons_t, outs_off, outs_on = asyncio.run(run())
    out["quant_tokens_per_sec_off"] = round(statistics.median(offs_t), 1)
    out["quant_tokens_per_sec_on"] = round(statistics.median(ons_t), 1)
    out["quant_tokens_per_sec_ratio"] = round(statistics.median(ratios), 4)
    if out["quant_tokens_per_sec_ratio"] < s["tps_gate"]:
        what = ("int8 not faster than bf16 on the bandwidth-bound preset"
                if tpu else "int8 pathologically slower on CPU")
        violations.append(
            f"quant: tokens/sec ratio {out['quant_tokens_per_sec_ratio']}"
            f" < {s['tps_gate']} — {what}")

    # -- parity judge (HARD gate): both engines share the same quantized
    # weights, so any divergence isolates int8-KV noise. At each stream's
    # first fork, the int8-KV engine's token must be within quantization
    # noise of the full-context oracle's argmax (same weights, exact KV)
    # — otherwise it is a pool-write/table bug, not noise.
    MARGIN = 0.35
    first_div = None
    margin_max = 0.0
    for a, b, p in zip(outs_off, outs_on, prompts):
        if len(a) != len(b):
            # per-stream continue, not break: the remaining streams'
            # margins are diagnostic evidence for the SAME round
            violations.append("quant: output LENGTHS diverge")
            continue
        i = next((i for i, (x, y) in enumerate(zip(a, b)) if x != y), None)
        if i is None:
            continue
        first_div = i if first_div is None else min(first_div, i)
        logits = decoder_forward(
            quant_params, jnp.asarray([list(p) + b[:i]], jnp.int32),
            cfg)[0, -1]
        margin = float(jnp.max(logits) - logits[b[i]])
        margin_max = max(margin_max, margin)
        if margin > MARGIN:
            violations.append(
                f"quant: stream forks at token {i} and the int8-KV token "
                f"is {margin:.3f} below the oracle argmax (gate {MARGIN})"
                " — KV write/dequant bug, not quantization noise")
    out["quant_parity_first_divergence"] = (
        -1 if first_div is None else first_div)
    out["quant_oracle_margin_max"] = round(margin_max, 4)

    out["violations"] = violations
    out["valid"] = not violations
    return out


# ---------------------------------------------------------------------------
# phase: observability overhead (ISSUE 8) — the full request-lifecycle
# instrumentation (per-request trace spans + flight recorder + latency
# histograms) priced against the REAL engine, two ways:
#
#   1. obs_overhead_frac — the ≤2% gate, deterministic everywhere: the
#      per-window and per-request instrumentation hooks are microbenched on
#      the live engine (tight loop, min-of-reps — scheduling noise only ADDS
#      time, so the min converges on the true cost) and multiplied by the
#      window/request rates measured in the same run. Wall-clock A/B cannot
#      resolve 2% on a shared CPU host (measured noise floor here: a NULL
#      on-vs-off comparison of two IDENTICAL configs swings ±10-15%), and
#      hiding that behind more passes would be flaky-evidence theater.
#   2. obs_tokens_per_sec_ratio — paired interleaved tokens/sec with
#      neighbor-averaged baselines (off,on,off,on,...,off), gated at ≥0.98
#      ONLY on a real TPU (device windows dominate there and the host-side
#      hooks overlap device compute); on CPU it is a catastrophe floor, the
#      same split the quant phase uses for its HBM-bound throughput gate.
#
# Plus a decomposition-sanity check that the per-phase spans actually tile
# the request (queue + prefill + decode ≈ e2e within tolerance) — a cheap
# recorder that records the wrong timeline is not telemetry.
# ---------------------------------------------------------------------------

def bench_obs(quick: bool = False) -> dict:
    import asyncio

    import numpy as _np

    from tpu9.observability.trace import new_trace_id, tracer
    from tpu9.serving.presets import load_engine
    from tpu9.utils import on_tpu

    os.makedirs(XLA_CACHE_DIR, exist_ok=True)
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", XLA_CACHE_DIR)

    tpu = on_tpu()
    # mixed-length prompts, paged engine with prefix cache + spec off: the
    # common serving shape. `repeats` request-sets per timed measurement
    # stretch each sample past the host's scheduling-jitter timescale.
    s = dict(preset="llama-tiny", batch=4, max_seq=512,
             requests=4 if quick else 8, max_new=96 if quick else 160,
             passes=3 if quick else 5, repeats=2 if quick else 3,
             prefill_buckets=(32, 64), decode_steps=(1, 4, 8),
             wall_gate=0.98 if tpu else 0.5)
    out: dict = {"obs_model": s["preset"], "on_tpu": tpu}
    violations: list[str] = []

    prompts = [[(7 * i + j) % 490 + 1 for j in range(8 + 6 * i)]
               for i in range(s["requests"])]

    def build(obs_on: bool):
        eng = load_engine(s["preset"], max_batch=s["batch"],
                          max_seq_len=s["max_seq"],
                          prefill_buckets=s["prefill_buckets"],
                          decode_steps=s["decode_steps"],
                          kv_block_size=32, kv_pool_blocks=0,
                          flight_cap=256 if obs_on else 0)
        eng.warmup()
        return eng

    async def measure(eng, traced: bool):
        """(tokens/sec, seconds, windows dispatched, trace ids) over
        `repeats` sequential request-sets."""
        tids: list = []
        total = 0
        rec0 = eng.flight.recorded if eng.flight is not None else 0
        t0 = time.perf_counter()
        for _ in range(s["repeats"]):
            batch_tids = [new_trace_id() if traced else ""
                          for _ in prompts]
            outs = await asyncio.gather(*[
                eng.generate(list(p), max_new_tokens=s["max_new"],
                             trace=(tid, "root") if tid else None)
                for p, tid in zip(prompts, batch_tids)])
            total += sum(len(o) for o in outs)
            tids = batch_tids
        dt = time.perf_counter() - t0
        # windows = flight records minus the admit records (one/request)
        windows = 0
        if eng.flight is not None:
            windows = (eng.flight.recorded - rec0
                       - s["repeats"] * len(prompts))
        return total / dt, dt, windows, tids

    def _min_time_us(fn, iters: int, reps: int) -> float:
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            for _ in range(iters):
                fn()
            best = min(best, time.perf_counter() - t0)
        return best / iters * 1e6

    def microbench_hooks(eng) -> tuple[float, float]:
        """(per-window, per-request) instrumentation cost in µs, driven
        through the REAL hook methods on the live engine — flight record
        + per-slot decode_window spans + histogram observes, with the
        metric reservoirs saturated to their steady-state (sorted-insert)
        cost by the iteration count itself."""
        from tpu9.serving.engine import _Request, _Window
        iters, reps = (400, 3) if quick else (1500, 5)
        trace = ("ab" * 16, "cd" * 8)

        def mk_reqs():
            reqs = []
            for i in range(s["batch"]):
                r = _Request(request_id=f"mb{i}", prompt=[1] * 16,
                             max_new_tokens=s["max_new"], trace=trace,
                             t_enqueue_mono=time.monotonic(),
                             t_enqueue_wall=time.time())
                r.span_id = "ef" * 8
                reqs.append(r)
            return tuple(reqs)

        reqs = mk_reqs()
        mask = _np.ones(s["batch"], dtype=bool)
        delivered = {i: max(s["decode_steps"]) for i in range(s["batch"])}

        def one_window():
            win = _Window(kind="decode", k=max(s["decode_steps"]),
                          toks=None, mask=mask, reqs=reqs)
            eng._obs_stamp_window(win)
            win.delivered = dict(delivered)
            eng._obs_window(win, time.monotonic())

        def one_request():
            r = _Request(request_id="mbr", prompt=[1] * 16,
                         max_new_tokens=s["max_new"], trace=trace,
                         t_enqueue_mono=time.monotonic(),
                         t_enqueue_wall=time.time())
            eng._obs_admit_start(r, time.monotonic(), time.time())
            eng._obs_admit_end(r, time.monotonic(), time.time(), 0)
            eng._obs_first_token(r)
            eng._obs_done(r)

        return (_min_time_us(one_window, iters, reps),
                _min_time_us(one_request, iters, reps))

    def microbench_fleet() -> tuple[float, float]:
        """(per-timeline-record, per-SLO-evaluation) cost in µs with the
        rings SATURATED to steady state (ISSUE 12): a full deque(maxlen)
        ring is the append cost the gateway actually pays, and the burn
        evaluator walks full fast/slow windows."""
        from tpu9.config import SloConfig
        from tpu9.observability.slo import SloEvaluator
        from tpu9.observability.timeline import TimelineStore
        iters, reps = (400, 3) if quick else (1500, 5)
        cfg = SloConfig()
        tl = TimelineStore(capacity=cfg.timeline_capacity)
        # saturate: every series the sampler records per stub/replica,
        # rings full, monotonic stamps fresh enough to land in windows
        for name in ("router.st.queue_depth", "router.st.shed_rate",
                     "router.st.pressure", "router.st.submitted_total",
                     "router.st.shed_total", "router.st.ttft_p95_s",
                     "router.st.queue_wait_p95_s",
                     "engine.c0.tokens_per_sec", "engine.c0.kv_blocks_free",
                     "engine.c0.spec_acceptance_rate"):
            for i in range(cfg.timeline_capacity + 8):
                tl.record(name, float(i))
        ev = SloEvaluator(tl, cfg.objectives, burn_alert=cfg.burn_alert)

        def one_record():
            tl.record("router.st.queue_depth", 3.0)

        def one_eval():
            ev.evaluate("st")

        return (_min_time_us(one_record, iters, reps),
                _min_time_us(one_eval, iters, reps))

    def microbench_health(eng) -> tuple[float, float]:
        """(per-watchdog-assess, per-HBM-sample) cost in µs (ISSUE 14):
        the watchdog classifies one stats dict per runner beat; the HBM
        watermark is one ``memory_stats()`` sweep over the submesh on the
        stats() read path — both heartbeat-cadence, never per token."""
        from tpu9.observability.health import EngineWatchdog
        iters, reps = (400, 3) if quick else (1500, 5)
        wd = EngineWatchdog()
        stats = eng.stats()       # the real scalar surface, frozen

        def one_assess():
            wd.assess(stats)

        def one_hbm():
            eng.policy.hbm_used_gb_per_chip()

        return (_min_time_us(one_assess, iters, reps),
                _min_time_us(one_hbm, iters, reps))

    def microbench_cache() -> tuple[float, float]:
        """(per-chunk exchange-accounting, per-heartbeat snapshot) cost in
        µs for the cache-plane hooks (ISSUE 13): ``_note_exchange`` runs
        once per verified peer chunk on the restore path, ``snapshot()``
        once per worker heartbeat. Priced with a realistic per-peer table
        (8 peers warm)."""
        from tpu9.cache.client import CacheClient
        from tpu9.cache.store import DiskStore
        iters, reps = (400, 3) if quick else (1500, 5)
        client = CacheClient(DiskStore(os.path.join(XLA_CACHE_DIR,
                                                    "obs-cache-mb")),
                             peers=None)
        peers = [f"10.0.0.{i}:7400" for i in range(8)]
        for p in peers:
            client._note_exchange(p, 0.004, 4 << 20)   # warm the table

        k = [0]

        def one_account():
            client._note_exchange(peers[k[0] % 8], 0.004, 4 << 20)
            k[0] += 1

        def one_snapshot():
            client.snapshot()

        return (_min_time_us(one_account, iters, reps),
                _min_time_us(one_snapshot, iters, reps))

    def microbench_decisions() -> tuple[float, float]:
        """(per-record hot path, per-new-request index eviction) cost in
        µs for the decision ledger (ISSUE 19). Saturated to steady state:
        full global ring, request index at max_requests — the hot path is
        ring append + index append + metrics inc on an EXISTING chain;
        the eviction path adds the longest-idle scan paid once per fresh
        request id once the index is full."""
        from tpu9.observability.decisions import DecisionLedger, rej
        iters, reps = (400, 3) if quick else (1500, 5)
        led = DecisionLedger()
        for i in range(led.capacity + led.max_requests):
            led.record("placement", "dispatch", request_id=f"mb{i}",
                       chosen="c0", rejected=[rej("c1", "saturated")],
                       signals={"queue_depth": 3.0, "candidates": 2.0},
                       stub_id="st")

        k = [0]

        def one_record():
            led.record("placement", "dispatch",
                       request_id=f"mb{led.capacity + k[0] % 64}",
                       chosen="c0", rejected=[rej("c1", "saturated")],
                       signals={"queue_depth": 3.0, "candidates": 2.0},
                       stub_id="st")
            k[0] += 1

        j = [led.capacity + led.max_requests]

        def one_fresh():
            led.record("placement", "dispatch", request_id=f"mb{j[0]}",
                       chosen="c0", rejected=[rej("c1", "saturated")],
                       signals={"queue_depth": 3.0, "candidates": 2.0},
                       stub_id="st")
            j[0] += 1

        rec = _min_time_us(one_record, iters, reps)
        fresh = _min_time_us(one_fresh, iters, reps)
        return rec, max(fresh - rec, 0.0)

    def microbench_kvtier(eng) -> tuple[float, float, float]:
        """(per-window quota check, per-tier-event journal append,
        per-beat digest) cost in µs for the KV tiering plane (ISSUE 20).
        The quota check rides EVERY window boundary — tiered or not;
        the decision-journal append is bounded at the down-page quota
        (2 per boundary worst case); the top-48 digest is heartbeat-
        cadence host work."""
        import collections as _collections
        iters, reps = (400, 3) if quick else (1500, 5)
        quota = _min_time_us(eng.scheduler.downpage_quota, iters, reps)
        journal = _collections.deque(maxlen=256)
        rec_d = {"decision": "spill", "chosen": "host:deadbeefdeadbeef",
                 "signals": {"n_tokens": 64.0, "free_blocks": 3.0,
                             "downpage_s": 0.002}}
        append = _min_time_us(lambda: journal.append(dict(rec_d)),
                              iters, reps)
        digest = _min_time_us(eng.kvtier_digest, iters, reps)
        return quota, append, digest

    async def run() -> dict:
        res: dict = {}
        off, on = build(False), build(True)
        await off.start()
        await on.start()
        for eng in (off, on):         # untimed admission/graph warm pass
            await asyncio.gather(*[
                eng.generate(list(p), max_new_tokens=8) for p in prompts])

        # interleaved off,(on,off)* — each ON sample is ratioed against
        # the MEAN of its two neighboring OFF samples, cancelling linear
        # host drift to first order
        offs = [await measure(off, traced=False)]
        ons = []
        last_tids: list = []
        for _ in range(s["passes"]):
            m = await measure(on, traced=True)
            ons.append(m)
            last_tids = m[3]
            offs.append(await measure(off, traced=False))
        ratios = [ons[i][0] / ((offs[i][0] + offs[i + 1][0]) / 2)
                  for i in range(s["passes"])]
        flight = on.flight_records(limit=256)

        res["obs_tokens_per_sec_off"] = round(
            statistics.median([m[0] for m in offs]), 1)
        res["obs_tokens_per_sec_on"] = round(
            statistics.median([m[0] for m in ons]), 1)
        res["obs_tokens_per_sec_ratio"] = round(
            statistics.median(ratios), 4)

        # instrumentation evidence: the ON engine must actually have
        # produced the records the gates claim to price
        if not flight or "decode" not in {r["kind"] for r in flight}:
            violations.append("obs: flight recorder produced no decode "
                              "records — the ON side measured nothing")

        # decomposition sanity from the REAL span trees of the last ON
        # measurement: queue_wait + prefill + decode windows ≈ the request
        # span, per request. The one-window-in-flight overlap
        # double-counts a little and loop bookkeeping leaks a little, so
        # the gate brackets ≈1 generously — catching the real failure
        # modes (spans missing, anchors wrong, windows double-booked) not
        # scheduler jitter. MUST run before the microbench below, which
        # floods the process tracer ring.
        coverage = []
        for tid in last_tids:
            spans = tracer.export(trace_id=tid)
            req = [sp for sp in spans if sp["name"] == "engine.request"]
            if not req:
                violations.append(f"obs: no engine.request span for {tid}")
                continue
            d = req[0]["durationMs"]
            parts = sum(sp["durationMs"] for sp in spans
                        if sp["name"] in ("engine.queue_wait",
                                          "engine.prefill",
                                          "engine.decode_window"))
            if d > 0:
                coverage.append(parts / d)
        if coverage:
            cov = statistics.median(coverage)
            res["obs_decomposition_coverage"] = round(cov, 4)
            if not 0.5 <= cov <= 1.7:
                violations.append(
                    f"obs: queue+prefill+decode covers {cov:.2f} of the "
                    "request span (gate 0.5..1.7) — the per-phase spans "
                    "do not decompose e2e latency")
        else:
            violations.append("obs: no span coverage measured")

        # the ≤2% gate: microbenched hook cost × measured rates
        win_us, req_us = microbench_hooks(on)
        dur = statistics.median([m[1] for m in ons])
        windows_ps = statistics.median([m[2] for m in ons]) / dur
        requests_ps = s["repeats"] * len(prompts) / dur
        frac = (win_us * windows_ps + req_us * requests_ps) / 1e6
        # fleet evidence layer (ISSUE 12): the timeline sampler + burn
        # evaluator run at FIXED cadences, not per token — price them at
        # their worst per-replica rates (engine series each heartbeat,
        # router series + one evaluation each sampler tick) and fold
        # into the same ≤2% budget
        rec_us, eval_us = microbench_fleet()
        from tpu9.config import SloConfig as _SloCfg
        _slo = _SloCfg()
        heartbeat_series = 10          # engine series per replica beat
        tick_series = 14               # router+slo series per stub tick
        # cache-plane series per worker per observer tick (ISSUE 13):
        # tier counters + rates + pool + 8 warm peers × 3 series
        cache_series = 44
        records_ps = (heartbeat_series / 2.0   # runner beat cadence
                      + (tick_series + cache_series)
                      / _slo.sample_interval_s)
        evals_ps = 1.0 / _slo.sample_interval_s
        # cache accounting hooks (ISSUE 13): snapshot() runs on the
        # 5 s worker heartbeat; the per-chunk _note_exchange hook runs on
        # the RESTORE path, not the serve loop — priced against its own
        # budget below, not folded into serve-time overhead
        account_us, snap_us = microbench_cache()
        # decision ledger (ISSUE 19): admission + placement records on
        # every request (failover records only on faults), one eviction
        # scan per fresh request id at steady state, and autoscaler /
        # replan records at sampler cadence — all priced against the
        # same ≤2% serve-time budget
        dec_rec_us, dec_evict_us = microbench_decisions()
        dec_frac = ((dec_rec_us * 2.0 + dec_evict_us) * requests_ps
                    + dec_rec_us / _slo.sample_interval_s) / 1e6
        frac += dec_frac
        res["obs_decision_record_us"] = round(dec_rec_us, 3)
        res["obs_decision_evict_us"] = round(dec_evict_us, 3)
        res["obs_decision_frac"] = round(dec_frac, 6)
        # KV tiering (ISSUE 20): the down-page quota check rides every
        # window boundary; journal appends are bounded at the quota (2
        # per boundary); the heartbeat digest is per-beat host work —
        # all priced against the same ≤2% serve-time budget (the paging
        # gathers themselves are window-boundary device syncs, priced
        # as wall time by bench.py --phase kvtier, not serve-loop hooks)
        kvt_quota_us, kvt_journal_us, kvt_digest_us = microbench_kvtier(on)
        kvt_frac = ((kvt_quota_us + 2.0 * kvt_journal_us) * windows_ps
                    + kvt_digest_us / 2.0) / 1e6
        frac += kvt_frac
        res["obs_kvtier_quota_us"] = round(kvt_quota_us, 3)
        res["obs_kvtier_journal_us"] = round(kvt_journal_us, 3)
        res["obs_kvtier_digest_us"] = round(kvt_digest_us, 3)
        res["obs_kvtier_frac"] = round(kvt_frac, 6)
        if dec_rec_us > 8.0:
            violations.append(
                f"obs: decision ledger record costs {dec_rec_us:.1f}µs"
                " (gate 8µs, same bar as the cache exchange-accounting"
                " hook) — the admission/placement hot path grew a heavy"
                " ledger hook")
        # replica health plane (ISSUE 14): one watchdog assess + one HBM
        # memory_stats() sweep per runner beat (2 s), plus the health
        # timeline/gauge records the gateway adds per beat (priced at
        # the timeline record cost already measured above)
        assess_us, hbm_us = microbench_health(on)
        health_records = 8     # hbm_*/liveness/health series per beat
        sampler_frac = (rec_us * records_ps + eval_us * evals_ps
                        + snap_us / 5.0
                        + (assess_us + hbm_us
                           + rec_us * health_records) / 2.0) / 1e6
        frac += sampler_frac
        res["obs_health_assess_us"] = round(assess_us, 3)
        res["obs_hbm_sample_us"] = round(hbm_us, 3)
        res["obs_timeline_record_us"] = round(rec_us, 3)
        res["obs_slo_eval_us"] = round(eval_us, 2)
        res["obs_cache_account_us"] = round(account_us, 3)
        res["obs_cache_snapshot_us"] = round(snap_us, 2)
        # a 4 MiB chunk at 10 GB/s local NVMe is ~400 µs of transfer —
        # the per-chunk accounting must stay ≤2% of even that best case
        if account_us > 8.0:
            violations.append(
                f"obs: cache exchange accounting costs {account_us:.1f}µs"
                " per chunk (gate 8µs = 2% of a best-case 4 MiB local"
                " transfer) — the restore hot path grew a heavy hook")
        res["obs_sampler_frac"] = round(sampler_frac, 6)
        res["obs_instr_window_us"] = round(win_us, 2)
        res["obs_instr_request_us"] = round(req_us, 2)
        res["obs_windows_per_sec"] = round(windows_ps, 2)
        res["obs_overhead_frac"] = round(frac, 5)
        if frac > 0.02:
            violations.append(
                f"obs: instrumentation costs {frac:.2%} of serve time "
                f"({win_us:.1f}µs/window × {windows_ps:.0f} windows/s + "
                f"{req_us:.1f}µs/request) — over the 2% budget")

        await off.stop()
        await on.stop()
        return res

    out.update(asyncio.run(run()))
    ratio = out.get("obs_tokens_per_sec_ratio", 0.0)
    if ratio < s["wall_gate"]:
        violations.append(
            f"obs: paired tokens/sec ratio {ratio} < {s['wall_gate']}"
            + (" — tracing + flight recorder slow the TPU serve loop "
               "beyond the overhead budget" if tpu else
               " — catastrophe floor on a noise-bound CPU host (NULL "
               "A/B noise here is ±10-15%; the binding 2% gate is "
               "obs_overhead_frac)"))
    out["violations"] = violations
    out["valid"] = not violations
    return out


# ---------------------------------------------------------------------------
# phase: mesh-sharded multi-chip serving (ISSUE 9) — the tp=2 sharded engine
# priced against the 1-chip engine it must not fork from:
#
#   1. multichip_per_chip_ratio — (tp=2 tokens/sec ÷ 2 chips) / 1-chip
#      tokens/sec. On a real slice this is the serving-economics gate
#      (spreading a model must buy throughput, not just capacity). On
#      forced-CPU virtual devices every "chip" shares the same host cores,
#      so tp=2 adds partitioning overhead over ZERO extra silicon — the
#      ratio is reported as evidence but the binding CPU gate is a
#      catastrophe floor on the TOTAL throughput ratio (the quant/obs
#      precedent for wins CPU physically cannot show).
#   2. parity judge (HARD): token-for-token vs the 1-chip engine at f32;
#      any fork is judged against the full-context oracle's argmax margin
#      (sharded reductions may reassociate; a table/layout bug may not).
#   3. planner-vs-actual: the topology planner prices per-chip weights from
#      feasibility's eval_shape arithmetic; this phase measures the bytes
#      ACTUALLY resident on one device after placement and fails if the
#      deploy gate's numbers do not describe the real layout. Plus the
#      flagship arithmetic: llama3-8b provably infeasible on one v5e chip,
#      planned onto 2x1 with the 1x1 rejection ledger populated.
#   4. MFU/MBU under sharding: per-chip decode physics of the tp=2 engine
#      (streamed bytes / FLOPs divide across the submesh; the ceiling is
#      per chip, so utilization stays comparable to the 1-chip engine).
# ---------------------------------------------------------------------------

def bench_multichip(quick: bool = False) -> dict:
    import asyncio
    from dataclasses import replace as _replace

    import jax
    import jax.numpy as jnp

    from tpu9.benchsuite.physics import (chip_spec, decode_byte_counts,
                                         decode_physics)
    from tpu9.models import init_decoder
    from tpu9.models.transformer import decoder_forward
    from tpu9.serving.engine import EngineConfig, InferenceEngine
    from tpu9.serving.feasibility import weight_bytes
    from tpu9.serving.presets import resolve_preset
    from tpu9.serving.shard import Topology, make_policy, plan_topology
    from tpu9.utils import on_tpu

    os.makedirs(XLA_CACHE_DIR, exist_ok=True)
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", XLA_CACHE_DIR)

    tpu = on_tpu()
    n_dev = jax.device_count()
    out: dict = {"on_tpu": tpu, "multichip_devices": n_dev}
    violations: list[str] = []
    TP = 2
    if n_dev < TP:
        raise RuntimeError(
            f"multichip phase needs >= {TP} devices, have {n_dev} — run "
            "via bench.py --cpu (forces an 8-device virtual CPU mesh) or "
            "on a real slice")

    # -- flagship planner arithmetic (pure host math, deterministic) ------
    plan = plan_topology("llama3-8b", "v5e-8")
    out["multichip_plan_llama3_8b_v5e"] = str(plan.topology)
    if plan.topology != Topology(2, 1) or len(plan.rejected) != 1:
        violations.append(
            f"multichip: planner put llama3-8b/v5e-8 on {plan.topology} "
            f"with {len(plan.rejected)} rejections (expected 2x1 after "
            "rejecting exactly 1x1) — the feasibility pricing moved")

    # f32 kills bf16 argmax-tie noise in the parity judge (spec/quant
    # precedent); tiny preset so CPU passes stay in budget
    s = dict(preset="llama-tiny", batch=4, max_seq=512,
             prefill_buckets=(32, 64), decode_steps=(1, 4, 8), kv_block=32,
             requests=4, max_new=64 if quick else 128,
             passes=2 if quick else 3)
    out["multichip_model"] = s["preset"]
    cfg, _ = resolve_preset(s["preset"])
    cfg = _replace(cfg, dtype=jnp.float32)
    params = init_decoder(jax.random.PRNGKey(0), cfg)

    # -- paired engines: 1-chip vs tp=2 -----------------------------------
    pol2 = make_policy(f"{TP}x1")
    def build(policy):
        eng = InferenceEngine(params, cfg, EngineConfig(
            max_batch=s["batch"], max_seq_len=s["max_seq"],
            prefill_buckets=s["prefill_buckets"],
            decode_steps=s["decode_steps"], kv_block_size=s["kv_block"],
            kv_pool_blocks=0, prefill_chunk=min(s["prefill_buckets"]),
            prefix_cache_blocks=s["max_seq"] // s["kv_block"]),
            policy=policy)
        eng.warmup()
        return eng

    one = build(make_policy(None))
    two = build(pol2)
    st = two.stats()
    out["multichip_topology"] = (
        f"{st['topo_tp']}x{st['topo_fsdp']}")

    # -- planner-vs-actual per-chip weight bytes --------------------------
    # the deploy-gate contract: feasibility's per-chip pricing (total
    # eval_shape bytes ÷ n_chips) must describe what the ENGINE actually
    # leaves resident on each device — measured from the serving engine's
    # own param tree, so a placement regression (e.g. a constructor path
    # that skips the policy and serves replicated weights) fails here
    # rather than silently inflating every other number. Small
    # non-dividing leaves replicate, so "describe" = within tolerance,
    # and genuinely ~1/tp of the model.
    dev0 = pol2.devices()[0]
    actual = 0
    for leaf in jax.tree_util.tree_leaves(two.params):
        for sh in leaf.addressable_shards:
            if sh.device == dev0:
                actual += sh.data.nbytes
    total = weight_bytes(cfg, False)
    planned = total / TP
    out["multichip_weight_shard_ratio"] = round(actual / total, 4)
    out["multichip_planner_weight_err"] = round(
        abs(actual - planned) / planned, 4)
    if out["multichip_weight_shard_ratio"] > 0.75:
        violations.append(
            f"multichip: tp={TP} leaves "
            f"{out['multichip_weight_shard_ratio']:.0%} of the weights on "
            "one chip (gate 75%) — the engine is not actually sharding")
    if out["multichip_planner_weight_err"] > 0.30:
        violations.append(
            f"multichip: planner per-chip weight pricing is off by "
            f"{out['multichip_planner_weight_err']:.0%} vs resident bytes "
            "(gate 30%) — the feasibility gate no longer describes the "
            "real layout")
    hbm = pol2.hbm_used_gb_per_chip()
    if hbm > 0.0:       # real backend memory stats (TPU); 0.0 on CPU
        out["multichip_hbm_used_gb_per_chip"] = hbm

    import random as _random
    rng = _random.Random(13)
    prompts = [[rng.randrange(1, 400) for _ in range(24)]
               for _ in range(s["requests"])]

    async def one_pass(eng):
        t0 = time.perf_counter()
        outs = await asyncio.gather(*[
            eng.generate(list(p), max_new_tokens=s["max_new"])
            for p in prompts])
        return sum(len(o) for o in outs) / (time.perf_counter() - t0), outs

    async def run():
        await one.start()
        await two.start()
        for eng in (one, two):       # untimed admission/graph warm pass
            await asyncio.gather(*[
                eng.generate(list(p), max_new_tokens=8) for p in prompts])
        ones_t, twos_t = [], []
        outs_one = outs_two = None
        for _ in range(s["passes"]):
            tps_one, outs_one = await one_pass(one)
            tps_two, outs_two = await one_pass(two)
            ones_t.append(tps_one)
            twos_t.append(tps_two)
        await one.stop()
        await two.stop()
        return ones_t, twos_t, outs_one, outs_two

    ones_t, twos_t, outs_one, outs_two = asyncio.run(run())
    tps_one = statistics.median(ones_t)
    tps_two = statistics.median(twos_t)
    out["multichip_tokens_per_sec_1chip"] = round(tps_one, 1)
    out["multichip_tokens_per_sec_tp2"] = round(tps_two, 1)
    out["multichip_total_ratio"] = round(tps_two / tps_one, 4)
    out["multichip_per_chip_ratio"] = round(tps_two / TP / tps_one, 4)
    if tpu and out["multichip_per_chip_ratio"] < 0.35:
        violations.append(
            f"multichip: per-chip tokens/sec ratio "
            f"{out['multichip_per_chip_ratio']} < 0.35 on a real slice — "
            "the sharding tax ate the submesh")
    if not tpu and out["multichip_total_ratio"] < 0.2:
        violations.append(
            f"multichip: tp={TP} total throughput is "
            f"{out['multichip_total_ratio']}x the 1-chip engine — below "
            "the CPU catastrophe floor 0.2 (virtual devices share the "
            "host's cores; per-chip economics only exist on real silicon)")

    # -- parity judge (HARD gate) -----------------------------------------
    # token-for-token at f32; at each stream's first fork the sharded
    # engine's token must be within the oracle-argmax margin (sharded
    # psum reassociation), else it is a layout/table bug, not noise
    MARGIN = 0.35
    first_div = None
    margin_max = 0.0
    for a, b, p in zip(outs_one, outs_two, prompts):
        if len(a) != len(b):
            violations.append("multichip: output LENGTHS diverge")
            continue
        i = next((i for i, (x, y) in enumerate(zip(a, b)) if x != y), None)
        if i is None:
            continue
        first_div = i if first_div is None else min(first_div, i)
        logits = decoder_forward(
            params, jnp.asarray([list(p) + b[:i]], jnp.int32), cfg)[0, -1]
        margin = float(jnp.max(logits) - logits[b[i]])
        margin_max = max(margin_max, margin)
        if margin > MARGIN:
            violations.append(
                f"multichip: stream forks at token {i} and the sharded "
                f"token is {margin:.3f} below the oracle argmax (gate "
                f"{MARGIN}) — sharded KV/table bug, not reassociation")
    out["multichip_parity_first_divergence"] = (
        -1 if first_div is None else first_div)
    out["multichip_oracle_margin_max"] = round(margin_max, 4)

    # -- per-chip decode physics under sharding ---------------------------
    # streamed weights, KV traffic and matmul FLOPs all divide across the
    # submesh (tp shards both weight matrices and the KV head axis), so
    # the per-CHIP ceiling ratio is the honest utilization figure
    counts = decode_byte_counts(two.params, cfg, s["batch"],
                                24 + s["max_new"] // 2)
    total_tokens = s["requests"] * s["max_new"] * 1.0
    steps = total_tokens / s["batch"]
    step_ms = (total_tokens / tps_two) / max(steps, 1e-9) * 1e3
    phys = decode_physics(
        step_ms=step_ms, batch=s["batch"],
        streamed_bytes=counts["streamed_bytes"] // TP,
        kv_bytes_per_step=counts["kv_bytes_per_step"] // TP,
        matmul_params=counts["matmul_params"] // TP,
        attn_flops_per_step=counts["attn_flops_per_step"] / TP,
        spec=chip_spec(jax.devices()[0].device_kind))
    out["multichip_physics"] = phys
    out["multichip_engine_mbu"] = phys.get("mbu")
    out["multichip_engine_mfu"] = phys.get("mfu")

    out["violations"] = violations
    out["valid"] = not violations
    return out


# ---------------------------------------------------------------------------
# orchestration
# ---------------------------------------------------------------------------

def _run_phase(phase: str, quick: bool, cpu: bool) -> dict:
    """Run one phase in a fresh subprocess (own process group), parse the
    last JSON line, then kill the whole group so nothing leaks forward."""
    cmd = [sys.executable, os.path.abspath(__file__), "--phase", phase]
    if quick:
        cmd.append("--quick")
    if cpu or phase in ("router", "spec", "quant", "obs", "multichip",
                        "faults", "disagg", "scaleout", "kvtier") \
            or (phase.startswith("coldstart") and phase != "coldstart_jax_tpu"):
        # the serving stack and its runner children must never dial the chip
        # — ALL cold-start stack phases, not just the original one (round-3
        # advisor finding: coldstart_native/coldstart_jax ran unguarded).
        # The router phase is a pure-asyncio simulation: always CPU.
        # coldstart_jax_tpu is the exception: like llm_endpoint it forces its
        # own parent CPU and hands ONLY the runner container the tunnel env.
        cmd.append("--cpu")
    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True,
                            start_new_session=True)
    # setsid'd runner containers leave the group AND reparent to init when
    # the phase dies, so pids must be snapshotted WHILE the phase is alive —
    # a post-exit walk from a dead pid finds nothing
    seen_pids: set[int] = set()
    deadline = time.monotonic() + PHASE_TIMEOUT_S[phase]
    timed_out = False
    while True:
        try:
            out, err = proc.communicate(timeout=2)
            break
        except subprocess.TimeoutExpired:
            seen_pids.update(_descendants(proc.pid))
            if time.monotonic() > deadline:
                timed_out = True
                _kill_group(proc, seen_pids)
                out, err = proc.communicate()
                break
    _kill_group(proc, seen_pids)
    if timed_out:
        return {f"{phase}_error": f"timeout after {PHASE_TIMEOUT_S[phase]}s",
                f"{phase}_stderr_tail": err[-500:] if err else ""}

    for line in reversed(out.strip().splitlines()):
        try:
            return json.loads(line)
        except json.JSONDecodeError:
            continue
    return {f"{phase}_error": f"no JSON (rc={proc.returncode})",
            f"{phase}_stderr_tail": (err or "")[-500:]}


def _descendants(root_pid: int) -> list[int]:
    """All live descendant pids of root_pid via /proc PPid chains. Needed
    because ProcessRuntime starts runner containers with os.setsid() — they
    leave the phase's process group, so killpg alone cannot reach them."""
    ppid_of: dict[int, int] = {}
    for entry in os.listdir("/proc"):
        if not entry.isdigit():
            continue
        try:
            with open(f"/proc/{entry}/status") as f:
                for line in f:
                    if line.startswith("PPid:"):
                        ppid_of[int(entry)] = int(line.split()[1])
                        break
        except OSError:
            continue
    out, frontier = [], {root_pid}
    while frontier:
        nxt = {pid for pid, ppid in ppid_of.items() if ppid in frontier}
        nxt -= set(out)
        out.extend(nxt)
        frontier = nxt
    return out


def _kill_group(proc: subprocess.Popen, extra_pids: set[int] = frozenset()) -> None:
    """SIGKILL the phase's process group plus every pid snapshotted while
    the phase was alive (setsid'd runner containers sit outside the group
    and reparent to init on phase death — the snapshot is the only handle)."""
    kids = set(_descendants(proc.pid)) | set(extra_pids)
    kids.discard(proc.pid)
    try:
        os.killpg(proc.pid, signal.SIGKILL)
    except (ProcessLookupError, PermissionError):
        pass
    for pid in kids:
        # snapshot pids may have died and been REUSED by unrelated
        # processes — only kill ones that are verifiably ours (runner
        # containers carry TPU9_* env)
        try:
            with open(f"/proc/{pid}/environ", "rb") as f:
                if b"TPU9_" not in f.read():
                    continue
            os.kill(pid, signal.SIGKILL)
        except (OSError, ProcessLookupError, PermissionError):
            continue


def _tpu_alive(timeout_s: float = 120.0) -> bool:
    """One cheap probe: can a fresh process initialize the accelerator
    backend at all? A dead tunnel hangs indefinitely — probing once here
    avoids paying the full phase timeout twice."""
    code = ("import jax; d = jax.devices(); "
            "print('TPU9_PROBE_OK', len(d), jax.default_backend())")
    proc = subprocess.Popen([sys.executable, "-c", code],
                            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
                            text=True, start_new_session=True)
    try:
        out, _ = proc.communicate(timeout=timeout_s)
        return "TPU9_PROBE_OK" in (out or "")
    except subprocess.TimeoutExpired:
        return False
    finally:
        _kill_group(proc)


def _merge_validated(extra: dict, phase: str, result: dict,
                     value_keys: tuple[str, ...]) -> None:
    """Merge a phase result, REMOVING its headline numbers if the phase's
    own evidence rejected them — BENCH must never carry an un-evidenced
    number (round-2 failure: a physically impossible tokens/sec shipped)."""
    result = dict(result)
    # per-phase valid/violations fold into the shared validation block —
    # left at top level they'd clobber each other across phases
    violations = result.pop("violations", [])
    result.pop("valid", None)
    if violations:
        for key in value_keys:
            result.pop(key, None)
        result[f"{phase}_rejected"] = "; ".join(violations)
    extra.setdefault("validation", {}).setdefault("violations", []) \
        .extend(violations)
    extra.update(result)


REPO_DIR = os.path.dirname(os.path.abspath(__file__))


def _persist(name: str, obj: dict) -> None:
    """Write evidence to a side file IN THE REPO — the driver's tail capture
    truncated round 3's single output line mid-JSON and the headline was
    lost (`BENCH_r03.json "parsed": null`). The final stdout line stays
    compact; everything else lives here."""
    try:
        with open(os.path.join(REPO_DIR, name), "w") as f:
            json.dump(obj, f, indent=1, sort_keys=True)
            f.write("\n")
    except OSError:
        pass


def _run_chip_phases(detail: dict, quick: bool, cpu: bool) -> bool:
    """llm + llm_endpoint + kernels. Returns False if a TPU attempt errored
    (caller may retry on CPU later); on a real-TPU success persists a
    BENCH_TPU.json snapshot IMMEDIATELY so a flaky tunnel window is never
    wasted (VERDICT r03 next-round #1b)."""
    llm = _run_phase("llm", quick, cpu)
    if "llm_error" in llm and not cpu:
        detail["llm_tpu_error"] = llm["llm_error"]
        return False
    _merge_validated(detail, "llm", llm, (
        "raw_decode_tokens_per_sec", "engine_tokens_per_sec",
        "engine_tokens_per_sec_per_chip"))

    # snapshot after EVERY completed chip phase (a flaky tunnel window
    # must never be wasted — VERDICT r03 #1b): the 8B compiles of the
    # endpoint phase take minutes over a relay, and a window closing
    # mid-phase must not lose the numbers already captured
    def snapshot() -> None:
        if cpu or not detail.get("on_tpu"):
            return
        # MERGE over any prior on-TPU snapshot (an earlier alive-window
        # may have captured phases this partial run hasn't reached yet —
        # a plain overwrite would destroy e.g. a captured endpoint number
        # when this window dies after the llm phase)
        snap: dict = {}
        try:
            with open(os.path.join(REPO_DIR, "BENCH_TPU.json")) as f:
                prior = json.load(f)
            if prior.get("on_tpu"):
                snap.update(prior)
        except (OSError, ValueError):
            pass
        snap.update(detail)
        snap["captured_at"] = time.strftime("%Y-%m-%d %H:%M:%S")
        snap.setdefault("captured_by", "bench.orchestrate")
        _persist("BENCH_TPU.json", snap)

    snapshot()

    # the endpoint phase's PARENT forces itself CPU internally; the runner
    # container dials the chip (unless the whole bench is CPU-forced, which
    # --cpu → TPU9_BENCH_CPU=1 propagates into the subprocess)
    lep = _run_phase("llm_endpoint", quick, cpu)
    _merge_validated(detail, "llm_endpoint", lep, (
        "endpoint_tokens_per_sec", "endpoint_tokens_per_sec_per_chip"))
    snapshot()

    kern = _run_phase("kernels", quick, cpu)
    if "kernels_error" in kern and not cpu:
        detail["kernels_tpu_error"] = kern["kernels_error"]
        kern = _run_phase("kernels", quick, True)
    # pop the shared validation keys BEFORE prefixing so _merge_validated
    # sees them (round-3 advisor finding: 'valid' leaked as 'kernel_valid')
    kern_viol = kern.pop("violations", [])
    kern.pop("valid", None)
    kern = {f"kernel_{k}" if not k.startswith("kernel") else k: v
            for k, v in kern.items()}
    kern["violations"] = kern_viol
    _merge_validated(detail, "kernels", kern, ("kernel_flash_ms",
                                               "kernel_paged_ms",
                                               "kernel_blocktable_ms"))
    snapshot()

    if not cpu and detail.get("on_tpu"):
        # spend the rest of the window on the on-chip restore cold start
        # (VERDICT r04 #1), then refresh the snapshot with its numbers
        cjt = _run_phase("coldstart_jax_tpu", quick, cpu=False)
        # strip the percentile dict and first-invoke time too on rejection —
        # an off-chip number must not survive under ANY _tpu key
        _merge_validated(detail, "coldstart_jax_tpu", cjt,
                         ("cold_start_jax_restore_tpu_p50_s",
                          "cold_start_jax_restore_tpu",
                          "cold_start_jax_first_tpu_s"))
        snapshot()
    return True


def orchestrate(quick: bool, cpu: bool) -> dict:
    detail: dict = {}

    tpu_up = (not cpu) and _tpu_alive()
    if not cpu and not tpu_up:
        detail["tpu_probe"] = ("accelerator backend did not initialize at "
                               "start; re-probing between phases")

    chip_done = False
    tpu_attempts = 0          # a half-alive tunnel (probe ok, phase hangs)
    MAX_TPU_ATTEMPTS = 2      # must not eat the whole bench budget

    def try_tpu(probe_timeout: float) -> bool:
        nonlocal chip_done, tpu_attempts
        if chip_done or cpu or tpu_attempts >= MAX_TPU_ATTEMPTS:
            return chip_done
        if _tpu_alive(timeout_s=probe_timeout):
            tpu_attempts += 1
            chip_done = _run_chip_phases(detail, quick, cpu=False)
        return chip_done

    if tpu_up:
        # chip phases FIRST, while nothing else has touched the tunnel
        tpu_attempts += 1
        chip_done = _run_chip_phases(detail, quick, cpu=False)

    # cold-start phases are always forced-CPU; between them, keep probing
    # for the chip so a tunnel that comes alive mid-run is still captured
    for phase, keys in (
            ("router", ("router_ttft_p50_ms", "router_ttft_p99_ms",
                        "router_shed_rate", "router_prefix_hit_rate",
                        "router_kv_hit_rate")),
            # chaos phase (ISSUE 15): a violation (any failed request,
            # a broken splice, or a chaos run that induced nothing)
            # strips every headline — bench_guard HARD-fails the
            # vanished faults_recovery_p95_s
            ("faults", ("faults_failed_requests", "faults_failovers",
                        "faults_recovered", "faults_recovery_p50_s",
                        "faults_recovery_p95_s",
                        "faults_stream_splice_ok",
                        # block-ship resume (ISSUE 16): the re-prefill
                        # baseline it must beat, and proof the
                        # kv_ship_error fallback was exercised
                        "faults_kv_resumes", "faults_kv_fallbacks",
                        "faults_recovery_p95_reprefill_s")),
            # KV wire + disaggregated prefill/decode (ISSUE 16): a
            # roundtrip that is not bit-exact strips
            # kvwire_roundtrip_exact — bench_guard HARD-fails the
            # vanished field (the quant parity precedent)
            ("disagg", ("kvwire_roundtrip_exact",
                        "kvwire_payload_kb_bf16", "kvwire_payload_kb_int8",
                        "kvwire_export_ms_bf16", "kvwire_import_ms_bf16",
                        "kvwire_export_ms_int8", "kvwire_import_ms_int8",
                        "disagg_longdoc_ttft_p99_ms_on",
                        "disagg_longdoc_ttft_p99_ms_off",
                        "disagg_shortchat_ttft_p99_ms_on",
                        "disagg_shortchat_ttft_p99_ms_off",
                        "disagg_longdoc_ttft_improvement",
                        "disagg_shortchat_ttft_ratio",
                        "disagg_long_on_prefill_frac")),
            # KV tiering + prefix directory (ISSUE 20): a violation (a
            # hit rate not strictly above the affinity baseline, a TTFT
            # p95 regression, a storm the host tier did not soften, or
            # any dropped request) strips every headline — bench_guard
            # HARD-fails the vanished kvtier_prefix_hit_rate
            ("kvtier", ("kvtier_prefix_hit_rate",
                        "kvtier_affinity_hit_rate",
                        "kvtier_ttft_p95_ms_on",
                        "kvtier_ttft_p95_ms_off",
                        "kvtier_ttft_p95_ratio",
                        "kvtier_storm_survival_on",
                        "kvtier_storm_survival_off",
                        "kvtier_downpage_ms", "kvtier_uppage_ms")),
            # scale-out plane (ISSUE 17): a violation (linear source
            # bytes, a failed chaos restore, or an execute-while-scaling
            # leg that never admitted early) strips every headline —
            # bench_guard HARD-fails the vanished
            # scaleout_source_bytes_ratio
            ("scaleout", ("scaleout_bringup_ratio",
                          "scaleout_source_bytes_ratio",
                          "scaleout_tree_wall_s",
                          "scaleout_single_restore_s",
                          "scaleout_serial_total_s",
                          "scaleout_serial_speedup",
                          "scaleout_source_bytes_serial",
                          "scaleout_source_bytes_tree",
                          "scaleout_peer_bytes_tree",
                          "scaleout_nonseed_peer_bytes",
                          "scaleout_bytes_by_edge",
                          "scaleout_tree_edges",
                          "scaleout_tree_source_edges",
                          "scaleout_first_group_frac",
                          "scaleout_first_admit_before_complete",
                          "scaleout_partial_admitted",
                          "scaleout_unhinted_fenced",
                          "scaleout_chaos_restore_ok",
                          "scaleout_chaos_peer_errors",
                          "scaleout_chaos_source_bytes",
                          "scaleout_report")),
            ("spec", ("spec_uplift_repetitive", "spec_adversarial_ratio",
                      "spec_tokens_per_sec_on_repetitive",
                      "spec_tokens_per_sec_off_repetitive",
                      "spec_acceptance_rate_repetitive")),
            ("quant", ("quant_shard_bytes_ratio",
                       "quant_shard_bytes_ratio_measured",
                       "quant_kv_capacity_ratio",
                       "quant_kv_capacity_ratio_measured",
                       "quant_tokens_per_sec_ratio",
                       "quant_tokens_per_sec_on",
                       "quant_tokens_per_sec_off")),
            ("multichip", ("multichip_tokens_per_sec_1chip",
                           "multichip_tokens_per_sec_tp2",
                           "multichip_total_ratio",
                           "multichip_per_chip_ratio",
                           "multichip_weight_shard_ratio",
                           "multichip_planner_weight_err",
                           "multichip_engine_mbu",
                           "multichip_engine_mfu")),
            ("obs", ("obs_tokens_per_sec_ratio",
                     "obs_tokens_per_sec_on",
                     "obs_tokens_per_sec_off",
                     "obs_decomposition_coverage",
                     "obs_overhead_frac", "obs_instr_window_us",
                     "obs_instr_request_us", "obs_windows_per_sec",
                     # replica health plane (ISSUE 14): watchdog tick +
                     # HBM sampler, priced microbench×rate like every
                     # other hook inside the same ≤2% budget
                     "obs_health_assess_us", "obs_hbm_sample_us",
                     # decision ledger (ISSUE 19): the WHY-record hook
                     # on admission/placement/failover, priced at its
                     # measured request rate inside the same budget
                     "obs_decision_record_us", "obs_decision_evict_us",
                     "obs_decision_frac",
                     # KV tiering (ISSUE 20): quota check + decision
                     # journal + heartbeat digest, priced at window/
                     # beat rates inside the same budget
                     "obs_kvtier_quota_us", "obs_kvtier_journal_us",
                     "obs_kvtier_digest_us", "obs_kvtier_frac")),
            ("coldstart", ("cold_start_p50_s",)),
            ("coldstart_native", ("cold_start_native_p50_s",
                                  "cold_start_native_pull_p50_s")),
            ("coldstart_jax", ("cold_start_jax_restore_p50_s",)),
            ("coldstart_stream", ("cold_start_jax_restore_stream_p50_s",
                                  "cold_start_warm_pool_restore_p50_s",
                                  "cold_start_classic_restore_p50_s",
                                  "weight_stream_fetch_s",
                                  "weight_stream_put_s",
                                  "warm_pool_hit",
                                  # decomposition evidence (ISSUE 13):
                                  # stripped as a block when the traced
                                  # spans disagree with the measured
                                  # intervals (>10%)
                                  "coldstart_fetch_window_s",
                                  "coldstart_put_window_s",
                                  "coldstart_overlap_frac",
                                  "coldstart_plan_s",
                                  "coldstart_trace_disagreement",
                                  "coldstart_trace_decomposition",
                                  "coldstart_bytes_by_tier",
                                  "coldstart_bytes_by_edge",
                                  "coldstart_hedge"))):
        try_tpu(probe_timeout=45)
        res = _run_phase(phase, quick, cpu)
        _merge_validated(detail, phase, res, keys)

    if not chip_done:
        # last chance on TPU (longer probe), else CPU so the metrics exist
        try_tpu(probe_timeout=180)
        if not chip_done:
            _run_chip_phases(detail, quick, cpu=True)

    v = detail.get("validation", {"violations": []})
    v["ok"] = not v["violations"]
    detail["validation"] = v

    # a mid-round opportunistic capture (scripts/tpu_opportunist.py) may
    # have caught the chip during an alive-window this run missed; surface
    # it CLEARLY LABELED as a snapshot — never promoted to this run's
    # headline numbers
    if not detail.get("on_tpu"):
        snap_path = os.path.join(REPO_DIR, "BENCH_TPU.json")
        if os.path.exists(snap_path):
            try:
                with open(snap_path) as f:
                    snap = json.load(f)
                if snap.get("on_tpu"):
                    detail["tpu_snapshot_file"] = "BENCH_TPU.json"
                    detail["tpu_snapshot_captured_at"] = snap.get(
                        "captured_at", "")
                    for k in ("engine_tokens_per_sec_per_chip",
                              "endpoint_tokens_per_sec_per_chip"):
                        if k in snap:
                            detail[f"tpu_snapshot_{k}"] = snap[k]
            except (OSError, ValueError):
                pass
    return detail


# compact-extra keys lifted verbatim from the full detail (VERDICT r03
# next-round #1a: the final line carries headline fields ONLY)
_COMPACT_KEYS = (
    "backend", "on_tpu", "device_kind", "model",
    "engine_tokens_per_sec_per_chip", "engine_served_proof_ok",
    "endpoint_tokens_per_sec_per_chip", "endpoint_served_proof_ok",
    "endpoint_container_on_tpu",
    "cold_start_p50_s", "cold_start_native_p50_s",
    "cold_start_native_pull_p50_s", "cold_start_jax_restore_p50_s",
    "cold_start_jax_restore_stream_p50_s",
    "cold_start_warm_pool_restore_p50_s", "warm_pool_hit",
    "weight_stream_fetch_s", "weight_stream_put_s",
    "cold_start_jax_restore_tpu_p50_s", "jax_restore_tpu_backend",
    "kernel_flash_ms", "kernel_paged_ms",
    "router_ttft_p50_ms", "router_ttft_p99_ms", "router_ttft_random_p50_ms",
    "router_shed_rate", "router_prefix_hit_rate", "router_kv_hit_rate",
    "router_kv_hit_rate_random",
    "spec_uplift_repetitive", "spec_adversarial_ratio",
    "spec_tokens_per_sec_on_repetitive", "spec_tokens_per_sec_off_repetitive",
    "spec_acceptance_rate_repetitive", "spec_acceptance_rate_adversarial",
    "faults_requests", "faults_failed_requests", "faults_failovers",
    "faults_recovered", "faults_recovery_p50_s", "faults_recovery_p95_s",
    "faults_injected_crash", "faults_injected_stall",
    "faults_injected_rpc_error", "faults_stream_splice_ok",
    "faults_stream_splice_n",
    "faults_kv_resumes", "faults_kv_fallbacks",
    "faults_recovery_p95_reprefill_s",
    "kvwire_roundtrip_exact",
    "disagg_longdoc_ttft_p99_ms_on", "disagg_longdoc_ttft_p99_ms_off",
    "disagg_shortchat_ttft_p99_ms_on", "disagg_shortchat_ttft_p99_ms_off",
    "disagg_longdoc_ttft_improvement", "disagg_shortchat_ttft_ratio",
    "disagg_long_on_prefill_frac",
    "quant_shard_bytes_ratio", "quant_shard_bytes_ratio_measured",
    "quant_kv_capacity_ratio", "quant_kv_capacity_ratio_measured",
    "quant_tokens_per_sec_ratio", "quant_tokens_per_sec_on",
    "quant_tokens_per_sec_off", "quant_parity_first_divergence",
    "quant_oracle_margin_max",
    "multichip_tokens_per_sec_1chip", "multichip_tokens_per_sec_tp2",
    "multichip_total_ratio", "multichip_per_chip_ratio",
    "multichip_weight_shard_ratio", "multichip_planner_weight_err",
    "multichip_plan_llama3_8b_v5e", "multichip_topology",
    "multichip_parity_first_divergence", "multichip_oracle_margin_max",
    "multichip_engine_mbu", "multichip_engine_mfu",
    # scale-out plane (ISSUE 17): the two bench_guard-gated headlines
    # MUST ride the compact line — the guard reads the round capture,
    # and a HARD field absent from every round is a gate that never
    # fires — plus the small scalars that make a round self-evident
    "scaleout_bringup_ratio", "scaleout_source_bytes_ratio",
    "scaleout_serial_speedup", "scaleout_tree_wall_s",
    "scaleout_single_restore_s", "scaleout_tree_source_edges",
    "scaleout_nonseed_peer_bytes", "scaleout_first_admit_before_complete",
    "scaleout_chaos_restore_ok", "scaleout_chaos_peer_errors",
    "scaleout_chaos_source_bytes",
    "tpu_snapshot_file", "tpu_snapshot_captured_at",
    "tpu_snapshot_engine_tokens_per_sec_per_chip",
    "tpu_snapshot_endpoint_tokens_per_sec_per_chip",
)


def _mk_summary(detail: dict) -> dict:
    """Flat headline summary lifted from the full detail: compact keys
    plus the physics-ceiling ratios. ``engine_mbu``/``engine_mfu`` come
    straight from the LLM phase's measured engine physics — per-token
    weight+KV bytes and FLOPs derived from the DecoderConfig — and are
    significant-digit rounded upstream so a CPU run reports its real
    (tiny) ratio instead of a flat 0.0."""
    extra: dict = {}
    for k in _COMPACT_KEYS:
        if k in detail:
            extra[k] = detail[k]
    for phys_key, short in (("engine_physics", "engine"),
                            ("endpoint_physics", "endpoint")):
        p = detail.get(phys_key)
        if isinstance(p, dict):
            extra[f"{short}_mbu"] = p.get("mbu")
            extra[f"{short}_mfu"] = p.get("mfu")
    return extra


def compact_line(detail: dict) -> dict:
    """One SMALL JSON line for the driver: headline metric + a flat summary.
    Full evidence (physics blocks, timelines, per-trial data) goes to
    BENCH_DETAIL.json via _persist, never into stdout."""
    extra = _mk_summary(detail)
    v = detail.get("validation", {"violations": [], "ok": False})
    extra["validation_ok"] = v.get("ok", False)
    extra["violations_n"] = len(v.get("violations", []))
    extra["detail_file"] = "BENCH_DETAIL.json"

    tps = extra.get("endpoint_tokens_per_sec_per_chip")
    if tps and extra.get("endpoint_container_on_tpu") \
            and extra.get("endpoint_served_proof_ok"):
        # the north-star config #2: llama3-8b int8 through @endpoint on the
        # chip. No published reference number exists (BASELINE.json
        # published:{}), so vs_baseline is the fraction of the chip's
        # physics ceiling achieved (endpoint mbu) — honest and comparable.
        return {"metric": "endpoint_tokens_per_sec_per_chip", "value": tps,
                "unit": "tok/s/chip",
                "vs_baseline": extra.get("endpoint_mbu") or 0.0,
                "extra": extra}
    if "cold_start_p50_s" in extra:
        value = extra["cold_start_p50_s"]
        return {"metric": "cold_start_p50_s", "value": value, "unit": "s",
                "vs_baseline": round(1.0 / max(value, 1e-9), 3),
                "extra": extra}
    if "engine_tokens_per_sec_per_chip" in extra:
        return {"metric": "engine_tokens_per_sec_per_chip",
                "value": extra["engine_tokens_per_sec_per_chip"],
                "unit": "tok/s/chip", "vs_baseline": 0.0, "extra": extra}
    return {"metric": "bench_failed", "value": 0, "unit": "",
            "vs_baseline": 0.0, "extra": extra}


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (local verification)")
    ap.add_argument("--phase",
                    choices=["llm", "llm_endpoint", "kernels", "coldstart",
                             "coldstart_native", "coldstart_jax",
                             "coldstart_jax_tpu", "coldstart_stream",
                             "router", "spec", "quant", "obs", "multichip",
                             "faults", "disagg", "scaleout", "kvtier"],
                    help="run one phase in-process (used by the orchestrator)")
    args = ap.parse_args()

    if args.cpu:
        # --cpu means force EVERYTHING CPU, including llm_endpoint's runner
        # container. Without --cpu, llm_endpoint still forces its own parent
        # process CPU internally while the container gets the chip.
        os.environ["TPU9_BENCH_CPU"] = "1"
        # llm_endpoint force_cpu()s itself; the router phase never imports
        # jax at all (pure asyncio simulation)
        if args.phase not in ("llm_endpoint", "router", "faults"):
            from tpu9.utils import force_cpu
            force_cpu(host_devices=0 if (args.phase or "")
                      .startswith("coldstart") else 8)

    if args.phase:
        fn = {"llm": bench_llm, "llm_endpoint": bench_llm_endpoint,
              "kernels": bench_kernels, "coldstart": bench_cold_start,
              "coldstart_native": bench_cold_start_native,
              "coldstart_jax": bench_cold_start_jax,
              "coldstart_jax_tpu": bench_cold_start_jax_tpu,
              "coldstart_stream": bench_cold_start_stream,
              "router": bench_router, "spec": bench_spec,
              "quant": bench_quant, "obs": bench_obs,
              "multichip": bench_multichip,
              "faults": bench_faults, "disagg": bench_disagg,
              "scaleout": bench_scaleout,
              "kvtier": bench_kvtier}[args.phase]
        try:
            print(json.dumps(fn(quick=args.quick)))
        except Exception as exc:   # noqa: BLE001 — phase errors are data
            import traceback
            traceback.print_exc()
            print(json.dumps(
                {f"{args.phase}_error": f"{type(exc).__name__}: {exc}"}))
            sys.exit(1)
        return

    detail = orchestrate(args.quick, args.cpu)
    _persist("BENCH_DETAIL.json", detail)
    line = compact_line(detail)
    print(json.dumps(line))
    if line["metric"] == "bench_failed":
        sys.exit(1)


if __name__ == "__main__":
    main()
