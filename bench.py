#!/usr/bin/env python3
"""tpu9 benchmark — prints ONE JSON line.

Two phases, mirroring BASELINE.md's north star ("container cold-start p50 +
tokens/sec/chip"):

1. **Serving cold start** through the real local stack (gateway + scheduler +
   worker + process runtime + runner): deploy a CPU endpoint, force scale-to-
   zero between trials, measure deploy→first-response p50.
2. **LLM decode throughput**: Llama-architecture model (bf16) on the default
   backend (TPU chip when present), batched decode steady-state tokens/sec
   per chip.

Primary metric: cold_start_p50_s with ``vs_baseline`` = 1.0 / p50 against the
reference's headline "under a second" cold-start claim (README.md:39 of
beam-cloud/beta9) — >1.0 means beating it. Decode throughput is attached in
``extra``.

Usage: python3 bench.py [--quick] [--skip-coldstart] [--skip-llm]
"""

from __future__ import annotations

import argparse
import json
import statistics
import sys
import time


def bench_llm_decode(quick: bool = False) -> dict:
    import jax
    import jax.numpy as jnp

    from tpu9.models import decoder_forward, init_decoder, init_kv_cache
    from tpu9.models.llama import LLAMA_PRESETS
    from tpu9.ops.sampling import sample_logits

    backend = jax.default_backend()
    n_chips = jax.device_count()
    preset = "llama-tiny" if (quick or backend == "cpu") else "llama-1b"
    cfg = LLAMA_PRESETS[preset]

    batch, prompt_len, decode_steps = (4, 64, 16) if quick or backend == "cpu" \
        else (8, 1024, 64)
    max_len = prompt_len + decode_steps + 8

    params = init_decoder(jax.random.PRNGKey(0), cfg)
    cache = init_kv_cache(cfg, batch, max_len)

    @jax.jit
    def prefill(params, tokens, cache):
        logits, cache = decoder_forward(params, tokens, cfg, kv_cache=cache)
        return logits[:, -1:].argmax(-1).astype(jnp.int32), cache

    def decode(params, cache, tok, cache_len, rng):
        positions = cache_len[:, None]
        logits, cache = decoder_forward(params, tok, cfg, positions=positions,
                                        kv_cache=cache, cache_len=cache_len + 1,
                                        decode=True)
        rng, sub = jax.random.split(rng)
        nxt = sample_logits(logits[:, -1], sub, temperature=0.0)
        return nxt[:, None].astype(jnp.int32), cache, cache_len + 1, rng

    decode = jax.jit(decode, donate_argnums=(1,))

    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, prompt_len),
                                0, cfg.vocab_size)
    # compile + warmup
    t0 = time.perf_counter()
    tok, cache = prefill(params, tokens, cache)
    tok.block_until_ready()
    prefill_compile_s = time.perf_counter() - t0

    cache_len = jnp.full((batch,), prompt_len, jnp.int32)
    rng = jax.random.PRNGKey(2)
    t0 = time.perf_counter()
    tok, cache, cache_len, rng = decode(params, cache, tok, cache_len, rng)
    tok.block_until_ready()
    decode_compile_s = time.perf_counter() - t0

    # steady state
    t0 = time.perf_counter()
    for _ in range(decode_steps):
        tok, cache, cache_len, rng = decode(params, cache, tok, cache_len, rng)
    tok.block_until_ready()
    elapsed = time.perf_counter() - t0

    toks_per_sec = batch * decode_steps / elapsed
    return {
        "backend": backend,
        "model": preset,
        "n_chips": n_chips,
        "batch": batch,
        "decode_tokens_per_sec": round(toks_per_sec, 2),
        "decode_tokens_per_sec_per_chip": round(toks_per_sec / max(n_chips, 1), 2),
        "decode_step_ms": round(1000 * elapsed / decode_steps, 3),
        "prefill_compile_s": round(prefill_compile_s, 2),
        "decode_compile_s": round(decode_compile_s, 2),
    }


def bench_cold_start(quick: bool = False) -> dict:
    """Deploy→first-response p50 through the local stack (import-gated: phases
    of the stack land incrementally)."""
    import asyncio

    from tpu9.testing.localstack import LocalStack  # noqa: WPS433

    trials = 3 if quick else 5

    async def run() -> dict:
        times = []
        async with LocalStack() as stack:
            name = "bench-echo"
            deploy = await stack.deploy_echo_endpoint(name)
            for _ in range(trials):
                await stack.scale_to_zero(deploy)
                t0 = time.perf_counter()
                resp = await stack.invoke(deploy, {"ping": 1})
                assert resp is not None
                times.append(time.perf_counter() - t0)
        return {
            "cold_start_p50_s": round(statistics.median(times), 4),
            "cold_start_min_s": round(min(times), 4),
            "cold_start_max_s": round(max(times), 4),
            "trials": trials,
        }

    return asyncio.run(run())


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (local verification)")
    ap.add_argument("--skip-coldstart", action="store_true")
    ap.add_argument("--skip-llm", action="store_true")
    args = ap.parse_args()

    if args.cpu:
        from tpu9.utils import force_cpu
        force_cpu(host_devices=8)

    extra: dict = {}
    cold = None
    if not args.skip_coldstart:
        try:
            cold = bench_cold_start(quick=args.quick)
            extra.update(cold)
        except Exception as exc:  # stack not ready / runtime failure
            extra["cold_start_error"] = f"{type(exc).__name__}: {exc}"
    if not args.skip_llm:
        try:
            extra.update(bench_llm_decode(quick=args.quick))
        except Exception as exc:
            extra["llm_error"] = f"{type(exc).__name__}: {exc}"

    if cold and "cold_start_p50_s" in cold:
        value = cold["cold_start_p50_s"]
        line = {"metric": "cold_start_p50_s", "value": value, "unit": "s",
                "vs_baseline": round(1.0 / max(value, 1e-9), 3),
                "extra": extra}
    elif "decode_tokens_per_sec_per_chip" in extra:
        line = {"metric": "decode_tokens_per_sec_per_chip",
                "value": extra["decode_tokens_per_sec_per_chip"],
                "unit": "tok/s/chip", "vs_baseline": 0.0, "extra": extra}
    else:
        line = {"metric": "bench_failed", "value": 0, "unit": "",
                "vs_baseline": 0.0, "extra": extra}
        print(json.dumps(line))
        sys.exit(1)

    print(json.dumps(line))


if __name__ == "__main__":
    main()
