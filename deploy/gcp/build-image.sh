#!/bin/bash
# Bake a tpu9 TPU-VM disk image (run from a workstation with gcloud auth).
# Reference analogue: the reference's prebuilt worker AMIs/images its
# providers boot (pkg/providers/provider.go:15-64).
#
# Usage: PROJECT=my-proj ZONE=us-central2-b ./build-image.sh v5p
set -euo pipefail

PROJECT="${PROJECT:?set PROJECT}"
ZONE="${ZONE:?set ZONE}"
GEN="${1:-v5e}"
case "$GEN" in
  v5e) RUNTIME=v2-alpha-tpuv5-lite; ACCEL=v5litepod-1 ;;
  v5p) RUNTIME=v2-alpha-tpuv5;      ACCEL=v5p-8 ;;
  v6e) RUNTIME=v2-alpha-tpuv6e;     ACCEL=v6e-1 ;;
  *) echo "unknown generation $GEN"; exit 2 ;;
esac
NAME="tpu9-bake-$(date +%s)"

gcloud compute tpus tpu-vm create "$NAME" \
  --project="$PROJECT" --zone="$ZONE" \
  --accelerator-type="$ACCEL" --version="$RUNTIME"

tar -C "$(git rev-parse --show-toplevel)" -czf /tmp/tpu9.tar.gz \
  --exclude='.git' --exclude='__pycache__' .
gcloud compute tpus tpu-vm scp /tmp/tpu9.tar.gz "$NAME":/tmp/ \
  --project="$PROJECT" --zone="$ZONE"

gcloud compute tpus tpu-vm ssh "$NAME" --project="$PROJECT" --zone="$ZONE" \
  --command='
set -e
sudo mkdir -p /opt/tpu9 && sudo tar -xzf /tmp/tpu9.tar.gz -C /opt/tpu9
sudo python3 -m venv /opt/tpu9-venv
sudo /opt/tpu9-venv/bin/pip install -U pip
sudo /opt/tpu9-venv/bin/pip install "jax[tpu]" aiohttp numpy \
  -f https://storage.googleapis.com/jax-releases/libtpu_releases.html
sudo make -C /opt/tpu9/native
# warm the XLA compile cache location the workers share
sudo mkdir -p /var/cache/tpu9-xla && sudo chmod 1777 /var/cache/tpu9-xla
'

# snapshot the boot disk into a reusable image
DISK="$(gcloud compute tpus tpu-vm describe "$NAME" --project="$PROJECT" \
  --zone="$ZONE" --format='value(bootDisk.sourceDisk)' || true)"
echo "TPU-VM $NAME provisioned. For single-host generations snapshot its"
echo "boot disk into an image family 'tpu9-worker-$GEN'; multi-host slices"
echo "re-run the startup script per host (images carry /opt/tpu9 + venv):"
echo "  gcloud compute images create tpu9-worker-$GEN-$(date +%Y%m%d) \\"
echo "    --source-disk=$DISK --family=tpu9-worker-$GEN --project=$PROJECT"
echo "Then set worker_pools[].runtime_version to that image family."
echo "Cleanup: gcloud compute tpus tpu-vm delete $NAME --zone=$ZONE"
