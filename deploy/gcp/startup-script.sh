#!/bin/bash
# tpu9 TPU-VM startup script (VERDICT r03 #10; reference analogue: the
# provider VM userdata that boots a k3s worker, pkg/providers/ec2.go:93 +
# pkg/scheduler/pool_provider.go:286).
#
# Runs on every host of a (multi-host) TPU slice at boot. Reads its join
# parameters from instance/TPU metadata (set by GceTpuPool's
# queued-resources call) and starts a tpu9 worker that registers with the
# cluster's gateway, carrying its slice identity so the scheduler can gang-
# place multi-host workloads.
set -euo pipefail

MD="http://metadata.google.internal/computeMetadata/v1"
H="Metadata-Flavor: Google"

md() { curl -sf -H "$H" "$MD/instance/attributes/$1" || echo ""; }

GATEWAY_URL="$(md tpu9-gateway-url)"
GATEWAY_STATE="$(md tpu9-gateway-state)"
WORKER_TOKEN="$(md tpu9-worker-token)"
POOL="$(md tpu9-pool)"
SLICE_ID="$(md tpu9-slice-id)"
SLICE_TOPOLOGY="$(md tpu9-slice-topology)"
TPU_GEN="$(md tpu9-tpu-gen)"

# per-host rank within the slice (multi-host slices run one worker/host)
SLICE_RANK="$(curl -sf -H "$H" "$MD/instance/attributes/agent-worker-number" || echo 0)"
SLICE_HOSTS="$(md tpu9-slice-hosts)"
SLICE_HOSTS="${SLICE_HOSTS:-1}"

# the baked image (see build-image.sh) ships /opt/tpu9 + a venv with
# jax[tpu]; fall back to a metadata-supplied tarball for dev clusters
if [ ! -d /opt/tpu9 ]; then
  REPO_URL="$(md tpu9-repo-tarball)"
  if [ -n "$REPO_URL" ]; then
    mkdir -p /opt/tpu9
    curl -sf "$REPO_URL" | tar -xz -C /opt/tpu9 --strip-components=1
  else
    echo "tpu9: no baked /opt/tpu9 and no tpu9-repo-tarball metadata" >&2
    exit 1
  fi
fi

# build the native pieces if the image didn't (idempotent)
make -C /opt/tpu9/native >/dev/null 2>&1 || true

cat > /etc/tpu9-worker.env <<ENV
TPU9_GATEWAY_URL=${GATEWAY_URL}
TPU9_GATEWAY_STATE=${GATEWAY_STATE}
TPU9_WORKER_TOKEN=${WORKER_TOKEN}
TPU9_POOL=${POOL}
TPU9_SLICE_ID=${SLICE_ID}
TPU9_SLICE_RANK=${SLICE_RANK}
TPU9_SLICE_HOSTS=${SLICE_HOSTS}
TPU9_SLICE_TOPOLOGY=${SLICE_TOPOLOGY}
TPU9_TPU_GEN=${TPU_GEN}
PYTHONPATH=/opt/tpu9
ENV

install -m 0644 /opt/tpu9/deploy/gcp/tpu9-worker.service \
  /etc/systemd/system/tpu9-worker.service
systemctl daemon-reload
systemctl enable --now tpu9-worker.service
