"""The five baseline-config examples must at least construct valid stubs
(import-time decorator validation: tpu specs, autoscalers, volumes)."""

import importlib.util
import os
import sys

import pytest

EXAMPLES = os.path.join(os.path.dirname(__file__), "..", "examples")


def load(name):
    path = os.path.join(EXAMPLES, name)
    spec = importlib.util.spec_from_file_location(name[:-3], path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name[:-3]] = mod
    spec.loader.exec_module(mod)
    return mod


@pytest.mark.slow
def test_cpu_classifier_config():
    mod = load("01_cpu_classifier.py")
    assert mod.classify.stub_type == "endpoint"
    assert mod.classify.config.runtime.tpu == ""
    assert mod.classify.config.runtime.cpu_millicores == 1000
    # the fallback tiny model path must actually work (no transformers net)
    ctx = mod.load_model()
    out = ctx("great stuff") if not hasattr(ctx, "task") else None
    if out is not None:
        assert out[0]["label"] in ("POSITIVE", "NEGATIVE")


def test_llama_v5e1_config():
    mod = load("02_llama_v5e1.py")
    assert mod.llama.config.runtime.tpu == "v5e-1"
    assert mod.llama.config.extra["runner"] == "llm"
    # declarative model → the gateway's deploy-time HBM gate fires, and
    # the declared config must actually be feasible
    assert mod.llama.config.extra["model"] == "llama3-8b-int8"
    from tpu9.serving.feasibility import validate_llm_deployment
    assert validate_llm_deployment("llama3-8b-int8", "v5e-1").fits
    assert mod.llama.config.checkpoint.enabled
    assert mod.llama.config.volumes[0]["mount_path"] == "/models/llama3-8b"


def test_clip_fanout_config():
    mod = load("03_clip_fanout.py")
    assert mod.embed_image.stub_type == "taskqueue"
    assert mod.embed_image.config.runtime.tpu == "v5e-1"
    assert mod.embed_image.config.autoscaler.max_containers == 16
    assert mod.embed_image.config.autoscaler.tasks_per_container == 4


def test_llama70b_tp_config():
    mod = load("04_llama70b_tp_v5e8.py")
    assert mod.llama70b.config.runtime.tpu == "v5e-8"
    assert mod.llama70b.config.autoscaler.type == "token_pressure"
    from tpu9.types import parse_tpu_spec
    assert parse_tpu_spec(mod.llama70b.config.runtime.tpu).chips == 8


def test_gemma_lora_config():
    mod = load("05_gemma_lora_v5p64.py")
    assert mod.finetune.stub_type == "function"
    spec_ = mod.finetune.config.runtime
    assert spec_.tpu == "v5p-64"
    from tpu9.types import parse_tpu_spec
    s = parse_tpu_spec(spec_.tpu)
    assert s.hosts == 16 and s.multi_host
    assert mod.finetune.config.timeout_s == 4 * 3600
