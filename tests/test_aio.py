"""tpu9.utils.aio (ISSUE 7): the cancellation-correct primitives tpu9lint
rules ASY001-003 point at."""

import asyncio
import gc

import pytest

from tpu9.utils.aio import (bg_task_count, cancellable_wait, event_wait,
                            queue_get, reap, spawn)


async def test_queue_get_returns_and_times_out():
    q = asyncio.Queue()
    q.put_nowait("x")
    assert await queue_get(q, 1.0) == "x"
    with pytest.raises(asyncio.TimeoutError):
        await queue_get(q, 0.01)


async def test_queue_get_timeout_race_requeues_item():
    """A put landing exactly as the timeout fires must not be eaten."""
    q = asyncio.Queue()
    try:
        await queue_get(q, 0.01)
    except asyncio.TimeoutError:
        pass
    # simulate the race window: the reaped getter may already hold an item
    q.put_nowait("survivor")
    assert await queue_get(q, 1.0) == "survivor"


async def test_queue_get_outer_cancel_propagates_and_preserves():
    q = asyncio.Queue()
    waiter = asyncio.ensure_future(queue_get(q, 10.0))
    await asyncio.sleep(0)
    q.put_nowait("item")
    waiter.cancel()
    try:
        got = await waiter
    except asyncio.CancelledError:
        got = None
    if got is None:
        await asyncio.sleep(0)  # let the done-callback requeue
        assert q.get_nowait() == "item"
    else:
        assert got == "item"


async def test_queue_get_requeue_preserves_order():
    """A raced item re-queued by a cancelled getter must go back to the
    FRONT: events published after it must not overtake it."""
    q = asyncio.Queue()
    waiter = asyncio.ensure_future(queue_get(q, 10.0))
    await asyncio.sleep(0)
    q.put_nowait("A")           # the getter wins this
    await asyncio.sleep(0)      # getter future resolved with A
    q.put_nowait("B")
    waiter.cancel()
    try:
        got = await waiter
    except asyncio.CancelledError:
        got = None
    if got is None:
        await asyncio.sleep(0)
        assert await queue_get(q, 1.0) == "A"
        assert await queue_get(q, 1.0) == "B"
    else:
        assert got == "A"
        assert await queue_get(q, 1.0) == "B"


async def test_reap_crashed_child_reraises_or_logs(caplog):
    async def boom():
        raise ValueError("died hours ago")

    t = asyncio.ensure_future(boom())
    await asyncio.sleep(0.01)   # child already crashed before stop()
    with pytest.raises(ValueError, match="died hours ago"):
        await reap(t)           # default: same contract as `await task`

    t2 = asyncio.ensure_future(boom())
    await asyncio.sleep(0.01)
    await reap(t2, absorb_errors=True)   # absorbed, but never silently
    assert any("died hours ago" in r.message for r in caplog.records)


async def test_event_wait_set_timeout_and_cancel():
    ev = asyncio.Event()
    assert await event_wait(ev, 0.01) is False
    ev.set()
    assert await event_wait(ev, 0.01) is True
    assert await event_wait(ev) is True

    ev2 = asyncio.Event()
    waiter = asyncio.ensure_future(event_wait(ev2, 10.0))
    await asyncio.sleep(0)
    waiter.cancel()
    with pytest.raises(asyncio.CancelledError):
        await waiter


async def test_cancellable_wait_result_timeout_cancel():
    async def quick():
        return 42

    assert await cancellable_wait(quick()) == 42
    assert await cancellable_wait(quick(), 5.0) == 42

    started = asyncio.Event()
    cancelled = asyncio.Event()

    async def slow():
        started.set()
        try:
            await asyncio.sleep(60)
        except asyncio.CancelledError:
            cancelled.set()
            raise

    with pytest.raises(asyncio.TimeoutError):
        await cancellable_wait(slow(), 0.01)
    assert cancelled.is_set()   # inner task was drained, not leaked

    # outer cancel propagates (never traded for the inner result)
    waiter = asyncio.ensure_future(cancellable_wait(asyncio.sleep(60), 30))
    await asyncio.sleep(0)
    waiter.cancel()
    with pytest.raises(asyncio.CancelledError):
        await waiter


async def test_cancellable_wait_timeout_surfaces_cleanup_crash():
    """bpo-40607 parity: if the inner task's cancellation cleanup raises a
    real exception, the caller sees IT, not a TimeoutError that hides it."""
    async def bad_cleanup():
        try:
            await asyncio.sleep(60)
        except asyncio.CancelledError:
            raise OSError("teardown failed")

    with pytest.raises(OSError, match="teardown failed"):
        await cancellable_wait(bad_cleanup(), 0.01)


def test_spawn_set_prunes_closed_loop_tasks(monkeypatch):
    """A task stranded by a closed loop must not pin frames forever or
    pollute bg_task_count for later loops (fresh-loop-per-test harness).
    The prune is amortized by a high-water mark; force it low here."""
    from tpu9.utils import aio as aio_mod
    monkeypatch.setattr(aio_mod, "_prune_watermark", 1)

    async def strand():
        spawn(asyncio.Event().wait(), name="stranded")

    asyncio.run(strand())       # loop closes with the task still pending
    assert bg_task_count() == 0     # count never includes dead-loop tasks

    async def next_loop():
        done = asyncio.Event()
        done.set()
        t = spawn(done.wait(), name="fresh")   # watermark hit -> prune
        await t

    asyncio.run(next_loop())
    assert all(not t.get_loop().is_closed() for t in aio_mod._BG_TASKS)


async def test_spawn_holds_strong_ref_until_done():
    done = asyncio.Event()

    async def bg():
        await done.wait()
        return "ok"

    t = spawn(bg(), name="test-bg")
    ref = t.get_name()
    del t
    gc.collect()                # a weak-ref'd task could be collected here
    assert bg_task_count() >= 1
    done.set()
    await asyncio.sleep(0.05)
    assert ref == "test-bg"


async def test_spawn_logs_crash_without_unraisable(caplog):
    async def boom():
        raise RuntimeError("bg crash")

    spawn(boom(), name="crasher")
    await asyncio.sleep(0.05)
    gc.collect()   # no 'exception was never retrieved' may escape
    assert any("bg crash" in r.message for r in caplog.records)


async def test_reap_absorbs_child_cancel_but_not_ours():
    child = asyncio.ensure_future(asyncio.sleep(60))
    await reap(child)           # returns cleanly, child cancelled
    assert child.cancelled()
    await reap(None)            # tolerated

    # a cancelled stop() must abort, not continue past the drain —
    # the child's slow cleanup keeps reap's gather parked while we cancel
    async def slow_exit():
        try:
            await asyncio.sleep(60)
        except asyncio.CancelledError:
            try:
                await asyncio.sleep(0.5)   # cleanup window
            except asyncio.CancelledError:
                pass
            raise

    async def stopper():
        blocker = asyncio.ensure_future(slow_exit())
        await asyncio.sleep(0)             # let the child start
        await reap(blocker)
        return "finished"

    s = asyncio.ensure_future(stopper())
    await asyncio.sleep(0.05)              # child is draining inside reap
    assert s.cancel()
    with pytest.raises(asyncio.CancelledError):
        await s
    assert s.cancelled()        # did NOT swallow our cancel and finish
