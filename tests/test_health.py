"""Replica health plane (ISSUE 14): gray-failure watchdog state machine,
HBM/liveness watermarks in engine stats, the post-mortem black box (build/
clamp/append), the Prometheus tpu9_health_*/tpu9_hbm_* gauge families
(golden exposition incl. label escaping), and the router's stalled-replica
ejection ledger."""

import asyncio
import json

import jax
import pytest

from tpu9.models import init_decoder
from tpu9.models.llama import LLAMA_PRESETS
from tpu9.observability import health
from tpu9.observability.health import (EngineWatchdog, WatchdogConfig,
                                       build_postmortem, clamp_postmortem,
                                       health_code, load_postmortems,
                                       publish_health, store_postmortem)
from tpu9.serving.engine import EngineConfig, InferenceEngine


# ---------------------------------------------------------------------------
# watchdog state machine
# ---------------------------------------------------------------------------

def _stats(**kw):
    base = dict(queued=0, active_streams=0, windows_processed=0,
                tokens_generated=0, admit_dispatches=0,
                graph_compiles_post_warmup=0)
    base.update(kw)
    return base


def test_watchdog_idle_is_ok_forever():
    wd = EngineWatchdog(WatchdogConfig(stall_after_s=1.0,
                                       degraded_after_s=0.5))
    assert wd.assess(_stats(), now=0.0) == ("ok", "")
    # hours of idle: the frozen watermark indicts nothing without work
    assert wd.assess(_stats(), now=10_000.0) == ("ok", "")
    assert not wd.pop_stall_trip()


def test_watchdog_degrades_then_stalls_with_queued_work():
    wd = EngineWatchdog(WatchdogConfig(stall_after_s=6.0,
                                       degraded_after_s=2.5))
    s = _stats(queued=2)
    assert wd.assess(s, now=0.0) == ("ok", "")
    assert wd.assess(s, now=3.0) == ("degraded", "slow_progress")
    state, reason = wd.assess(s, now=6.5)
    assert (state, reason) == ("stalled", "no_progress_with_queued_work")
    # the trip fires exactly once per incident
    assert wd.pop_stall_trip()
    assert not wd.pop_stall_trip()
    assert wd.assess(s, now=8.0)[0] == "stalled"
    assert not wd.pop_stall_trip()


def test_watchdog_recovers_on_watermark_movement():
    wd = EngineWatchdog(WatchdogConfig(stall_after_s=1.0,
                                       degraded_after_s=0.5))
    s = _stats(active_streams=1)
    wd.assess(s, now=0.0)
    assert wd.assess(s, now=2.0)[0] == "stalled"
    # any progress counter moving = alive again
    assert wd.assess(_stats(active_streams=1, tokens_generated=5),
                     now=2.5) == ("ok", "")
    # and a NEW incident trips a NEW post-mortem
    assert wd.assess(_stats(active_streams=1, tokens_generated=5),
                     now=5.0)[0] == "stalled"
    assert wd.pop_stall_trip()


def test_watchdog_post_idle_work_does_not_inherit_idle_age():
    """A replica idle for an hour that then receives a request must get
    a FRESH stall window — the idle age is not missing progress."""
    wd = EngineWatchdog(WatchdogConfig(stall_after_s=5.0,
                                       degraded_after_s=2.0))
    wd.assess(_stats(), now=0.0)
    wd.assess(_stats(), now=3600.0)
    assert wd.assess(_stats(queued=1), now=3601.0) == ("ok", "")
    assert wd.assess(_stats(queued=1), now=3604.0)[0] == "degraded"


def test_watchdog_compile_storm_degrades_without_work():
    wd = EngineWatchdog(WatchdogConfig(storm_window_s=10.0))
    # first sample is the BASELINE — a restarted watchdog must not flag
    # compiles that happened before it was watching
    assert wd.assess(_stats(graph_compiles_post_warmup=4),
                     now=0.0) == ("ok", "")
    state, reason = wd.assess(_stats(graph_compiles_post_warmup=5),
                              now=1.0)
    assert (state, reason) == ("degraded", "compile_storm")
    # sticky for the storm window, then clears
    assert wd.assess(_stats(graph_compiles_post_warmup=5),
                     now=9.0)[0] == "degraded"
    assert wd.assess(_stats(graph_compiles_post_warmup=5),
                     now=12.0) == ("ok", "")


def test_watchdog_engine_dead_is_stalled_immediately():
    wd = EngineWatchdog()
    state, reason = wd.assess(_stats(engine_dead=True), now=0.0)
    assert (state, reason) == ("stalled", "engine_dead")
    assert wd.pop_stall_trip()


def test_watchdog_hbm_pressure_degrades():
    wd = EngineWatchdog(WatchdogConfig(hbm_pressure_frac=0.97))
    ok = _stats(hbm_used_gb_per_chip=10.0, hbm_limit_gb_per_chip=16.0)
    assert wd.assess(ok, now=0.0) == ("ok", "")
    hot = _stats(hbm_used_gb_per_chip=15.8, hbm_limit_gb_per_chip=16.0)
    assert wd.assess(hot, now=1.0) == ("degraded", "hbm_pressure")
    # no limit reported (CPU): never classified on HBM
    wd2 = EngineWatchdog()
    assert wd2.assess(_stats(hbm_used_gb_per_chip=15.8),
                      now=0.0) == ("ok", "")


def test_watchdog_config_from_env():
    cfg = WatchdogConfig.from_env({"TPU9_HEALTH_STALL_S": "1.5",
                                   "TPU9_HEALTH_DEGRADED_S": "0.4",
                                   "TPU9_HEALTH_HBM_FRAC": "garbage"})
    assert cfg.stall_after_s == 1.5
    assert cfg.degraded_after_s == 0.4
    assert cfg.hbm_pressure_frac == WatchdogConfig.hbm_pressure_frac


def test_health_code_unknown_reads_stalled():
    assert health_code("ok") == 0
    assert health_code("degraded") == 1
    assert health_code("stalled") == 2
    # an unparseable verdict must never look healthy
    assert health_code("???") == 2
    assert health_code(None) == 2


# ---------------------------------------------------------------------------
# post-mortem black box: build / clamp / append
# ---------------------------------------------------------------------------

def test_build_postmortem_bounds_tails():
    rec = build_postmortem(
        reason="watchdog_stall", exception="X" * 5000, container_id="c0",
        stats={"queued": 3, "nested": {"drop": 1}},
        flight=[{"seq": i} for i in range(500)],
        spans=[{"spanId": str(i)} for i in range(500)])
    assert len(rec["exception"]) == 2000
    assert len(rec["flight"]) == health.FLIGHT_TAIL
    assert rec["flight"][-1]["seq"] == 499          # newest survive
    assert len(rec["spans"]) == health.SPAN_TAIL
    assert "nested" not in rec["stats"]             # scalars only
    assert rec["stats"]["queued"] == 3


def test_clamp_postmortem_byte_bound_keeps_header():
    rec = {"reason": "engine_crash", "exception": "boom",
           "container_id": "c1", "ts": 1.0,
           "stats": {"big": "x" * 4096},
           "scheduler": {}, "kv_pool": {}, "hbm": {"u": 1.0},
           "flight": [{"seq": i, "pad": "y" * 512} for i in range(64)],
           "spans": [{"spanId": str(i), "pad": "z" * 512}
                     for i in range(64)]}
    out = clamp_postmortem(rec, max_bytes=8 * 1024)
    assert len(json.dumps(out)) <= 8 * 1024
    # the header always survives, evidence is shed oldest-first
    assert out["reason"] == "engine_crash" and out["exception"] == "boom"
    if out["flight"]:
        assert out["flight"][-1]["seq"] == 63


def test_clamp_postmortem_bounds_hostile_records():
    """Review regression: the byte bound must hold for ANY record a
    container-token holder ships — payload under novel keys, oversized
    header-adjacent dicts, garbage types — not just well-formed ones."""
    rec = {"reason": "x" * 5000, "exception": 12345, "ts": "garbage",
           "container_id": "c" * 500,
           "hbm": {"pad": "A" * 3_000_000},
           "evil_extra": "B" * 2_000_000,
           "flight": [], "spans": []}
    out = clamp_postmortem(rec)
    assert len(json.dumps(out)) <= health.MAX_POSTMORTEM_BYTES
    assert "evil_extra" not in out                 # schema whitelist
    assert len(out["reason"]) == 200
    assert out["exception"] == "12345"
    assert out["ts"] == 0.0
    assert len(out["container_id"]) == 128
    # section TYPES coerced too: every consumer .get()s the dicts and
    # iterates flight/spans as dicts — shape-hostile values must not
    # crash `tpu9 postmortem` downstream
    out = clamp_postmortem({"reason": "x", "hbm": [1, 2],
                            "scheduler": "nope", "stats": 7,
                            "flight": ["a", {"seq": 1}], "spans": "zz"})
    assert out["hbm"] == {} and out["scheduler"] == {} and \
        out["stats"] == {}
    assert out["flight"] == [{"seq": 1}] and out["spans"] == []


def test_clamp_postmortem_unserializable_keeps_header():
    out = clamp_postmortem({"reason": "r", "exception": "e",
                            "stats": {"bad": object()},
                            "flight": [], "spans": []})
    assert out["reason"] == "r"
    assert out["stats"] == {}


def test_store_postmortem_atomic_list_caps_and_skips_corrupt():
    """Storage contract: rpush+ltrim (atomic — the gateway's heartbeat
    record and the worker's exit record for the same container land from
    different processes; a get→append→set would let one erase the
    other), newest MAX_POSTMORTEM_RECORDS retained, corrupt elements
    skipped on read."""
    from tpu9.statestore import MemoryStore

    async def run():
        store = MemoryStore()
        for i in range(12):
            await store_postmortem(store, "cX", {"reason": f"r{i}"})
        records = await load_postmortems(store, "postmortem:cX")
        assert len(records) == health.MAX_POSTMORTEM_RECORDS
        assert records[-1]["reason"] == "r11"        # newest win
        assert records[0]["reason"] == "r4"
        assert (await store.ttl("postmortem:cX")) > 0
        # a corrupt element (store damage) is skipped, never fatal
        await store.rpush("postmortem:cX", "{not json")
        records = await load_postmortems(store, "postmortem:cX")
        assert [r["reason"] for r in records][-1] == "r11"

    asyncio.run(run())


# ---------------------------------------------------------------------------
# Prometheus golden exposition: tpu9_health_* / tpu9_hbm_* families
# (ISSUE 14 satellite, mirroring the tpu9_slo_*/tpu9_goodput_* golden)
# ---------------------------------------------------------------------------

def test_health_publish_uses_stable_prometheus_names():
    from tpu9.observability import metrics as global_metrics
    publish_health("cA", {"health": "stalled",
                          "hbm_used_gb_per_chip": 12.5,
                          "hbm_peak_gb_per_chip": 14.0,
                          "hbm_predicted_gb_per_chip": 13.0,
                          "hbm_limit_gb_per_chip": 16.0})
    publish_health("cB", {"health": "ok",
                          "hbm_used_gb_per_chip": 1.0})
    text = global_metrics.prometheus_text()
    for needle in (
            'tpu9_health_state{replica="cA"} 2',
            'tpu9_health_stalled{replica="cA"} 1.0',
            'tpu9_health_state{replica="cB"} 0',
            'tpu9_health_stalled{replica="cB"} 0.0',
            'tpu9_hbm_used_gb{replica="cA"} 12.5',
            'tpu9_hbm_peak_gb{replica="cA"} 14.0',
            'tpu9_hbm_predicted_gb{replica="cA"} 13.0',
            'tpu9_hbm_limit_gb{replica="cA"} 16.0',
            'tpu9_hbm_headroom_frac{replica="cA"} 0.21875',
            # no limit shipped → no headroom/limit series for cB
            'tpu9_hbm_used_gb{replica="cB"} 1.0'):
        assert needle in text, f"missing exposition line: {needle}"
    assert 'tpu9_hbm_headroom_frac{replica="cB"}' not in text


def test_forget_replica_drops_all_health_gauges():
    """Review regression: a scaled-away replica's last verdict (often
    `stalled`) must not alert forever, and per-cid gauge series must not
    accumulate under autoscaler churn — forget_replica drops exactly the
    families publish_health mints."""
    from tpu9.observability import metrics as global_metrics
    health.publish_health("cDead", {"health": "stalled",
                                    "hbm_used_gb_per_chip": 12.0,
                                    "hbm_peak_gb_per_chip": 13.0,
                                    "hbm_predicted_gb_per_chip": 11.0,
                                    "hbm_limit_gb_per_chip": 16.0})
    assert 'tpu9_health_stalled{replica="cDead"}' in \
        global_metrics.prometheus_text()
    health.forget_replica("cDead")
    text = global_metrics.prometheus_text()
    assert 'replica="cDead"' not in text
    # idempotent on an unknown replica
    health.forget_replica("cNever")


def test_health_publish_escapes_label_values():
    """Label-value escaping rules (backslash, quote, newline) apply to
    the replica label exactly as the text exposition format requires —
    the same Metrics._key contract the SLO golden test pins."""
    from tpu9.observability import metrics as global_metrics
    publish_health('c\\evil"id\n', {"health": "degraded"})
    text = global_metrics.prometheus_text()
    assert 'tpu9_health_state{replica="c\\\\evil\\"id\\n"} 1' in text


# ---------------------------------------------------------------------------
# engine-side: liveness watermark + HBM watermarks + blackbox
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def tiny():
    cfg = LLAMA_PRESETS["llama-tiny"]
    return cfg, init_decoder(jax.random.PRNGKey(0), cfg)


def _engine(tiny, **kw):
    cfg, params = tiny
    base = dict(max_batch=2, max_seq_len=256, prefill_buckets=(32, 64),
                decode_steps=(1, 4), kv_block_size=32, kv_pool_blocks=16,
                prefill_chunk=32)
    base.update(kw)
    return InferenceEngine(params, cfg, EngineConfig(**base))


def test_engine_stats_carry_liveness_and_hbm_watermarks(tiny):
    eng = _engine(tiny)

    async def run():
        await eng.start()
        s0 = eng.stats()
        assert s0["windows_processed"] == 0
        assert s0["last_dispatch_age_s"] == -1.0     # never dispatched
        assert s0["last_progress_age_s"] >= 0.0
        assert s0["hbm_predicted_gb_per_chip"] > 0.0
        assert s0["hbm_peak_gb_per_chip"] >= s0["hbm_used_gb_per_chip"]
        assert "hbm_limit_gb_per_chip" in s0
        out = await eng.generate([1, 2, 3, 4], max_new_tokens=6)
        assert len(out) == 6
        s1 = eng.stats()
        assert s1["windows_processed"] > 0
        assert s1["last_dispatch_age_s"] >= 0.0
        await eng.stop()

    asyncio.run(run())


def test_engine_blackbox_snapshot(tiny):
    eng = _engine(tiny)

    async def run():
        await eng.start()
        await eng.generate([5, 6, 7], max_new_tokens=4)
        bb = eng.blackbox("watchdog_stall", "synthetic")
        assert bb["reason"] == "watchdog_stall"
        assert bb["kv_pool"]["n_blocks"] > 0
        assert bb["scheduler"]["queued"] == 0
        assert any(r["kind"] == "decode" for r in bb["flight"])
        assert set(bb["hbm"]) == {"hbm_used_gb_per_chip",
                                  "hbm_peak_gb_per_chip",
                                  "hbm_predicted_gb_per_chip",
                                  "hbm_limit_gb_per_chip"}
        # the whole record is JSON-serializable after the runner clamp
        json.dumps(build_postmortem(container_id="c0", **bb))
        await eng.stop()

    asyncio.run(run())


def test_engine_crash_leaves_postmortem(tiny):
    """A serve-loop death captures the black box BEFORE request fan-out
    clears the scheduler state — and generate() fails fast afterward."""
    eng = _engine(tiny)

    async def run():
        await eng.start()
        await eng.generate([1, 2], max_new_tokens=2)
        # break the next dispatch from the inside
        eng._decode_k = None      # TypeError in the loop = crash
        # infrastructure failures raise RuntimeError since ISSUE 15 (the
        # runner maps them to 500 so the gateway failover can retry them)
        with pytest.raises(RuntimeError, match="engine failure"):
            await eng.generate([3, 4], max_new_tokens=4)
        assert eng.last_postmortem is not None
        assert eng.last_postmortem["reason"] == "engine_crash"
        assert "TypeError" in eng.last_postmortem["exception"]
        assert eng.stats()["engine_dead"]
        with pytest.raises(RuntimeError, match="engine is dead"):
            await eng.generate([5], max_new_tokens=1)
        await eng.stop()

    asyncio.run(run())
