from dataclasses import replace

import jax
import jax.numpy as jnp
import numpy as np

from tpu9.models import decoder_forward, init_decoder
from tpu9.models.llama import LLAMA_PRESETS
from tpu9.ops.quant import (dequantize_weight, quantize_decoder,
                            quantize_weight, quantized_bytes,
                            quantized_matmul)

TINY = replace(LLAMA_PRESETS["llama-tiny"], dtype=jnp.float32)


def test_quantize_roundtrip_error_small():
    w = jax.random.normal(jax.random.PRNGKey(0), (256, 128)) * 0.05
    entry = quantize_weight(w)
    assert entry["q"].dtype == jnp.int8
    back = dequantize_weight(entry, dtype=jnp.float32)
    rel = float(jnp.abs(back - w).max() / jnp.abs(w).max())
    assert rel < 0.02


def test_quantized_matmul_close():
    w = jax.random.normal(jax.random.PRNGKey(0), (64, 32)) * 0.1
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 64))
    entry = quantize_weight(w)
    ref = x @ w
    got = quantized_matmul(x, entry)
    # int8 weights + bf16 activations: expect ~1% relative error
    rel = float(jnp.abs(got - ref).max() / (jnp.abs(ref).max() + 1e-9))
    assert rel < 0.05, rel


def test_quantized_decoder_outputs_close_and_smaller():
    params = init_decoder(jax.random.PRNGKey(0), TINY)
    qparams = quantize_decoder(params)
    tokens = jnp.array([[1, 5, 9, 13, 2, 7, 3, 8]])
    ref = decoder_forward(params, tokens, TINY)
    got = decoder_forward(qparams, tokens, TINY)
    # logits drift from int8 weights but ranking should broadly agree
    ref_top = jnp.argmax(ref, axis=-1)
    got_top = jnp.argmax(got, axis=-1)
    agreement = float((ref_top == got_top).mean())
    assert agreement >= 0.5, agreement
    assert jnp.isfinite(got).all()
    # memory win: projections drop from 4 bytes (f32) to ~1 byte/param
    assert quantized_bytes(qparams) < 0.55 * quantized_bytes(params)


def test_quantized_decode_path():
    from tpu9.models import init_kv_cache
    params = quantize_decoder(init_decoder(jax.random.PRNGKey(0), TINY))
    cache = init_kv_cache(TINY, 1, 32)
    logits, cache = decoder_forward(params, jnp.array([[1, 2, 3]]), TINY,
                                    kv_cache=cache)
    step, cache = decoder_forward(params, jnp.array([[4]]), TINY,
                                  positions=jnp.array([[3]]), kv_cache=cache,
                                  cache_len=jnp.array([4]), decode=True)
    assert step.shape == (1, 1, TINY.vocab_size)
    assert bool(jnp.isfinite(step).all())
