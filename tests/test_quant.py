from dataclasses import replace

import jax
import jax.numpy as jnp
import numpy as np

from tpu9.models import decoder_forward, init_decoder
from tpu9.models.llama import LLAMA_PRESETS
import pytest

from tpu9.ops.quant import (dequantize_weight, quantize_decoder,
                            quantize_weight, quantized_bytes,
                            quantized_matmul)

TINY = replace(LLAMA_PRESETS["llama-tiny"], dtype=jnp.float32)


def test_quantize_roundtrip_error_small():
    w = jax.random.normal(jax.random.PRNGKey(0), (256, 128)) * 0.05
    entry = quantize_weight(w)
    assert entry["q"].dtype == jnp.int8
    back = dequantize_weight(entry, dtype=jnp.float32)
    rel = float(jnp.abs(back - w).max() / jnp.abs(w).max())
    assert rel < 0.02


def test_quantized_matmul_close():
    w = jax.random.normal(jax.random.PRNGKey(0), (64, 32)) * 0.1
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 64))
    entry = quantize_weight(w)
    ref = x @ w
    got = quantized_matmul(x, entry)
    # int8 weights + bf16 activations: expect ~1% relative error
    rel = float(jnp.abs(got - ref).max() / (jnp.abs(ref).max() + 1e-9))
    assert rel < 0.05, rel


def test_quantized_decoder_outputs_close_and_smaller():
    params = init_decoder(jax.random.PRNGKey(0), TINY)
    qparams = quantize_decoder(params)
    tokens = jnp.array([[1, 5, 9, 13, 2, 7, 3, 8]])
    ref = decoder_forward(params, tokens, TINY)
    got = decoder_forward(qparams, tokens, TINY)
    # logits drift from int8 weights but ranking should broadly agree
    ref_top = jnp.argmax(ref, axis=-1)
    got_top = jnp.argmax(got, axis=-1)
    agreement = float((ref_top == got_top).mean())
    assert agreement >= 0.5, agreement
    assert jnp.isfinite(got).all()
    # memory win: projections drop from 4 bytes (f32) to ~1 byte/param
    assert quantized_bytes(qparams) < 0.55 * quantized_bytes(params)


@pytest.mark.slow
def test_quantized_decode_path():
    from tpu9.models import init_kv_cache
    params = quantize_decoder(init_decoder(jax.random.PRNGKey(0), TINY))
    cache = init_kv_cache(TINY, 1, 32)
    logits, cache = decoder_forward(params, jnp.array([[1, 2, 3]]), TINY,
                                    kv_cache=cache)
    step, cache = decoder_forward(params, jnp.array([[4]]), TINY,
                                  positions=jnp.array([[3]]), kv_cache=cache,
                                  cache_len=jnp.array([4]), decode=True)
    assert step.shape == (1, 1, TINY.vocab_size)
    assert bool(jnp.isfinite(step).all())


@pytest.mark.slow
def test_int8_quality_bound_vs_bf16():
    """VERDICT r03 #9: a NUMERIC bound on int8 weight-only quality, not
    just structural checks. Quantize real bf16 params, compare full-model
    logits and greedy continuations on a fixed prompt set.

    Documented bound (pinned here): per-channel symmetric int8 on
    llama-tiny keeps max |Δlogit| < 0.25 and softmax top-1 agreement
    ≥ 90% across prompts; greedy 8-token continuations agree on ≥ 75% of
    positions. (The deltas scale with dim⁻¹ᐟ²; production 8B is tighter.)
    """
    import jax
    import jax.numpy as jnp

    from tpu9.models import init_decoder
    from tpu9.models.llama import LLAMA_PRESETS
    from tpu9.models.transformer import decoder_forward
    from tpu9.ops.quant import quantize_decoder

    cfg = LLAMA_PRESETS["llama-tiny"]
    dense = init_decoder(jax.random.PRNGKey(7), cfg)
    quant = quantize_decoder(dense)

    prompts = [
        [(i * 13) % 250 + 1 for i in range(24)],
        [(i * 7 + 3) % 250 + 1 for i in range(24)],
        [(i * 29 + 11) % 250 + 1 for i in range(24)],
        [5] * 24,
    ]
    toks = jnp.asarray(prompts, jnp.int32)
    logits_d = decoder_forward(dense, toks, cfg)      # [P, T, V]
    logits_q = decoder_forward(quant, toks, cfg)

    max_abs = float(jnp.max(jnp.abs(logits_d - logits_q)))
    assert max_abs < 0.25, f"int8 logit drift {max_abs}"

    top1_d = jnp.argmax(logits_d, axis=-1)
    top1_q = jnp.argmax(logits_q, axis=-1)
    agreement = float(jnp.mean(top1_d == top1_q))
    assert agreement >= 0.90, f"top-1 agreement {agreement}"

    # greedy continuations through the full forward (teacher-forced on
    # each model's own argmax — end-to-end drift, not single-step)
    def greedy(params, prompt, steps=8):
        seq = list(prompt)
        for _ in range(steps):
            lg = decoder_forward(params, jnp.asarray([seq], jnp.int32), cfg)
            seq.append(int(jnp.argmax(lg[0, -1])))
        return seq[len(prompt):]

    agree_pos = 0
    total = 0
    for p in prompts[:2]:
        gd = greedy(dense, p)
        gq = greedy(quant, p)
        agree_pos += sum(a == b for a, b in zip(gd, gq))
        total += len(gd)
    assert agree_pos / total >= 0.75, (agree_pos, total)
