"""CacheFS read-through volume mounts with overlay write-back (VERDICT
r04 #5): a container must be READY before a multi-GB volume is local,
reads must fault exactly the chunks touched, and writes must persist to
the object store on exit.

Reference analogue: per-workspace S3 FUSE mounts
(``/root/reference/pkg/storage/storage.go:24-31``,
``pkg/worker/storage_manager.go:36``).
Root-gated: needs /dev/fuse + the t9cachefs binary + overlayfs.
"""

import asyncio
import hashlib
import os
import sys
import time

import aiohttp
import pytest

from tpu9.cache.fusefs import CacheFsManager
from tpu9.config import AppConfig, WorkerConfig
from tpu9.gateway import Gateway
from tpu9.statestore import MemoryStore

pytestmark = [
    pytest.mark.e2e,
    pytest.mark.skipif(not CacheFsManager.supported(),
                       reason="needs root + /dev/fuse + t9cachefs"),
]


def _cfg(tmp_path) -> AppConfig:
    cfg = AppConfig()
    cfg.gateway.http_port = 0
    cfg.gateway.state_port = 0
    cfg.database.path = ":memory:"
    cfg.storage.local_root = str(tmp_path / "ws")
    cfg.image.registry_dir = str(tmp_path / "registry")
    return cfg


async def test_volume_cachefs_mount_reads_and_writes_back(tmp_path):
    from tpu9.cache import CacheClient, DiskStore
    from tpu9.images.manifest import ImageManifest
    from tpu9.repository import ContainerRepository
    from tpu9.runtime import ProcessRuntime
    from tpu9.storage.volmount import VolumeMounter
    from tpu9.types import ContainerRequest, Mount
    from tpu9.worker.lifecycle import ContainerLifecycle
    from tpu9.worker.tpu_manager import TpuDeviceManager

    gw = Gateway(_cfg(tmp_path), store=MemoryStore())
    await gw.start()
    base_url = f"http://127.0.0.1:{gw.port}"
    ws_id = gw.default_workspace.workspace_id
    # a "big" dataset volume: 24 MiB spans several 4 MiB chunks
    payload = os.urandom(24 * 1024 * 1024)
    await gw.volume_files.write(ws_id, "data", "big/dataset.bin", payload)
    await gw.volume_files.write(ws_id, "data", "README", b"hello volume")

    session = aiohttp.ClientSession(
        headers={"Authorization": f"Bearer {gw.worker_token}"})

    async def volume_manifest(workspace_id, name):
        async with session.get(
                f"{base_url}/rpc/internal/volume/"
                f"{workspace_id}/{name}/manifest") as resp:
            if resp.status != 200:
                return None
            return ImageManifest.from_json(await resp.text())

    pushed = []

    async def volume_push(workspace_id, name, local_dir):
        for dirpath, _dirs, files in os.walk(local_dir):
            for fn in files:
                full = os.path.join(dirpath, fn)
                rel = os.path.relpath(full, local_dir).replace(os.sep, "/")
                with open(full, "rb") as f:
                    await gw.volume_files.write(workspace_id, name, rel,
                                                f.read())
                pushed.append(rel)

    # worker-side cache whose SOURCE is the gateway chunk endpoint — the
    # same fetch path a cross-host worker uses
    async def source(digest):
        async with session.get(
                f"{base_url}/rpc/image/chunk/{digest}") as resp:
            return await resp.read() if resp.status == 200 else None

    async def peers():
        return []

    store = DiskStore(str(tmp_path / "chunkstore"))
    client = CacheClient(store, peers, source=source)
    fusefs = CacheFsManager(client, str(tmp_path / "fuse"))
    mounter = VolumeMounter(fusefs, volume_manifest, volume_push,
                            str(tmp_path / "volmounts"),
                            min_bytes=1024 * 1024)

    cfg = WorkerConfig(containers_dir=str(tmp_path / "c"),
                       storage_root=str(tmp_path / "unshared"),
                       storage_shared=False)
    lc = ContainerLifecycle(
        "w1", cfg, ProcessRuntime(base_dir=cfg.containers_dir),
        ContainerRepository(MemoryStore()), TpuDeviceManager())
    lc.volmount = mounter
    lc.volume_push = volume_push

    app = (
        "import hashlib, os, time\n"
        "t0 = time.time()\n"
        "sha = hashlib.sha256(\n"
        "    open('vol/data/big/dataset.bin', 'rb').read()).hexdigest()\n"
        "open('vol/data/result.txt', 'w').write(\n"
        "    sha + ' ' + open('vol/data/README').read())\n")
    req = ContainerRequest(
        container_id="c-volmnt", stub_id="s", workspace_id=ws_id,
        stub_type="pod",
        entrypoint=[sys.executable, "-c", app],
        mounts=[Mount(source="data", target="/vol/data", kind="volume")])

    try:
        t0 = time.perf_counter()
        await lc.run_container(req)
        start_s = time.perf_counter() - t0
        # READY fast: nothing of the 24 MiB was copied at start (the
        # mount is a manifest view) — generous bound for CI noise, the
        # real assertion is the fault counters below
        assert start_s < 20.0
        mounts = mounter._mounts.get("c-volmnt")
        assert mounts, "volume was synced, not CacheFS-mounted"
        cfs = mounts[0][2]

        await lc.runtime.wait("c-volmnt")
        for _ in range(200):              # supervisor runs unmount+push
            if "result.txt" in pushed:
                break
            await asyncio.sleep(0.05)
        assert "result.txt" in pushed, pushed
        # chunk-proven reads: the container's read faulted chunks through
        # the cache (cold store → every chunk came via the fault socket)
        assert cfs.stats["faults"] > 0, cfs.stats

        out = await gw.volume_files.read(ws_id, "data", "result.txt")
        want = hashlib.sha256(payload).hexdigest() + " hello volume"
        assert out is not None and out.decode() == want
        # ONLY the written file pushed back (overlay upper = the delta),
        # not a re-upload of the 24 MiB dataset
        assert "big/dataset.bin" not in pushed
        # the unmodified dataset is untouched in the store
        back = await gw.volume_files.read(ws_id, "data", "big/dataset.bin")
        assert back == payload
    finally:
        await mounter.close()
        await session.close()
        await gw.stop()


async def test_small_volume_falls_back_to_sync(tmp_path):
    """Below the size threshold the mounter declines and the existing
    sync-down path serves the volume (one copy beats FUSE round-trips)."""
    from tpu9.storage.volmount import VolumeMounter

    async def manifest_fetch(ws, name):
        from tpu9.images.manifest import FileEntry, ImageManifest
        m = ImageManifest(image_id="small", kind="env")
        m.files.append(FileEntry(path="x", mode=0o644, size=10,
                                 chunks=["d"]))
        m.total_bytes = 10
        return m

    mounter = VolumeMounter(object(), manifest_fetch, None,
                            str(tmp_path / "vm"), min_bytes=1024)
    assert await mounter.try_mount("ws", "vol", "c1") is None
