"""NativeRuntime: real containment (t9container namespaces + pivot_root +
netns/veth + userspace port proxy + overlay). Root-gated — the reference
gates its worker/network tests on privileges the same way
(pkg/worker/network_test.go)."""

import asyncio
import os
import sys

import pytest

from tpu9.runtime import NativeRuntime
from tpu9.runtime.base import ContainerSpec

pytestmark = [
    pytest.mark.e2e,
    pytest.mark.skipif(not NativeRuntime.supported(),
                       reason="needs root + t9container + iproute2"),
]


def _spec(container_id: str, entrypoint, workdir: str = "",
          ports=None) -> ContainerSpec:
    return ContainerSpec(container_id=container_id, entrypoint=entrypoint,
                         env={"TPU9_MARK": "native"}, workdir=workdir,
                         ports=ports or {})


async def _run_and_wait(rt: NativeRuntime, spec: ContainerSpec,
                        timeout: float = 60.0):
    lines: list[str] = []
    rt_handle = await rt.run(spec, log_cb=lambda line, s: lines.append(line))
    code = await asyncio.wait_for(rt.wait(spec.container_id), timeout)
    return code, lines


async def test_pid_hostname_env_isolation(tmp_path):
    rt = NativeRuntime(base_dir=str(tmp_path))
    code, lines = await _run_and_wait(rt, _spec(
        "nat-iso1", ["/bin/sh", "-c",
                     "echo pid=$$; hostname; echo mark=$TPU9_MARK; "
                     "ls /tmp | wc -l"]))
    try:
        assert code == 0, lines
        assert "pid=1" in lines            # PID namespace: entrypoint is init
        assert "nat-iso1" in lines         # UTS namespace: own hostname
        assert "mark=native" in lines      # env file delivered
        assert lines[-1].strip() == "0"    # fresh /tmp — host's is invisible
    finally:
        await rt.cleanup("nat-iso1")


async def test_workdir_bind_rw(tmp_path):
    rt = NativeRuntime(base_dir=str(tmp_path / "rt"))
    work = tmp_path / "work"
    work.mkdir()
    code, lines = await _run_and_wait(rt, _spec(
        "nat-wd", ["/bin/sh", "-c", "pwd && echo out > result.txt"],
        workdir=str(work)))
    try:
        assert code == 0, lines
        assert (work / "result.txt").read_text().strip() == "out"
    finally:
        await rt.cleanup("nat-wd")


async def test_egress_blocked_but_host_reachable(tmp_path):
    """The netns reaches the host veth peer, and nothing beyond — the
    reference's egress blocking (network.go:275) by construction."""
    rt = NativeRuntime(base_dir=str(tmp_path))

    # host-side listener bound to the veth address must be reachable;
    # 1.1.1.1 must not (no route at all, fails fast)
    probe = (
        "import socket, sys\n"
        "host = sys.argv[1]\n"
        "s = socket.socket(); s.settimeout(3)\n"
        "try:\n"
        "    s.connect((host, int(sys.argv[2]))); print('CONNECT-OK')\n"
        "except OSError as e: print('CONNECT-FAIL', type(e).__name__)\n"
    )
    server = await asyncio.start_server(
        lambda r, w: w.close(), "0.0.0.0", 0)
    port = server.sockets[0].getsockname()[1]
    try:
        spec = _spec("nat-net", ["/bin/sh", "-c", (
            f"{sys.executable} -c \"{probe}\" $TPU9_HOST_IP {port}; "
            f"{sys.executable} -c \"{probe}\" 1.1.1.1 80")])
        code, lines = await _run_and_wait(rt, spec)
        assert code == 0, lines
        assert "CONNECT-OK" in lines, lines          # host reachable
        assert any("CONNECT-FAIL" in l for l in lines), lines  # egress dead
    finally:
        server.close()
        await rt.cleanup("nat-net")


async def test_port_proxy_round_trip(tmp_path):
    rt = NativeRuntime(base_dir=str(tmp_path))
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    srv = (
        "import http.server, functools\n"
        "h = http.server.SimpleHTTPRequestHandler\n"
        f"http.server.HTTPServer(('0.0.0.0', {port}), h).serve_forever()\n"
    )
    spec = _spec("nat-proxy", [sys.executable, "-c", srv],
                 ports={port: port})
    await rt.run(spec, log_cb=lambda l, s: None)
    try:
        import aiohttp
        ok = False
        async with aiohttp.ClientSession() as session:
            for _ in range(60):
                try:
                    async with session.get(
                            f"http://127.0.0.1:{port}/") as resp:
                        ok = resp.status == 200
                        break
                except aiohttp.ClientError:
                    await asyncio.sleep(0.25)
        assert ok, "proxied HTTP request never succeeded"
    finally:
        await rt.kill("nat-proxy", 9)
        await rt.wait("nat-proxy")
        await rt.cleanup("nat-proxy")


async def test_exec_in_namespaces(tmp_path):
    rt = NativeRuntime(base_dir=str(tmp_path))
    spec = _spec("nat-exec", ["/bin/sh", "-c", "sleep 30"])
    await rt.run(spec, log_cb=lambda l, s: None)
    try:
        await asyncio.sleep(0.5)
        code, out = await rt.exec("nat-exec", ["hostname"])
        assert code == 0
        assert out.strip() == "nat-exec"
    finally:
        await rt.kill("nat-exec", 9)
        await rt.wait("nat-exec")
        await rt.cleanup("nat-exec")


async def test_e2e_endpoint_under_native_runtime(tmp_path, monkeypatch):
    """The flagship check from VERDICT item 3: the serving path runs under
    real containment."""
    monkeypatch.setenv("TPU9_RUNTIME", "native")
    from tpu9.testing.localstack import LocalStack
    async with LocalStack() as stack:
        dep = await stack.deploy_echo_endpoint("native-echo")
        out = await stack.invoke(dep, {"x": 42}, timeout=120.0)
        assert out["echo"] == {"x": 42}
        running = await stack.running_containers(dep["stub_id"])
        assert len(running) == 1


async def test_privilege_containment_uid_drop(tmp_path):
    """VERDICT r03 #2: tenant code must not be root-with-full-caps inside
    the namespaces. With run_as_uid set: uid != 0, CapEff == 0, and the
    seccomp deny-list makes mount(2) fail."""
    rt = NativeRuntime(base_dir=str(tmp_path))
    wd = tmp_path / "work"
    wd.mkdir()
    spec = ContainerSpec(
        container_id="nat-priv1",
        entrypoint=["/bin/sh", "-c",
                    "id -u; grep CapEff /proc/self/status; "
                    "mount -t tmpfs none /tmp 2>/dev/null; echo mount_rc=$?; "
                    "echo probe > out.txt && echo write_ok"],
        workdir=str(wd), run_as_uid=65534, run_as_gid=65534)
    code, lines = await _run_and_wait(rt, spec)
    text = "\n".join(lines)
    assert code == 0, text
    assert "65534" in text
    assert "CapEff:\t0000000000000000" in text
    assert "mount_rc=0" not in text
    # the chown handoff keeps the workspace writable for the dropped uid
    assert "write_ok" in text


async def test_privilege_containment_root_still_seccomped(tmp_path):
    """Containers that keep root (TPU device holders, builds) still get
    no_new_privs + bounding-set drop + seccomp: mount/unshare fail even
    at uid 0."""
    rt = NativeRuntime(base_dir=str(tmp_path))
    spec = ContainerSpec(
        container_id="nat-priv2",
        entrypoint=["/bin/sh", "-c",
                    "id -u; mount -t tmpfs none /tmp 2>/dev/null; "
                    "echo mount_rc=$?; unshare -n true 2>/dev/null; "
                    "echo unshare_rc=$?; grep NoNewPrivs /proc/self/status"])
    code, lines = await _run_and_wait(rt, spec)
    text = "\n".join(lines)
    assert code == 0, text
    assert "mount_rc=0" not in text
    assert "unshare_rc=0" not in text
    assert "NoNewPrivs:\t1" in text
