import os

import pytest

from tpu9.cache import CacheClient, DiskStore
from tpu9.images import ImageBuilder, ImageManifest, ImagePuller, ImageSpec
from tpu9.images.manifest import materialize, snapshot_dir


def test_spec_id_deterministic():
    a = ImageSpec(python_packages=["jax", "flax"], commands=["echo hi"])
    b = ImageSpec(python_packages=["jax", "flax"], commands=["echo hi"])
    c = ImageSpec(python_packages=["jax"])
    assert a.image_id == b.image_id != c.image_id


def test_snapshot_and_materialize_roundtrip(tmp_path):
    src = tmp_path / "src"
    (src / "sub").mkdir(parents=True)
    (src / "a.txt").write_bytes(b"A" * 10)
    big = os.urandom(3 * 1024 * 1024)
    (src / "sub" / "big.bin").write_bytes(big)
    os.chmod(src / "a.txt", 0o640)
    os.symlink("a.txt", src / "link.txt")

    chunks: dict[str, bytes] = {}
    manifest = snapshot_dir(str(src), chunk_bytes=1 << 20,
                            put_chunk=lambda d, h: chunks.__setitem__(h, d))
    assert manifest.total_bytes == 10 + len(big)
    big_entry = next(f for f in manifest.files if f.path.endswith("big.bin"))
    assert len(big_entry.chunks) == 3
    link = next(f for f in manifest.files if f.path == "link.txt")
    assert link.link_target == "a.txt"

    dest = tmp_path / "dest"
    materialize(manifest, str(dest), chunks.get)
    assert (dest / "a.txt").read_bytes() == b"A" * 10
    assert (dest / "sub" / "big.bin").read_bytes() == big
    assert oct((dest / "a.txt").stat().st_mode & 0o777) == "0o640"
    assert os.readlink(dest / "link.txt") == "a.txt"

    # manifest json roundtrip
    back = ImageManifest.from_json(manifest.to_json())
    assert back.manifest_hash == manifest.manifest_hash


async def test_builder_commands_and_dedupe(tmp_path):
    builder = ImageBuilder(str(tmp_path / "registry"))
    spec = ImageSpec(commands=["mkdir -p env && echo marker > env/file.txt"])
    logs = []
    m1 = await builder.build(spec, log_cb=logs.append)
    assert builder.has_image(spec.image_id)
    assert any("file.txt" in f.path for f in m1.files)
    # second build returns cached manifest without running commands
    m2 = await builder.build(spec)
    assert m2.manifest_hash == m1.manifest_hash


async def test_builder_failure_surfaces(tmp_path):
    from tpu9.images.builder import BuildError
    builder = ImageBuilder(str(tmp_path / "registry"))
    spec = ImageSpec(commands=["exit 3"])
    with pytest.raises(BuildError):
        await builder.build(spec)
    assert not builder.has_image(spec.image_id)


async def test_puller_end_to_end(tmp_path):
    builder = ImageBuilder(str(tmp_path / "registry"))
    spec = ImageSpec(commands=["mkdir -p env && echo data > env/x.txt"],
                     env={"IMGVAR": "1"})
    manifest = await builder.build(spec)

    store = DiskStore(str(tmp_path / "cache"))

    async def peers():
        return []

    async def source(digest):
        return builder.read_chunk(digest)

    client = CacheClient(store, peers, source=source)
    puller = ImagePuller(client, str(tmp_path / "bundles"))
    bundle = await puller.pull(spec.image_id, manifest=manifest)
    assert os.path.exists(os.path.join(bundle, "env", "x.txt"))
    assert os.path.exists(os.path.join(bundle, ".tpu9-env.json"))
    # second pull is a no-op fast path
    bundle2 = await puller.pull(spec.image_id, manifest=manifest)
    assert bundle2 == bundle
    await client.close()


# ---------------------------------------------------------------------------
# lazy materialization (VERDICT r03 #3: containers start while images stream)
# ---------------------------------------------------------------------------

def _make_cache(tmp_path, builder):
    store = DiskStore(str(tmp_path / "cache"))

    async def peers():
        return []

    async def source(digest):
        return builder.read_chunk(digest)

    return CacheClient(store, peers, source=source)


async def test_lazy_pull_skeleton_then_fill(tmp_path):
    import asyncio
    import hashlib

    from tpu9.images.builder import ImageBuilder

    builder = ImageBuilder(str(tmp_path / "registry"))
    spec = ImageSpec(commands=[
        "mkdir -p env && for i in 1 2 3 4; do "
        "head -c 2097152 /dev/urandom > env/f$i.bin; done "
        "&& echo small > env/tiny.txt && ln -s tiny.txt env/link.txt"])
    manifest = await builder.build(spec)
    client = _make_cache(tmp_path, builder)
    puller = ImagePuller(client, str(tmp_path / "bundles"),
                         lazy_threshold=1)   # force lazy

    bundle = await puller.pull(spec.image_id, manifest=manifest)
    fill = puller.active_fill(spec.image_id)

    # skeleton contract: stat-correct tree before the bytes arrive
    f1 = os.path.join(bundle, "env", "f1.bin")
    assert os.path.getsize(f1) == 2097152
    assert os.readlink(os.path.join(bundle, "env", "link.txt")) == "tiny.txt"
    assert os.path.exists(os.path.join(bundle, ".tpu9-env.json"))
    assert os.path.exists(os.path.join(bundle, ".tpu9-lazy"))

    # fault one file on demand through the socket protocol
    if fill is not None and not fill.complete:
        reader, writer = await asyncio.open_unix_connection(
            puller.lazy_sock(spec.image_id))
        writer.write(f"REQ {f1}\n".encode())
        await writer.drain()
        assert (await reader.readline()).strip() == b"OK"
        writer.close()
        entry = next(e for e in manifest.files if e.path == "env/f1.bin")
        got = hashlib.sha256(open(f1, "rb").read()).hexdigest()
        want = hashlib.sha256(
            b"".join(builder.read_chunk(c) for c in entry.chunks)).hexdigest()
        assert got == want

    # background fill completes and publishes the marker
    if fill is not None:
        await asyncio.wait_for(fill.wait(), 60)
    assert os.path.exists(os.path.join(bundle, ".tpu9-complete"))
    assert not os.path.exists(os.path.join(bundle, ".tpu9-lazy"))
    for e in manifest.files:
        if e.link_target:
            continue
        data = open(os.path.join(bundle, e.path), "rb").read()
        want = b"".join(builder.read_chunk(c) for c in e.chunks)
        assert data == want, f"content mismatch for {e.path}"
    await puller.close()
    await client.close()


async def test_lazy_pull_restarts_after_crash(tmp_path):
    """No completion marker on disk → the next pull must re-skeleton and
    refill rather than trusting half-written placeholders."""
    from tpu9.images.builder import ImageBuilder

    builder = ImageBuilder(str(tmp_path / "registry"))
    spec = ImageSpec(commands=["mkdir -p env && echo hello > env/a.txt"])
    manifest = await builder.build(spec)
    client = _make_cache(tmp_path, builder)

    # simulate a crashed fill: placeholders present, no marker
    dest = os.path.join(str(tmp_path / "bundles"), spec.image_id)
    os.makedirs(os.path.join(dest, "env"), exist_ok=True)
    with open(os.path.join(dest, "env", "a.txt"), "wb") as f:
        f.truncate(6)

    puller = ImagePuller(client, str(tmp_path / "bundles"), lazy_threshold=1)
    bundle = await puller.pull(spec.image_id, manifest=manifest)
    fill = puller.active_fill(spec.image_id)
    if fill is not None:
        import asyncio
        await asyncio.wait_for(fill.wait(), 30)
    assert open(os.path.join(bundle, "env", "a.txt")).read() == "hello\n"
    await puller.close()
    await client.close()


async def test_small_image_stays_eager(tmp_path):
    from tpu9.images.builder import ImageBuilder

    builder = ImageBuilder(str(tmp_path / "registry"))
    spec = ImageSpec(commands=["mkdir -p env && echo tiny > env/t.txt"])
    manifest = await builder.build(spec)
    client = _make_cache(tmp_path, builder)
    puller = ImagePuller(client, str(tmp_path / "bundles"))  # default 64 MB
    bundle = await puller.pull(spec.image_id, manifest=manifest)
    assert puller.active_fill(spec.image_id) is None
    assert os.path.exists(os.path.join(bundle, ".tpu9-complete"))
    await client.close()


def test_manifest_path_traversal_rejected(tmp_path):
    """Advisor r04: manifests can arrive over the wire and every writer
    (materialize / lazy skeleton / lazy fill) runs as root — entries that
    escape the bundle via '..' or a symlinked parent must be refused."""
    from tpu9.images.manifest import FileEntry, ImageManifest, safe_join

    dest = tmp_path / "bundle"
    dest.mkdir()
    for bad in ("../evil", "/abs/evil", "a/../../evil", ""):
        with pytest.raises(ValueError):
            safe_join(str(dest), bad)
    assert safe_join(str(dest), "ok/fine.txt").startswith(str(dest))

    # symlinked parent: entry 'out' links outside dest; 'out/x' must not
    # write through it
    outside = tmp_path / "outside"
    outside.mkdir()
    m = ImageManifest(image_id="evil", kind="env", files=[
        FileEntry(path="out", mode=0o777, size=0,
                  link_target=str(outside)),
        FileEntry(path="out/x", mode=0o644, size=4, chunks=["d1"]),
    ])
    with pytest.raises(ValueError):
        materialize(m, str(dest), {"d1": b"evil"}.get)
    assert not (outside / "x").exists()


def test_safe_join_second_pass_with_symlinks(tmp_path):
    """Review regression: safe_join must NOT resolve through the final
    component — an absolute-target venv-style symlink ('bin/python' ->
    /usr/bin/python3) exists after the first pass, and resume
    (_ensure_tree / re-materialize) must see the LINK path, not its
    resolved target, or every second pass over the bundle fails."""
    from tpu9.images.manifest import FileEntry, ImageManifest, safe_join

    dest = tmp_path / "bundle"
    m = ImageManifest(image_id="venv", kind="env", files=[
        FileEntry(path="bin/python", mode=0o777, size=0,
                  link_target="/usr/bin/python3"),
        FileEntry(path="link.cfg", mode=0o777, size=0,
                  link_target="real.cfg"),
        FileEntry(path="real.cfg", mode=0o644, size=2, chunks=["c1"]),
    ])
    chunks = {"c1": b"ok"}
    materialize(m, str(dest), chunks.get)
    # second pass over the same tree: must not raise and must address the
    # link itself
    materialize(m, str(dest), chunks.get)
    assert os.readlink(dest / "bin" / "python") == "/usr/bin/python3"
    assert safe_join(str(dest), "link.cfg").endswith("/link.cfg")
    assert (dest / "real.cfg").read_bytes() == b"ok"


def test_symlink_then_file_entry_cannot_write_through(tmp_path):
    """Round-5 review (high): a hostile manifest pairing a symlink entry
    with a SAME-PATH file entry must not write (or chmod) through the
    link as root — O_NOFOLLOW writers refuse the swapped-in link."""
    from tpu9.images.manifest import FileEntry, ImageManifest

    victim = tmp_path / "victim.txt"
    victim.write_text("precious")
    dest = tmp_path / "bundle"
    m = ImageManifest(image_id="evil2", kind="env", files=[
        FileEntry(path="x", mode=0o777, size=0,
                  link_target=str(victim)),
        FileEntry(path="x", mode=0o666, size=4, chunks=["d1"]),
    ])
    try:
        materialize(m, str(dest), {"d1": b"evil"}.get)
    except OSError:
        pass                              # refusing loudly is acceptable
    assert victim.read_text() == "precious"
    assert oct(victim.stat().st_mode & 0o777) != "0o666"

    # the lazy skeleton writer takes the same O_NOFOLLOW path
    from tpu9.images.lazy import LazyFill

    fill = LazyFill(m, str(tmp_path / "bundle2"), None,
                    str(tmp_path / "fill.sock"))
    try:
        fill._write_skeleton()
    except OSError:
        pass                              # refusing loudly is acceptable
    assert victim.read_text() == "precious"
    assert oct(victim.stat().st_mode & 0o777) != "0o666"
