import os

import pytest

from tpu9.cache import CacheClient, DiskStore
from tpu9.images import ImageBuilder, ImageManifest, ImagePuller, ImageSpec
from tpu9.images.manifest import materialize, snapshot_dir


def test_spec_id_deterministic():
    a = ImageSpec(python_packages=["jax", "flax"], commands=["echo hi"])
    b = ImageSpec(python_packages=["jax", "flax"], commands=["echo hi"])
    c = ImageSpec(python_packages=["jax"])
    assert a.image_id == b.image_id != c.image_id


def test_snapshot_and_materialize_roundtrip(tmp_path):
    src = tmp_path / "src"
    (src / "sub").mkdir(parents=True)
    (src / "a.txt").write_bytes(b"A" * 10)
    big = os.urandom(3 * 1024 * 1024)
    (src / "sub" / "big.bin").write_bytes(big)
    os.chmod(src / "a.txt", 0o640)
    os.symlink("a.txt", src / "link.txt")

    chunks: dict[str, bytes] = {}
    manifest = snapshot_dir(str(src), chunk_bytes=1 << 20,
                            put_chunk=lambda d, h: chunks.__setitem__(h, d))
    assert manifest.total_bytes == 10 + len(big)
    big_entry = next(f for f in manifest.files if f.path.endswith("big.bin"))
    assert len(big_entry.chunks) == 3
    link = next(f for f in manifest.files if f.path == "link.txt")
    assert link.link_target == "a.txt"

    dest = tmp_path / "dest"
    materialize(manifest, str(dest), chunks.get)
    assert (dest / "a.txt").read_bytes() == b"A" * 10
    assert (dest / "sub" / "big.bin").read_bytes() == big
    assert oct((dest / "a.txt").stat().st_mode & 0o777) == "0o640"
    assert os.readlink(dest / "link.txt") == "a.txt"

    # manifest json roundtrip
    back = ImageManifest.from_json(manifest.to_json())
    assert back.manifest_hash == manifest.manifest_hash


async def test_builder_commands_and_dedupe(tmp_path):
    builder = ImageBuilder(str(tmp_path / "registry"))
    spec = ImageSpec(commands=["mkdir -p env && echo marker > env/file.txt"])
    logs = []
    m1 = await builder.build(spec, log_cb=logs.append)
    assert builder.has_image(spec.image_id)
    assert any("file.txt" in f.path for f in m1.files)
    # second build returns cached manifest without running commands
    m2 = await builder.build(spec)
    assert m2.manifest_hash == m1.manifest_hash


async def test_builder_failure_surfaces(tmp_path):
    from tpu9.images.builder import BuildError
    builder = ImageBuilder(str(tmp_path / "registry"))
    spec = ImageSpec(commands=["exit 3"])
    with pytest.raises(BuildError):
        await builder.build(spec)
    assert not builder.has_image(spec.image_id)


async def test_puller_end_to_end(tmp_path):
    builder = ImageBuilder(str(tmp_path / "registry"))
    spec = ImageSpec(commands=["mkdir -p env && echo data > env/x.txt"],
                     env={"IMGVAR": "1"})
    manifest = await builder.build(spec)

    store = DiskStore(str(tmp_path / "cache"))

    async def peers():
        return []

    async def source(digest):
        return builder.read_chunk(digest)

    client = CacheClient(store, peers, source=source)
    puller = ImagePuller(client, str(tmp_path / "bundles"))
    bundle = await puller.pull(spec.image_id, manifest=manifest)
    assert os.path.exists(os.path.join(bundle, "env", "x.txt"))
    assert os.path.exists(os.path.join(bundle, ".tpu9-env.json"))
    # second pull is a no-op fast path
    bundle2 = await puller.pull(spec.image_id, manifest=manifest)
    assert bundle2 == bundle
    await client.close()
