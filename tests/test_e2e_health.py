"""E2E: replica health plane (ISSUE 14 acceptance) — an induced serve-loop
stall on one of two replicas flips its heartbeated health to `stalled`
within the beat budget, the router stops dispatching to it (measured
dispatch counts), a post-mortem black box with flight windows + HBM
breakdown is retrievable at /api/v1/postmortem, and recovery restores
routing. An induced engine crash during generation also leaves a
post-mortem record.

The stall is a real gray failure: the engine's serve loop spins without
progress while the RUNNER keeps heartbeating — exactly the case the
fleet's staleness aging can never catch."""

import asyncio
import os
import time

import aiohttp
import pytest

from tpu9.testing.localstack import LocalStack

pytestmark = pytest.mark.e2e

# FaultyEngine: dispatch spins (stall) or raises (crash) while a per-
# replica flag file exists — the serve LOOP wedges, the event loop (and
# so the pressure heartbeat) stays alive: a gray failure on demand.
FAULTY_APP = """
import os, time

def load_engine():
    from dataclasses import replace
    import jax
    from tpu9.models import init_decoder
    from tpu9.models.llama import LLAMA_PRESETS
    from tpu9.serving import EngineConfig, InferenceEngine

    flag_dir = os.environ.get("TPU9_TEST_FLAG_DIR", "")
    cid = os.environ.get("TPU9_CONTAINER_ID", "")

    class FaultyEngine(InferenceEngine):
        def _dispatch_window(self):
            if flag_dir and os.path.exists(
                    os.path.join(flag_dir, "crash-" + cid)):
                raise RuntimeError("induced crash for postmortem test")
            if flag_dir and os.path.exists(
                    os.path.join(flag_dir, "stall-" + cid)):
                time.sleep(0.05)   # cheap spin; the loop's sleep(0) still
                return None        # yields, so heartbeats keep flowing
            return super()._dispatch_window()

    cfg = replace(LLAMA_PRESETS["llama-tiny"])
    params = init_decoder(jax.random.PRNGKey(0), cfg)
    return FaultyEngine(params, cfg,
                        EngineConfig(max_batch=2, max_seq_len=256,
                                     prefill_buckets=(16, 64),
                                     kv_block_size=16))
"""


async def _engine_stats(stack, cid: str) -> dict:
    return await stack.gateway.store.hgetall(f"llm:pressure:{cid}") or {}


async def _wait_health(stack, cid: str, want: str, timeout: float = 25.0):
    deadline = time.monotonic() + timeout
    last = {}
    while time.monotonic() < deadline:
        last = await _engine_stats(stack, cid)
        if str(last.get("health", "")) == want:
            return last
        await asyncio.sleep(0.2)
    raise AssertionError(
        f"replica {cid} never reported health={want}; last beat: "
        f"{ {k: last.get(k) for k in ('health', 'health_reason', 'queued', 'active_streams', 'last_progress_age_s')} }")


async def _direct_generate(address: str, max_new: int, timeout: float):
    """POST straight to one replica's runner (bypassing the router) —
    how the test pins work onto the victim."""
    async with aiohttp.ClientSession() as sess:
        async with sess.post(
                f"http://{address}/",
                json={"tokens": [3, 1, 4, 1, 5], "max_new_tokens": max_new},
                timeout=aiohttp.ClientTimeout(total=timeout)) as resp:
            return resp.status, await resp.json()


async def test_stall_flips_health_ejects_replica_and_recovers(tmp_path):
    flag_dir = str(tmp_path)
    async with LocalStack() as stack:
        dep = await stack.deploy_endpoint(
            "healthllm", {"app.py": FAULTY_APP}, "app:load_engine",
            config_extra={
                "timeout_s": 240.0,
                "concurrent_requests": 2,
                "extra": {"runner": "llm"},
                "env": {"TPU9_TEST_FLAG_DIR": flag_dir,
                        # tight beat budget so the e2e stays fast: beats
                        # at 0.5 s, stalled after 1.5 s of frozen
                        # watermark with work waiting (3 beats)
                        "TPU9_PRESSURE_INTERVAL_S": "0.5",
                        "TPU9_HEALTH_STALL_S": "1.5",
                        "TPU9_HEALTH_DEGRADED_S": "0.75"},
                "autoscaler": {"max_containers": 2,
                               "min_containers": 2}})
        await stack.wait_running(dep["stub_id"], 2, timeout=120.0)
        # warm both replicas directly (compiles + first flight records)
        states = await stack.running_containers(dep["stub_id"])
        cids = sorted(s.container_id for s in states)
        addr = {s.container_id: s.address for s in states}
        for cid in cids:
            deadline = time.monotonic() + 120.0
            while True:
                try:
                    status, out = await _direct_generate(addr[cid], 4, 120)
                    assert status == 200, out
                    break
                except aiohttp.ClientError:
                    assert time.monotonic() < deadline, f"{cid} never up"
                    await asyncio.sleep(0.5)
        victim, healthy = cids[0], cids[1]
        router = stack.gateway.fleet_router
        assert router is not None

        # ---- induce the gray failure -----------------------------------
        open(os.path.join(flag_dir, f"stall-{victim}"), "w").close()
        # pin work on the victim: this request admits, then its decode
        # dispatch spins forever — it completes only after recovery
        hung = asyncio.create_task(
            _direct_generate(addr[victim], 64, timeout=180.0))
        beat = await _wait_health(stack, victim, "stalled")
        assert beat.get("health_reason") == "no_progress_with_queued_work"
        # the runner was STILL heartbeating while wedged (gray, not dead):
        # the beat that carried the verdict is fresh
        assert float(beat.get("ts", 0)) > time.time() - 5.0

        # the gateway's observer folded the verdict into routing
        deadline = time.monotonic() + 10.0
        while (not router.admission.is_stalled(victim)
               and time.monotonic() < deadline):
            await asyncio.sleep(0.1)
        assert router.admission.is_stalled(victim)
        assert not router.admission.is_draining(victim)

        # ---- measured dispatch counts: router routes around it ---------
        dispatches = []
        orig_launch = router._launch

        def spy_launch(st, req, prefer, replica, affinity_hit=None,
                       evidence=None):
            dispatches.append(replica)
            return orig_launch(st, req, prefer, replica,
                               affinity_hit=affinity_hit,
                               evidence=evidence)

        router._launch = spy_launch
        try:
            results = await asyncio.gather(*[
                stack.api("POST", "/endpoint/healthllm",
                          json_body={"tokens": [9, 9, 9, i + 1],
                                     "max_new_tokens": 4},
                          timeout=120)
                for i in range(6)])
        finally:
            router._launch = orig_launch
        assert all(status == 200 for status, _ in results), results
        assert len(dispatches) == 6
        assert victim not in dispatches, dispatches
        assert healthy in dispatches

        # ---- post-mortem black box at /api/v1/postmortem ---------------
        deadline = time.monotonic() + 15.0
        records = []
        while time.monotonic() < deadline:
            status, pm = await stack.api(
                "GET", f"/api/v1/postmortem?container_id={victim}")
            assert status == 200, pm
            records = pm.get("replicas", {}).get(victim, [])
            if records:
                break
            await asyncio.sleep(0.3)
        assert records, "watchdog trip never shipped a post-mortem"
        rec = records[-1]
        assert rec["reason"] == "watchdog_stall"
        assert rec["container_id"] == victim
        assert rec["flight"], "black box carries no flight windows"
        assert {"hbm_used_gb_per_chip", "hbm_predicted_gb_per_chip"} <= \
            set(rec["hbm"])
        # the scheduler snapshot shows the wedged work
        assert rec["scheduler"]["active_slots"] or \
            rec["scheduler"]["queued"] > 0 or rec["stats"].get(
                "active_streams", 0) > 0

        # ---- recovery: health returns to ok, routing restored ----------
        os.unlink(os.path.join(flag_dir, f"stall-{victim}"))
        status, out = await hung          # the wedged request completes
        assert status == 200 and len(out["tokens"]) == 64, out
        await _wait_health(stack, victim, "ok")
        deadline = time.monotonic() + 10.0
        while (router.admission.is_stalled(victim)
               and time.monotonic() < deadline):
            await asyncio.sleep(0.1)
        assert not router.admission.is_stalled(victim)
        running = {s.container_id
                   for s in await router._running(dep["stub_id"])}
        assert victim in running
        # and traffic genuinely flows to it again
        status, out = await _direct_generate(addr[victim], 4, 60)
        assert status == 200 and len(out["tokens"]) == 4


async def test_engine_crash_during_generation_leaves_postmortem(tmp_path):
    flag_dir = str(tmp_path)
    async with LocalStack() as stack:
        dep = await stack.deploy_endpoint(
            "crashllm", {"app.py": FAULTY_APP}, "app:load_engine",
            config_extra={
                "timeout_s": 240.0,
                "extra": {"runner": "llm"},
                "env": {"TPU9_TEST_FLAG_DIR": flag_dir,
                        "TPU9_PRESSURE_INTERVAL_S": "0.5"},
                "autoscaler": {"max_containers": 1,
                               "min_containers": 1}})
        await stack.wait_running(dep["stub_id"], 1, timeout=120.0)
        status, warm = await stack.api(
            "POST", "/endpoint/crashllm",
            json_body={"tokens": [5, 3, 9], "max_new_tokens": 4},
            timeout=240)
        assert status == 200, warm
        (state,) = await stack.running_containers(dep["stub_id"])
        cid = state.container_id

        # crash the engine mid-generation: the next dispatch raises
        open(os.path.join(flag_dir, f"crash-{cid}"), "w").close()
        status, out = await _direct_generate(state.address, 16, 60)
        assert status != 200, out       # the request saw the failure

        deadline = time.monotonic() + 20.0
        records = []
        while time.monotonic() < deadline:
            status, pm = await stack.api(
                "GET", f"/api/v1/postmortem?container_id={cid}")
            assert status == 200, pm
            records = pm.get("replicas", {}).get(cid, [])
            if records:
                break
            await asyncio.sleep(0.3)
        assert records, "engine crash never shipped a post-mortem"
        rec = records[-1]
        assert rec["reason"] == "engine_crash"
        assert "induced crash" in rec["exception"]
        assert rec["flight"], "black box carries no flight windows"
        assert "hbm_used_gb_per_chip" in rec["hbm"]
        # the dead engine also reads as stalled on the health plane
        beat = await _wait_health(stack, cid, "stalled", timeout=10.0)
        assert beat.get("health_reason") == "engine_dead"
