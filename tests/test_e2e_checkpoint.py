"""E2E: checkpoint on readiness → restore on next cold start.

The handler simulates expensive init (writes a build artifact). First
container builds + checkpoints; after scale-to-zero the next container must
restore the snapshot (artifact present without rebuilding, TPU9_RESTORED
set)."""

import asyncio

import pytest

from tpu9.testing.localstack import LocalStack

pytestmark = pytest.mark.e2e

EXPENSIVE = """
import os, time, pathlib

ART = pathlib.Path("model_artifact.bin")

def _build():
    # "expensive" init: only ever done when no checkpoint exists
    time.sleep(0.5)
    ART.write_bytes(b"weights-v1")
    return ART.read_bytes()

if ART.exists():
    WEIGHTS = ART.read_bytes()
    BUILT = False
else:
    WEIGHTS = _build()
    BUILT = True

def handler(**kw):
    return {"weights": WEIGHTS.decode(), "built": BUILT,
            "restored": os.environ.get("TPU9_RESTORED", "0")}
"""


async def test_checkpoint_restore_cycle():
    async with LocalStack() as stack:
        dep = await stack.deploy_endpoint(
            "ckpt", {"app.py": EXPENSIVE}, "app:handler",
            config_extra={"checkpoint": {"enabled": True}})
        out1 = await stack.invoke(dep, {})
        assert out1["built"] is True and out1["restored"] == "0"

        # wait for the readiness checkpoint to land
        for _ in range(100):
            row = await stack.backend.latest_checkpoint(dep["stub_id"])
            if row:
                break
            await asyncio.sleep(0.1)
        assert row, "checkpoint never became available"

        await stack.scale_to_zero(dep)
        out2 = await stack.invoke(dep, {})
        # restored container found the artifact: no rebuild
        assert out2["restored"] == "1"
        assert out2["built"] is False
        assert out2["weights"] == "weights-v1"


async def test_checkpoint_restore_fallback_to_cold_boot():
    async with LocalStack() as stack:
        dep = await stack.deploy_endpoint(
            "ckpt2", {"app.py": EXPENSIVE}, "app:handler",
            config_extra={"checkpoint": {"enabled": True}})
        await stack.invoke(dep, {})
        for _ in range(100):
            row = await stack.backend.latest_checkpoint(dep["stub_id"])
            if row:
                break
            await asyncio.sleep(0.1)
        assert row
        # poison the manifest so restore fails → cold boot must still work
        import os
        os.unlink(stack._ckpt_path(row["checkpoint_id"]))
        await stack.scale_to_zero(dep)
        out = await stack.invoke(dep, {})
        assert out["built"] is True     # rebuilt from scratch, no crash


async def test_gateway_ckpt_rpc_surface():
    """The worker-token RPC endpoints the standalone worker's
    CheckpointManager rides (record → manifest put → status → lookup):
    the same wiring `tpu9 worker` uses against a remote gateway."""
    import aiohttp

    from tpu9.images.manifest import ImageManifest

    async with LocalStack() as stack:
        base = stack.base_url
        wtok = {"Authorization": f"Bearer {stack.gateway.worker_token}"}
        utok = {"Authorization": f"Bearer {stack.gateway.default_token}"}
        manifest = ImageManifest(image_id="", files=[],
                                 chunk_bytes=4 << 20).to_json()
        async with aiohttp.ClientSession() as s:
            # record requires the WORKER token — a user token is forbidden
            async with s.post(f"{base}/rpc/internal/ckpt/ws/stub-1/ct-1",
                              headers=utok) as r:
                assert r.status == 403
            async with s.post(f"{base}/rpc/internal/ckpt/ws/stub-1/ct-1",
                              headers=wtok) as r:
                assert r.status == 200
                ckpt_id = (await r.json())["checkpoint_id"]

            # a pending checkpoint must NOT be handed to the scheduler
            assert await stack.backend.latest_checkpoint("stub-1") is None

            async with s.post(
                    f"{base}/rpc/internal/ckpt/manifest/{ckpt_id}",
                    headers=wtok, data="not json") as r:
                assert r.status == 400
            async with s.post(
                    f"{base}/rpc/internal/ckpt/manifest/{ckpt_id}",
                    headers=wtok, data=manifest) as r:
                assert r.status == 200
            async with s.post(
                    f"{base}/rpc/internal/ckpt/status/{ckpt_id}",
                    headers=wtok,
                    json={"status": "available", "size": 123}) as r:
                assert r.status == 200

            row = await stack.backend.latest_checkpoint("stub-1")
            assert row and row["checkpoint_id"] == ckpt_id
            assert row["size"] == 123

            async with s.get(
                    f"{base}/rpc/internal/ckpt/manifest/{ckpt_id}",
                    headers=wtok) as r:
                assert r.status == 200
                assert ImageManifest.from_json(await r.text()).chunk_bytes \
                    == 4 << 20
            async with s.get(
                    f"{base}/rpc/internal/ckpt/manifest/ckpt-missing",
                    headers=wtok) as r:
                assert r.status == 404
            # path traversal in the id must be rejected, not resolved
            async with s.get(
                    f"{base}/rpc/internal/ckpt/manifest/..%2F..%2Fetc",
                    headers=wtok) as r:
                assert r.status in (400, 404)


# ---------------------------------------------------------------------------
# ISSUE 13: streamed restore emits the cold-start evidence layer end to end
# ---------------------------------------------------------------------------

STREAMED = """
import os
import numpy as np
from tpu9.runner import ckpt

def _init():
    rng = np.random.default_rng(7)
    return {"w": [rng.standard_normal(4096).astype(np.float32)
                  for _ in range(4)]}

if ckpt.is_restored():
    PARAMS = ckpt.load_params()
    BUILT = False
else:
    PARAMS = _init()
    ckpt.save_params(PARAMS)
    BUILT = True

def handler(**kw):
    return {"built": BUILT,
            "restored": os.environ.get("TPU9_RESTORED", "0"),
            "checksum": float(sum(np.asarray(a).sum()
                                  for a in PARAMS["w"]))}
"""


async def test_streamed_restore_trace_and_coldstart_evidence():
    """A cold start that streams `.tpu9w` weights must light up every layer
    of the evidence plane: one gapless restore span tree at /api/v1/traces
    (worker.cold_start ⊃ restore.request ⊃ restore.fetch ∥ device_put with
    tier/bytes attrs, wall-anchor containment), a decomposition record at
    /api/v1/coldstart, and cache.*/weightpool.* timeline series."""
    stack = LocalStack()
    # tighten the evidence cadences so the test doesn't wait out defaults
    stack.cfg.worker.heartbeat_interval_s = 0.2
    stack.cfg.slo.sample_interval_s = 0.2
    async with stack:
        dep = await stack.deploy_endpoint(
            "ckstream", {"app.py": STREAMED}, "app:handler",
            config_extra={"checkpoint": {"enabled": True}})
        out1 = await stack.invoke(dep, {}, timeout=180.0)
        assert out1["built"] is True and out1["restored"] == "0"
        for _ in range(200):
            row = await stack.backend.latest_checkpoint(dep["stub_id"])
            if row:
                break
            await asyncio.sleep(0.1)
        assert row, "checkpoint never became available"

        await stack.scale_to_zero(dep)
        out2 = await stack.invoke(dep, {}, timeout=180.0)
        assert out2["restored"] == "1" and out2["built"] is False
        assert abs(out2["checksum"] - out1["checksum"]) < 1e-3

        # the restore actually STREAMED a weight group (not classic-only)
        metrics = next(
            (w.checkpoints.last_restore_metrics for w in stack.workers
             if w.checkpoints is not None
             and w.checkpoints.last_restore_metrics.get("weight_groups")),
            None)
        assert metrics, "no worker recorded a streamed restore"
        assert metrics["weight_stream_bytes"] > 0
        assert metrics["tiers"]["local"] + metrics["tiers"]["peer"] \
            + metrics["tiers"]["source"] + metrics["tiers"]["pool"] > 0

        # ---- /api/v1/traces: the gapless restore span tree ----
        status, data = await stack.api("GET", "/api/v1/traces?limit=3000")
        assert status == 200
        spans = data["spans"]
        reqs = [s for s in spans if s["name"] == "restore.request"]
        assert reqs, f"no restore.request span in {len(spans)} spans"
        req = reqs[-1]
        tree = [s for s in spans if s["traceId"] == req["traceId"]]
        names = {s["name"] for s in tree}
        assert "worker.cold_start" in names
        assert "restore.fetch" in names
        assert "restore.device_put" in names
        root = [s for s in tree if s["name"] == "worker.cold_start"][0]
        assert req["parentSpanId"] == root["spanId"]
        slack = 50e6                     # 50 ms, the PR-8 e2e convention
        for sp in tree:
            if sp["name"] not in ("restore.fetch", "restore.device_put"):
                continue
            assert sp["parentSpanId"] == req["spanId"]
            assert sp["startTimeUnixNano"] >= \
                req["startTimeUnixNano"] - slack
            assert sp["endTimeUnixNano"] <= req["endTimeUnixNano"] + slack
            assert sp["attributes"]["workspace_id"], "tenancy stamp missing"
        fetch = [s for s in tree if s["name"] == "restore.fetch"][0]
        assert fetch["attributes"]["bytes"] > 0
        assert fetch["attributes"]["tier"] in ("local", "peer", "source")

        # traced fetch/put intervals agree with the worker's measured
        # record (the same ≤10% cross-check the bench gates)
        from tpu9.observability import coldstart as cs
        traced = cs.decompose_spans(tree)
        want_fetch = sum(g["fetch_iv"][1] - g["fetch_iv"][0]
                         for g in metrics["groups_detail"]
                         if g.get("fetch_iv"))
        assert cs.agreement(traced["fetch_s"], want_fetch) < 0.10, \
            (traced, want_fetch)

        # ---- /api/v1/coldstart: the per-replica decomposition record ----
        rec = None
        for _ in range(150):
            status, cold = await stack.api("GET", "/api/v1/coldstart")
            assert status == 200
            for cid, r in cold.get("replicas", {}).items():
                if r.get("restore", {}).get("weight_groups"):
                    rec = r
            if rec:
                break
            await asyncio.sleep(0.1)
        assert rec, "coldstart record never shipped on the heartbeat"
        assert rec["stub_id"] == dep["stub_id"]
        assert rec["restore"]["weight_stream_bytes"] > 0
        assert "overlap_frac" in rec["restore"]
        assert "hedge" in rec["restore"]

        # ---- /api/v1/timeline: cache.* / weightpool.* series ----
        series = {}
        for _ in range(150):
            status, tl = await stack.api(
                "GET", "/api/v1/timeline?series=cache.*,weightpool.*")
            assert status == 200
            series = tl.get("series", {})
            if any(k.startswith("cache.") and v
                   for k, v in series.items()) \
                    and any(k.startswith("weightpool.")
                            for k in series):
                break
            await asyncio.sleep(0.1)
        assert any(k.startswith("cache.") and v
                   for k, v in series.items()), sorted(series)[:20]
        assert any(k.startswith("weightpool.") for k in series)
        # /api/v1/metrics carries the cache-plane snapshot section too
        status, m = await stack.api("GET", "/api/v1/metrics")
        assert status == 200 and m.get("cache"), "metrics cache section"
        wsnap = next(iter(m["cache"].values()))
        assert "weightpool" in wsnap and wsnap["weightpool"]["hits"] >= 0
