"""E2E: checkpoint on readiness → restore on next cold start.

The handler simulates expensive init (writes a build artifact). First
container builds + checkpoints; after scale-to-zero the next container must
restore the snapshot (artifact present without rebuilding, TPU9_RESTORED
set)."""

import asyncio

import pytest

from tpu9.testing.localstack import LocalStack

pytestmark = pytest.mark.e2e

EXPENSIVE = """
import os, time, pathlib

ART = pathlib.Path("model_artifact.bin")

def _build():
    # "expensive" init: only ever done when no checkpoint exists
    time.sleep(0.5)
    ART.write_bytes(b"weights-v1")
    return ART.read_bytes()

if ART.exists():
    WEIGHTS = ART.read_bytes()
    BUILT = False
else:
    WEIGHTS = _build()
    BUILT = True

def handler(**kw):
    return {"weights": WEIGHTS.decode(), "built": BUILT,
            "restored": os.environ.get("TPU9_RESTORED", "0")}
"""


async def test_checkpoint_restore_cycle():
    async with LocalStack() as stack:
        dep = await stack.deploy_endpoint(
            "ckpt", {"app.py": EXPENSIVE}, "app:handler",
            config_extra={"checkpoint": {"enabled": True}})
        out1 = await stack.invoke(dep, {})
        assert out1["built"] is True and out1["restored"] == "0"

        # wait for the readiness checkpoint to land
        for _ in range(100):
            row = await stack.backend.latest_checkpoint(dep["stub_id"])
            if row:
                break
            await asyncio.sleep(0.1)
        assert row, "checkpoint never became available"

        await stack.scale_to_zero(dep)
        out2 = await stack.invoke(dep, {})
        # restored container found the artifact: no rebuild
        assert out2["restored"] == "1"
        assert out2["built"] is False
        assert out2["weights"] == "weights-v1"


async def test_checkpoint_restore_fallback_to_cold_boot():
    async with LocalStack() as stack:
        dep = await stack.deploy_endpoint(
            "ckpt2", {"app.py": EXPENSIVE}, "app:handler",
            config_extra={"checkpoint": {"enabled": True}})
        await stack.invoke(dep, {})
        for _ in range(100):
            row = await stack.backend.latest_checkpoint(dep["stub_id"])
            if row:
                break
            await asyncio.sleep(0.1)
        assert row
        # poison the manifest so restore fails → cold boot must still work
        import os
        os.unlink(stack._ckpt_path(row["checkpoint_id"]))
        await stack.scale_to_zero(dep)
        out = await stack.invoke(dep, {})
        assert out["built"] is True     # rebuilt from scratch, no crash


async def test_gateway_ckpt_rpc_surface():
    """The worker-token RPC endpoints the standalone worker's
    CheckpointManager rides (record → manifest put → status → lookup):
    the same wiring `tpu9 worker` uses against a remote gateway."""
    import aiohttp

    from tpu9.images.manifest import ImageManifest

    async with LocalStack() as stack:
        base = stack.base_url
        wtok = {"Authorization": f"Bearer {stack.gateway.worker_token}"}
        utok = {"Authorization": f"Bearer {stack.gateway.default_token}"}
        manifest = ImageManifest(image_id="", files=[],
                                 chunk_bytes=4 << 20).to_json()
        async with aiohttp.ClientSession() as s:
            # record requires the WORKER token — a user token is forbidden
            async with s.post(f"{base}/rpc/internal/ckpt/ws/stub-1/ct-1",
                              headers=utok) as r:
                assert r.status == 403
            async with s.post(f"{base}/rpc/internal/ckpt/ws/stub-1/ct-1",
                              headers=wtok) as r:
                assert r.status == 200
                ckpt_id = (await r.json())["checkpoint_id"]

            # a pending checkpoint must NOT be handed to the scheduler
            assert await stack.backend.latest_checkpoint("stub-1") is None

            async with s.post(
                    f"{base}/rpc/internal/ckpt/manifest/{ckpt_id}",
                    headers=wtok, data="not json") as r:
                assert r.status == 400
            async with s.post(
                    f"{base}/rpc/internal/ckpt/manifest/{ckpt_id}",
                    headers=wtok, data=manifest) as r:
                assert r.status == 200
            async with s.post(
                    f"{base}/rpc/internal/ckpt/status/{ckpt_id}",
                    headers=wtok,
                    json={"status": "available", "size": 123}) as r:
                assert r.status == 200

            row = await stack.backend.latest_checkpoint("stub-1")
            assert row and row["checkpoint_id"] == ckpt_id
            assert row["size"] == 123

            async with s.get(
                    f"{base}/rpc/internal/ckpt/manifest/{ckpt_id}",
                    headers=wtok) as r:
                assert r.status == 200
                assert ImageManifest.from_json(await r.text()).chunk_bytes \
                    == 4 << 20
            async with s.get(
                    f"{base}/rpc/internal/ckpt/manifest/ckpt-missing",
                    headers=wtok) as r:
                assert r.status == 404
            # path traversal in the id must be rejected, not resolved
            async with s.get(
                    f"{base}/rpc/internal/ckpt/manifest/..%2F..%2Fetc",
                    headers=wtok) as r:
                assert r.status in (400, 404)
