"""Physics/anti-fooling validation for the benchmark harness.

VERDICT round-2 items #1/#10: BENCH must never again carry a number that
violates the chip's physical limits (73k tok/s/chip on a v5e implied 23 TB/s
of HBM bandwidth). These tests pin the validator's behavior: impossible
timings are rejected, plausible ones pass, and the accounting (bytes/step,
FLOPs/step) matches hand-computed values for known configs.
"""

import jax
import jax.numpy as jnp
import pytest

from tpu9.benchsuite.physics import (chip_spec, decode_byte_counts,
                                     decode_physics,
                                     linear_scaling_violations,
                                     matmul_physics, physics_violations)
from tpu9.models import init_decoder
from tpu9.models.llama import LLAMA_PRESETS
from tpu9.ops.quant import init_quantized_decoder, quantized_bytes


def test_chip_spec_lookup():
    v5e = chip_spec("TPU v5 lite")
    assert v5e.name == "tpu-v5e"
    assert v5e.hbm_gbps == 819.0
    assert chip_spec("TPU v4").name == "tpu-v4"
    # unknown chips get a GENEROUS ceiling (can't mask impossible numbers)
    unk = chip_spec("mystery accelerator")
    assert unk.peak_bf16_tflops > chip_spec("TPU v6e").peak_bf16_tflops


def test_round2_number_is_rejected():
    """The exact BENCH_r02 fiction: llama-1b (≈2.47 GB bf16 streamed),
    batch 8, 0.109 ms/step on a v5e ⇒ ~23 TB/s. Must be flagged."""
    spec = chip_spec("TPU v5 lite")
    phys = decode_physics(step_ms=0.109, batch=8,
                          streamed_bytes=2_470_000_000,
                          kv_bytes_per_step=0, matmul_params=1_240_000_000,
                          spec=spec)
    assert phys["mbu"] > 20            # ~28x the chip's bandwidth
    fails = physics_violations(phys, what="llm")
    assert fails and "did not fence" in fails[0]


def test_plausible_number_passes():
    """8B int8 (~8 GB streamed) at 17 ms/step on v5e ≈ 0.6 MBU — fine."""
    spec = chip_spec("TPU v5 lite")
    phys = decode_physics(step_ms=17.0, batch=8,
                          streamed_bytes=8_000_000_000,
                          kv_bytes_per_step=1_100_000_000,
                          matmul_params=8_000_000_000, spec=spec)
    assert 0.3 < phys["mbu"] < 1.0
    assert physics_violations(phys, what="llm") == []


def test_kernel_mfu_rejection():
    """BENCH_r02's flash '0.029 ms' at [4,2048,16,128] ⇒ ~4.7 PFLOP/s on a
    197-TFLOP/s chip. Must be flagged."""
    spec = chip_spec("TPU v5 lite")
    b, t, h, d = 4, 2048, 16, 128
    rep = matmul_physics(elapsed_ms=0.029, flops=2.0 * b * t * t * h * d,
                         bytes_moved=4 * b * t * h * d * 2, spec=spec)
    assert rep["mfu"] > 5
    assert physics_violations(rep, what="flash")


def test_linear_scaling_detects_async_clock():
    # round-2 failure shape: 2x work "completes" in ~the same elapsed time
    assert linear_scaling_violations(0.007, 0.008, what="llm")
    assert linear_scaling_violations(0.10, 0.21, what="llm") == []
    assert linear_scaling_violations(0.0, 0.2, what="llm")


def test_decode_byte_counts_tiny_exact():
    cfg = LLAMA_PRESETS["llama-tiny"]
    params = init_decoder(jax.random.PRNGKey(0), cfg)
    c = decode_byte_counts(params, cfg, batch=2, mean_ctx=64)
    # hand count: per layer wq 128*128, wk/wv 128*64 each, wo 128*128,
    # gate/up 128*256 each, down 256*128; 2 layers; lm_head 128*512
    per_layer = (128 * 128 * 2 + 128 * 64 * 2 + 3 * 128 * 256)
    expect_params = per_layer * 2 + 128 * 512
    assert c["matmul_params"] == expect_params
    # bf16: 2 bytes/param (+ norm vectors: 5 * 128 f32 = 2560 bytes)
    assert c["streamed_bytes"] == expect_params * 2 + 5 * 128 * 4
    # kv: 2(K,V) * L * B * ctx * KH*D * 2B  read + one-row write
    kv_read = 2 * 2 * 2 * 64 * (2 * 32) * 2
    kv_write = 2 * 2 * 2 * (2 * 32) * 2
    assert c["kv_bytes_per_step"] == kv_read + kv_write


def test_quantized_init_structure_and_size():
    cfg = LLAMA_PRESETS["llama-tiny"]
    qp = init_quantized_decoder(jax.random.PRNGKey(0), cfg)
    # same tree paths as the dense init
    dense = init_decoder(jax.random.PRNGKey(0), cfg)
    assert set(qp.keys()) == set(dense.keys())
    assert set(qp["layers"][0].keys()) == set(dense["layers"][0].keys())
    # projections are int8 entries
    assert qp["layers"][0]["wq"]["q"].dtype == jnp.int8
    assert qp["lm_head"]["q"].shape == (cfg.dim, cfg.vocab_size)
    # ~half the bytes of the bf16 tree (embed stays bf16)
    assert quantized_bytes(qp) < 0.75 * quantized_bytes(dense)


def test_quantized_init_serves_through_engine():
    """The int8-synthesized tree must run the full engine path (decode
    windows + sampling) — this is the flagship bench configuration at toy
    scale."""
    import asyncio

    from tpu9.serving.presets import load_engine

    async def run():
        engine = load_engine("llama-tiny-int8", max_batch=2, max_seq_len=64,
                             prefill_buckets=(16,), decode_steps=(1, 4))
        await engine.start()
        out = await engine.generate([3, 1, 4, 1, 5], max_new_tokens=6)
        out2 = await engine.generate([3, 1, 4, 1, 5], max_new_tokens=6)
        await engine.stop()
        return out, out2

    out, out2 = asyncio.run(run())
    assert len(out) == 6
    assert out == out2                 # greedy decode is deterministic


def test_int8_streamed_bytes_counted_at_int8_width():
    cfg = LLAMA_PRESETS["llama-tiny"]
    qp = init_quantized_decoder(jax.random.PRNGKey(0), cfg)
    dense = init_decoder(jax.random.PRNGKey(0), cfg)
    cq = decode_byte_counts(qp, cfg, batch=1, mean_ctx=8)
    cd = decode_byte_counts(dense, cfg, batch=1, mean_ctx=8)
    assert cq["matmul_params"] == cd["matmul_params"]
    assert cq["streamed_bytes"] < 0.75 * cd["streamed_bytes"]


def test_unknown_preset_raises():
    from tpu9.serving.presets import resolve_preset
    with pytest.raises(KeyError):
        resolve_preset("llama-nope")
    cfg, q = resolve_preset("llama3-8b-int8")
    assert q and cfg.n_layers == 32


def test_compact_line_is_small_and_complete():
    """VERDICT r03 #1a: the driver's tail capture truncated the r03 output
    line mid-JSON and the headline was lost. The final line must stay
    compact regardless of how much evidence the run produced."""
    import importlib.util
    import json
    import os
    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(os.path.dirname(__file__), "..", "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    detail = {
        "backend": "tpu", "on_tpu": True, "model": "llama3-8b-int8",
        "endpoint_tokens_per_sec_per_chip": 1234.5,
        "endpoint_served_proof_ok": True,
        "endpoint_container_on_tpu": True,
        "endpoint_physics": {"mbu": 0.61, "mfu": 0.05},
        "cold_start_p50_s": 0.9,
        "validation": {"violations": [], "ok": True},
        # a huge evidence blob that must NOT reach the stdout line
        "phase_timeline": {f"phase{i}": {"p50": 0.1} for i in range(500)},
    }
    line = bench.compact_line(detail)
    assert len(json.dumps(line)) < 2000
    assert line["metric"] == "endpoint_tokens_per_sec_per_chip"
    assert line["extra"]["backend"] == "tpu"
    assert line["extra"]["endpoint_served_proof_ok"] is True
    assert "phase_timeline" not in line["extra"]

    # CPU fallback keeps cold start as the headline
    cpu_detail = {"backend": "cpu", "cold_start_p50_s": 0.9,
                  "validation": {"violations": [], "ok": True}}
    line = bench.compact_line(cpu_detail)
    assert line["metric"] == "cold_start_p50_s"

    # a TPU number whose served proof failed must NOT become the headline
    bad = dict(detail)
    bad["endpoint_served_proof_ok"] = False
    assert bench.compact_line(bad)["metric"] == "cold_start_p50_s"
