import asyncio

from tpu9.config import SchedulerConfig, WorkerPoolConfig
from tpu9.repository import ContainerRepository, WorkerRepository
from tpu9.scheduler import LocalProcessPool, Scheduler, select_worker
from tpu9.scheduler.selector import filter_workers, find_slice_gang
from tpu9.statestore import MemoryStore
from tpu9.types import (ContainerRequest, ContainerStatus, WorkerState,
                        WorkerStatus, parse_tpu_spec)


def W(worker_id, chips=0, gen="", cpu=8000, mem=32768, pool="default",
      slice_id="", rank=0, hosts=1, status=WorkerStatus.AVAILABLE.value):
    return WorkerState(
        worker_id=worker_id, pool=pool, status=status,
        total_cpu_millicores=cpu, total_memory_mb=mem,
        free_cpu_millicores=cpu, free_memory_mb=mem,
        tpu_generation=gen, tpu_chip_count=chips, tpu_free_chips=chips,
        slice_id=slice_id, slice_host_rank=rank, slice_host_count=hosts,
        address=f"10.0.0.{rank}:80")


class TestSelector:
    def test_cpu_request_avoids_tpu_workers(self):
        workers = [W("cpu1"), W("tpu1", chips=8, gen="v5e")]
        req = ContainerRequest(cpu_millicores=1000, memory_mb=1024)
        got = filter_workers(workers, req)
        assert [w.worker_id for w in got] == ["cpu1"]

    def test_tpu_request_matches_generation_and_chips(self):
        workers = [W("a", chips=4, gen="v5e"), W("b", chips=8, gen="v5e"),
                   W("c", chips=8, gen="v5p"), W("cpu")]
        req = ContainerRequest(cpu_millicores=100, memory_mb=128, tpu="v5e-8")
        got = filter_workers(workers, req)
        assert [w.worker_id for w in got] == ["b"]

    def test_binpack_prefers_tightest_fit(self):
        workers = [W("big", chips=8, gen="v5e"), W("tight", chips=1, gen="v5e")]
        req = ContainerRequest(cpu_millicores=100, memory_mb=128, tpu="v5e-1")
        chosen = select_worker(workers, req)
        assert chosen.worker_id == "tight"

    def test_resource_exhaustion_filters(self):
        w = W("a", cpu=1000, mem=512)
        req = ContainerRequest(cpu_millicores=2000, memory_mb=128)
        assert filter_workers([w], req) == []

    def test_gang_discovery(self):
        spec = parse_tpu_spec("v5p-8")  # 2 hosts x 4 chips
        workers = [
            W("h0", chips=4, gen="v5p", slice_id="s1", rank=0, hosts=2),
            W("h1", chips=4, gen="v5p", slice_id="s1", rank=1, hosts=2),
            W("lone", chips=4, gen="v5p", slice_id="s2", rank=0, hosts=2),
        ]
        req = ContainerRequest(cpu_millicores=100, memory_mb=128, tpu="v5p-8")
        gang = find_slice_gang(workers, spec, req)
        assert gang is not None
        assert [w.worker_id for w in gang] == ["h0", "h1"]

    def test_gang_all_or_nothing(self):
        spec = parse_tpu_spec("v5p-8")
        h1 = W("h1", chips=4, gen="v5p", slice_id="s1", rank=1, hosts=2)
        h1.tpu_free_chips = 0   # busy host poisons the slice
        workers = [W("h0", chips=4, gen="v5p", slice_id="s1", rank=0, hosts=2),
                   h1]
        req = ContainerRequest(cpu_millicores=100, memory_mb=128, tpu="v5p-8")
        assert find_slice_gang(workers, spec, req) is None


class TestScheduler:
    async def _scheduler(self, pools=None):
        store = MemoryStore()
        cfg = SchedulerConfig(loop_interval_s=0.01)
        sched = Scheduler(store, cfg, pools=pools or {})
        return store, sched

    async def test_schedules_to_worker_stream(self):
        store, sched = await self._scheduler()
        workers = WorkerRepository(store)
        await workers.register(W("w1", cpu=4000, mem=8192))
        await sched.start()
        try:
            req = ContainerRequest(container_id="c1", stub_id="s1",
                                   cpu_millicores=1000, memory_mb=1024)
            await sched.run(req)
            got = []
            for _ in range(100):
                got = await workers.read_requests("w1", timeout=0.05)
                if got:
                    break
            assert got and got[0][1].container_id == "c1"
            # capacity was reserved
            w = await workers.get("w1")
            assert w.free_cpu_millicores == 3000
            st = await ContainerRepository(store).get_state("c1")
            assert st.status == ContainerStatus.SCHEDULED.value
        finally:
            await sched.stop()

    async def test_gang_scheduling_atomic(self):
        store, sched = await self._scheduler()
        workers = WorkerRepository(store)
        for rank in range(2):
            await workers.register(
                W(f"h{rank}", chips=4, gen="v5p", slice_id="s1", rank=rank,
                  hosts=2, cpu=4000, mem=8192))
        await sched.start()
        try:
            req = ContainerRequest(container_id="g1", stub_id="s1",
                                   cpu_millicores=500, memory_mb=512,
                                   tpu="v5p-8")
            await sched.run(req)
            for _ in range(200):
                if sched.stats["gangs_scheduled"]:
                    break
                await asyncio.sleep(0.01)
            assert sched.stats["gangs_scheduled"] == 1
            r0 = await workers.read_requests("h0", timeout=0.5)
            r1 = await workers.read_requests("h1", timeout=0.5)
            assert r0 and r1
            g0, g1 = r0[0][1].gang, r1[0][1].gang
            assert g0.gang_id == g1.gang_id
            assert {g0.rank, g1.rank} == {0, 1}
            assert g0.coordinator_addr == g1.coordinator_addr
            assert g0.coordinator_addr.startswith("10.0.0.0:")
            # chips reserved on both hosts
            assert (await workers.get("h0")).tpu_free_chips == 0
            assert (await workers.get("h1")).tpu_free_chips == 0
        finally:
            await sched.stop()

    async def test_retry_then_fail(self):
        store, sched = await self._scheduler()
        sched.cfg.max_retries = 2
        await sched.start()
        try:
            req = ContainerRequest(container_id="c1", stub_id="s1",
                                   cpu_millicores=1000, memory_mb=1024,
                                   pool_selector="nope")
            await sched.run(req)
            for _ in range(300):
                if sched.stats["failed"]:
                    break
                await asyncio.sleep(0.02)
            assert sched.stats["failed"] == 1
            exit_info = await ContainerRepository(store).get_exit("c1")
            assert "scheduler_failed" in exit_info["reason"]
        finally:
            await sched.stop()

    async def test_pool_scale_up_called(self):
        calls = []

        class FakePool:
            async def can_host(self, request):
                return True

            async def add_worker(self, request):
                calls.append(request.container_id)

        store, sched = await self._scheduler(pools={"default": FakePool()})
        await sched.start()
        try:
            req = ContainerRequest(container_id="c1", stub_id="s1",
                                   cpu_millicores=100, memory_mb=128)
            await sched.run(req)
            for _ in range(100):
                if calls:
                    break
                await asyncio.sleep(0.01)
            assert "c1" in calls
        finally:
            await sched.stop()


class TestLocalPool:
    async def test_multihost_scaleup_creates_slice(self):
        created = []

        async def factory(**kw):
            created.append(kw)

            class FakeWorker:
                async def stop(self):
                    pass
            return FakeWorker()

        pool = LocalProcessPool(
            WorkerPoolConfig(name="tpu", tpu_type="v5p-64", max_workers=64),
            factory)
        req = ContainerRequest(tpu="v5p-8", cpu_millicores=100, memory_mb=128)
        assert await pool.can_host(req)
        await pool.add_worker(req)
        assert len(created) == 2
        assert created[0]["slice_id"] == created[1]["slice_id"]
        assert [c["slice_host_rank"] for c in created] == [0, 1]
        await pool.shutdown()
