"""OCI registry pull: distribution-API client against an in-process fake
registry, plus the full Image.from_registry build-on-worker flow.
(Reference parity: pkg/worker/image.go pull path, build.go registry
images — tpu9 pulls via plain HTTP API + unpacks whiteout-aware.)"""

import gzip
import hashlib
import io
import json
import os
import tarfile

import pytest
from aiohttp import web

from tpu9.images.oci import OciClient, OciError, parse_ref, _extract_layer
from tpu9.testing.localstack import LocalStack

pytestmark = pytest.mark.e2e


def _tar_layer(entries: dict[str, bytes], gz: bool = True) -> bytes:
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w") as tf:
        for name, content in entries.items():
            if name.endswith("/"):
                info = tarfile.TarInfo(name.rstrip("/"))
                info.type = tarfile.DIRTYPE
                tf.addfile(info)
                continue
            info = tarfile.TarInfo(name)
            info.size = len(content)
            info.mode = 0o755
            tf.addfile(info, io.BytesIO(content))
    raw = buf.getvalue()
    return gzip.compress(raw) if gz else raw


class FakeRegistry:
    """Minimal /v2 distribution server holding one image."""

    def __init__(self, name: str, layers: list[bytes],
                 env: list[str] = ()):  # noqa: B006
        self.name = name
        self.blobs: dict[str, bytes] = {}
        config = json.dumps({
            "architecture": "amd64", "os": "linux",
            "config": {"Env": list(env), "Cmd": ["/bin/sh"]},
        }).encode()
        cfg_digest = "sha256:" + hashlib.sha256(config).hexdigest()
        self.blobs[cfg_digest] = config
        layer_descs = []
        for blob in layers:
            d = "sha256:" + hashlib.sha256(blob).hexdigest()
            self.blobs[d] = blob
            layer_descs.append({
                "mediaType": "application/vnd.oci.image.layer.v1.tar+gzip",
                "digest": d, "size": len(blob)})
        manifest = {
            "schemaVersion": 2,
            "mediaType": "application/vnd.oci.image.manifest.v1+json",
            "config": {"mediaType": "application/vnd.oci.image.config.v1+json",
                       "digest": cfg_digest, "size": len(config)},
            "layers": layer_descs,
        }
        self.manifest_blob = json.dumps(manifest).encode()
        man_digest = "sha256:" + hashlib.sha256(self.manifest_blob).hexdigest()
        index = {
            "schemaVersion": 2,
            "mediaType": "application/vnd.oci.image.index.v1+json",
            "manifests": [{
                "mediaType": "application/vnd.oci.image.manifest.v1+json",
                "digest": man_digest, "size": len(self.manifest_blob),
                "platform": {"os": "linux", "architecture": "amd64"}}],
        }
        self.blobs[man_digest] = self.manifest_blob
        self.index_blob = json.dumps(index).encode()
        self.port = 0
        self._runner = None

    async def start(self) -> "FakeRegistry":
        app = web.Application()
        app.router.add_get("/v2/{name:.+}/manifests/{ref}", self._manifests)
        app.router.add_get("/v2/{name:.+}/blobs/{digest}", self._blob)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, "127.0.0.1", 0)
        await site.start()
        self.port = self._runner.addresses[0][1]
        return self

    async def stop(self) -> None:
        if self._runner:
            await self._runner.cleanup()

    async def _manifests(self, request):
        ref = request.match_info["ref"]
        if ref.startswith("sha256:"):
            return web.Response(
                body=self.blobs[ref],
                content_type="application/vnd.oci.image.manifest.v1+json")
        return web.Response(
            body=self.index_blob,
            content_type="application/vnd.oci.image.index.v1+json")

    async def _blob(self, request):
        d = request.match_info["digest"]
        if d not in self.blobs:
            return web.json_response({"error": "unknown blob"}, status=404)
        return web.Response(body=self.blobs[d],
                            content_type="application/octet-stream")


class TestParseRef:
    def test_dockerhub_shortname(self):
        base, name, tag = parse_ref("python:3.12")
        assert base == "https://registry-1.docker.io"
        assert name == "library/python" and tag == "3.12"

    def test_custom_registry(self):
        base, name, tag = parse_ref("127.0.0.1:5000/app/api:v1")
        assert base == "http://127.0.0.1:5000"
        assert name == "app/api" and tag == "v1"

    def test_default_tag(self):
        assert parse_ref("ubuntu")[2] == "latest"


class TestExtractLayer:
    def test_whiteouts(self, tmp_path):
        dest = str(tmp_path / "root")
        _extract_layer(_tar_layer({"bin/": b"", "bin/tool": b"v1",
                                   "etc/conf": b"old"}), dest)
        assert open(f"{dest}/bin/tool").read() == "v1"
        # second layer deletes etc/conf via whiteout and replaces tool
        _extract_layer(_tar_layer({"etc/.wh.conf": b"",
                                   "bin/tool": b"v2"}), dest)
        assert not os.path.exists(f"{dest}/etc/conf")
        assert open(f"{dest}/bin/tool").read() == "v2"

    def test_path_escape_rejected(self, tmp_path):
        dest = str(tmp_path / "root")
        with pytest.raises(OciError):
            _extract_layer(_tar_layer({"../evil": b"x"}), dest)


async def test_pull_via_fake_registry(tmp_path):
    reg = await FakeRegistry(
        "library/base",
        [_tar_layer({"usr/bin/app": b"#!/bin/sh\necho app\n"}),
         _tar_layer({"etc/version": b"2.0"})],
        env=["PATH=/usr/bin", "APP_MODE=prod"]).start()
    try:
        async def transport(method, url, headers):
            import aiohttp
            async with aiohttp.ClientSession() as s:
                async with s.request(method, url, headers=headers) as resp:
                    return resp.status, dict(resp.headers), await resp.read()

        dest = str(tmp_path / "rootfs")
        config = await OciClient(transport).pull(
            f"127.0.0.1:{reg.port}/library/base:latest", dest)
        assert open(f"{dest}/usr/bin/app").read().startswith("#!")
        assert open(f"{dest}/etc/version").read() == "2.0"
        assert "APP_MODE=prod" in config.get("Env", [])
    finally:
        await reg.stop()


async def test_from_registry_build_through_worker():
    """Full flow: spec.from_registry → build container on a worker pulls
    from the registry, snapshots rootfs/, manifest lands in the gateway
    registry and materializes through the cache."""
    reg = await FakeRegistry(
        "library/base",
        [_tar_layer({"opt/marker.txt": b"from-oci-layer"})]).start()
    try:
        async with LocalStack() as stack:
            spec = {"from_registry": f"127.0.0.1:{reg.port}/library/base",
                    "commands": ["mkdir -p env && echo built > env/ok"]}
            status, out = await stack.api("POST", "/rpc/image/build",
                                          json_body=spec)
            assert status == 200
            image_id = out["image_id"]
            import asyncio
            st = {}
            for _ in range(600):
                _, st = await stack.api("GET",
                                        f"/rpc/image/status/{image_id}")
                if st.get("status") in ("ready", "failed"):
                    break
                await asyncio.sleep(0.1)
            assert st["status"] == "ready", st.get("logs", [])[-5:]

            # the snapshot contains the OCI rootfs and the command output
            m = stack.gateway.images.builder.load_manifest(image_id)
            paths = {f.path for f in m.files}
            assert "rootfs/opt/marker.txt" in paths
            assert "env/ok" in paths

            # materializes through a worker's puller/cache
            w = await stack._worker_factory()
            bundle = await w.cache.puller.pull(image_id, manifest=m)
            assert open(os.path.join(
                bundle, "rootfs/opt/marker.txt")).read() == "from-oci-layer"
    finally:
        await reg.stop()


class PrivateFakeRegistry(FakeRegistry):
    """FakeRegistry requiring a token obtained by basic-auth'd token dance
    (the private-pull flow of pkg/registry/credentials.go's basic case)."""

    def __init__(self, *a, user="bob", password="hunter2", **kw):
        super().__init__(*a, **kw)
        self.user, self.password = user, password
        self.granted = "tok-" + hashlib.sha256(password.encode()).hexdigest()[:12]

    async def start(self):
        await super().start()
        # re-mount with auth wrappers + token endpoint
        app = web.Application()
        app.router.add_get("/token", self._token)
        app.router.add_get("/v2/{name:.+}/manifests/{ref}", self._authed(self._manifests))
        app.router.add_get("/v2/{name:.+}/blobs/{digest}", self._authed(self._blob))
        await self._runner.cleanup()
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, "127.0.0.1", 0)
        await site.start()
        self.port = self._runner.addresses[0][1]
        return self

    def _authed(self, handler):
        async def wrapped(request):
            auth = request.headers.get("Authorization", "")
            if auth != f"Bearer {self.granted}":
                return web.json_response(
                    {"errors": [{"code": "UNAUTHORIZED"}]}, status=401,
                    headers={"Www-Authenticate":
                             f'Bearer realm="http://127.0.0.1:{self.port}'
                             f'/token",service="fake",scope="pull"'})
            return await handler(request)
        return wrapped

    async def _token(self, request):
        import base64
        auth = request.headers.get("Authorization", "")
        if auth.startswith("Basic "):
            raw = base64.b64decode(auth[6:]).decode()
            if raw == f"{self.user}:{self.password}":
                return web.json_response({"token": self.granted})
        return web.json_response({"error": "bad credentials"}, status=401)


async def test_private_registry_pull_with_credentials(tmp_path):
    from tpu9.images.oci import OciClient, aiohttp_transport

    layer = _tar_layer({"app/secret.txt": b"private bits"})
    reg = await PrivateFakeRegistry("corp/app", [layer]).start()
    try:
        ref = f"127.0.0.1:{reg.port}/corp/app:latest"
        # without credentials: the token dance fails → pull raises
        t_anon = aiohttp_transport()
        try:
            with pytest.raises(Exception):
                await OciClient(t_anon).pull(ref, str(tmp_path / "anon"))
        finally:
            await t_anon.aclose()
        # with credentials: basic-auth'd token exchange succeeds
        t_auth = aiohttp_transport(credentials={
            f"127.0.0.1:{reg.port}": ("bob", "hunter2")})
        try:
            await OciClient(t_auth).pull(ref, str(tmp_path / "ok"))
        finally:
            await t_auth.aclose()
        assert (tmp_path / "ok" / "app" / "secret.txt").read_bytes() \
            == b"private bits"
    finally:
        await reg.stop()


async def test_registry_secret_threads_to_build_env():
    """spec.registry_secret resolves the workspace secret into the build
    container's env (value never in the spec hash), and a missing secret
    fails loudly."""
    from tpu9.images.spec import ImageSpec

    async with LocalStack() as stack:
        ws = stack.gateway.default_workspace
        await stack.gateway.backend.upsert_secret(
            ws.workspace_id, "regcred", "bob:hunter2")
        spec = ImageSpec(from_registry="example.com/app:v1",
                         registry_secret="regcred")
        # a set secret name joins the id; unset stays back-compatible
        assert spec.image_id != ImageSpec(
            from_registry="example.com/app:v1").image_id
        req = stack.gateway.images._build_request(ws.workspace_id, spec)
        await stack.gateway.images._finish_schedule(ws.workspace_id, spec,
                                                    req)
        assert req.env.get("TPU9_REGISTRY_AUTH") == "bob:hunter2"
        assert "TPU9_REGISTRY_AUTH" not in json.dumps(
            spec.to_dict())   # value nowhere in the spec

        bad = ImageSpec(from_registry="example.com/app:v1",
                        registry_secret="missing")
        req2 = stack.gateway.images._build_request(ws.workspace_id, bad)
        with pytest.raises(ValueError):
            await stack.gateway.images._finish_schedule(
                ws.workspace_id, bad, req2)


async def test_private_image_dedupe_requires_credentials(tmp_path):
    """A foreign workspace with the same (ref, secret NAME) must NOT get
    dedupe access to a privately-pulled image: verify() reports
    exists=False and build() demands working credentials."""
    from tpu9.images.spec import ImageSpec

    async with LocalStack() as stack:
        svc = stack.gateway.images
        ws_a = stack.gateway.default_workspace.workspace_id
        spec = ImageSpec(from_registry="corp.example.com/app:v1",
                         registry_secret="regcred")
        # simulate A's completed build
        svc.builder.has_image = lambda image_id: True
        await stack.gateway.backend.grant_image_access(spec.image_id, ws_a)

        # A (has access): dedupe fast path works
        out = await svc.verify(spec, workspace_id=ws_a)
        assert out["exists"] is True

        # B (no access, guessed the secret name): no dedupe grant
        ws_b = (await stack.gateway.backend.create_workspace("b")).workspace_id
        out = await svc.verify(spec, workspace_id=ws_b)
        assert out["exists"] is False
        assert not await stack.gateway.backend.has_image_access(
            spec.image_id, ws_b)

        # B's build without a secret of that name fails loudly
        with pytest.raises(ValueError):
            await svc.build(ws_b, spec)
        # ... and with a secret whose credentials the registry rejects
        await stack.gateway.backend.upsert_secret(ws_b, "regcred", "x:wrong")

        async def deny(spec_, value):
            return False
        svc._check_registry_credentials = deny
        with pytest.raises(PermissionError):
            await svc.build(ws_b, spec)
        assert not await stack.gateway.backend.has_image_access(
            spec.image_id, ws_b)

        # with verifying credentials, access is granted
        async def allow(spec_, value):
            return True
        svc._check_registry_credentials = allow
        out = await svc.build(ws_b, spec)
        assert out["status"] == "ready"
        assert await stack.gateway.backend.has_image_access(
            spec.image_id, ws_b)


async def test_credential_check_probes_manifest(tmp_path):
    """_check_registry_credentials does one authenticated manifest GET."""
    from tpu9.images.spec import ImageSpec

    layer = _tar_layer({"x": b"y"})
    reg = await PrivateFakeRegistry("corp/app", [layer]).start()
    try:
        async with LocalStack() as stack:
            svc = stack.gateway.images
            spec = ImageSpec(
                from_registry=f"127.0.0.1:{reg.port}/corp/app:latest",
                registry_secret="r")
            assert await svc._check_registry_credentials(spec,
                                                         "bob:hunter2")
            assert not await svc._check_registry_credentials(spec,
                                                             "bob:wrong")
    finally:
        await reg.stop()
