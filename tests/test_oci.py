"""OCI registry pull: distribution-API client against an in-process fake
registry, plus the full Image.from_registry build-on-worker flow.
(Reference parity: pkg/worker/image.go pull path, build.go registry
images — tpu9 pulls via plain HTTP API + unpacks whiteout-aware.)"""

import gzip
import hashlib
import io
import json
import os
import tarfile

import pytest
from aiohttp import web

from tpu9.images.oci import OciClient, OciError, parse_ref, _extract_layer
from tpu9.testing.localstack import LocalStack

pytestmark = pytest.mark.e2e


def _tar_layer(entries: dict[str, bytes], gz: bool = True) -> bytes:
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w") as tf:
        for name, content in entries.items():
            if name.endswith("/"):
                info = tarfile.TarInfo(name.rstrip("/"))
                info.type = tarfile.DIRTYPE
                tf.addfile(info)
                continue
            info = tarfile.TarInfo(name)
            info.size = len(content)
            info.mode = 0o755
            tf.addfile(info, io.BytesIO(content))
    raw = buf.getvalue()
    return gzip.compress(raw) if gz else raw


class FakeRegistry:
    """Minimal /v2 distribution server holding one image."""

    def __init__(self, name: str, layers: list[bytes],
                 env: list[str] = ()):  # noqa: B006
        self.name = name
        self.blobs: dict[str, bytes] = {}
        config = json.dumps({
            "architecture": "amd64", "os": "linux",
            "config": {"Env": list(env), "Cmd": ["/bin/sh"]},
        }).encode()
        cfg_digest = "sha256:" + hashlib.sha256(config).hexdigest()
        self.blobs[cfg_digest] = config
        layer_descs = []
        for blob in layers:
            d = "sha256:" + hashlib.sha256(blob).hexdigest()
            self.blobs[d] = blob
            layer_descs.append({
                "mediaType": "application/vnd.oci.image.layer.v1.tar+gzip",
                "digest": d, "size": len(blob)})
        manifest = {
            "schemaVersion": 2,
            "mediaType": "application/vnd.oci.image.manifest.v1+json",
            "config": {"mediaType": "application/vnd.oci.image.config.v1+json",
                       "digest": cfg_digest, "size": len(config)},
            "layers": layer_descs,
        }
        self.manifest_blob = json.dumps(manifest).encode()
        man_digest = "sha256:" + hashlib.sha256(self.manifest_blob).hexdigest()
        index = {
            "schemaVersion": 2,
            "mediaType": "application/vnd.oci.image.index.v1+json",
            "manifests": [{
                "mediaType": "application/vnd.oci.image.manifest.v1+json",
                "digest": man_digest, "size": len(self.manifest_blob),
                "platform": {"os": "linux", "architecture": "amd64"}}],
        }
        self.blobs[man_digest] = self.manifest_blob
        self.index_blob = json.dumps(index).encode()
        self.port = 0
        self._runner = None

    async def start(self) -> "FakeRegistry":
        app = web.Application()
        app.router.add_get("/v2/{name:.+}/manifests/{ref}", self._manifests)
        app.router.add_get("/v2/{name:.+}/blobs/{digest}", self._blob)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, "127.0.0.1", 0)
        await site.start()
        self.port = self._runner.addresses[0][1]
        return self

    async def stop(self) -> None:
        if self._runner:
            await self._runner.cleanup()

    async def _manifests(self, request):
        ref = request.match_info["ref"]
        if ref.startswith("sha256:"):
            return web.Response(
                body=self.blobs[ref],
                content_type="application/vnd.oci.image.manifest.v1+json")
        return web.Response(
            body=self.index_blob,
            content_type="application/vnd.oci.image.index.v1+json")

    async def _blob(self, request):
        d = request.match_info["digest"]
        if d not in self.blobs:
            return web.json_response({"error": "unknown blob"}, status=404)
        return web.Response(body=self.blobs[d],
                            content_type="application/octet-stream")


class TestParseRef:
    def test_dockerhub_shortname(self):
        base, name, tag = parse_ref("python:3.12")
        assert base == "https://registry-1.docker.io"
        assert name == "library/python" and tag == "3.12"

    def test_custom_registry(self):
        base, name, tag = parse_ref("127.0.0.1:5000/app/api:v1")
        assert base == "http://127.0.0.1:5000"
        assert name == "app/api" and tag == "v1"

    def test_default_tag(self):
        assert parse_ref("ubuntu")[2] == "latest"


class TestExtractLayer:
    def test_whiteouts(self, tmp_path):
        dest = str(tmp_path / "root")
        _extract_layer(_tar_layer({"bin/": b"", "bin/tool": b"v1",
                                   "etc/conf": b"old"}), dest)
        assert open(f"{dest}/bin/tool").read() == "v1"
        # second layer deletes etc/conf via whiteout and replaces tool
        _extract_layer(_tar_layer({"etc/.wh.conf": b"",
                                   "bin/tool": b"v2"}), dest)
        assert not os.path.exists(f"{dest}/etc/conf")
        assert open(f"{dest}/bin/tool").read() == "v2"

    def test_path_escape_rejected(self, tmp_path):
        dest = str(tmp_path / "root")
        with pytest.raises(OciError):
            _extract_layer(_tar_layer({"../evil": b"x"}), dest)


async def test_pull_via_fake_registry(tmp_path):
    reg = await FakeRegistry(
        "library/base",
        [_tar_layer({"usr/bin/app": b"#!/bin/sh\necho app\n"}),
         _tar_layer({"etc/version": b"2.0"})],
        env=["PATH=/usr/bin", "APP_MODE=prod"]).start()
    try:
        async def transport(method, url, headers):
            import aiohttp
            async with aiohttp.ClientSession() as s:
                async with s.request(method, url, headers=headers) as resp:
                    return resp.status, dict(resp.headers), await resp.read()

        dest = str(tmp_path / "rootfs")
        config = await OciClient(transport).pull(
            f"127.0.0.1:{reg.port}/library/base:latest", dest)
        assert open(f"{dest}/usr/bin/app").read().startswith("#!")
        assert open(f"{dest}/etc/version").read() == "2.0"
        assert "APP_MODE=prod" in config.get("Env", [])
    finally:
        await reg.stop()


async def test_from_registry_build_through_worker():
    """Full flow: spec.from_registry → build container on a worker pulls
    from the registry, snapshots rootfs/, manifest lands in the gateway
    registry and materializes through the cache."""
    reg = await FakeRegistry(
        "library/base",
        [_tar_layer({"opt/marker.txt": b"from-oci-layer"})]).start()
    try:
        async with LocalStack() as stack:
            spec = {"from_registry": f"127.0.0.1:{reg.port}/library/base",
                    "commands": ["mkdir -p env && echo built > env/ok"]}
            status, out = await stack.api("POST", "/rpc/image/build",
                                          json_body=spec)
            assert status == 200
            image_id = out["image_id"]
            import asyncio
            st = {}
            for _ in range(600):
                _, st = await stack.api("GET",
                                        f"/rpc/image/status/{image_id}")
                if st.get("status") in ("ready", "failed"):
                    break
                await asyncio.sleep(0.1)
            assert st["status"] == "ready", st.get("logs", [])[-5:]

            # the snapshot contains the OCI rootfs and the command output
            m = stack.gateway.images.builder.load_manifest(image_id)
            paths = {f.path for f in m.files}
            assert "rootfs/opt/marker.txt" in paths
            assert "env/ok" in paths

            # materializes through a worker's puller/cache
            w = await stack._worker_factory()
            bundle = await w.cache.puller.pull(image_id, manifest=m)
            assert open(os.path.join(
                bundle, "rootfs/opt/marker.txt")).read() == "from-oci-layer"
    finally:
        await reg.stop()
