"""Concurrency quotas (concurrencylimit.go analogue), signed task
callbacks (auth/sign.go analogue), and the apps API — e2e through the
stack."""

import asyncio
import json

import pytest
from aiohttp import web

from tpu9.testing.localstack import LocalStack
from tpu9.utils.signing import (SIG_HEADER, SIGNING_KEY_SECRET, TS_HEADER,
                                sign_payload, verify_payload)

pytestmark = pytest.mark.e2e


# ---------------------------------------------------------------------------
# signing unit
# ---------------------------------------------------------------------------

def test_sign_verify_roundtrip_and_tamper():
    ts, sig = sign_payload(b"hello", "k1")
    assert verify_payload(b"hello", ts, sig, "k1")
    assert not verify_payload(b"hello!", ts, sig, "k1")      # body tamper
    assert not verify_payload(b"hello", ts, sig, "k2")       # wrong key
    assert not verify_payload(b"hello", ts - 600, sig, "k1")  # stale ts


# ---------------------------------------------------------------------------
# concurrency limits
# ---------------------------------------------------------------------------

async def _sandbox_stub(stack, name="qbox", cpu=500):
    status, out = await stack.api("POST", "/rpc/stub/get-or-create",
                                  json_body={
        "name": name, "stub_type": "sandbox",
        "config": {"runtime": {"cpu_millicores": cpu, "memory_mb": 128}}})
    assert status == 200, out
    return out["stub_id"]


async def test_cpu_quota_blocks_then_releases():
    async with LocalStack() as stack:
        ws_id = stack.gateway.default_workspace.workspace_id
        # cap the workspace at 600 millicores (operator = default ws token)
        status, _ = await stack.api(
            "POST", f"/api/v1/concurrency-limit/{ws_id}",
            json_body={"cpu_millicore_limit": 600})
        assert status == 200

        stub = await _sandbox_stub(stack, "q1", cpu=500)
        status, pod1 = await stack.api("POST", "/rpc/pod/create", json_body={
            "stub_id": stub, "wait": True, "timeout": 30})
        assert status == 200, pod1

        # second pod would need 1000 total > 600 → 429
        stub2 = await _sandbox_stub(stack, "q2", cpu=500)
        status, out = await stack.api("POST", "/rpc/pod/create", json_body={
            "stub_id": stub2, "wait": False})
        assert status == 429, out
        assert "quota exceeded" in out["error"]

        # in-use view reflects the charge
        status, view = await stack.api("GET", "/api/v1/concurrency-limit")
        assert view["in_use"]["cpu_millicores"] == 500
        assert view["limit"]["cpu_millicore_limit"] == 600

        # stopping pod1 releases the charge; the next create succeeds
        status, _ = await stack.api(
            "POST", f"/api/v1/container/{pod1['container_id']}/stop")
        assert status == 200
        for _ in range(100):
            _, view = await stack.api("GET", "/api/v1/concurrency-limit")
            if view["in_use"]["cpu_millicores"] == 0:
                break
            await asyncio.sleep(0.05)
        assert view["in_use"]["cpu_millicores"] == 0
        status, _ = await stack.api("POST", "/rpc/pod/create", json_body={
            "stub_id": stub2, "wait": True, "timeout": 30})
        assert status == 200


async def test_chip_quota_counts_full_slice():
    """A v5p-8 gang request (2 hosts × 4 chips) charges all 8 chips."""
    async with LocalStack() as stack:
        ws_id = stack.gateway.default_workspace.workspace_id
        await stack.api("POST", f"/api/v1/concurrency-limit/{ws_id}",
                        json_body={"tpu_chip_limit": 7})
        from tpu9.scheduler.quota import QuotaExceeded
        from tpu9.types import ContainerRequest
        req = ContainerRequest(stub_id="s", workspace_id=ws_id,
                               cpu_millicores=100, memory_mb=64, tpu="v5p-8")
        with pytest.raises(QuotaExceeded) as exc:
            await stack.gateway.scheduler.run(req)
        assert exc.value.what == "tpu_chip"


async def test_quota_writes_are_operator_only():
    async with LocalStack() as stack:
        from tests.test_tenancy import _req, _second_workspace
        ws2, intruder = await _second_workspace(stack)
        try:
            status, _ = await _req(
                intruder, "POST",
                f"{stack.base_url}/api/v1/concurrency-limit/"
                f"{ws2.workspace_id}",
                json={"cpu_millicore_limit": 999999})
            assert status == 403
        finally:
            await intruder.close()


# ---------------------------------------------------------------------------
# signed task callbacks
# ---------------------------------------------------------------------------

TASK_APP = """
def handler(**kwargs):
    return {"doubled": kwargs.get("x", 0) * 2}
"""


async def test_task_callback_delivers_signed_payload():
    received: list[tuple[bytes, dict]] = []
    got_one = asyncio.Event()

    async def receiver(request):
        received.append((await request.read(), dict(request.headers)))
        got_one.set()
        return web.json_response({"ok": True})

    app = web.Application()
    app.router.add_post("/hook", receiver)
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    port = site._server.sockets[0].getsockname()[1]

    try:
        async with LocalStack() as stack:
            status, out = await stack.api(
                "POST", "/rpc/stub/get-or-create", json_body={
                    "name": "cbq", "stub_type": "taskqueue",
                    "config": {"handler": "app:handler",
                               "callback_url":
                                   f"http://127.0.0.1:{port}/hook",
                               "runtime": {"cpu_millicores": 500,
                                           "memory_mb": 256}},
                    "object_id": await stack.upload_workspace(
                        {"app.py": TASK_APP})})
            assert status == 200, out
            status, task = await stack.api(
                "POST", "/rpc/taskqueue/put",
                json_body={"stub_id": out["stub_id"],
                           "kwargs": {"x": 21}})
            assert status == 200, task

            await asyncio.wait_for(got_one.wait(), timeout=60)
            body, headers = received[0]
            payload = json.loads(body)
            assert payload["task_id"] == task["task_id"]
            assert payload["status"] == "complete"
            assert payload["result"]["doubled"] == 42

            # the signature verifies with the workspace signing key
            ws_id = stack.gateway.default_workspace.workspace_id
            key = await stack.backend.get_secret(ws_id, SIGNING_KEY_SECRET)
            assert key, "signing key was not minted"
            assert verify_payload(body, int(headers[TS_HEADER]),
                                  headers[SIG_HEADER], key)
            # and fails against a tampered body (the point of signing)
            assert not verify_payload(body + b" ", int(headers[TS_HEADER]),
                                      headers[SIG_HEADER], key)
    finally:
        await runner.cleanup()


# ---------------------------------------------------------------------------
# apps API
# ---------------------------------------------------------------------------

async def test_apps_list_and_delete_drain_deployments():
    async with LocalStack() as stack:
        dep = await stack.deploy_echo_endpoint("appecho")
        await stack.invoke(dep, {"x": 1})

        status, apps = await stack.api("GET", "/api/v1/app")
        assert status == 200 and apps, apps
        app = next(a for a in apps
                   if any(d["stub_id"] == dep["stub_id"]
                          for d in a["deployments"]))

        status, out = await stack.api("DELETE",
                                      f"/api/v1/app/{app['app_id']}")
        assert status == 200 and out["deployments_drained"] >= 1

        # deployment is gone: invoking 404s and the app no longer lists
        status, _ = await stack.api("POST", "/endpoint/appecho",
                                    json_body={"x": 2}, timeout=15)
        # route may 404 (deployment inactive); a draining 503 also accepts
        assert status in (404, 503)
        status, apps = await stack.api("GET", "/api/v1/app")
        assert all(a["app_id"] != app["app_id"] for a in apps)


# ---------------------------------------------------------------------------
# reconcile + atomicity hardening
# ---------------------------------------------------------------------------

async def test_quota_reconcile_releases_orphaned_charges():
    """A worker host dying hard leaves a charge with no container state and
    no terminal event; the reconcile sweep must release it (but must NOT
    touch fresh charges or backlogged requests)."""
    import tpu9.scheduler.quota as quota_mod
    from tpu9.repository.keys import Keys

    async with LocalStack() as stack:
        q = stack.gateway.quota
        store = stack.gateway.store
        ws = stack.gateway.default_workspace.workspace_id
        key = Keys.workspace_active(ws)
        # orphan: stamped in the past, no state, not in backlog
        await store.hset(key, "ct-dead", "500:4:1")
        # fresh: inside the grace window
        await store.hset(key, "ct-new", f"500:0:{2**62}")
        # backlogged: old stamp but a live backlog entry
        await store.hset(key, "ct-queued", "250:0:1")
        await store.zadd(Keys.BACKLOG, "ct-queued", 1.0)
        released = await q.reconcile()
        assert released == 1
        left = await store.hgetall(key)
        assert set(left) == {"ct-new", "ct-queued"}
        # in_use still parses both 2- and 3-part charge values
        await store.hset(key, "ct-old-fmt", "100:2")
        cpu, chips = await q.in_use(ws)
        assert cpu == 850 and chips == 2


async def test_function_dispatch_failure_finalizes_task():
    """Quota rejection AFTER the task record exists must fail the task, not
    strand it PENDING forever."""
    async with LocalStack() as stack:
        ws_id = stack.gateway.default_workspace.workspace_id
        status, _ = await stack.api(
            "POST", f"/api/v1/concurrency-limit/{ws_id}",
            json_body={"cpu_millicore_limit": 100})
        assert status == 200
        status, out = await stack.api("POST", "/rpc/stub/get-or-create",
                                      json_body={
            "name": "qfn", "stub_type": "function",
            "config": {"handler": "app:handler",
                       "runtime": {"cpu_millicores": 500,
                                   "memory_mb": 128}}})
        assert status == 200, out
        status, res = await stack.api("POST", "/rpc/function/invoke",
                                      json_body={"stub_id": out["stub_id"],
                                                 "args": [], "kwargs": {},
                                                 "wait": False})
        assert status == 429, (status, res)
        # the task record the dispatcher created must be terminal now
        tasks = stack.gateway.dispatcher.tasks
        # find it via the backend task rows
        rows = await stack.gateway.backend.list_tasks(ws_id)
        assert rows, "task record should exist"
        msg = await tasks.get_message(rows[0]["task_id"])
        assert msg is not None and msg.status == "error"


async def test_ensure_secret_is_create_if_absent():
    async with LocalStack() as stack:
        backend = stack.gateway.backend
        ws = stack.gateway.default_workspace.workspace_id
        v1 = await backend.ensure_secret(ws, "race-key", "first")
        v2 = await backend.ensure_secret(ws, "race-key", "second")
        assert v1 == "first" and v2 == "first"
        assert await backend.get_secret(ws, "race-key") == "first"
