"""Mesh-sharded multi-chip serving (ISSUE 9): the topology planner, the
sharding policy, and the sharded engine's parity with the single-device
engine.

Planner/parse tests are pure host arithmetic over feasibility pricing and
run anywhere. Engine tests are ``multichip``-marked: they need the
8-device CPU mesh conftest forces (``force_cpu(host_devices=8)``) and are
skipped with a re-run recipe when XLA_FLAGS overrode it. Parity is judged
at f32 (no bf16 argmax-tie noise — the spec/quant precedent), with any
fork measured against the full-context oracle's argmax margin.
"""

import asyncio
import os
from dataclasses import replace

import jax
import jax.numpy as jnp
import pytest

from tpu9.models import decoder_forward, init_decoder
from tpu9.models.llama import LLAMA_PRESETS
from tpu9.serving.engine import EngineConfig, InferenceEngine
from tpu9.serving.feasibility import InfeasibleDeployment, hbm_budget
from tpu9.serving.shard import (MeshPolicy, SingleDevicePolicy, Topology,
                                candidate_topologies, make_policy,
                                parse_topology, plan_topology,
                                resolve_topology)

TINY = replace(LLAMA_PRESETS["llama-tiny"], dtype=jnp.float32)


# ---------------------------------------------------------------------------
# topology syntax + validation
# ---------------------------------------------------------------------------

def test_parse_topology_forms():
    assert parse_topology("2") == Topology(tp=2)
    assert parse_topology("2x4") == Topology(tp=2, fsdp=4)
    assert parse_topology("tp=2,fsdp=4") == Topology(tp=2, fsdp=4)
    assert parse_topology("fsdp=2") == Topology(tp=1, fsdp=2)
    assert parse_topology(None) is None
    assert parse_topology("") is None
    t = Topology(tp=4)
    assert parse_topology(t) is t
    with pytest.raises(ValueError, match="dp"):
        parse_topology("dp=2")


def test_topology_validation_and_props():
    with pytest.raises(ValueError):
        Topology(tp=0)
    assert Topology(2, 4).n_chips == 8
    assert Topology(1, 1).is_single
    assert str(Topology(2, 4)) == "2x4"
    assert Topology(2, 1).as_dict() == {"tp": 2, "fsdp": 1, "n_chips": 2}


def test_resolve_topology_chain(monkeypatch):
    # explicit beats env beats default
    monkeypatch.setenv("TPU9_TOPOLOGY", "4x1")
    assert resolve_topology("2x1") == Topology(2, 1)
    assert resolve_topology(None) == Topology(4, 1)
    monkeypatch.delenv("TPU9_TOPOLOGY")
    assert resolve_topology(None) == Topology(1, 1)
    # auto REQUIRES a slice spec to price against
    with pytest.raises(ValueError, match="auto"):
        resolve_topology("auto", preset="llama3-8b")
    assert resolve_topology("auto", preset="llama3-8b",
                            tpu="v5e-8") == Topology(2, 1)
    # env auto behaves like the explicit string
    monkeypatch.setenv("TPU9_TOPOLOGY", "auto")
    assert resolve_topology(None, preset="llama3-8b",
                            tpu="v5e-8") == Topology(2, 1)


# ---------------------------------------------------------------------------
# planner: feasibility-priced submesh choice
# ---------------------------------------------------------------------------

def test_candidate_topologies_head_divisibility():
    # power-of-two chip counts; tp takes what divides the KV heads, the
    # rest goes to fsdp (weight-only sharding)
    assert candidate_topologies(8, 8) == [
        Topology(1, 1), Topology(2, 1), Topology(4, 1), Topology(8, 1)]
    assert candidate_topologies(2, 8) == [
        Topology(1, 1), Topology(2, 1), Topology(2, 2), Topology(2, 4)]
    assert candidate_topologies(3, 4) == [
        Topology(1, 1), Topology(1, 2), Topology(1, 4)]


def test_planner_smallest_fit_wins():
    # ~1B weights fit one 16GB v5e chip — spreading it wider would halve
    # tokens/sec/chip for nothing
    plan = plan_topology("llama-1b", "v5e-8")
    assert plan.topology == Topology(1, 1)
    assert plan.rejected == ()
    assert plan.budget.fits


def test_planner_tp2_unlocks_one_chip_infeasible():
    # the ISSUE's motivating case: 8B bf16 (~16GB weights) cannot fit a
    # 16GB v5e chip with KV + headroom, but tp=2 halves per-chip weights
    # AND shards the KV head axis
    plan = plan_topology("llama3-8b", "v5e-8")
    assert plan.topology == Topology(2, 1)
    assert not hbm_budget("llama3-8b", "v5e-8", tp=1).fits
    # the rejection ledger carries the 1x1 arithmetic (the deploy log
    # that makes "why 2 chips?" answerable)
    (topo, required, have), = plan.rejected
    assert topo == Topology(1, 1) and required > have
    assert plan.as_dict()["rejected"][0]["n_chips"] == 1
    # same model on a 95GB v5p chip: one chip, no sharding tax
    assert plan_topology("llama3-8b", "v5p-8").topology == Topology(1, 1)


def test_planner_infeasible_raises_with_arithmetic():
    # 70B bf16 needs ~17.6GB/chip of weights alone at tp=8 on v5e —
    # reject with the largest candidate's numbers and remedies, never an
    # OOM at bind time
    with pytest.raises(InfeasibleDeployment, match="int8"):
        plan_topology("llama3-70b", "v5e-8")
    # ...and the remedy it names actually works: int8 weights + int8 KV
    # make the same slice feasible (at the full 8 chips)
    plan = plan_topology("llama3-70b", "v5e-8", quantize="int8",
                         kv_quant=True)
    assert plan.topology == Topology(8, 1)
    assert len(plan.rejected) == 3


def test_budget_prices_fsdp_weight_only():
    # fsdp shards weights only: per-chip weight cost divides by tp*fsdp,
    # KV stays divided by the tp head shard alone
    tp2 = hbm_budget("llama3-8b", "v5e-8", tp=2, fsdp=1)
    tp2f2 = hbm_budget("llama3-8b", "v5e-8", tp=2, fsdp=2)
    assert tp2f2.weight_gb_per_chip == pytest.approx(
        tp2.weight_gb_per_chip / 2)
    assert tp2f2.kv_gb_per_chip == pytest.approx(tp2.kv_gb_per_chip)
    assert tp2f2.as_dict()["fsdp"] == 2


# ---------------------------------------------------------------------------
# policy objects
# ---------------------------------------------------------------------------

def test_make_policy_identity_for_1x1():
    """1x1 is the single-device engine VERBATIM: every hook is the
    identity, so no sharding machinery gets near the traced graphs."""
    pol = make_policy(None)
    assert isinstance(pol, SingleDevicePolicy)
    assert not isinstance(pol, MeshPolicy)
    assert make_policy("1x1").__class__ is SingleDevicePolicy
    x = jnp.arange(8.0)
    tree = {"k": x}
    assert pol.place_params({"w": x})["w"] is x
    assert pol.place_kv(tree)["k"] is x
    assert pol.constrain_kv(tree)["k"] is x
    assert pol.describe() == {"tp": 1, "fsdp": 1, "n_chips": 1}


def test_make_policy_rejects_oversubscribed_mesh():
    with pytest.raises(ValueError, match="devices"):
        make_policy("4x4")  # 16 > the 8 forced host devices


# ---------------------------------------------------------------------------
# sharded engine (multichip tier: forced 8-device CPU mesh)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def tiny_params():
    return init_decoder(jax.random.PRNGKey(0), TINY)


def _engine(params, topology=None, **kw):
    base = dict(max_batch=2, max_seq_len=256, prefill_buckets=(32, 64),
                decode_steps=(1, 4), kv_block_size=32, kv_pool_blocks=16,
                prefill_chunk=32)
    base.update(kw)
    policy = make_policy(topology)
    return InferenceEngine(policy.place_params(params), TINY,
                           EngineConfig(**base), policy=policy)


def _run(coro):
    return asyncio.run(coro)


def _generate_all(engine, jobs):
    async def go():
        await engine.start()
        outs = await asyncio.gather(*[
            engine.generate(list(p), max_new_tokens=n) for p, n in jobs])
        await engine.stop()
        return outs

    return _run(go())


JOBS = ([3, 1, 4, 1, 5, 9, 2, 6], 12), (list(range(2, 40)), 8)
CYCLER = [7, 8, 9, 7, 8, 9, 7, 8]


def _margin_vs_oracle(params, prompt, prefix, tok) -> float:
    logits = decoder_forward(
        params, jnp.asarray([list(prompt) + prefix], jnp.int32), TINY)[0, -1]
    return float(jnp.max(logits) - logits[tok])


def _assert_parity(params, jobs, ref_outs, outs):
    """Token-for-token equality, with any fork judged against the
    full-context oracle's argmax margin (the bench parity rule the quant
    and spec suites established — sharded reductions may reassociate)."""
    for (prompt, _), a, b in zip(jobs, ref_outs, outs):
        assert len(a) == len(b)
        for i, (x, y) in enumerate(zip(a, b)):
            if x != y:
                margin = _margin_vs_oracle(params, prompt, b[:i], y)
                assert margin < 0.35, (i, margin)
                break


@pytest.mark.multichip
def test_tp2_greedy_parity(tiny_params):
    """The tentpole gate: a tp=2 sharded paged engine must match the
    single-device paged engine token-for-token (llama-tiny has 2 KV
    heads — each chip holds exactly one head's KV)."""
    ref = _generate_all(_engine(tiny_params), JOBS)
    eng = _engine(tiny_params, topology="2x1")
    assert isinstance(eng.policy, MeshPolicy)
    _assert_parity(tiny_params, JOBS, ref, _generate_all(eng, JOBS))


@pytest.mark.multichip
def test_tp2xfsdp2_greedy_parity(tiny_params):
    """tp×fsdp submesh (4 chips): fsdp shards weights on top of tp; the
    outputs must not notice."""
    ref = _generate_all(_engine(tiny_params), JOBS)
    eng = _engine(tiny_params, topology="2x2")
    assert eng.policy.mesh.devices.size == 4
    _assert_parity(tiny_params, JOBS, ref, _generate_all(eng, JOBS))


@pytest.mark.multichip
def test_tp2_weights_and_kv_actually_sharded(tiny_params):
    """Not just parity — the layout must really shard: a tp-partitioned
    weight's per-device shard is half the global array, and the KV pool's
    head axis carries the tp mesh axis."""
    eng = _engine(tiny_params, topology="2x1")
    wo = eng.params["layers"][0]["wo"]    # row-parallel [H*Dh, dim]
    shard = wo.addressable_shards[0].data
    assert shard.shape[0] == wo.shape[0] // 2
    for name in ("k", "v"):
        spec = eng.kv_cache[name].sharding.spec
        assert spec[-2] == "tp", (name, spec)
    # the block table stays replicated — host-side block ids are global
    assert all(s is None for s in eng.kv_cache["table"].sharding.spec)


@pytest.mark.multichip
def test_engine_places_raw_params_through_policy(tiny_params):
    """The constructor itself routes weights through the policy: a mesh
    engine handed RAW host params must not serve replicated weights (the
    silent failure mode where XLA implicitly places them at first
    dispatch and every chip holds the full model)."""
    eng = InferenceEngine(tiny_params, TINY, EngineConfig(
        max_batch=2, max_seq_len=256, prefill_buckets=(32,),
        kv_block_size=32, kv_pool_blocks=16, prefill_chunk=32),
        policy=make_policy("2x1"))
    wo = eng.params["layers"][0]["wo"]
    assert wo.addressable_shards[0].data.shape[0] == wo.shape[0] // 2


@pytest.mark.multichip
def test_non_dividing_tp_rejected_at_bind(tiny_params):
    """tp must divide the KV heads: fit_spec would silently REPLICATE the
    head axis (all the HBM, none of the capacity) while feasibility
    priced the gcd shard — the engine must refuse loudly at bind time."""
    with pytest.raises(ValueError, match="n_kv_heads"):
        InferenceEngine(tiny_params, TINY, EngineConfig(
            max_batch=2, max_seq_len=256, prefill_buckets=(32,),
            kv_block_size=32, kv_pool_blocks=16, prefill_chunk=32),
            policy=make_policy("3x1"))   # llama-tiny has 2 KV heads


@pytest.mark.multichip
def test_sharded_spec_verify_parity(tiny_params):
    """Speculative decoding under tp=2: the sharded verify graph's
    accept/rollback must leave outputs identical to sharded classic
    decode (exact at f32 — decode and verify share the head shard). The
    cyclic prompt guarantees prompt-lookup actually proposes, so the
    verify graph really dispatches on the mesh."""
    jobs = (CYCLER, 64), (list(range(2, 40)), 8)
    classic = _generate_all(_engine(tiny_params, topology="2x1"), jobs)
    spec_eng = _engine(tiny_params, topology="2x1", spec_len=4)
    outs = _generate_all(spec_eng, jobs)
    assert classic == outs
    assert spec_eng._stats["spec_proposed"] > 0


@pytest.mark.multichip
def test_sharded_int8_kv_parity(tiny_params):
    """int8 paged KV on a tp=2 submesh: scale planes shard with the
    payload head axis, and outputs stay within KV-quantization noise of
    the single-device int8 engine."""
    ref = _generate_all(_engine(tiny_params, kv_quant="int8"), JOBS)
    eng = _engine(tiny_params, topology="2x1", kv_quant="int8")
    assert eng.kv_cache["k_scale"].sharding.spec[-1] == "tp"
    _assert_parity(tiny_params, JOBS, ref, _generate_all(eng, JOBS))


@pytest.mark.multichip
def test_sharded_paged_kv_alloc_evict_prefix_reuse(tiny_params):
    """The host-side pool machinery is topology-oblivious: allocation,
    prefix-cache reuse and eviction run the same global-block-id
    arithmetic under tp=2, and reused KV decodes correctly."""
    prefix = [(i * 5) % 200 + 1 for i in range(128)]
    cold = _engine(tiny_params, prefix_cache_blocks=0)
    warm = _engine(tiny_params, topology="2x1", prefix_cache_blocks=4)

    async def run(engine):
        await engine.start()
        a = await engine.generate(prefix + [7, 7, 7], max_new_tokens=5)
        b = await engine.generate(prefix + [9, 9, 9], max_new_tokens=5)
        await engine.stop()
        return a, b

    assert _run(run(cold)) == _run(run(warm))
    st = warm.prefix_cache.stats()
    assert st["hits"] >= 1
    assert st["tokens_reused"] >= 96
    # blocks all returned on retirement: only the trash block and the
    # prefix cache's retained blocks stay allocated
    held = warm.allocator.used_count - 1      # minus the trash block
    assert held <= st["held_blocks"], (held, st)
    # force eviction: a second DIFFERENT 4-block prefix overflows the
    # 4-block cache budget, so the LRU entry must give its blocks up
    other = [(i * 7) % 190 + 3 for i in range(128)]

    async def one(engine, prompt):
        await engine.start()
        out = await engine.generate(list(prompt), max_new_tokens=4)
        await engine.stop()
        return out

    _run(one(warm, other))
    assert warm.prefix_cache.evictions >= 1


@pytest.mark.multichip
def test_sharded_engine_stats_topology(tiny_params):
    """Satellite 1's replica-side contract: stats() carries flat topology
    scalars for the heartbeat; a 1x1 engine reports tp=1 (single chip !=
    not reporting)."""
    eng = _engine(tiny_params, topology="2x1")
    st = eng.stats()
    assert (st["topo_tp"], st["topo_fsdp"], st["topo_n_chips"]) == (2, 1, 2)
    assert "hbm_used_gb_per_chip" in st
    ref = _engine(tiny_params)
    st1 = ref.stats()
    assert (st1["topo_tp"], st1["topo_n_chips"]) == (1, 1)


@pytest.mark.multichip
def test_load_engine_topology_knob(monkeypatch):
    """The preset front door: load_engine(topology=...) builds a sharded
    engine; TPU9_TOPOLOGY overrides when the arg is absent."""
    from tpu9.serving.presets import load_engine

    async def drive(engine):
        await engine.start()
        out = await engine.generate([5, 6, 7], max_new_tokens=3)
        await engine.stop()
        return out

    eng = load_engine("llama-tiny", max_batch=2, max_seq_len=256,
                      topology="2x1")
    assert eng.policy.topology == Topology(2, 1)
    assert len(_run(drive(eng))) == 3
    monkeypatch.setenv("TPU9_TOPOLOGY", "2x1")
    eng2 = load_engine("llama-tiny", max_batch=2, max_seq_len=256)
    assert eng2.policy.topology == Topology(2, 1)
