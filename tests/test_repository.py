from tpu9.repository import ContainerRepository, TaskRepository, WorkerRepository
from tpu9.statestore import MemoryStore
from tpu9.types import (ContainerRequest, ContainerState, ContainerStatus,
                        TaskMessage, WorkerState, WorkerStatus)


def make_worker(worker_id="w1", chips=8, pool="default"):
    return WorkerState(
        worker_id=worker_id, pool=pool, status=WorkerStatus.AVAILABLE.value,
        total_cpu_millicores=8000, total_memory_mb=32768,
        free_cpu_millicores=8000, free_memory_mb=32768,
        tpu_generation="v5e" if chips else "", tpu_chip_count=chips,
        tpu_free_chips=chips, address="127.0.0.1:1000")


async def test_worker_register_capacity():
    repo = WorkerRepository(MemoryStore(), keepalive_ttl_s=5)
    await repo.register(make_worker())
    w = await repo.get("w1")
    assert w.tpu_free_chips == 8
    assert await repo.is_alive("w1")

    assert await repo.adjust_capacity("w1", cpu_millicores=-2000, tpu_chips=-8)
    w = await repo.get("w1")
    assert w.free_cpu_millicores == 6000 and w.tpu_free_chips == 0
    # over-release clamps at totals
    assert await repo.adjust_capacity("w1", tpu_chips=8)
    assert not await repo.adjust_capacity("w1", tpu_chips=-9)  # insufficient
    assert (await repo.get("w1")).tpu_free_chips == 8

    workers = await repo.list(alive_only=True)
    assert [x.worker_id for x in workers] == ["w1"]
    await repo.deregister("w1")
    assert await repo.get("w1") is None


async def test_worker_request_stream():
    repo = WorkerRepository(MemoryStore())
    await repo.register(make_worker())
    req = ContainerRequest(container_id="c1", stub_id="s1", tpu="v5e-8")
    await repo.push_request("w1", req)
    got = await repo.read_requests("w1", last_id="0", timeout=0.2)
    assert len(got) == 1
    entry_id, r = got[0]
    assert r.container_id == "c1" and r.tpu_spec().chips == 8
    assert await repo.read_requests("w1", last_id=entry_id, timeout=0.05) == []
    assert await repo.worker_container_ids("w1") == ["c1"]


async def test_container_state_and_discovery():
    repo = ContainerRepository(MemoryStore())
    st = ContainerState(container_id="c1", stub_id="s1",
                        status=ContainerStatus.RUNNING.value)
    await repo.update_state(st)
    await repo.set_address("c1", "127.0.0.1:9000")
    found = await repo.containers_by_stub("s1", status=ContainerStatus.RUNNING.value)
    assert len(found) == 1
    assert await repo.get_address("c1") == "127.0.0.1:9000"

    st.status = ContainerStatus.STOPPED.value
    await repo.update_state(st)
    assert await repo.containers_by_stub("s1") == []


async def test_request_tokens():
    repo = ContainerRepository(MemoryStore())
    assert await repo.acquire_request_token("s1", "c1", limit=2)
    assert await repo.acquire_request_token("s1", "c1", limit=2)
    assert not await repo.acquire_request_token("s1", "c1", limit=2)
    await repo.release_request_token("s1", "c1")
    assert await repo.acquire_request_token("s1", "c1", limit=2)
    assert await repo.in_flight("s1", "c1") == 2


async def test_task_repo_flow():
    repo = TaskRepository(MemoryStore())
    msg = TaskMessage(task_id="t1", stub_id="s1", workspace_id="w1",
                      executor="taskqueue", handler_args=[1])
    await repo.put_message(msg)
    await repo.enqueue("w1", "s1", "t1")
    assert await repo.queue_depth("w1", "s1") == 1
    assert await repo.tasks_in_flight("s1") == 1

    task_id = await repo.dequeue("w1", "s1")
    assert task_id == "t1"
    await repo.claim("c1", "t1", 123.0)
    assert "t1" in await repo.claims("c1")

    await repo.set_status("t1", "complete")
    assert await repo.tasks_in_flight("s1") == 0
    await repo.store_result("t1", {"ok": True})
    assert (await repo.get_result("t1"))["ok"] is True
    await repo.unclaim("c1", "t1")
    assert await repo.claims("c1") == {}
