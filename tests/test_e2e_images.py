"""E2E: image build → lazy pull through worker cache → container uses the
image env."""

import asyncio
import filecmp
import os

import pytest

from tpu9.testing.localstack import LocalStack

pytestmark = pytest.mark.e2e

USES_IMAGE = """
import os
def handler(**kwargs):
    marker = open(os.environ["MARKER_PATH"]).read().strip()
    return {"marker": marker, "imgvar": os.environ.get("IMGVAR", "")}
"""


async def build_image(stack, spec, timeout_s=20.0):
    status, out = await stack.api("POST", "/rpc/image/build", json_body=spec)
    assert status == 200, out
    image_id = out["image_id"]
    for _ in range(int(timeout_s * 10)):
        _, st = await stack.api("GET", f"/rpc/image/status/{image_id}")
        if st["status"] in ("ready", "failed"):
            break
        await asyncio.sleep(0.1)
    assert st["status"] == "ready", st
    return image_id


async def test_endpoint_with_built_image():
    async with LocalStack() as stack:
        image_id = await build_image(stack, {
            "commands": ["mkdir -p env && echo from-image > env/marker.txt"],
            "env": {"IMGVAR": "42"}})
        # bundles materialize at a deterministic per-stack path
        marker = os.path.join(stack.cfg.cache.data_dir, "bundles", image_id,
                              "env", "marker.txt")
        dep = await stack.deploy_endpoint(
            "imaged", {"app.py": USES_IMAGE}, "app:handler",
            config_extra={"runtime": {"image_id": image_id,
                                      "cpu_millicores": 1000,
                                      "memory_mb": 1024},
                          "env": {"MARKER_PATH": marker}})
        result = await stack.invoke(dep, {})
        assert result["marker"] == "from-image"
        assert result["imgvar"] == "42"        # image env reached container


async def test_image_chunks_served_via_cache_peers():
    """Second worker pulls the image with chunks flowing from the first
    worker's chunk server (peer path), not the registry."""
    async with LocalStack() as stack:
        image_id = await build_image(stack, {
            "commands":
                ["mkdir -p env && head -c 3000000 /dev/urandom > env/blob.bin"]})
        w1 = await stack._worker_factory()
        manifest = await stack._manifest_fetch(image_id)
        # give each worker a private bundle dir so both actually pull
        w1.cache.puller.bundles_dir = os.path.join(stack.tmp.name, "b1")
        os.makedirs(w1.cache.puller.bundles_dir, exist_ok=True)

        b1 = await w1.cache.puller.pull(image_id, manifest=manifest)
        assert w1.cache.client.stats["source_fetches"] > 0
        # w2 joins only now: had it been registered during w1's pull, w1's
        # source fetch would asynchronously seed the canonical HRW holder
        # (often w2), turning w2's read into a local hit at random
        w2 = await stack._worker_factory()
        w2.cache.puller.bundles_dir = os.path.join(stack.tmp.name, "b2")
        os.makedirs(w2.cache.puller.bundles_dir, exist_ok=True)
        b2 = await w2.cache.puller.pull(image_id, manifest=manifest)
        assert w2.cache.client.stats["peer_hits"] > 0, w2.cache.client.stats
        assert filecmp.cmp(os.path.join(b1, "env", "blob.bin"),
                           os.path.join(b2, "env", "blob.bin"),
                           shallow=False)


LAZY_APP = """
import hashlib, os

def handler(op="", **kwargs):
    blob = os.environ["BLOB_PATH"]
    if op == "read":
        data = open(blob, "rb").read()       # gated by t9lazy_preload.so
        return {"sha": hashlib.sha256(data).hexdigest(), "n": len(data)}
    # readiness probe path: stat only — must not block on the fill
    return {"size": os.path.getsize(blob)}
"""


async def test_lazy_image_container_starts_before_fill(tmp_path):
    """VERDICT r03 #3 e2e: with a lazy image, container.ready precedes full
    materialization, and an on-demand open of a streamed file returns
    correct bytes through the shim gate."""
    import hashlib
    import shutil
    shim = os.path.join(os.path.dirname(__file__), "..", "native", "build",
                        "t9lazy_preload.so")
    if not os.path.exists(shim):
        pytest.skip("t9lazy_preload.so not built")

    async with LocalStack() as stack:
        # workers are pool-created on demand and read cfg.cache at
        # construction — lower the threshold BEFORE the first schedule
        stack.cfg.cache.lazy_threshold_mb = 8
        image_id = await build_image(stack, {
            "commands": ["mkdir -p env && for i in 1 2 3 4 5 6; do "
                         "head -c 2097152 /dev/urandom > env/f$i.bin; done"],
        }, timeout_s=60)
        bundle = os.path.join(stack.cfg.cache.data_dir, "bundles", image_id)
        blob = os.path.join(bundle, "env", "f3.bin")

        # force a cold pull (the build may have materialized on this host)
        shutil.rmtree(bundle, ignore_errors=True)

        dep = await stack.deploy_endpoint(
            "lazy-imaged", {"app.py": LAZY_APP}, "app:handler",
            config_extra={"runtime": {"image_id": image_id,
                                      "cpu_millicores": 500,
                                      "memory_mb": 512},
                          "env": {"BLOB_PATH": blob}})
        first = await stack.invoke(dep, {})
        ready_before_complete = not os.path.exists(
            os.path.join(bundle, ".tpu9-complete"))
        assert first["size"] == 2097152, first

        # on-demand faulted read returns REAL bytes, not placeholder zeros
        read = await stack.invoke(dep, {"op": "read"})
        manifest = await stack._manifest_fetch(image_id)
        entry = next(e for e in manifest.files if e.path == "env/f3.bin")
        worker = stack.workers[0]
        want = hashlib.sha256(b"".join(
            [await worker.cache.client.get(c) for c in entry.chunks]
        )).hexdigest()
        assert read["sha"] == want

        # the container may land on any pool worker — find the one whose
        # puller ran the lazy fill
        fill = next((w.cache.puller._fills[image_id] for w in stack.workers
                     if image_id in w.cache.puller._fills), None)
        assert fill is not None, "pull did not go through the lazy path"
        import asyncio as aio
        await aio.wait_for(fill.wait(), 60)
        assert os.path.exists(os.path.join(bundle, ".tpu9-complete"))
        # whether readiness beat the 12 MB fill is host-speed dependent;
        # the strict GB-scale ready-before-complete guarantee lives in
        # bench.py's coldstart_native phase. Here: the fill really
        # streamed the payload.
        assert fill.stats["bytes_streamed"] >= 12 * 2**20
        del ready_before_complete
