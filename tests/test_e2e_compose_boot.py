"""Boot the SHIPPED deploy artifacts end-to-end (VERDICT r04 #4).

Reference analogue: the reference's ``make setup`` k3d cluster + SDK-driven
e2e (``/root/reference/Makefile:16-20``, ``e2e/build_tests/app.py``). Two
tiers:

- with docker: build deploy/docker images and run deploy/compose.yaml
  verbatim (skipped when docker is absent — this CI image has none);
- without docker: boot the exact service COMMANDS, configs, and env that
  compose.yaml + deploy/docker/Dockerfile declare, as host processes —
  the artifact wiring (entrypoints, flags, config files, port topology,
  token handoff) is what rots, and it is fully exercised here.

Both deploy ``examples/01_cpu_classifier.py`` through the real CLI and
invoke it over HTTP.
"""

import json
import os
import shutil
import socket
import subprocess
import sys
import time
import urllib.request

import pytest
import yaml

pytestmark = pytest.mark.e2e

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
COMPOSE = os.path.join(REPO, "deploy", "compose.yaml")


def _docker_ok() -> bool:
    try:
        return subprocess.run(["docker", "info"], capture_output=True,
                              timeout=10).returncode == 0
    except (OSError, subprocess.TimeoutExpired):
        return False


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _wait_http(url: str, timeout_s: float = 60.0) -> None:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            urllib.request.urlopen(url, timeout=2)
            return
        except Exception:
            time.sleep(0.3)
    raise TimeoutError(f"{url} never came up")


def _deploy_and_invoke(gateway_url: str, token: str, tmp_path) -> dict:
    """The SDK-driven half: real CLI deploy of example 01, HTTP invoke."""
    proj = tmp_path / "proj"
    proj.mkdir(exist_ok=True)
    shutil.copy(os.path.join(REPO, "examples", "01_cpu_classifier.py"),
                proj / "app01.py")
    env = {**os.environ, "PYTHONPATH": REPO, "JAX_PLATFORMS": "cpu",
           "TPU9_GATEWAY_URL": gateway_url, "TPU9_TOKEN": token}
    out = subprocess.run(
        [sys.executable, "-m", "tpu9.cli.main", "deploy",
         "app01.py:classify", "--name", "sentiment"],
        cwd=proj, env=env, capture_output=True, text=True, timeout=180)
    assert out.returncode == 0, out.stderr[-800:]
    req = urllib.request.Request(
        f"{gateway_url}/endpoint/sentiment",
        data=json.dumps({"text": "tpu9 is great"}).encode(),
        headers={"Authorization": f"Bearer {token}",
                 "Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=180) as resp:
        return json.loads(resp.read())


@pytest.mark.slow
def test_compose_service_commands_boot_without_docker(tmp_path):
    """Run the compose topology's commands as host processes: gateway with
    the shipped config (ports/db redirected to the sandbox), worker with
    compose.yaml's exact argument list and environment, token handed off
    the way the compose comments prescribe."""
    with open(COMPOSE) as f:
        compose = yaml.safe_load(f)
    services = compose["services"]

    # gateway: ENTRYPOINT ["tpu9","gateway"] + command ["--config", ...];
    # the shipped config pins port 1993 and /var/lib — redirect both into
    # the sandbox, keeping every other shipped default
    assert services["gateway"]["command"][0] == "--config"
    with open(os.path.join(REPO, "deploy", "local", "gateway.yaml")) as f:
        gw_cfg = yaml.safe_load(f)
    http_port, state_port = _free_port(), _free_port()
    gw_cfg["gateway"]["http_port"] = http_port
    gw_cfg["gateway"]["state_port"] = state_port
    gw_cfg["database"]["path"] = str(tmp_path / "gateway.db")
    cfg_path = tmp_path / "gateway.yaml"
    cfg_path.write_text(yaml.safe_dump(gw_cfg))

    env = {**os.environ, "PYTHONPATH": REPO, "JAX_PLATFORMS": "cpu"}
    procs = []
    try:
        gw = subprocess.Popen(
            [sys.executable, "-m", "tpu9.cli.main", "gateway",
             "--config", str(cfg_path)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        procs.append(gw)
        gateway_url = f"http://127.0.0.1:{http_port}"
        _wait_http(f"{gateway_url}/health")
        token = worker_token = ""
        deadline = time.monotonic() + 30
        boot_log = []
        while time.monotonic() < deadline and not (token and worker_token):
            line = gw.stdout.readline()
            boot_log.append(line)
            if line.startswith("token:"):
                token = line.split()[1]
            elif line.startswith("worker-token:"):
                worker_token = line.split()[1]
        assert token and worker_token, "".join(boot_log)

        # worker: compose's exact argv with the service-DNS name resolved
        # the way compose would resolve it, plus compose's environment
        # block (TPU9_TOKEN comes from the gateway boot log, per the
        # compose file's own ${TPU9_WORKER_TOKEN:?...} contract)
        wk_cmd = [str(a).replace("gateway:1994", f"127.0.0.1:{state_port}")
                  .replace("http://gateway:1993", gateway_url)
                  for a in services["worker"]["command"]]
        wk_env = dict(env)
        for k, v in services["worker"].get("environment", {}).items():
            wk_env[k] = worker_token if "TPU9_WORKER_TOKEN" in str(v) \
                else str(v)
        wk = subprocess.Popen(
            [sys.executable, "-m", "tpu9.cli.main", "worker", *wk_cmd,
             "--token", wk_env.pop("TPU9_TOKEN")],
            env=wk_env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)
        procs.append(wk)

        out = _deploy_and_invoke(gateway_url, token, tmp_path)
        assert "label" in json.dumps(out), out
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


@pytest.mark.slow
@pytest.mark.skipif(not _docker_ok(), reason="docker not available")
def test_compose_boot_with_docker(tmp_path):
    """The full shipped path: build the images, boot compose.yaml
    verbatim, deploy+invoke through the published port."""
    env = {**os.environ}
    up = None
    try:
        # gateway first (worker needs its boot-log token)
        subprocess.run(
            ["docker", "compose", "-f", COMPOSE, "up", "--build", "-d",
             "gateway"], cwd=REPO, env=env, check=True, timeout=1800)
        deadline = time.monotonic() + 120
        token = worker_token = ""
        while time.monotonic() < deadline and not (token and worker_token):
            logs = subprocess.run(
                ["docker", "compose", "-f", COMPOSE, "logs", "gateway"],
                cwd=REPO, capture_output=True, text=True).stdout
            for line in logs.splitlines():
                if "token:" in line and "worker-token:" not in line:
                    token = line.split()[-1]
                if "worker-token:" in line:
                    worker_token = line.split()[-1]
            time.sleep(2)
        assert token and worker_token
        env["TPU9_WORKER_TOKEN"] = worker_token
        up = subprocess.run(
            ["docker", "compose", "-f", COMPOSE, "up", "-d", "worker"],
            cwd=REPO, env=env, check=True, timeout=600)
        out = _deploy_and_invoke("http://127.0.0.1:1993", token, tmp_path)
        assert "label" in json.dumps(out), out
    finally:
        subprocess.run(["docker", "compose", "-f", COMPOSE, "down", "-v"],
                       cwd=REPO, env=env, capture_output=True, timeout=300)
