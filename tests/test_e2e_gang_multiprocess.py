"""The multi-host contract, for real: TWO separate worker processes (not
in-process objects) joined over the state server, one v5p-8 gang scheduled
across them, and each runner calling ``jax.distributed.initialize()`` off
the gang env on the CPU backend. Both ranks must observe the GLOBAL device
count — this is what proves the rank/coordinator wiring tpu9 injects
(tpu_manager._env_for) actually drives a jax.distributed cluster, which no
amount of in-process gang testing can show."""

import asyncio
import json
import os
import subprocess
import sys
import time

import aiohttp
import pytest

from tpu9.backend import BackendDB
from tpu9.config import AppConfig
from tpu9.gateway import Gateway
from tpu9.statestore import MemoryStore
from tpu9.types import ContainerRequest, ContainerStatus

pytestmark = pytest.mark.e2e

# jax.distributed.initialize must run before the backend is initialized, so
# it happens at handler-module import inside the runner container process.
DIST_HANDLER = """
import os
from tpu9.utils import force_cpu
force_cpu()
import jax
jax.distributed.initialize(
    coordinator_address=os.environ["TPU9_COORDINATOR_ADDR"],
    num_processes=int(os.environ["TPU9_GANG_SIZE"]),
    process_id=int(os.environ["TPU9_GANG_RANK"]))
# backend init (the first device query) performs the cluster-wide device
# exchange and blocks until EVERY process reaches it — do it at import so
# both ranks rendezvous during container start, not inside one handler call
GLOBAL_DEVICES = jax.device_count()
LOCAL_DEVICES = jax.local_device_count()

# ONE REAL pjit TRAIN STEP over the global (multi-process) mesh — the actual
# multi-host training contract: every rank participates in the same SPMD
# program, gradients reduce across processes over the jax.distributed
# cluster. Runs at import so both ranks enter the collective together.
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

_mesh = Mesh(jax.devices(), ("fsdp",))          # all global devices
_W = jax.device_put(jnp.ones((GLOBAL_DEVICES, 4), jnp.float32),
                    NamedSharding(_mesh, P("fsdp", None)))
_X = jax.device_put(jnp.full((GLOBAL_DEVICES, 4), 2.0, jnp.float32),
                    NamedSharding(_mesh, P("fsdp", None)))

@jax.jit
def _step(w, x):
    loss = jnp.mean((w * x - 1.0) ** 2)
    grad = jax.grad(lambda w: jnp.mean((w * x - 1.0) ** 2))(w)
    return loss, w - 0.1 * grad

_loss, _W2 = _step(_W, _X)
TRAIN_LOSS = float(_loss)                        # implicit cross-process psum
TRAIN_W_MEAN = float(jnp.mean(_W2))

def handler(**kw):
    return {
        "rank": int(os.environ["TPU9_GANG_RANK"]),
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "global_devices": GLOBAL_DEVICES,
        "local_devices": LOCAL_DEVICES,
        "train_loss": TRAIN_LOSS,
        "train_w_mean": TRAIN_W_MEAN,
    }
"""


async def _wait(predicate, timeout=90.0, interval=0.2, what=""):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        out = await predicate()
        if out:
            return out
        await asyncio.sleep(interval)
    raise TimeoutError(f"timed out waiting for {what}")


async def test_two_process_gang_jax_distributed(tmp_path):
    cfg = AppConfig()
    cfg.gateway.http_port = 0
    cfg.gateway.state_port = -1          # any free port: workers join remotely
    cfg.database.path = ":memory:"
    cfg.storage.local_root = str(tmp_path / "ws")
    cfg.worker.containers_dir = str(tmp_path / "containers")
    cfg.scheduler.loop_interval_s = 0.02

    gw = Gateway(cfg, store=MemoryStore())
    await gw.start()
    procs: list[subprocess.Popen] = []
    session = aiohttp.ClientSession(headers={
        "Authorization": f"Bearer {gw.default_token}"})
    try:
        state_addr = gw.state_server.address
        base = f"http://127.0.0.1:{gw.port}"
        for rank in range(2):
            env = dict(os.environ)
            env["TPU9_FAKE_TPU_CHIPS"] = "4"
            env["PYTHONPATH"] = "/root/repo"
            env["JAX_PLATFORMS"] = "cpu"
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "tpu9.cli.main", "worker",
                 "--gateway-state", state_addr,
                 "--gateway-url", base,
                 "--token", gw.worker_token,
                 "--tpu", "v5p",
                 "--slice-id", "slice-mp",
                 "--slice-rank", str(rank),
                 "--slice-hosts", "2"],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True))

        async def workers_up():
            ws = await gw.workers.list()
            return ws if len(ws) >= 2 else None

        await _wait(workers_up, what="2 workers to register")

        # upload the distributed handler workspace
        import zipfile
        zpath = tmp_path / "ws.zip"
        with zipfile.ZipFile(zpath, "w") as z:
            z.writestr("app.py", DIST_HANDLER)
        async with session.post(f"{base}/rpc/object/put",
                                data=zpath.read_bytes()) as resp:
            assert resp.status == 200
            object_id = (await resp.json())["object_id"]

        async with session.post(f"{base}/rpc/stub/get-or-create", json={
            "name": "distfn", "stub_type": "endpoint",
            "config": {"handler": "app:handler",
                       "keep_warm_seconds": 30.0, "timeout_s": 120.0,
                       "runtime": {"tpu": "v5p-8", "cpu_millicores": 500,
                                   "memory_mb": 1024}},
            "object_id": object_id}) as resp:
            assert resp.status == 200
            stub_id = (await resp.json())["stub_id"]

        req = ContainerRequest(
            stub_id=stub_id,
            workspace_id=gw.default_workspace.workspace_id,
            stub_type="endpoint", cpu_millicores=500, memory_mb=1024,
            tpu="v5p-8", object_id=object_id,
            env={"TPU9_HANDLER": "app:handler", "TPU9_STUB_TYPE": "endpoint",
                 "TPU9_CONCURRENT_REQUESTS": "1", "TPU9_WORKERS": "1",
                 "TPU9_TIMEOUT_S": "120"})
        await gw.scheduler.run(req)

        async def both_running():
            states = await gw.containers.containers_by_stub(
                stub_id, status=ContainerStatus.RUNNING.value)
            return states if len(states) == 2 else None

        # jax import + distributed rendezvous inside each runner is slow
        states = await _wait(both_running, timeout=180.0,
                             what="both gang members RUNNING")

        results = []
        for s in states:
            async with session.post(f"http://{s.address}/", json={}) as resp:
                assert resp.status == 200, await resp.text()
                results.append(await resp.json())

        ranks = sorted(r["rank"] for r in results)
        assert ranks == [0, 1]
        for r in results:
            assert r["process_count"] == 2, r
            assert r["process_index"] == r["rank"], r
            # THE multi-host assertion: each process sees every process's
            # devices through the jax.distributed cluster, not just its own
            assert r["global_devices"] == 2 * r["local_devices"], r
        # the pjit step ran as ONE SPMD program: both ranks computed the
        # same global loss over globally-sharded arrays (mean((1*2-1)^2)=1)
        losses = {round(r["train_loss"], 6) for r in results}
        assert losses == {1.0}, results
        w_means = {round(r["train_w_mean"], 6) for r in results}
        assert len(w_means) == 1, results
    finally:
        await session.close()
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
        await gw.stop()
