"""HBM feasibility math (VERDICT r03 #8): BASELINE.md config #4
(llama3-70b tensor-parallel on v5e-8) must be validated or rejected at
deploy time with pinned arithmetic — not discovered as a chip OOM.
"""

import asyncio

import pytest

from tpu9.serving.feasibility import (InfeasibleDeployment, hbm_budget,
                                      matmul_param_count,
                                      validate_llm_deployment, weight_bytes)
from tpu9.serving.presets import resolve_preset


def test_llama3_8b_param_arithmetic():
    cfg, quant = resolve_preset("llama3-8b-int8")
    assert quant
    mm = matmul_param_count(cfg)
    # llama3-8b: ~7.5B matmul params incl. lm_head (embeddings separate;
    # total 8.03B with the 128256×4096 embedding — the published count)
    assert 6.9e9 < mm < 7.7e9
    wb = weight_bytes(cfg, quantized=True)
    # int8 payload + scales + bf16 embeddings ≈ 8.6 GB
    assert 8.0e9 < wb < 8.9e9
    # bf16 weights alone ≈ 16.06 GB — with KV they can never fit a 16 GiB
    # v5e, the reason the flagship is int8 (VERDICT r03 accepted rationale)
    assert weight_bytes(cfg, quantized=False) > 15.9e9


def test_8b_int8_fits_v5e1():
    b = hbm_budget("llama3-8b-int8", "v5e-1", max_batch=8,
                   max_seq_len=2048)
    assert b.fits, b.as_dict()
    # pinned: ~8.1 GB weights + ~2.3 GB KV (8 kv heads × 128 × 32L × 8 ×
    # 2048 × 2 k/v × 2B) + scratch ≪ 16 GB
    assert 7.5 < b.weight_gb_per_chip < 8.7
    assert 1.9 < b.kv_gb_per_chip < 2.6


def test_8b_bf16_rejected_on_v5e1():
    with pytest.raises(InfeasibleDeployment, match="int8"):
        validate_llm_deployment("llama3-8b", "v5e-1", max_batch=8,
                                max_seq_len=2048)


def test_config4_llama70b_on_v5e8():
    """BASELINE.md config #4: the deploy-time verdict with pinned numbers.
    70B int8 over tp=8 → ~8.8 GB weights/chip; KV at batch 8 × seq 2048 is
    head-sharded over min(tp, 8 kv heads) = 8 → ~1.3 GB/chip. It FITS —
    and bf16 does not."""
    b = validate_llm_deployment("llama3-70b-int8", "v5e-8", max_batch=8,
                                max_seq_len=2048)
    assert b.fits
    assert 8.0 < b.weight_gb_per_chip < 9.6, b.as_dict()
    assert b.kv_gb_per_chip < 2.0
    with pytest.raises(InfeasibleDeployment):
        validate_llm_deployment("llama3-70b", "v5e-8", max_batch=8,
                                max_seq_len=2048)


def test_kv_blowup_rejected():
    """Long-context KV at high batch must flip the verdict: the KV term,
    not the weights, is what breaks it (linear in batch × seq)."""
    ok = hbm_budget("llama3-8b-int8", "v5e-1", max_batch=8,
                    max_seq_len=2048)
    assert ok.fits
    with pytest.raises(InfeasibleDeployment):
        validate_llm_deployment("llama3-8b-int8", "v5e-1", max_batch=32,
                                max_seq_len=8192)


def test_deploy_gate_rejects_through_gateway():
    """The arithmetic runs at stub creation: an infeasible declarative LLM
    stub is a 400 with the budget in the message, a feasible one records
    its hbm_budget in config.extra."""
    from tpu9.testing.localstack import LocalStack

    async def run():
        async with LocalStack() as stack:
            status, out = await stack.api(
                "POST", "/rpc/stub/get-or-create", json_body={
                    "name": "llm-infeasible", "stub_type": "endpoint",
                    "config": {
                        "handler": "app:load",
                        "runtime": {"tpu": "v5e-1"},
                        "extra": {"runner": "llm", "model": "llama3-70b",
                                  "max_batch": 8, "max_seq_len": 2048}}})
            assert status == 400, out
            assert "GB" in out["error"]

            status, out = await stack.api(
                "POST", "/rpc/stub/get-or-create", json_body={
                    "name": "llm-feasible", "stub_type": "endpoint",
                    "config": {
                        "handler": "app:load",
                        "runtime": {"tpu": "v5e-1"},
                        "extra": {"runner": "llm",
                                  "model": "llama3-8b-int8"}}})
            assert status == 200, out
            return out

    out = asyncio.run(run())
    assert "stub_id" in out
