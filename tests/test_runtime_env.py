"""Control-plane env rewriting in NativeRuntime (no root needed).

Regression for the round-3 advisor finding: the outbound reverse proxy must
only be opened for worker-injected control-plane keys, never for
tenant-supplied TPU9_* env — otherwise a tenant could tunnel out of its
netns to arbitrary host-loopback ports (other tenants' port proxies,
worker internals), bypassing gateway auth.
"""

from tpu9.runtime.native import _rewrite_cp_env


def test_cp_keys_rewritten_and_proxied():
    env = {"TPU9_GATEWAY_URL": "http://127.0.0.1:8311",
           "TPU9_COORDINATOR_ADDR": "127.0.0.1:9411"}
    ports = _rewrite_cp_env(
        env, ["TPU9_GATEWAY_URL", "TPU9_COORDINATOR_ADDR"], "10.77.0.1")
    assert env["TPU9_GATEWAY_URL"] == "http://10.77.0.1:8311"
    assert env["TPU9_COORDINATOR_ADDR"] == "10.77.0.1:9411"
    assert ports == {8311, 9411}


def test_tenant_env_never_proxied():
    # A tenant smuggling a loopback URL under any key — including TPU9_-
    # prefixed ones it can legitimately set — gets no rewrite and no proxy.
    env = {"TPU9_EVIL": "http://127.0.0.1:6379",
           "TPU9_CHECKPOINT_ENABLED": "1",
           "MY_SERVICE": "http://127.0.0.1:5000"}
    ports = _rewrite_cp_env(
        env, ["TPU9_GATEWAY_URL", "TPU9_COORDINATOR_ADDR"], "10.77.0.1")
    assert ports == set()
    assert env["TPU9_EVIL"] == "http://127.0.0.1:6379"
    assert env["MY_SERVICE"] == "http://127.0.0.1:5000"


def test_missing_cp_key_is_ignored():
    env = {}
    assert _rewrite_cp_env(env, ["TPU9_GATEWAY_URL"], "10.0.0.1") == set()
    assert env == {}
