"""wirecheck (ISSUE 18): per-rule fixtures on a mini repo, the gate
round-trip, the contracts-vs-reality cross-check, and the repo gate
itself (this test IS the tier-1 wiring, next to test_lint.py /
test_graphcheck.py).

tpu9: wirecheck-fixture-corpus — the string literals below are seeded
violations and fixture routes/metrics, not uses of the real wire
surfaces; the scanner skips this file entirely.
"""

import ast
import json
import os
import sys
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "scripts"))

import wire_gate  # noqa: E402

from tpu9.analysis import tomlmini  # noqa: E402
from tpu9.analysis.findings import Baseline, load_baseline  # noqa: E402
from tpu9.analysis.wirecheck import run_wirecheck  # noqa: E402
from tpu9.analysis.wirecheck import extract as wex  # noqa: E402


# -- mini repo ---------------------------------------------------------------

CLEAN_CONTRACTS = """\
[surface.mini]
producers = ["tpu9/prod.py::Engine.stats::out"]
consumers = ["tpu9/cons.py::consume::out"]
fields = ["alpha", "beta"]

[metrics]
entity_labels = ["container"]
assert_ok = ["tpu9_mini_rss_mb: per-container gauge, scraped not asserted"]

[keys.mini_loc]
pattern = "mini:loc:*"
writers = ["tpu9/"]
ttl = "required"

[env.TPU9_MINI_FLAG]
readers = ["tpu9/env_use.py"]
"""


def _mini_repo(tmp_path):
    (tmp_path / "scripts").mkdir()
    pkg = tmp_path / "tpu9"
    pkg.mkdir()
    (pkg / "prod.py").write_text(textwrap.dedent("""\
        class Engine:
            def stats(self):
                out = {}
                out["alpha"] = 1
                out["beta"] = 2
                return out
    """))
    (pkg / "cons.py").write_text(textwrap.dedent("""\
        def consume(out):
            return out["alpha"] + out["beta"]
    """))
    (pkg / "metrics_use.py").write_text(textwrap.dedent("""\
        def sample(metrics, cid):
            metrics.set_gauge("tpu9_mini_rss_mb", 1.0, {"container": cid})
            metrics.inc("tpu9_mini_requests", 1)

        def forget(metrics, cid):
            metrics.remove_gauge("tpu9_mini_rss_mb", {"container": cid})
    """))
    (pkg / "store_use.py").write_text(textwrap.dedent("""\
        async def write(store, wid):
            await store.set(f"mini:loc:{wid}", "x", ttl=30)
    """))
    (pkg / "env_use.py").write_text(textwrap.dedent("""\
        import os

        def flag():
            return os.environ.get("TPU9_MINI_FLAG", "0")
    """))
    (pkg / "rpc_srv.py").write_text(textwrap.dedent("""\
        def routes(r, h):
            r.add_post("/rpc/mini/run", h)
    """))
    (pkg / "rpc_cli.py").write_text(textwrap.dedent("""\
        def call(c):
            return c.request("POST", "/rpc/mini/run")
    """))
    tdir = tmp_path / "tests"
    tdir.mkdir()
    (tdir / "test_mini.py").write_text(textwrap.dedent("""\
        def test_requests(snapshot):
            assert "tpu9_mini_requests" in snapshot
    """))
    (tmp_path / "contracts.toml").write_text(CLEAN_CONTRACTS)
    return tmp_path


def _check(root, **kw):
    return run_wirecheck(str(root),
                         contracts_path=str(root / "contracts.toml"), **kw)


def _gate(root, *extra):
    return wire_gate.main(["--repo-root", str(root),
                           "--contracts", "contracts.toml", *extra])


def test_mini_repo_is_clean(tmp_path):
    res = _check(_mini_repo(tmp_path))
    assert res.parse_errors == []
    assert res.findings == [], [f.format() for f in res.findings]
    assert res.warnings == []
    assert _gate(tmp_path) == 0


# -- one seeded violation per rule, each must redden the gate ----------------

def test_wir001_phantom_consumer(tmp_path):
    root = _mini_repo(tmp_path)
    (root / "tpu9" / "cons.py").write_text(textwrap.dedent("""\
        def consume(out):
            return out["alpha"] + out["gamma"]
    """))
    res = _check(root)
    assert any(f.rule == "WIR001" and f.symbol == "gamma"
               for f in res.findings)
    assert _gate(root) == 1


def test_wir001_producer_drift(tmp_path):
    """Renaming a produced field trips BOTH sides: the contract entry
    nothing produces and the undeclared new name."""
    root = _mini_repo(tmp_path)
    (root / "tpu9" / "prod.py").write_text(textwrap.dedent("""\
        class Engine:
            def stats(self):
                out = {}
                out["alpha"] = 1
                out["beta_renamed"] = 2
                return out
    """))
    res = _check(root)
    syms = {f.symbol for f in res.findings if f.rule == "WIR001"}
    assert "mini.beta" in syms          # contract rot
    assert "beta_renamed" in syms       # undeclared production
    assert "beta" in syms               # phantom consumer read
    assert _gate(root) == 1


def test_wir001_dead_telemetry_warns_not_gates(tmp_path):
    root = _mini_repo(tmp_path)
    (root / "tpu9" / "cons.py").write_text(textwrap.dedent("""\
        def consume(out):
            return out["alpha"]
    """))
    res = _check(root)
    assert res.findings == []
    assert any(w.rule == "WIR001" and w.symbol == "beta"
               for w in res.warnings)
    assert _gate(root) == 0             # warn tier never gates


def test_wir002_ghost_assert(tmp_path):
    root = _mini_repo(tmp_path)
    (root / "tests" / "test_mini.py").write_text(textwrap.dedent("""\
        def test_requests(snapshot):
            assert "tpu9_mini_ghost" in snapshot
    """))
    res = _check(root)
    assert any(f.rule == "WIR002" and f.symbol == "tpu9_mini_ghost"
               for f in res.findings)
    assert _gate(root) == 1


def test_wir002_gauge_without_remove(tmp_path):
    root = _mini_repo(tmp_path)
    (root / "tpu9" / "metrics_use.py").write_text(textwrap.dedent("""\
        def sample(metrics, cid):
            metrics.set_gauge("tpu9_mini_rss_mb", 1.0, {"container": cid})
            metrics.inc("tpu9_mini_requests", 1)
    """))
    res = _check(root)
    assert any(f.rule == "WIR002" and f.symbol == "tpu9_mini_rss_mb"
               for f in res.findings)
    assert _gate(root) == 1


def test_key001_undeclared_namespace(tmp_path):
    root = _mini_repo(tmp_path)
    (root / "tpu9" / "store2.py").write_text(textwrap.dedent("""\
        async def rogue(store):
            await store.set("rogue:k:1", "v")
    """))
    res = _check(root)
    assert any(f.rule == "KEY001" and f.symbol.startswith("rogue:")
               for f in res.findings)
    assert _gate(root) == 1


def test_key001_ttl_discipline(tmp_path):
    root = _mini_repo(tmp_path)
    (root / "tpu9" / "store_use.py").write_text(textwrap.dedent("""\
        async def write(store, wid):
            await store.set(f"mini:loc:{wid}", "x")
    """))
    res = _check(root)
    assert any(f.rule == "KEY001" and "TTL" in f.message
               for f in res.findings)
    assert _gate(root) == 1


def test_env001_divergent_default(tmp_path):
    root = _mini_repo(tmp_path)
    (root / "tpu9" / "env2.py").write_text(textwrap.dedent("""\
        import os

        def flag():
            return os.environ.get("TPU9_MINI_FLAG", "1")
    """))
    res = _check(root)
    rules = [f for f in res.findings if f.rule == "ENV001"]
    assert any("outside its declared readers" in f.message for f in rules)
    assert any("divergent" in f.message for f in rules)
    assert _gate(root) == 1


def test_env001_undeclared_var(tmp_path):
    root = _mini_repo(tmp_path)
    (root / "tpu9" / "env2.py").write_text(textwrap.dedent("""\
        import os

        def other():
            return os.environ.get("TPU9_MINI_OTHER")
    """))
    res = _check(root)
    assert any(f.rule == "ENV001" and f.symbol == "TPU9_MINI_OTHER"
               for f in res.findings)
    assert _gate(root) == 1


def test_rpc001_dead_handler_and_orphan_call(tmp_path):
    root = _mini_repo(tmp_path)
    (root / "tpu9" / "rpc_srv.py").write_text(textwrap.dedent("""\
        def routes(r, h):
            r.add_post("/rpc/mini/run", h)
            r.add_get("/rpc/mini/dead", h)
    """))
    res = _check(root)
    assert any(f.rule == "RPC001" and f.symbol == "/rpc/mini/dead"
               for f in res.findings)
    (root / "tpu9" / "rpc_srv.py").write_text(textwrap.dedent("""\
        def routes(r, h):
            r.add_post("/rpc/mini/run", h)
    """))
    (root / "tpu9" / "rpc_cli.py").write_text(textwrap.dedent("""\
        def call(c):
            return c.request("POST", "/rpc/mini/orphan")
    """))
    res = _check(root)
    assert any(f.rule == "RPC001" and f.symbol == "/rpc/mini/orphan"
               for f in res.findings)
    assert _gate(root) == 1


def test_fixture_corpus_pragma_skips_file(tmp_path):
    """A file marked ``tpu9: wirecheck-fixture-corpus`` in its head is
    excluded from inventory extraction — its strings are data."""
    root = _mini_repo(tmp_path)
    (root / "tests" / "test_fixtures.py").write_text(
        '"""tpu9: wirecheck-fixture-corpus"""\n'
        'GHOST = "tpu9_mini_ghost2"\n'
        'ROUTE = "/rpc/mini/never"\n')
    res = _check(root)
    assert res.findings == [], [f.format() for f in res.findings]


def test_route_match_prefix_semantics():
    """Call-side patterns from f-strings/concats prefix-match longer
    registered routes; registered patterns never prefix-match."""
    assert wex.route_match("/rpc/pod/*/exec", "/rpc/pod/**")
    assert wex.route_match("/rpc/pod/*/proc/*", "/rpc/pod/")
    assert wex.route_match("/api/v1/machine", "/api/v1/machine*")
    assert wex.route_match("/api/v1/machine/*/logs", "/api/v1/machine*")
    assert not wex.route_match("/rpc/other/x", "/rpc/pod/")
    assert not wex.route_match("/rpc/pod", "/rpc/pod/extra")
    assert wex.route_match("/rpc/deploy", "/rpc/deploy")
    assert not wex.route_match("/rpc/deploy", "/rpc/deplo")


# -- gate round-trip ---------------------------------------------------------

def test_gate_round_trip(tmp_path, capsys):
    root = _mini_repo(tmp_path)
    (root / "tpu9" / "store2.py").write_text(
        "async def rogue(store):\n"
        "    await store.set(\"rogue:k:1\", \"v\")\n")
    rc = _gate(root)
    out = capsys.readouterr().out
    assert rc == 1 and "KEY001" in out and "NEW" in out

    # triage into the baseline -> green
    assert _gate(root, "--update-baseline", "--reason",
                 "test debt, reviewed") == 0
    assert _gate(root) == 0

    # fixing leaves a stale entry; --strict-stale ratchets it out
    (root / "tpu9" / "store2.py").write_text("")
    assert _gate(root) == 0
    assert _gate(root, "--strict-stale") == 1


def test_gate_rejects_reasonless_update(tmp_path):
    root = _mini_repo(tmp_path)
    (root / "tpu9" / "env2.py").write_text(
        "import os\nX = os.environ.get(\"TPU9_MINI_OTHER\")\n")
    assert _gate(root, "--update-baseline") == 2


def test_scoped_update_preserves_out_of_scope_entries(tmp_path):
    """A --roots-narrowed baseline update must not destroy triage the
    narrowed run never saw (the tpu9lint PR 14 regression class)."""
    root = _mini_repo(tmp_path)
    (root / "tpu9" / "a").mkdir()
    (root / "tpu9" / "b").mkdir()
    (root / "tpu9" / "a" / "bad.py").write_text(
        "async def w(store):\n"
        "    await store.set(\"roguea:k\", 1)\n")
    (root / "tpu9" / "b" / "bad.py").write_text(
        "async def w(store):\n"
        "    await store.set(\"rogueb:k\", 1)\n")
    assert _gate(root, "--update-baseline", "--reason", "debt") == 0

    bl_path = root / "scripts" / "wire_baseline.json"
    before = Baseline.load(str(bl_path))
    assert len(before.entries) == 2

    # fix a's violation, update scoped to tpu9/a: a's entry pruned,
    # b's (out of scope) preserved
    (root / "tpu9" / "a" / "bad.py").write_text("")
    assert _gate(root, "--roots", "tpu9/a",
                 "--update-baseline", "--reason", "debt") == 0
    after = Baseline.load(str(bl_path))
    assert len(after.entries) == 1
    assert all(e["path"] == "tpu9/b/bad.py" for e in after.entries.values())
    assert _gate(root) == 0


def test_scoped_run_filters_stale_reporting(tmp_path):
    root = _mini_repo(tmp_path)
    (root / "tpu9" / "b").mkdir()
    (root / "tpu9" / "b" / "bad.py").write_text(
        "async def w(store):\n"
        "    await store.set(\"rogueb:k\", 1)\n")
    assert _gate(root, "--update-baseline", "--reason", "debt") == 0
    (root / "tpu9" / "b" / "bad.py").write_text("")
    # the entry is stale repo-wide, but a run scoped elsewhere must not
    # claim (or strict-fail on) staleness it cannot see
    assert _gate(root, "--roots", "tpu9/a", "--strict-stale") == 0
    assert _gate(root, "--strict-stale") == 1


# -- json schema -------------------------------------------------------------

def test_json_schema_round_trip(tmp_path, capsys):
    from tpu9.analysis.wirecheck.__main__ import main as wiremain
    root = _mini_repo(tmp_path)
    (root / "tpu9" / "env2.py").write_text(
        "import os\nX = os.environ.get(\"TPU9_MINI_OTHER\")\n")
    rc = wiremain(["--repo-root", str(root), "--contracts",
                   "contracts.toml", "--format", "json"])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert payload["version"] == 1 and payload["tool"] == "wirecheck"
    rec = [r for r in payload["findings"] if r["status"] == "new"][0]
    assert {"file", "line", "col", "rule", "symbol", "message",
            "fingerprint", "status"} <= set(rec)


# -- contracts.toml vs reality (independent extractor) -----------------------

def _qualnames(tree):
    """Independently-written qualname walker (no wirecheck imports)."""
    out = {}

    def visit(node, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                qual = f"{prefix}{child.name}" if prefix else child.name
                out[qual] = child
                visit(child, qual + ".")
            else:
                visit(child, prefix)

    visit(tree, "")
    return out


def _scope_mentions_var(node, var):
    if var.startswith("self."):
        attr = var.split(".", 1)[1]
        return any(isinstance(n, ast.Attribute) and n.attr == attr
                   for n in ast.walk(node))
    return any(isinstance(n, ast.Name) and n.id == var
               for n in ast.walk(node)) or \
        any(isinstance(n, ast.arg) and n.arg == var
            for n in ast.walk(node))


def test_contracts_scopes_exist_in_real_code():
    """Every declared producer/consumer scope must resolve against the
    real tree — a refactor that moves a scope shows up here even if the
    checker would only report it as 'contracts stale'."""
    raw = tomlmini.load_file(
        os.path.join(REPO, "tpu9", "analysis", "contracts.toml"))
    assert raw.get("surface"), "no surfaces declared"
    for sname, surf in raw["surface"].items():
        scopes = list(surf.get("producers", [])) + \
            list(surf.get("consumers", []))
        assert scopes, f"surface {sname} declares no scopes"
        for entry in scopes:
            path, qual, var = entry.split("::")
            full = os.path.join(REPO, path)
            assert os.path.exists(full), entry
            with open(full, encoding="utf-8") as fh:
                tree = ast.parse(fh.read())
            quals = _qualnames(tree)
            assert qual in quals, entry
            assert _scope_mentions_var(quals[qual], var), entry
        for entry in surf.get("consumer_lists", []):
            path, const = entry.split("::")
            with open(os.path.join(REPO, path), encoding="utf-8") as fh:
                tree = ast.parse(fh.read())
            assert any(isinstance(n, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == const
                for t in n.targets) for n in ast.walk(tree)), entry


def test_contracts_env_readers_exist():
    raw = tomlmini.load_file(
        os.path.join(REPO, "tpu9", "analysis", "contracts.toml"))
    for var, t in raw.get("env", {}).items():
        assert var.startswith("TPU9_"), var
        for rd in t.get("readers", []):
            assert os.path.exists(os.path.join(REPO, rd)), (var, rd)


def test_contracts_external_routes_are_registered():
    """external_ok declares a route exists but is called from outside the
    repo — the route must still be *registered*, independently scanned."""
    raw = tomlmini.load_file(
        os.path.join(REPO, "tpu9", "analysis", "contracts.toml"))
    entries = raw.get("rpc", {}).get("external_ok", [])
    if not entries:
        return
    registered = set()
    gw = os.path.join(REPO, "tpu9", "gateway", "gateway.py")
    with open(gw, encoding="utf-8") as fh:
        tree = ast.parse(fh.read())
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr.startswith("add_"):
            for a in node.args:
                if isinstance(a, ast.Constant) and isinstance(a.value, str) \
                        and a.value.startswith("/"):
                    import re
                    registered.add(re.sub(r"\{[^}]*\}", "*", a.value))
    for e in entries:
        route = e.split(":", 1)[0].strip()
        assert route in registered, route


def test_analysis_all_static_only(capsys):
    """``python -m tpu9.analysis --all`` (satellite): every static plane
    behind one exit code and one JSON stream."""
    from tpu9.analysis.__main__ import main as amain
    rc = amain(["--all", "--static-only", "--format", "json",
                "--repo-root", REPO])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert payload["tools"] == ["tpu9lint", "wirecheck"]
    assert payload["parse_errors"] == []
    for rec in payload["findings"]:
        assert rec["status"] == "baselined"
        assert rec["tool"] in ("tpu9lint", "wirecheck")


# -- regressions: real drift bugs surfaced by the checker --------------------

def test_worker_prunes_rss_gauges_for_reaped_containers():
    """WIR002 regression: the per-container RSS gauge must be removed
    when the container leaves the police set, or the series leaks
    fleet-wide for the worker's whole lifetime."""
    from tpu9.observability import Metrics
    from tpu9.worker.worker import Worker

    class _W:
        _prune_rss_gauges = Worker._prune_rss_gauges

    w, m = _W(), Metrics()
    m.set_gauge("tpu9_container_rss_mb", 64.0, {"container": "c1"})
    m.set_gauge("tpu9_container_rss_mb", 32.0, {"container": "c2"})
    w._prune_rss_gauges({"c1", "c2"}, m)      # both still policed
    assert len(m.gauges) == 2
    w._prune_rss_gauges({"c1"}, m)            # c2 reaped
    assert list(m.gauges) == ['tpu9_container_rss_mb{container="c1"}']
    w._prune_rss_gauges(set(), m)             # all gone
    assert m.gauges == {}


def test_gateway_registers_no_serve_rpc():
    """RPC001 regression: the dead /rpc/serve handler is gone — serve
    sessions ride /rpc/deploy (see tpu9/cli/main.py serve)."""
    from tpu9.gateway.gateway import Gateway
    gw_path = os.path.join(REPO, "tpu9", "gateway", "gateway.py")
    with open(gw_path, encoding="utf-8") as fh:
        tree = ast.parse(fh.read())
    registered = {a.value for node in ast.walk(tree)
                  if isinstance(node, ast.Call)
                  and isinstance(node.func, ast.Attribute)
                  and node.func.attr.startswith("add_")
                  for a in node.args
                  if isinstance(a, ast.Constant)
                  and isinstance(a.value, str) and a.value.startswith("/")}
    assert "/rpc/serve" not in registered
    assert "/rpc/deploy" in registered
    assert not hasattr(Gateway, "_rpc_serve")


# -- the repo gate -----------------------------------------------------------

def test_repo_is_wire_clean():
    """THE tier-1 gate: zero new wire findings on the repo, fast enough
    for the fast suite (acceptance: full run < 60 s)."""
    res = run_wirecheck(REPO)
    assert res.parse_errors == []
    bl = load_baseline(os.path.join(REPO, "scripts", "wire_baseline.json"))
    new, _known, stale = bl.split(res.findings)
    assert new == [], "\n".join(f.format() for f in new)
    assert stale == [], "stale wire-baseline entries: " + str(stale)
    assert res.elapsed_s < 60.0
