import json

from tpu9.config import AppConfig, load_config


def test_defaults():
    cfg = load_config(environ={})
    assert cfg.scheduler.loop_interval_s == 0.05
    assert cfg.pools[0].name == "default"


def test_file_overlay(tmp_path):
    p = tmp_path / "cfg.yaml"
    p.write_text("""
gateway:
  http_port: 9000
pools:
  - name: tpu
    mode: gce-tpu
    tpu_type: v5e-8
  - name: cpu
""")
    cfg = load_config(path=str(p), environ={})
    assert cfg.gateway.http_port == 9000
    assert len(cfg.pools) == 2
    assert cfg.pools[0].tpu_type == "v5e-8"
    assert cfg.pools[1].name == "cpu"


def test_env_overrides():
    cfg = load_config(environ={
        "TPU9_GATEWAY__HTTP_PORT": "8123",
        "TPU9_DEBUG": "true",
        "TPU9_SCHEDULER__LOOP_INTERVAL_S": "0.2",
    })
    assert cfg.gateway.http_port == 8123
    assert cfg.debug is True
    assert cfg.scheduler.loop_interval_s == 0.2


def test_config_json_layer():
    cfg = load_config(environ={
        "TPU9_CONFIG_JSON": json.dumps({"cluster_name": "prod",
                                        "worker": {"keepalive_ttl_s": 30}}),
    })
    assert cfg.cluster_name == "prod"
    assert cfg.worker.keepalive_ttl_s == 30


def test_overrides_win():
    cfg = load_config(environ={"TPU9_GATEWAY__HTTP_PORT": "1"},
                      overrides={"gateway": {"http_port": 2}})
    assert cfg.gateway.http_port == 2
    assert isinstance(cfg, AppConfig)
