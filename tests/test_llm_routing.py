import json

from tpu9.abstractions.llm import LlmRouter, prefix_hash
from tpu9.statestore import MemoryStore
from tpu9.types import ContainerState


def S(cid):
    return ContainerState(container_id=cid, stub_id="s", status="running",
                          address=f"127.0.0.1:{hash(cid) % 1000 + 2000}")


def test_prefix_hash_stability():
    a = prefix_hash(json.dumps({"prompt": "hello world", "temp": 0.7}).encode())
    b = prefix_hash(json.dumps({"prompt": "hello world", "temp": 0.1}).encode())
    c = prefix_hash(json.dumps({"prompt": "different"}).encode())
    assert a == b != c
    # non-JSON bodies hash raw bytes
    assert prefix_hash(b"raw") == prefix_hash(b"raw")


async def test_admission_excludes_saturated():
    store = MemoryStore()
    r = LlmRouter(store, max_token_pressure=0.8, max_active_streams=4)
    await r.record_pressure("hot", 0.95, 2)
    await r.record_pressure("busy", 0.2, 10)
    await r.record_pressure("cool", 0.1, 1)
    ranked = await r.rank("s", [S("hot"), S("busy"), S("cool")])
    # cool first (only admissible), saturated last
    assert ranked[0].container_id == "cool"
    assert {ranked[1].container_id, ranked[2].container_id} == {"hot", "busy"}


async def test_prefix_affinity_preferred():
    store = MemoryStore()
    r = LlmRouter(store)
    await r.record_pressure("a", 0.5, 1)
    await r.record_pressure("b", 0.1, 1)
    body = json.dumps({"prompt": "the quick brown fox"}).encode()
    await r.record_served("s", prefix_hash(body), "a")
    for _ in range(5):
        ranked = await r.rank("s", [S("a"), S("b")], body)
        assert ranked[0].container_id == "a"   # affinity beats lower pressure


async def test_affinity_skipped_when_saturated():
    store = MemoryStore()
    r = LlmRouter(store, max_token_pressure=0.8)
    await r.record_pressure("a", 0.95, 1)   # affinity target saturated
    await r.record_pressure("b", 0.1, 1)
    body = json.dumps({"prompt": "xyz"}).encode()
    await r.record_served("s", prefix_hash(body), "a")
    ranked = await r.rank("s", [S("a"), S("b")], body)
    assert ranked[0].container_id == "b"


async def test_p2c_prefers_lighter():
    store = MemoryStore()
    r = LlmRouter(store)
    await r.record_pressure("heavy", 0.7, 1)
    await r.record_pressure("light", 0.1, 1)
    firsts = set()
    for _ in range(20):
        ranked = await r.rank("s", [S("heavy"), S("light")])
        firsts.add(ranked[0].container_id)
    assert firsts == {"light"}   # two candidates → always picks lighter


async def test_mean_pressure():
    store = MemoryStore()
    r = LlmRouter(store)
    await r.record_pressure("a", 0.4, 1)
    await r.record_pressure("b", 0.6, 1)
    assert abs(await r.mean_pressure(["a", "b"]) - 0.5) < 1e-9
    assert await r.mean_pressure(["nope"]) == 0.0


async def test_mean_pressure_counts_stalled_as_missing_capacity():
    """ISSUE 14: a stalled replica often reports LOW token pressure
    (nothing moves through a wedged loop) — the autoscaler must read it
    as a missing replica (pressure 1.0), not an idle one, or the fleet
    never backfills the ejected capacity."""
    store = MemoryStore()
    r = LlmRouter(store)
    await r.record_pressure("ok", 0.4, 1)
    await r.record_pressure("wedged", 0.0, 1,
                            extra={"health": "stalled",
                                   "health_reason": "no_progress"})
    assert abs(await r.mean_pressure(["ok", "wedged"]) - 0.7) < 1e-9
