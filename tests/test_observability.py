import asyncio

from tpu9.config import WorkerPoolConfig
from tpu9.observability import EventBus, Metrics
from tpu9.repository import WorkerRepository
from tpu9.scheduler.pool_health import PoolMonitor
from tpu9.statestore import MemoryStore
from tpu9.types import WorkerState, WorkerStatus


def test_metrics_registry():
    m = Metrics()
    m.inc("reqs", labels={"route": "/x"})
    m.inc("reqs", 2, labels={"route": "/x"})
    m.set_gauge("depth", 7)
    for v in [0.1, 0.2, 0.3, 0.9]:
        m.observe("lat", v)
    d = m.to_dict()
    assert d["counters"]['reqs{route="/x"}'] == 3
    assert d["gauges"]["depth"] == 7
    assert d["summaries"]["lat"]["count"] == 4
    assert 0.1 <= d["summaries"]["lat"]["p50"] <= 0.3
    assert d["summaries"]["lat"]["max"] == 0.9
    text = m.prometheus_text()
    assert 'reqs{route="/x"} 3' in text
    assert "lat_p95" in text


def test_metrics_timer():
    import time
    m = Metrics()
    with m.timer("op"):
        time.sleep(0.01)
    assert m.to_dict()["summaries"]["op"]["max"] >= 0.01


async def test_event_bus_emit_and_query():
    store = MemoryStore()
    bus = EventBus(store)
    await bus.emit("container.started", {"container_id": "c1"}, "w1")
    await bus.emit("container.exited", {"container_id": "c1"}, "w1")
    await bus.emit("worker.registered", {"worker_id": "w"}, "")
    rows = await bus.query()
    assert len(rows) == 3
    containers_only = await bus.query(kind_prefix="container")
    assert len(containers_only) == 2
    assert containers_only[0]["data"]["container_id"] == "c1"


async def test_pool_monitor_reaps_dead_and_warms():
    store = MemoryStore()
    workers = WorkerRepository(store, keepalive_ttl_s=0.1)
    alive = WorkerState(worker_id="alive", pool="p",
                        status=WorkerStatus.AVAILABLE.value,
                        total_cpu_millicores=4000, free_cpu_millicores=4000,
                        total_memory_mb=8192, free_memory_mb=8192)
    dead = WorkerState(worker_id="dead", pool="p",
                       status=WorkerStatus.AVAILABLE.value)
    await workers.register(alive)
    await workers.register(dead)
    # let dead's keepalive lapse; keep alive fresh
    await asyncio.sleep(0.15)
    await workers.touch_keepalive("alive")

    added = []

    class FakePool:
        async def can_host(self, request):
            return True

        async def add_worker(self, request):
            added.append(request)

    cfg = WorkerPoolConfig(name="p", min_free_tpu_chips=4)
    mon = PoolMonitor(store, {"p": FakePool()}, {"p": cfg},
                      interval_s=0.05)
    await mon.tick()
    assert mon.status["p"].alive == 1
    assert await workers.get("dead") is None          # reaped
    assert added, "warm-pool sizing should have requested a worker"
