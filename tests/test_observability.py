import asyncio

import pytest

from tpu9.config import WorkerPoolConfig
from tpu9.observability import EventBus, Metrics
from tpu9.repository import WorkerRepository
from tpu9.scheduler.pool_health import PoolMonitor
from tpu9.statestore import MemoryStore
from tpu9.types import WorkerState, WorkerStatus


def test_metrics_registry():
    m = Metrics()
    m.inc("reqs", labels={"route": "/x"})
    m.inc("reqs", 2, labels={"route": "/x"})
    m.set_gauge("depth", 7)
    for v in [0.1, 0.2, 0.3, 0.9]:
        m.observe("lat", v)
    d = m.to_dict()
    assert d["counters"]['reqs{route="/x"}'] == 3
    assert d["gauges"]["depth"] == 7
    assert d["summaries"]["lat"]["count"] == 4
    assert 0.1 <= d["summaries"]["lat"]["p50"] <= 0.3
    assert d["summaries"]["lat"]["max"] == 0.9
    text = m.prometheus_text()
    assert 'reqs{route="/x"} 3' in text
    assert "lat_p95" in text


def test_metrics_timer():
    import time
    m = Metrics()
    with m.timer("op"):
        time.sleep(0.01)
    assert m.to_dict()["summaries"]["op"]["max"] >= 0.01


async def test_event_bus_emit_and_query():
    store = MemoryStore()
    bus = EventBus(store)
    await bus.emit("container.started", {"container_id": "c1"}, "w1")
    await bus.emit("container.exited", {"container_id": "c1"}, "w1")
    await bus.emit("worker.registered", {"worker_id": "w"}, "")
    rows = await bus.query()
    assert len(rows) == 3
    containers_only = await bus.query(kind_prefix="container")
    assert len(containers_only) == 2
    assert containers_only[0]["data"]["container_id"] == "c1"


async def test_pool_monitor_reaps_dead_and_warms():
    store = MemoryStore()
    workers = WorkerRepository(store, keepalive_ttl_s=0.1)
    alive = WorkerState(worker_id="alive", pool="p",
                        status=WorkerStatus.AVAILABLE.value,
                        total_cpu_millicores=4000, free_cpu_millicores=4000,
                        total_memory_mb=8192, free_memory_mb=8192)
    dead = WorkerState(worker_id="dead", pool="p",
                       status=WorkerStatus.AVAILABLE.value)
    await workers.register(alive)
    await workers.register(dead)
    # let dead's keepalive lapse; keep alive fresh
    await asyncio.sleep(0.15)
    await workers.touch_keepalive("alive")

    added = []

    class FakePool:
        async def can_host(self, request):
            return True

        async def add_worker(self, request):
            added.append(request)

    cfg = WorkerPoolConfig(name="p", min_free_tpu_chips=4)
    mon = PoolMonitor(store, {"p": FakePool()}, {"p": cfg},
                      interval_s=0.05)
    await mon.tick()
    assert mon.status["p"].alive == 1
    assert await workers.get("dead") is None          # reaped
    assert added, "warm-pool sizing should have requested a worker"


# ---------------------------------------------------------------------------
# usage metering (usage_openmeter.go analogue)
# ---------------------------------------------------------------------------

async def test_usage_sampler_and_service_roundtrip():
    from tpu9.backend import BackendDB
    from tpu9.observability.usage import (UsageSampler, UsageService,
                                          bucket_of, usage_key)
    from tpu9.statestore import MemoryStore

    store = MemoryStore()
    backend = BackendDB(":memory:")
    sampler = UsageSampler(store)
    # two containers in ws-a (one with 4 chips), one in ws-b, 5s beat
    await sampler.sample([("ws-a", 0), ("ws-a", 4), ("ws-b", 0)], 5.0)
    svc = UsageService(store, backend)
    await svc.record_request("ws-a", 3)

    out = await svc.query("ws-a", hours=2)
    assert out["totals"]["container_seconds"] == 10.0
    assert out["totals"]["chip_seconds"] == 20.0
    assert out["totals"]["requests"] == 3.0
    out_b = await svc.query("ws-b", hours=2)
    assert out_b["totals"]["container_seconds"] == 5.0

    # durable flush: hot bucket persists; query still correct (no double
    # count — flusher writes totals and query dedupes with max())
    n = await svc.flush()
    assert n >= 3
    out2 = await svc.query("ws-a", hours=2)
    assert out2["totals"]["container_seconds"] == 10.0
    # hot state gone (expiry simulated by delete) → durable serves the data
    await store.delete(usage_key("ws-a", bucket_of()))
    out3 = await svc.query("ws-a", hours=2)
    assert out3["totals"]["container_seconds"] == 10.0
    await backend.close()


# ---------------------------------------------------------------------------
# tracing (common/trace.go analogue)
# ---------------------------------------------------------------------------

def test_tracer_spans_nest_and_export():
    from tpu9.observability.trace import Tracer
    t = Tracer("test")
    with t.span("outer", attrs={"k": 1}) as outer:
        with t.span("inner") as inner:
            assert inner.trace_id == outer.trace_id
            assert inner.parent_id == outer.span_id
    spans = t.export(trace_id=outer.trace_id)
    assert [s["name"] for s in spans] == ["inner", "outer"]
    assert spans[1]["durationMs"] >= spans[0]["durationMs"] >= 0
    # error status recorded
    try:
        with t.span("boom"):
            raise ValueError("x")
    except ValueError:
        pass
    assert t.export()[-1]["status"] == "error"


def test_span_duration_survives_wall_clock_step(monkeypatch):
    """ISSUE 8 satellite: durationMs must come from time.monotonic() — an
    NTP step (wall clock jumping BACKWARDS mid-span) must never produce a
    negative duration or an end before the start."""
    import time as _time

    from tpu9.observability import trace as trace_mod

    real_time = _time.time
    wall = {"offset": 0.0}
    monkeypatch.setattr(trace_mod.time, "time",
                        lambda: real_time() + wall["offset"])
    t = trace_mod.Tracer("steptest")
    with t.span("stepped") as sp:
        _time.sleep(0.02)
        wall["offset"] = -3600.0          # NTP steps the clock back 1h
    d = sp.to_dict()
    assert d["durationMs"] >= 20.0, d     # monotonic: the real elapsed time
    assert d["endTimeUnixNano"] >= d["startTimeUnixNano"]
    # the wall anchor is the (pre-step) start; end = anchor + duration
    # (durationMs is rounded to 3 decimals; allow sub-ms slack)
    assert d["endTimeUnixNano"] - d["startTimeUnixNano"] == \
        pytest.approx(d["durationMs"] * 1e6, abs=1e6)

    # forward step too: duration reflects sleep, not the +1h jump
    with t.span("fwd") as sp2:
        _time.sleep(0.01)
        wall["offset"] = 3600.0
    assert sp2.to_dict()["durationMs"] < 1000.0


def test_export_new_watermark_is_monotonic(monkeypatch):
    """The heartbeat ship cursor (export_new) must be immune to wall-clock
    steps: a span finished after a backward NTP step still ships, and an
    already-shipped span never re-ships once the watermark advances."""
    import time as _time

    from tpu9.observability import trace as trace_mod

    t = trace_mod.Tracer("ship")
    with t.span("first"):
        pass
    spans, hi = t.export_new(since_mono=0.0)
    assert [s["name"] for s in spans] == ["first"]
    assert hi > 0.0
    # watermark NOT advanced (gateway rejected the beat): same span again
    again, _ = t.export_new(since_mono=0.0)
    assert [s["name"] for s in again] == ["first"]

    # wall clock steps back an hour; the next span must still ship
    real_time = _time.time
    monkeypatch.setattr(trace_mod.time, "time",
                        lambda: real_time() - 3600.0)
    with t.span("post_step"):
        pass
    spans2, hi2 = t.export_new(since_mono=hi)
    assert [s["name"] for s in spans2] == ["post_step"]
    assert hi2 > hi
    # accepted: nothing left to ship, watermark stable
    spans3, hi3 = t.export_new(since_mono=hi2)
    assert spans3 == [] and hi3 == hi2


def test_tracer_manual_spans_and_context():
    """Manual start/finish spans (cross-task propagation) + explicit
    remote parents + record_span backdating."""
    import time as _time

    from tpu9.observability.trace import Tracer
    t = Tracer("manual")
    assert t.context() == ("", "")
    with t.span("invoke") as root:
        ctx = t.context()
        assert ctx == (root.trace_id, root.span_id)
    # manual span finished OUTSIDE the contextvar scope, explicit parent
    sp = t.start_span("queue_wait", trace_id=ctx[0], parent_id=ctx[1])
    _time.sleep(0.01)
    t.finish_span(sp)
    d = sp.to_dict()
    assert d["parentSpanId"] == root.span_id
    assert d["traceId"] == root.trace_id
    assert d["durationMs"] >= 10.0
    # record_span: an already-elapsed interval becomes a span with the
    # captured anchor pair
    t0_wall, t0_mono = _time.time() - 5.0, _time.monotonic() - 0.25
    rec = t.record_span("window", ctx[0], ctx[1], t0_wall, t0_mono,
                        attrs={"k": 4})
    d = rec.to_dict()
    assert 240.0 <= d["durationMs"] <= 2000.0
    assert d["startTimeUnixNano"] == int(t0_wall * 1e9)
    # error status propagates through finish_span
    sp2 = t.start_span("boom", trace_id=ctx[0], parent_id=ctx[1])
    t.finish_span(sp2, status="error")
    assert sp2.to_dict()["status"] == "error"


# ---------------------------------------------------------------------------
# log rate limiting
# ---------------------------------------------------------------------------

def test_log_limiter_throttles_and_reports_drops():
    from tpu9.observability import LogLimiter
    lim = LogLimiter(rate_per_s=10.0, burst=5.0)
    admitted = sum(1 for _ in range(100) if lim.admit()[0])
    assert admitted <= 7          # burst + trickle, not 100
    assert lim.dropped > 0 or admitted < 100
    import time as _t
    _t.sleep(1.1)                 # refill window → marker reports drops
    ok, dropped = lim.admit()
    assert ok and dropped > 0


async def test_usage_and_traces_flow_through_stack():
    """E2E: one invoke produces usage buckets and a cold-start trace
    (scheduler + worker spans under one trace id)."""
    from tpu9.testing.localstack import LocalStack

    async with LocalStack() as stack:
        dep = await stack.deploy_endpoint(
            "obs-echo", {"app.py": "def handler(**kw):\n    return kw\n"},
            "app:handler", config_extra={"keep_warm_seconds": 60.0})
        await stack.invoke(dep, {"x": 1})

        status, usage = await stack.api("GET", "/api/v1/usage?hours=2")
        assert status == 200
        assert usage["totals"].get("requests", 0) >= 1

        # drive the heartbeat's usage/trace ship deterministically (two
        # beats: the first arms dt, the second samples it)
        import asyncio as _a
        worker = stack.workers[0]
        await worker._ship_usage_and_traces()
        await _a.sleep(0.3)
        await worker._ship_usage_and_traces()
        status, usage = await stack.api("GET", "/api/v1/usage?hours=2")
        assert usage["totals"].get("container_seconds", 0) > 0

        status, traces = await stack.api("GET", "/api/v1/traces")
        assert status == 200
        names = {s["name"] for s in traces["spans"]}
        assert "scheduler.schedule" in names, names
        assert "worker.cold_start" in names, names
        assert "gateway.invoke" in names, names
        # scheduler + worker spans share the container-start trace
        sched = [s for s in traces["spans"]
                 if s["name"] == "scheduler.schedule"][0]
        cold = [s for s in traces["spans"]
                if s["name"] == "worker.cold_start"][0]
        assert sched["traceId"] == cold["traceId"]


# ---------------------------------------------------------------------------
# OTLP export (reference pkg/common/trace.go OTLP-HTTP exporter)
# ---------------------------------------------------------------------------

async def test_otlp_exporter_pushes_spans_and_metrics():
    from tpu9.observability.metrics import Metrics
    from tpu9.observability.otel import OtlpExporter
    from tpu9.observability.trace import Tracer

    tracer = Tracer(service="test-svc")
    registry = Metrics()
    with tracer.span("outer", attrs={"stub_id": "s1"}):
        with tracer.span("inner"):
            pass
    registry.inc("tpu9_requests", 3,  # tpu9: noqa[WIR002] local-registry fixture series, not product telemetry
                 {"route": "invoke"})
    registry.set_gauge("tpu9_pool_workers", 2, {"pool": "default"})
    registry.observe("tpu9_startup_phase_s", 0.25, {"phase": "image"})

    pushes = []

    async def transport(path, payload):
        pushes.append((path, payload))
        return 200

    exp = OtlpExporter("http://collector:4318", service="test-svc",
                       transport=transport, tracer=tracer,
                       registry=registry)
    exp._last_flush = 0.0   # everything counts as "since last flush"
    out = await exp.flush()
    assert out["spans"] == 2
    assert out["trace_status"] == 200 and out["metrics_status"] == 200

    (tpath, tpayload), (mpath, mpayload) = pushes
    assert tpath == "/v1/traces" and mpath == "/v1/metrics"
    spans = tpayload["resourceSpans"][0]["scopeSpans"][0]["spans"]
    names = {s["name"] for s in spans}
    assert names == {"outer", "inner"}
    inner = next(s for s in spans if s["name"] == "inner")
    outer = next(s for s in spans if s["name"] == "outer")
    assert inner["parentSpanId"] == outer["spanId"]
    assert {"key": "service.name",
            "value": {"stringValue": "test-svc"}} in \
        tpayload["resourceSpans"][0]["resource"]["attributes"]

    ms = mpayload["resourceMetrics"][0]["scopeMetrics"][0]["metrics"]
    by_name = {m["name"]: m for m in ms}
    sum_pt = by_name["tpu9_requests"]["sum"]["dataPoints"][0]
    assert sum_pt["asDouble"] == 3.0
    assert {"key": "route", "value": {"stringValue": "invoke"}} \
        in sum_pt["attributes"]
    assert by_name["tpu9_pool_workers"]["gauge"]["dataPoints"][0][
        "asDouble"] == 2.0
    summ_pt = by_name["tpu9_startup_phase_s"]["summary"]["dataPoints"][0]
    assert summ_pt["count"] == "1"

    # incremental: a second flush with nothing new pushes no spans
    pushes.clear()
    out2 = await exp.flush()
    assert out2["spans"] == 0
    assert [p for p, _ in pushes] == ["/v1/metrics"]


async def test_otlp_flush_survives_transport_failure():
    from tpu9.observability.metrics import Metrics
    from tpu9.observability.otel import OtlpExporter
    from tpu9.observability.trace import Tracer

    calls = []

    async def broken(path, payload):
        calls.append(path)
        raise OSError("collector down")

    exp = OtlpExporter("http://x", transport=broken, tracer=Tracer(),
                       registry=Metrics(), interval_s=0.01)
    await exp.start()
    await __import__("asyncio").sleep(0.1)
    await exp.stop()          # loop survived repeated failures
    assert calls              # and kept trying


async def test_otlp_failed_push_does_not_advance_flush_window():
    """The retry-don't-drop contract (otel.py flush docstring): a rejected
    or failed trace push must leave the flush window where it was, so the
    SAME spans go out on the next flush instead of vanishing."""
    from tpu9.observability.metrics import Metrics
    from tpu9.observability.otel import OtlpExporter
    from tpu9.observability.trace import Tracer

    tracer = Tracer("retry")
    with tracer.span("survivor"):
        pass

    mode = {"fail": True}
    pushes = []

    async def transport(path, payload):
        pushes.append((path, payload))
        if mode["fail"] and path == "/v1/traces":
            return 503                      # collector rejecting
        return 200

    exp = OtlpExporter("http://c", transport=transport, tracer=tracer,
                       registry=Metrics())
    exp._last_flush = 0.0
    window_before = exp._last_flush
    with pytest.raises(RuntimeError):
        await exp.flush()
    assert exp._last_flush == window_before, \
        "a failed push must not advance the window"
    # metrics were NOT pushed either (trace failure aborts the flush
    # before the metrics snapshot — one atomic retry unit)
    assert [p for p, _ in pushes] == ["/v1/traces"]

    # collector recovers: the SAME span ships
    mode["fail"] = False
    pushes.clear()
    out = await exp.flush()
    assert out["spans"] == 1 and out["trace_status"] == 200
    shipped = pushes[0][1]["resourceSpans"][0]["scopeSpans"][0]["spans"]
    assert [s["name"] for s in shipped] == ["survivor"]
    assert exp._last_flush > window_before

    # a hard transport error (OSError) must behave the same way
    with tracer.span("second"):
        pass
    window = exp._last_flush

    async def explode(path, payload):
        raise OSError("down")

    exp.transport = explode
    with pytest.raises(OSError):
        await exp.flush()
    assert exp._last_flush == window


def test_otlp_attr_and_field_golden_mapping():
    """Golden tests for the OTLP/JSON field mapping: every tpu9 attr type
    → the right OTLP value wrapper; span status/kind/nano fields; counter
    → monotonic cumulative sum; summary → quantileValues."""
    from tpu9.observability.otel import _attr, metrics_to_otlp, spans_to_otlp

    assert _attr("b", True) == {"key": "b", "value": {"boolValue": True}}
    assert _attr("i", 7) == {"key": "i", "value": {"intValue": "7"}}
    assert _attr("f", 0.5) == {"key": "f", "value": {"doubleValue": 0.5}}
    assert _attr("s", "x") == {"key": "s", "value": {"stringValue": "x"}}
    # non-primitive falls back to its string form
    assert _attr("l", [1, 2]) == \
        {"key": "l", "value": {"stringValue": "[1, 2]"}}

    span = {"traceId": "t" * 32, "spanId": "s" * 16, "parentSpanId": "p",
            "name": "gateway.invoke", "startTimeUnixNano": 1_000,
            "endTimeUnixNano": 3_500, "durationMs": 0.0000025,
            "attributes": {"stub_id": "st", "ok": True}, "status": "error"}
    otlp = spans_to_otlp([span], "svc")["resourceSpans"][0]
    assert otlp["resource"]["attributes"] == \
        [{"key": "service.name", "value": {"stringValue": "svc"}}]
    out = otlp["scopeSpans"][0]["spans"][0]
    assert out["kind"] == 1                              # SPAN_KIND_INTERNAL
    assert out["status"] == {"code": 2}                  # error → ERROR
    assert out["startTimeUnixNano"] == "1000"            # stringified nanos
    assert out["endTimeUnixNano"] == "3500"
    assert {"key": "ok", "value": {"boolValue": True}} in out["attributes"]
    ok_span = dict(span, status="ok")
    assert spans_to_otlp([ok_span], "svc")["resourceSpans"][0][
        "scopeSpans"][0]["spans"][0]["status"] == {"code": 1}

    snapshot = {
        "counters": {'tpu9_requests{route="invoke"}': 3.0},
        "gauges": {"tpu9_depth": 7.0},  # tpu9: noqa[WIR002] fixture series name, not product telemetry
        "summaries": {"tpu9_lat_s": {"count": 4, "mean": 0.375,  # tpu9: noqa[WIR002] fixture series name, not product telemetry
                                     "p50": 0.2, "p95": 0.9, "max": 0.9}},
    }
    ms = metrics_to_otlp(snapshot, "svc")["resourceMetrics"][0][
        "scopeMetrics"][0]["metrics"]
    by_name = {m["name"]: m for m in ms}
    s = by_name["tpu9_requests"]["sum"]
    assert s["isMonotonic"] is True
    assert s["aggregationTemporality"] == 2              # CUMULATIVE
    pt = s["dataPoints"][0]
    assert pt["asDouble"] == 3.0
    assert {"key": "route", "value": {"stringValue": "invoke"}} \
        in pt["attributes"]
    summ = by_name["tpu9_lat_s"]["summary"]["dataPoints"][0]
    assert summ["count"] == "4"
    assert summ["sum"] == pytest.approx(1.5)             # mean × count
    assert {"quantile": 0.5, "value": 0.2} in summ["quantileValues"]
    assert {"quantile": 1.0, "value": 0.9} in summ["quantileValues"]


def test_prometheus_exposition_golden_slo_goodput_naming():
    """Golden text exposition for the ISSUE 12 gauge families: stable
    ``tpu9_slo_*`` / ``tpu9_goodput_*`` naming, deterministic label order
    (sorted keys), and label-value escaping per the Prometheus text
    format (backslash, double-quote, newline) — mirroring the otel.py
    golden-mapping test above."""
    m = Metrics()
    m.set_gauge("tpu9_slo_burn_rate", 2.5,
                labels={"stub": "s1", "objective": "ttft",
                        "window": "fast"})
    m.set_gauge("tpu9_slo_burn_rate", 0.25,
                labels={"stub": "s1", "objective": "ttft",
                        "window": "slow"})
    m.set_gauge("tpu9_slo_burning", 1.0,
                labels={"stub": "s1", "objective": "availability"})
    m.set_gauge("tpu9_goodput_frac", 0.75, labels={"workspace": "ws-1"})
    m.set_gauge("tpu9_goodput_tokens_per_chip_second", 12.5,
                labels={"workspace": "ws-1"})
    m.set_gauge("tpu9_goodput_waste_frac", 0.1,
                labels={"workspace": "ws-1", "bucket": "queue_wait"})
    # hostile label value: quotes, backslash and newline must escape,
    # not corrupt the exposition line structure
    m.set_gauge("tpu9_goodput_frac", 0.5,
                labels={"workspace": 'we"ird\\ws\nname'})
    assert m.prometheus_text() == (
        'tpu9_goodput_frac{workspace="we\\"ird\\\\ws\\nname"} 0.5\n'
        'tpu9_goodput_frac{workspace="ws-1"} 0.75\n'
        'tpu9_goodput_tokens_per_chip_second{workspace="ws-1"} 12.5\n'
        'tpu9_goodput_waste_frac{bucket="queue_wait",workspace="ws-1"} 0.1\n'
        'tpu9_slo_burn_rate{objective="ttft",stub="s1",window="fast"} 2.5\n'
        'tpu9_slo_burn_rate{objective="ttft",stub="s1",window="slow"} 0.25\n'
        'tpu9_slo_burning{objective="availability",stub="s1"} 1.0\n')
    # the exposition stays parseable: every line is `name{labels} value`
    for line in m.prometheus_text().strip().split("\n"):
        name, _, rest = line.partition("{")
        assert name.startswith("tpu9_")
        labels, _, value = rest.rpartition("} ")
        float(value)                                     # parses
        assert "\n" not in labels


# ---------------------------------------------------------------------------
# cold-start decomposition helpers + cache-plane timeline ingest (ISSUE 13)
# ---------------------------------------------------------------------------

def test_coldstart_interval_helpers():
    from tpu9.observability import coldstart as cs
    assert cs.interval_overlap_s((0.0, 2.0), (1.0, 3.0)) == 1.0
    assert cs.interval_overlap_s((0.0, 1.0), (2.0, 3.0)) == 0.0
    assert cs.interval_overlap_s(None, (0.0, 1.0)) == 0.0
    # shorter phase fully hidden → 1.0; serial → 0.0
    assert cs.overlap_frac((0.0, 10.0), (2.0, 4.0)) == 1.0
    assert cs.overlap_frac((0.0, 1.0), (1.0, 2.0)) == 0.0
    assert cs.overlap_frac((0.0, 4.0), (2.0, 6.0)) == 0.5
    # agreement: relative disagreement vs the larger side
    assert cs.agreement(1.0, 1.0) == 0.0
    assert cs.agreement(0.9, 1.0) == pytest.approx(0.1)
    assert cs.agreement(0.0, 0.0) == 0.0


def test_coldstart_decompose_spans_and_merge_record():
    from tpu9.observability import coldstart as cs

    def sp(name, dur_ms, attrs=None):
        return {"name": name, "durationMs": dur_ms,
                "attributes": attrs or {}}

    spans = [sp(cs.SPAN_REQUEST, 1000),
             sp(cs.SPAN_FETCH, 400, {"bytes": 100}),
             sp(cs.SPAN_FETCH, 200, {"bytes": 50}),
             sp(cs.SPAN_DEVICE_PUT, 500),
             sp(cs.SPAN_COMPILE_AHEAD, 300),
             sp("engine.request", 777)]        # unrelated span ignored
    d = cs.decompose_spans(spans)
    assert d["request_s"] == 1.0
    assert d["fetch_s"] == pytest.approx(0.6)
    assert d["device_put_s"] == pytest.approx(0.5)
    assert d["compile_ahead_s"] == pytest.approx(0.3)
    assert d["groups"] == 2 and d["bytes"] == 150

    merged = cs.merge_record(
        {"container_id": "c1", "restore": {"plan_s": 0.1}},
        {"coldstart_ready_s": 2.5, "coldstart_warmup_s": 0.5,
         "tokens_per_sec": 99})               # non-coldstart key dropped
    assert merged["container_id"] == "c1"
    assert merged["runner"] == {"ready_s": 2.5, "warmup_s": 0.5}
    assert cs.merge_record(None, None) == {}


def test_tracer_record_window_and_inherited_attrs():
    import time as _time

    from tpu9.observability.trace import Tracer
    tracer = Tracer("t")
    wall, mono = 1_000_000.0, _time.monotonic()
    with tracer.span("root", attrs={"workspace_id": "ws",
                                    "container_id": "ct",
                                    "other": "x"}) as root:
        assert tracer.inherited_attrs("workspace_id", "container_id",
                                      "missing") == \
            {"workspace_id": "ws", "container_id": "ct"}
        sp = tracer.record_window("child", wall, mono, mono + 1.0,
                                  mono + 3.0, attrs={"k": "v"})
        # wall start = anchor + monotonic offset; duration from the pair
        assert sp.start == pytest.approx(wall + 1.0)
        assert sp.duration_s == pytest.approx(2.0)
        assert sp.parent_id == root.span_id
        assert sp.trace_id == root.trace_id
        # a window that never opened records nothing
        assert tracer.record_window("none", wall, mono, None, None) is None
    assert tracer.inherited_attrs("workspace_id") == {}


async def test_fleetobs_ingests_cache_plane_series():
    import json

    from tpu9.config import SloConfig
    from tpu9.gateway.fleetobs import FleetObserver

    store = MemoryStore()
    obs = FleetObserver(SloConfig(), store)
    snap = {"ts": 123.0, "worker_id": "w0",
            "cache": {"local_hits": 5, "peer_hits": 2,
                      "hedged_reads": 3, "hedge_wins": 1,
                      "hedge_wasted_bytes": 4096,
                      "bytes_local": 1000, "bytes_peer": 2000,
                      "bytes_source": 0,
                      "peers": {"10.0.0.2:7400": {"lat_ewma_s": 0.004,
                                                  "bytes": 2000,
                                                  "errors": 1}}},
            "peer_bytes_per_s": 512.0,
            "weightpool": {"hits": 1, "misses": 2, "evictions": 0,
                           "entries": 1, "bytes": 777}}
    await store.set("worker:cache:w0", json.dumps(snap))
    await obs.sample_cache_plane()
    tl = obs.timeline
    q = tl.query(["cache.w0.*", "weightpool.w0.*"])
    assert q["cache.w0.local_hits"][-1][1] == 5.0
    assert q["cache.w0.hedge_wasted_bytes"][-1][1] == 4096.0
    assert q["cache.w0.peer_bytes_per_s"][-1][1] == 512.0
    # the PER-PEER series the acceptance criterion names
    assert q["cache.w0.peer.10.0.0.2:7400.lat_ewma_s"][-1][1] == 0.004
    assert q["cache.w0.peer.10.0.0.2:7400.errors"][-1][1] == 1.0
    assert q["weightpool.w0.bytes"][-1][1] == 777.0
    # garbage snapshots are skipped, not fatal
    await store.set("worker:cache:w1", "not json")
    await obs.sample_cache_plane()


async def test_fleetobs_ingests_health_and_folds_into_router():
    """ISSUE 14: a heartbeat carrying a health verdict records the
    numeric engine.<cid>.health series + the hbm_* watermark series,
    publishes the tpu9_health_*/tpu9_hbm_* gauges, and folds the verdict
    into the router's stalled ledger (eject on stalled, restore on ok)."""
    from tpu9.config import SloConfig
    from tpu9.gateway.fleetobs import FleetObserver
    from tpu9.observability.metrics import metrics as global_metrics

    class RouterSpy:
        def __init__(self):
            self.notes = []

        def note_replica_health(self, cid, state, reason=""):
            self.notes.append((cid, state, reason))

    store = MemoryStore()
    spy = RouterSpy()
    obs = FleetObserver(SloConfig(), store, fleet_router=spy)
    obs.ingest_heartbeat(
        "cH", "ws", "st", token_pressure=0.2, active_streams=1,
        extra={"health": "stalled",
               "health_reason": "no_progress_with_queued_work",
               "hbm_used_gb_per_chip": 12.0,
               "hbm_peak_gb_per_chip": 13.0,
               "hbm_predicted_gb_per_chip": 11.5,
               "hbm_limit_gb_per_chip": 16.0,
               "last_progress_age_s": 7.5,
               "windows_processed": 42})
    q = obs.timeline.query(["engine.cH.*"])
    assert q["engine.cH.health"][-1][1] == 2.0          # stalled code
    assert q["engine.cH.hbm_used_gb_per_chip"][-1][1] == 12.0
    assert q["engine.cH.hbm_predicted_gb_per_chip"][-1][1] == 11.5
    assert q["engine.cH.last_progress_age_s"][-1][1] == 7.5
    assert q["engine.cH.windows_processed"][-1][1] == 42.0
    assert spy.notes == [("cH", "stalled",
                          "no_progress_with_queued_work")]
    assert global_metrics.gauges.get(
        'tpu9_health_state{replica="cH"}') == 2
    # recovery flows through the same path
    obs.ingest_heartbeat("cH", "ws", "st", token_pressure=0.1,
                         active_streams=0, extra={"health": "ok",
                                                  "health_reason": ""})
    assert spy.notes[-1] == ("cH", "ok", "")
    assert global_metrics.gauges.get(
        'tpu9_health_state{replica="cH"}') == 0
    # a health-less heartbeat (non-LLM runner) records nothing new
    obs.ingest_heartbeat("cQ", "ws", "st", token_pressure=0.1,
                         active_streams=0, extra={"queued": 0})
    assert "engine.cQ.health" not in obs.timeline.series_names()
    assert len(spy.notes) == 2
