"""Bot abstraction e2e: petri-net transitions firing real task containers.

Reference analogue: ``pkg/abstractions/experimental/bot/`` (marker
locations, transition tasks, session event history). Drives the full
stack: push marker → transition fires a one-shot container (function
runner) → completion hook pushes output markers → cascade fires the next
transition — plus validation, restore-on-failure, and event-stream checks.
"""

import asyncio

import pytest

from tpu9.testing.localstack import LocalStack

pytestmark = pytest.mark.e2e

BOT_HANDLERS = """
def summarize(markers, session_id, transition):
    doc = markers["docs"][0]
    return {"summaries": {"text": doc["text"].upper()}}

def archive(markers, session_id, transition):
    s = markers["summaries"][0]
    return {"archived": {"text": s["text"] + "!"}}

def explode(markers, session_id, transition):
    raise RuntimeError("transition bug")
"""

DOC_SCHEMA = {"fields": {"text": {"kind": "string"}}}


def bot_config(transitions: dict) -> dict:
    return {
        "runtime": {"cpu_millicores": 250, "memory_mb": 256},
        "timeout_s": 60.0,
        "extra": {"bot": {
            "locations": {"docs": {"schema": DOC_SCHEMA},
                          "summaries": {"schema": DOC_SCHEMA},
                          "archived": {"schema": DOC_SCHEMA}},
            "transitions": transitions,
        }},
    }


async def deploy_bot(stack, name: str, transitions: dict) -> dict:
    object_id = await stack.upload_workspace({"app.py": BOT_HANDLERS})
    status, out = await stack.api("POST", "/rpc/stub/get-or-create",
                                  json_body={
        "name": name, "stub_type": "bot",
        "config": bot_config(transitions), "object_id": object_id})
    assert status == 200, out
    return out


async def wait_for(fn, timeout=60.0, interval=0.25):
    deadline = asyncio.get_event_loop().time() + timeout
    while True:
        out = await fn()
        if out:
            return out
        if asyncio.get_event_loop().time() > deadline:
            raise TimeoutError("condition not met")
        await asyncio.sleep(interval)


async def test_bot_cascade_fires_chained_transitions():
    async with LocalStack() as stack:
        out = await deploy_bot(stack, "docbot", {
            "summarize": {"handler": "app:summarize",
                          "inputs": {"docs": 1}, "outputs": ["summaries"]},
            "archive": {"handler": "app:archive",
                        "inputs": {"summaries": 1},
                        "outputs": ["archived"]},
        })
        stub_id = out["stub_id"]
        status, sess = await stack.api("POST", "/rpc/bot/session",
                                       json_body={"stub_id": stub_id})
        assert status == 200, sess
        sid = sess["session_id"]

        status, push = await stack.api(
            "POST", f"/rpc/bot/{stub_id}/session/{sid}/push",
            json_body={"location": "docs", "marker": {"text": "hello"}})
        assert status == 200, push
        assert push["fired"] == ["summarize"]

        async def archived_ready():
            _, st = await stack.api(
                "GET", f"/rpc/bot/{stub_id}/session/{sid}/state")
            return st["markers"]["archived"] == 1 and not st["inflight"]

        await wait_for(archived_ready, timeout=90.0)
        status, popped = await stack.api(
            "POST", f"/rpc/bot/{stub_id}/session/{sid}/pop",
            json_body={"location": "archived"})
        assert status == 200
        assert popped["marker"] == {"text": "HELLO!"}

        # event history shows the full cascade
        _, events = await stack.api(
            "GET", f"/rpc/bot/{stub_id}/session/{sid}/events")
        kinds = [e["type"] for e in events]
        assert kinds.count("transition_started") == 2
        assert kinds.count("transition_completed") == 2


async def test_bot_marker_validation_and_unknowns():
    async with LocalStack() as stack:
        out = await deploy_bot(stack, "valbot", {
            "summarize": {"handler": "app:summarize",
                          "inputs": {"docs": 1}, "outputs": ["summaries"]}})
        stub_id = out["stub_id"]
        _, sess = await stack.api("POST", "/rpc/bot/session",
                                  json_body={"stub_id": stub_id})
        sid = sess["session_id"]
        # schema violation → 400, no marker stored
        status, err = await stack.api(
            "POST", f"/rpc/bot/{stub_id}/session/{sid}/push",
            json_body={"location": "docs", "marker": {"text": 42}})
        assert status == 400, err
        # unknown location → 400
        status, _ = await stack.api(
            "POST", f"/rpc/bot/{stub_id}/session/{sid}/push",
            json_body={"location": "nope", "marker": {"text": "x"}})
        assert status == 400
        # unknown session → 400
        status, _ = await stack.api(
            "POST", f"/rpc/bot/{stub_id}/session/bs-nope/push",
            json_body={"location": "docs", "marker": {"text": "x"}})
        assert status == 400
        _, st = await stack.api(
            "GET", f"/rpc/bot/{stub_id}/session/{sid}/state")
        assert st["markers"]["docs"] == 0


async def test_bot_failed_transition_restores_markers():
    async with LocalStack() as stack:
        out = await deploy_bot(stack, "failbot", {
            "explode": {"handler": "app:explode",
                        "inputs": {"docs": 2}, "outputs": ["summaries"]}})
        stub_id = out["stub_id"]
        _, sess = await stack.api("POST", "/rpc/bot/session",
                                  json_body={"stub_id": stub_id})
        sid = sess["session_id"]
        # first push: below threshold, nothing fires
        status, push = await stack.api(
            "POST", f"/rpc/bot/{stub_id}/session/{sid}/push",
            json_body={"location": "docs", "marker": {"text": "a"}})
        assert push["fired"] == []
        status, push = await stack.api(
            "POST", f"/rpc/bot/{stub_id}/session/{sid}/push",
            json_body={"location": "docs", "marker": {"text": "b"}})
        assert push["fired"] == ["explode"]

        async def restored():
            _, st = await stack.api(
                "GET", f"/rpc/bot/{stub_id}/session/{sid}/state")
            return st["markers"]["docs"] == 2 and not st["inflight"]

        await wait_for(restored, timeout=90.0)
        _, events = await stack.api(
            "GET", f"/rpc/bot/{stub_id}/session/{sid}/events")
        kinds = [e["type"] for e in events]
        assert "transition_failed" in kinds
        # no refire loop: exactly one start despite markers being restored
        assert kinds.count("transition_started") == 1


async def test_bot_session_lifecycle():
    async with LocalStack() as stack:
        out = await deploy_bot(stack, "lcbot", {
            "summarize": {"handler": "app:summarize",
                          "inputs": {"docs": 1}, "outputs": ["summaries"]}})
        stub_id = out["stub_id"]
        _, s1 = await stack.api("POST", "/rpc/bot/session",
                                json_body={"stub_id": stub_id})
        _, s2 = await stack.api("POST", "/rpc/bot/session",
                                json_body={"stub_id": stub_id})
        _, sessions = await stack.api("GET", f"/rpc/bot/{stub_id}/sessions")
        assert {s["session_id"] for s in sessions} == {s1["session_id"],
                                                       s2["session_id"]}
        status, d = await stack.api(
            "DELETE", f"/rpc/bot/{stub_id}/session/{s1['session_id']}")
        assert d["ok"]
        _, sessions = await stack.api("GET", f"/rpc/bot/{stub_id}/sessions")
        assert len(sessions) == 1
        # a non-bot stub can't create sessions
        status, out2 = await stack.api("POST", "/rpc/stub/get-or-create",
                                       json_body={
            "name": "plain", "stub_type": "function",
            "config": {"handler": "app:summarize"}})
        status, err = await stack.api("POST", "/rpc/bot/session",
                                      json_body={"stub_id": out2["stub_id"]})
        assert status == 400


# ---------------------------------------------------------------------------
# SDK declaration mechanics (no stack needed)
# ---------------------------------------------------------------------------

def test_sdk_bot_declaration():
    import tpu9
    from tpu9.schema import String

    class Doc(tpu9.Schema):
        text = String()

    bot = tpu9.Bot(name="declbot",
                   locations=[tpu9.BotLocation("docs", marker=Doc),
                              tpu9.BotLocation("out")])

    @bot.transition(inputs={"docs": 2}, outputs=["out"], cpu=2,
                    memory="512Mi", tpu="v5e-1", retries=1, timeout=30)
    def crunch(markers, session_id, transition):
        return {}

    cfg = bot.config.extra["bot"]
    assert cfg["locations"]["docs"]["schema"]["fields"]["text"]["kind"] \
        == "string"
    t = cfg["transitions"]["crunch"]
    assert t["inputs"] == {"docs": 2} and t["outputs"] == ["out"]
    assert t["cpu_millicores"] == 2000 and t["memory_mb"] == 512
    assert t["tpu"] == "v5e-1" and t["retries"] == 1
    assert t["handler"].endswith(":crunch")

    import pytest as _pytest
    with _pytest.raises(ValueError):
        bot.transition(inputs={"nope": 1})(lambda **kw: None)
    with _pytest.raises(ValueError):
        bot.transition(inputs={})(lambda **kw: None)
    with _pytest.raises(ValueError):
        bot.transition(inputs={"docs": 0})(lambda **kw: None)


async def test_bot_sessions_are_tenant_scoped():
    """An attacker with their OWN bot stub (same location names) must not be
    able to read or pop another workspace's session markers."""
    import aiohttp
    import json as _json

    async with LocalStack() as stack:
        out = await deploy_bot(stack, "victimbot", {
            "summarize": {"handler": "app:summarize",
                          "inputs": {"docs": 5},   # never fires in this test
                          "outputs": ["summaries"]}})
        stub_id = out["stub_id"]
        _, sess = await stack.api("POST", "/rpc/bot/session",
                                  json_body={"stub_id": stub_id})
        sid = sess["session_id"]
        await stack.api(
            "POST", f"/rpc/bot/{stub_id}/session/{sid}/push",
            json_body={"location": "docs", "marker": {"text": "secret"}})

        ws = await stack.backend.create_workspace("intruder")
        tok = await stack.backend.create_token(ws.workspace_id)
        session = aiohttp.ClientSession(
            headers={"Authorization": f"Bearer {tok.key}"})
        try:
            # intruder registers their own bot stub with the same location
            async with session.post(
                    f"{stack.base_url}/rpc/stub/get-or-create",
                    json=_json.loads(_json.dumps({
                        "name": "evil", "stub_type": "bot",
                        "config": bot_config({"summarize": {
                            "handler": "app:summarize",
                            "inputs": {"docs": 5},
                            "outputs": ["summaries"]}})}))) as resp:
                evil = await resp.json()
            evil_stub = evil["stub_id"]
            for method, path, body in [
                    ("POST", f"/rpc/bot/{evil_stub}/session/{sid}/pop",
                     {"location": "docs"}),
                    ("GET", f"/rpc/bot/{evil_stub}/session/{sid}/state",
                     None),
                    ("GET", f"/rpc/bot/{evil_stub}/session/{sid}/events",
                     None),
                    ("POST", f"/rpc/bot/{evil_stub}/session/{sid}/push",
                     {"location": "docs", "marker": {"text": "x"}})]:
                async with session.request(
                        method, stack.base_url + path, json=body) as resp:
                    assert resp.status in (400, 404), (method, path,
                                                       resp.status)
        finally:
            await session.close()
        # victim's marker untouched
        _, st = await stack.api(
            "GET", f"/rpc/bot/{stub_id}/session/{sid}/state")
        assert st["markers"]["docs"] == 1
