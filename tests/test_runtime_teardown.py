"""Runtime teardown ordering (ISSUE 9 satellite — the coldstart_native
container-teardown flake).

``NativeRuntime.run`` spawns a reap task that performs the real teardown
(close proxies, tear down the netns, unmount the overlay) after the
container process exits. ``wait()`` used to return at ``proc.wait()`` —
BEFORE that teardown — so the lifecycle marked the container stopped while
the unmount was still in flight, and a scale-down that then deleted or
re-mounted the same image bundle (exactly what the coldstart_native bench
does between trials) raced it. ``wait()`` must now return only after the
registered reap task has fully finished.

No root needed: these tests inject a real (trivial) subprocess plus a
controlled reap task, exercising the wait()/waiter contract directly.
"""

import asyncio

from tpu9.runtime import NativeRuntime
from tpu9.utils.aio import spawn


async def _spawn_true() -> asyncio.subprocess.Process:
    return await asyncio.create_subprocess_exec(
        "true", stdout=asyncio.subprocess.DEVNULL,
        stderr=asyncio.subprocess.DEVNULL)


async def test_wait_returns_only_after_reap_teardown(tmp_path):
    rt = NativeRuntime(base_dir=str(tmp_path))
    cid = "nat-teardown-order"
    proc = await _spawn_true()
    rt._procs[cid] = proc

    teardown_done = asyncio.Event()

    async def reap():
        await proc.wait()
        # simulated slow unmount: the window the flake lived in — the
        # process is dead (wait() used to return HERE) but the overlay
        # teardown is still running
        await asyncio.sleep(0.2)
        teardown_done.set()

    rt._waiters[cid] = spawn(reap(), name="test-reap")
    code = await rt.wait(cid)
    assert code == 0
    assert teardown_done.is_set(), (
        "wait() returned before the reap task finished its teardown — "
        "callers that delete the image bundle on wait() race the unmount")


async def test_cancelled_waiter_does_not_cancel_shared_reap(tmp_path):
    """The reap is shared by every wait() caller and owns the terminal
    teardown: cancelling one caller must not cancel it (the
    ProcessRuntime.wait precedent — a cancelled bare `await reap` strands
    the teardown half-finished)."""
    rt = NativeRuntime(base_dir=str(tmp_path))
    cid = "nat-teardown-cancel"
    proc = await _spawn_true()
    rt._procs[cid] = proc

    release = asyncio.Event()
    teardown_done = asyncio.Event()

    async def reap():
        await proc.wait()
        await release.wait()
        teardown_done.set()

    reap_task = spawn(reap(), name="test-reap-cancel")
    rt._waiters[cid] = reap_task

    waiter = asyncio.ensure_future(rt.wait(cid))
    await asyncio.sleep(0.05)       # caller parked on the reap
    waiter.cancel()
    try:
        await waiter
    except asyncio.CancelledError:
        pass
    assert not reap_task.cancelled()
    release.set()
    await asyncio.wait_for(reap_task, 5)
    assert teardown_done.is_set()

    # a second caller still observes the completed teardown + exit code
    assert await rt.wait(cid) == 0


async def test_wait_survives_crashed_reap_and_logs(tmp_path, caplog):
    """A reap that CRASHES mid-teardown must be LOGGED but must not break
    wait()'s exit-code contract: lifecycle._supervise does its container
    bookkeeping + tpu.release unconditionally after wait() returns, and
    an exception here would skip both — leaking the chip reservation
    forever (worse than a half-torn netns, which the next gc sweeps)."""
    import logging

    rt = NativeRuntime(base_dir=str(tmp_path))
    cid = "nat-teardown-crash"
    proc = await _spawn_true()
    rt._procs[cid] = proc

    async def reap():
        await proc.wait()
        raise RuntimeError("umount exploded")

    rt._waiters[cid] = spawn(reap(), name="test-reap-crash")
    with caplog.at_level(logging.WARNING, logger="tpu9.runtime"):
        code = await rt.wait(cid)
    assert code == 0
    assert any("umount exploded" in r.getMessage() for r in caplog.records), \
        "crashed reap was silently absorbed without a log line"


# ---------------------------------------------------------------------------
# stop-after-exit must not resurrect a terminal container state (ISSUE 13 —
# surfaced by the evidence-plane timing shifts; the race is older)
# ---------------------------------------------------------------------------

async def test_stop_after_exit_does_not_resurrect_state():
    """A stop request landing AFTER the supervisor terminalized the
    container used to write STOPPING back into the store — re-adding the
    container to the stub index (only terminal update_state removes it)
    with no supervisor left to ever terminalize it again. A retrying
    scale-down loop then refreshed the phantom's TTL forever and spun on
    'containers did not stop'."""
    from tpu9.config import WorkerConfig
    from tpu9.repository import ContainerRepository
    from tpu9.statestore import MemoryStore
    from tpu9.repository.keys import Keys
    from tpu9.types import ContainerState, ContainerStatus
    from tpu9.worker.lifecycle import ContainerLifecycle
    from tpu9.worker.tpu_manager import TpuDeviceManager

    class DeadRuntime:
        name = "process"

        async def kill(self, container_id, sig=15):
            return False          # container already exited / unknown

    store = MemoryStore()
    containers = ContainerRepository(store)
    # the supervisor's terminal write: STOPPED state row persists (TTL),
    # stub index entry removed
    state = ContainerState(container_id="ct-dead", stub_id="stub-x",
                           workspace_id="ws-x",
                           status=ContainerStatus.STOPPED.value)
    await containers.update_state(state)
    assert await store.hgetall(Keys.stub_containers("stub-x")) == {}

    lc = ContainerLifecycle("w0", WorkerConfig(), DeadRuntime(),
                            containers, TpuDeviceManager())
    assert await lc.stop_container("ct-dead", reason="scale_down") is False
    # neither resurrected in the index nor flipped off terminal status
    assert await store.hgetall(Keys.stub_containers("stub-x")) == {}
    got = await containers.get_state("ct-dead")
    assert got is not None
    assert got.status == ContainerStatus.STOPPED.value
    # and no pending-reason leak for a container with no supervisor
    assert "ct-dead" not in lc._pending_reasons


async def test_unorchestrated_exit_records_worker_postmortem():
    """ISSUE 14: an OOM-killed (or plain crashed) container process can
    never ship its own black box — the worker's supervisor writes the
    minimal header record under postmortem:<cid>. An orchestrated stop
    (scale_down) is not an incident and records nothing."""
    from tpu9.config import WorkerConfig
    from tpu9.observability.health import load_postmortems
    from tpu9.repository import ContainerRepository
    from tpu9.statestore import MemoryStore
    from tpu9.types import ContainerRequest, ContainerState, ContainerStatus
    from tpu9.worker.lifecycle import ContainerLifecycle
    from tpu9.worker.tpu_manager import TpuDeviceManager

    class ExitRuntime:
        name = "process"

        def __init__(self, code):
            self.code = code

        async def wait(self, container_id):
            return self.code

        async def kill(self, container_id, sig=15):
            return True

    store = MemoryStore()
    containers = ContainerRepository(store)

    async def run_one(cid, code, reason_noted=""):
        lc = ContainerLifecycle("w0", WorkerConfig(), ExitRuntime(code),
                                containers, TpuDeviceManager())
        state = ContainerState(container_id=cid, stub_id="stub-x",
                               workspace_id="ws-x",
                               status=ContainerStatus.RUNNING.value)
        await containers.update_state(state)
        if reason_noted:
            lc.note_stop_reason(cid, reason_noted)
        await lc._supervise(ContainerRequest(container_id=cid,
                                             stub_id="stub-x",
                                             workspace_id="ws-x"), state)

    # SIGKILL (asyncio reports -9) normalizes to OOM → oom_killed record
    await run_one("ct-oom", -9)
    records = await load_postmortems(store, "postmortem:ct-oom")
    assert len(records) == 1
    rec = records[0]
    assert rec["reason"] == "oom_killed"
    assert rec["workspace_id"] == "ws-x" and rec["stub_id"] == "stub-x"
    assert rec["stats"]["exit_code"] == -9
    assert "exited with code -9" in rec["exception"]

    # plain non-zero exit → process_exit record
    await run_one("ct-crash", 3)
    rec = (await load_postmortems(store, "postmortem:ct-crash"))[0]
    assert rec["reason"] == "process_exit"
    assert rec["stats"]["stop_reason"] == "exit"

    # orchestrated scale-down (even with a non-zero code) records nothing
    await run_one("ct-drain", 1, reason_noted="scale_down")
    assert await load_postmortems(store, "postmortem:ct-drain") == []

    # clean exit records nothing
    await run_one("ct-clean", 0)
    assert await load_postmortems(store, "postmortem:ct-clean") == []
