"""tpu9lint (ISSUE 7): rule fixtures, suppression/baseline round-trips, the
boundaries.toml-vs-reality check, and the repo gate itself (this test IS the
tier-1 wiring, next to test_bench_guard.py)."""

import ast
import json
import os
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "scripts"))

import lint_gate  # noqa: E402

from tpu9.analysis import run_analysis  # noqa: E402
from tpu9.analysis import boundaries as bnd  # noqa: E402
from tpu9.analysis import rules  # noqa: E402
from tpu9.analysis import tomlmini  # noqa: E402
from tpu9.analysis.findings import (Baseline, apply_suppressions,  # noqa: E402
                                    parse_suppressions)


def check(src: str, path: str = "mod.py"):
    tree = ast.parse(textwrap.dedent(src))
    return rules.check_file(path, tree)


def rule_ids(src: str):
    return sorted({f.rule for f in check(src)})


# -- per-rule fixtures: positive AND negative --------------------------------

class TestASY001:
    def test_wait_for_queue_get_flagged(self):
        src = """
        import asyncio
        async def poll(sub):
            while True:
                msg = await asyncio.wait_for(sub.get(), 1.0)
        """
        fs = [f for f in check(src) if f.rule == "ASY001"]
        assert len(fs) == 1
        assert "poll loop" in fs[0].message

    def test_wait_for_event_wait_flagged(self):
        src = """
        import asyncio
        async def gate(ev):
            await asyncio.wait_for(ev.wait(), timeout=15.0)
        """
        assert "ASY001" in rule_ids(src)

    def test_bare_get_and_shield_not_flagged(self):
        src = """
        import asyncio
        async def ok(sub, ev):
            msg = await sub.get()
            await asyncio.wait_for(asyncio.shield(ev.wait()), 1.0)
            await asyncio.wait_for(some_coro(), 1.0)
        """
        assert "ASY001" not in rule_ids(src)


class TestASY002:
    def test_discarded_create_task_flagged(self):
        src = """
        import asyncio
        from asyncio import create_task
        async def fire(loop):
            asyncio.create_task(pump())
            loop.create_task(pump())
            asyncio.ensure_future(pump())
            create_task(pump())     # bare from-import: same weak-ref bug
        """
        assert len([f for f in check(src) if f.rule == "ASY002"]) == 4

    def test_stored_or_awaited_not_flagged(self):
        src = """
        import asyncio
        async def ok(tasks):
            t = asyncio.create_task(pump())
            tasks.add(asyncio.create_task(pump()))
            await asyncio.create_task(pump())
            return asyncio.create_task(pump())
        """
        assert "ASY002" not in rule_ids(src)


class TestASY003:
    def test_swallowing_handlers_flagged(self):
        src = """
        import asyncio
        async def bad1():
            try:
                await work()
            except BaseException:
                pass
        async def bad2():
            try:
                await work()
            except asyncio.CancelledError:
                return None
        async def bad3():
            try:
                await work()
            except:
                log()
        """
        assert len([f for f in check(src) if f.rule == "ASY003"]) == 3

    def test_raise_inside_nested_def_does_not_silence(self):
        src = """
        import asyncio
        async def bad():
            try:
                await work()
            except BaseException:
                def helper():
                    raise RuntimeError("not OUR re-raise")
                helper()
        """
        assert "ASY003" in rule_ids(src)

    def test_reraise_and_sync_not_flagged(self):
        src = """
        import asyncio
        async def ok1():
            try:
                await work()
            except BaseException:
                cleanup()
                raise
        async def ok2():
            try:
                await work()
            except Exception:
                pass
        def sync_ok():
            try:
                work()
            except BaseException:
                pass
        """
        assert "ASY003" not in rule_ids(src)


class TestASY004:
    def test_blocking_calls_flagged(self):
        src = """
        import time, subprocess, shutil
        async def bad():
            time.sleep(1)
            subprocess.run(["ls"])
            shutil.rmtree("/tmp/x")
            with open("f") as f:
                pass
        """
        assert len([f for f in check(src) if f.rule == "ASY004"]) == 4

    def test_sync_def_and_nested_sync_not_flagged(self):
        src = """
        import time, asyncio
        def sync():
            time.sleep(1)
        async def ok():
            def inner():
                time.sleep(1)      # runs via to_thread
            await asyncio.to_thread(inner)
            await asyncio.sleep(1)
        """
        assert "ASY004" not in rule_ids(src)


class TestJAX002:
    def test_inline_jit_and_jit_in_loop_flagged(self):
        src = """
        import jax
        def bad(x, fns):
            y = jax.jit(f)(x)
            for i in range(3):
                fns.append(jax.jit(g))
        """
        assert len([f for f in check(src) if f.rule == "JAX002"]) == 2

    def test_cached_jit_not_flagged(self):
        src = """
        import jax
        compiled = jax.jit(f)
        class M:
            def get(self):
                fn = self._c["k"] = jax.jit(g)
                return fn
        """
        assert "JAX002" not in rule_ids(src)


class TestOBS001:
    """time.time() arithmetic for durations/deadlines in hot-path planes
    (ISSUE 8 satellite — the trace.py durationMs NTP-step bug class)."""

    PATH = "tpu9/serving/engine.py"

    def test_direct_arithmetic_and_compare_flagged(self):
        src = """
        import time
        def shed(deadline):
            deadline = time.time() + 30.0
            if time.time() > deadline:
                return True
        """
        fs = [f for f in check(src, path=self.PATH) if f.rule == "OBS001"]
        assert len(fs) == 2
        assert "monotonic" in fs[0].message

    def test_tainted_local_name_flagged(self):
        src = """
        import time
        def measure(fn):
            t0 = time.time()
            fn()
            return time.time() - t0
        """
        fs = [f for f in check(src, path=self.PATH) if f.rule == "OBS001"]
        assert fs, "wall-wall subtraction must be flagged"

    def test_tainted_attribute_flagged_file_wide(self):
        # the ORIGINAL trace.py bug: start stored from time.time() in one
        # method, subtracted in another
        src = """
        import time
        class Span:
            def __init__(self):
                self.start = time.time()
            def duration(self, end):
                return end - self.start
        """
        fs = [f for f in check(src, path=self.PATH) if f.rule == "OBS001"]
        assert len(fs) == 1
        assert fs[0].symbol == "Span.duration"

    def test_monotonic_and_anchor_not_flagged(self):
        src = """
        import time
        class Span:
            def __init__(self):
                self.start = time.time()       # wall ANCHOR: stored only
                self.t0 = time.monotonic()
            def duration(self):
                return time.monotonic() - self.t0
            def start_nanos(self):
                return int(self.start * 1e9)   # epoch conversion (mult)
        """
        assert "OBS001" not in {f.rule
                                for f in check(src, path=self.PATH)}

    def test_parallel_tuple_assign_taints_only_wall_half(self):
        src = """
        import time
        def f():
            t_mono, t_wall = time.monotonic(), time.time()
            ok = time.monotonic() - t_mono
            bad = 5.0 + t_wall
            return ok, bad
        """
        fs = [f for f in check(src, path=self.PATH) if f.rule == "OBS001"]
        assert len(fs) == 1 and "t_wall" in fs[0].message

    def test_out_of_scope_path_not_flagged(self):
        src = """
        import time
        def paid_deadline():
            return time.time() + 600.0   # store-persisted epoch (gateway)
        """
        assert check(src, path="tpu9/gateway/gateway.py") == []

    def test_lambda_bodies_are_scanned(self):
        # lambdas are scopes of their own (excluded from the enclosing
        # scan) — wall arithmetic inside one must still be flagged
        src = """
        import time
        f = lambda t0: time.time() - t0
        def waiter(deadline):
            expired = lambda: time.time() > deadline
            return expired
        """
        fs = [f for f in check(src, path=self.PATH) if f.rule == "OBS001"]
        assert len(fs) == 2
        assert {f.symbol for f in fs} == {"<lambda>", "waiter.<lambda>"}

    def test_monotonic_lambda_not_flagged(self):
        src = """
        import time
        def waiter(deadline_mono):
            return lambda: time.monotonic() > deadline_mono
        """
        assert "OBS001" not in {f.rule
                                for f in check(src, path=self.PATH)}


class TestOBS002:
    """Unbounded metric-label cardinality (ISSUE 12 satellite): request/
    trace/prompt identity as a metrics.inc/observe/set_gauge label value
    mints a permanent registry series per request."""

    def test_request_id_label_flagged(self):
        src = """
        from tpu9.observability import metrics
        def record(request_id):
            metrics.inc("tpu9_requests_total",
                        labels={"request": request_id})
        """
        fs = [f for f in check(src) if f.rule == "OBS002"]
        assert len(fs) == 1
        assert "request_id" in fs[0].message

    def test_trace_id_fstring_and_attribute_flagged(self):
        src = """
        from tpu9.observability import metrics
        def record(req, ctx):
            metrics.observe("tpu9_lat_s", 0.1,
                            labels={"t": f"trace-{ctx.trace_id}"})
            metrics.set_gauge("tpu9_depth", 1,
                              labels={"r": req.request_id})
        """
        assert len([f for f in check(src) if f.rule == "OBS002"]) == 2

    def test_prompt_and_minted_id_flagged(self):
        src = """
        from tpu9.observability import metrics
        from tpu9.observability.trace import new_trace_id
        def record(prompt):
            metrics.inc("hits", labels={"p": prompt[:64]})
            metrics.inc("spans", labels={"id": new_trace_id()})
        """
        assert len([f for f in check(src) if f.rule == "OBS002"]) == 2

    def test_self_metrics_receiver_and_positional_labels_flagged(self):
        src = """
        class Engine:
            def _obs(self, req):
                self.metrics.observe("tpu9_engine_ttft_s", 0.2,
                                     {"request": req.request_id})
        """
        assert len([f for f in check(src) if f.rule == "OBS002"]) == 1

    def test_bounded_labels_not_flagged(self):
        src = """
        from tpu9.observability import metrics
        def record(stub_id, tenant, reason, worker_id, phase):
            metrics.inc("tpu9_router_shed_total",
                        labels={"stub": stub_id, "reason": reason})
            metrics.observe("tpu9_router_queue_wait_s", 0.1,
                            labels={"tenant": tenant})
            metrics.set_gauge("tpu9_startup_phase_s", 1.0,
                              labels={"worker": worker_id, "phase": phase})
        """
        assert "OBS002" not in rule_ids(src)

    def test_non_metrics_receiver_not_flagged(self):
        src = """
        def record(store, request_id):
            store.inc("hits", labels={"request": request_id})
            attrs = {"request": request_id}     # span attrs are the
            span.set_attrs(attrs)               # CORRECT home for ids
        """
        assert "OBS002" not in rule_ids(src)


class TestTMO001:
    """ISSUE 15: network-facing awaits without a timeout/deadline in the
    gateway/router/runner/worker/cache/statestore planes."""

    PATH = "tpu9/gateway/mod.py"

    def ids(self, src, path=None):
        tree = ast.parse(textwrap.dedent(src))
        return sorted({f.rule
                       for f in rules.check_file(path or self.PATH, tree)})

    def test_awaited_http_call_without_timeout_flagged(self):
        src = """
        async def ship(session, url):
            await session.post(url, json={})
        """
        assert "TMO001" in self.ids(src)

    def test_async_with_http_call_without_timeout_flagged(self):
        # the dominant aiohttp idiom: the request awaits in __aenter__,
        # not through an Await node
        src = """
        async def ship(session, url):
            async with session.post(url, json={}) as resp:
                return await resp.read()
        """
        assert "TMO001" in self.ids(src)

    def test_timeout_kwarg_satisfies(self):
        src = """
        import aiohttp
        async def ship(session, url):
            await session.post(url, json={},
                               timeout=aiohttp.ClientTimeout(total=5))
            async with session.get(url, timeout=5.0) as resp:
                return await resp.read()
        """
        assert "TMO001" not in self.ids(src)

    def test_direct_open_connection_flagged_wrapped_not(self):
        src = """
        import asyncio
        async def dial(host, port):
            r, w = await asyncio.open_connection(host, port)
        async def dial_bounded(host, port):
            r, w = await asyncio.wait_for(
                asyncio.open_connection(host, port), 5.0)
        """
        fs = [f for f in rules.check_file(
            self.PATH, ast.parse(textwrap.dedent(src)))
            if f.rule == "TMO001"]
        assert len(fs) == 1
        assert fs[0].symbol == "dial"

    def test_blocking_store_read_without_timeout_flagged(self):
        src = """
        async def drain(store, key):
            item = await store.blpop(key)
            evs = await store.xread(key, "0")
        """
        fs = [f for f in rules.check_file(
            self.PATH, ast.parse(textwrap.dedent(src)))
            if f.rule == "TMO001"]
        assert len(fs) == 2

    def test_blocking_store_read_with_timeout_ok(self):
        src = """
        async def drain(store, key):
            item = await store.blpop(key, 5.0)
            evs = await store.xread(key, "0", timeout=2.0)
        """
        assert "TMO001" not in self.ids(src)

    def test_out_of_scope_path_not_flagged(self):
        src = """
        async def ship(session, url):
            await session.post(url, json={})
        """
        assert "TMO001" not in self.ids(src, path="tpu9/sdk/client.py")

    def test_non_session_receiver_not_flagged(self):
        src = """
        async def run(queue, repo):
            await queue.get()
            await repo.get("key")
        """
        assert "TMO001" not in self.ids(src)


class TestJAX001:
    HOT = """
    import jax, numpy as np
    class Engine:
        def _serve_loop_inner(self):
            self._step()
            self._cold()   # not defined here: name-linked only to defs
        def _step(self):
            x = jax.device_get(self.buf)
            return np.asarray(x)
        def _warm(self):
            jax.device_get(self.buf)   # NOT reachable from the loop
    """

    def run(self, src):
        tree = ast.parse(textwrap.dedent(src))
        return rules.check_jax_hotpath({"hot.py": tree},
                                       ["_serve_loop_inner"])

    def test_reachable_syncs_flagged_unreachable_not(self):
        fs = self.run(self.HOT)
        assert {f.symbol for f in fs} == {"Engine._step"}
        assert len(fs) == 2   # device_get + np.asarray

    def test_item_and_block_until_ready(self):
        src = """
        def _serve_loop_inner(arr):
            n = arr.item()
            arr.block_until_ready()
        """
        assert len(self.run(src)) == 2


class TestBND001:
    TOML = """
    [allow]
    "tpu9.serving" = ["tpu9.ops"]
    [forbid]
    "tpu9.router" = ["tpu9.serving"]
    [restricted]
    "tpu9.ops.quant" = ["tpu9.ops", "tpu9.serving"]
    """

    def cfg(self):
        return bnd.BoundaryConfig(
            **{k: v for k, v in tomlmini.loads(
                textwrap.dedent(self.TOML)).items()})

    def run(self, path, src):
        tree = ast.parse(textwrap.dedent(src))
        return bnd.check_boundaries({path: tree}, self.cfg())

    def test_allow_violation(self):
        fs = self.run("tpu9/serving/engine.py",
                      "from tpu9.gateway import gateway")
        assert len(fs) == 1 and "contract" in fs[0].message

    def test_allow_ok_and_intra_package(self):
        assert self.run("tpu9/serving/engine.py", """
            from tpu9.ops import attention
            from . import spec
            from ..ops.quant import quantize_kv
        """) == []

    def test_forbid_and_relative_resolution(self):
        fs = self.run("tpu9/router/fleet.py", "from ..serving import engine")
        assert len(fs) == 1 and "forbidden" in fs[0].message

    def test_restricted(self):
        fs = self.run("tpu9/worker/worker.py",
                      "from tpu9.ops.quant import quantize_kv")
        assert len(fs) == 1 and "restricted" in fs[0].message


# -- suppressions & baseline -------------------------------------------------

class TestSuppressions:
    SRC = ("import asyncio\n"
           "async def f(sub):\n"
           "    await asyncio.wait_for(sub.get(), 1)"
           "  # tpu9: noqa[ASY001] reviewed: single-shot helper\n")

    def test_noqa_with_reason_suppresses(self):
        tree = ast.parse(self.SRC)
        fs = rules.check_file("m.py", tree)
        kept, supp = apply_suppressions(fs, parse_suppressions(self.SRC),
                                        "m.py")
        assert kept == [] and len(supp) == 1

    def test_noqa_without_reason_is_sup001_and_does_not_suppress(self):
        src = self.SRC.replace(" reviewed: single-shot helper", "")
        tree = ast.parse(src)
        fs = rules.check_file("m.py", tree)
        kept, supp = apply_suppressions(fs, parse_suppressions(src), "m.py")
        assert supp == []
        assert sorted(f.rule for f in kept) == ["ASY001", "SUP001"]

    def test_reasonless_noqa_in_clean_file_raises_sup001(self, tmp_path):
        """A dead/bare noqa in a file with NO findings must still surface
        (the ratchet would otherwise rot invisibly)."""
        root = _mini_repo(tmp_path)
        (root / "pkg" / "clean.py").write_text(
            "x = 1  # tpu9: noqa[ASY001]\n")
        res = run_analysis(str(root), roots=("pkg",))
        assert [f.rule for f in res.findings] == ["SUP001"]

    def test_comment_above_covers_next_line(self):
        src = ("import asyncio\n"
               "async def f(sub):\n"
               "    # tpu9: noqa[ASY001] reviewed: the caller re-cancels\n"
               "    await asyncio.wait_for(sub.get(), 1)\n")
        tree = ast.parse(src)
        kept, supp = apply_suppressions(
            rules.check_file("m.py", tree), parse_suppressions(src), "m.py")
        assert kept == [] and len(supp) == 1

    def test_end_of_line_noqa_does_not_leak_to_next_line(self):
        """A new finding added directly below an end-of-line suppression
        must NOT ride it — the ratchet stays tight for adjacent lines."""
        src = ("import asyncio\n"
               "async def f(a, b):\n"
               "    await asyncio.wait_for(a.get(), 1)"
               "  # tpu9: noqa[ASY001] reviewed: helper re-cancels\n"
               "    await asyncio.wait_for(b.get(), 1)\n")
        tree = ast.parse(src)
        kept, supp = apply_suppressions(
            rules.check_file("m.py", tree), parse_suppressions(src), "m.py")
        assert len(supp) == 1 and len(kept) == 1
        assert kept[0].line == 4


class TestBaseline:
    def test_round_trip_and_split(self, tmp_path):
        fs = check("""
        import asyncio
        async def f():
            asyncio.create_task(g())
        async def h():
            asyncio.create_task(g())
        """)
        bl = Baseline()
        bl.add(fs[0], "triaged: test debt")
        p = tmp_path / "bl.json"
        bl.save(str(p))
        bl2 = Baseline.load(str(p))
        new, known, stale = bl2.split(fs)
        assert [f.fingerprint for f in known] == [fs[0].fingerprint]
        assert [f.fingerprint for f in new] == [fs[1].fingerprint]
        assert stale == []
        new2, known2, stale2 = bl2.split([])
        assert new2 == [] and known2 == [] and len(stale2) == 1

    def test_reason_is_mandatory(self, tmp_path):
        p = tmp_path / "bl.json"
        p.write_text(json.dumps({"version": 1, "findings": [
            {"fingerprint": "aa", "rule": "ASY001", "path": "x.py",
             "status": "suppressed", "reason": "  "}]}))
        with pytest.raises(ValueError, match="no reason"):
            Baseline.load(str(p))

    def test_occurrence_keeps_same_site_distinct(self):
        from tpu9.analysis.findings import assign_occurrences
        fs = assign_occurrences(check("""
        import asyncio
        async def f():
            asyncio.create_task(g())
            asyncio.create_task(g())
        """))
        assert len({f.fingerprint for f in fs}) == 2


# -- the gate ----------------------------------------------------------------

def _mini_repo(tmp_path):
    (tmp_path / "pyproject.toml").write_text("[project]\nname='x'\n")
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "ok.py").write_text("import asyncio\n")
    (tmp_path / "scripts").mkdir()
    return tmp_path


def test_gate_fails_on_injected_asy001(tmp_path, capsys):
    root = _mini_repo(tmp_path)
    (root / "pkg" / "bad.py").write_text(
        "import asyncio\n"
        "async def poll(sub):\n"
        "    while True:\n"
        "        await asyncio.wait_for(sub.get(), 1.0)\n")
    rc = lint_gate.main(["--repo-root", str(root), "--roots", "pkg"])
    out = capsys.readouterr().out
    assert rc == 1 and "ASY001" in out and "NEW" in out

    # triage it into the baseline -> gate goes green
    rc = lint_gate.main(["--repo-root", str(root), "--roots", "pkg",
                         "--update-baseline", "--reason",
                         "test debt, reviewed"])
    assert rc == 0
    rc = lint_gate.main(["--repo-root", str(root), "--roots", "pkg"])
    assert rc == 0

    # fixing the bug leaves a stale entry; --strict-stale ratchets it out
    (root / "pkg" / "bad.py").write_text("import asyncio\n")
    assert lint_gate.main(["--repo-root", str(root), "--roots", "pkg"]) == 0
    assert lint_gate.main(["--repo-root", str(root), "--roots", "pkg",
                           "--strict-stale"]) == 1


def test_gate_rejects_reasonless_update(tmp_path):
    root = _mini_repo(tmp_path)
    (root / "pkg" / "bad.py").write_text(
        "import time\nasync def f():\n    time.sleep(1)\n")
    rc = lint_gate.main(["--repo-root", str(root), "--roots", "pkg",
                         "--update-baseline"])
    assert rc == 2


def test_repo_is_lint_clean():
    """THE tier-1 gate: zero new findings on the repo, and fast enough to
    live in the fast suite (acceptance: full run < 60 s)."""
    result = run_analysis(REPO)
    bl = Baseline.load(os.path.join(REPO, "scripts", "lint_baseline.json"))
    new, _known, stale = bl.split(result.findings)
    assert result.parse_errors == []
    assert new == [], "\n".join(f.format() for f in new)
    assert stale == [], "stale baseline entries: " + str(stale)
    assert result.elapsed_s < 60.0
    # every shipped rule has recorded triage: a fix or a suppression
    triaged = {e["rule"] for e in bl.entries.values()}
    triaged |= {e["rule"] for e in bl.fixed}
    triaged |= {f.rule for f in result.suppressed}
    assert {"ASY001", "ASY002", "ASY003", "ASY004",
            "JAX001", "JAX002", "BND001"} <= triaged


# -- boundaries.toml vs the real import graph --------------------------------

def _scan_imports(rel, tree):
    """Import extraction written independently of the checker's
    bnd.extract_imports — a bug there must not blind this cross-check."""
    mod = rel[:-3].replace("/", ".")
    is_pkg = mod.endswith(".__init__")
    if is_pkg:
        mod = mod[: -len(".__init__")]
    pkg_parts = mod.split(".") if is_pkg else mod.split(".")[:-1]
    out = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            out.update(a.name for a in node.names
                       if a.name.split(".")[0] == "tpu9")
        elif isinstance(node, ast.ImportFrom):
            if node.level == 0:
                base = node.module or ""
            else:
                anchor = pkg_parts[: len(pkg_parts) - (node.level - 1)]
                base = ".".join(anchor + ([node.module] if node.module
                                          else []))
            if base.split(".")[0] == "tpu9":
                for a in node.names:
                    if a.name != "*":
                        out.add(f"{base}.{a.name}")
                if not node.names:
                    out.add(base)
    return out


def _real_imports():
    """Independent import scan (not the checker's walker): module ->
    set of imported tpu9 targets."""
    edges = {}
    for dirpath, dirnames, filenames in os.walk(os.path.join(REPO, "tpu9")):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in filenames:
            if not fn.endswith(".py"):
                continue
            rel = os.path.relpath(os.path.join(dirpath, fn), REPO)
            rel = rel.replace(os.sep, "/")
            with open(os.path.join(REPO, rel)) as f:
                tree = ast.parse(f.read())
            mod = rel[:-3].replace("/", ".")
            if mod.endswith(".__init__"):
                mod = mod[: -len(".__init__")]
            edges.setdefault(mod, set()).update(_scan_imports(rel, tree))
    return edges


def test_independent_scanner_agrees_with_checker_extraction():
    """The two extractors (checker's + this test's) must agree on the real
    tree — divergence means one of them mis-resolves an import form."""
    for dirpath, dirnames, filenames in os.walk(os.path.join(REPO, "tpu9")):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in filenames:
            if not fn.endswith(".py"):
                continue
            rel = os.path.relpath(os.path.join(dirpath, fn), REPO)
            rel = rel.replace(os.sep, "/")
            with open(os.path.join(REPO, rel)) as f:
                tree = ast.parse(f.read())
            checker = {t for t, _ in bnd.extract_imports(rel, tree)}
            ours = _scan_imports(rel, tree)
            assert checker == ours, f"extractors disagree on {rel}"


def test_boundaries_toml_matches_real_import_graph():
    cfg = bnd.BoundaryConfig.load(
        os.path.join(REPO, "tpu9", "analysis", "boundaries.toml"))
    edges = _real_imports()

    def covered(target, allowed, selfpkg):
        return (target == selfpkg or target.startswith(selfpkg + ".")
                or any(target == a or target.startswith(a + ".")
                       for a in allowed))

    # 1) every [allow] contract holds against reality. Most specific key
    # wins, mirroring the checker: a module governed by a deeper allow
    # key (tpu9.serving.shard under tpu9.serving — the ONE serving
    # subtree allowed to reach tpu9.parallel) answers to that contract
    # alone, not to every enclosing one.
    for pkg, allowed in cfg.allow.items():
        for mod, targets in edges.items():
            if not (mod == pkg or mod.startswith(pkg + ".")):
                continue
            if any(k != pkg and len(k) > len(pkg)
                   and (mod == k or mod.startswith(k + "."))
                   for k in cfg.allow):
                continue
            for t in targets:
                assert covered(t, allowed, pkg), \
                    f"{mod} imports {t}, outside {pkg}'s allow contract"
    # 2) the forbid edges the engine split depends on are really absent
    for pkg, banned in cfg.forbid.items():
        for mod, targets in edges.items():
            if not (mod == pkg or mod.startswith(pkg + ".")):
                continue
            for t in targets:
                for b in banned:
                    assert not (t == b or t.startswith(b + ".")), \
                        f"{mod} imports {t}, forbidden by {pkg} -> {b}"
    # 3) restricted modules are touched only by their declared importers
    for rmod, importers in cfg.restricted.items():
        for mod, targets in edges.items():
            for t in targets:
                if t == rmod or t.startswith(rmod + "."):
                    assert any(mod == i or mod.startswith(i + ".")
                               for i in importers), \
                        f"{mod} touches restricted {rmod}"
    # 4) the contracts are live: the strong-form packages exist and import
    #    something (an allow entry for a dead package would be vacuous)
    for pkg in ("tpu9.serving", "tpu9.router", "tpu9.ops"):
        assert any(m == pkg or m.startswith(pkg + ".") for m in edges)


def test_slo_observability_contracts_declared_and_live():
    """ISSUE 12 satellite: the fleet SLO/timeline modules carry explicit
    boundary contracts — observability is a closed leaf (no reverse edge
    into serving/router/gateway), and the slo/timeline modules are
    restricted to the control plane + CLI + bench. The cross-check test
    above asserts these against the real import graph; this one asserts
    they are DECLARED (a deleted contract must fail loudly, not vacuously
    pass) and still live."""
    cfg = bnd.BoundaryConfig.load(
        os.path.join(REPO, "tpu9", "analysis", "boundaries.toml"))
    assert "tpu9.observability" in cfg.allow
    # the leaf must not be allowed to reach the planes that record into it
    for banned in ("tpu9.serving", "tpu9.router", "tpu9.gateway",
                   "tpu9.worker"):
        assert banned not in cfg.allow["tpu9.observability"]
    for rmod in ("tpu9.observability.timeline", "tpu9.observability.slo"):
        assert rmod in cfg.restricted, rmod
        importers = cfg.restricted[rmod]
        assert "tpu9.gateway" in importers and "tpu9.cli" in importers
        # serving must NOT grow a reverse edge into the fleet ledger
        assert not any(i == "tpu9.serving" or i.startswith("tpu9.serving.")
                       for i in importers)
    # liveness: the gateway really imports both restricted modules (via
    # fleetobs), so the contracts guard a real edge, not a dead name
    edges = _real_imports()
    gw = edges.get("tpu9.gateway.fleetobs", set())
    assert any(t.startswith("tpu9.observability.timeline") for t in gw)
    assert any(t.startswith("tpu9.observability.slo") for t in gw)


def test_health_plane_contract_declared_and_live():
    """ISSUE 14 satellite: the replica health plane is a closed leaf —
    the watchdog/black-box module is restricted to the runner (watchdog
    on the heartbeat loop), the gateway (verdict fold + black-box store),
    the CLI and bench; the serving engine and the router must NOT import
    it (they exchange plain scalars over the heartbeat). Declared here,
    asserted against the real import graph by the cross-check test."""
    cfg = bnd.BoundaryConfig.load(
        os.path.join(REPO, "tpu9", "analysis", "boundaries.toml"))
    rmod = "tpu9.observability.health"
    assert rmod in cfg.restricted
    importers = cfg.restricted[rmod]
    for needed in ("tpu9.gateway", "tpu9.runner", "tpu9.worker",
                   "tpu9.cli"):
        assert needed in importers, importers
    # NO reverse edge into the planes the watchdog judges
    for banned in ("tpu9.serving", "tpu9.router"):
        assert not any(i == banned or i.startswith(banned + ".")
                       for i in importers), importers
    # liveness: the runner (watchdog + post-mortem ship) and the gateway
    # (gauge publication + black-box clamp) really import the module —
    # the contract guards real edges, not a dead name
    edges = _real_imports()
    assert any(t.startswith(rmod)
               for t in edges.get("tpu9.runner.llm", set()))
    gw_edges = (edges.get("tpu9.gateway.fleetobs", set())
                | edges.get("tpu9.gateway.gateway", set()))
    assert any(t.startswith(rmod) for t in gw_edges)
    assert any(t.startswith(rmod)
               for t in edges.get("tpu9.worker.lifecycle", set()))
    # and the serving/router planes genuinely do not
    for mod, targets in edges.items():
        if mod.startswith("tpu9.serving") or mod.startswith("tpu9.router"):
            assert not any(t.startswith(rmod) for t in targets), mod


def test_fault_plane_contract_declared_and_live():
    """ISSUE 15 satellite: the fault-injection plane is chaos tooling —
    restricted to its declared hook sites (runner/worker/cache, all
    env-gated lazy imports), the test plane and bench. The gateway/
    router/serving planes must never import it: the recovery machinery
    under test cannot depend on the failure injector."""
    cfg = bnd.BoundaryConfig.load(
        os.path.join(REPO, "tpu9", "analysis", "boundaries.toml"))
    rmod = "tpu9.testing.faults"
    assert rmod in cfg.restricted
    importers = cfg.restricted[rmod]
    for needed in ("tpu9.runner", "tpu9.worker", "tpu9.cache",
                   "tpu9.testing"):
        assert needed in importers, importers
    for banned in ("tpu9.gateway", "tpu9.router", "tpu9.serving"):
        assert not any(i == banned or i.startswith(banned + ".")
                       for i in importers), importers
    # liveness: the declared hook sites really import it (lazily)
    edges = _real_imports()
    assert any(t.startswith(rmod)
               for t in edges.get("tpu9.runner.llm", set()))
    assert any(t.startswith(rmod)
               for t in edges.get("tpu9.cache.client", set()))
    assert any(t.startswith(rmod)
               for t in edges.get("tpu9.worker.worker", set()))
    # and the production planes genuinely do not
    for mod, targets in edges.items():
        if (mod.startswith("tpu9.gateway") or mod.startswith("tpu9.router")
                or mod.startswith("tpu9.serving")):
            assert not any(t.startswith(rmod) for t in targets), mod
    # the hook-site imports are env-GATED: a production container without
    # TPU9_FAULTS never executes them (source-level check on the gate —
    # the raw environ read now lives in config.env_faults_spec, ISSUE 18)
    for rel in ("tpu9/runner/llm.py", "tpu9/cache/client.py",
                "tpu9/worker/worker.py"):
        src = open(os.path.join(REPO, rel)).read()
        gate = src.index("if env_faults_spec()")
        imp = src.index("from ..testing.faults import")
        assert gate < imp, f"{rel}: faults import is not env-gated"
    cfg_src = open(os.path.join(REPO, "tpu9", "config.py")).read()
    assert 'os.environ.get("TPU9_FAULTS"' in cfg_src


def test_kvwire_contract_declared_and_live():
    """ISSUE 16 satellite: the KV wire format is a serialization boundary
    — restricted to the two ends of the pipe (serving encodes/decodes,
    the runner moves payloads between transport and engine), the cache
    transport and bench. The gateway and router must NEVER import it:
    they speak keys/flags/token counts, and a payload crossing the
    control plane is exactly the layering bug this contract catches."""
    cfg = bnd.BoundaryConfig.load(
        os.path.join(REPO, "tpu9", "analysis", "boundaries.toml"))
    rmod = "tpu9.serving.kvwire"
    assert rmod in cfg.restricted
    importers = cfg.restricted[rmod]
    for needed in ("tpu9.serving", "tpu9.runner", "tpu9.cache", "bench"):
        assert needed in importers, importers
    for banned in ("tpu9.gateway", "tpu9.router"):
        assert not any(i == banned or i.startswith(banned + ".")
                       for i in importers), importers
    # liveness: the pool (encode/decode) and the runner (header peeks on
    # publish/drain) really import the module — real edges, not a name
    edges = _real_imports()
    assert any(t.startswith(rmod)
               for t in edges.get("tpu9.serving.kvpool", set()))
    assert any(t.startswith(rmod)
               for t in edges.get("tpu9.runner.llm", set()))
    # and the control plane genuinely does not touch payloads
    for mod, targets in edges.items():
        if mod.startswith("tpu9.gateway") or mod.startswith("tpu9.router"):
            assert not any(t.startswith(rmod) for t in targets), mod
    # the module runs on the replica: the hot-path policy must cover it
    raw = tomlmini.load_file(
        os.path.join(REPO, "tpu9", "analysis", "boundaries.toml"))
    assert "tpu9/serving/kvwire.py" in raw["jax"]["hotpath"]["files"]


def test_scaleout_contract_declared_and_live():
    """ISSUE 17 satellite: the scale-out plane is a closed subsystem —
    an [allow] contract caps its import surface (cache/observability/
    config/utils; never serving, router, gateway or worker: the planes
    CALL it, it calls nobody back), and a [restricted] list names its
    only importers (gateway coordinator host, abstractions predictive
    wrapper, CLI tree-hint bootstrap, bench). Declared here, asserted
    against the real import graph by the cross-check test above."""
    cfg = bnd.BoundaryConfig.load(
        os.path.join(REPO, "tpu9", "analysis", "boundaries.toml"))
    assert "tpu9.scaleout" in cfg.allow
    for banned in ("tpu9.serving", "tpu9.router", "tpu9.gateway",
                   "tpu9.worker", "tpu9.abstractions"):
        assert banned not in cfg.allow["tpu9.scaleout"]
    assert "tpu9.scaleout" in cfg.restricted
    importers = cfg.restricted["tpu9.scaleout"]
    for needed in ("tpu9.gateway", "tpu9.abstractions", "tpu9.cli",
                   "bench"):
        assert needed in importers, importers
    # the serving engine ships flat scaleout_* scalars over the
    # heartbeat and the router parses plain stats — no import edge
    for banned in ("tpu9.serving", "tpu9.router"):
        assert not any(i == banned or i.startswith(banned + ".")
                       for i in importers), importers
    # serving's loud forbid list names the reverse edge explicitly
    assert "tpu9.scaleout" in cfg.forbid["tpu9.serving"]
    # liveness: the declared importers really import it — the gateway
    # (coordinator + report), fleetobs (ledger feed + plan publish) and
    # the abstractions endpoint (predictive policy wrap)
    edges = _real_imports()
    gw_edges = (edges.get("tpu9.gateway.gateway", set())
                | edges.get("tpu9.gateway.fleetobs", set()))
    assert any(t.startswith("tpu9.scaleout") for t in gw_edges)
    assert any(t.startswith("tpu9.scaleout")
               for t in edges.get("tpu9.abstractions.endpoint", set()))
    # and the serving/router planes genuinely do not
    for mod, targets in edges.items():
        if mod.startswith("tpu9.serving") or mod.startswith("tpu9.router"):
            assert not any(t.startswith("tpu9.scaleout")
                           for t in targets), mod


def test_tomlmini_parses_boundaries_toml():
    raw = tomlmini.load_file(
        os.path.join(REPO, "tpu9", "analysis", "boundaries.toml"))
    assert "tpu9.serving" in raw["allow"]
    assert raw["jax"]["hotpath"]["roots"] == ["_serve_loop",
                                              "_serve_loop_inner"]
    assert "tpu9/serving/engine.py" in raw["jax"]["hotpath"]["files"]
