"""End-to-end: deploy → autoscale from zero → real runner subprocess →
request forwarding → scale down. This exercises the full call stack of
SURVEY.md §3.2/§3.3 (minus OCI/caching): gateway HTTP → endpoint instance →
request buffer → scheduler backlog → worker selection → pool scale-up →
process runtime spawn → readiness probe → reverse proxy → response.
"""

import asyncio

import pytest

from tpu9.testing.localstack import LocalStack
from tpu9.types import ContainerStatus

pytestmark = pytest.mark.e2e

SLOW_HANDLER = """
import time, os
def handler(**kwargs):
    time.sleep(kwargs.get("sleep", 0))
    return {"pid": os.getpid(), "got": kwargs}
"""

FAILING_IMPORT = """
raise RuntimeError("boom at import")
"""


async def test_endpoint_cold_start_and_echo():
    async with LocalStack() as stack:
        dep = await stack.deploy_echo_endpoint("echo")
        out = await stack.invoke(dep, {"x": 1, "y": "z"})
        assert out["echo"] == {"x": 1, "y": "z"}
        # a second request hits the warm container (same pid)
        out2 = await stack.invoke(dep, {"x": 2})
        assert out2["pid"] == out["pid"]
        # exactly one container running
        running = await stack.running_containers(dep["stub_id"])
        assert len(running) == 1


async def test_scale_to_zero_and_back():
    async with LocalStack() as stack:
        dep = await stack.deploy_echo_endpoint("scaler")
        out1 = await stack.invoke(dep, {"n": 1})
        await stack.scale_to_zero(dep)
        assert await stack.running_containers(dep["stub_id"]) == []
        # next request cold-starts a fresh container
        out2 = await stack.invoke(dep, {"n": 2})
        assert out2["pid"] != out1["pid"]


async def test_concurrent_requests_fan_out():
    async with LocalStack() as stack:
        dep = await stack.deploy_endpoint(
            "fan", {"app.py": SLOW_HANDLER}, "app:handler",
            config_extra={"concurrent_requests": 1,
                          "autoscaler": {"max_containers": 3,
                                         "tasks_per_container": 1}})
        results = await asyncio.gather(*[
            stack.invoke(dep, {"sleep": 1.0, "i": i}) for i in range(3)])
        pids = {r["pid"] for r in results}
        assert len(pids) >= 2, f"expected fan-out across containers, got {pids}"


async def test_worker_reports_failure_on_bad_handler():
    async with LocalStack() as stack:
        dep = await stack.deploy_endpoint(
            "broken", {"app.py": FAILING_IMPORT}, "app:handler",
            config_extra={"timeout_s": 10.0})
        status, _ = await stack.api("POST", "/endpoint/broken",
                                    json_body={}, timeout=30.0)
        # request cannot be served: readiness never passes → 504 from buffer
        assert status in (502, 504)


async def test_handler_error_returns_500():
    bad = """
def handler(**kwargs):
    raise ValueError("user bug")
"""
    async with LocalStack() as stack:
        dep = await stack.deploy_endpoint("oops", {"app.py": bad},
                                          "app:handler")
        status, out = await stack.api("POST", "/endpoint/oops", json_body={})
        assert status == 500
        assert "user bug" in out["error"]


async def test_auth_enforced():
    async with LocalStack() as stack:
        dep = await stack.deploy_echo_endpoint("private")
        # no token → 401
        import aiohttp
        async with aiohttp.ClientSession() as s:
            async with s.post(f"{stack.base_url}/endpoint/private",
                              json={}) as resp:
                assert resp.status == 401
            async with s.get(f"{stack.base_url}/api/v1/worker") as resp:
                assert resp.status == 401


async def test_rest_api_surfaces():
    async with LocalStack() as stack:
        dep = await stack.deploy_echo_endpoint("api-test")
        await stack.invoke(dep, {"a": 1})
        status, deployments = await stack.api("GET", "/api/v1/deployment")
        assert status == 200 and deployments[0]["name"] == "api-test"
        status, containers = await stack.api("GET", "/api/v1/container")
        assert status == 200 and len(containers) == 1
        status, workers = await stack.api("GET", "/api/v1/worker")
        assert status == 200 and len(workers) >= 1 and workers[0]["alive"]
        container_id = containers[0]["container_id"]
        status, logs = await stack.api(
            "GET", f"/api/v1/container/{container_id}/logs")
        assert status == 200
        # secrets CRUD
        status, _ = await stack.api("POST", "/api/v1/secret",
                                    json_body={"name": "K", "value": "v"})
        assert status == 200
        status, names = await stack.api("GET", "/api/v1/secret")
        assert names == ["K"]


async def test_subdomain_routing():
    """Host-header routing (reference middleware/subdomain.go): the
    deployment's subdomain resolves without a path-based route or token when
    the stub is public."""
    import aiohttp
    async with LocalStack() as stack:
        object_id = await stack.upload_workspace(
            {"app.py": "def handler(**kw):\n    return {'via': 'subdomain'}\n"})
        _, out = await stack.api("POST", "/rpc/stub/get-or-create", json_body={
            "name": "pub", "stub_type": "endpoint",
            "config": {"handler": "app:handler", "authorized": False,
                       "keep_warm_seconds": 2.0},
            "object_id": object_id})
        _, dep = await stack.api("POST", "/rpc/deploy", json_body={
            "stub_id": out["stub_id"], "name": "pub"})
        sub = dep["subdomain"]          # globally unique: name-version-wstag
        async with aiohttp.ClientSession() as s:
            async with s.post(f"{stack.base_url}/",
                              headers={"Host": f"{sub}.tpu9.example"},
                              json={}) as resp:
                assert resp.status == 200, await resp.text()
                assert (await resp.json()) == {"via": "subdomain"}
            # unknown subdomain → 404
            async with s.post(f"{stack.base_url}/",
                              headers={"Host": "nope-9.tpu9.example"},
                              json={}) as resp:
                assert resp.status == 404


async def test_oom_watcher_kills_over_limit_container():
    """RSS-based OOM enforcement (pkg/runtime/oom_watcher.go analogue):
    a container exceeding its memory_mb is killed and marked OOM."""
    hog = """
import time
def handler(**kw):
    blob = bytearray(300 * 1024 * 1024)   # 300MB RSS vs 128MB limit
    for i in range(0, len(blob), 4096):
        blob[i] = 1                        # force residency
    time.sleep(30)
    return {"survived": True}
"""
    async with LocalStack() as stack:
        stack.cfg.worker.heartbeat_interval_s = 1.0
        dep = await stack.deploy_endpoint(
            "hog", {"app.py": hog}, "app:handler",
            config_extra={"timeout_s": 60.0,
                          "runtime": {"cpu_millicores": 1000,
                                      "memory_mb": 128}})
        status, _ = await stack.api("POST", "/endpoint/hog", json_body={},
                                    timeout=90)
        assert status in (502, 504)        # request died with the container
        # at least one container must record an OOM exit (the supervisor
        # writes it moments after the kill severs the request)
        import json
        all_exits = []
        for _ in range(50):
            all_exits = []
            for key in await stack.store.keys("container:exit:*"):
                raw = await stack.store.get(key)
                if raw:
                    all_exits.append(json.loads(raw))
            if any(e.get("reason") == "oom" for e in all_exits):
                break
            await asyncio.sleep(0.2)
        assert any(e.get("reason") == "oom" for e in all_exits), all_exits
