"""E2E chaos (ISSUE 15 acceptance): a replica killed or stalled
MID-SSE-STREAM by the fault plane, with the gateway resuming the stream
on a healthy replica. The client must receive the complete,
duplicate-free token sequence (greedy determinism across same-weight
replicas makes "complete and duplicate-free" an exact-equality check
against an unkilled reference run), with exactly one `gateway.failover`
span on the request's trace, and the idempotency journal answering a
client-initiated retry of the completed request instead of
double-executing it."""

import asyncio
import json
import os
import time

import aiohttp
import pytest

from tpu9.testing.localstack import LocalStack

pytestmark = pytest.mark.e2e

# Plain engine — the FAULTS are injected by the runner's fault plane
# (TPU9_FAULTS env), not a hand-rolled FaultyEngine subclass. Same
# PRNGKey on every replica: greedy output is replica-independent, so the
# spliced stream must equal the unkilled reference exactly.
LLM_APP = """
def load_engine():
    from dataclasses import replace
    import jax
    from tpu9.models import init_decoder
    from tpu9.models.llama import LLAMA_PRESETS
    from tpu9.serving import EngineConfig, InferenceEngine

    cfg = replace(LLAMA_PRESETS["llama-tiny"])
    params = init_decoder(jax.random.PRNGKey(0), cfg)
    return InferenceEngine(params, cfg,
                           EngineConfig(max_batch=2, max_seq_len=256,
                                        prefill_buckets=(16, 64),
                                        kv_block_size=16))
"""

PROMPT = [5, 3, 9]
MAX_NEW = 200


async def _direct_generate(address: str, max_new: int, timeout: float):
    async with aiohttp.ClientSession() as sess:
        async with sess.post(
                f"http://{address}/",
                json={"tokens": PROMPT, "max_new_tokens": max_new},
                timeout=aiohttp.ClientTimeout(total=timeout)) as resp:
            return resp.status, await resp.json()


async def _warm_replicas(stack, stub_id, n, timeout=120.0):
    states = await stack.running_containers(stub_id)
    assert len(states) == n
    addr = {s.container_id: s.address for s in states}
    for cid, address in addr.items():
        deadline = time.monotonic() + timeout
        while True:
            try:
                status, out = await _direct_generate(address, 4, timeout)
                assert status == 200, out
                break
            except aiohttp.ClientError:
                assert time.monotonic() < deadline, f"{cid} never up"
                await asyncio.sleep(0.5)
    return addr


async def _stream_with_mid_flight_fault(stack, endpoint, flag_path_for,
                                        request_id, fault_after=5):
    """Open the SSE stream through the gateway, identify the serving
    replica from the router's live budget ledger (in-process), arm the
    per-replica fault flag after ``fault_after`` tokens, and collect the
    full event stream."""
    router = stack.gateway.fleet_router
    events = []
    victim = None
    async with aiohttp.ClientSession() as sess:
        async with sess.post(
                stack.base_url + endpoint,
                json={"tokens": PROMPT, "max_new_tokens": MAX_NEW,
                      "stream": True},
                headers={"Accept": "text/event-stream",
                         "Authorization":
                         f"Bearer {stack.gateway.default_token}",
                         "X-Tpu9-Request-Id": request_id},
                timeout=aiohttp.ClientTimeout(total=240)) as resp:
            assert resp.status == 200, await resp.text()
            buf = b""
            async for chunk in resp.content.iter_any():
                buf += chunk
                while b"\n\n" in buf:
                    frame, buf = buf.split(b"\n\n", 1)
                    if frame.startswith(b"data: "):
                        events.append(json.loads(frame[6:]))
                n_tokens = sum(1 for e in events if "token" in e)
                if victim is None and n_tokens >= fault_after:
                    # the live stream holds exactly one budget slot:
                    # that replica is the victim
                    inflight = {cid: n for cid, n
                                in router.budgets._inflight.items()
                                if n > 0}
                    assert len(inflight) == 1, inflight
                    victim = next(iter(inflight))
                    open(flag_path_for(victim), "w").close()
    return events, victim


def _assert_seamless(events, reference):
    toks = [e["token"] for e in events if "token" in e]
    dones = [e for e in events if e.get("done")]
    errors = [e for e in events if "error" in e]
    assert not errors, f"client saw an error event: {errors}"
    assert len(dones) == 1, f"expected exactly one done event: {dones}"
    # the complete, duplicate-free sequence: exact equality against the
    # unkilled greedy reference — any duplicated or skipped token across
    # the splice breaks this
    assert toks == reference, (
        f"splice broke the stream: got {len(toks)} tokens, "
        f"reference {len(reference)}; first divergence at "
        f"{next((i for i, (a, b) in enumerate(zip(toks, reference)) if a != b), 'length')}")
    assert dones[0]["tokens"] == reference


async def _failover_spans(stack, stub_id):
    status, data = await stack.api("GET", "/api/v1/traces?limit=4000")
    assert status == 200
    return [s for s in data["spans"] if s["name"] == "gateway.failover"]


async def test_replica_crash_mid_stream_resumes_seamlessly(tmp_path):
    flag_dir = str(tmp_path)
    async with LocalStack() as stack:
        dep = await stack.deploy_endpoint(
            "chaosllm", {"app.py": LLM_APP}, "app:load_engine",
            config_extra={
                "timeout_s": 240.0,
                "concurrent_requests": 2,
                "extra": {"runner": "llm"},
                "env": {"TPU9_FAULTS": "crash:flag=1",
                        "TPU9_FAULTS_FLAG_DIR": flag_dir,
                        "TPU9_PRESSURE_INTERVAL_S": "0.5"},
                "autoscaler": {"max_containers": 2,
                               "min_containers": 2}})
        await stack.wait_running(dep["stub_id"], 2, timeout=120.0)
        addr = await _warm_replicas(stack, dep["stub_id"], 2)

        # unkilled greedy reference (no flag armed yet — the fault plane
        # is inert until the per-replica flag file exists)
        any_addr = next(iter(addr.values()))
        status, ref = await _direct_generate(any_addr, MAX_NEW, 240)
        assert status == 200 and len(ref["tokens"]) == MAX_NEW

        events, victim = await _stream_with_mid_flight_fault(
            stack, "/endpoint/chaosllm",
            lambda cid: os.path.join(flag_dir, f"crash-{cid}"),
            request_id="e2e-crash-1")
        assert victim is not None
        _assert_seamless(events, ref["tokens"])

        # exactly ONE failover span on the trace tree, naming the victim
        spans = await _failover_spans(stack, dep["stub_id"])
        assert len(spans) == 1, spans
        attrs = spans[0]["attributes"]
        assert attrs["failed_replica"] == victim
        assert attrs["watermark"] >= 5
        assert attrs["reason"] in ("engine_error", "stream_eof",
                                   "stream_gap") \
            or attrs["reason"].startswith("transport_"), attrs

        # decision ledger (ISSUE 19): the WHY chain for this same
        # request id — admission, stream placement, the failover retry
        # naming the victim, and the resume-mode verdict (no drain ran,
        # so no KV key was announced: block-ship is the REJECTED
        # alternative and re-prefill the chosen one) — in seq order
        trace_id = spans[0]["traceId"]
        status, dec = await stack.api(
            "GET", f"/api/v1/decisions?request_id={trace_id}&limit=100")
        assert status == 200
        chain = dec["records"]
        kinds = [(r["plane"], r["decision"]) for r in chain]
        for want in (("admission", "admitted"),
                     ("placement", "stream_admit"),
                     ("failover", "retry"),
                     ("failover", "resume_mode")):
            assert want in kinds, kinds
        assert kinds.index(("admission", "admitted")) \
            < kinds.index(("placement", "stream_admit")) \
            < kinds.index(("failover", "retry")) \
            < kinds.index(("failover", "resume_mode")), kinds
        seqs = [r["seq"] for r in chain]
        assert seqs == sorted(seqs)
        retry = next(r for r in chain if r["decision"] == "retry")
        assert retry["rejected"][0]["alternative"] == victim
        assert retry["signals"]["failed_attempt"] == 1
        resume = next(r for r in chain if r["decision"] == "resume_mode")
        assert resume["chosen"] == "re_prefill"
        assert resume["rejected"] == [
            {"alternative": "block_ship",
             "reason": "no_kv_key_announced"}]
        assert resume["signals"]["watermark"] >= 5
        placed = next(r for r in chain if r["decision"] == "stream_admit")
        assert placed["chosen"] == victim
        assert placed["workspace_id"]

        # `tpu9 why <request-id>`: the same chain interleaved with the
        # span tree, via the real CLI against the live gateway
        from click.testing import CliRunner
        from tpu9.cli.main import cli as tpu9_cli
        env = {"TPU9_GATEWAY_URL": stack.base_url,
               "TPU9_TOKEN": stack.gateway.default_token}
        res = await asyncio.to_thread(
            lambda: CliRunner().invoke(tpu9_cli, ["why", trace_id],
                                       env=env))
        assert res.exit_code == 0, res.output
        lines = res.output.splitlines()

        def _line(snippet):
            idx = [i for i, ln in enumerate(lines) if snippet in ln]
            assert idx, (snippet, res.output)
            return idx[0]

        assert _line("admission") < _line("stream_admit") \
            < _line("attempt_2") < _line("re_prefill")
        assert any("gateway.failover" in ln for ln in lines), res.output
        assert "no_kv_key_announced" in res.output

        # the victim's engine really died (the crash was real, not a
        # transport blip) and left a post-mortem behind
        beat = {}
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline:
            beat = await stack.gateway.store.hgetall(
                f"llm:pressure:{victim}") or {}
            if str(beat.get("health", "")) == "stalled":
                break
            await asyncio.sleep(0.3)
        assert str(beat.get("health", "")) == "stalled", beat

        # idempotency journal: a client retry of the COMPLETED request
        # id attaches to the journal instead of re-executing
        status, replay = await stack.api(
            "POST", "/endpoint/chaosllm",
            json_body={"tokens": PROMPT, "max_new_tokens": MAX_NEW,
                       "stream": True},
            headers={"Accept": "text/event-stream",
                     "X-Tpu9-Request-Id": "e2e-crash-1"},
            timeout=60)
        assert status == 409, replay
        assert replay["tokens_delivered"] == MAX_NEW
        assert replay["attempts"] >= 2


async def test_replica_stall_mid_stream_fails_over_on_gap(tmp_path,
                                                          monkeypatch):
    """Gray stall mid-generation: the victim's dispatch wedges (no
    tokens, no error, runner heartbeat alive) — the relay's per-chunk
    gap bound declares the stream wedged and failover resumes it."""
    flag_dir = str(tmp_path)
    # tight gap so the e2e stays fast (the buffer reads this per call)
    monkeypatch.setenv("TPU9_STREAM_GAP_S", "2.0")
    async with LocalStack() as stack:
        dep = await stack.deploy_endpoint(
            "stallllm", {"app.py": LLM_APP}, "app:load_engine",
            config_extra={
                "timeout_s": 240.0,
                "concurrent_requests": 2,
                "extra": {"runner": "llm"},
                "env": {"TPU9_FAULTS": "stall:flag=1,duration_s=120",
                        "TPU9_FAULTS_FLAG_DIR": flag_dir,
                        "TPU9_PRESSURE_INTERVAL_S": "0.5"},
                "autoscaler": {"max_containers": 2,
                               "min_containers": 2}})
        await stack.wait_running(dep["stub_id"], 2, timeout=120.0)
        addr = await _warm_replicas(stack, dep["stub_id"], 2)
        any_addr = next(iter(addr.values()))
        status, ref = await _direct_generate(any_addr, MAX_NEW, 240)
        assert status == 200 and len(ref["tokens"]) == MAX_NEW

        events, victim = await _stream_with_mid_flight_fault(
            stack, "/endpoint/stallllm",
            lambda cid: os.path.join(flag_dir, f"stall-{cid}"),
            request_id="e2e-stall-1")
        assert victim is not None
        _assert_seamless(events, ref["tokens"])
        spans = await _failover_spans(stack, dep["stub_id"])
        assert len(spans) == 1, spans
        assert spans[0]["attributes"]["reason"] == "stream_gap"
        assert spans[0]["attributes"]["failed_replica"] == victim


async def test_buffered_request_retries_transparently(tmp_path):
    """Non-stream failover: a buffered request landing on a crashed
    replica (engine dead, container still RUNNING) is re-submitted
    through the router transparently — the client sees one 200, with a
    failover span on its trace."""
    flag_dir = str(tmp_path)
    async with LocalStack() as stack:
        dep = await stack.deploy_endpoint(
            "bufllm", {"app.py": LLM_APP}, "app:load_engine",
            config_extra={
                "timeout_s": 240.0,
                "concurrent_requests": 2,
                "extra": {"runner": "llm"},
                # SLOW beat: the health plane must not eject the victim
                # before this test's dispatch can land on it — the
                # failover has to do the saving, not the watchdog
                "env": {"TPU9_FAULTS": "crash:flag=1",
                        "TPU9_FAULTS_FLAG_DIR": flag_dir,
                        "TPU9_PRESSURE_INTERVAL_S": "5.0"},
                "autoscaler": {"max_containers": 2,
                               "min_containers": 2}})
        await stack.wait_running(dep["stub_id"], 2, timeout=120.0)
        addr = await _warm_replicas(stack, dep["stub_id"], 2)
        cids = sorted(addr)
        victim = cids[0]

        # kill the victim's engine: arm its flag, then trip the crash
        # with a direct request (the chaos trigger, not the client)
        open(os.path.join(flag_dir, f"crash-{victim}"), "w").close()
        status, out = await _direct_generate(addr[victim], 16, 60)
        assert status != 200, out

        # pin the next request's affinity onto the DEAD victim so the
        # dispatch deterministically lands there first
        body = json.dumps({"tokens": [7, 7, 7, 7],
                           "max_new_tokens": 8}).encode()
        router = stack.gateway.fleet_router
        router.affinity.record_served(body, victim)

        status, out = await stack.api(
            "POST", "/endpoint/bufllm",
            json_body={"tokens": [7, 7, 7, 7], "max_new_tokens": 8},
            timeout=120)
        assert status == 200, out
        assert len(out["tokens"]) == 8
        spans = await _failover_spans(stack, dep["stub_id"])
        assert len(spans) == 1, spans
        assert spans[0]["attributes"]["failed_replica"] == victim
        assert spans[0]["attributes"]["failed_status"] in (500, 502)
