"""Typed input/output schemas (tpu9/schema.py).

Reference parity: sdk/src/beta9/schema.py (field validation, dynamic
from-spec round trip, Schema.object builder) + runner-side enforcement
(sdk/src/beta9/runner/common.py:212-221). The e2e case drives a
schema-validated endpoint through the full local stack: bad input → 400
with a field error before user code runs; good input → coerced kwargs.
"""

import base64

import pytest

from tpu9.schema import (JSON, Array, Boolean, Field, File, Float, Integer,
                         Object, Schema, String, ValidationError, schema_spec)
from tpu9.testing.localstack import LocalStack


class Inputs(Schema):
    prompt = String()
    max_tokens = Integer(required=False, default=16)


def test_basic_validation_and_defaults():
    out = Inputs.validate({"prompt": "hi"})
    assert out == {"prompt": "hi", "max_tokens": 16}
    out = Inputs.validate({"prompt": "hi", "max_tokens": 3})
    assert out["max_tokens"] == 3


def test_missing_and_wrong_type_raise():
    with pytest.raises(ValidationError) as e:
        Inputs.validate({})
    assert e.value.field == "prompt"
    with pytest.raises(ValidationError):
        Inputs.validate({"prompt": 7})
    with pytest.raises(ValidationError):
        Inputs.validate({"prompt": "x", "max_tokens": "many"})
    with pytest.raises(ValidationError):
        Inputs.validate({"prompt": "x", "max_tokens": True})  # bool ≠ int
    with pytest.raises(ValidationError):
        Inputs.validate("not a dict")


def test_float_bool_json_array():
    class S(Schema):
        temp = Float()
        flag = Boolean()
        meta = JSON()
        tags = Array(String())

    out = S.validate({"temp": 1, "flag": False, "meta": {"a": [1]},
                      "tags": ["x", "y"]})
    assert out["temp"] == 1.0 and isinstance(out["temp"], float)
    with pytest.raises(ValidationError):
        S.validate({"temp": 1, "flag": 0, "meta": {}, "tags": []})
    with pytest.raises(ValidationError):
        S.validate({"temp": 1, "flag": True, "meta": {}, "tags": ["x", 2]})


def test_file_field_base64_round_trip():
    f = File()
    data = b"\x00\x01binary"
    b64 = base64.b64encode(data).decode()
    assert f.check(b64) == data
    assert f.check(f"data:application/octet-stream;base64,{b64}") == data
    assert f.check(data) == data
    assert base64.b64decode(f.encode(data)) == data
    with pytest.raises(ValidationError):
        f.check("!!! not base64 !!!")
    with pytest.raises(ValidationError):
        File(max_bytes=2).check(b64)


def test_nested_object_and_spec_round_trip():
    class Inner(Schema):
        name = String()

    class Outer(Schema):
        item = Object(Inner)
        n = Integer()

    spec = Outer.to_spec()
    rebuilt = Schema.from_spec(spec)
    out = rebuilt.validate({"item": {"name": "a"}, "n": 1})
    assert out["item"] == {"name": "a"}
    with pytest.raises(ValidationError):
        rebuilt.validate({"item": {"name": 5}, "n": 1})
    # specs survive JSON (what the stub config / env transport does)
    import json
    assert Schema.from_spec(json.loads(json.dumps(spec))).validate(
        {"item": {"name": "b"}, "n": 2})["n"] == 2


def test_schema_object_dynamic_builder():
    S = Schema.object({"x": Integer(), "nested": {"y": String()}})
    out = S.validate({"x": 1, "nested": {"y": "z"}})
    assert out == {"x": 1, "nested": {"y": "z"}}
    with pytest.raises(TypeError):
        Schema.object({"x": 42})


def test_schema_instance_and_dump():
    inst = Inputs(prompt="p")
    assert inst.prompt == "p" and inst.max_tokens == 16
    assert inst.dump() == {"prompt": "p", "max_tokens": 16}


def test_output_encode_passthrough_extras():
    class Out(Schema):
        blob = File()

    enc = Out.encode({"blob": b"abc", "extra": 1})
    assert base64.b64decode(enc["blob"]) == b"abc"
    assert enc["extra"] == 1


def test_schema_spec_normalizer():
    assert schema_spec(None) is None
    assert schema_spec(Inputs)["fields"]["prompt"]["kind"] == "string"
    assert schema_spec({"x": Integer()})["fields"]["x"]["kind"] == "integer"
    spec = schema_spec(Inputs.to_spec())
    assert spec["fields"]["max_tokens"]["required"] is False
    with pytest.raises(TypeError):
        schema_spec(42)


def test_unknown_kind_rejected():
    with pytest.raises(ValidationError):
        Field.from_spec({"kind": "nope"})


SCHEMA_HANDLER = """
def handler(**kwargs):
    return {"got": kwargs, "type": type(kwargs.get("max_tokens")).__name__}
"""


@pytest.mark.e2e
async def test_endpoint_schema_enforced_e2e():
    async with LocalStack() as stack:
        dep = await stack.deploy_endpoint(
            "schemaed", {"app.py": SCHEMA_HANDLER}, "app:handler",
            config_extra={"inputs": Inputs.to_spec()})
        out = await stack.invoke(dep, {"prompt": "hi"})
        assert out["got"] == {"prompt": "hi", "max_tokens": 16}
        assert out["type"] == "int"
        status, payload = await stack.api(
            "POST", "/endpoint/schemaed", json_body={"max_tokens": 4},
            timeout=60.0)
        assert status == 400, (status, payload)
        assert payload["field"] == "prompt"


def test_output_schema_errors_are_server_side():
    from tpu9.schema import OutputValidationError

    class Out(Schema):
        n = Integer()

    with pytest.raises(OutputValidationError):
        Out.encode_output({})          # missing required output field
    with pytest.raises(OutputValidationError):
        # bytes required by File.encode; an int is a handler bug
        type("O2", (Schema,), {"f": File()}).encode_output({"f": 42})
    assert Out.encode_output({"n": 1, "extra": "ok"}) == {"n": 1,
                                                          "extra": "ok"}


async def test_handler_output_schema_enforced():
    from tpu9.runner.common import FunctionHandler, RunnerConfig
    from tpu9.schema import OutputValidationError

    class Out(Schema):
        blob = File()

    import os
    import sys
    import tempfile
    with tempfile.TemporaryDirectory() as d:
        with open(os.path.join(d, "outmod.py"), "w") as f:
            f.write("def h(**kw):\n    return {'blob': b'xyz'}\n"
                    "def bad(**kw):\n    return {}\n")
        cfg = RunnerConfig(handler="outmod:h", workdir=d,
                           outputs=Out.to_spec())
        h = FunctionHandler(cfg)
        try:
            result = await h.call()
            assert result["blob"] == base64.b64encode(b"xyz").decode()
            cfg2 = RunnerConfig(handler="outmod:bad", workdir=d,
                                outputs=Out.to_spec())
            h2 = FunctionHandler(cfg2)
            with pytest.raises(OutputValidationError):
                await h2.call()
        finally:
            sys.modules.pop("outmod", None)
