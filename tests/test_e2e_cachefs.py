"""FUSE CacheFS (VERDICT r03 missing #5): read-through mounts whose page
faults stream chunks from the distributed cache — covering mmap and
static-binary readers the LD_PRELOAD shims cannot.

Reference analogue: pkg/cache/cachefs.go:47 (+ cachefs_node.go).
Root-gated: needs /dev/fuse and the t9cachefs binary.
"""

import asyncio
import hashlib
import mmap
import os

import pytest

from tpu9.cache import CacheClient, DiskStore
from tpu9.cache.fusefs import CacheFsManager
from tpu9.images.manifest import snapshot_dir

pytestmark = [
    pytest.mark.e2e,
    pytest.mark.skipif(not CacheFsManager.supported(),
                       reason="needs root + /dev/fuse + t9cachefs"),
]


async def _setup(tmp_path, populate_store: bool):
    """A manifest over a small tree; chunks live either in the local store
    (warm) or only behind the client's source fn (cold → fault path)."""
    src = tmp_path / "src"
    (src / "sub").mkdir(parents=True)
    big = os.urandom(5 * 1024 * 1024 + 333)       # spans chunks
    (src / "sub" / "weights.bin").write_bytes(big)
    (src / "hello.txt").write_bytes(b"hi fuse\n")
    os.symlink("hello.txt", src / "link.txt")

    origin: dict[str, bytes] = {}
    manifest = snapshot_dir(str(src), chunk_bytes=2 * 1024 * 1024,
                            put_chunk=lambda d, h: origin.__setitem__(h, d))
    manifest.image_id = "cfs-test"

    store = DiskStore(str(tmp_path / "store"))

    async def peers():
        return []

    async def source(digest):
        return origin.get(digest)

    client = CacheClient(store, peers, source=source)
    if populate_store:
        for h, d in origin.items():
            await store.put(d, h)
    return manifest, client, big


async def test_warm_mount_reads_and_mmap(tmp_path):
    manifest, client, big = await _setup(tmp_path, populate_store=True)
    mgr = CacheFsManager(client, str(tmp_path / "fuse"))
    mnt = str(tmp_path / "mnt")
    mount = await mgr.mount(manifest, mnt)
    try:
        assert sorted(os.listdir(mnt)) == ["hello.txt", "link.txt", "sub"]
        assert open(os.path.join(mnt, "hello.txt"), "rb").read() \
            == b"hi fuse\n"
        assert os.readlink(os.path.join(mnt, "link.txt")) == "hello.txt"
        p = os.path.join(mnt, "sub", "weights.bin")
        assert os.path.getsize(p) == len(big)
        data = open(p, "rb").read()
        assert hashlib.sha256(data).hexdigest() \
            == hashlib.sha256(big).hexdigest()
        # mmap — the reader class LD_PRELOAD fundamentally cannot gate
        with open(p, "rb") as f:
            mm = mmap.mmap(f.fileno(), 0, prot=mmap.PROT_READ)
            assert mm[2 * 1024 * 1024 - 5:2 * 1024 * 1024 + 5] \
                == big[2 * 1024 * 1024 - 5:2 * 1024 * 1024 + 5]
            mm.close()
        assert mount.stats["faults"] == 0      # everything was local
    finally:
        await mgr.close()


async def test_cold_mount_faults_chunks_through_cache(tmp_path):
    """Chunks absent from the local store: reads must fault them in via
    the socket → CacheClient → source, then succeed with correct bytes."""
    manifest, client, big = await _setup(tmp_path, populate_store=False)
    mgr = CacheFsManager(client, str(tmp_path / "fuse"))
    mnt = str(tmp_path / "mnt")
    mount = await asyncio.wait_for(mgr.mount(manifest, mnt), 30)
    try:
        p = os.path.join(mnt, "sub", "weights.bin")

        # faulted reads must run OFF the event loop: this test process
        # hosts the fault server, and a blocking read on the loop thread
        # would deadlock it (production readers are tenant processes)
        def read_head():
            with open(p, "rb") as f:
                return f.read(100)

        head = await asyncio.wait_for(asyncio.to_thread(read_head), 30)
        assert head == big[:100]
        assert mount.stats["faults"] >= 1
        first_faults = mount.stats["faults"]
        # full read faults the rest and matches
        data = await asyncio.wait_for(
            asyncio.to_thread(lambda: open(p, "rb").read()), 30)
        assert data == big
        assert mount.stats["faults"] >= first_faults
        assert mount.stats["fault_failures"] == 0
        # read-through populated the store with the READ file's chunks
        # (untouched files stay cold — that's the point of on-demand)
        weights = next(e for e in manifest.files
                       if e.path.endswith("weights.bin"))
        for digest in weights.chunks:
            assert client.store.has(digest), digest
    finally:
        await mgr.close()


async def test_missing_chunk_is_eio_not_zeros(tmp_path):
    """A chunk nobody can produce must fail the read loudly — never
    silently serve placeholder zeros."""
    manifest, client, _ = await _setup(tmp_path, populate_store=False)

    async def broken_source(digest):
        return None

    client.source = broken_source
    mgr = CacheFsManager(client, str(tmp_path / "fuse"))
    mnt = str(tmp_path / "mnt")
    mount = await mgr.mount(manifest, mnt)
    try:
        def read_all():
            return open(os.path.join(mnt, "sub", "weights.bin"),
                        "rb").read()

        with pytest.raises(OSError):
            await asyncio.wait_for(asyncio.to_thread(read_all), 30)
        assert mount.stats["fault_failures"] >= 1
    finally:
        await mgr.close()


async def test_lazy_oci_bundle_is_fuse_mounted(tmp_path):
    """OCI rootfs manifests ≥ the lazy threshold become FUSE mounts (the
    overlay lowerdir streams on demand) instead of eager materialization —
    closing the 'OCI images stay eager' gap."""
    import shutil

    from tpu9.images.manifest import snapshot_dir
    from tpu9.images.puller import ImagePuller

    src = tmp_path / "tree"
    (src / "rootfs" / "usr").mkdir(parents=True)
    payload = os.urandom(3 * 1024 * 1024)
    (src / "rootfs" / "usr" / "big.bin").write_bytes(payload)

    origin: dict[str, bytes] = {}
    manifest = snapshot_dir(str(src), chunk_bytes=1024 * 1024,
                            put_chunk=lambda d, h: origin.__setitem__(h, d))
    manifest.image_id = "img-ocilazy"
    manifest.kind = "oci"
    manifest.env = {"FROM_IMAGE": "1"}

    store = DiskStore(str(tmp_path / "store"))

    async def peers():
        return []

    async def source(digest):
        return origin.get(digest)

    client = CacheClient(store, peers, source=source)
    mgr = CacheFsManager(client, str(tmp_path / "fuse"))
    puller = ImagePuller(client, str(tmp_path / "bundles"),
                         lazy_threshold=1024 * 1024, fusefs=mgr)

    bundle = await puller.pull("img-ocilazy", manifest=manifest)
    try:
        assert "img-ocilazy" in puller._fuse_mounts
        # the lifecycle's metadata probe works inside the mount
        import json
        meta = json.load(open(os.path.join(bundle, ".tpu9-env.json")))
        assert meta["kind"] == "oci" and meta["env"]["FROM_IMAGE"] == "1"
        # overlay over the FUSE lowerdir: the exact shape NativeRuntime
        # mounts for OCI bundles (rootfs as lowerdir)
        lower = os.path.join(bundle, "rootfs")
        upper, work, merged = (str(tmp_path / d) for d in
                               ("up", "wk", "mg"))
        for d in (upper, work, merged):
            os.makedirs(d)
        import subprocess
        rc = subprocess.run(
            ["mount", "-t", "overlay", "overlay", "-o",
             f"lowerdir={lower},upperdir={upper},workdir={work}", merged],
            capture_output=True, text=True)
        assert rc.returncode == 0, rc.stderr
        try:
            def read_all():
                return open(os.path.join(merged, "usr", "big.bin"),
                            "rb").read()

            data = await asyncio.wait_for(asyncio.to_thread(read_all), 30)
            assert data == payload            # faulted through the cache
            with open(os.path.join(merged, "usr", "scratch"), "wb") as f:
                f.write(b"upper-write")       # writes land in upper
        finally:
            subprocess.run(["umount", merged], capture_output=True)
        # second pull of a mounted image is a refcount, not a remount
        again = await puller.pull("img-ocilazy", manifest=manifest)
        assert again == bundle
        # gc must not rmtree a live mount
        await puller.gc(keep=0)
        assert os.path.exists(os.path.join(bundle, ".tpu9-env.json"))
    finally:
        await puller.close()
        shutil.rmtree(str(tmp_path / "bundles"), ignore_errors=True)
